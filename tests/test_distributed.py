"""Distributed-layer logic tests (SURVEY.md §4 item 3).

Collective lowering is validated on 8 fake CPU host-platform devices.
This box's sitecustomize force-registers the single-chip axon TPU
backend at interpreter start (overriding JAX_PLATFORMS), so each test
runs in a subprocess with a scrubbed env: PALLAS_AXON_POOL_IPS unset,
JAX_PLATFORMS=cpu, xla_force_host_platform_device_count=8.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _scrubbed_env(fake_devices: int | None = 8) -> dict:
    """Env for a CPU-backend subprocess: drop the axon pool var (the
    dev box's sitecustomize force-registers the TPU backend when it is
    set), force CPU, optionally request fake devices."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # make_mesh joins the multi-host job when coordinator vars are
    # present; these isolated fake-device subprocesses must not (the
    # run_two_procs workers set their own coordinator deliberately)
    for var in (
        "JAX_COORDINATOR_ADDRESS",
        "COORDINATOR_ADDRESS",
        "JAX_NUM_PROCESSES",
        "JAX_PROCESS_ID",
    ):
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    if fake_devices:
        env["XLA_FLAGS"] = (
            env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={fake_devices}"
        ).strip()
    prev = env.get("PYTHONPATH", "")
    env["PYTHONPATH"] = REPO + (os.pathsep + prev if prev else "")
    return env


def run_cpu8(body: str) -> str:
    env = _scrubbed_env(8)
    proc = subprocess.run(
        [sys.executable, "-c", textwrap.dedent(body)],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, f"STDOUT:\n{proc.stdout}\nSTDERR:\n{proc.stderr}"
    return proc.stdout


def run_procs(worker_body: str, nprocs: int = 2) -> None:
    """Launch an nprocs-process jax.distributed job. `worker_body` is
    formatted with {port} and run with the process id as argv[1] (the
    worker sets its own fake-device count); each worker must print
    'proc <pid>: OK'."""
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    worker = textwrap.dedent(worker_body.format(port=port))
    env = _scrubbed_env(fake_devices=None)  # workers set their own
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker, str(i)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nprocs)
    ]
    try:
        outs = [p.communicate(timeout=240)[0] for p in procs]
        for i, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"proc {i} failed:\n{out}"
            assert f"proc {i}: OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def run_two_procs(worker_body: str) -> None:
    """2-process jax.distributed job (4 fake CPU devices per process,
    8 global) — see run_procs."""
    run_procs(worker_body, nprocs=2)


def test_allreduce_sum_matches_mpi_semantics():
    out = run_cpu8("""
        import jax, numpy as np, jax.numpy as jnp
        assert jax.default_backend() == 'cpu' and len(jax.devices()) == 8
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import allreduce_sum
        mesh = make_mesh(8)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((8, 1024)), jnp.float32)
        out = np.asarray(allreduce_sum(x, mesh))
        want = np.asarray(x).sum(axis=0)
        for r in range(8):
            np.testing.assert_allclose(out[r], want, rtol=1e-5)
        print('OK')
    """)
    assert "OK" in out


def test_jacobi2d_dist_matches_single_device():
    out = run_cpu8("""
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import jacobi2d_dist
        from tpukernels.kernels.stencil import jacobi2d_reference
        mesh = make_mesh(8)
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((256, 128)), jnp.float32)
        out = np.asarray(jacobi2d_dist(x, 7, mesh))
        ref = np.asarray(jacobi2d_reference(x, 7))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
        print('OK')
    """)
    assert "OK" in out


def test_jacobi3d_dist_matches_single_device():
    out = run_cpu8("""
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import jacobi3d_dist
        from tpukernels.kernels.stencil import jacobi3d_reference
        mesh = make_mesh(8)
        rng = np.random.default_rng(4)
        x = jnp.asarray(rng.standard_normal((32, 16, 64)), jnp.float32)
        # iters=7 with default k=4 exercises a full round + remainder
        out = np.asarray(jacobi3d_dist(x, 7, mesh))
        ref = np.asarray(jacobi3d_reference(x, 7))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
        ref_k1 = np.asarray(jacobi3d_dist(x, 7, mesh, k=1))
        np.testing.assert_array_equal(out, ref_k1)
        print('OK')
    """)
    assert "OK" in out


@pytest.mark.parametrize("k", [1, 2, 8, 64])
def test_jacobi2d_dist_comm_avoiding_k(k):
    # result must be bitwise independent of the halo depth (k=64
    # exceeds the 32-row local shard and exercises the clamp)
    out = run_cpu8(f"""
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import jacobi2d_dist
        mesh = make_mesh(8)
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((256, 128)), jnp.float32)
        out = np.asarray(jacobi2d_dist(x, 7, mesh, k={k}))
        ref = np.asarray(jacobi2d_dist(x, 7, mesh, k=1))
        np.testing.assert_array_equal(out, ref)
        print('OK')
    """)
    assert "OK" in out


def test_bcast_matches_mpi_semantics():
    out = run_cpu8("""
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import bcast
        mesh = make_mesh(8)
        rng = np.random.default_rng(9)
        x = jnp.asarray(rng.standard_normal((8, 64)), jnp.float32)
        for root in (0, 3, 7):
            out = np.asarray(bcast(x, mesh, root=root))
            for r in range(8):
                np.testing.assert_array_equal(out[r], np.asarray(x)[root])
        try:
            bcast(x, mesh, root=8)
            raise SystemExit('bcast(root=8) did not raise')
        except ValueError as e:
            assert 'root=8' in str(e)
        print('OK')
    """)
    assert "OK" in out


def test_ring_shift_matches_sendrecv_semantics():
    out = run_cpu8("""
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import ring_shift
        mesh = make_mesh(8)
        rng = np.random.default_rng(12)
        x = jnp.asarray(rng.standard_normal((8, 32)), jnp.float32)
        for shift in (1, -1, 3):
            got = np.asarray(ring_shift(x, mesh, shift=shift))
            want = np.roll(np.asarray(x), shift, axis=0)
            np.testing.assert_array_equal(got, want)
        print('OK')
    """)
    assert "OK" in out


def test_jacobi_dist_residual():
    # residual=True returns the same grid plus the global squared norm
    # of the next sweep's update — checked against the single-device
    # oracle run one iteration further
    out = run_cpu8("""
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import jacobi2d_dist
        from tpukernels.kernels.stencil import jacobi2d_reference
        mesh = make_mesh(8)
        rng = np.random.default_rng(10)
        x = jnp.asarray(rng.standard_normal((128, 128)), jnp.float32)
        grid, res = jacobi2d_dist(x, 5, mesh, residual=True)
        plain = np.asarray(jacobi2d_dist(x, 5, mesh))
        np.testing.assert_array_equal(np.asarray(grid), plain)
        r5 = np.asarray(jacobi2d_reference(x, 5), dtype=np.float64)
        r6 = np.asarray(jacobi2d_reference(x, 6), dtype=np.float64)
        want = ((r6 - r5) ** 2).sum()
        np.testing.assert_allclose(float(res), want, rtol=1e-4)
        print('OK')
    """)
    assert "OK" in out


@pytest.mark.parametrize("exclusive", [False, True])
def test_scan_dist_matches_oracle(exclusive):
    # int32 must be bitwise-exact (mod-2^32 wraparound included: the
    # large random values overflow int32 partial sums on purpose);
    # float32 matches the cumsum oracle to rtol
    out = run_cpu8(f"""
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import scan_dist
        mesh = make_mesh(8)
        rng = np.random.default_rng(5)
        n = 4096
        xi = rng.integers(-2**30, 2**30, n).astype(np.int32)
        got = np.asarray(scan_dist(jnp.asarray(xi), mesh,
                                   exclusive={exclusive}))
        want = np.cumsum(xi.astype(np.int64)).astype(np.int32)
        if {exclusive}:
            want = np.concatenate([[np.int32(0)], want[:-1]])
        np.testing.assert_array_equal(got, want)
        xf = rng.standard_normal(n).astype(np.float32)
        gotf = np.asarray(scan_dist(jnp.asarray(xf), mesh,
                                    exclusive={exclusive}))
        wantf = np.cumsum(xf, dtype=np.float64)
        if {exclusive}:
            wantf = np.concatenate([[0.0], wantf[:-1]])
        np.testing.assert_allclose(gotf, wantf, rtol=1e-4, atol=1e-4)
        print('OK')
    """)
    assert "OK" in out


def test_histogram_dist_matches_oracle():
    out = run_cpu8("""
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import histogram_dist
        mesh = make_mesh(8)
        rng = np.random.default_rng(6)
        n, nbins = 100000 - 100000 % 8, 256
        x = rng.integers(-4, nbins + 4, n).astype(np.int32)  # incl. OOR
        got = np.asarray(histogram_dist(jnp.asarray(x), nbins, mesh))
        want = np.bincount(x[(x >= 0) & (x < nbins)], minlength=nbins)
        np.testing.assert_array_equal(got, want)
        print('OK')
    """)
    assert "OK" in out


@pytest.mark.parametrize("variant", ["psum", "ring"])
def test_nbody_dist_matches_single_device(variant):
    out = run_cpu8(f"""
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import nbody_dist_psum, nbody_dist_ring
        from tpukernels.kernels.nbody import nbody_reference
        mesh = make_mesh(8)
        rng = np.random.default_rng(2)
        n = 512
        state = tuple(jnp.asarray(rng.standard_normal(n), jnp.float32) for _ in range(6)) + (
            jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32),)
        fn = nbody_dist_{variant}
        out = fn(state, 3, mesh)
        ref = nbody_reference(*state, steps=3)
        for got, want in zip(out, ref):
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=5e-4, atol=5e-5)
        print('OK')
    """)
    assert "OK" in out


def test_nbody_ring_skip_last_bitwise_identical():
    """TPK_NBODY_RING_SKIP_LAST=1 peels the ring's final pass so the
    last (result-unused) ppermute is never emitted — 1/P of ring comm
    volume (docs/NEXT.md item 5, pre-staged for a pod A/B). The accel
    accumulation order is unchanged, so trajectories must be BITWISE
    identical to the default formulation."""
    out = run_cpu8("""
        import os
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import nbody_dist_ring
        mesh = make_mesh(8)
        rng = np.random.default_rng(7)
        n = 512
        state = tuple(jnp.asarray(rng.standard_normal(n), jnp.float32)
                      for _ in range(6)) + (
            jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32),)
        base = nbody_dist_ring(state, 3, mesh)
        os.environ["TPK_NBODY_RING_SKIP_LAST"] = "1"
        skip = nbody_dist_ring(state, 3, mesh)
        del os.environ["TPK_NBODY_RING_SKIP_LAST"]
        for got, want in zip(skip, base):
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # the knob must actually remove ring hops. The ppermutes sit
        # inside the ring fori_loop's BODY, so their static op count is
        # identical either way — what the peel changes is the loop's
        # trip count: nranks passes default, nranks-1 skipped. Read it
        # from the jaxpr's scan lengths (fori_loop with static bounds
        # lowers to scan).
        import re
        from tpukernels.parallel.collectives import _nbody_ring_build
        lens = []
        for flag in (False, True):
            fn = _nbody_ring_build(3, mesh, "x", 1e-3, 1e-2, flag)
            jaxpr = str(jax.make_jaxpr(fn)(*state))
            lens.append({int(m) for m in re.findall(r"length=(\\d+)", jaxpr)})
        n_def, n_skip = lens
        assert 8 in n_def and 7 not in n_def, n_def
        assert 7 in n_skip and 8 not in n_skip, n_skip
        print('OK')
    """)
    assert "OK" in out


def test_nbody_ring_bidir():
    """TPK_NBODY_RING_BIDIR=1 rotates j-block halves in opposite ring
    directions so both full-duplex ICI link directions carry bytes
    every pass (half the per-pass comm time when bandwidth-bound;
    docs/NEXT.md pod A/B). Must match the single-device oracle within
    the distributed-nbody tolerance, compose bitwise with SKIP_LAST,
    and actually emit collective-permutes in BOTH directions."""
    out = run_cpu8("""
        import os
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import nbody_dist_ring
        from tpukernels.kernels.nbody import nbody_reference
        mesh = make_mesh(8)
        rng = np.random.default_rng(11)
        n = 512
        state = tuple(jnp.asarray(rng.standard_normal(n), jnp.float32)
                      for _ in range(6)) + (
            jnp.asarray(rng.uniform(0.5, 1.5, n), jnp.float32),)
        os.environ["TPK_NBODY_RING_BIDIR"] = "1"
        got = nbody_dist_ring(state, 3, mesh)
        ref = nbody_reference(*state, steps=3)
        for g, w in zip(got, ref):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                       rtol=5e-4, atol=5e-5)
        # composes with the last-hop peel, bitwise
        os.environ["TPK_NBODY_RING_SKIP_LAST"] = "1"
        got_skip = nbody_dist_ring(state, 3, mesh)
        del os.environ["TPK_NBODY_RING_SKIP_LAST"]
        del os.environ["TPK_NBODY_RING_BIDIR"]
        for g, w in zip(got_skip, got):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))
        # structural: the bidir program must carry BOTH ring
        # directions — 8 collective-permutes in the loop body (4
        # arrays x 2 directions) vs the unidirectional 4
        from tpukernels.parallel.collectives import _nbody_ring_build
        def n_perms(bidir):
            fn = _nbody_ring_build(3, mesh, "x", 1e-3, 1e-2, False, bidir)
            txt = fn.lower(*state).compile().as_text()
            k = txt.count("collective-permute-start")
            return k if k else txt.count("collective-permute(")
        assert n_perms(False) == 4, n_perms(False)
        assert n_perms(True) == 8, n_perms(True)
        print('OK')
    """)
    assert "OK" in out


def test_multiprocess_allreduce():
    """Real jax.distributed across 2 processes (4 fake CPU devices
    each, 8 global): the multi-host path the 8→64-chip bus-bw run
    uses, where the C driver launches once per host with identical
    args — the moral equivalent of mpirun (SURVEY.md §7)."""
    run_two_procs("""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        import jax
        from tpukernels.compat import ensure_cpu_collectives
        ensure_cpu_collectives()  # 0.4.x jax ships CPU gloo off
        pid = int(sys.argv[1])
        jax.distributed.initialize(
            "127.0.0.1:{port}", num_processes=2, process_id=pid)
        import numpy as np
        assert jax.device_count() == 8
        assert jax.local_device_count() == 4
        from jax.sharding import NamedSharding, PartitionSpec as P
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import allreduce_sum
        mesh = make_mesh(8)
        rng = np.random.default_rng(0)
        full = rng.standard_normal((8, 256)).astype(np.float32)
        sharding = NamedSharding(mesh, P("x", None))
        local_rows = full[pid * 4:(pid + 1) * 4]
        arrs = [jax.device_put(local_rows[i:i + 1], d)
                for i, d in enumerate(jax.local_devices())]
        x = jax.make_array_from_single_device_arrays(
            (8, 256), sharding, arrs)
        out = allreduce_sum(x, mesh)
        local = np.concatenate(
            [np.asarray(s.data) for s in out.addressable_shards])
        np.testing.assert_allclose(
            local, np.tile(full.sum(axis=0), (4, 1)), rtol=1e-5)
        print(f"proc {{pid}}: OK")
    """)


def test_multiprocess_4x2_collectives():
    """4 processes × 2 fake devices each (8 global): wider than the
    2-process jobs everywhere else (VERDICT r2 item 2). Every ring
    step now crosses a process boundary at 4 distinct host seams, and
    the two-level scan's carry crosses 3 of them — shapes of failure
    a 2-process job can't produce."""
    run_procs("""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        import jax
        from tpukernels.compat import ensure_cpu_collectives
        ensure_cpu_collectives()  # 0.4.x jax ships CPU gloo off
        pid = int(sys.argv[1])
        jax.distributed.initialize(
            "127.0.0.1:{port}", num_processes=4, process_id=pid)
        import numpy as np, jax.numpy as jnp
        assert jax.device_count() == 8
        assert jax.local_device_count() == 2
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.mesh import (
            host_to_global, global_to_host, row_sharding)
        from tpukernels.parallel.collectives import (
            allreduce_sum, ring_shift, scan_dist, nbody_dist_ring)
        from tpukernels.kernels.nbody import nbody_reference
        mesh = make_mesh(8)
        rng = np.random.default_rng(21)  # same seed on all hosts
        full = rng.standard_normal((8, 128)).astype(np.float32)
        x = host_to_global(full, row_sharding(mesh))
        out = global_to_host(allreduce_sum(x, mesh))
        np.testing.assert_allclose(
            out, np.tile(full.sum(axis=0), (8, 1)), rtol=1e-5)
        np.testing.assert_array_equal(
            global_to_host(ring_shift(x, mesh, shift=1)),
            np.roll(full, 1, axis=0))
        vals = rng.integers(-2**30, 2**30, 64 * 8).astype(np.int32)
        sv = host_to_global(vals, row_sharding(mesh))
        np.testing.assert_array_equal(
            global_to_host(scan_dist(sv, mesh)),
            np.cumsum(vals.astype(np.int64)).astype(np.int32))
        # the ring N-body rotates j-blocks through all 4 processes
        nb = 64
        state_np = [rng.standard_normal(nb).astype(np.float32)
                    for _ in range(6)]
        m_np = rng.uniform(0.5, 1.5, nb).astype(np.float32)
        sh = row_sharding(mesh)
        state = tuple(host_to_global(a, sh) for a in state_np) + (
            host_to_global(m_np, sh),)
        ref = nbody_reference(
            *(jnp.asarray(a) for a in state_np), jnp.asarray(m_np),
            steps=2)
        for got, want in zip(nbody_dist_ring(state, 2, mesh), ref):
            np.testing.assert_allclose(
                global_to_host(got), np.asarray(want),
                rtol=5e-4, atol=5e-5)
        print(f"proc {{pid}}: OK")
    """, nprocs=4)


def test_multiprocess_8x1_collectives():
    """8 processes × 1 device each — the fully-distributed extreme
    where EVERY ring hop and every scan-carry crossing is a process
    boundary (a pod of single-chip hosts). Complements 2×4 (mostly
    local) and 4×2 (mixed)."""
    run_procs("""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        import jax
        from tpukernels.compat import ensure_cpu_collectives
        ensure_cpu_collectives()  # 0.4.x jax ships CPU gloo off
        pid = int(sys.argv[1])
        jax.distributed.initialize(
            "127.0.0.1:{port}", num_processes=8, process_id=pid)
        import numpy as np
        assert jax.device_count() == 8
        assert jax.local_device_count() == 1
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.mesh import (
            host_to_global, global_to_host, row_sharding)
        from tpukernels.parallel.collectives import (
            allreduce_sum, ring_shift, scan_dist)
        mesh = make_mesh(8)
        rng = np.random.default_rng(33)  # same seed on all hosts
        full = rng.standard_normal((8, 64)).astype(np.float32)
        x = host_to_global(full, row_sharding(mesh))
        np.testing.assert_allclose(
            global_to_host(allreduce_sum(x, mesh)),
            np.tile(full.sum(axis=0), (8, 1)), rtol=1e-5)
        np.testing.assert_array_equal(
            global_to_host(ring_shift(x, mesh)),
            np.roll(full, 1, axis=0))
        vals = rng.integers(-2**30, 2**30, 16 * 8).astype(np.int32)
        sv = host_to_global(vals, row_sharding(mesh))
        np.testing.assert_array_equal(
            global_to_host(scan_dist(sv, mesh)),
            np.cumsum(vals.astype(np.int64)).astype(np.int32))
        print(f"proc {{pid}}: OK")
    """, nprocs=8)


def test_multiprocess_small_collectives():
    """bcast, ring_shift and the stencil residual under real
    2-process jax.distributed — the masked-psum, ppermute and
    replicated-scalar-output seams that fake-device runs can't prove
    cross-host."""
    run_two_procs("""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        import jax
        from tpukernels.compat import ensure_cpu_collectives
        ensure_cpu_collectives()  # 0.4.x jax ships CPU gloo off
        pid = int(sys.argv[1])
        jax.distributed.initialize(
            "127.0.0.1:{port}", num_processes=2, process_id=pid)
        import numpy as np
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.mesh import host_to_global, \\
            global_to_host, row_sharding
        from tpukernels.parallel.collectives import (
            bcast, jacobi2d_dist, ring_shift)
        from tpukernels.kernels.stencil import jacobi2d_reference
        mesh = make_mesh(8)
        rng = np.random.default_rng(13)  # same seed on both hosts
        full = rng.standard_normal((8, 32)).astype(np.float32)
        x = host_to_global(full, row_sharding(mesh))
        np.testing.assert_array_equal(
            global_to_host(bcast(x, mesh, root=5)),
            np.tile(full[5], (8, 1)))
        np.testing.assert_array_equal(
            global_to_host(ring_shift(x, mesh, shift=1)),
            np.roll(full, 1, axis=0))
        grid_full = rng.standard_normal((64, 32)).astype(np.float32)
        g = host_to_global(grid_full, row_sharding(mesh))
        out, res = jacobi2d_dist(g, 3, mesh, residual=True)
        # exact cross-host psum value vs the single-device oracle
        # (a wrong reduction would still be >= 0 — compare the value)
        r3 = np.asarray(jacobi2d_reference(grid_full, 3), np.float64)
        r4 = np.asarray(jacobi2d_reference(grid_full, 4), np.float64)
        np.testing.assert_allclose(
            float(res), ((r4 - r3) ** 2).sum(), rtol=1e-4)
        plain = global_to_host(jacobi2d_dist(g, 3, mesh))
        np.testing.assert_array_equal(global_to_host(out), plain)
        print(f"proc {{pid}}: OK")
    """)


def test_multiprocess_busbw_sweep():
    """The bus-bw microbenchmark must run under real multi-process
    jax.distributed (the 8→64-chip configuration): global input arrays
    are assembled shard-by-shard and the timing probe is a replicated
    scalar every host can fetch."""
    run_two_procs("""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        import jax
        from tpukernels.compat import ensure_cpu_collectives
        ensure_cpu_collectives()  # 0.4.x jax ships CPU gloo off
        pid = int(sys.argv[1])
        jax.distributed.initialize(
            "127.0.0.1:{port}", num_processes=2, process_id=pid)
        assert jax.device_count() == 8
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.busbw import sweep
        res = sweep(min_bytes=1024, max_bytes=4096, reps=2,
                    mesh=make_mesh(8), verbose=False)
        assert len(res) == 2 and all(bw > 0 for _, _, bw in res)
        print(f"proc {{pid}}: OK")
    """)


def test_multiprocess_busbw_cli():
    """`python -m tpukernels.parallel.busbw` — the exact entry the
    supervisor's busbw_sweep step runs on a pod — must survive a
    coordinator-configured env: jax.distributed.initialize (inside
    make_mesh) has to run BEFORE the backend-initializing
    device-inventory probe, or every pod host crashes (or, on jaxes
    without the init-order guard, silently meshes only local chips).
    Exercises the __main__ path itself, not sweep()."""
    run_two_procs("""
        import glob, json, os, sys, tempfile
        pid = int(sys.argv[1])
        tmp = tempfile.mkdtemp()
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = \\
            "--xla_force_host_platform_device_count=4"
        os.environ["JAX_COORDINATOR_ADDRESS"] = "127.0.0.1:{port}"
        os.environ["JAX_NUM_PROCESSES"] = "2"
        os.environ["JAX_PROCESS_ID"] = str(pid)
        os.environ["TPK_SCALING_DIR"] = tmp
        os.environ["TPK_HEALTH_JOURNAL"] = \\
            os.path.join(tmp, "health.jsonl")
        import runpy
        sys.argv = ["busbw", "--min=1024", "--max=4096", "--reps=1"]
        runpy.run_module("tpukernels.parallel.busbw",
                         run_name="__main__")
        import jax
        assert jax.process_count() == 2
        (art,) = glob.glob(os.path.join(tmp, "scaling_busbw_*.json"))
        rec = json.load(open(art))
        assert rec["n_devices"] == 8  # global mesh, not local-only
        inv = rec["device_inventory"]
        assert inv["source"] == "jax" and inv["process_count"] == 2
        print(f"proc {{pid}}: OK")
    """)


def test_multiprocess_weak_scaling_inner():
    """tools/weak_scaling.py --inner under a coordinator (the --real
    pod mode): inner() must join the multi-host job before its
    device-inventory probe initializes the backend. Runs the
    multi-process-safe allreduce program only (the others feed
    host-local full arrays, the single-process fake-device design)."""
    run_two_procs("""
        import importlib.util, json, os, sys, tempfile
        pid = int(sys.argv[1])
        tmp = tempfile.mkdtemp()
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = \\
            "--xla_force_host_platform_device_count=4"
        os.environ["JAX_COORDINATOR_ADDRESS"] = "127.0.0.1:{port}"
        os.environ["JAX_NUM_PROCESSES"] = "2"
        os.environ["JAX_PROCESS_ID"] = str(pid)
        os.environ["TPK_HEALTH_JOURNAL"] = \\
            os.path.join(tmp, "health.jsonl")
        import tpukernels
        repo = os.path.dirname(os.path.dirname(tpukernels.__file__))
        spec = importlib.util.spec_from_file_location(
            "weak_scaling",
            os.path.join(repo, "tools", "weak_scaling.py"))
        ws = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(ws)
        ws.PROGRAMS = {{"allreduce": ws.PROGRAMS["allreduce"]}}
        rc = ws.inner(8, 1, True)
        assert rc == 0, "allreduce point failed under coordinator"
        import jax
        assert jax.process_count() == 2
        invs = [json.loads(l) for l in
                open(os.environ["TPK_HEALTH_JOURNAL"])]
        (ev,) = [e for e in invs
                 if e.get("kind") == "device_inventory"]
        assert ev["source"] == "jax" and ev["process_count"] == 2
        print(f"proc {{pid}}: OK")
    """)


def test_multiprocess_capi_mesh():
    """The C-shim adapters must work under real multi-process
    jax.distributed (SURVEY.md §7 "multi-chip under a C driver"): the
    driver runs once per host holding FULL buffers, so inputs are
    assembled shard-by-shard and sharded outputs all-gathered back.
    Exercises a sharded-in/sharded-out kernel (stencil), a
    sharded-in/replicated-out one (histogram), the scan, and both
    N-body formulations (ring: all-sharded state; psum: replicated
    positions + sharded masses)."""
    run_two_procs("""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        os.environ["TPK_MESH"] = "8"
        import jax
        from tpukernels.compat import ensure_cpu_collectives
        ensure_cpu_collectives()  # 0.4.x jax ships CPU gloo off
        pid = int(sys.argv[1])
        jax.distributed.initialize(
            "127.0.0.1:{port}", num_processes=2, process_id=pid)
        assert jax.device_count() == 8
        import json
        import numpy as np
        import jax.numpy as jnp
        from tpukernels import capi
        from tpukernels.kernels.stencil import jacobi2d_reference
        from tpukernels.kernels.nbody import nbody_reference

        rng = np.random.default_rng(11)  # same seed on both hosts
        h, w = 64, 128
        x = np.ascontiguousarray(rng.standard_normal((h, w)), np.float32)
        ref = np.asarray(jacobi2d_reference(jnp.asarray(x), 4))
        params = json.dumps(
            {{"iters": 4, "buffers": [{{"shape": [h, w], "dtype": "f32"}}]}})
        assert capi.run_from_c("stencil2d", params, [x.ctypes.data]) == 0
        np.testing.assert_allclose(x, ref, rtol=1e-5, atol=1e-6)

        ns = 2048
        xi = np.ascontiguousarray(rng.integers(0, 256, ns).astype(np.int32))
        scan_buf = np.zeros(ns, np.int32)
        params = json.dumps(
            {{"buffers": [{{"shape": [ns], "dtype": "i32"}}] * 2}})
        assert capi.run_from_c(
            "scan", params, [xi.ctypes.data, scan_buf.ctypes.data]) == 0
        np.testing.assert_array_equal(scan_buf, np.cumsum(xi))

        hist_buf = np.zeros(256, np.int32)
        params = json.dumps({{
            "nbins": 256,
            "buffers": [{{"shape": [ns], "dtype": "i32"}},
                        {{"shape": [256], "dtype": "i32"}}]}})
        assert capi.run_from_c(
            "histogram", params, [xi.ctypes.data, hist_buf.ctypes.data]) == 0
        np.testing.assert_array_equal(
            hist_buf, np.bincount(xi, minlength=256))

        # ring: all-sharded state; psum: replicated positions + sharded
        # masses — two different multi-host input-assembly seams
        for variant in ("ring", "psum"):
            os.environ["TPK_NBODY_DIST"] = variant
            nb = 256
            state = [np.ascontiguousarray(
                         rng.standard_normal(nb), np.float32)
                     for _ in range(6)]
            m = np.ascontiguousarray(
                rng.uniform(0.5, 1.5, nb), np.float32)
            ref6 = nbody_reference(
                *(jnp.asarray(a) for a in state), jnp.asarray(m), steps=2)
            params = json.dumps({{
                "steps": 2,
                "buffers": [{{"shape": [nb], "dtype": "f32"}}] * 7}})
            bufs = state + [m]
            assert capi.run_from_c(
                "nbody", params, [a.ctypes.data for a in bufs]) == 0
            for got, want in zip(state, ref6):
                np.testing.assert_allclose(
                    got, np.asarray(want), rtol=5e-4, atol=5e-5)

        print(f"proc {{pid}}: OK")
    """)


def test_multiprocess_env_driven_join():
    """The pod workflow exactly as docs/NEXT.md prescribes it: each
    host exports the coordinator env vars and runs the C driver — no
    code calls jax.distributed.initialize explicitly; the shim's
    adapter path must join the job itself (mesh.maybe_distributed_init
    via make_mesh/_mesh_size) BEFORE reading the topology. Covers the
    allreduce adapter plus the TPK_BUSBW_SWEEP table, and proves the
    join is idempotent across the driver's repeated calls."""
    run_two_procs("""
        import os, sys
        pid = int(sys.argv[1])
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        os.environ["JAX_COORDINATOR_ADDRESS"] = "127.0.0.1:{port}"
        os.environ["JAX_NUM_PROCESSES"] = "2"
        os.environ["JAX_PROCESS_ID"] = str(pid)
        os.environ["TPK_MESH"] = "8"
        os.environ["TPK_BUSBW_SWEEP"] = "1"
        os.environ["TPK_BUSBW_MIN"] = "1K"
        os.environ["TPK_BUSBW_MAX"] = "4K"
        os.environ["TPK_BUSBW_REPS"] = "2"
        import json
        import numpy as np
        from tpukernels import capi

        s = 256
        rng = np.random.default_rng(13)  # same seed on both hosts
        xs = np.ascontiguousarray(rng.standard_normal(s), np.float32)
        out_buf = np.zeros(s, np.float32)
        params = json.dumps(
            {{"buffers": [{{"shape": [s], "dtype": "f32"}}] * 2}})
        for _ in range(3):  # check + warm-up + timed rep
            assert capi.run_from_c(
                "allreduce", params,
                [xs.ctypes.data, out_buf.ctypes.data]) == 0
        np.testing.assert_allclose(out_buf, 8 * xs, rtol=1e-5)

        import jax
        assert jax.process_count() == 2, jax.process_count()
        assert jax.device_count() == 8
        print(f"proc {{pid}}: OK")
    """)


def test_capi_mesh_routing():
    """TPK_MESH>1 routes the C-shim adapters through the shard_map
    collective variants (SURVEY.md §5 config system) — the C driver's
    `mpirun -np N` analog. Verified against the single-device oracle
    on 8 fake CPU devices."""
    out = run_cpu8("""
        import os
        os.environ["TPK_MESH"] = "8"
        import json
        import numpy as np
        import jax.numpy as jnp
        from tpukernels import capi
        from tpukernels.kernels.stencil import jacobi2d_reference
        from tpukernels.kernels.nbody import nbody_reference

        rng = np.random.default_rng(7)
        h, w = 256, 128
        x = np.ascontiguousarray(rng.standard_normal((h, w)), np.float32)
        ref = np.asarray(jacobi2d_reference(jnp.asarray(x), 5))
        params = json.dumps(
            {"iters": 5, "buffers": [{"shape": [h, w], "dtype": "f32"}]})
        assert capi.run_from_c("stencil2d", params, [x.ctypes.data]) == 0
        np.testing.assert_allclose(x, ref, rtol=1e-5, atol=1e-6)

        for variant in ("psum", "ring"):
            os.environ["TPK_NBODY_DIST"] = variant
            n = 512
            state = [np.ascontiguousarray(rng.standard_normal(n), np.float32)
                     for _ in range(6)]
            m = np.ascontiguousarray(rng.uniform(0.5, 1.5, n), np.float32)
            ref6 = nbody_reference(
                *(jnp.asarray(a) for a in state), jnp.asarray(m), steps=2)
            params = json.dumps({
                "steps": 2,
                "buffers": [{"shape": [n], "dtype": "f32"}] * 7,
            })
            bufs = state + [m]
            assert capi.run_from_c(
                "nbody", params, [a.ctypes.data for a in bufs]) == 0
            for got, want in zip(state, ref6):
                np.testing.assert_allclose(
                    got, np.asarray(want), rtol=5e-4, atol=5e-5)

        # scan + histogram route through the dist variants under mesh
        ns = 4096
        xs_i = np.ascontiguousarray(
            rng.integers(0, 256, ns).astype(np.int32))
        scan_buf = np.zeros(ns, np.int32)
        params = json.dumps(
            {"buffers": [{"shape": [ns], "dtype": "i32"}] * 2})
        assert capi.run_from_c(
            "scan", params, [xs_i.ctypes.data, scan_buf.ctypes.data]) == 0
        np.testing.assert_array_equal(scan_buf, np.cumsum(xs_i))
        hist_buf = np.zeros(256, np.int32)
        params = json.dumps({
            "nbins": 256,
            "buffers": [{"shape": [ns], "dtype": "i32"},
                        {"shape": [256], "dtype": "i32"}]})
        assert capi.run_from_c(
            "histogram", params, [xs_i.ctypes.data, hist_buf.ctypes.data]) == 0
        np.testing.assert_array_equal(
            hist_buf, np.bincount(xs_i, minlength=256))

        # allreduce honors TPK_MESH for its contribution count
        s = 256
        xs = np.ascontiguousarray(rng.standard_normal(s), np.float32)
        out_buf = np.zeros(s, np.float32)
        params = json.dumps(
            {"buffers": [{"shape": [s], "dtype": "f32"}] * 2})
        assert capi.run_from_c(
            "allreduce", params, [xs.ctypes.data, out_buf.ctypes.data]) == 0
        np.testing.assert_allclose(out_buf, 8 * xs, rtol=1e-5)
        print('OK')
    """)
    assert "OK" in out


def test_busbw_env_knob_parsing(monkeypatch):
    """sweep_from_env forwards exactly the TPK_BUSBW_* knobs (shared
    by `python -m ...busbw` users and the C driver's TPK_BUSBW_SWEEP
    path) — sizes accept the 1K/64M suffix forms."""
    from tpukernels.parallel import busbw

    captured = {}
    monkeypatch.setattr(
        busbw, "sweep", lambda mesh=None, **kw: captured.update(kw)
    )
    monkeypatch.setenv("TPK_BUSBW_MIN", "1K")
    monkeypatch.setenv("TPK_BUSBW_MAX", "2M")
    monkeypatch.setenv("TPK_BUSBW_REPS", "3")
    monkeypatch.setenv("TPK_BUSBW_OP", "ppermute")
    busbw.sweep_from_env()
    assert captured == {
        "min_bytes": 1024,
        "max_bytes": 2 << 20,
        "reps": 3,
        "op": "ppermute",
    }
    captured.clear()
    for var in ("TPK_BUSBW_MIN", "TPK_BUSBW_MAX", "TPK_BUSBW_REPS",
                "TPK_BUSBW_OP"):
        monkeypatch.delenv(var)
    busbw.sweep_from_env()
    assert captured == {}  # unset knobs: sweep defaults untouched


def test_capi_busbw_sweep_env():
    """TPK_BUSBW_SWEEP=1 makes the allreduce adapter emit the swept
    bus-bandwidth table (the pod metric of record) exactly once per
    process — on the C driver's first, untimed call — leaving repeat
    (timed) calls undisturbed. SURVEY.md §3(d), zero new C flags."""
    out = run_cpu8("""
        import os, json
        os.environ["TPK_MESH"] = "8"
        os.environ["TPK_BUSBW_SWEEP"] = "1"
        os.environ["TPK_BUSBW_MIN"] = "1K"
        os.environ["TPK_BUSBW_MAX"] = "16K"
        os.environ["TPK_BUSBW_REPS"] = "2"
        import numpy as np
        from tpukernels import capi

        s = 256
        rng = np.random.default_rng(7)
        xs = np.ascontiguousarray(rng.standard_normal(s), np.float32)
        out_buf = np.zeros(s, np.float32)
        params = json.dumps(
            {"buffers": [{"shape": [s], "dtype": "f32"}] * 2})
        for _ in range(3):  # check + warm-up + timed rep
            assert capi.run_from_c(
                "allreduce", params,
                [xs.ctypes.data, out_buf.ctypes.data]) == 0
        np.testing.assert_allclose(out_buf, 8 * xs, rtol=1e-5)
        print('CALLS-DONE')
    """)
    assert "CALLS-DONE" in out
    # sizes 1K, 4K, 16K — one table, printed once despite 3 calls
    sweep_lines = [l for l in out.splitlines() if l.startswith("allreduce n=8")]
    assert len(sweep_lines) == 3, out
    assert "size=      1024B" in out and "size=     16384B" in out


def test_capi_mesh_too_large_raises():
    out = run_cpu8("""
        import os, json
        os.environ["TPK_MESH"] = "64"
        import numpy as np
        from tpukernels import capi
        x = np.zeros((64, 128), np.float32)
        params = json.dumps(
            {"iters": 1, "buffers": [{"shape": [64, 128], "dtype": "f32"}]})
        try:
            capi.run_from_c("stencil2d", params, [x.ctypes.data])
        except RuntimeError as e:
            assert "TPK_MESH=64" in str(e), e
            print('OK')
    """)
    assert "OK" in out


def test_fuzz_dist_shapes():
    """Seeded shape-fuzz of every distributed variant across mesh
    sizes 2/3/4/5/8 (the single-chip analog lives in
    test_fuzz_shapes.py): divisible-but-awkward extents — one row per
    rank, prime multiples, halo depths past the shard size — are where
    sharding/clamp logic silently corrupts, and the odd/prime mesh
    sizes catch any hidden power-of-2 assumption in the ring perms,
    scan offsets or halo wrap. One subprocess runs the whole
    deterministic sweep."""
    out = run_cpu8("""
        import numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import (
            bcast, histogram_dist, jacobi2d_dist, jacobi3d_dist,
            nbody_dist_psum, nbody_dist_ring, scan_dist)
        from tpukernels.kernels.stencil import (
            jacobi2d_reference, jacobi3d_reference)
        from tpukernels.kernels.nbody import nbody_reference
        rng = np.random.default_rng(42)

        for P_ in (2, 3, 4, 5, 8):
            mesh = make_mesh(P_)

            for n in (P_, 37 * P_, 128 * P_ + P_):
                xi = jnp.asarray(
                    rng.integers(-2**30, 2**30, n), jnp.int32)
                want = np.cumsum(
                    np.asarray(xi, np.int64)).astype(np.int32)
                np.testing.assert_array_equal(
                    np.asarray(scan_dist(xi, mesh)), want)
                np.testing.assert_array_equal(
                    np.asarray(scan_dist(xi, mesh, exclusive=True)),
                    np.concatenate([[np.int32(0)], want[:-1]]))
                xf = jnp.asarray(rng.standard_normal(n), jnp.float32)
                np.testing.assert_allclose(
                    np.asarray(scan_dist(xf, mesh)),
                    np.cumsum(np.asarray(xf, np.float64)),
                    rtol=1e-4, atol=1e-4)

            for nbins in (1, 17, 256):
                n = 41 * P_
                xh = jnp.asarray(
                    rng.integers(-2, nbins + 2, n), jnp.int32)
                xh_np = np.asarray(xh)
                np.testing.assert_array_equal(
                    np.asarray(histogram_dist(xh, nbins, mesh)),
                    np.bincount(xh_np[(xh_np >= 0) & (xh_np < nbins)],
                                minlength=nbins))

            for rows, k in ((1, 1), (5, 3), (3, 64)):
                g = jnp.asarray(
                    rng.standard_normal((rows * P_, 37)), jnp.float32)
                np.testing.assert_allclose(
                    np.asarray(jacobi2d_dist(g, 4, mesh, k=k)),
                    np.asarray(jacobi2d_reference(g, 4)),
                    rtol=1e-5, atol=1e-6)
            g3 = jnp.asarray(
                rng.standard_normal((3 * P_, 5, 37)), jnp.float32)
            np.testing.assert_allclose(
                np.asarray(jacobi3d_dist(g3, 3, mesh, k=2)),
                np.asarray(jacobi3d_reference(g3, 3)),
                rtol=1e-5, atol=1e-6)

            nb = 9 * P_
            state = tuple(
                jnp.asarray(rng.standard_normal(nb), jnp.float32)
                for _ in range(6)) + (
                jnp.asarray(rng.uniform(0.5, 1.5, nb), jnp.float32),)
            ref = nbody_reference(*state, steps=2)
            for fn in (nbody_dist_psum, nbody_dist_ring):
                for got, want in zip(fn(state, 2, mesh), ref):
                    np.testing.assert_allclose(
                        np.asarray(got), np.asarray(want),
                        rtol=5e-4, atol=5e-5)

            xb = jnp.asarray(
                rng.standard_normal((P_, 13)), jnp.float32)
            for root in (0, P_ - 1):
                np.testing.assert_array_equal(
                    np.asarray(bcast(xb, mesh, root=root)),
                    np.tile(np.asarray(xb)[root], (P_, 1)))
        print('OK')
    """)
    assert "OK" in out


def test_busbw_sweep_runs():
    out = run_cpu8("""
        from tpukernels.parallel.busbw import sweep, bus_bandwidth
        res = sweep(min_bytes=1024, max_bytes=16384, reps=2, verbose=True)
        assert len(res) == 3
        assert all(bw > 0 for _, _, bw in res)
        # the sendrecv-analog mode (per-link point-to-point accounting)
        res_pp = sweep(min_bytes=1024, max_bytes=4096, reps=2,
                       op="ppermute", verbose=False)
        assert len(res_pp) == 2
        assert all(bw > 0 for _, _, bw in res_pp)
        try:
            sweep(op="nope")
            raise SystemExit("sweep(op='nope') did not raise")
        except ValueError as e:
            assert "nope" in str(e)
        # accounting formula spot-checks
        assert abs(bus_bandwidth(1.0, 1e9, 8) - 2*7/8) < 1e-9
        assert abs(bus_bandwidth(1.0, 1e9, 1) - 1.0) < 1e-9
        print('OK')
    """)
    assert "OK" in out


def test_busbw_collective_not_narrowed():
    """The sweep's metric-of-record program must move the FULL message
    through the collective. Lower the exact timed program
    (busbw.timed_program) through XLA's optimization pipeline and
    assert the all-reduce / collective-permute operand in the
    optimized HLO carries every element — if a future probe (or a
    future XLA) narrows the collective to the live slice of a partial
    probe, this fails."""
    out = run_cpu8("""
        import re
        import numpy as np
        import jax
        from tpukernels.parallel.busbw import timed_program
        from tpukernels.parallel.mesh import make_mesh

        mesh = make_mesh()
        nranks = mesh.shape["x"]
        assert nranks == 8
        elems = 2048  # 8 KiB message per rank row
        x = np.ones((nranks, elems), np.float32)

        for op, hlo_op in (("allreduce", "all-reduce"),
                           ("ppermute", "collective-permute")):
            fn = timed_program(op, mesh)
            hlo = fn.lower(x).compile().as_text()
            # optimized HLO is SPMD-partitioned: the per-shard operand
            # is (1, elems); collect every <op>(...) result shape
            pat = "f32\\\\[([0-9,]+)\\\\][^=\\\\n]*? " + hlo_op + "\\\\("
            shapes = [
                tuple(int(d) for d in m.group(1).split(","))
                for m in re.finditer(pat, hlo)
            ]
            assert shapes, f"no {hlo_op} op in optimized HLO for {op}"
            full = max(int(np.prod(s)) for s in shapes)
            assert full >= elems, (
                f"{op}: collective narrowed to {shapes} "
                f"(expected >= {elems} elements)"
            )
            print(op, "shapes", shapes)
        print('OK')
    """)
    assert "OK" in out


def test_depth_pipeline_bitwise_identical_two_procs():
    """ISSUE 20 acceptance: TPK_DIST_DEPTH=2/3 must be BITWISE
    identical to the depth-1 path of record for both pipelined kernels
    (nbody_dist_ring's ring and _jacobi_dist's halo bands) under real
    2-process gloo, and the same run must produce span evidence of
    comm/compute concurrency (an overlap/<op> span holding comm/<op>
    and compute/<op> children plus an overlap_point with a measured
    overlap_frac)."""
    run_two_procs("""
        import json, os, sys, tempfile
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        os.environ["TPK_TRACE"] = "1"
        os.environ.pop("TPK_DIST_DEPTH", None)
        journal_path = os.path.join(tempfile.mkdtemp(), "health.jsonl")
        os.environ["TPK_HEALTH_JOURNAL"] = journal_path
        import jax
        from tpukernels.compat import ensure_cpu_collectives
        ensure_cpu_collectives()  # 0.4.x jax ships CPU gloo off
        pid = int(sys.argv[1])
        jax.distributed.initialize(
            "127.0.0.1:{port}", num_processes=2, process_id=pid)
        import numpy as np, jax.numpy as jnp
        assert jax.device_count() == 8
        from tpukernels.parallel import make_mesh, overlap
        from tpukernels.parallel.mesh import (
            host_to_global, global_to_host, row_sharding)
        from tpukernels.parallel.collectives import (
            allreduce_sum, jacobi3d_dist, nbody_dist_ring)
        mesh = make_mesh(8)
        sh = row_sharding(mesh)
        rng = np.random.default_rng(7)  # same seed on both hosts
        nb = 64
        state_np = [rng.standard_normal(nb).astype(np.float32)
                    for _ in range(6)]
        state_np.append(rng.uniform(0.5, 1.5, nb).astype(np.float32))
        grid = rng.standard_normal((64, 8, 8)).astype(np.float32)

        def barrier():
            # draining rendezvous between kernel rounds: receiving the
            # peer's allreduce contribution proves it finished (and its
            # socket drained) the previous round — without it, a proc
            # that races ahead interleaves the NEXT executable's gloo
            # traffic with the peer's in-flight round and the transport
            # aborts on a pair size mismatch (the busbw.py tcp/pair.cc
            # note; depth changes the executable every round here, so
            # this test is maximally exposed)
            b = host_to_global(np.ones((8, 1), np.float32), sh)
            global_to_host(allreduce_sum(b, mesh))

        def run_at(depth):
            os.environ["TPK_DIST_DEPTH"] = str(depth)
            state = tuple(host_to_global(a, sh) for a in state_np)
            nb_out = nbody_dist_ring(state, 2, mesh)
            nb_bytes = tuple(
                global_to_host(o).tobytes() for o in nb_out)
            barrier()
            jc_out = jacobi3d_dist(host_to_global(grid, sh), 8, mesh)
            jc_bytes = global_to_host(jc_out).tobytes()
            barrier()
            return nb_bytes, jc_bytes

        ref_nb, ref_jc = run_at(1)
        for depth in (2, 3):
            got_nb, got_jc = run_at(depth)
            assert got_nb == ref_nb, (
                "nbody depth %d not bitwise identical to depth 1"
                % depth)
            assert got_jc == ref_jc, (
                "jacobi3d depth %d not bitwise identical to depth 1"
                % depth)

        # span evidence in the SAME run: the overlap probe at depth 2
        pts = overlap.measure(
            ops=("nbody_ring",), mesh=mesh, depth=2, reps=2,
            quick=True, verbose=False, fake=True)
        assert len(pts) == 1
        frac = pts[0]["overlap_frac"]
        assert 0.0 <= frac <= 1.0
        events = [json.loads(line) for line in open(journal_path)
                  if line.strip()]
        spans = [e for e in events if e.get("kind") == "span"]
        names = [e["name"] for e in spans]
        assert "overlap/nbody_ring" in names, names
        assert "overlap/nbody_ring/comm/nbody_ring" in names, names
        assert "overlap/nbody_ring/compute/nbody_ring" in names, names
        op_events = [e for e in events
                     if e.get("kind") == "overlap_point"]
        assert len(op_events) == 1
        assert op_events[0]["op"] == "nbody_ring"
        assert op_events[0]["depth"] == 2
        assert op_events[0]["fake"] is True
        print("overlap_frac", frac)
        print(f"proc {{pid}}: OK")
    """)


def test_allreduce2d_two_phase_matches_sum():
    """2-D mesh allreduce (ISSUE 20 tentpole 2): the reduce-scatter-
    along-x / allgather-along-y decomposition over make_mesh((2, 4))
    must equal the plain row sum, and the mesh must carry both axes."""
    out = run_cpu8("""
        import jax, numpy as np, jax.numpy as jnp
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.collectives import allreduce_sum
        mesh = make_mesh((2, 4))
        assert mesh.shape["x"] == 2 and mesh.shape["y"] == 4
        rng = np.random.default_rng(11)
        x = jnp.asarray(rng.standard_normal((8, 512)), jnp.float32)
        out = np.asarray(allreduce_sum(x, mesh))
        want = np.asarray(x).sum(axis=0)
        # the two-phase decomposition reorders the summation, so the
        # tolerance is looser than the 1-D ring's
        for r in range(8):
            np.testing.assert_allclose(out[r], want, rtol=1e-4,
                                       atol=1e-5)
        print('OK')
    """)
    assert "OK" in out


def test_mesh2d_host_global_roundtrip_two_procs():
    """Bugfix ride-along (ISSUE 20): host_to_global/global_to_host on
    a 2-D sharding across a REAL process boundary — the helpers used
    to assume the 1-D row sharding, so a (2, 4) mesh with rows split
    over both axes mis-assembled on multi-process runs."""
    run_two_procs("""
        import os, sys
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        import jax
        from tpukernels.compat import ensure_cpu_collectives
        ensure_cpu_collectives()  # 0.4.x jax ships CPU gloo off
        pid = int(sys.argv[1])
        jax.distributed.initialize(
            "127.0.0.1:{port}", num_processes=2, process_id=pid)
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel.mesh import (
            host_to_global, global_to_host)
        mesh = make_mesh((2, 4))
        rng = np.random.default_rng(5)  # same seed on both hosts
        full = rng.standard_normal((16, 12)).astype(np.float32)
        # rows split over BOTH mesh axes: 8-way on dim 0
        sh = NamedSharding(mesh, P(("x", "y"), None))
        x = host_to_global(full, sh)
        np.testing.assert_array_equal(global_to_host(x), full)
        # columns on y only: 2-D tiling, neither axis trivial
        sh2 = NamedSharding(mesh, P("x", "y"))
        x2 = host_to_global(full, sh2)
        np.testing.assert_array_equal(global_to_host(x2), full)
        print(f"proc {{pid}}: OK")
    """)


def test_dispatch_mesh_matches_single_device():
    """Serve-over-mesh dispatch layer (ISSUE 20 tentpole 3): every
    registry.MESH_KERNELS entry dispatched through dispatch_mesh on a
    4-device ring must match the single-device registry.dispatch
    answer, bump the dispatch.mesh.<kernel> counter, and reject bad
    mesh shapes loudly."""
    out = run_cpu8("""
        import numpy as np, jax.numpy as jnp
        from tpukernels import registry
        from tpukernels.obs import metrics

        x = np.arange(1 << 14, dtype=np.int32)
        out = registry.dispatch_mesh("scan", jnp.asarray(x),
                                     mesh_shape=(4,))
        np.testing.assert_array_equal(np.asarray(out), np.cumsum(x))
        out = registry.dispatch_mesh("scan_exclusive", jnp.asarray(x),
                                     mesh_shape=(4,))
        np.testing.assert_array_equal(np.asarray(out),
                                      np.cumsum(x) - x)
        h = np.random.default_rng(0).integers(
            0, 256, 1 << 14).astype(np.int32)
        out = registry.dispatch_mesh("histogram", jnp.asarray(h),
                                     mesh_shape=(4,), nbins=256)
        np.testing.assert_array_equal(np.asarray(out),
                                      np.bincount(h, minlength=256))
        g = np.random.default_rng(1).standard_normal(
            (64, 32)).astype(np.float32)
        m2 = registry.dispatch_mesh("stencil2d", jnp.asarray(g),
                                    mesh_shape=(4,), iters=4)
        s2 = registry.dispatch("stencil2d", jnp.asarray(g), iters=4)
        np.testing.assert_allclose(np.asarray(m2), np.asarray(s2),
                                   rtol=1e-5, atol=1e-5)
        g3 = np.random.default_rng(2).standard_normal(
            (16, 12, 10)).astype(np.float32)
        m3 = registry.dispatch_mesh("stencil3d", jnp.asarray(g3),
                                    mesh_shape=(4,), iters=2)
        s3 = registry.dispatch("stencil3d", jnp.asarray(g3), iters=2)
        np.testing.assert_allclose(np.asarray(m3), np.asarray(s3),
                                   rtol=1e-5, atol=1e-5)
        rng = np.random.default_rng(3)
        st = [rng.standard_normal(64).astype(np.float32)
              for _ in range(6)]
        st.append(rng.uniform(0.5, 1.5, 64).astype(np.float32))
        outs = registry.dispatch_mesh(
            "nbody", *(jnp.asarray(a) for a in st), mesh_shape=(4,),
            dt=1e-3, eps=1e-2, steps=2)
        ref = registry.dispatch(
            "nbody", *(jnp.asarray(a) for a in st),
            dt=1e-3, eps=1e-2, steps=2)
        for a, b in zip(outs, ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-3, atol=1e-3)
        snap = metrics.snapshot()
        assert snap["counters"].get("dispatch.mesh.scan") == 1
        assert snap["counters"].get("dispatch.calls.scan", 0) >= 1
        try:
            registry.dispatch_mesh("scan", jnp.asarray(x),
                                   mesh_shape=None)
            raise SystemExit("expected ValueError for mesh_shape=None")
        except ValueError:
            pass
        try:
            registry.dispatch_mesh("scan", jnp.asarray(x),
                                   mesh_shape=(16,))
            raise SystemExit("expected ValueError: only 8 devices")
        except ValueError:
            pass
        try:
            registry.dispatch_mesh("sgemm", np.zeros((8, 8), np.float32),
                                   np.zeros((8, 8), np.float32),
                                   mesh_shape=(4,))
            raise SystemExit("expected KeyError for non-mesh kernel")
        except KeyError:
            pass
        print('OK')
    """)
    assert "OK" in out
