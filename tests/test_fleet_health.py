"""CPU chaos suite for the self-healing serving fleet
(docs/SERVING.md §self-healing; ISSUE 14).

The acceptance headline, all on CPU over Unix sockets: a `kill -9`'d
worker mid-burst (the new ``kill_worker`` fault key, env-narrowed by
``TPK_SERVE_WORKER_ID``) is detected within a probe interval, its shm
leftovers swept, its in-flight request REPLAYED on the ring sibling
(zero dropped accepted requests, the replay reassembling in
``reqtrace`` with an explicit dead-worker gap), and the worker is
respawned and back in the ring before the seeded loadgen run ends —
with ``obs_report --check`` rc 0. Plus: crash-loop → loud quarantine
instead of flapping, both-ring-members-down → priority-ordered
shedding with honest retry hints, the client-side stale-socket
reconnect across a daemon restart, and the pure units (pidfile
probes, targeted shm sweep, retry-hint arithmetic, ``kill_worker``
match rules, the reqtrace gap).
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from test_distributed import _scrubbed_env
from test_fleet import _ctl, _fleet
from test_serve import _events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# scan's 8192 exact-fit bucket (direct ServeClient dispatches below);
# its md5 ring placement is the routing oracle (test_fleet pins the
# ring math itself)
SCAN_BUCKET_ID = "scan|8192|-"


def _record_bucket_id(kernel="scan"):
    """The bucket id a ``loadgen --shapes record`` request rides —
    computed from the LIVE avatar table, never assumed: the record
    shape is whatever ``aot.BENCH_CONFIGS`` registers, and the kill
    plan must target that bucket's actual ring home."""
    from tpukernels.serve import bucketing

    spec = bucketing.bucket_configs()[kernel]
    arrays = [
        np.zeros(shape, dtype=np.dtype(name))
        for name, shape in bucketing._spec_args(spec)
    ]
    statics = dict(spec.get("statics") or {})
    bspec, _frac = bucketing.bucket_for(kernel, arrays, statics)
    return bucketing.bucket_id(kernel, bspec, statics, arrays)

FAST_HEALTH = {
    "TPK_FLEET_PROBE_S": "0.3",
    "TPK_FLEET_RESTART_BACKOFF_S": "0.2",
}


def _wait_events(journal, pred, timeout=90.0, msg="event"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        events = _events(journal)
        hits = [e for e in events if pred(e)]
        if hits:
            return events, hits
        time.sleep(0.3)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------- #
# pure units                                                       #
# ---------------------------------------------------------------- #

def test_probe_and_sweep_units(tmp_path):
    from tpukernels.serve import health, protocol

    # a worker that never existed is dead, not slow
    assert health.probe_worker(str(tmp_path / "no.sock"), 0.2) == (
        "dead", None,
    )
    assert health.pidfile_state(str(tmp_path / "no.pid")) == (
        False, None,
    )
    # an unheld pidfile with a recorded pid: dead, pid preserved
    pf = tmp_path / "serve.pid"
    pf.write_text("12345\n")
    assert health.pidfile_state(str(pf)) == (False, 12345)

    # targeted shm sweep: a DEAD creator's segment is reclaimed with
    # its byte count; a live creator's segment is left alone
    child = subprocess.run([sys.executable, "-c", "import os;"
                            "print(os.getpid())"],
                           capture_output=True, text=True)
    dead_pid = int(child.stdout.strip())
    dead_name = f"tpkserve-{dead_pid}-0-deadbeef"
    live_name = f"tpkserve-{os.getpid()}-0-deadbeef"
    for name in (dead_name, live_name):
        with open(os.path.join(protocol.SHM_DIR, name), "wb") as f:
            f.write(b"\0" * 24)
    try:
        assert protocol.sweep_segments_for_pid(dead_pid) == (1, 24)
        assert not os.path.exists(
            os.path.join(protocol.SHM_DIR, dead_name)
        )
        assert protocol.sweep_segments_for_pid(os.getpid()) == (0, 0)
        assert os.path.exists(
            os.path.join(protocol.SHM_DIR, live_name)
        )
        # junk pids are refused, not trusted
        assert protocol.sweep_segments_for_pid("9") == (0, 0)
        assert protocol.sweep_segments_for_pid(-4) == (0, 0)
    finally:
        protocol.unlink_shm(live_name)
        protocol.unlink_shm(dead_name)


def test_retry_hint_and_knob_parse(tmp_path, monkeypatch):
    from tpukernels.serve import health

    hm = health.HealthManager(
        [str(tmp_path / "w0" / "serve.sock"),
         str(tmp_path / "w1" / "serve.sock")],
        repo=REPO, probe_s=0.5, restart_max=2, backoff_s=0.2,
    )
    # all up: the hint is one probe interval's patience
    assert hm.retry_hint() == 0.5
    # a down worker's hint is its backoff remainder + a probe
    hm.workers[0].state = "down"
    hm.workers[0].next_attempt = time.perf_counter() + 2.0
    hint = hm.retry_hint({0})
    assert 2.0 < hint <= 3.0
    # quarantined workers are not coming back: the cap
    hm.workers[1].state = "quarantined"
    assert hm.retry_hint({1}) == health.MAX_DEGRADED_HINT_S
    # the soonest candidate wins across a set
    assert hm.retry_hint({0, 1}) == hint
    # fail-loud knob parses (the daemon knob contract)
    monkeypatch.setenv("TPK_FLEET_PROBE_S", "banana")
    with pytest.raises(ValueError, match="TPK_FLEET_PROBE_S"):
        health.HealthManager(["x"], repo=REPO)
    monkeypatch.setenv("TPK_FLEET_PROBE_S", "0.5")
    monkeypatch.setenv("TPK_FLEET_RESTART_MAX", "0")
    with pytest.raises(ValueError, match="TPK_FLEET_RESTART_MAX"):
        health.HealthManager(["x"], repo=REPO)


def test_reset_probes_before_reringing_and_disabled_mode(tmp_path):
    """`undrain`'s health reset must not put a corpse back in the
    ring: a still-dead worker stays down and is scheduled for an
    immediate respawn; with the manager DISABLED
    (TPK_FLEET_PROBE_S=0) the operator's word is restored verbatim
    and transport losses never declare deaths (nothing could revive
    them)."""
    from tpukernels.serve import health

    class _RouterStub:
        def __init__(self):
            self.calls = []

        def set_worker_down(self, idx, down, quarantined=False):
            self.calls.append((idx, down))

        def worker_draining(self, idx):
            return False

    sock = str(tmp_path / "w0" / "serve.sock")
    r = _RouterStub()
    hm = health.HealthManager([sock], repo=REPO, router=r,
                              probe_s=0.5, restart_max=2,
                              backoff_s=0.2)
    w = hm.workers[0]
    w.state = "quarantined"
    w.crashes = 5
    w.smoke_fails = 3
    hm.reset(0)  # no pidfile anywhere: the worker is a corpse
    assert w.state == "down"
    assert (w.crashes, w.smoke_fails) == (0, 0)
    assert r.calls[-1] == (0, True), "a corpse must stay out of the ring"
    # disabled manager: reset trusts the operator (old contract) ...
    hm0 = health.HealthManager([sock], repo=REPO, router=r,
                               probe_s=0, restart_max=2,
                               backoff_s=0.2)
    hm0.workers[0].state = "quarantined"
    hm0.reset(0)
    assert hm0.workers[0].state == "up"
    assert r.calls[-1] == (0, False)
    # ... and transport losses never declare deaths it cannot heal
    assert hm0.note_transport_loss(0) is False
    assert hm0.workers[0].state == "up"


def test_kill_worker_fault_match_rules(tmp_path, monkeypatch):
    """The kill_worker spec's NON-firing paths are provable
    in-process (the firing path would SIGKILL pytest — the fleet e2e
    below proves it for real): wrong kernel, wrong env, wrong call
    number, and a consumed once_file all leave the process alive."""
    from tpukernels.resilience import faults

    once = tmp_path / "fired"
    once.write_text("1\n")
    monkeypatch.setenv("TPK_FAULT_PLAN", json.dumps({
        "kill_worker": {"kernel": "scan", "on_call": 2,
                        "once_file": str(once),
                        "env": {"TPK_SERVE_WORKER_ID": "0"}},
    }))
    monkeypatch.setenv("TPK_SERVE_WORKER_ID", "0")
    faults.reload_plan()
    try:
        faults.dispatch_fault("sgemm")   # kernel mismatch
        faults.dispatch_fault("scan")    # call 1 != on_call 2
        faults.dispatch_fault("scan")    # call 2, but once_file exists
        monkeypatch.setenv("TPK_SERVE_WORKER_ID", "1")
        faults.reload_plan()
        faults.dispatch_fault("scan")    # env mismatch
        faults.dispatch_fault("scan")
    finally:
        monkeypatch.delenv("TPK_FAULT_PLAN", raising=False)
        faults.reload_plan()


def test_reqtrace_dead_worker_gap_unit():
    from tpukernels.obs import reqtrace

    events = [
        {"kind": "serve_client_request", "request_id": "r1",
         "kernel": "scan", "wall_s": 0.5, "ok": True, "t": 100.0},
        {"kind": "serve_spill", "request_id": "r1", "kernel": "scan",
         "from_worker": 0, "to_worker": 1, "reason": "transport",
         "t": 100.1},
        {"kind": "serve_request_replayed", "request_id": "r1",
         "kernel": "scan", "from_worker": 0, "to_worker": 1,
         "t": 100.1, "pid": 7},
        {"kind": "serve_request", "request_id": "r1",
         "kernel": "scan", "ok": True, "worker_id": "1",
         "wall_s": 0.01, "t": 100.4},
    ]
    tl = reqtrace.assemble(events)["r1"]
    assert tl["replayed"] is True
    assert tl["clean"] is False, "a replayed request must never gate"
    gaps = {g["kind"] for g in tl["gaps"]}
    assert "dead-worker" in gaps
    assert "missing-server-record" not in gaps  # the sibling answered
    assert tl["final"]["worker_id"] == "1"


# ---------------------------------------------------------------- #
# the chaos e2e suite                                              #
# ---------------------------------------------------------------- #

def test_kill_worker_mid_burst_self_heals(tmp_path):
    """THE acceptance headline: kill -9 the scan bucket's home worker
    mid-burst (kill_worker fault, once_file so the respawned
    incarnation runs clean) — the seeded loadgen run drops ZERO
    requests (the in-flight one is replayed on the sibling with
    serve_request_replayed evidence + a reqtrace dead-worker gap),
    the dead worker's death is journaled with its swept shm
    accounting, it is respawned + smoke-gated back into the ring
    before run end, the degradation level round-trips
    degraded -> ok, and obs_report --check stays rc 0."""
    from tpukernels.obs import reqtrace
    from tpukernels.serve import router

    primary, sibling = router.ring_order(_record_bucket_id(), 2)[:2]
    once = tmp_path / "killed.once"
    plan = json.dumps({"kill_worker": {
        "kernel": "scan", "on_call": 3, "once_file": str(once),
        "env": {"TPK_SERVE_WORKER_ID": str(primary)},
    }})
    slo_dir = tmp_path / "slo"
    slo_dir.mkdir()
    with _fleet(tmp_path, n=2, env_extra=dict(FAST_HEALTH, **{
        "TPK_FAULT_PLAN": plan,
        "TPK_TRACE": "1",
    })) as (front, journal, env):
        lg_env = dict(env)
        lg_env["TPK_SLO_DIR"] = str(slo_dir)
        # the injected outage puts one cold spill compile in the tail
        # on purpose; this test judges the healing, not the p99 —
        # widen the targets the honest way (the known-slow-host knob)
        lg_env["TPK_SLO_SCALE"] = "100"
        lg = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "loadgen.py"),
             "--serve", front, "--kernel", "scan", "--shapes",
             "record", "--arrivals", "poisson", "--seed", "11",
             "--requests", "50", "--rate", "2", "--tenant", "chaos"],
            capture_output=True, text=True, timeout=300, cwd=REPO,
            env=lg_env,
        )
        assert lg.returncode == 0, lg.stdout + lg.stderr
        assert "dropped" not in lg.stderr, lg.stderr
        # the fleet converged back to 2 live ring members
        r = _ctl(env, "health", "--wait", "60")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "CONVERGED" in r.stdout
        r = _ctl(env, "status")
        assert r.returncode == 0, r.stdout + r.stderr
        assert "restarts=1" in r.stdout
    assert once.exists(), "the kill fault never fired"

    events = _events(journal)
    # zero dropped accepted requests: every client-observed request ok
    client_reqs = [e for e in events
                   if e.get("kind") == "serve_client_request"]
    assert len(client_reqs) == 51  # 50 scheduled + 1 warm
    assert all(e.get("ok") for e in client_reqs)
    # the death was detected, attributed and swept
    dead = [e for e in events if e.get("kind") == "worker_dead"]
    assert len(dead) == 1
    assert dead[0]["worker"] == primary
    assert dead[0]["via"] in ("transport", "probe")
    assert dead[0]["crashes"] == 1
    assert "swept_segments" in dead[0] and "swept_bytes" in dead[0]
    # the in-flight request was replayed ONCE onto the ring sibling
    replays = [e for e in events
               if e.get("kind") == "serve_request_replayed"]
    assert len(replays) == 1
    assert replays[0]["from_worker"] == primary
    assert replays[0]["to_worker"] == sibling
    rid = replays[0]["request_id"]
    assert rid is not None
    # ... and the sibling's serve_request carries the replay count
    replayed_srv = [e for e in events
                    if e.get("kind") == "serve_request"
                    and e.get("request_id") == rid]
    assert any(e.get("replayed") == 1 and e.get("ok")
               for e in replayed_srv)
    # the replay reassembles with an EXPLICIT dead-worker gap
    tl = reqtrace.assemble(events)[rid]
    assert tl["clean"] is False
    assert any(g["kind"] == "dead-worker" for g in tl["gaps"])
    assert tl["final"]["ok"]
    # respawn + smoke-gated rejoin happened DURING the run
    resp = [e for e in events if e.get("kind") == "worker_respawned"]
    assert len(resp) == 1 and resp[0]["worker"] == primary
    assert resp[0]["down_s"] is not None
    # traffic returned to the healed home before run end
    t_rejoin = resp[0]["t"]
    post = [e for e in events if e.get("kind") == "serve_route"
            and e.get("t", 0) > t_rejoin]
    assert any(e["worker"] == primary for e in post), (
        "no routed request landed on the healed worker after rejoin"
    )
    # degradation level round-tripped degraded -> ok
    levels = [e["level"] for e in events
              if e.get("kind") == "fleet_degraded"]
    assert levels == ["degraded", "ok"]
    # the rejoin smoke is visible, request-id'd evidence
    assert any(e.get("kind") == "serve_request"
               and str(e.get("request_id") or "").startswith(
                   "fleet-smoke-")
               for e in events)
    # the gating surface is unchanged: no trace_inconsistent /
    # copy_regression / breach from the replay path
    chk_env = _scrubbed_env(None)
    chk_env["TPK_SLO_DIR"] = str(slo_dir)
    chk_env["TPK_SLO_SCALE"] = "100"
    chk = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "obs_report.py"),
         "--check", "--journal", journal],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=chk_env,
    )
    assert chk.returncode == 0, chk.stdout + chk.stderr


def test_crash_loop_quarantines_loudly(tmp_path):
    """Every incarnation of the home worker dies on its first scan
    dispatch (kill_worker WITHOUT once_file — the rejoin smoke is a
    scan, so each respawn dies at its gate): after
    TPK_FLEET_RESTART_MAX confirmed crashes the worker is
    QUARANTINED — left out of the ring loudly instead of flapping —
    while the sibling keeps serving, batch included (shedding needs
    home AND sibling out)."""
    from tpukernels.serve import client as serve_client
    from tpukernels.serve import router

    primary, sibling = router.ring_order(SCAN_BUCKET_ID, 2)[:2]
    plan = json.dumps({"kill_worker": {
        "kernel": "scan",
        "env": {"TPK_SERVE_WORKER_ID": str(primary)},
    }})
    with _fleet(tmp_path, n=2, env_extra=dict(FAST_HEALTH, **{
        "TPK_FAULT_PLAN": plan,
        "TPK_FLEET_RESTART_MAX": "2",
    })) as (front, journal, env):
        x = np.arange(8192, dtype=np.int32)
        want = np.cumsum(x, dtype=np.int64).astype(np.int32)
        with serve_client.ServeClient(front, timeout_s=180) as c:
            # the home dies holding this request; the replay answers
            np.testing.assert_array_equal(c.dispatch("scan", x), want)
        # crash 1 (the kill) + crash 2 (the respawn dies on its own
        # rejoin smoke) -> threshold 2 -> quarantine, no flapping
        events, _ = _wait_events(
            journal,
            lambda e: e.get("kind") == "worker_quarantined",
            timeout=120, msg="worker_quarantined",
        )
        deaths = [e for e in events if e.get("kind") == "worker_dead"]
        assert len(deaths) >= 2
        assert all(e["worker"] == primary for e in deaths)
        assert any(e["via"] == "join" for e in deaths), (
            "the smoke-gate death must be attributed to the join"
        )
        quar = [e for e in events
                if e.get("kind") == "worker_quarantined"]
        assert len(quar) == 1
        assert quar[0]["worker"] == primary
        assert quar[0]["threshold"] == 2
        # no rejoin ever happened: the gate held
        assert not any(e.get("kind") == "worker_respawned"
                       for e in events)
        # the ring still serves, interactive AND batch (home+sibling
        # not BOTH out), from the sibling
        with serve_client.ServeClient(front, timeout_s=180) as c:
            np.testing.assert_array_equal(c.dispatch("scan", x), want)
        with serve_client.ServeClient(front, timeout_s=180,
                                      priority="batch") as c:
            np.testing.assert_array_equal(c.dispatch("scan", x), want)
        events = _events(journal)
        routes = [e for e in events if e.get("kind") == "serve_route"]
        assert all(e["worker"] == sibling for e in routes[-2:])
        # quarantine is visible on the operator surfaces
        r = _ctl(env, "status")
        assert "QUARANTINED" in r.stdout, r.stdout + r.stderr
        r = _ctl(env, "health", "--wait", "1")
        assert r.returncode == 1
        assert "NOT converged" in r.stdout
        # no further respawn attempts accumulate after the breaker
        n_deaths = len([e for e in _events(journal)
                        if e.get("kind") == "worker_dead"])
        time.sleep(2.0)
        assert len([e for e in _events(journal)
                    if e.get("kind") == "worker_dead"]) == n_deaths


def test_both_ring_members_down_sheds_by_priority(tmp_path):
    """Degradation levels: with scan's home AND sibling both dead
    (respawn backoff pinned high so they stay down), the fleet goes
    CRITICAL — batch requests shed FIRST with an honest
    retry_after_s, interactive requests keep riding the last ring
    member — and with every worker dead, interactive sheds too
    instead of timing out."""
    from tpukernels.serve import client as serve_client
    from tpukernels.serve import router

    ring = router.ring_order(SCAN_BUCKET_ID, 3)
    home, sib, last = ring[0], ring[1], ring[2]
    with _fleet(tmp_path, n=3, env_extra={
        "TPK_FLEET_PROBE_S": "0.3",
        # down workers must STAY down for the length of the test
        "TPK_FLEET_RESTART_BACKOFF_S": "120",
    }) as (front, journal, env):
        serve_dir = env["TPK_SERVE_DIR"]

        def _kill(idx):
            pidfile = os.path.join(serve_dir, "fleet", f"worker{idx}",
                                   "serve.pid")
            with open(pidfile) as f:
                os.kill(int(f.readline().strip()), signal.SIGKILL)

        _kill(home)
        _kill(sib)
        events, crit = _wait_events(
            journal,
            lambda e: (e.get("kind") == "fleet_degraded"
                       and e.get("level") == "critical"),
            timeout=30, msg="fleet_degraded critical",
        )
        assert sorted(crit[-1]["down"]) == sorted([home, sib])
        x = np.arange(8192, dtype=np.int32)
        want = np.cumsum(x, dtype=np.int64).astype(np.int32)
        # batch sheds FIRST: home+sibling both out
        with serve_client.ServeClient(front, timeout_s=60,
                                      priority="batch",
                                      tenant="bg") as c:
            with pytest.raises(serve_client.ServeRejected) as exc:
                c.dispatch("scan", x)
        assert 0 < exc.value.retry_after_s <= 30.0
        # interactive still rides the last ring member
        with serve_client.ServeClient(front, timeout_s=180) as c:
            np.testing.assert_array_equal(c.dispatch("scan", x), want)
        events = _events(journal)
        routes = [e for e in events if e.get("kind") == "serve_route"
                  and e.get("ok")]
        assert routes and routes[-1]["worker"] == last
        sheds = [e for e in events if e.get("kind") == "serve_rejected"
                 and e.get("reason") == "fleet_degraded"]
        assert len(sheds) == 1
        assert sheds[0]["priority"] == "batch"
        assert sheds[0]["request_id"] is not None
        # nothing left alive: interactive sheds too, with the hint —
        # an honest answer instead of a client timeout
        _kill(last)
        _wait_events(
            journal,
            lambda e: (e.get("kind") == "worker_dead"
                       and e.get("worker") == last),
            timeout=30, msg=f"worker_dead for worker {last}",
        )
        with serve_client.ServeClient(front, timeout_s=60) as c:
            with pytest.raises(serve_client.ServeRejected) as exc:
                c.dispatch("scan", x)
        assert 0 < exc.value.retry_after_s <= 30.0
        r = _ctl(env, "health", "--wait", "1")
        assert r.returncode == 1, r.stdout + r.stderr


def test_client_reconnects_across_daemon_restart(tmp_path):
    """The stale-socket satellite: a client holding a connection to a
    daemon that was since RESTARTED on the same socket absorbs the
    ECONNRESET/EPIPE/mid-frame-EOF transparently — ONE reconnect,
    SAME request_id — while a daemon that is actually gone still
    surfaces as the transport error it is."""
    from tpukernels.serve import client as serve_client

    d = tmp_path / "solo"
    d.mkdir()
    journal = str(d / "health.jsonl")
    env = _scrubbed_env(None)
    env["TPK_SERVE_DIR"] = str(d)
    env["TPK_HEALTH_JOURNAL"] = journal
    sock = str(d / "serve.sock")
    r = _ctl(env, "start", "--wait", "90")
    assert r.returncode == 0, r.stdout + r.stderr
    try:
        x = (np.arange(64) % 7).astype(np.int32)
        want = np.cumsum(x, dtype=np.int64).astype(np.int32)
        cli = serve_client.ServeClient(sock, timeout_s=120)
        out = serve_client.dispatch_with_backpressure(
            cli, "scan", (x,), {}
        )
        np.testing.assert_array_equal(out, want)
        # restart the daemon under the held connection
        assert _ctl(env, "stop", "--wait", "30").returncode == 0
        r = _ctl(env, "start", "--wait", "90")
        assert r.returncode == 0, r.stdout + r.stderr
        cli.next_request_id = "reconnect-rid"
        out = serve_client.dispatch_with_backpressure(
            cli, "scan", (x,), {}
        )
        np.testing.assert_array_equal(out, want)
        assert cli.last_request_id == "reconnect-rid"
        # one logical request, one delivery: the retry reused the id
        # and only the SECOND daemon ever saw it
        served = [e for e in _events(journal)
                  if e.get("kind") == "serve_request"
                  and e.get("request_id") == "reconnect-rid"]
        assert len(served) == 1 and served[0]["ok"]
        # a daemon that is actually gone is still a hard error
        assert _ctl(env, "stop", "--wait", "30").returncode == 0
        with pytest.raises(OSError):
            serve_client.dispatch_with_backpressure(
                cli, "scan", (x,), {}
            )
        cli.close()
    finally:
        _ctl(env, "stop", "--wait", "30")
