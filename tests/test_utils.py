"""Shared-utils tests: the interpret-mode knob every Pallas kernel
consults (TPU_KERNELS_INTERPRET, documented in README) and cdiv."""

import os
import subprocess
import sys

from tpukernels.utils import cdiv

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_cdiv():
    assert cdiv(0, 8) == 0
    assert cdiv(1, 8) == 1
    assert cdiv(8, 8) == 1
    assert cdiv(9, 8) == 2


def _interpret_in_subprocess(override: str | None) -> str:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("TPU_KERNELS_INTERPRET", None)
    if override is not None:
        env["TPU_KERNELS_INTERPRET"] = override
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c",
         "from tpukernels.utils import default_interpret; "
         "print(default_interpret())"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr
    return proc.stdout.strip()


def test_default_interpret_cpu_backend_defaults_on():
    assert _interpret_in_subprocess(None) == "True"


def test_default_interpret_env_override(monkeypatch):
    # the override branch returns before any backend query, so it can
    # be exercised in-process (only the defaults case needs subprocess
    # isolation for backend selection)
    from tpukernels.utils import default_interpret

    for value, want in (("0", False), ("1", True), ("false", False)):
        monkeypatch.setenv("TPU_KERNELS_INTERPRET", value)
        default_interpret.cache_clear()
        assert default_interpret() is want
    default_interpret.cache_clear()  # don't leak state to other tests
