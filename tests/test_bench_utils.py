"""Host-side unit tests for bench.py's timing machinery — the slope
methodology everything in BASELINE.md rests on. The benchmark bodies
need a chip; the watchdog, best-of timer, and slope arithmetic are
pure host code and testable here."""

import time

import numpy as np
import pytest

import bench


def test_with_timeout_interrupts_a_hang():
    with pytest.raises(bench._Timeout):
        bench._with_timeout(lambda: time.sleep(5), seconds=1)


def test_with_timeout_passes_result_and_restores_alarm():
    assert bench._with_timeout(lambda: 42, seconds=1) == 42
    # sleep PAST the 1s alarm: if the cancel in _with_timeout's
    # finally block regressed, the stale alarm fires here and kills
    # the test instead of shipping silently
    time.sleep(1.2)


def test_timeit_returns_best_and_counts_calls():
    calls = []

    def fn():
        calls.append(1)
        return np.zeros(1)

    best = bench._timeit(fn, reps=3, warmup=2)
    assert best >= 0.0
    assert len(calls) == 5  # warmup + reps


def test_slope_cancels_fixed_cost():
    # fake "kernel": cost = FIXED + R * PER_ITER, implemented with
    # sleeps; the slope must recover PER_ITER, not FIXED + PER_ITER
    fixed, per_iter = 0.05, 0.01

    def make_fn(r):
        def fn():
            time.sleep(fixed + r * per_iter)
            return np.zeros(1)

        return fn, ()

    est = bench._slope(make_fn, 2, 10, samples=3)
    assert est == pytest.approx(per_iter, rel=0.3)
