"""Host-side unit tests for bench.py's timing machinery — the slope
methodology everything in BASELINE.md rests on. The benchmark bodies
need a chip; the watchdog, best-of timer, and slope arithmetic are
pure host code and testable here."""

import time

import numpy as np
import pytest

import bench


def test_with_timeout_interrupts_a_hang():
    with pytest.raises(bench._Timeout):
        bench._with_timeout(lambda: time.sleep(5), seconds=1)


def test_with_timeout_passes_result_and_restores_alarm():
    assert bench._with_timeout(lambda: 42, seconds=1) == 42
    # sleep PAST the 1s alarm: if the cancel in _with_timeout's
    # finally block regressed, the stale alarm fires here and kills
    # the test instead of shipping silently
    time.sleep(1.2)


def test_timeit_returns_best_and_counts_calls():
    calls = []

    def fn():
        calls.append(1)
        return np.zeros(1)

    best = bench._timeit(fn, reps=3, warmup=2)
    assert best >= 0.0
    assert len(calls) == 5  # warmup + reps


def test_slope_cancels_fixed_cost():
    # fake "kernel": cost = FIXED + R * PER_ITER, implemented with
    # sleeps; the slope must recover PER_ITER, not FIXED + PER_ITER
    fixed, per_iter = 0.05, 0.01

    def make_fn(r):
        def fn():
            time.sleep(fixed + r * per_iter)
            return np.zeros(1)

        return fn, ()

    est = bench._slope(make_fn, 2, 10, samples=3)
    assert est == pytest.approx(per_iter, rel=0.3)


def test_slope_cancels_linear_latency_drift():
    # Post-recovery tunnel mode (2026-07-31): the fixed cost DRAINS
    # linearly for minutes while the marginal signal is small — the
    # regime where the old (s-then-b) sample order read sgemm 19-58%
    # above its physical ceiling. Constants mirror that shape: the
    # marginal signal is ~8 ms per R-delta against a fixed cost
    # declining 20 ms/s, so the old ordering under-measured Δt by
    # ~50% (reproduced 2026-07-31: 0.000515 for a true 0.001).
    # per_iter=0.001 keeps big/small call durations asymmetric
    # (~82 vs ~90 ms), which also broke palindrome-window schemes;
    # the midpoint-regression estimator must recover PER_ITER with
    # no symmetry assumptions.
    per_iter = 0.001
    t0 = time.monotonic()

    def fixed_now():
        return max(0.0, 0.08 - 0.02 * (time.monotonic() - t0))

    def make_fn(r):
        def fn():
            time.sleep(fixed_now() + r * per_iter)
            return np.zeros(1)

        return fn, ()

    est = bench._slope(make_fn, 2, 10, samples=3)
    assert est == pytest.approx(per_iter, rel=0.3)


def test_check_regression_gates_on_measured_baseline():
    """VERDICT r3 item 3: vs_baseline must be a real ratio against the
    BASELINE.json "measured" medians, and the revalidation queue must
    fail loudly on >15% drops. check_regression is that gate."""
    import json

    ok = json.dumps({
        "value": 60000,
        "vs_measured": {"sgemm_gflops": 0.99, "saxpy_gb_s": 1.02},
        "details": {"sgemm_gflops": 60000, "saxpy_gb_s": 9300},
    })
    assert bench.check_regression(ok) == 0

    slow = json.dumps({
        "value": 48000,
        "vs_measured": {"sgemm_gflops": 0.79},
        "details": {"sgemm_gflops": 48000},
    })
    assert bench.check_regression(slow) == 1
    # inside tolerance passes
    assert bench.check_regression(slow, tolerance=0.25) == 0

    # coverage failures are rc 2 (retryable: nothing measured slow),
    # distinct from rc 1 (deterministic regression) — the watcher's
    # retry loop keys on this split
    nulled = json.dumps({"value": None, "vs_measured": {}, "details": {}})
    assert bench.check_regression(nulled) == 2

    # a metric that errored out (details value None) must fail even if
    # every surviving ratio is healthy — but as retryable coverage
    partial = json.dumps({
        "value": 60000,
        "vs_measured": {"sgemm_gflops": 1.0},
        "details": {"sgemm_gflops": 60000, "nbody_ginter_s": None},
    })
    assert bench.check_regression(partial) == 2

    # regression + missing together -> 1 (the regression is the more
    # actionable fact; retrying won't fix it)
    both = json.dumps({
        "value": 48000,
        "vs_measured": {"sgemm_gflops": 0.79},
        "details": {"sgemm_gflops": 48000, "nbody_ginter_s": None},
    })
    assert bench.check_regression(both) == 1


def test_baseline_measured_block_covers_all_bench_metrics():
    """Every metric bench.py reports must have a measured median to
    regress against — a new bench_* without a BASELINE.json row would
    silently escape the gate. Iterates bench.BENCH_METRICS itself (the
    list main() runs) so adding a metric there without a baseline row
    fails here."""
    measured = bench._load_baseline().get("measured", {})
    assert len(bench.BENCH_METRICS) >= 7
    for name, _fn in bench.BENCH_METRICS:
        assert isinstance(measured.get(name), (int, float)), name


def test_ratios_vs_baseline_merge_and_zero():
    """Per-metric published-over-measured precedence (one published
    entry must not strip other metrics' gates) and the measured-0.0
    case (must surface as ratio 0.0, not vanish)."""
    baseline = {
        "measured": {"a": 100.0, "b": 50.0, "c": 10.0},
        "published": {"a": 200.0},
    }
    results = {"a": 100.0, "b": 0.0, "c": None, "d": 5.0}
    r = bench._ratios_vs_baseline(results, baseline)
    assert r == {"a": 0.5, "b": 0.0}  # a vs published, b vs measured
    # and check_regression flags the 0.0 ratio
    import json
    line = json.dumps({"value": 100.0, "vs_measured": r,
                       "details": {"a": 100.0, "b": 0.0}})
    assert bench.check_regression(line) == 1


def test_check_regression_cli():
    """The tools/tpu_revalidate.sh invocation path: JSON line on
    stdin, verdict as exit status."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # no backend init needed,
    env["JAX_PLATFORMS"] = "cpu"           # but keep imports cheap/safe

    def run(line):
        return subprocess.run(
            [sys.executable, "bench.py", "--check-regression"],
            input=line, capture_output=True, text=True, cwd=repo, env=env,
            timeout=120,
        )

    ok = run(json.dumps({"value": 1.0, "vs_measured": {"m": 1.0},
                         "details": {"m": 1.0}}))
    assert ok.returncode == 0, ok.stdout + ok.stderr
    bad = run(json.dumps({"value": None, "vs_measured": {},
                          "details": {}}))
    assert bad.returncode == 2, bad.stdout + bad.stderr  # retryable
    assert "REGRESSION" in bad.stdout
    slow = run(json.dumps({"value": 1.0, "vs_measured": {"m": 0.5},
                           "details": {"m": 1.0}}))
    assert slow.returncode == 1, slow.stdout + slow.stderr  # deterministic


def test_one_metric_child_protocol():
    """`bench.py --one <name>` is the killable-child half of main()'s
    per-metric isolation (a wedged PJRT call ignores SIGALRM, so each
    metric runs in a subprocess the parent can kill): last stdout line
    must be JSON with the metric's value. TPK_BENCH_SMOKE collapses
    the slope loop so this runs on CPU in seconds."""
    import json
    import os
    import subprocess
    import sys

    from test_distributed import _scrubbed_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _scrubbed_env(fake_devices=None)  # CPU, never the tunnel
    env["TPK_BENCH_SMOKE"] = "1"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--one", "saxpy_gb_s"],
        env=env, capture_output=True, text=True, timeout=300, cwd=repo,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["name"] == "saxpy_gb_s"
    assert isinstance(rec["value"], float) and rec["value"] > 0


def test_run_one_subprocess_classifies_failures():
    """The parent half: an unknown metric exits nonzero -> "error"
    (fast KeyError, no backend touched); an impossible deadline kills
    the child mid-startup -> "timeout" (the wedge signature main()'s
    fast-fail probe keys on)."""
    value, status = bench._run_one_subprocess("no_such_metric", 120)
    assert (value, status) == (None, "error")
    value, status = bench._run_one_subprocess("saxpy_gb_s", 0.5)
    assert (value, status) == (None, "timeout")


def test_one_metric_child_refuses_cpu_fallback():
    """A --one child re-initializes JAX; a fail-fast tunnel outage
    between metrics silently lands it on CPU, and a CPU number must
    never be persisted as a TPU metric. TPK_BENCH_EXPECT_TPU drives
    the guard without the axon plugin (with the real pool var set,
    sitecustomize would dial the tunnel)."""
    import os
    import subprocess
    import sys

    from test_distributed import _scrubbed_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _scrubbed_env(fake_devices=None)  # CPU backend
    env["TPK_BENCH_SMOKE"] = "1"
    env["TPK_BENCH_EXPECT_TPU"] = "1"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--one", "saxpy_gb_s"],
        env=env, capture_output=True, text=True, timeout=300, cwd=repo,
    )
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "refusing to run" in proc.stderr
    assert not proc.stdout.strip()  # no JSON line a parent could parse


def test_main_deadline_emits_json_line(monkeypatch, capsys):
    """The whole-run deadline exists so bench.py ALWAYS emits its JSON
    line itself rather than being killed mid-run by a caller's outer
    timeout (which would discard every captured metric and orphan the
    in-flight child). Deadline 0 -> every metric skipped, line still
    printed, with nulls."""
    import json

    monkeypatch.setattr(bench, "_tpu_alive", lambda *a, **k: True)
    monkeypatch.setattr(
        bench,
        "_run_one_subprocess",
        lambda *a, **k: (_ for _ in ()).throw(
            AssertionError("no child may be spawned past the deadline")
        ),
    )
    monkeypatch.setenv("TPK_BENCH_DEADLINE_S", "0")
    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["value"] is None
    assert set(rec["details"]) == {n for n, _ in bench.BENCH_METRICS}
    assert all(v is None for v in rec["details"].values())


def test_latest_persisted_artifact_picks_newest_nonnull(tmp_path):
    """The unreachable-tunnel pointer must name the newest artifact
    whose headline is non-null — newest by the FILENAME timestamp the
    writer embeds (git does not preserve mtimes, so after a clone the
    mtime order is arbitrary). A later wedged re-run's null line must
    not shadow real numbers captured earlier in the flap cycle."""
    import json
    import os

    logs = tmp_path / "docs" / "logs"
    logs.mkdir(parents=True)
    good = {"metric": "sgemm_gflops_per_chip", "value": 60000.0}
    stale = {"metric": "sgemm_gflops_per_chip", "value": 59000.0}
    null_line = {"metric": "sgemm_gflops_per_chip", "value": None}
    (logs / "bench_2026-07-31_080000.json").write_text(json.dumps(stale))
    (logs / "bench_2026-07-31_120000.json").write_text(json.dumps(good))
    (logs / "bench_2026-07-31_180000.json").write_text(json.dumps(null_line))

    ptr = bench._latest_persisted_artifact(root=str(tmp_path))
    assert ptr["path"] == os.path.join(
        "docs", "logs", "bench_2026-07-31_120000.json"
    )
    assert ptr["line"]["value"] == 60000.0
    assert bench._latest_persisted_artifact(root=str(tmp_path / "nope")) is None


def test_invalidated_artifact_values_stay_dead(tmp_path):
    """Invalidation convention (2026-07-31, the drift-inflated sgemm
    captures): a superseded measurement is moved OUT of details/value
    into an 'invalidated' key — [original_value, reason] — and nulled
    where it stood. Both evidence scanners must treat such an
    artifact by its nulls: the union accumulator must not count the
    invalidated value and the unreachable-tunnel pointer must skip an
    artifact with nothing valid left. No scanner may ever read values
    back out of 'invalidated'."""
    import datetime
    import json

    logs = tmp_path / "docs" / "logs"
    logs.mkdir(parents=True)
    stamp = datetime.datetime.now().strftime("bench_%Y-%m-%d_%H%M%S.json")
    (logs / stamp).write_text(
        json.dumps(
            {
                "metric": "sgemm_gflops_per_chip",
                "value": None,
                "details": {"sgemm_gflops": None},
                "invalidated": {
                    "sgemm_gflops": [95973.82, "drift-inflated"]
                },
            }
        )
    )
    assert bench._recent_captured_metrics(root=str(tmp_path)) == {}
    assert bench._latest_persisted_artifact(root=str(tmp_path)) is None


def test_unreachable_line_points_at_persisted_artifact(monkeypatch, capsys):
    """When the tunnel is down at bench time, the null line carries a
    POINTER to the latest committed artifact — the headline itself
    stays null (nothing was measured now)."""
    import json

    sentinel = {"path": "docs/logs/bench_x.json", "line": {"value": 1.0}}
    monkeypatch.setattr(bench, "_tpu_alive", lambda *a, **k: False)
    monkeypatch.setattr(
        bench, "_latest_persisted_artifact", lambda root=None: sentinel
    )
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None
    assert rec["details"]["last_persisted_artifact"] == sentinel


def _write_artifact(logs, stamp, details, value=None):
    import json

    rec = {"metric": "sgemm_gflops_per_chip", "value": value,
           "details": details}
    (logs / f"bench_{stamp}.json").write_text(json.dumps(rec))


def test_recent_captured_metrics_unions_newest_wins(tmp_path):
    """The flap-cycle accumulator: non-null details union across
    artifacts <24h old (by FILENAME timestamp), newest value winning
    per metric; stale and future-stamped files are excluded."""
    import datetime

    logs = tmp_path / "docs" / "logs"
    logs.mkdir(parents=True)
    now = datetime.datetime.now()
    fmt = "%Y-%m-%d_%H%M%S"
    old = (now - datetime.timedelta(hours=30)).strftime(fmt)
    recent1 = (now - datetime.timedelta(hours=3)).strftime(fmt)
    recent2 = (now - datetime.timedelta(hours=1)).strftime(fmt)
    future = (now + datetime.timedelta(hours=2)).strftime(fmt)
    _write_artifact(logs, old, {"a": 1.0, "b": 1.0})       # too old
    _write_artifact(logs, recent1, {"a": 2.0, "b": None, "c": 5.0})
    _write_artifact(logs, recent2, {"a": 3.0})             # newest a
    _write_artifact(logs, future, {"d": 9.0})              # clock skew
    (logs / "bench_garbagename.json").write_text("{}")     # no stamp

    got = bench._recent_captured_metrics(root=str(tmp_path))
    assert {n: v for n, (v, _p) in got.items()} == {"a": 3.0, "c": 5.0}
    # provenance points at the artifact each value came from
    assert got["a"][1].endswith(f"bench_{recent2}.json")
    assert got["c"][1].endswith(f"bench_{recent1}.json")


def test_check_regression_union_persisted(tmp_path, monkeypatch):
    """Watcher-mode gate: the union of persisted artifacts plus the
    fresh line must cover every BENCH_METRICS name within tolerance —
    evidence accumulated across flap windows passes together, a
    missing or slow metric still fails."""
    import datetime
    import json

    logs = tmp_path / "docs" / "logs"
    logs.mkdir(parents=True)
    measured = bench._load_baseline()["measured"]
    names = [n for n, _ in bench.BENCH_METRICS]
    assert names[0] == "sgemm_gflops"
    stamp = (datetime.datetime.now()
             - datetime.timedelta(hours=2)).strftime("%Y-%m-%d_%H%M%S")
    # persisted artifact covers everything except the headline
    _write_artifact(logs, stamp, {n: float(measured[n])
                                  for n in names[1:]})
    fresh_line = json.dumps({
        "value": float(measured[names[0]]),
        "details": {names[0]: float(measured[names[0]])},
        "vs_measured": {},
    })
    assert bench.check_regression(
        fresh_line, union_persisted=True, root=str(tmp_path)) == 0

    # the headline must be fresh: a union where sgemm rides on a
    # persisted artifact (this run measured only saxpy) must fail —
    # as rc 2 (coverage): nothing measured slow, another window can
    # supply the fresh canary
    _write_artifact(logs, stamp, {n: float(measured[n]) for n in names})
    carried_headline = json.dumps({
        "value": None,
        "details": {names[-1]: float(measured[names[-1]])},
        "vs_measured": {},
    })
    assert bench.check_regression(
        carried_headline, union_persisted=True, root=str(tmp_path)) == 2

    # a >15% drop inside the union is rc 1 (deterministic) even when
    # coverage is full
    slow_line = json.dumps({
        "value": 0.5 * float(measured[names[0]]),
        "details": {names[0]: 0.5 * float(measured[names[0]])},
        "vs_measured": {},
    })
    assert bench.check_regression(
        slow_line, union_persisted=True, root=str(tmp_path)) == 1

    # the carried block counts toward the union AT DECISION-TIME
    # values: with no artifacts on disk at gate time, a line whose
    # carried block covers the non-headline metrics still passes —
    # evidence can't age out between the skip decision and the gate
    for f in logs.iterdir():
        f.unlink()
    carried_line = json.dumps({
        "value": float(measured[names[0]]),
        "details": {names[0]: float(measured[names[0]])},
        "vs_measured": {},
        "carried": {n: [float(measured[n]), "docs/logs/gone.json"]
                    for n in names[1:]},
    })
    assert bench.check_regression(
        carried_line, union_persisted=True, root=str(tmp_path)) == 0


def test_main_skip_captured_measures_only_missing(monkeypatch, capsys):
    """TPK_BENCH_SKIP_CAPTURED=1: metrics with healthy persisted
    evidence <24h old are not re-measured (short flap windows go to
    missing ones); they appear under "carried" with provenance, NOT in
    details. Two exceptions always re-measure: the sgemm headline (a
    fresh canary each attempt, so same-day code changes can't ride
    entirely on pre-change artifacts) and any carried value already
    below tolerance (freezing a degraded number would fail every
    retry on the metric it refuses to re-run)."""
    import json

    measured = bench._load_baseline()["measured"]
    ran = []
    monkeypatch.setattr(bench, "_tpu_alive", lambda *a, **k: True)
    monkeypatch.setattr(
        bench, "_run_one_subprocess",
        lambda name, t: (ran.append(name) or (1.0, "ok")))
    monkeypatch.setattr(
        bench, "_recent_captured_metrics",
        lambda root=None: {
            # healthy -> skipped
            "stencil2d_mcells_s": (float(measured["stencil2d_mcells_s"]),
                                   "docs/logs/x.json"),
            # headline -> canary, re-measured despite healthy evidence
            "sgemm_gflops": (float(measured["sgemm_gflops"]),
                             "docs/logs/x.json"),
            # below tolerance -> re-measured, not frozen
            "nbody_ginter_s": (0.5 * float(measured["nbody_ginter_s"]),
                               "docs/logs/x.json"),
        })
    monkeypatch.setenv("TPK_BENCH_SKIP_CAPTURED", "1")
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["carried"] == {
        "stencil2d_mcells_s": [float(measured["stencil2d_mcells_s"]),
                               "docs/logs/x.json"]}
    assert set(ran) == {n for n, _ in bench.BENCH_METRICS} - {
        "stencil2d_mcells_s"}
    # details are fresh-only: carried metrics must not masquerade as
    # this run's measurements
    assert "stencil2d_mcells_s" not in rec["details"]
    assert rec["details"]["sgemm_gflops"] == 1.0  # fresh canary value


def test_persisted_artifact_ignores_error_lines(tmp_path):
    """A tunnel-down run's null line (string-valued details: "error",
    "last_persisted_artifact") gets persisted by the queue before the
    gate aborts; it must count as evidence for NEITHER the pointer
    path NOR the union — else each down-run points at an artifact
    with no measurements and nests them recursively."""
    import datetime
    import json

    logs = tmp_path / "docs" / "logs"
    logs.mkdir(parents=True)
    stamp = (datetime.datetime.now()
             - datetime.timedelta(hours=1)).strftime("%Y-%m-%d_%H%M%S")
    (logs / f"bench_{stamp}.json").write_text(json.dumps({
        "metric": "sgemm_gflops_per_chip", "value": None,
        "details": {"error": "TPU backend unreachable (tunnel down)",
                    "last_persisted_artifact": {"path": "x"}},
    }))
    assert bench._latest_persisted_artifact(root=str(tmp_path)) is None
    assert bench._recent_captured_metrics(root=str(tmp_path)) == {}


def test_check_regression_refuses_carried_line_without_union():
    """A skip-captured line (carried metrics absent from details) must
    not slip through the single-run gate with only 1-2 fresh metrics
    checked; it requires --union-persisted."""
    import json

    line = json.dumps({
        "value": 60000.0,
        "details": {"sgemm_gflops": 60000.0},
        "vs_measured": {"sgemm_gflops": 1.0},
        "carried": {"saxpy_gb_s": [9000.0, "docs/logs/x.json"]},
    })
    assert bench.check_regression(line) == 1


def test_main_points_wedge_nulls_at_prior_evidence(monkeypatch, capsys):
    """When a wedge nulls a metric mid-run but an earlier flap window
    captured it, the emitted line gains a labeled prior_evidence
    pointer (the judge reads this line as the round artifact) —
    without merging anything into details/value."""
    import json

    monkeypatch.setattr(bench, "_tpu_alive", lambda *a, **k: True)
    monkeypatch.setattr(
        bench, "_run_one_subprocess",
        lambda name, t: (2.0, "ok") if name == "sgemm_gflops"
        else (None, "timeout"))
    monkeypatch.setattr(
        bench, "_recent_captured_metrics",
        lambda root=None: {"nbody_ginter_s": (192.0, "docs/logs/y.json"),
                           "sgemm_gflops": (1.0, "docs/logs/y.json")})
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] == 2.0                     # fresh, not prior
    assert rec["details"]["nbody_ginter_s"] is None
    assert rec["prior_evidence"] == {
        "nbody_ginter_s": [192.0, "docs/logs/y.json"]}
    # measured metrics never get a prior_evidence entry
    assert "sgemm_gflops" not in rec["prior_evidence"]


def test_main_wedged_headline_emits_null_vs_baseline(monkeypatch, capsys):
    """VERDICT r4 weak #4: a run whose sgemm child died used to emit
    vs_baseline 1.0 — which a naive parser reads as "exactly on
    baseline". A null headline must carry a null vs_baseline; the 1.0
    placeholder survives only for a measured headline with no baseline
    row to divide by."""
    import json

    monkeypatch.setattr(bench, "_tpu_alive", lambda *a, **k: True)
    monkeypatch.setattr(
        bench, "_recent_captured_metrics", lambda root=None: {})
    monkeypatch.setattr(
        bench, "_run_one_subprocess",
        lambda name, t: (None, "error") if name == "sgemm_gflops"
        else (2.0, "ok"))
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["value"] is None
    assert rec["vs_baseline"] is None
    # every emitted line records which code produced it
    assert isinstance(rec.get("git_head"), str) and rec["git_head"]


def test_main_invalidates_capture_above_ceiling(monkeypatch, capsys):
    """A fresh capture ABOVE its physical ceiling (BASELINE.json
    "ceilings") is a measurement artifact — the 2026-07-31
    drift-inflated sgemm readings — and must be nulled at the source
    under the invalidation convention ([value, reason], scanners
    ignore it) so no persisted artifact carries it into the union or
    a baseline promotion."""
    import json

    monkeypatch.setattr(bench, "_tpu_alive", lambda *a, **k: True)
    monkeypatch.setattr(
        bench, "_load_baseline",
        lambda: {"measured": {"sgemm_gflops": 60000.0},
                 "ceilings": {"sgemm_gflops": 61333.0}})
    monkeypatch.setattr(
        bench, "_recent_captured_metrics", lambda root=None: {})
    monkeypatch.setattr(
        bench, "_run_one_subprocess",
        lambda name, t: (95973.82, "ok") if name == "sgemm_gflops"
        else (1.0, "ok"))
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["details"]["sgemm_gflops"] is None
    assert rec["value"] is None
    assert rec["vs_baseline"] is None
    assert rec["invalidated"]["sgemm_gflops"][0] == 95973.82
    assert "ceiling" in rec["invalidated"]["sgemm_gflops"][1]
    assert rec["details"]["nbody_ginter_s"] == 1.0  # others unaffected


def test_device_normal_shares_one_executable_per_shape():
    """ADVICE r4 (medium): a fresh jax.jit wrapper per call keys the
    jit cache per WRAPPER, so same-shape operands (saxpy_stream's x
    and y) each paid the ~20-40 s cold remote compile. The generator
    must be cached per shape; only the PRNGKey varies."""
    bench._normal_generator.cache_clear()
    a = bench._device_normal(1, (8, 16))
    b = bench._device_normal(2, (8, 16))
    info = bench._normal_generator.cache_info()
    assert (info.misses, info.hits) == (1, 1)
    assert not np.allclose(np.asarray(a), np.asarray(b))


def test_metric_kernel_sources_cover_all_metrics():
    """Every BENCH_METRICS name must map to its kernel sources for the
    git-aware evidence cut-off — a metric without an entry would
    silently get only the weaker bench.py-only epoch — and the mapped
    paths must exist (a renamed kernel file would quietly disable the
    filter for its metrics: git log on a missing path returns no
    commits)."""
    import os

    repo = os.path.dirname(os.path.abspath(bench.__file__))
    for name, _fn in bench.BENCH_METRICS:
        srcs = bench._METRIC_KERNEL_SOURCES.get(name)
        assert srcs, name
        for s in srcs:
            assert os.path.exists(os.path.join(repo, s)), s


def test_union_rejects_evidence_predating_kernel_commit(tmp_path):
    """VERDICT r4 weak #5: the evidence window must be git-aware, not
    just wall-clock. An artifact stamped BEFORE the last commit
    touching a metric's kernel sources (or bench.py) was measured on
    pre-change code and must not satisfy the union for THAT metric;
    metrics whose sources were untouched keep their evidence, and
    evidence captured after the commit is accepted again."""
    import datetime
    import os
    import subprocess

    def git(*args, date=None):
        env = dict(os.environ)
        env["GIT_CONFIG_GLOBAL"] = "/dev/null"
        env["GIT_CONFIG_SYSTEM"] = "/dev/null"
        if date:
            env["GIT_COMMITTER_DATE"] = date
            env["GIT_AUTHOR_DATE"] = date
        subprocess.run(
            ["git", "-C", str(tmp_path), *args],
            check=True, capture_output=True, env=env)

    now = datetime.datetime.now()

    def iso(hours_ago):
        return (now - datetime.timedelta(hours=hours_ago)).strftime(
            "%Y-%m-%dT%H:%M:%S")

    git("init", "-q")
    git("config", "user.email", "t@test")
    git("config", "user.name", "t")
    kdir = tmp_path / "tpukernels" / "kernels"
    kdir.mkdir(parents=True)
    (kdir / "sgemm.py").write_text("x = 1\n")
    (kdir / "nbody.py").write_text("x = 1\n")
    (tmp_path / "bench.py").write_text("y = 1\n")
    git("add", "-A")
    git("commit", "-qm", "base", date=iso(48))
    (kdir / "sgemm.py").write_text("x = 2\n")
    git("add", "-A")
    git("commit", "-qm", "touch sgemm kernel", date=iso(1))

    logs = tmp_path / "docs" / "logs"
    logs.mkdir(parents=True)
    fmt = "%Y-%m-%d_%H%M%S"
    stamp_between = (now - datetime.timedelta(hours=2)).strftime(fmt)
    _write_artifact(logs, stamp_between,
                    {"sgemm_gflops": 100.0, "nbody_ginter_s": 50.0})
    got = bench._recent_captured_metrics(root=str(tmp_path))
    assert "sgemm_gflops" not in got          # predates the kernel commit
    assert got["nbody_ginter_s"][0] == 50.0   # untouched kernel: kept

    stamp_after = now.strftime(fmt)
    _write_artifact(logs, stamp_after, {"sgemm_gflops": 101.0})
    got = bench._recent_captured_metrics(root=str(tmp_path))
    assert got["sgemm_gflops"][0] == 101.0


def _git_kernel_repo(tmp_path, touched_kernel, touch_hours_ago=1):
    """A tmp git repo whose base commit is 48h old and where ONE
    kernel file was touched `touch_hours_ago` ago — the shape the
    git-aware evidence epoch keys on."""
    import datetime
    import os
    import subprocess

    def git(*args, date=None):
        env = dict(os.environ)
        env["GIT_CONFIG_GLOBAL"] = "/dev/null"
        env["GIT_CONFIG_SYSTEM"] = "/dev/null"
        if date:
            env["GIT_COMMITTER_DATE"] = date
            env["GIT_AUTHOR_DATE"] = date
        subprocess.run(
            ["git", "-C", str(tmp_path), *args],
            check=True, capture_output=True, env=env)

    now = datetime.datetime.now()

    def iso(hours_ago):
        return (now - datetime.timedelta(hours=hours_ago)).strftime(
            "%Y-%m-%dT%H:%M:%S")

    git("init", "-q")
    git("config", "user.email", "t@test")
    git("config", "user.name", "t")
    kdir = tmp_path / "tpukernels" / "kernels"
    kdir.mkdir(parents=True)
    for f in ("sgemm.py", "nbody.py", "vector_add.py", "stencil.py",
              "scan.py", "histogram.py"):
        (kdir / f).write_text("x = 1\n")
    (tmp_path / "bench.py").write_text("y = 1\n")
    git("add", "-A")
    git("commit", "-qm", "base", date=iso(48))
    (kdir / touched_kernel).write_text("x = 2\n")
    git("add", "-A")
    git("commit", "-qm", f"touch {touched_kernel}",
        date=iso(touch_hours_ago))
    return now


def test_epoch_rejection_is_never_silent(tmp_path, capsys):
    """ADVICE r5: an artifact dropped by the git-epoch filter must
    announce itself — stderr note naming metric, artifact and the
    blocking commit ts, plus an entry in the caller's `rejected`
    dict — instead of silently shrinking the evidence union."""
    import datetime

    now = _git_kernel_repo(tmp_path, "sgemm.py")
    logs = tmp_path / "docs" / "logs"
    logs.mkdir(parents=True)
    stamp = (now - datetime.timedelta(hours=2)).strftime(
        "%Y-%m-%d_%H%M%S")
    _write_artifact(logs, stamp,
                    {"sgemm_gflops": 100.0, "nbody_ginter_s": 50.0})
    rejected = {}
    got = bench._recent_captured_metrics(
        root=str(tmp_path), rejected=rejected)
    assert "sgemm_gflops" not in got
    assert set(rejected) == {"sgemm_gflops"}
    art, ts = rejected["sgemm_gflops"]
    assert art.endswith(f"bench_{stamp}.json")
    assert isinstance(ts, int)
    err = capsys.readouterr().err
    assert "epoch-rejected: sgemm_gflops" in err
    assert f"bench_{stamp}.json" in err
    assert str(ts) in err


def test_union_gate_distinguishes_epoch_rejected_from_absent(tmp_path, capsys):
    """check_regression's union-mode "no value" breadcrumb must say
    WHY coverage is missing: "epoch-rejected" (re-measure on current
    code) reads differently from "absent" (wait for a window)."""
    import datetime
    import json

    now = _git_kernel_repo(tmp_path, "nbody.py")
    logs = tmp_path / "docs" / "logs"
    logs.mkdir(parents=True)
    measured = bench._load_baseline()["measured"]
    names = [n for n, _ in bench.BENCH_METRICS]
    stamp = (now - datetime.timedelta(hours=2)).strftime(
        "%Y-%m-%d_%H%M%S")
    # persisted artifact covers nbody only — and predates its commit
    _write_artifact(logs, stamp,
                    {"nbody_ginter_s": float(measured["nbody_ginter_s"])})
    fresh = {n: float(measured[n]) for n in names
             if n != "nbody_ginter_s"}
    line = json.dumps({
        "value": fresh["sgemm_gflops"], "details": fresh,
        "vs_measured": {},
    })
    assert bench.check_regression(
        line, union_persisted=True, root=str(tmp_path)) == 2
    out = capsys.readouterr().out
    assert "nbody_ginter_s: FAILED (epoch-rejected:" in out
    assert "re-measure" in out
    # an absent metric (no artifact at all) keeps the plain message
    for f in logs.iterdir():
        f.unlink()
    assert bench.check_regression(
        line, union_persisted=True, root=str(tmp_path)) == 2
    out = capsys.readouterr().out
    assert "nbody_ginter_s: FAILED (no value in any artifact <24h)" in out


def test_union_reapplies_epoch_filter_to_carried(tmp_path, capsys):
    """ADVICE r5: carried entries pin the evidence WINDOW to the skip
    decision, but must not pin the CODE epoch — a commit touching the
    metric's kernel between the skip decision and the gate invalidates
    the carried value exactly like a persisted artifact."""
    import datetime
    import json

    now = _git_kernel_repo(tmp_path, "nbody.py")
    measured = bench._load_baseline()["measured"]
    names = [n for n, _ in bench.BENCH_METRICS]
    fresh = {n: float(measured[n]) for n in names
             if n != "nbody_ginter_s"}
    old_stamp = (now - datetime.timedelta(hours=2)).strftime(
        "%Y-%m-%d_%H%M%S")
    line = json.dumps({
        "value": fresh["sgemm_gflops"], "details": fresh,
        "vs_measured": {},
        "carried": {"nbody_ginter_s": [
            float(measured["nbody_ginter_s"]),
            f"docs/logs/bench_{old_stamp}.json"]},
    })
    assert bench.check_regression(
        line, union_persisted=True, root=str(tmp_path)) == 2
    assert "epoch-rejected" in capsys.readouterr().out

    # carried evidence captured AFTER the commit is still honored
    new_stamp = now.strftime("%Y-%m-%d_%H%M%S")
    line = json.dumps({
        "value": fresh["sgemm_gflops"], "details": fresh,
        "vs_measured": {},
        "carried": {"nbody_ginter_s": [
            float(measured["nbody_ginter_s"]),
            f"docs/logs/bench_{new_stamp}.json"]},
    })
    assert bench.check_regression(
        line, union_persisted=True, root=str(tmp_path)) == 0


def test_ceiling_epsilon_keeps_near_peak_captures(monkeypatch, capsys):
    """The sgemm ceiling sits 0.8% above the median of record, so
    ordinary upward noise used to invalidate genuine near-peak
    captures. A value INSIDE ceiling*(1+_CEILING_EPS) must be kept;
    only past the band is it drift."""
    import json

    inside = 61333.0 * 1.005   # noise on an honest near-peak capture
    outside = 61333.0 * 1.02   # past the documented band: drift
    for value, expect_kept in ((inside, True), (outside, False)):
        monkeypatch.setattr(bench, "_tpu_alive", lambda *a, **k: True)
        monkeypatch.setattr(
            bench, "_load_baseline",
            lambda: {"measured": {"sgemm_gflops": 60834.0},
                     "ceilings": {"sgemm_gflops": 61333.0}})
        monkeypatch.setattr(
            bench, "_recent_captured_metrics",
            lambda root=None, rejected=None: {})
        monkeypatch.setattr(
            bench, "_run_one_subprocess",
            lambda name, t, v=value: (v, "ok")
            if name == "sgemm_gflops" else (1.0, "ok"))
        bench.main()
        rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        if expect_kept:
            assert rec["value"] == inside
            assert "invalidated" not in rec
        else:
            assert rec["value"] is None
            # the raw value survives in the artifact for forensics
            assert rec["invalidated"]["sgemm_gflops"][0] == outside
            assert "ceiling" in rec["invalidated"]["sgemm_gflops"][1]


def test_bench_only_restricts_metrics(monkeypatch, capsys):
    """TPK_BENCH_ONLY (chaos-test / targeted re-measure knob): only
    the named metrics run; unknown names fail loudly."""
    import json

    ran = []
    monkeypatch.setattr(bench, "_tpu_alive", lambda *a, **k: True)
    monkeypatch.setattr(
        bench, "_run_one_subprocess",
        lambda name, t: (ran.append(name) or (1.0, "ok")))
    monkeypatch.setenv("TPK_BENCH_ONLY", "saxpy_gb_s")
    bench.main()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert ran == ["saxpy_gb_s"]
    assert set(rec["details"]) == {"saxpy_gb_s"}

    monkeypatch.setenv("TPK_BENCH_ONLY", "nope")
    with pytest.raises(ValueError, match="TPK_BENCH_ONLY"):
        bench.main()


def test_bare_prewarm_or_one_errors_instead_of_running_main():
    """`bench.py --prewarm` / `--one` without a metric name must exit
    with a usage error — not fall through to main() and run the full
    seven-metric suite (holding the chip for the whole deadline and,
    for --prewarm, emitting the JSON line the mode promises never to
    produce). Unknown metric names get the same refusal."""
    import os
    import subprocess
    import sys

    from test_distributed import _scrubbed_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _scrubbed_env(fake_devices=None)
    for args in (["--prewarm"], ["--one"], ["--prewarm", "nope"]):
        proc = subprocess.run(
            [sys.executable, "bench.py"] + args,
            env=env, capture_output=True, text=True, timeout=300,
            cwd=repo)
        assert proc.returncode == 2, (args, proc.stdout, proc.stderr)
        assert "usage:" in proc.stderr
        assert not proc.stdout.strip()


def test_prewarm_emits_no_stdout_json():
    """`bench.py --prewarm <name>` (the revalidation queue's stencil3d
    compile-cache warmer) compiles and runs both R variants but must
    emit NO stdout line — nothing a scanner or parser could mistake
    for a measurement — and must say so on stderr."""
    import os
    import subprocess
    import sys

    from test_distributed import _scrubbed_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = _scrubbed_env(fake_devices=None)  # CPU, never the tunnel
    env["TPK_BENCH_SMOKE"] = "1"  # collapse R so CPU finishes fast
    proc = subprocess.run(
        [sys.executable, "bench.py", "--prewarm", "saxpy_gb_s"],
        env=env, capture_output=True, text=True, timeout=300, cwd=repo)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert not proc.stdout.strip()
    assert "prewarm complete" in proc.stderr


def test_probe_attempts_env_cap(monkeypatch):
    """TPK_BENCH_PROBE_ATTEMPTS caps _tpu_alive's patience (the
    watcher-fired queue sets 1: it just probed healthy, so a failure
    here means re-wedged — don't burn ~30 min inside the queue).
    Garbage fails loudly."""
    calls = []

    class FakeProc:
        returncode = 1
        stdout = ""

    import subprocess

    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **k: calls.append(1) or FakeProc())
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)

    monkeypatch.setenv("TPK_BENCH_PROBE_ATTEMPTS", "1")
    assert bench._tpu_alive() is False
    assert len(calls) == 1

    monkeypatch.setenv("TPK_BENCH_PROBE_ATTEMPTS", "3")
    calls.clear()
    assert bench._tpu_alive() is False
    assert len(calls) == 3

    for bad in ("0", "-2", "abc"):
        monkeypatch.setenv("TPK_BENCH_PROBE_ATTEMPTS", bad)
        with pytest.raises(ValueError, match="TPK_BENCH_PROBE_ATTEMPTS"):
            bench._tpu_alive()
