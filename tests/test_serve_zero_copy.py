"""CPU suite for the zero-copy wire path + continuous batching
(docs/SERVING.md §wire format / §continuous batching; ISSUE 12).

Covers: the copy-free send/recv path (memoryview payloads, no
``bytes()`` materialization), frame-boundary cases (payload exactly
at the small-frame threshold and at the oversize cap, zero-length
payloads), the shm segment lifecycle (create/map/torn/dead-creator
sweep), lane negotiation (shm client vs an inline-only daemon and vs
an old server that predates ``lanes``), the torn-segment
poisons-only-its-connection contract, the daemon-side zero-copy
proof (``serve.bytes_copied`` stays 0 across warm exact-fit shm
dispatches), the adaptive batch window (collapse-to-zero idle,
widen under burst), the fleet router's O(1) descriptor forwarding,
and the tier-1 copy-budget smoke: ``loadgen --serve`` →
``serve_copy_budget`` journal evidence → ``obs_report --check``
gating a synthetic copy regression like a bench regression.
"""

import contextlib
import json
import os
import socket as socket_mod
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from test_distributed import _scrubbed_env
from test_serve import SCAN_BUCKET, _daemon, _events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# an exact-fit avatar at the canary-free shape the shm tests use:
# 8192 int32 = 32 KiB per payload, comfortably over the small-frame
# threshold so the inline comparison paths stream, not join
EXACT = np.arange(8192, dtype=np.int32) % 17
EXACT_WANT = np.cumsum(EXACT, dtype=np.int64).astype(np.int32)


# ---------------------------------------------------------------- #
# protocol: zero-copy send path + frame boundaries                 #
# ---------------------------------------------------------------- #

def test_pack_arrays_returns_views_not_copies():
    """Satellite 1: the send path must not materialize ``bytes()``
    twins — pack_arrays hands back buffer views over the operands
    themselves for contiguous arrays."""
    from tpukernels.serve import protocol

    arr = np.arange(4096, dtype=np.int32)
    specs, payloads = protocol.pack_arrays([arr])
    assert specs == [{"shape": [4096], "dtype": "int32"}]
    view = np.frombuffer(payloads[0], dtype=np.int32)
    assert np.shares_memory(view, arr), \
        "pack_arrays must return a view, not a copy"


def test_recv_frame_returns_views_over_one_blob():
    from tpukernels.serve import protocol

    a, b = socket_mod.socketpair()
    try:
        arrays = [np.arange(100, dtype=np.int32),
                  np.ones((4, 5), np.float32)]
        specs, payloads = protocol.pack_arrays(arrays)
        sent = protocol.send_frame(a, {"op": "dispatch",
                                       "args": specs}, payloads)
        assert sent == 100 * 4 + 20 * 4
        header, got = protocol.recv_frame(b)
        assert all(isinstance(p, memoryview) for p in got)
        outs = protocol.unpack_arrays(header["args"], got)
        for orig, back in zip(arrays, outs):
            np.testing.assert_array_equal(orig, back)
    finally:
        a.close()
        b.close()


def test_frame_boundary_small_frame_threshold():
    """Payloads exactly at / one past the small-frame join threshold
    take the two different send paths; both must roundtrip
    byte-identically."""
    from tpukernels.serve import protocol

    for n in (protocol.SMALL_FRAME, protocol.SMALL_FRAME + 1):
        a, b = socket_mod.socketpair()
        try:
            payload = bytes(range(256)) * (n // 256) + b"x" * (n % 256)
            assert len(payload) == n
            got = []

            def reader(sock=b, got=got):
                got.append(protocol.recv_frame(sock))

            t = threading.Thread(target=reader)
            t.start()
            sent = protocol.send_frame(a, {"op": "x"}, [payload])
            t.join(30)
            assert sent == n
            header, payloads = got[0]
            assert header == {"op": "x"}
            assert len(payloads) == 1 and payloads[0] == payload
        finally:
            a.close()
            b.close()


def test_frame_boundary_oversize_cap(monkeypatch):
    """Exactly AT the payload cap is a legal frame; one byte past it
    is rejected on send, and a crafted preamble claiming past-cap is
    rejected on recv BEFORE any payload is read."""
    from tpukernels.serve import protocol

    monkeypatch.setattr(protocol, "MAX_PAYLOAD", 4096)
    a, b = socket_mod.socketpair()
    try:
        at_cap = b"\xab" * 4096
        got = []
        t = threading.Thread(
            target=lambda: got.append(protocol.recv_frame(b))
        )
        t.start()
        assert protocol.send_frame(a, {"op": "x"}, [at_cap]) == 4096
        t.join(30)
        assert got[0][1][0] == at_cap
        with pytest.raises(protocol.ProtocolError, match="too large"):
            protocol.send_frame(a, {"op": "x"}, [at_cap + b"y"])
        # recv side: a preamble claiming cap+1 dies without reading
        a.sendall(protocol._PREAMBLE.pack(protocol.MAGIC, 2, 4097)
                  + b"{}")
        with pytest.raises(protocol.ProtocolError, match="absurd"):
            protocol.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_zero_length_payloads_roundtrip():
    from tpukernels.serve import protocol

    a, b = socket_mod.socketpair()
    try:
        empty = np.zeros(0, np.int32)
        data = np.arange(7, dtype=np.int32)
        specs, payloads = protocol.pack_arrays([empty, data, empty])
        protocol.send_frame(a, {"op": "x", "args": specs}, payloads)
        header, got = protocol.recv_frame(b)
        outs = protocol.unpack_arrays(header["args"], got)
        assert outs[0].shape == (0,) and outs[2].shape == (0,)
        np.testing.assert_array_equal(outs[1], data)
        # zero-length payloads never go to shm, whatever the threshold
        descs, wire, segs, staged = protocol.stage_shm_payloads(
            payloads, min_bytes=0
        )
        assert staged == 28 and len(segs) == 1
        assert descs[0] is None and descs[2] is None
        for seg in segs:
            seg.close()
            seg.unlink()
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------- #
# shm segments: lifecycle units                                    #
# ---------------------------------------------------------------- #

def test_shm_segment_roundtrip_torn_and_sweep():
    from tpukernels.serve import protocol

    if not protocol.shm_available():
        pytest.skip("no usable /dev/shm on this host")
    data = os.urandom(4096)
    seg = protocol.ShmSegment(4096)
    try:
        assert seg.write(data) == 4096
        mm = protocol.open_shm(seg.name, 4096)
        assert bytes(mm[:]) == data
        mm.close()
        # a reader claiming MORE than the file holds = torn
        with pytest.raises(protocol.ProtocolError, match="torn"):
            protocol.open_shm(seg.name, 8192)
    finally:
        seg.close()
        seg.unlink()
    # unlinked: now the name itself is torn
    with pytest.raises(protocol.ProtocolError, match="torn"):
        protocol.open_shm(seg.name, 4096)
    # names outside the namespace are rejected, never path-joined
    for bad in ("../etc/passwd", "x/y", "psm_123", "", None):
        with pytest.raises(protocol.ProtocolError, match="shm"):
            protocol.open_shm(bad, 64)
    # dead-creator sweep: a segment named for a pid that cannot exist
    dead = f"tpkserve-{2 ** 22 + 1}-0-deadbeef"
    with open(os.path.join(protocol.SHM_DIR, dead), "wb") as f:
        f.write(b"\0" * 16)
    live = protocol.ShmSegment(16)
    try:
        assert protocol.sweep_stale_segments() >= 1
        assert not os.path.exists(os.path.join(protocol.SHM_DIR, dead))
        # the live creator's segment survives the sweep
        assert os.path.exists(os.path.join(protocol.SHM_DIR, live.name))
    finally:
        live.close()
        live.unlink()
        with contextlib.suppress(OSError):
            os.unlink(os.path.join(protocol.SHM_DIR, dead))


def test_check_shm_descs_front_door():
    """The router's structural ``_shm`` validation: malformed
    descriptors must die as bad requests at the front door, never
    ride upstream to read as worker transport loss."""
    from tpukernels.serve import protocol

    args = [{"shape": [8192], "dtype": "int32"}]
    good = {"args": args,
            "_shm": [{"name": "tpkserve-1-0-deadbeef",
                      "nbytes": 32768}]}
    protocol.check_shm_descs(good, 0)          # passes
    protocol.check_shm_descs({"args": args}, 1)  # no _shm: passes
    bad_cases = [
        ({"args": args, "_shm": [{"name": "x"}]}, 0),       # bad name
        ({"args": args, "_shm": "nope"}, 0),                # not a list
        ({"args": args, "_shm": []}, 0),                    # wrong arity
        ({"args": args,
          "_shm": [{"name": "tpkserve-1-0-deadbeef"}]}, 0),  # no nbytes
        ({"args": args,
          "_shm": [{"name": "tpkserve-1-0-deadbeef",
                    "nbytes": -4}]}, 0),                    # bad size
        (good, 1),                      # inline count disagrees
        ({"args": args, "_shm": [None]}, 0),  # slot inline, no payload
    ]
    for header, n_payloads in bad_cases:
        with pytest.raises(protocol.ProtocolError):
            protocol.check_shm_descs(dict(header), n_payloads)


# ---------------------------------------------------------------- #
# adaptive batch window: policy unit                               #
# ---------------------------------------------------------------- #

def test_adaptive_window_policy(monkeypatch):
    """The continuous-batching policy, pinned: 0 when idle (empty
    queue) or when arrivals are slower than the cap; ~7 projected
    gaps under burst, capped; the fixed mode returns the knob
    verbatim."""
    from tpukernels.serve import server as serve_server

    srv = serve_server.Server(
        socket_path="/nonexistent/unused.sock", queue_max=4,
        workers=1, batch_window_ms=2.0, request_timeout_s=60,
    )
    assert srv.batch_adapt is True  # the default
    # idle: empty queue dispatches immediately, whatever the EWMA says
    srv._arrival_ewma = 0.0001
    assert srv._window_s(0) == 0.0
    # no arrival history yet: nothing to project, dispatch now
    srv._arrival_ewma = None
    assert srv._window_s(3) == 0.0
    # burst: gap 0.2ms -> 7 gaps = 1.4ms, under the 2ms cap
    srv._arrival_ewma = 0.0002
    assert srv._window_s(3) == pytest.approx(0.0014)
    # heavier projection than the cap: capped
    srv._arrival_ewma = 0.0005
    assert srv._window_s(3) == pytest.approx(0.002)
    # arrivals slower than the cap: waiting is pure latency
    srv._arrival_ewma = 0.01
    assert srv._window_s(3) == 0.0
    # fixed mode: the PR-10 semantics verbatim
    monkeypatch.setenv("TPK_SERVE_BATCH_ADAPT", "0")
    fixed = serve_server.Server(
        socket_path="/nonexistent/unused.sock", queue_max=4,
        workers=1, batch_window_ms=2.0, request_timeout_s=60,
    )
    fixed._arrival_ewma = 0.01
    assert fixed.batch_adapt is False
    assert fixed._window_s(0) == pytest.approx(0.002)
    assert fixed._window_s(3) == pytest.approx(0.002)


# ---------------------------------------------------------------- #
# copy-budget verdict unit                                         #
# ---------------------------------------------------------------- #

def test_analyze_copy_budget_verdicts():
    from tpukernels.obs import trend

    def ev(lane, bpr, expected_zero, sock="/tmp/s.sock"):
        return {"kind": "serve_copy_budget", "socket": sock,
                "lane": lane, "requests": 10,
                "bytes_per_request": bpr,
                "expected_zero": expected_zero}

    # a clean zero-copy run and a bounded inline run are both ok
    v = trend.analyze_copy_budget(
        [ev("shm", 0, True), ev("inline", 48000.0, False)]
    )
    assert {x["verdict"] for x in v.values()} == {"ok"}
    # a single copied byte on an expected-zero run gates
    v = trend.analyze_copy_budget([ev("shm", 0.1, True)])
    (only,) = v.values()
    assert only["verdict"] == "copy_regression" and only["flags"]
    # only the LATEST event per (socket, lane) is judged
    v = trend.analyze_copy_budget(
        [ev("shm", 409.6, True), ev("shm", 0, True)]
    )
    (only,) = v.values()
    assert only["verdict"] == "ok"
    # inline is never gated, whatever the byte count
    v = trend.analyze_copy_budget([ev("inline", 10 ** 9, False)])
    (only,) = v.values()
    assert only["verdict"] == "ok"


# ---------------------------------------------------------------- #
# daemon e2e: zero-copy proof, negotiation, torn segment           #
# ---------------------------------------------------------------- #

def test_shm_lane_end_to_end_zero_copy(tmp_path, monkeypatch):
    """The headline: warm exact-fit dispatches over the negotiated
    shm lane move every operand and result through /dev/shm — the
    daemon's ``serve.bytes_copied`` does not move at all, and neither
    does the client's. No segments leak."""
    from tpukernels.serve import client as serve_client
    from tpukernels.serve import protocol

    if not protocol.shm_available():
        pytest.skip("no usable /dev/shm on this host")
    monkeypatch.setenv("TPK_SERVE_SHM_MIN_BYTES", "0")
    with _daemon(tmp_path, {
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_SHM_MIN_BYTES": "0",
    }) as (sock, journal, _proc):
        with serve_client.ServeClient(sock, timeout_s=120) as c:
            ping = c.ping()
            assert ping.get("lanes") == ["inline", "shm"]
            assert ping.get("shm_min_bytes") == 0
            for _ in range(4):
                np.testing.assert_array_equal(
                    c.dispatch("scan", EXACT), EXACT_WANT
                )
            after = c.ping()
            assert after.get("bytes_copied") == 0, \
                "warm shm path must copy NOTHING daemon-side"
            assert c.bytes_copied == 0 and c.inline_payloads == 0
            assert c.staged_payloads == 4
    events = _events(journal)
    neg = [e for e in events
           if e.get("kind") == "serve_lane_negotiated"]
    assert len(neg) == 1 and neg[0].get("lane") == "shm"
    served = [e for e in events if e.get("kind") == "serve_request"]
    assert len(served) == 4 and all(e.get("ok") for e in served)
    leftovers = [n for n in os.listdir(protocol.SHM_DIR)
                 if n.startswith("tpkserve-")]
    assert not leftovers, f"leaked segments: {leftovers}"


def test_shm_client_against_inline_only_daemon(tmp_path, monkeypatch):
    """Negotiation falls back cleanly: a daemon with the lane
    switched off advertises inline only, and an shm-capable client
    speaks inline to it — right answers, zero staged segments."""
    from tpukernels.serve import client as serve_client

    monkeypatch.setenv("TPK_SERVE_SHM_MIN_BYTES", "0")
    with _daemon(tmp_path, {
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_SHM": "0",
    }) as (sock, _journal, _proc):
        with serve_client.ServeClient(sock, timeout_s=120) as c:
            assert c.ping().get("lanes") == ["inline"]
            np.testing.assert_array_equal(
                c.dispatch("scan", EXACT), EXACT_WANT
            )
            assert c.staged_payloads == 0
            assert c.inline_payloads == 1
            assert c.bytes_copied > 0  # inline lane is O(tensor)


def test_shm_client_against_old_server(monkeypatch, tmp_path):
    """A pre-lanes server (its pong has no ``lanes`` key) pins the
    client to the inline lane — the request frame carries no ``_shm``
    and every payload rides the socket."""
    from tpukernels.serve import client as serve_client
    from tpukernels.serve import protocol

    monkeypatch.setenv("TPK_SERVE_SHM_MIN_BYTES", "0")
    sock_path = str(tmp_path / "old.sock")
    listener = socket_mod.socket(socket_mod.AF_UNIX,
                                 socket_mod.SOCK_STREAM)
    listener.bind(sock_path)
    listener.listen(1)
    seen = {}

    def old_server():
        conn, _ = listener.accept()
        with contextlib.closing(conn):
            header, _p = protocol.recv_frame(conn)
            assert header.get("op") == "ping"
            protocol.send_frame(conn, {"v": 1, "op": "pong",
                                       "ok": True})  # NO lanes key
            header, payloads = protocol.recv_frame(conn)
            seen["header"] = header
            seen["n_payloads"] = len(payloads)
            arr = protocol.unpack_arrays(header["args"], payloads)[0]
            specs, outs = protocol.pack_arrays([arr])
            protocol.send_frame(
                conn, {"v": 1, "id": header["id"], "ok": True,
                       "outputs": specs}, outs,
            )

    t = threading.Thread(target=old_server, daemon=True)
    t.start()
    try:
        with serve_client.ServeClient(sock_path, timeout_s=30) as c:
            out = c.dispatch("scan", EXACT)
        np.testing.assert_array_equal(out, EXACT)  # echo server
        assert "_shm" not in seen["header"]
        assert "shm_ok" not in seen["header"]
        assert seen["n_payloads"] == 1
        t.join(30)
    finally:
        listener.close()


def test_torn_shm_segment_poisons_only_its_connection(tmp_path):
    """A dispatch naming a segment that does not exist is a desynced
    stream: that CONNECTION dies (EOF/reset), the daemon does not —
    a fresh client is served normally right after."""
    from tpukernels.serve import client as serve_client
    from tpukernels.serve import protocol

    if not protocol.shm_available():
        pytest.skip("no usable /dev/shm on this host")
    with _daemon(tmp_path, {
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_SHM_MIN_BYTES": "0",
    }) as (sock, journal, _proc):
        raw = socket_mod.socket(socket_mod.AF_UNIX,
                                socket_mod.SOCK_STREAM)
        raw.connect(sock)
        raw.settimeout(30)
        try:
            protocol.send_frame(raw, {
                "v": 1, "op": "dispatch", "id": 1, "kernel": "scan",
                "statics": {}, "shm_ok": True,
                "args": [{"shape": [8192], "dtype": "int32"}],
                "_shm": [{"name": "tpkserve-999999-0-deadbeef",
                          "nbytes": 32768}],
            })
            with pytest.raises((ConnectionResetError,
                                protocol.ProtocolError)):
                if protocol.recv_frame(raw) is None:
                    raise protocol.ProtocolError("clean EOF")
        finally:
            raw.close()
        # the daemon survived: a fresh connection is served
        with serve_client.ServeClient(sock, timeout_s=120) as c:
            np.testing.assert_array_equal(
                c.dispatch("scan", EXACT), EXACT_WANT
            )
    served = [e for e in _events(journal)
              if e.get("kind") == "serve_request"]
    assert len(served) == 1 and served[0].get("ok")


def test_adaptive_window_idle_collapses_burst_widens(tmp_path):
    """Continuous batching, live: an idle request dispatches with a
    0 ms window (ping reports it); a same-bucket burst behind a slow
    dispatch widens the window (ping catches a nonzero value
    mid-burst) and coalesces."""
    from tpukernels.serve import client as serve_client

    plan = json.dumps({"slow_dispatch": {"kernel": "scan",
                                         "delay_s": 0.3}})
    with _daemon(tmp_path, {
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_WORKERS": "1",
        "TPK_SERVE_BATCH_WINDOW_MS": "400",
        "TPK_FAULT_PLAN": plan,
    }) as (sock, journal, _proc):
        with serve_client.ServeClient(sock, timeout_s=120) as c:
            np.testing.assert_array_equal(
                c.dispatch("scan", EXACT), EXACT_WANT
            )
            ping = c.ping()
            assert ping.get("batch_adapt") is True
            assert ping.get("batch_window_ms") == 0.0, \
                "an idle request must not pay the window"
        errors = []

        def one():
            try:
                with serve_client.ServeClient(sock,
                                              timeout_s=120) as cc:
                    np.testing.assert_array_equal(
                        cc.dispatch("scan", EXACT), EXACT_WANT
                    )
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(repr(e))

        threads = [threading.Thread(target=one) for _ in range(6)]
        for t in threads:
            t.start()
        # the burst queues behind the slow first dispatch; once a
        # pickup sees a non-empty queue the window must widen
        widened = 0.0
        deadline = time.monotonic() + 30
        with serve_client.ServeClient(sock, timeout_s=30) as mon:
            while time.monotonic() < deadline:
                w = mon.ping().get("batch_window_ms") or 0.0
                widened = max(widened, w)
                if widened > 0:
                    break
                time.sleep(0.02)
        for t in threads:
            t.join(120)
        assert not errors, errors
        assert widened > 0.0, "burst pickups must widen the window"
    served = [e for e in _events(journal)
              if e.get("kind") == "serve_request"]
    assert len(served) == 7 and all(e.get("ok") for e in served)
    assert max(e.get("batch_size") or 0 for e in served) >= 2


# ---------------------------------------------------------------- #
# fleet: the router forwards descriptors, not tensors              #
# ---------------------------------------------------------------- #

def test_fleet_router_forwards_shm_descriptors(tmp_path, monkeypatch):
    """Through a router + worker fleet on the shm lane, the front-end
    relays only names: the router's own bytes_copied stays 0 while
    answers stay exact — the fleet path stopped being O(tensor)."""
    from test_fleet import _fleet

    from tpukernels.serve import client as serve_client
    from tpukernels.serve import protocol

    if not protocol.shm_available():
        pytest.skip("no usable /dev/shm on this host")
    monkeypatch.setenv("TPK_SERVE_SHM_MIN_BYTES", "0")
    with _fleet(tmp_path, n=2, env_extra={
        "TPK_SERVE_BUCKETS": SCAN_BUCKET,
        "TPK_SERVE_MAX_PAD_FRAC": "0.9",
        "TPK_SERVE_SHM_MIN_BYTES": "0",
    }) as (front, journal, _env):
        with serve_client.ServeClient(front, timeout_s=120) as c:
            ping = c.ping()
            assert "shm" in (ping.get("lanes") or []), \
                "the front socket must advertise its workers' lanes"
            for _ in range(3):
                np.testing.assert_array_equal(
                    c.dispatch("scan", EXACT), EXACT_WANT
                )
            after = c.ping()
            assert after.get("bytes_copied") == 0, \
                "the router must relay descriptors, not tensors"
            assert c.staged_payloads == 3 and c.bytes_copied == 0
    events = _events(journal)
    routed = [e for e in events if e.get("kind") == "serve_route"]
    assert len(routed) == 3 and all(e.get("ok") for e in routed)
    leftovers = [n for n in os.listdir(protocol.SHM_DIR)
                 if n.startswith("tpkserve-")]
    assert not leftovers, f"leaked segments: {leftovers}"


# ---------------------------------------------------------------- #
# tier-1 copy-budget smoke: loadgen -> journal -> obs_report gate  #
# ---------------------------------------------------------------- #

def test_copy_budget_smoke_and_trend_gate(tmp_path):
    """The acceptance loop, mechanical end to end: a fully-negotiated
    shm ``loadgen --serve`` run stamps ``serve_copy_budget`` with 0
    bytes/request and ``expected_zero`` (rc 0 through ``obs_report
    --check``); the same run inline is bounded but nonzero; and a
    synthetic expected-zero run that copied bytes flips the check to
    rc 1 as a ``copy_regression`` — a copy regression gates like a
    bench regression."""
    from tpukernels.serve import protocol

    if not protocol.shm_available():
        pytest.skip("no usable /dev/shm on this host")
    slo_dir = tmp_path / "slo"
    slo_dir.mkdir()
    loadgen = os.path.join(REPO, "tools", "loadgen.py")
    obs_report = os.path.join(REPO, "tools", "obs_report.py")
    with _daemon(tmp_path, {
        "TPK_SERVE_SHM_MIN_BYTES": "0",
    }) as (sock, _journal, _proc):

        def run_loadgen(journal, extra_env=None):
            env = _scrubbed_env(None)
            env["TPK_SLO_DIR"] = str(slo_dir)
            env["TPK_HEALTH_JOURNAL"] = journal
            env["TPK_SERVE_SHM_MIN_BYTES"] = "0"
            env.update(extra_env or {})
            return subprocess.run(
                [sys.executable, loadgen, "--serve", sock,
                 "--kernel", "scan", "--arrivals", "poisson",
                 "--seed", "7", "--requests", "25", "--rate", "50"],
                capture_output=True, text=True, timeout=300,
                cwd=REPO, env=env,
            )

        shm_journal = str(tmp_path / "lg_shm.jsonl")
        r = run_loadgen(shm_journal)
        assert r.returncode == 0, r.stdout + r.stderr
        (budget,) = [e for e in _events(shm_journal)
                     if e.get("kind") == "serve_copy_budget"]
        assert budget["lane"] == "shm"
        assert budget["expected_zero"] is True
        assert budget["daemon_bytes_copied"] == 0
        assert budget["bytes_per_request"] == 0
        assert budget["client_bytes_copied"] == 0
        assert budget["inline_payloads"] == 0

        inline_journal = str(tmp_path / "lg_inline.jsonl")
        r = run_loadgen(inline_journal, {"TPK_SERVE_SHM": "0"})
        assert r.returncode == 0, r.stdout + r.stderr
        (budget,) = [e for e in _events(inline_journal)
                     if e.get("kind") == "serve_copy_budget"]
        assert budget["lane"] == "inline"
        assert budget["expected_zero"] is False
        # bounded: request + response payload traffic per request,
        # nothing more (scan canary = 4093 int32 each way ~= 33 KB)
        assert 0 < budget["bytes_per_request"] < 100_000

        env = _scrubbed_env(None)
        env["TPK_SLO_DIR"] = str(slo_dir)
        chk = subprocess.run(
            [sys.executable, obs_report, "--check",
             "--journal", shm_journal],
            capture_output=True, text=True, timeout=120,
            cwd=REPO, env=env,
        )
        assert chk.returncode == 0, chk.stdout + chk.stderr
        assert "0 copy-budget regression(s)" in chk.stdout

    # the gate: a zero-copy run that copied bytes fails the check
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({
        "kind": "serve_copy_budget", "socket": "/tmp/s.sock",
        "lane": "shm", "lanes": ["inline", "shm"], "requests": 25,
        "daemon_bytes_copied": 102400, "bytes_per_request": 4096.0,
        "expected_zero": True, "pid": 1,
    }) + "\n")
    env = _scrubbed_env(None)
    env["TPK_SLO_DIR"] = str(slo_dir)
    chk = subprocess.run(
        [sys.executable, obs_report, "--check",
         "--journal", str(bad)],
        capture_output=True, text=True, timeout=120, cwd=REPO,
        env=env,
    )
    assert chk.returncode == 1, chk.stdout + chk.stderr
    assert "copy_regression" in chk.stdout
    assert "1 copy-budget regression(s)" in chk.stdout
