"""Declarative autotuning search spaces (docs/TUNING.md §schema).

A kernel's tunable surface is data, not code: a
:class:`SearchSpace` names each knob (:class:`Tunable`), its env-var
spelling, shipped default and sweep values, plus an analytic
VMEM-budget model so infeasible candidates are pruned *before* burning
chip time — generalizing the 32 MiB arithmetic the old
``tools/sgemm_tune.py`` documented in prose.

:func:`resolve` is the single param-resolution path every kernel
wrapper calls, with the documented precedence

    env-override  >  tuned-cache  >  shipped-default

Env parsing is fail-loud (``TPK_SGEMM_BM=abc`` raises a ValueError
naming the var, like every other TPK_* knob); cache-sourced values are
validated with the same rules but REJECTED (treated as absent, with a
``tuning_rejected`` journal event) instead of raising — a corrupt
cache file must degrade to shipped defaults, never take down a kernel
call.

Stdlib-only at import time: jax is only imported lazily via the cache
module, so search spaces are introspectable (``tools/autotune.py
--list``) without initializing a backend.
"""

from __future__ import annotations

import itertools
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from tpukernels.resilience import journal


@dataclass(frozen=True)
class Tunable:
    """One tunable knob: a positive int (block dims, pipeline depth) or
    a categorical choice (impl selectors). ``default=None`` means "the
    kernel computes its own fallback" (e.g. histogram's nbins-dependent
    impl pick) — resolve then returns None for the default source and
    the kernel keeps its in-code logic."""

    name: str
    env: str
    default: Any
    values: tuple = ()
    choice: bool = False  # categorical (string) vs positive-int

    def parse_env(self, raw: str):
        """Fail-loud env parsing (the TPK_* knob contract)."""
        if self.choice:
            if raw not in self.values:
                raise ValueError(
                    f"{self.env}={raw!r}: expected one of "
                    + ", ".join(repr(v) for v in self.values)
                )
            return raw
        try:
            val = int(raw)
        except ValueError:
            val = 0
        if val <= 0:
            raise ValueError(
                f"{self.env}={raw!r}: expected a positive integer"
            )
        return val

    def coerce_cached(self, v):
        """(ok, value) for a cache-sourced candidate value: same rules
        as parse_env but never raises — see module docstring."""
        if self.choice:
            return (v in self.values), v
        return (isinstance(v, int) and not isinstance(v, bool) and v > 0), v


@dataclass(frozen=True)
class SearchSpace:
    """Declarative search space for one registry kernel.

    ``sources`` are the repo-relative files whose git history epochs
    the tuning cache (an entry tuned before the last commit touching
    them is stale). ``metric``/``bench_shape``/``bench_dtype`` bind the
    space to its ``bench.py --one`` metric of record and the cache key
    that metric's kernel call resolves with, so the sweep runner writes
    the exact entry later dispatches will read. ``vmem_bytes(params,
    shape)`` is the analytic VMEM model; candidates over
    ``vmem_budget_bytes`` are pruned (both optional — kernels whose
    geometry self-adapts, like the stencil slab picker, omit them)."""

    kernel: str
    tunables: tuple
    sources: tuple
    metric: Optional[str] = None
    bench_shape: Optional[tuple] = None
    bench_dtype: Optional[str] = None
    vmem_budget_bytes: Optional[int] = None
    vmem_bytes: Optional[Callable] = field(default=None, repr=False)

    def defaults(self) -> dict:
        return {t.name: t.default for t in self.tunables}

    def env_for(self, params: dict) -> dict:
        """Env-var assignments selecting ``params`` in a subprocess
        (None values — kernel-computed defaults — are left unset)."""
        by_name = {t.name: t for t in self.tunables}
        return {
            by_name[k].env: str(v)
            for k, v in params.items()
            if k in by_name and v is not None
        }

    def feasible(self, params: dict, shape=None) -> bool:
        if self.vmem_bytes is None or self.vmem_budget_bytes is None:
            return True
        return self.vmem_bytes(params, shape) <= self.vmem_budget_bytes

    def candidates(self, shape=None):
        """Feasibility-pruned sweep candidates, shipped defaults FIRST
        (the control row every promotion is judged against), then the
        cartesian product of sweep values in declaration order.
        Returns (candidates, n_pruned) — callers must surface n_pruned
        (no silent caps)."""
        default = self.defaults()
        axes = [
            t.values if t.values else (t.default,) for t in self.tunables
        ]
        names = [t.name for t in self.tunables]
        out, pruned = [], 0
        seen = set()

        def _add(params):
            nonlocal pruned
            key = tuple(sorted(params.items()))
            if key in seen:
                return
            seen.add(key)
            if self.feasible(params, shape):
                out.append(params)
            else:
                pruned += 1

        _add(default)
        for combo in itertools.product(*axes):
            _add(dict(zip(names, combo)))
        return out, pruned

    def quick_candidates(self, shape=None):
        """The --quick sweep: the control plus single-axis probes of
        the FIRST declared tunable, max 3 rows — declare the
        highest-leverage knob first (for sgemm this reproduces the old
        sgemm_tune QUICK rows exactly: control, bm=128, bm=512)."""
        cands, _pruned = self.candidates(shape=shape)
        if not cands:
            return []
        first, rest = self.tunables[0], self.tunables[1:]
        return (
            cands[:1]
            + [
                c
                for c in cands[1:]
                if c[first.name] != first.default
                and all(c[t.name] == t.default for t in rest)
            ]
        )[:3]


# once-per-process memo of journaled cache-sourced resolutions, so a
# kernel wrapper called in a loop doesn't spam the health journal
_JOURNALED: set = set()


def resolve(space: SearchSpace, shape=None, dtype=None) -> dict:
    """Resolved knob values for one kernel call.

    Per-tunable precedence: a set env var wins (fail-loud parse), else
    a validated tuning-cache entry for (kernel, shape, dtype,
    device_kind), else the shipped default. Emits one
    ``tuning_resolved`` journal event per (kernel, key) per process
    when the cache contributed at least one value, recording the
    per-knob sources — the "demonstrably reads it" evidence the
    acceptance tests key on. ``TPK_TUNING_CACHE=0`` disables the cache
    layer entirely (env and defaults still apply)."""
    from tpukernels.tuning import cache as tcache

    cached = tcache.get(space, shape, dtype)
    params, sources = {}, {}
    for t in space.tunables:
        raw = os.environ.get(t.env)
        if raw is not None:
            params[t.name] = t.parse_env(raw)
            sources[t.name] = "env"
            continue
        if cached is not None and t.name in cached:
            ok, v = t.coerce_cached(cached[t.name])
            if ok:
                params[t.name] = v
                sources[t.name] = "cache"
                continue
            journal.emit(
                "tuning_rejected",
                kernel=space.kernel,
                reason=f"bad cached value for {t.name}: {cached[t.name]!r}",
            )
        params[t.name] = t.default
        sources[t.name] = "default"
    if "cache" in sources.values():
        memo = (space.kernel, repr(shape), repr(dtype))
        if memo not in _JOURNALED:
            _JOURNALED.add(memo)
            journal.emit(
                "tuning_resolved",
                kernel=space.kernel,
                shape=list(shape) if shape else None,
                dtype=dtype,
                params=params,
                sources=sources,
            )
    return params


def spaces_of(module) -> Sequence[SearchSpace]:
    """A module's exported TUNABLES as a flat sequence (modules with
    several registry kernels — stencil — export a tuple)."""
    tun = getattr(module, "TUNABLES", None)
    if tun is None:
        return ()
    if isinstance(tun, SearchSpace):
        return (tun,)
    return tuple(tun)
