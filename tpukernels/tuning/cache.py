"""Persistent tuning cache (docs/TUNING.md §cache).

One JSON file under the ``_cachedir`` root (``tuning.json``, path via
``TPK_TUNING_CACHE_DIR`` override for tests/sweeps) holding one entry
per key

    kernel|shape|dtype|device_kind      e.g. sgemm|1024x1024x1024|float32|cpu

Each entry records the promoted params plus the evidence that scoped
them: the jax version, the sha of the last commit touching the
kernel's sources, the repo HEAD at promotion time, the measured value
and control, a wall-clock stamp, and whether it came from a --smoke
run (smoke entries are honored only under ``TPK_BENCH_SMOKE=1`` —
their params were picked by meaningless collapsed-repeat values).
``get`` re-validates jax version and source sha at READ time —
git-epoch invalidation mirroring bench.py's evidence rules: params
tuned on pre-change kernel code are rejected (loudly: stderr note +
``tuning_rejected`` journal event), never silently applied. Outside a
git checkout (sha unavailable) the sha check is skipped — the cache
then degrades to version-scoped, which installs without history can
live with.

Reads are memoized on (mtime, size) so a kernel wrapper consulting the
cache per call costs dict lookups, not file I/O. Writes are atomic
(tmp + rename) read-modify-write.

``TPK_TUNING_CACHE=0`` (or ``off``/``none``) disables lookups — kernels
then run env overrides / shipped defaults only; the sweep runner sets
it for its bench children so a half-written cache can never steer the
sweep measuring it.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from tpukernels import _cachedir
from tpukernels.obs import metrics as obs_metrics
from tpukernels.resilience import journal

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_DISABLED = ("0", "off", "none")
_FILE_MEMO: dict = {}  # path -> (stat_key, parsed)
_SHA_MEMO: dict = {}  # (root, sources) -> sha_or_None
_REJECT_NOTED: set = set()  # (key, reason) already surfaced this process


def enabled() -> bool:
    raw = os.environ.get("TPK_TUNING_CACHE")
    return raw is None or raw.strip().lower() not in _DISABLED


def path() -> str:
    return _cachedir.tuning_cache_path()


def canon_shape(shape) -> str:
    if not shape:
        return "-"
    return "x".join(str(int(d)) for d in shape)


def canon_dtype(dtype) -> str:
    if dtype is None:
        return "-"
    return str(dtype)


def device_kind() -> str:
    """Canonical device kind of the default backend (lazy jax import —
    by the time a kernel resolves params, jax is loaded anyway)."""
    import jax

    return jax.devices()[0].device_kind.lower().replace(" ", "_")


def key_str(kernel, shape=None, dtype=None, kind=None) -> str:
    if kind is None:
        kind = device_kind()
    return "|".join(
        (kernel, canon_shape(shape), canon_dtype(dtype), kind)
    )


def source_sha(sources, root=None):
    """Sha of the newest commit touching any of `sources` (the cache's
    git epoch — the sha sibling of bench._last_commit_ts), or None
    when git/history is unavailable. Memoized per process."""
    root = root or _REPO
    memo = (root, tuple(sources))
    if memo in _SHA_MEMO:
        return _SHA_MEMO[memo]
    try:
        r = subprocess.run(
            ["git", "-C", root, "log", "-1", "--format=%H", "--",
             *sources],
            capture_output=True,
            text=True,
            timeout=30,
        )
        sha = r.stdout.strip() or None
        if r.returncode != 0:
            sha = None
    except Exception:
        sha = None
    _SHA_MEMO[memo] = sha
    return sha


def _load(p):
    """Parsed cache file via the shared stat-memoized tolerant reader
    (``_cachedir.read_json_memoized``) — {} when absent/corrupt: an
    unreadable cache degrades to shipped defaults, never raises."""
    return _cachedir.read_json_memoized(p, _FILE_MEMO)


def _reject(key, reason, **fields):
    """Loud-rejection contract (same as bench's epoch rejections): a
    stale entry's dismissal must be reconstructable from stderr and
    the journal, but only once per process per cause."""
    # counted per occurrence (a hot dispatch loop re-hitting a stale
    # entry shows up as volume), noted/journaled once per cause
    obs_metrics.inc("tuning.cache.rejections")
    memo = (key, reason)
    if memo in _REJECT_NOTED:
        return
    _REJECT_NOTED.add(memo)
    print(f"# tuning-cache rejected: {key} ({reason})", file=sys.stderr)
    journal.emit("tuning_rejected", key=key, reason=reason, **fields)


def get(space, shape=None, dtype=None, kind=None):
    """Validated params dict for (space.kernel, shape, dtype, kind), or
    None on miss/disabled/stale. See module docstring for the
    validation rules."""
    if not enabled():
        return None
    data = _load(path())
    entries = data.get("entries")
    key = key_str(space.kernel, shape, dtype, kind)
    entry = entries.get(key) if isinstance(entries, dict) else None
    if not isinstance(entry, dict):
        obs_metrics.inc("tuning.cache.misses")
        return None
    if entry.get("smoke") and os.environ.get("TPK_BENCH_SMOKE") != "1":
        # smoke entries prove the sweep->cache->dispatch pipeline;
        # their params were picked by MEANINGLESS collapsed-repeat
        # values, so they are honored only inside smoke runs (the CI
        # proof path) — a normal dispatch at the same key must keep
        # the shipped defaults. device_kind=cpu keying already shields
        # TPU runs; this shields CPU/interpret runs in the same
        # checkout after a revalidate step-3b smoke sweep.
        _reject(key, "smoke entry ignored outside TPK_BENCH_SMOKE=1")
        return None
    import jax

    if entry.get("jax") != jax.__version__:
        _reject(
            key,
            f"tuned on jax {entry.get('jax')}, running {jax.__version__}",
        )
        return None
    sha = source_sha(space.sources)
    if sha is not None and entry.get("source_sha") not in (None, sha):
        _reject(
            key,
            "stale: a commit touching "
            + ",".join(space.sources)
            + " postdates this entry",
            entry_sha=entry.get("source_sha"),
            current_sha=sha,
        )
        return None
    params = entry.get("params")
    if isinstance(params, dict):
        obs_metrics.inc("tuning.cache.hits")
        return params
    obs_metrics.inc("tuning.cache.misses")
    return None


def put(
    space,
    params: dict,
    shape=None,
    dtype=None,
    kind=None,
    value=None,
    control=None,
    smoke=False,
    jax_version=None,
):
    """Atomically upsert one entry; returns its key. ``jax_version``/
    ``kind`` let the sweep runner stamp the identity its bench
    CHILDREN measured under (probed via subprocess) instead of the
    parent's."""
    if jax_version is None:
        import jax

        jax_version = jax.__version__
    key = key_str(space.kernel, shape, dtype, kind)
    p = path()
    entry = {
        "params": {k: v for k, v in params.items() if v is not None},
        "value": value,
        "control": control,
        "jax": jax_version,
        "source_sha": source_sha(space.sources),
        "git_head": journal.git_head(),
        "recorded": round(time.time(), 3),
        "smoke": bool(smoke),
    }
    d = os.path.dirname(p)
    if d:
        os.makedirs(d, exist_ok=True)
    # flock-serialized read-modify-write: tmp+rename alone keeps the
    # file uncorrupted but lets two near-simultaneous sweeps (the
    # daily revalidate smoke step vs an operator sweep) each write a
    # snapshot missing the other's promotion — last writer would win
    import fcntl

    from tpukernels.resilience import atomic

    with open(f"{p}.lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        _FILE_MEMO.pop(p, None)  # re-read under the lock, not the memo
        data = _load(p)
        data.setdefault("entries", {})[key] = entry
        # fsync'd tmp+rename (docs/RESILIENCE.md §atomic state): a
        # crash mid-put must leave the old cache, never a torn one
        atomic.dump_json(p, data)
    _FILE_MEMO.pop(p, None)
    journal.emit(
        "tuning_cache_put", key=key, params=entry["params"],
        value=value, control=control, smoke=bool(smoke),
    )
    return key
