"""Analytic per-kernel roofline models (docs/PERF.md §rooflines).

The suite's only validated on-chip capture (stencil2d 131,799
Mcells/s, 1.014x baseline) says the kernels are near-*baseline*; this
module is how the repo knows whether they are near-*hardware*. For
each bench metric it states, as plain arithmetic over the config of
record, (a) the FLOPs one metric pass executes, (b) the minimum HBM
bytes it must move, and (c) which machine peak binds — so the analytic
peak metric value is

    peak = work / max(flops / compute_peak, bytes / hbm_bw)

and every committed capture gets a machine-checked "% of roofline"
instead of an unexamined "ok". ``obs/trend.py`` turns a fraction under
:func:`min_frac` (``TPK_ROOFLINE_MIN_FRAC``, default 0.5) into the
NON-GATING ``below_roofline`` verdict; ``tools/obs_report.py
--roofline`` renders the table. The byte formulas are pinned against
hand-computed values per BASELINE.json config by
``tests/test_roofline.py``.

Peaks are per canonical ``device_kind`` (the tuning cache's spelling:
lowered, spaces -> underscores). The evidence device of record is the
v5-lite row — BASELINE.json's medians were measured there — and a
documented CPU fallback row exists so reports and tests run on any
host; an unknown TPU kind assumes the v5-lite row (flagged in
``basis``), anything else falls back to CPU. The fallback rows are
order-of-magnitude placeholders for plumbing, never evidence.

Stdlib-only at import time, like the rest of ``tpukernels.tuning`` —
``obs/trend.py`` (also stdlib-only) imports this module directly.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable

from tpukernels.resilience import journal

DEFAULT_MIN_FRAC = 0.5  # below this fraction of roofline -> verdict

# Machine peaks per canonical device_kind. The v5-lite numbers are the
# measured/derived figures docs/PERF.md §hardware-model records: MXU
# 184 TFLOPS measured single-pass bf16 (fp32 multiplicands emulate at
# 1/passes of that), VPU 8x128 lanes x ~4 ops/cycle x 0.94 GHz, HBM
# ~819 GB/s.
PEAKS = {
    "tpu_v5_lite": {
        "mxu_flops": 184e12,
        "mxu_passes_f32": 3,  # bf16_3x: the fp32-operand config of record
        "vpu_ops": 3.9e12,
        "hbm_gb_s": 819.0,
    },
    # Documented CPU FALLBACK row: single-core order-of-magnitude
    # numbers (one AVX-512 port stream) so the roofline plumbing runs
    # on any host. Chip conclusions never come from this row.
    "cpu": {
        "mxu_flops": 100e9,
        "mxu_passes_f32": 1,
        "vpu_ops": 50e9,
        "hbm_gb_s": 20.0,
    },
}

# The BASELINE.json "measured" medians were captured on v5 lite; trend
# verdicts judge committed evidence against this row unless
# TPK_ROOFLINE_DEVICE overrides it.
EVIDENCE_KIND = "tpu_v5_lite"


def resolve_kind(kind=None):
    """(peaks_row, requested_kind, basis) for a device kind string.

    basis: "exact" (a PEAKS row), "assumed-<row>" (unknown TPU kind
    borrowing the evidence row), or "cpu-fallback"."""
    if kind is None:
        kind = os.environ.get("TPK_ROOFLINE_DEVICE") or EVIDENCE_KIND
    if kind in PEAKS:
        return PEAKS[kind], kind, "exact"
    if kind.startswith("tpu"):
        return PEAKS[EVIDENCE_KIND], kind, f"assumed-{EVIDENCE_KIND}"
    return PEAKS["cpu"], kind, "cpu-fallback"


def min_frac() -> float:
    """The below_roofline threshold (TPK_ROOFLINE_MIN_FRAC, default
    0.5). Fail-loud parse, the TPK_* knob contract."""
    raw = os.environ.get("TPK_ROOFLINE_MIN_FRAC")
    if raw is None:
        return DEFAULT_MIN_FRAC
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if not 0.0 <= val <= 1.0:
        raise ValueError(
            f"TPK_ROOFLINE_MIN_FRAC={raw!r}: expected a float in [0, 1]"
        )
    return val


# ------------------------------------------------------------------ #
# shared sgemm byte arithmetic (the tuning VMEM model's other half)  #
# ------------------------------------------------------------------ #

def sgemm_bytes_per_block(bm: int, bn: int, bk: int) -> dict:
    """Byte components of one (bm, bn, bk) sgemm tile — the ONE place
    this arithmetic lives (ISSUE 6 satellite: the 32 MiB VMEM model in
    kernels/sgemm.py and the roofline byte count below both derive
    from it instead of hand-maintaining twin formulas).

    ``a``/``b`` are the K-streamed operand blocks as bf16 hi+lo pairs
    (4 B/elem — the same traffic as the f32 originals); ``c`` is the
    f32 C-in + out pair; ``acc`` the f32 accumulator scratch
    (VMEM-only, never HBM traffic)."""
    return {
        "a": 4 * bm * bk,
        "b": 4 * bk * bn,
        "c": 8 * bm * bn,
        "acc": 4 * bm * bn,
    }


def sgemm_hbm_bytes(m: int, n: int, k: int) -> float:
    """Minimum HBM traffic of the tiled kernel = one streamed visit
    per distinct block (Pallas re-fetches a block only when its index
    changes), i.e. the whole problem as one "block" of the shared
    arithmetic with the VMEM-only accumulator excluded:
    4·(m·k + k·n + 2·m·n) — the same figure kernels/sgemm.py reports
    to XLA via ``pl.CostEstimate``."""
    blk = sgemm_bytes_per_block(m, n, k)
    return float(blk["a"] + blk["b"] + blk["c"])


# ------------------------------------------------------------------ #
# per-metric models                                                  #
# ------------------------------------------------------------------ #

@dataclass(frozen=True)
class RooflineModel:
    """Analytic roofline for one bench metric at its config of record.

    ``flops``/``hbm_bytes``/``work`` are functions of the config tuple
    (so tests can pin them at other shapes): total FLOPs of one metric
    pass, its minimum HBM byte traffic, and the metric numerator
    (metric value = work / seconds). ``compute`` names the peak the
    compute leg runs against: "mxu_f32" (bf16-split fp32 operands,
    peak/passes), "mxu" (single-pass bf16), or "vpu". ``artifact``
    marks metrics whose config of record legitimately beats the HBM
    roofline (VMEM-resident working sets) — reported, never
    verdict-ed."""

    metric: str
    kernel: str
    config: tuple
    flops: Callable
    hbm_bytes: Callable
    work: Callable
    compute: str = "vpu"
    artifact: bool = False
    note: str = ""


MODELS = {
    # 2·m·n·k metric FLOPs execute as 3 MXU passes (bf16_3x), so the
    # compute peak is 184/3 ≈ 61.3 TFLOPS — the analytic peak lands on
    # the BASELINE.json ceiling (61,333 GFLOPS) by construction.
    "sgemm_gflops": RooflineModel(
        metric="sgemm_gflops",
        kernel="sgemm",
        config=(1024, 1024, 1024),
        flops=lambda m, n, k: 2.0 * m * n * k,
        hbm_bytes=lambda m, n, k: sgemm_hbm_bytes(m, n, k),
        work=lambda m, n, k: 2.0 * m * n * k / 1e9,
        compute="mxu_f32",
        note="bf16_3x: metric FLOPs run as 3 MXU passes",
    ),
    # SAXPY config of record (N=2^20, 8 MiB working set) stays
    # VMEM-resident across bench reps — measured values beat the HBM
    # roofline BY DESIGN (docs/PERF.md); the streaming metric below is
    # the honest sustained-HBM number.
    "saxpy_gb_s": RooflineModel(
        metric="saxpy_gb_s",
        kernel="vector_add",
        config=(1 << 20,),
        flops=lambda n: 2.0 * n,
        hbm_bytes=lambda n: 12.0 * n,  # read x, read y, write y
        work=lambda n: 12.0 * n / 1e9,  # the metric IS GB moved
        compute="vpu",
        artifact=True,
        note="VMEM-resident config of record; exceeds the HBM "
             "roofline by design (see saxpy_stream_gb_s)",
    ),
    "saxpy_stream_gb_s": RooflineModel(
        metric="saxpy_stream_gb_s",
        kernel="vector_add",
        config=(1 << 26,),
        flops=lambda n: 2.0 * n,
        hbm_bytes=lambda n: 12.0 * n,
        work=lambda n: 12.0 * n / 1e9,
        compute="vpu",
    ),
    # Per cell per sweep: 4 neighbor adds + 1 scale + 1 boundary
    # select = 6 VPU ops (docs/PERF.md's "~6 ops/cell/sweep"); HBM
    # traffic is 8 B/cell/sweep divided by the temporal-blocking depth
    # of record (k=8).
    "stencil2d_mcells_s": RooflineModel(
        metric="stencil2d_mcells_s",
        kernel="stencil2d",
        config=(4096, 4096),
        flops=lambda h, w: 6.0 * h * w,
        hbm_bytes=lambda h, w: 8.0 * h * w / 8.0,
        work=lambda h, w: h * w / 1e6,
        compute="vpu",
        note="per sweep at temporal depth k=8",
    ),
    # 3D: 5 neighbor adds + 1 scale + 1 select + ~1 mask-iota
    # amortized = 8 VPU ops/cell/sweep; same 8 B/cell/sweep over k=8.
    "stencil3d_mcells_s": RooflineModel(
        metric="stencil3d_mcells_s",
        kernel="stencil3d",
        config=(384, 384, 384),
        flops=lambda d, h, w: 8.0 * d * h * w,
        hbm_bytes=lambda d, h, w: 8.0 * d * h * w / 8.0,
        work=lambda d, h, w: d * h * w / 1e6,
        compute="vpu",
        note="per sweep at temporal depth k=8",
    ),
    # 20 fp32 ops per pairwise interaction (3 sub, 3 mul+2 add for r2,
    # eps add, rsqrt ~7, 3 FMA accumulates counted as 2 each ≈ 20 —
    # the factor that makes the 192.7 Ginter/s median 3.85 TFLOPS,
    # docs/PERF.md). The j-set is VMEM-resident; HBM is 7 f32 arrays.
    "nbody_ginter_s": RooflineModel(
        metric="nbody_ginter_s",
        kernel="nbody",
        config=(65536,),
        flops=lambda n: 20.0 * n * n,
        hbm_bytes=lambda n: 28.0 * n,
        work=lambda n: n * n / 1e9,
        compute="vpu",
    ),
    # Unfused pass of record: scan reads + writes its array, histogram
    # re-reads it = 12 B/elem (the fused TPK_SCANHIST_FUSE=on variant
    # cuts it to 8). MXU work (~1.5k flops/elem across the triangular
    # scan + nibble-count matmuls) is far off the binding leg.
    "scan_hist_melem_s": RooflineModel(
        metric="scan_hist_melem_s",
        kernel="scan",
        config=(1 << 22, 256),
        flops=lambda n, nbins: 1536.0 * n,
        hbm_bytes=lambda n, nbins: 12.0 * n,
        work=lambda n, nbins: n / 1e6,
        compute="mxu",
        note="bandwidth-bound; fused single-pass variant "
             "(TPK_SCANHIST_FUSE=on) cuts traffic to 8 B/elem",
    ),
}

# Registry kernel -> metric model, the completeness-lint surface
# (tests/test_registry_contract.py): every registry kernel must map
# here (directly, or through registry.DERIVED_KERNELS for derived
# entries like scan_exclusive).
KERNEL_METRIC = {
    "vector_add": "saxpy_gb_s",
    "sgemm": "sgemm_gflops",
    "stencil2d": "stencil2d_mcells_s",
    "stencil3d": "stencil3d_mcells_s",
    "scan": "scan_hist_melem_s",
    "histogram": "scan_hist_melem_s",
    "scan_histogram": "scan_hist_melem_s",
    "nbody": "nbody_ginter_s",
}


def _compute_peak(row: dict, compute: str) -> float:
    if compute == "mxu_f32":
        return row["mxu_flops"] / row["mxu_passes_f32"]
    if compute == "mxu":
        return row["mxu_flops"]
    return row["vpu_ops"]


def peak(metric: str, kind=None) -> dict:
    """The analytic roofline for one metric on one device kind:
    ``{metric, kernel, peak, bound, flops, hbm_bytes, device_kind,
    basis, artifact, note}`` — ``peak`` in the metric's own units,
    ``bound`` naming the binding leg."""
    model = MODELS[metric]
    row, rkind, basis = resolve_kind(kind)
    f = model.flops(*model.config)
    b = model.hbm_bytes(*model.config)
    w = model.work(*model.config)
    t_compute = f / _compute_peak(row, model.compute)
    t_bw = b / (row["hbm_gb_s"] * 1e9)
    t = max(t_compute, t_bw)
    return {
        "metric": metric,
        "kernel": model.kernel,
        "peak": w / t,
        "bound": "compute" if t_compute >= t_bw else "bandwidth",
        "flops": f,
        "hbm_bytes": b,
        "device_kind": rkind,
        "basis": basis,
        "artifact": model.artifact,
        "note": model.note,
    }


def report_rows(verdicts=None, kind=None) -> list:
    """One row per modeled metric, achieved values joined in from a
    ``trend.analyze`` verdict table (``achieved``/``frac`` are None
    for no-data metrics). Emits one ``roofline_computed`` journal
    event so a traced session records which peaks the table was judged
    against — the evidence twin of the rendered table."""
    rows = []
    for metric in sorted(MODELS):
        p = peak(metric, kind)
        v = (verdicts or {}).get(metric) or {}
        achieved = v.get("latest")
        frac = achieved / p["peak"] if achieved else None
        rows.append({
            **p,
            "achieved": achieved,
            "frac": frac,
            "verdict": v.get("verdict"),
        })
    journal.emit(
        "roofline_computed",
        device_kind=rows[0]["device_kind"] if rows else None,
        basis=rows[0]["basis"] if rows else None,
        min_frac=min_frac(),
        metrics={
            r["metric"]: {
                "peak": round(r["peak"], 1),
                "frac": round(r["frac"], 3) if r["frac"] is not None
                else None,
                "bound": r["bound"],
            }
            for r in rows
        },
    )
    return rows
