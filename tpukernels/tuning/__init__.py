"""Autotuning subsystem (docs/TUNING.md).

Three layers, each importable on its own:

- ``space``  — declarative search spaces. Each kernel module exports
  ``TUNABLES`` (a :class:`~tpukernels.tuning.space.SearchSpace`, or a
  tuple of them for multi-kernel modules) naming its tunable knobs,
  their env-var spellings, shipped defaults, sweep values, and an
  analytic VMEM-budget model that prunes infeasible candidates before
  they burn chip time. ``space.resolve`` is the single param-resolution
  path every kernel wrapper calls, with documented precedence
  env-override > tuned-cache > shipped-default.
- ``cache``  — the persistent JSON tuning cache under the
  ``_cachedir`` root, keyed by (kernel, shape, dtype, device_kind) and
  validated against the jax version and the HEAD sha of the kernel's
  sources (git-epoch invalidation, mirroring bench.py's evidence
  rules: params tuned on pre-change code are rejected loudly, never
  silently applied).
- ``runner`` — the sweep driver behind ``tools/autotune.py``: each
  candidate runs through the real metric path (``bench.py --one``) in
  a killable subprocess via the resilience watchdog, journaling
  ``tuning_candidate``/``tuning_promoted`` health events; ``--smoke``
  exercises the whole pipeline on CPU interpret mode for CI.

This package is stdlib-only at import time (jax is imported lazily
inside functions) so ``tpukernels.registry`` can import it without
breaking the ``import tpukernels`` jax-free contract.
"""

from tpukernels.tuning.space import (  # noqa: F401
    SearchSpace,
    Tunable,
    resolve,
)
