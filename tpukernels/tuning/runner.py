"""Autotune sweep runner (docs/TUNING.md §runner; CLI tools/autotune.py).

Every candidate runs through the REAL metric path — ``bench.py --one
<metric>`` with the candidate's env knobs — in a killable subprocess
via the resilience watchdog, so one wedged candidate costs its timeout
and nothing more (the PR-1 lesson: SIGALRM cannot interrupt a hung
C-level PJRT call; a subprocess kill can). Each candidate lands a
``tuning_candidate`` journal event; a promotion lands
``tuning_promoted`` plus the cache write.

Promotion rule (docs/TUNING.md): a candidate is promoted into the
tuning cache only when it beats the shipped-default CONTROL row by
more than :data:`PROMOTE_MARGIN` on the bench medians — matching the
old sgemm_tune's ">3% before promoting" guidance, now enforced in code
instead of prose. ``--smoke`` mode is the exception: values there are
meaningless (TPK_BENCH_SMOKE collapses the repeat counts), so smoke
promotes the first measurable candidate marked ``smoke: true`` — its
purpose is proving the sweep → cache → dispatch pipeline on CPU, and
its entry is keyed by device_kind=cpu so it can never steer a TPU run.

Bench children always run with ``TPK_TUNING_CACHE=0``: env overrides
dominate every tunable anyway, but a knob the candidate leaves unset
(a kernel-computed default) must fall back to the SHIPPED default, not
to whatever a half-written cache says.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from tpukernels import _cachedir
from tpukernels.obs import metrics as obs_metrics
from tpukernels.obs import trace
from tpukernels.resilience import journal, watchdog

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
PROMOTE_MARGIN = 0.03  # tuned config must beat control by >3% on medians

# CPU interpret-mode sweep for CI: never touches the tunnel, collapses
# repeat counts, forces interpret so kernels need no chip to compile
_SMOKE_ENV = {
    "PALLAS_AXON_POOL_IPS": "",
    "JAX_PLATFORMS": "cpu",
    "TPK_BENCH_SMOKE": "1",
    "TPU_KERNELS_INTERPRET": "1",
}


def probe_identity(env, timeout_s=240):
    """(device_kind, jax_version) as the bench CHILDREN will see them —
    probed in a subprocess under the same env, because the parent may
    run scrubbed-CPU while the children dial the tunnel. Returns None
    when the probe hangs or errors (the caller aborts the sweep: with
    no identity there is no valid cache key to write)."""
    code = (
        "import jax, json; d = jax.devices()[0]; "
        "print(json.dumps({'device_kind': "
        "d.device_kind.lower().replace(' ', '_'), "
        "'jax': jax.__version__}))"
    )
    r, status = watchdog.kill_after(
        [sys.executable, "-c", code],
        timeout_s,
        site="autotune identity probe",
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    if status != "ok" or r.returncode != 0:
        return None
    try:
        return json.loads(r.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        return None


def _journal_file(env) -> str | None:
    """The health-journal file the bench CHILDREN will append to under
    ``env``, or None when journaling is off — the runner tails it to
    measure each candidate's AOT hit ratio (the children's
    ``aot_hit``/``aot_miss`` events are the only cross-process compile
    evidence; stdout must stay byte-identical by contract). Resolution
    — including the directory-valued form — is the journal module's
    own rule, applied to the child env instead of ours."""
    return journal.resolve(env.get("TPK_HEALTH_JOURNAL"))


def _journal_size(path) -> int:
    if path is None:
        return 0
    try:
        return os.stat(path).st_size
    except OSError:
        return 0


def _sweep_guard_kernels(kernel, metric):
    """The registry kernels whose integrity failures indict THIS
    sweep's candidates: the tuned kernel plus every kernel bound to
    the same bench metric (tuning ``scan`` measures through
    ``scan_hist_melem_s``, whose bench child guards the combined
    ``scan_histogram`` pass). Filtering matters: the journal is the
    shared dated file, and an unrelated kernel's failure in a
    concurrent run must not discard a healthy candidate."""
    from tpukernels.tuning import roofline

    names = {kernel}
    names.update(
        k for k, m in roofline.KERNEL_METRIC.items() if m == metric
    )
    return names


def _integrity_failures(path, offset, kernels):
    """Count ``output_integrity_failed`` events for ``kernels``
    appended past byte ``offset`` — the candidate child's own guard
    confirming its results are corrupt (docs/RESILIENCE.md §output
    integrity). The child quarantines the (kernel, candidate-knob
    config) itself via the shared quarantine ledger; the runner's job
    is to DISCARD the measurement so a corrupt variant can never win
    a promotion."""
    if path is None:
        return 0
    n = 0
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            for line in f.read().splitlines():
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                n += (
                    ev.get("kind") == "output_integrity_failed"
                    and ev.get("kernel") in kernels
                )
    except OSError:
        return 0
    return n


def _aot_hit_ratio(path, offset):
    """hits/(hits+misses) over journal events appended past byte
    ``offset``, or None when journaling is off / no compile happened
    (a fully warm candidate emits hits only — ratio 1.0; a genuinely
    new block shape shows up as < 1.0)."""
    if path is None:
        return None
    hits = misses = 0
    try:
        with open(path, "rb") as f:
            f.seek(offset)
            for line in f.read().splitlines():
                try:
                    kind = json.loads(line).get("kind")
                except ValueError:
                    continue
                hits += kind == "aot_hit"
                misses += kind == "aot_miss"
    except OSError:
        return None
    if hits + misses == 0:
        return None
    return round(hits / (hits + misses), 3)


def run_candidate(metric, env, timeout_s):
    """One candidate through ``bench.py --one`` under the watchdog's
    hard kill. (value, status) with status in ok|timeout|error|parse —
    the same vocabulary bench's own per-metric isolation uses."""
    r, status = watchdog.kill_after(
        [sys.executable, os.path.join(_REPO, "bench.py"), "--one", metric],
        timeout_s,
        site=f"autotune --one {metric}",
        env=env,
        cwd=_REPO,
        stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL,
        text=True,
    )
    if status == "timeout":
        return None, "timeout"
    if r.returncode != 0:
        return None, "error"
    try:
        return json.loads(r.stdout.strip().splitlines()[-1])["value"], "ok"
    except (ValueError, KeyError, IndexError):
        return None, "parse"


def tune(
    kernel: str,
    smoke: bool = False,
    quick: bool = False,
    max_candidates: int | None = None,
    timeout_s: float | None = None,
    base_env: dict | None = None,
    echo=None,
):
    """Sweep one kernel's search space; returns a summary dict.

    ``base_env`` is the environment bench children run under (default:
    os.environ — callers that scrub their OWN env for a tunnel-free
    parent pass the original here). ``echo`` gets one line per
    candidate for CLI progress."""
    from tpukernels import registry
    from tpukernels.tuning import cache as tcache

    space = registry.tunables(kernel)
    if space.metric is None:
        raise ValueError(
            f"kernel {kernel!r} declares no bench metric; nothing to tune"
        )
    echo = echo or (lambda line: None)
    env0 = dict(os.environ if base_env is None else base_env)
    if smoke:
        env0.update(_SMOKE_ENV)
    env0["TPK_TUNING_CACHE"] = "0"  # children never read mid-sweep
    # the bench children journal to this file anyway (bench.py's CLI
    # default); making it explicit in env0 lets the runner tail their
    # aot_hit/aot_miss AND output_integrity_failed evidence — without
    # it, an unset var here meant the runner read None while the
    # children wrote the dated default. An explicit "0"/off stays off.
    if env0.get("TPK_HEALTH_JOURNAL") is None:
        env0["TPK_HEALTH_JOURNAL"] = journal.default_path()
    # every candidate re-enters a cold process; the shared persistent
    # compilation cache (docs/PERF.md §compile discipline) means only
    # genuinely NEW block shapes compile — candidate N+1 re-lowers but
    # never re-pays the backend compile for programs candidate N
    # already built. setdefault semantics: an explicit cache dir in
    # base_env wins.
    _cachedir.ensure_compilation_cache(env0)
    if timeout_s is None:
        timeout_s = float(
            os.environ.get("TPK_TUNE_TIMEOUT_S", "60" if smoke else "420")
        )

    ident = probe_identity(env0)
    if ident is None:
        raise RuntimeError(
            "autotune: environment identity probe failed (backend "
            "unreachable?) - no valid cache key can be written"
        )

    cands, pruned = space.candidates(shape=space.bench_shape)
    if pruned:
        # no silent caps: pruned candidates are part of the story
        echo(
            f"# {pruned} candidate(s) pruned by the VMEM budget "
            f"({space.vmem_budget_bytes // 2**20} MiB)"
        )
    if quick:
        # "3 most promising" (space.quick_candidates docstring): the
        # control plus single-axis probes of the first declared
        # tunable — the A-reload vs accumulator-locality trade the
        # old sgemm grid rationale ranked first
        cands = space.quick_candidates(shape=space.bench_shape)
    if smoke and max_candidates is None:
        max_candidates = 3
    if max_candidates is not None and len(cands) > max_candidates:
        echo(
            f"# sweep capped at {max_candidates} of {len(cands)} "
            "candidates (--max-candidates)"
        )
        cands = cands[:max_candidates]
    if not cands:
        # everything pruned or capped away: the documented "nothing
        # measured" outcome, not an IndexError mid-summary
        journal.emit(
            "tuning_sweep_end", kernel=kernel, measured=0, failed=0,
            promoted=None,
        )
        return {
            "kernel": kernel, "metric": space.metric, "identity": ident,
            "rows": [], "control": None, "best": None, "promoted": None,
            "cache_key": None, "cache_path": tcache.path(),
            "pruned": pruned,
        }

    journal.emit(
        "tuning_sweep_start",
        kernel=kernel,
        metric=space.metric,
        candidates=len(cands),
        pruned=pruned,
        smoke=smoke,
        device_kind=ident["device_kind"],
    )
    rows = []
    for params in cands:
        env = dict(env0)
        env.update(space.env_for(params))
        t0 = time.monotonic()
        # re-resolved per candidate: a directory-valued journal
        # rotates to a new dated file at midnight, and a long sweep
        # must tail the file THIS candidate's children append to
        jpath = _journal_file(env0)
        j0 = _journal_size(jpath)
        # candidate params ride on the span so a trace of the sweep
        # shows where the sweep's wall clock went per configuration
        with trace.span(f"tune/{kernel}", **params):
            value, status = run_candidate(space.metric, env, timeout_s)
        elapsed = round(time.monotonic() - t0, 2)
        # the child's aot_hit/aot_miss events landed in the shared
        # journal past j0: its compile-cache hit ratio is the
        # chip-minute story of this candidate (1.0 = fully warm, the
        # sweep spent its wall measuring; <1.0 = new block shapes)
        aot_ratio = _aot_hit_ratio(jpath, j0)
        # the child's integrity guard confirmed corrupt output for
        # this candidate's knob config: the measured value is garbage
        # by definition — discard it (status "integrity") so max()
        # can never promote a fast-but-wrong variant. The child
        # already journaled output_integrity_failed and quarantined
        # the (kernel, config) in the shared ledger.
        integrity_failed = _integrity_failures(
            jpath, j0, _sweep_guard_kernels(kernel, space.metric)
        )
        if integrity_failed and value is not None:
            value, status = None, "integrity"
        obs_metrics.inc(
            "tuning.candidates_ok" if value is not None
            else "tuning.candidates_failed"
        )
        journal.emit(
            "tuning_candidate",
            kernel=kernel,
            params=params,
            value=value,
            status=status,
            elapsed_s=elapsed,
            aot_hit_ratio=aot_ratio,
            integrity_failed=integrity_failed,
        )
        shown = (
            f"{value:12.2f}" if value is not None else f"  FAIL ({status})"
        )
        echo(
            "  ".join(f"{k}={v}" for k, v in params.items())
            + f"  {shown}"
            + (f"  [aot hit {aot_ratio:.0%}]" if aot_ratio is not None
               else "")
        )
        rows.append({"params": params, "value": value, "status": status,
                     "aot_hit_ratio": aot_ratio,
                     "integrity_failed": integrity_failed})

    # candidates() puts the shipped defaults first; if a space ever
    # ships infeasible defaults (pruned), there is no control row and
    # nothing can prove the >3% margin — no promotion then.
    control = rows[0] if rows[0]["params"] == space.defaults() else None
    measured = [r for r in rows if r["value"] is not None]
    best = max(measured, key=lambda r: r["value"], default=None)
    promoted = None
    if smoke:
        # pipeline proof, not a tuning claim (see module docstring):
        # sweep-order-first, so the written entry is deterministic —
        # the collapsed-repeat values max() would pick between are
        # meaningless by construction
        promoted = measured[0] if measured else None
    elif (
        best is not None
        and control is not None
        and best is not control
        and control["value"]
        and best["value"] > control["value"] * (1.0 + PROMOTE_MARGIN)
    ):
        promoted = best
    key = None
    if promoted is not None:
        key = tcache.put(
            space,
            promoted["params"],
            shape=space.bench_shape,
            dtype=space.bench_dtype,
            kind=ident["device_kind"],
            value=promoted["value"],
            control=control["value"] if control else None,
            smoke=smoke,
            jax_version=ident["jax"],
        )
        journal.emit(
            "tuning_promoted",
            kernel=kernel,
            key=key,
            params=promoted["params"],
            value=promoted["value"],
            control=control["value"] if control else None,
            smoke=smoke,
        )
    journal.emit(
        "tuning_sweep_end",
        kernel=kernel,
        measured=len(measured),
        failed=len(rows) - len(measured),
        promoted=promoted["params"] if promoted else None,
    )
    return {
        "kernel": kernel,
        "metric": space.metric,
        "identity": ident,
        "rows": rows,
        "control": control,
        "best": best,
        "promoted": promoted,
        "cache_key": key,
        "cache_path": tcache.path(),
        "pruned": pruned,
    }


def tune_table(
    table: dict,
    smoke: bool = False,
    quick: bool = False,
    max_candidates: int | None = None,
    timeout_s: float | None = None,
    base_env: dict | None = None,
    echo=None,
):
    """Sweep every tunable kernel a bucket TABLE names — the
    adaptive-bucket canary's re-autotune step (docs/SERVING.md
    §adaptive buckets): a candidate table changes the shapes the fleet
    compiles for, so the tuned knobs deserve a fresh look before the
    canary measures it. Same promotion rule as :func:`tune` (the >3%
    margin per kernel); kernels with no declared bench metric are
    skipped loudly, never an error — a table is allowed to bucket
    kernels that don't bench. Returns ``{kernel: summary-or-None}``
    (None = skipped)."""
    from tpukernels import registry

    echo = echo or (lambda line: None)
    out = {}
    for kernel in sorted(table):
        try:
            space = registry.tunables(kernel)
        except KeyError:
            echo(f"# tune_table: {kernel!r} not in the registry, "
                 "skipped")
            out[kernel] = None
            continue
        if space.metric is None:
            echo(f"# tune_table: {kernel} declares no bench metric, "
                 "skipped")
            out[kernel] = None
            continue
        out[kernel] = tune(
            kernel, smoke=smoke, quick=quick,
            max_candidates=max_candidates, timeout_s=timeout_s,
            base_env=base_env, echo=echo,
        )
    return out
