"""Distributed layer (SURVEY.md C9): the MPI harness, rebuilt TPU-native.

The reference's multi-node story is MPI: rank topology, domain
decomposition, `MPI_Sendrecv` halo exchange, `MPI_Allreduce` (measured
as a bus-bandwidth microbenchmark 8→64 chips). Here the wire is owned
by the XLA runtime instead: `jax.distributed.initialize()` +
`jax.sharding.Mesh` over ICI/DCN, with collectives expressed as
`jax.lax.psum` / `ppermute` / `all_gather` inside `shard_map`. No
NCCL/Gloo/UCX anywhere.

- ``mesh``        — device mesh construction (single- and multi-host)
- ``collectives`` — distributed kernel variants: row-sharded stencil
                    with ppermute halos, i-sharded N-body with a
                    j-block ring, two-level prefix scan, psum-merged
                    histogram, plain allreduce
- ``busbw``       — collective bandwidth microbenchmark (allreduce
                    bus-bw; ppermute per-link point-to-point)
"""

from tpukernels.parallel.mesh import make_mesh, maybe_distributed_init  # noqa: F401
