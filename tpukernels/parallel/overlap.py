"""Comm/compute overlap measurement (docs/DISTRIBUTED.md §overlap).

The depth-pipelined kernels (TPK_DIST_DEPTH, collectives.py) claim to
hide ppermute hops under compute. This module makes that claim a
measured, artifact-backed figure the obs stack can judge — CPU-provable
under the 2-process gloo harness, no chip window needed.

Per op it times three warm jitted programs, best-of-reps:

- ``comm``    — only the op's wire pattern (the ring rotations / halo
                ppermutes), chained so hops serialize like the real
                program's;
- ``compute`` — only the op's arithmetic (force blocks / sweeps), no
                collectives;
- ``full``    — the real kernel at the configured pipeline depth.

If the runtime truly overlaps, ``t_full < t_comm + t_compute``; the
headline figure is

    overlap_frac = clamp01((t_comm + t_compute - t_full)
                           / min(t_comm, t_compute))

i.e. the fraction of the SMALLER phase that the full program hid (1.0 =
the cheaper side rode entirely under the other). Each op's measurement
runs inside an ``overlap/<op>`` span with pre-measured ``comm/<op>``
and ``compute/<op>`` child spans (docs/OBSERVABILITY.md §span names),
emits one ``overlap_point`` journal event, and the CLI persists the
sweep as a ``docs/logs/scaling_overlap_*.json`` artifact that
``tools/obs_report.py`` judges: a validated non-fake point under
``TPK_OVERLAP_MIN_FRAC`` earns the NON-GATING ``overlap_low`` verdict.

CLI:  python -m tpukernels.parallel.overlap [--ops=nbody_ring,stencil2d]
          [--reps=5] [--quick] [--depth=D]
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from tpukernels.obs import metrics as obs_metrics
from tpukernels.obs import scaling, trace
from tpukernels.parallel import collectives
from tpukernels.parallel.mesh import host_to_global, make_mesh, row_sharding
from tpukernels.resilience import journal

DEFAULT_OPS = ("nbody_ring", "stencil2d")

# per-op working-set knobs: (default, --quick)
_WORK = {
    "nbody_bodies": (4096, 256),   # per rank
    "nbody_steps": (2, 1),
    "stencil_rows": (1024, 64),    # per rank
    "stencil_cols": (2048, 256),
    "stencil_iters": (16, 8),
    "stencil_k": (4, 4),
}


def _work(name: str, quick: bool) -> int:
    return _WORK[name][1 if quick else 0]


def _probe(fn):
    """Wrap a program so it returns one fully-replicated scalar — the
    busbw timed_program rule: fetchable on every host, and the full
    output stays live so XLA cannot narrow the collective."""
    return jax.jit(
        lambda *a: sum(
            jnp.sum(o) for o in jax.tree_util.tree_leaves(fn(*a))
        )
    )


def _nbody_programs(mesh, axis, depth, quick):
    """(full, comm, compute, args) for the ring N-body op."""
    from jax.sharding import PartitionSpec as P

    from tpukernels.compat import shard_map

    nranks = mesh.shape[axis]
    steps = _work("nbody_steps", quick)
    n = _work("nbody_bodies", quick) * nranks
    rng = np.random.default_rng(0)
    state = tuple(
        host_to_global(
            rng.standard_normal(n).astype(np.float32)
            if i < 6 else
            rng.uniform(0.5, 1.5, n).astype(np.float32),
            row_sharding(mesh, axis),
        )
        for i in range(7)
    )
    full = _probe(
        collectives._nbody_ring_build(
            steps, mesh, axis, 1e-3, 1e-2, False, False, depth
        )
    )
    fwd = collectives._ring_perm(nranks, 1)
    eps2 = jnp.float32(1e-4)

    def comm_local(jx, jy, jz, jm):
        # the ring's wire pattern alone: steps x (nranks-1) chained
        # block rotations (chained through the carry, so hops
        # serialize exactly like the pipeline's critical path)
        def body(_, bs):
            return tuple(
                jax.lax.ppermute(b, axis, fwd) for b in bs
            )

        return jax.lax.fori_loop(
            0, steps * max(nranks - 1, 1), body, (jx, jy, jz, jm)
        )

    def compute_local(px, py, pz, m):
        # the arithmetic alone: steps x nranks force blocks on the
        # local i-bodies, no collective anywhere
        def body(_, acc):
            ax, ay, az = acc
            dax, day, daz = collectives._pairwise_accel(
                px, py, pz, px, py, pz, m, eps2
            )
            return (ax + dax, ay + day, az + daz)

        zero = jnp.zeros_like(px)
        return jax.lax.fori_loop(
            0, steps * nranks, body, (zero, zero, zero)
        )

    shard = P(axis)
    comm = _probe(jax.jit(shard_map(
        comm_local, mesh=mesh, in_specs=(shard,) * 4,
        out_specs=(shard,) * 4,
    )))
    compute = _probe(jax.jit(shard_map(
        compute_local, mesh=mesh, in_specs=(shard,) * 4,
        out_specs=(shard,) * 3,
    )))
    xyzm = (state[0], state[1], state[2], state[6])
    return {"full": (full, state), "comm": (comm, xyzm),
            "compute": (compute, xyzm)}


def _stencil_programs(mesh, axis, depth, quick):
    """(full, comm, compute, args) for the 2-D Jacobi halo op."""
    from jax.sharding import PartitionSpec as P

    from tpukernels.compat import shard_map

    nranks = mesh.shape[axis]
    rows = _work("stencil_rows", quick) * nranks
    cols = _work("stencil_cols", quick)
    iters = _work("stencil_iters", quick)
    k = _work("stencil_k", quick)
    l0 = rows // nranks
    passes = max(iters // k, 1)
    rng = np.random.default_rng(1)
    x = host_to_global(
        rng.standard_normal((rows, cols)).astype(np.float32),
        row_sharding(mesh, axis),
    )
    full = _probe(
        collectives._jacobi_dist_build(
            (rows, cols), iters, mesh, axis, k, False, depth
        )
    )
    up = collectives._ring_perm(nranks, 1)
    down = collectives._ring_perm(nranks, -1)

    def comm_local(v):
        # the halo wire pattern alone: one k-deep top+bottom exchange
        # per round, received bands written back into the carry so
        # rounds serialize like the real halo dependency chain
        def body(_, v):
            top = jax.lax.ppermute(v[-k:], axis, up)
            bot = jax.lax.ppermute(v[:k], axis, down)
            return jnp.concatenate([top, v[k : l0 - k], bot], axis=0)

        return jax.lax.fori_loop(0, passes, body, v)

    def compute_local(v):
        # the sweeps alone: k fused local sweeps per round, no halos
        def body(_, v):
            for _s in range(k):
                v = 0.25 * sum(
                    collectives._edge_shift(v, a, f)
                    for a in (0, 1) for f in (True, False)
                )
            return v

        return jax.lax.fori_loop(0, passes, body, v)

    shard = P(axis, None)
    comm = _probe(jax.jit(shard_map(
        comm_local, mesh=mesh, in_specs=shard, out_specs=shard,
    )))
    compute = _probe(jax.jit(shard_map(
        compute_local, mesh=mesh, in_specs=shard, out_specs=shard,
    )))
    return {"full": (full, (x,)), "comm": (comm, (x,)),
            "compute": (compute, (x,))}


_BUILDERS = {
    "nbody_ring": _nbody_programs,
    "stencil2d": _stencil_programs,
}


def _time_best(fn, args, reps: int) -> float:
    """Warm (compile + first run, untimed), then best-of-reps wall.
    The probe output is a replicated scalar; np.asarray inside the
    timed window forces real completion (the busbw materialization
    rule), the barrier after catches straggler local devices."""
    w = fn(*args)
    np.asarray(w)
    jax.block_until_ready(w)
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        r = fn(*args)
        np.asarray(r)
        best = min(best, time.perf_counter() - t0)
        jax.block_until_ready(r)
    return best


def overlap_frac(t_comm: float, t_compute: float,
                 t_full: float) -> float:
    """clamp01((t_comm + t_compute - t_full) / min(t_comm, t_compute)):
    the fraction of the cheaper phase the full program hid."""
    denom = min(t_comm, t_compute)
    if denom <= 0:
        return 0.0
    return max(0.0, min(1.0, (t_comm + t_compute - t_full) / denom))


def measure(ops=None, mesh=None, axis: str = "x", depth=None,
            reps: int = 5, quick: bool = False, verbose: bool = True,
            fake=None):
    """Measure comm/compute overlap for each op; returns the artifact
    ``points`` list. ``depth`` defaults to the TPK_DIST_DEPTH knob —
    measuring the configured path of record, not a hypothetical."""
    if mesh is None:
        mesh = make_mesh()  # joins the multi-host job when configured
    nranks = mesh.shape[axis]
    if depth is None:
        depth = collectives._dist_depth()
    if fake is None:
        fake = scaling.inventory(probe=True).get("fake", True)
    points = []
    for op in ops or DEFAULT_OPS:
        if op not in _BUILDERS:
            raise ValueError(
                f"op={op!r}: expected one of {sorted(_BUILDERS)}"
            )
        progs = _BUILDERS[op](mesh, axis, int(depth), quick)
        with trace.span(f"overlap/{op}", n=nranks, depth=int(depth)):
            t_comm = _time_best(*progs["comm"], reps)
            trace.emit_span(f"comm/{op}", t_comm, n=nranks)
            t_compute = _time_best(*progs["compute"], reps)
            trace.emit_span(f"compute/{op}", t_compute, n=nranks)
            t_full = _time_best(*progs["full"], reps)
        frac = overlap_frac(t_comm, t_compute, t_full)
        point = {
            "op": op, "n_devices": int(nranks), "mesh_shape": None,
            "depth": int(depth), "t_comm_s": round(t_comm, 6),
            "t_compute_s": round(t_compute, 6),
            "t_full_s": round(t_full, 6),
            "overlap_frac": round(frac, 4),
        }
        points.append(point)
        obs_metrics.inc("scaling.overlap_points")
        journal.emit("overlap_point", fake=bool(fake), **point)
        if verbose:
            print(
                f"overlap {op:<12} n={nranks} depth={depth} "
                f"comm={t_comm * 1e3:8.3f}ms "
                f"compute={t_compute * 1e3:8.3f}ms "
                f"full={t_full * 1e3:8.3f}ms frac={frac:5.3f}"
            )
    return points


if __name__ == "__main__":
    import os
    import sys

    kw = {}
    for a in sys.argv[1:]:
        if a.startswith("--ops="):
            kw["ops"] = tuple(
                t for t in a[6:].split(",") if t.strip()
            )
        elif a.startswith("--reps="):
            kw["reps"] = int(a[7:])
        elif a == "--quick":
            kw["quick"] = True
        elif a.startswith("--depth="):
            kw["depth"] = int(a[8:])
    # CLI journal default (the bench/busbw/loadgen contract)
    if os.environ.get("TPK_HEALTH_JOURNAL") is None:
        os.environ["TPK_HEALTH_JOURNAL"] = journal.default_path()
    # mesh FIRST, probe second (the busbw CLI ordering rule:
    # jax.distributed.initialize must precede any backend init)
    mesh = make_mesh()
    inv = scaling.emit_inventory("overlap", probe=True)
    pts = measure(mesh=mesh, fake=inv.get("fake", True), **kw)
    artifact = scaling.write_overlap_artifact(pts, inv)
    print(f"# overlap artifact: {artifact}", file=sys.stderr)
