"""Device mesh helpers (SURVEY.md C9; patterns cf. SNIPPETS.md [1]-[3]).

The reference's `MPI_Init` + rank topology becomes: optionally
`jax.distributed.initialize()` (multi-host), then a named 1-D ring
mesh over however many chips are visible. The C driver runs once per
host with identical args — the moral equivalent of `mpirun` — and the
XLA runtime owns the wire (SURVEY.md §3(d), §5).
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def maybe_distributed_init() -> None:
    """Initialize multi-host JAX when launched under a coordinator.

    Single-process single-host (the common case, and always the case
    on this 1-chip dev box) needs nothing. Multi-host runs set the
    standard env vars; mirror mpirun's contract by only initializing
    when they are present. Idempotent — jax.distributed.initialize
    raises on a second call, and every make_mesh (one per adapter
    call, so the C driver's warm-up + timed reps repeat it) funnels
    through here.
    """
    from tpukernels.compat import (
        distributed_is_initialized,
        ensure_cpu_collectives,
    )

    if distributed_is_initialized():
        return
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if not addr:
        return
    # CPU-platform multi-process jobs (fake-device rehearsals) need
    # the gloo collectives backend that 0.4.x jax ships disabled
    ensure_cpu_collectives()
    # num_processes/process_id: jax reads JAX_COORDINATOR_ADDRESS
    # itself but fills the other two only from cluster auto-detection
    # (Slurm/OMPI/TPU-metadata). Pass them from the env explicitly so
    # the mpirun-style contract — export 3 vars, run the same command
    # per host — also works outside auto-detected clusters.
    kw = {}
    if "JAX_NUM_PROCESSES" in os.environ:
        kw["num_processes"] = int(os.environ["JAX_NUM_PROCESSES"])
    if "JAX_PROCESS_ID" in os.environ:
        kw["process_id"] = int(os.environ["JAX_PROCESS_ID"])
    jax.distributed.initialize(coordinator_address=addr, **kw)


def make_mesh(n_devices: int | None = None, axis: str = "x") -> Mesh:
    """A 1-D ring mesh over the first `n_devices` devices (default all).

    All the reference's communication patterns (halo sendrecv, ring
    body rotation, allreduce) are 1-D ring patterns, so a 1-D mesh is
    the faithful topology; ICI ring ordering is what
    `jax.lax.ppermute` rides on.

    Joins the multi-host job first when a coordinator is configured:
    EVERY pod-capable path (all C-shim adapters, busbw, the dryrun)
    builds its mesh here, and a mesh built before
    jax.distributed.initialize would silently cover only this host's
    chips.
    """
    maybe_distributed_init()
    devs = jax.devices()
    if n_devices is None:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(
            f"requested {n_devices} devices, have {len(devs)}"
        )
    return Mesh(np.array(devs[:n_devices]), (axis,))


def row_sharding(mesh: Mesh, axis: str = "x") -> NamedSharding:
    """Shard the leading dim across the mesh (domain decomposition)."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def host_to_global(a, sharding: NamedSharding):
    """Device input for a shard_map program from a FULL per-host copy
    (SURVEY.md §7 "multi-chip under a C driver": every host runs the
    same driver with identical buffers). Single-process: plain
    transfer, jit (re)shards it. Multi-process (8→64-chip pods): a
    host-local array can't feed a mesh spanning other hosts' devices,
    so assemble the global array shard-by-shard — each host
    materializes only the slices its own devices hold."""
    import jax.numpy as jnp

    if jax.process_count() == 1:
        return jnp.asarray(a)
    return jax.make_array_from_callback(
        a.shape, sharding, lambda idx: a[idx]
    )


def global_to_host(o) -> np.ndarray:
    """Full host value of a shard_map output. Replicated outputs are
    fetchable from any local shard; sharded outputs on a multi-process
    run live partly on other hosts and are all-gathered first so every
    host's driver sees (and checks) the whole result."""
    if jax.process_count() > 1 and not o.is_fully_replicated:
        from jax.experimental import multihost_utils

        o = multihost_utils.process_allgather(o, tiled=True)
    return np.asarray(o)
