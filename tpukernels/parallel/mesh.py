"""Device mesh helpers (SURVEY.md C9; patterns cf. SNIPPETS.md [1]-[3]).

The reference's `MPI_Init` + rank topology becomes: optionally
`jax.distributed.initialize()` (multi-host), then a named 1-D ring
mesh over however many chips are visible. The C driver runs once per
host with identical args — the moral equivalent of `mpirun` — and the
XLA runtime owns the wire (SURVEY.md §3(d), §5).
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def maybe_distributed_init() -> None:
    """Initialize multi-host JAX when launched under a coordinator.

    Single-process single-host (the common case, and always the case
    on this 1-chip dev box) needs nothing. Multi-host runs set the
    standard env vars; mirror mpirun's contract by only initializing
    when they are present. Idempotent — jax.distributed.initialize
    raises on a second call, and every make_mesh (one per adapter
    call, so the C driver's warm-up + timed reps repeat it) funnels
    through here.
    """
    from tpukernels.compat import (
        distributed_is_initialized,
        ensure_cpu_collectives,
    )

    if distributed_is_initialized():
        return
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS") or os.environ.get(
        "COORDINATOR_ADDRESS"
    )
    if not addr:
        return
    # CPU-platform multi-process jobs (fake-device rehearsals) need
    # the gloo collectives backend that 0.4.x jax ships disabled
    ensure_cpu_collectives()
    # num_processes/process_id: jax reads JAX_COORDINATOR_ADDRESS
    # itself but fills the other two only from cluster auto-detection
    # (Slurm/OMPI/TPU-metadata). Pass them from the env explicitly so
    # the mpirun-style contract — export 3 vars, run the same command
    # per host — also works outside auto-detected clusters.
    kw = {}
    if "JAX_NUM_PROCESSES" in os.environ:
        kw["num_processes"] = int(os.environ["JAX_NUM_PROCESSES"])
    if "JAX_PROCESS_ID" in os.environ:
        kw["process_id"] = int(os.environ["JAX_PROCESS_ID"])
    # the rest of the multi-host coordinator contract
    # (docs/DISTRIBUTED.md §multi-host): pin which local devices this
    # process owns (hosts sharing chips across processes), and bound
    # the coordinator rendezvous so a dead peer fails the job instead
    # of hanging it
    if "JAX_LOCAL_DEVICE_IDS" in os.environ:
        kw["local_device_ids"] = [
            int(t) for t in
            os.environ["JAX_LOCAL_DEVICE_IDS"].split(",") if t.strip()
        ]
    if "JAX_COORDINATOR_TIMEOUT_S" in os.environ:
        kw["initialization_timeout"] = int(
            os.environ["JAX_COORDINATOR_TIMEOUT_S"]
        )
    jax.distributed.initialize(coordinator_address=addr, **kw)


def make_mesh(n_devices=None, axis: str = "x",
              axes=("x", "y")) -> Mesh:
    """A mesh over the first devices (default: all, 1-D).

    ``n_devices`` as an int (or None) builds the 1-D ring of record —
    all the reference's communication patterns (halo sendrecv, ring
    body rotation, allreduce) are 1-D ring patterns, and ICI ring
    ordering is what `jax.lax.ppermute` rides on. ``n_devices`` as an
    ``(r, c)`` tuple builds a 2-D ``axes``-named mesh over the first
    ``r*c`` devices — the torus topology real pods expose, on which
    ``allreduce_sum`` decomposes into reduce-scatter-along-x /
    allgather-along-y (collectives.py) and 2-D shardings split both
    leading dims.

    Joins the multi-host job first when a coordinator is configured:
    EVERY pod-capable path (all C-shim adapters, busbw, the dryrun)
    builds its mesh here, and a mesh built before
    jax.distributed.initialize would silently cover only this host's
    chips.
    """
    maybe_distributed_init()
    devs = jax.devices()
    if isinstance(n_devices, (tuple, list)):
        r, c = (int(d) for d in n_devices)
        if r * c > len(devs):
            raise ValueError(
                f"requested {r}x{c}={r * c} devices, have {len(devs)}"
            )
        return Mesh(
            np.array(devs[: r * c]).reshape(r, c), tuple(axes)
        )
    if n_devices is None:
        n_devices = len(devs)
    if n_devices > len(devs):
        raise ValueError(
            f"requested {n_devices} devices, have {len(devs)}"
        )
    return Mesh(np.array(devs[:n_devices]), (axis,))


def row_sharding(mesh: Mesh, axis: str = "x") -> NamedSharding:
    """Shard the leading dim across the mesh (domain decomposition)."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def host_to_global(a, sharding: NamedSharding):
    """Device input for a shard_map program from a FULL per-host copy
    (SURVEY.md §7 "multi-chip under a C driver": every host runs the
    same driver with identical buffers). Single-process: plain
    transfer, jit (re)shards it. Multi-process (8→64-chip pods): a
    host-local array can't feed a mesh spanning other hosts' devices,
    so assemble the global array shard-by-shard — each host
    materializes only the slices its own devices hold."""
    import jax.numpy as jnp

    if jax.process_count() == 1:
        return jnp.asarray(a)
    return jax.make_array_from_callback(
        a.shape, sharding, lambda idx: a[idx]
    )


def global_to_host(o) -> np.ndarray:
    """Full host value of a shard_map output. Replicated outputs are
    fetchable from any local shard; sharded outputs on a multi-process
    run live partly on other hosts and are gathered first so every
    host's driver sees (and checks) the whole result. The gather is a
    jit identity resharded to replicated: `process_allgather(tiled=
    True)` concatenates host shards along axis 0 — correct only for
    the 1-D row sharding, silently interleaved garbage for a 2-D
    ``P("x","y")`` output — while an out_shardings respec follows the
    array's OWN sharding whatever its rank."""
    if jax.process_count() > 1 and not o.is_fully_replicated:
        from jax import jit
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(o.sharding.mesh, PartitionSpec())
        o = jit(lambda v: v, out_shardings=rep)(o)
    return np.asarray(o)
