"""Distributed kernel variants (SURVEY.md C9, §3(b)-(d)).

Each function is the TPU-native rebuild of one of the reference's MPI
patterns, as a `shard_map` program over a 1-D ring mesh:

- `allreduce_sum`    — MPI_Allreduce               → jax.lax.psum
- `jacobi2d_dist`    — halo MPI_Sendrecv + sweep   → ppermute halos,
                        fused into the per-iteration XLA program
- `nbody_dist_psum`  — partial forces allreduced   → psum (the
                        north-star's named formulation)
- `nbody_dist_ring`  — ring body-block rotation    → ppermute ring
                        (memory O(N/P) per chip; the ring-attention
                        structural analog, SURVEY.md §5)

On the dev box these are logic-tested on 8 fake CPU devices
(tests/test_distributed.py spawns subprocesses with the right env);
on a real v5e pod the same code rides ICI.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from tpukernels.utils import cdiv


def _ring_perm(n: int, shift: int = 1):
    """(src, dst) pairs rotating data `shift` ranks forward."""
    return [(i, (i + shift) % n) for i in range(n)]


# ------------------------------------------------------------ allreduce

def allreduce_sum(x, mesh: Mesh, axis: str = "x"):
    """MPI_Allreduce(SUM): x is (P, S) with row r = rank r's
    contribution; every row of the result is the elementwise sum."""
    f = shard_map(
        lambda xl: jax.lax.psum(xl, axis),
        mesh=mesh,
        in_specs=P(axis, None),
        out_specs=P(axis, None),
    )
    return f(x)


# ------------------------------------------------------------- stencil

def jacobi2d_dist(x, iters: int, mesh: Mesh, axis: str = "x", k: int = 4):
    """Row-sharded Jacobi 5-point: halo exchange via ppermute, sweep
    locally; comm + compute fuse into one XLA program per iteration
    (SURVEY.md §3(b)). x: (H, W) float32 with H % P == 0.

    Comm-avoiding: each round ppermutes a k-deep halo band and runs k
    fused local sweeps (the multi-chip mirror of the single-chip
    temporal blocking in kernels/stencil.py), trading k x halo bytes
    for 1/k as many ICI message rounds. Halo rows go stale one-per-
    sweep inward — k-deep halos bound that, so owned rows stay exact
    and the result is bitwise independent of k. Ring-wrapped halos at
    the global top/bottom carry wrong values, but those rows sit
    outside the Dirichlet interior mask and are never read by an
    unmasked row."""
    nranks = mesh.shape[axis]
    h, w = x.shape
    if h % nranks:
        raise ValueError(f"H={h} must divide across {nranks} ranks")
    lh = h // nranks
    k = max(1, min(int(k), lh))

    up_perm = _ring_perm(nranks, 1)  # my last rows -> (r+1)'s top halo
    down_perm = _ring_perm(nranks, -1)  # my first rows -> (r-1)'s bottom

    def local_fn(xl):  # (lh, w) local rows
        rank = jax.lax.axis_index(axis)

        def rounds(v, kk):
            top_halo = jax.lax.ppermute(v[-kk:], axis, up_perm)
            bot_halo = jax.lax.ppermute(v[:kk], axis, down_perm)
            p = jnp.concatenate([top_halo, v, bot_halo], axis=0)
            rows = lh + 2 * kk
            gr = (
                rank * lh
                - kk
                + jax.lax.broadcasted_iota(jnp.int32, (rows, w), 0)
            )
            gc = jax.lax.broadcasted_iota(jnp.int32, (rows, w), 1)
            interior = (gr > 0) & (gr < h - 1) & (gc > 0) & (gc < w - 1)
            for _ in range(kk):
                north = jnp.concatenate([p[:1], p[:-1]], axis=0)
                south = jnp.concatenate([p[1:], p[-1:]], axis=0)
                west = jnp.concatenate([p[:, :1], p[:, :-1]], axis=1)
                east = jnp.concatenate([p[:, 1:], p[:, -1:]], axis=1)
                out = 0.25 * (north + south + west + east)
                p = jnp.where(interior, out, p)
            return p[kk : kk + lh]

        passes, rem = divmod(iters, k)
        v = jax.lax.fori_loop(0, passes, lambda _, v: rounds(v, k), xl)
        if rem:
            v = rounds(v, rem)
        return v

    f = shard_map(
        local_fn, mesh=mesh, in_specs=P(axis, None), out_specs=P(axis, None)
    )
    return jax.jit(f)(x)


# -------------------------------------------------------------- nbody

def _pairwise_accel(pxi, pyi, pzi, jx, jy, jz, jm, eps2, chunk=2048):
    """Accelerations on i-bodies from j-bodies, chunked over j."""
    nj = jx.shape[0]
    nchunks = cdiv(nj, chunk)
    if nj % chunk:
        pad = nchunks * chunk - nj
        jx = jnp.pad(jx, (0, pad))
        jy = jnp.pad(jy, (0, pad))
        jz = jnp.pad(jz, (0, pad))
        jm = jnp.pad(jm, (0, pad))  # zero mass: no contribution

    def body(c, acc):
        ax, ay, az = acc
        sl = jax.lax.dynamic_slice_in_dim
        cx = sl(jx, c * chunk, chunk)
        cy = sl(jy, c * chunk, chunk)
        cz = sl(jz, c * chunk, chunk)
        cm = sl(jm, c * chunk, chunk)
        dx = cx[None, :] - pxi[:, None]
        dy = cy[None, :] - pyi[:, None]
        dz = cz[None, :] - pzi[:, None]
        r2 = dx * dx + dy * dy + dz * dz + eps2
        inv_r = jax.lax.rsqrt(r2)
        w = cm[None, :] * inv_r * inv_r * inv_r
        return (
            ax + jnp.sum(w * dx, axis=1),
            ay + jnp.sum(w * dy, axis=1),
            az + jnp.sum(w * dz, axis=1),
        )

    zero = jnp.zeros_like(pxi)
    return jax.lax.fori_loop(0, nchunks, body, (zero, zero, zero))


def nbody_dist_psum(state, steps: int, mesh: Mesh, axis: str = "x",
                    dt=1e-3, eps=1e-2):
    """North-star formulation: bodies partitioned as force *sources*
    (j sharded), positions replicated; each rank computes partial
    forces on all bodies from its j-partition, then `psum` combines
    (SURVEY.md C8/§3(c)). state = (px,py,pz,vx,vy,vz,m), all (N,)."""
    px, py, pz, vx, vy, vz, m = state
    dt = jnp.float32(dt)
    eps2 = jnp.float32(eps * eps)

    def local_fn(px, py, pz, vx, vy, vz, ml):
        # px..vz replicated (N,); ml local shard (N/P,)
        nranks = jax.lax.psum(1, axis)
        n = px.shape[0]
        lsz = n // nranks
        rank = jax.lax.axis_index(axis)

        def step(_, s):
            px, py, pz, vx, vy, vz = s
            j0 = rank * lsz
            sl = jax.lax.dynamic_slice_in_dim
            jx, jy, jz = (sl(a, j0, lsz) for a in (px, py, pz))
            ax, ay, az = _pairwise_accel(px, py, pz, jx, jy, jz, ml, eps2)
            ax = jax.lax.psum(ax, axis)
            ay = jax.lax.psum(ay, axis)
            az = jax.lax.psum(az, axis)
            vx = vx + ax * dt
            vy = vy + ay * dt
            vz = vz + az * dt
            return (px + vx * dt, py + vy * dt, pz + vz * dt, vx, vy, vz)

        return jax.lax.fori_loop(0, steps, step, (px, py, pz, vx, vy, vz))

    rep = P()
    shard = P(axis)
    f = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(rep, rep, rep, rep, rep, rep, shard),
        out_specs=(rep, rep, rep, rep, rep, rep),
        check_rep=False,  # psum of replicated inputs is intentional
    )
    return jax.jit(f)(px, py, pz, vx, vy, vz, m)


def nbody_dist_ring(state, steps: int, mesh: Mesh, axis: str = "x",
                    dt=1e-3, eps=1e-2):
    """Ring formulation: i-bodies sharded, j-blocks rotate around the
    ring via ppermute (memory O(N/P) per chip) — the reference's
    Sendrecv body-rotation pipeline (SURVEY.md §2 C8, §5 'ring
    communication'). state arrays (N,), N % P == 0."""
    px, py, pz, vx, vy, vz, m = state
    dt = jnp.float32(dt)
    eps2 = jnp.float32(eps * eps)
    nranks = mesh.shape[axis]
    perm = _ring_perm(nranks, 1)

    def local_fn(pxl, pyl, pzl, vxl, vyl, vzl, ml):
        def step(_, s):
            pxl, pyl, pzl, vxl, vyl, vzl = s

            def ring(k, carry):
                ax, ay, az, jx, jy, jz, jm = carry
                dax, day, daz = _pairwise_accel(
                    pxl, pyl, pzl, jx, jy, jz, jm, eps2
                )
                jx = jax.lax.ppermute(jx, axis, perm)
                jy = jax.lax.ppermute(jy, axis, perm)
                jz = jax.lax.ppermute(jz, axis, perm)
                jm = jax.lax.ppermute(jm, axis, perm)
                return (ax + dax, ay + day, az + daz, jx, jy, jz, jm)

            zero = jnp.zeros_like(pxl)
            ax, ay, az, *_ = jax.lax.fori_loop(
                0, nranks, ring, (zero, zero, zero, pxl, pyl, pzl, ml)
            )
            vxl = vxl + ax * dt
            vyl = vyl + ay * dt
            vzl = vzl + az * dt
            return (
                pxl + vxl * dt, pyl + vyl * dt, pzl + vzl * dt,
                vxl, vyl, vzl,
            )

        return jax.lax.fori_loop(
            0, steps, step, (pxl, pyl, pzl, vxl, vyl, vzl)
        )

    shard = P(axis)
    f = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(shard,) * 7,
        out_specs=(shard,) * 6,
    )
    return jax.jit(f)(px, py, pz, vx, vy, vz, m)
