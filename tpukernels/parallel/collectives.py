"""Distributed kernel variants (SURVEY.md C9, §3(b)-(d)).

Each function is the TPU-native rebuild of one of the reference's MPI
patterns, as a `shard_map` program over a 1-D ring mesh:

- `allreduce_sum`    — MPI_Allreduce               → jax.lax.psum
- `jacobi2d_dist` /
  `jacobi3d_dist`    — halo MPI_Sendrecv + sweep   → comm-avoiding
                        k-deep ppermute halo bands, fused into the
                        per-round XLA program (shared _jacobi_dist)
- `nbody_dist_psum`  — partial forces allreduced   → psum (the
                        north-star's named formulation)
- `nbody_dist_ring`  — ring body-block rotation    → ppermute ring
                        (memory O(N/P) per chip; the ring-attention
                        structural analog, SURVEY.md §5)
- `scan_dist`        — MPI two-level prefix sum    → local cumsum +
                        all_gather of rank totals (the MPI_Exscan
                        decomposition)
- `histogram_dist`   — privatized bins + MPI merge → local count +
                        psum (SURVEY.md §5 "MPI_Allreduce for ...
                        histogram merge")
- `bcast`            — MPI_Bcast of root's params  → masked psum
- `ring_shift`       — bare MPI_Sendrecv neighbor  → ppermute (the
                        halo/j-ring primitive, measurable alone)
- `jacobi*_dist(residual=True)` — the stencil loop's periodic
                        residual MPI_Allreduce (SURVEY.md §3(b)):
                        global ||x_{k+1} - x_k||² via psum

On the dev box these are logic-tested on 8 fake CPU devices
(tests/test_distributed.py spawns subprocesses with the right env);
on a real v5e pod the same code rides ICI.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# drift-prone names resolve in compat (docs/DISTRIBUTED.md): jax's
# shard_map moved twice and renamed its check kwarg across the 0.4->0.9
# span, and lax.pcast only exists on the new surface
from tpukernels.compat import pcast, shard_map
from tpukernels.obs import trace
from tpukernels.utils import cdiv

# Every public entry builds its shard_map program through an
# lru_cache'd builder keyed on the static configuration: jax.jit
# caches by function identity, so constructing a fresh closure per
# call would retrace on every invocation — the C driver's timing loop
# (capi.py) calls these once per timed rep and must hit the jit cache.


def _ring_perm(n: int, shift: int = 1):
    """(src, dst) pairs rotating data `shift` ranks forward."""
    return [(i, (i + shift) % n) for i in range(n)]


def _dist_depth() -> int:
    """TPK_DIST_DEPTH: comm/compute pipeline depth for the distributed
    kernels (docs/DISTRIBUTED.md §overlap). 1 = the synchronous path of
    record; >= 2 issues that many hops' `ppermute`s before the compute
    that consumes them, so the shift for hop k+1 is in flight while hop
    k's sweep/force block runs. Results are bitwise identical at every
    depth (same accumulation order, same fp ops — only the *issue*
    order of independent comm moves). Fail-loud parse per the TPK_*
    contract: a malformed or < 1 value must never silently degrade a
    measured run to the sync path."""
    raw = os.environ.get("TPK_DIST_DEPTH")
    if raw is None:
        return 1
    try:
        depth = int(raw)
    except ValueError:
        depth = 0
    if depth < 1:
        raise ValueError(
            f"TPK_DIST_DEPTH={raw!r}: expected an int >= 1"
        )
    return depth


# ------------------------------------------------------------ allreduce

@functools.lru_cache(maxsize=None)
def _allreduce_build(mesh: Mesh, axis: str):
    return jax.jit(
        shard_map(
            lambda xl: jax.lax.psum(xl, axis),
            mesh=mesh,
            in_specs=P(axis, None),
            out_specs=P(axis, None),
        )
    )


@functools.lru_cache(maxsize=None)
def _allreduce2d_build(mesh: Mesh, axes, scatter: bool):
    ax0, ax1 = axes

    def local_fn(xl):  # (rows/(r*c), S) local slab
        if scatter:
            # two-phase decomposition over the (r, c) torus: reduce-
            # scatter along x leaves each x-rank a distinct 1/r of the
            # columns (summed over its y-column group), the small psum
            # along y completes the reduction on 1/r of the bytes, and
            # the allgather along x restores full rows — 2(r-1)/r + ...
            # of the flat ring's per-link volume split across both
            # mesh dimensions' links.
            s = jax.lax.psum_scatter(
                xl, ax0, scatter_dimension=1, tiled=True
            )
            s = jax.lax.psum(s, ax1)
            return jax.lax.all_gather(s, ax0, axis=1, tiled=True)
        # columns not divisible by r: hierarchical two-phase reduce
        # (sum along x, then along y) — same wire pattern class,
        # no scatter tiling constraint
        return jax.lax.psum(jax.lax.psum(xl, ax0), ax1)

    spec = P((ax0, ax1), None)
    return jax.jit(
        shard_map(local_fn, mesh=mesh, in_specs=spec, out_specs=spec)
    )


def allreduce_sum(x, mesh: Mesh, axis: str = "x"):
    """MPI_Allreduce(SUM): x is (P, S) with row r = rank r's
    contribution; every row of the result is the elementwise sum.

    On a 2-D mesh (make_mesh((r, c))) the reduction decomposes into
    the two-phase reduce-scatter-along-x / reduce-along-y /
    allgather-along-x program (`axis` is ignored; both mesh axes
    participate); rows must divide r*c."""
    axes = mesh.axis_names
    if len(axes) == 2:
        r, c = mesh.shape[axes[0]], mesh.shape[axes[1]]
        if x.shape[0] % (r * c):
            raise ValueError(
                f"rows={x.shape[0]} must divide across {r}x{c} ranks"
            )
        scatter = x.shape[-1] % r == 0
        with trace.span("collective/allreduce", n=r * c,
                        mesh_shape=f"{r}x{c}"):
            return _allreduce2d_build(mesh, tuple(axes), scatter)(x)
    with trace.span("collective/allreduce", n=mesh.shape[axis]):
        return _allreduce_build(mesh, axis)(x)


@functools.lru_cache(maxsize=None)
def _bcast_build(mesh: Mesh, axis: str, root: int):
    def local_fn(xl):  # (1, S) local row
        rank = jax.lax.axis_index(axis)
        contrib = jnp.where(rank == root, xl, jnp.zeros_like(xl))
        return jax.lax.psum(contrib, axis)

    return jax.jit(
        shard_map(
            local_fn,
            mesh=mesh,
            in_specs=P(axis, None),
            out_specs=P(axis, None),
        )
    )


@functools.lru_cache(maxsize=None)
def _ring_shift_build(mesh: Mesh, axis: str, shift: int):
    perm = _ring_perm(mesh.shape[axis], shift)

    def local_fn(xl):  # (1, S) local row
        return jax.lax.ppermute(xl, axis, perm)

    return jax.jit(
        shard_map(
            local_fn,
            mesh=mesh,
            in_specs=P(axis, None),
            out_specs=P(axis, None),
        )
    )


def ring_shift(x, mesh: Mesh, axis: str = "x", shift: int = 1):
    """Neighbor exchange (the MPI_Sendrecv halo pattern in isolation):
    x is (P, S) with row r = rank r's send buffer; row r of the result
    is what rank r received, i.e. row (r - shift) mod P. This is the
    primitive under the stencil halo exchange and the N-body j-ring —
    exposed bare so its link bandwidth is measurable (busbw.py)."""
    with trace.span("collective/ring_shift", n=mesh.shape[axis]):
        return _ring_shift_build(mesh, axis, int(shift))(x)


def bcast(x, mesh: Mesh, axis: str = "x", root: int = 0):
    """MPI_Bcast (SURVEY.md §5 "likely ... MPI_Bcast params"): x is
    (P, S) with row r = rank r's buffer; every row of the result is
    row `root`'s data. Expressed as a masked psum — only root
    contributes — which XLA lowers to the same one-to-all movement."""
    nranks = mesh.shape[axis]
    if not 0 <= root < nranks:
        raise ValueError(f"root={root} out of range for {nranks} ranks")
    with trace.span("collective/bcast", n=nranks):
        return _bcast_build(mesh, axis, int(root))(x)


# ------------------------------------------------------------- stencil

def _edge_shift(p, ax: int, toward_end: bool):
    """Neighbor values along `ax` with edge replication: index i gets
    i-1 (toward_end=True, the 'previous' neighbor) or i+1."""
    n = p.shape[ax]
    sl = jax.lax.slice_in_dim
    if toward_end:
        return jnp.concatenate(
            [sl(p, 0, 1, axis=ax), sl(p, 0, n - 1, axis=ax)], axis=ax
        )
    return jnp.concatenate(
        [sl(p, 1, n, axis=ax), sl(p, n - 1, n, axis=ax)], axis=ax
    )


def _jacobi_dist(x, iters: int, mesh: Mesh, axis: str, k: int,
                 residual: bool = False):
    """Dimension-generic sharded Jacobi: dim 0 sharded across the mesh
    axis, halo exchange via ppermute, mean-of-face-neighbors update,
    Dirichlet boundary.

    Comm-avoiding: each round ppermutes a k-deep halo band and runs k
    fused local sweeps (the multi-chip mirror of the single-chip
    temporal blocking in kernels/stencil.py), trading k x halo bytes
    for 1/k as many ICI message rounds. Halo slices go stale one-per-
    sweep inward — k-deep halos bound that, so owned slices stay exact
    and the result is bitwise independent of k. Ring-wrapped halos at
    the global ends carry wrong values, but those sit outside the
    Dirichlet interior mask and are never read by an unmasked cell."""
    nranks = mesh.shape[axis]
    if x.shape[0] % nranks:
        raise ValueError(
            f"dim0={x.shape[0]} must divide across {nranks} ranks"
        )
    # clamp BEFORE the cache lookup so raw k values with the same
    # effective depth share one compiled program
    k = max(1, min(int(k), x.shape[0] // nranks))
    # Pipeline depth saturates at 2 here: a round's outgoing halos are
    # its own first/last k rows, so at most ONE round's ppermutes can
    # be in flight ahead of the sweep that needs them. The 2-deep path
    # sweeps the k-wide edge bands first (each needs only 2k owned rows
    # plus the in-hand halo), ships them, then does the full sweep —
    # which requires 2k <= l0 or the bands would wrap; smaller blocks
    # fall back to the sync path. Clamped before the cache lookup for
    # the same sharing reason as k.
    depth = min(_dist_depth(), 2)
    if depth > 1 and 2 * k > x.shape[0] // nranks:
        depth = 1
    with trace.span(f"collective/jacobi{len(x.shape)}d", n=nranks, k=k,
                    depth=depth):
        return _jacobi_dist_build(
            x.shape, int(iters), mesh, axis, k, bool(residual), depth
        )(x)


@functools.lru_cache(maxsize=None)
def _jacobi_dist_build(dims, iters: int, mesh: Mesh, axis: str, k: int,
                       residual: bool = False, depth: int = 1):
    nranks = mesh.shape[axis]
    nd = len(dims)
    l0 = dims[0] // nranks
    scale = 1.0 / (2 * nd)

    up_perm = _ring_perm(nranks, 1)  # my last slices -> (r+1)'s top halo
    down_perm = _ring_perm(nranks, -1)  # my first -> (r-1)'s bottom

    def local_fn(xl):  # (l0, *dims[1:]) local block
        rank = jax.lax.axis_index(axis)
        base = rank * l0  # global row index of the local block's row 0

        def sweep_band(band, kk, start):
            """kk masked sweeps over a band whose row 0 sits at global
            dim-0 index `start` (traced). Band-edge replication (from
            _edge_shift) contaminates one row inward per sweep; callers
            slice out the rows that stayed exact."""
            shape = band.shape
            iota = lambda a: jax.lax.broadcasted_iota(  # noqa: E731
                jnp.int32, shape, a
            )
            g0 = start + iota(0)
            interior = (g0 > 0) & (g0 < dims[0] - 1)
            for a in range(1, nd):
                ga = iota(a)
                interior &= (ga > 0) & (ga < dims[a] - 1)
            p = band
            for _ in range(kk):
                out = scale * sum(
                    _edge_shift(p, a, fwd)
                    for a in range(nd)
                    for fwd in (True, False)
                )
                p = jnp.where(interior, out, p)
            return p

        def rounds(v, kk):
            top = jax.lax.ppermute(v[-kk:], axis, up_perm)
            bot = jax.lax.ppermute(v[:kk], axis, down_perm)
            p = sweep_band(
                jnp.concatenate([top, v, bot], axis=0), kk, base - kk
            )
            return p[kk : kk + l0]

        passes, rem = divmod(iters, k)
        if depth == 1:
            v = jax.lax.fori_loop(
                0, passes, lambda _, v: rounds(v, k), xl
            )
            if rem:
                v = rounds(v, rem)
        else:
            # Double-buffered rounds: each round receives its k-deep
            # halos from the PREVIOUS round's tail ppermutes, sweeps
            # just the k-wide edge bands it must export (3k-row bands:
            # after k sweeps the middle k rows are exact, matching the
            # full sweep bitwise), ships them for the NEXT round, and
            # only then runs the full local sweep — so the next hop's
            # halo bytes ride the wire under this round's bulk compute.
            def round_db(_, carry):
                v, top, bot = carry
                head = sweep_band(
                    jnp.concatenate([top, v[: 2 * k]], axis=0),
                    k, base - k,
                )[k : 2 * k]  # == v_new[:k], bitwise
                tail = sweep_band(
                    jnp.concatenate([v[-2 * k :], bot], axis=0),
                    k, base + l0 - 2 * k,
                )[k : 2 * k]  # == v_new[-k:], bitwise
                # next round's halos leave before the bulk sweep starts
                nt = jax.lax.ppermute(tail, axis, up_perm)
                nb = jax.lax.ppermute(head, axis, down_perm)
                p = sweep_band(
                    jnp.concatenate([top, v, bot], axis=0), k, base - k
                )
                return p[k : k + l0], nt, nb

            top0 = jax.lax.ppermute(xl[-k:], axis, up_perm)
            bot0 = jax.lax.ppermute(xl[:k], axis, down_perm)
            v, top, bot = jax.lax.fori_loop(
                0, passes, round_db, (xl, top0, bot0)
            )
            if rem:
                # the k-deep halos from the last ppermute pair are in
                # hand; a rem-round needs only their innermost rem rows
                p = sweep_band(
                    jnp.concatenate(
                        [top[k - rem :], v, bot[:rem]], axis=0
                    ),
                    rem, base - rem,
                )
                v = p[rem : rem + l0]
        if residual:
            # the reference's periodic residual MPI_Allreduce
            # (SURVEY.md §3(b)): the Jacobi convergence monitor
            # ||x_{k+1} - x_k||² measured by one extra 1-deep-halo
            # sweep whose result is only used for the delta — the
            # returned grid is untouched, and psum over owned slices
            # gives the exact global norm.
            d = rounds(v, 1) - v
            return v, jax.lax.psum(jnp.sum(d * d), axis)
        return v

    spec = P(axis, *([None] * (nd - 1)))
    out_spec = (spec, P()) if residual else spec
    return jax.jit(
        shard_map(local_fn, mesh=mesh, in_specs=spec, out_specs=out_spec)
    )


def jacobi2d_dist(x, iters: int, mesh: Mesh, axis: str = "x", k: int = 4,
                  residual: bool = False):
    """Row-sharded Jacobi 5-point (SURVEY.md §3(b)): x (H, W) float32,
    H % P == 0. See _jacobi_dist for the comm-avoiding halo scheme.
    residual=True also returns the global ||x_{iters+1} - x_iters||²
    (the loop's residual MPI_Allreduce) as a second output."""
    return _jacobi_dist(x, iters, mesh, axis, k, residual)


def jacobi3d_dist(x, iters: int, mesh: Mesh, axis: str = "x", k: int = 4,
                  residual: bool = False):
    """z-sharded Jacobi 7-point: x (D, H, W) float32, D % P == 0.
    See _jacobi_dist for the comm-avoiding halo scheme; residual as in
    jacobi2d_dist."""
    return _jacobi_dist(x, iters, mesh, axis, k, residual)


# ---------------------------------------------------- scan + histogram

def scan_dist(x, mesh: Mesh, axis: str = "x", exclusive: bool = False):
    """Distributed prefix sum (SURVEY.md C7 under C9): x (N,) int32 or
    float32, N % P == 0, elements block-sharded across ranks. The MPI
    two-level decomposition — each rank scans its local block, ranks
    exchange block totals (MPI_Exscan / Allgather), and the exclusive
    prefix of totals offsets every local result. int32 stays exact:
    XLA's int32 adds wrap mod 2^32 like the serial-C oracle's."""
    n = x.shape[0]
    nranks = mesh.shape[axis]
    if n % nranks:
        raise ValueError(f"N={n} must divide across {nranks} ranks")
    with trace.span("collective/scan", n=nranks):
        return _scan_dist_build(mesh, axis, bool(exclusive))(x)


@functools.lru_cache(maxsize=None)
def _scan_dist_build(mesh: Mesh, axis: str, exclusive: bool):
    nranks = mesh.shape[axis]

    def local_fn(xl):  # (N/P,) local block
        incl = jnp.cumsum(xl)
        totals = jax.lax.all_gather(incl[-1], axis)  # (P,) rank totals
        rank = jax.lax.axis_index(axis)
        offset = jnp.sum(
            jnp.where(jnp.arange(nranks) < rank, totals, 0)
        ).astype(xl.dtype)
        # the exclusive variant shifts *locally*: rank r's element 0 is
        # exactly the sum of all previous ranks' elements (= offset).
        # Derived by shifting, not subtracting, so float partial sums
        # are never re-rounded (mirrors kernels/scan.py exclusive_scan).
        if exclusive:
            incl = jnp.concatenate(
                [jnp.zeros((1,), incl.dtype), incl[:-1]]
            )
        return incl + offset

    return jax.jit(
        shard_map(
            local_fn, mesh=mesh, in_specs=P(axis), out_specs=P(axis)
        )
    )


def histogram_dist(x, nbins: int, mesh: Mesh, axis: str = "x"):
    """Distributed histogram (SURVEY.md §5: "MPI_Allreduce for ...
    histogram merge"): x (N,) int32 values, N % P == 0, elements
    block-sharded; each rank privatizes its own bin counts (the OpenMP
    per-thread-bins pattern, rank-level) and one psum merges them.
    Returns replicated (nbins,) int32 counts; out-of-range values count
    nothing (same contract as kernels/histogram.py)."""
    n = x.shape[0]
    nranks = mesh.shape[axis]
    if n % nranks:
        raise ValueError(f"N={n} must divide across {nranks} ranks")
    with trace.span("collective/histogram", n=nranks):
        return _hist_dist_build(int(nbins), mesh, axis)(x)


@functools.lru_cache(maxsize=None)
def _hist_dist_build(nbins: int, mesh: Mesh, axis: str):
    chunk = 32768  # bound the (chunk, nbins) one-hot working set

    def local_fn(xl):  # (N/P,) local block of int32 values
        n = xl.shape[0]
        c = min(chunk, n)
        nchunks = cdiv(n, c)
        if n % c:
            # -1 is out of range for every bin: counts nothing
            xl = jnp.pad(xl, (0, nchunks * c - n), constant_values=-1)
        ids = jnp.arange(nbins, dtype=xl.dtype)

        def body(i, acc):
            v = jax.lax.dynamic_slice_in_dim(xl, i * c, c)
            return acc + jnp.sum(
                (v[:, None] == ids[None, :]).astype(jnp.int32), axis=0
            )

        # the carry must be typed as device-varying over the mesh axis
        # (the body mixes in xl, which is) or the scan carry types
        # clash; on pre-varying-type jax the cast is an identity
        init = pcast(
            jnp.zeros((nbins,), jnp.int32), (axis,), to="varying"
        )
        counts = jax.lax.fori_loop(0, nchunks, body, init)
        return jax.lax.psum(counts, axis)

    return jax.jit(
        shard_map(
            local_fn, mesh=mesh, in_specs=P(axis), out_specs=P()
        )
    )


# -------------------------------------------------------------- nbody

def _pairwise_accel(pxi, pyi, pzi, jx, jy, jz, jm, eps2, chunk=2048):
    """Accelerations on i-bodies from j-bodies, chunked over j."""
    nj = jx.shape[0]
    nchunks = cdiv(nj, chunk)
    if nj % chunk:
        pad = nchunks * chunk - nj
        jx = jnp.pad(jx, (0, pad))
        jy = jnp.pad(jy, (0, pad))
        jz = jnp.pad(jz, (0, pad))
        jm = jnp.pad(jm, (0, pad))  # zero mass: no contribution

    def body(c, acc):
        ax, ay, az = acc
        sl = jax.lax.dynamic_slice_in_dim
        cx = sl(jx, c * chunk, chunk)
        cy = sl(jy, c * chunk, chunk)
        cz = sl(jz, c * chunk, chunk)
        cm = sl(jm, c * chunk, chunk)
        dx = cx[None, :] - pxi[:, None]
        dy = cy[None, :] - pyi[:, None]
        dz = cz[None, :] - pzi[:, None]
        r2 = dx * dx + dy * dy + dz * dz + eps2
        inv_r = jax.lax.rsqrt(r2)
        w = cm[None, :] * inv_r * inv_r * inv_r
        return (
            ax + jnp.sum(w * dx, axis=1),
            ay + jnp.sum(w * dy, axis=1),
            az + jnp.sum(w * dz, axis=1),
        )

    zero = jnp.zeros_like(pxi)
    return jax.lax.fori_loop(0, nchunks, body, (zero, zero, zero))


def _nbody_check_divisible(state, mesh: Mesh, axis: str):
    n = state[0].shape[0]
    nranks = mesh.shape[axis]
    if n % nranks:
        raise ValueError(
            f"N={n} bodies must divide across {nranks} ranks"
        )


def nbody_dist_psum(state, steps: int, mesh: Mesh, axis: str = "x",
                    dt=1e-3, eps=1e-2):
    """North-star formulation: bodies partitioned as force *sources*
    (j sharded), positions replicated; each rank computes partial
    forces on all bodies from its j-partition, then `psum` combines
    (SURVEY.md C8/§3(c)). state = (px,py,pz,vx,vy,vz,m), all (N,)."""
    _nbody_check_divisible(state, mesh, axis)
    with trace.span("collective/nbody_psum", n=mesh.shape[axis]):
        return _nbody_psum_build(
            int(steps), mesh, axis, float(dt), float(eps)
        )(*state)


@functools.lru_cache(maxsize=None)
def _nbody_psum_build(steps: int, mesh: Mesh, axis: str,
                      dt: float, eps: float):
    dt = jnp.float32(dt)
    eps2 = jnp.float32(eps * eps)

    def local_fn(px, py, pz, vx, vy, vz, ml):
        # px..vz replicated (N,); ml local shard (N/P,)
        nranks = jax.lax.psum(1, axis)
        n = px.shape[0]
        lsz = n // nranks
        rank = jax.lax.axis_index(axis)

        def step(_, s):
            px, py, pz, vx, vy, vz = s
            j0 = rank * lsz
            sl = jax.lax.dynamic_slice_in_dim
            jx, jy, jz = (sl(a, j0, lsz) for a in (px, py, pz))
            ax, ay, az = _pairwise_accel(px, py, pz, jx, jy, jz, ml, eps2)
            ax = jax.lax.psum(ax, axis)
            ay = jax.lax.psum(ay, axis)
            az = jax.lax.psum(az, axis)
            vx = vx + ax * dt
            vy = vy + ay * dt
            vz = vz + az * dt
            return (px + vx * dt, py + vy * dt, pz + vz * dt, vx, vy, vz)

        return jax.lax.fori_loop(0, steps, step, (px, py, pz, vx, vy, vz))

    rep = P()
    shard = P(axis)
    return jax.jit(
        shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(rep, rep, rep, rep, rep, rep, shard),
            out_specs=(rep, rep, rep, rep, rep, rep),
            check_vma=False,  # psum of replicated inputs is intentional
        )
    )


def nbody_dist_ring(state, steps: int, mesh: Mesh, axis: str = "x",
                    dt=1e-3, eps=1e-2):
    """Ring formulation: i-bodies sharded, j-blocks rotate around the
    ring via ppermute (memory O(N/P) per chip) — the reference's
    Sendrecv body-rotation pipeline (SURVEY.md §2 C8, §5 'ring
    communication'). state arrays (N,), N % P == 0."""
    _nbody_check_divisible(state, mesh, axis)
    # TPK_NBODY_RING_SKIP_LAST=1 (docs/NEXT.md item 5): the plain ring
    # rotates the j-blocks on its LAST pass too — they arrive back at
    # their origin rank and are never read, 1/P of the ring's total
    # comm volume. The knob peels that pass out of the loop so the
    # final ppermute never exists in the compiled program. Output is
    # bitwise identical (tests/test_distributed.py); default stays the
    # uniform-loop formulation until a pod A/B shows XLA wasn't
    # already overlapping the dead hop.
    skip_last = os.environ.get("TPK_NBODY_RING_SKIP_LAST") == "1"
    # TPK_NBODY_RING_BIDIR=1: ICI links are full-duplex, but the plain
    # ring only ever pushes bytes one way around — half the available
    # link bandwidth sits idle. The bidirectional variant splits each
    # rank's j-block into two halves that rotate in OPPOSITE
    # directions, so every pass moves half the bytes over each link
    # direction concurrently: same total volume, ~half the per-pass
    # comm time when bandwidth-bound. Accumulation order differs from
    # the unidirectional ring (tolerance-tested vs the single-device
    # oracle, not bitwise); composes with SKIP_LAST (the peeled final
    # pass drops BOTH directions' dead rotations). Default stays off
    # until the pod A/B (docs/NEXT.md) measures it.
    bidir = os.environ.get("TPK_NBODY_RING_BIDIR") == "1"
    # TPK_DIST_DEPTH >= 2: pipeline the ring. The prologue pre-rotates
    # depth-1 j-block groups, and each loop pass issues the NEXT hop's
    # ppermute before computing forces from the oldest in-hand group —
    # the shift rides the wire under the force block. Bitwise identical
    # at every depth (same accel order 0..P-1, same accumulation);
    # depth > P buys nothing, so clamp to the ring length.
    depth = min(_dist_depth(), mesh.shape[axis])
    with trace.span("collective/nbody_ring", n=mesh.shape[axis],
                    depth=depth):
        return _nbody_ring_build(
            int(steps), mesh, axis, float(dt), float(eps), skip_last,
            bidir, depth
        )(*state)


@functools.lru_cache(maxsize=None)
def _nbody_ring_build(steps: int, mesh: Mesh, axis: str,
                      dt: float, eps: float, skip_last: bool = False,
                      bidir: bool = False, depth: int = 1):
    dt = jnp.float32(dt)
    eps2 = jnp.float32(eps * eps)
    nranks = mesh.shape[axis]
    fwd = _ring_perm(nranks, 1)
    bwd = _ring_perm(nranks, -1)

    def local_fn(pxl, pyl, pzl, vxl, vyl, vzl, ml):
        lsz = pxl.shape[0]
        h = lsz // 2  # bidir split point (static); h may be 0 at lsz=1

        def step(_, s):
            pxl, pyl, pzl, vxl, vyl, vzl = s

            def accel_pair(carry_blocks):
                """Accel on the local i-bodies from the currently-held
                j-data: one block (uni) or fwd+bwd halves concatenated
                (bidir — one fused kernel, same flops as one block)."""
                if not bidir:
                    jx, jy, jz, jm = carry_blocks
                else:
                    jx, jy, jz, jm = (
                        jnp.concatenate([a, b])
                        for a, b in zip(carry_blocks[:4], carry_blocks[4:])
                    )
                return _pairwise_accel(pxl, pyl, pzl, jx, jy, jz, jm, eps2)

            def rotate(carry_blocks):
                if not bidir:
                    return tuple(
                        jax.lax.ppermute(a, axis, fwd) for a in carry_blocks
                    )
                return tuple(
                    jax.lax.ppermute(a, axis, fwd) for a in carry_blocks[:4]
                ) + tuple(
                    jax.lax.ppermute(b, axis, bwd) for b in carry_blocks[4:]
                )

            def ring(k, carry):
                ax, ay, az = carry[:3]
                blocks = carry[3:]
                dax, day, daz = accel_pair(blocks)
                blocks = rotate(blocks)
                return (ax + dax, ay + day, az + daz) + blocks

            zero = jnp.zeros_like(pxl)
            if not bidir:
                init_blocks = (pxl, pyl, pzl, ml)
            else:
                init_blocks = tuple(a[:h] for a in (pxl, pyl, pzl, ml)) + \
                    tuple(a[h:] for a in (pxl, pyl, pzl, ml))
            if depth == 1:
                nloops = nranks - 1 if skip_last else nranks
                out = jax.lax.fori_loop(
                    0, nloops, ring, (zero, zero, zero) + init_blocks
                )
                ax, ay, az = out[:3]
                if skip_last:
                    # the peeled final pass: accumulate the last
                    # j-data's contribution without rotating it onward.
                    # Same accel op sequence as the uniform loop ->
                    # bitwise-identical trajectories (per formulation).
                    dax, day, daz = accel_pair(out[3:])
                    ax, ay, az = ax + dax, ay + day, az + daz
            else:
                # Pipelined ring: hold a `depth`-entry queue of j-block
                # groups (queue[i] = hop base+i's data). Each pass
                # issues the rotate producing the NEXT group before the
                # force block on the oldest, then shifts the queue. The
                # epilogue drains the queue without rotating — total
                # rotations = P-1, so the dead last-hop shift is gone
                # and SKIP_LAST is subsumed at depth >= 2. Forces still
                # accumulate in hop order 0..P-1: bitwise identical.
                g = len(init_blocks)
                queue = [init_blocks]
                for _d in range(depth - 1):
                    queue.append(rotate(queue[-1]))

                def ring_deep(k, carry):
                    ax, ay, az = carry[:3]
                    qs = carry[3:]
                    q = [
                        qs[i * g : (i + 1) * g] for i in range(depth)
                    ]
                    # next hop's shift leaves before this hop's forces
                    newest = rotate(q[-1])
                    dax, day, daz = accel_pair(q[0])
                    flat = tuple(
                        b for grp in q[1:] for b in grp
                    ) + newest
                    return (ax + dax, ay + day, az + daz) + flat

                flat0 = tuple(b for grp in queue for b in grp)
                out = jax.lax.fori_loop(
                    0, nranks - depth, ring_deep,
                    (zero, zero, zero) + flat0
                )
                ax, ay, az = out[:3]
                qs = out[3:]
                for i in range(depth):
                    dax, day, daz = accel_pair(
                        qs[i * g : (i + 1) * g]
                    )
                    ax, ay, az = ax + dax, ay + day, az + daz
            vxl = vxl + ax * dt
            vyl = vyl + ay * dt
            vzl = vzl + az * dt
            return (
                pxl + vxl * dt, pyl + vyl * dt, pzl + vzl * dt,
                vxl, vyl, vzl,
            )

        return jax.lax.fori_loop(
            0, steps, step, (pxl, pyl, pzl, vxl, vyl, vzl)
        )

    shard = P(axis)
    return jax.jit(
        shard_map(
            local_fn,
            mesh=mesh,
            in_specs=(shard,) * 7,
            out_specs=(shard,) * 6,
        )
    )
