"""Collective bandwidth microbenchmark (SURVEY.md C9, §3(d)).

The reference's measured metric: MPI_Allreduce bus bandwidth swept
over message sizes at 8→64 ranks. Bus bandwidth uses the standard
ring-allreduce accounting:

    bus_bw = 2 * (n-1)/n * bytes / t

Here the allreduce is `jax.lax.psum` under `shard_map` over the ICI
ring; run on a v5e pod slice this measures achieved ICI bandwidth.
On fewer chips it still runs (n=1 is a degenerate no-comm copy) so
the C driver's acceptance check works anywhere.

op="ppermute" instead sweeps the bare neighbor exchange (the
MPI_Sendrecv pattern under the stencil halos and the N-body j-ring):
every rank sends its S-byte buffer one hop, so the reported figure is
per-link point-to-point bandwidth, bytes / t — the number that
predicts halo-exchange cost directly.

Observability (docs/OBSERVABILITY.md §scaling): every sweep point is
journaled as a ``busbw_point`` event, and the CLI stamps a
``device_inventory`` event then persists the whole sweep as a
structured ``docs/logs/scaling_busbw_*.json`` artifact (redirect with
``TPK_SCALING_DIR``) that ``tools/obs_report.py`` trend-checks —
fake-device (non-TPU) artifacts are flagged ``fake`` and never gate.
Stdout stays byte-identical to the pre-artifact CLI (the artifact
path prints to stderr): the C driver greps these lines.

CLI:  python -m tpukernels.parallel.busbw [--min=1KB] [--max=64MB]
          [--op=allreduce|ppermute]
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from tpukernels.obs import metrics as obs_metrics
from tpukernels.obs import scaling
from tpukernels.parallel.collectives import allreduce_sum, ring_shift
from tpukernels.parallel.mesh import (
    host_to_global,
    make_mesh,
    row_sharding,
)
from tpukernels.resilience import journal


def bus_bandwidth(seconds: float, nbytes: int, nranks: int) -> float:
    """GB/s by ring-allreduce algorithm-bandwidth accounting."""
    if nranks <= 1:
        return nbytes / seconds / 1e9
    return 2.0 * (nranks - 1) / nranks * nbytes / seconds / 1e9


def timed_program(op: str, mesh):
    """The exact jitted program the sweep times: collective + probe.

    The probe must (a) be fetchable on every host — so it reduces to a
    fully-replicated scalar — and (b) keep the WHOLE collective output
    live. A partial probe (one column was the old design) leaves the
    rest dead inside the jit, and XLA is then free to narrow the
    all-reduce to the live slice, silently turning the bandwidth sweep
    into a latency benchmark. The full-array sum pins the operand
    shape (tests/test_distributed.py lowers this very function and
    asserts it in the optimized HLO); the VPU reduction it adds reads
    S bytes at HBM bandwidth, negligible vs moving S bytes over ICI."""
    coll = allreduce_sum if op == "allreduce" else ring_shift
    return jax.jit(lambda v: jnp.sum(coll(v, mesh)))


def sweep(min_bytes: int = 1 << 10, max_bytes: int = 64 << 20,
          reps: int = 10, mesh=None, verbose: bool = True,
          op: str = "allreduce"):
    """Time a collective over message sizes; returns
    [(bytes, seconds, bw_GBps)]. op: "allreduce" (bus-bw accounting)
    or "ppermute" (per-link point-to-point bandwidth)."""
    if op not in ("allreduce", "ppermute"):
        raise ValueError(f"op={op!r}: expected allreduce or ppermute")
    if mesh is None:
        mesh = make_mesh()  # joins the multi-host job when configured
    axes = mesh.axis_names
    mesh_shape = tuple(int(mesh.shape[a]) for a in axes) \
        if len(axes) == 2 else None
    if mesh_shape is not None and op != "allreduce":
        raise ValueError(
            f"op={op!r} has no 2-D decomposition; only allreduce "
            "sweeps 2-D meshes"
        )
    nranks = 1
    for a in axes:
        nranks *= int(mesh.shape[a])
    if mesh_shape is None:
        sharding = row_sharding(mesh)
    else:
        from jax.sharding import NamedSharding, PartitionSpec

        sharding = NamedSharding(mesh, PartitionSpec(tuple(axes), None))
    fake = scaling.inventory(probe=True).get("fake", True)
    results = []
    size = min_bytes
    while size <= max_bytes:
        elems = max(size // 4, 1)
        # multi-host safe: see mesh.host_to_global (a host-local array
        # can't feed a mesh spanning other hosts' devices)
        x = host_to_global(
            np.ones((nranks, elems), np.float32), sharding
        )

        fn = timed_program(op, mesh)  # see timed_program: un-DCE-able
        # warm-up (compile) then per-call timing with a 4-byte
        # materialization to force real completion (device-side
        # block_until_ready is unreliable through the axon tunnel).
        # The materialization blocks on ONE addressable shard; the
        # barrier after it waits for every local device's execution —
        # on multi-device-per-process CPU (gloo) a straggler device's
        # collective ops would otherwise interleave with the NEXT
        # program's and desync the transport pairs (tcp/pair.cc
        # size-mismatch aborts). Outside the timed window by design;
        # the warm-up keeps the materialization too, since through the
        # axon tunnel block_until_ready alone can return early and a
        # straggling compile would then bleed into the first timed rep.
        w = fn(x)
        np.asarray(w)
        jax.block_until_ready(w)
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            r = fn(x)
            np.asarray(r)
            t1 = time.perf_counter()
            jax.block_until_ready(r)
            best = min(best, t1 - t0)
        if op == "allreduce":
            bw = bus_bandwidth(best, size, nranks)
        else:
            bw = size / best / 1e9  # per-link point-to-point
        results.append((size, best, bw))
        # structured twin of the stdout line (docs/OBSERVABILITY.md
        # §scaling): no I/O when journaling is off, nothing on stdout
        # either way — the clean-path byte-identity proof covers this
        obs_metrics.inc("scaling.busbw_points")
        # mesh_shape rides the event only on 2-D sweeps so the 1-D
        # ring's journal payload stays byte-shaped
        extra = {"mesh_shape": list(mesh_shape)} if mesh_shape else {}
        journal.emit(
            "busbw_point", op=op, n_devices=nranks,
            size_bytes=size, seconds=round(best, 6),
            gb_s=round(bw, 4), fake=bool(fake), **extra,
        )
        if verbose:
            line = (
                f"{op} n={nranks} size={size:>10d}B "
                f"time={best * 1e3:9.3f}ms bw={bw:8.3f} GB/s"
            )
            if mesh_shape:
                # appended, never inserted: the 1-D line prefix is the
                # byte-stable surface the C driver greps
                line += f" mesh={mesh_shape[0]}x{mesh_shape[1]}"
            print(line)
        size *= 4
    return results


def sweep_from_env(mesh=None):
    """sweep() configured by the TPK_BUSBW_* env knobs (SURVEY.md §5
    config system: the C driver grows zero new flags, so
    `allreduce_bench --device=tpu` under TPK_BUSBW_SWEEP=1 tunes the
    table through TPK_BUSBW_MIN/MAX (sizes, e.g. 1K/64M),
    TPK_BUSBW_REPS and TPK_BUSBW_OP (allreduce|ppermute))."""
    import os

    kw = {}
    if "TPK_BUSBW_MIN" in os.environ:
        kw["min_bytes"] = _parse_size(os.environ["TPK_BUSBW_MIN"])
    if "TPK_BUSBW_MAX" in os.environ:
        kw["max_bytes"] = _parse_size(os.environ["TPK_BUSBW_MAX"])
    if "TPK_BUSBW_REPS" in os.environ:
        kw["reps"] = int(os.environ["TPK_BUSBW_REPS"])
    if "TPK_BUSBW_OP" in os.environ:
        kw["op"] = os.environ["TPK_BUSBW_OP"]
    return sweep(mesh=mesh, **kw)


def _parse_size(s: str) -> int:
    s = s.upper().rstrip("B")
    for suffix, mult in (("K", 1 << 10), ("M", 1 << 20), ("G", 1 << 30)):
        if s.endswith(suffix):
            return int(float(s[:-1]) * mult)
    return int(s)


if __name__ == "__main__":
    import os
    import sys

    kw = {}
    mesh_arg = None
    for a in sys.argv[1:]:
        if a.startswith("--min="):
            kw["min_bytes"] = _parse_size(a[6:])
        elif a.startswith("--max="):
            kw["max_bytes"] = _parse_size(a[6:])
        elif a.startswith("--reps="):
            kw["reps"] = int(a[7:])
        elif a.startswith("--op="):
            kw["op"] = a[5:]
        elif a.startswith("--mesh="):
            r, _, c = a[7:].partition("x")
            mesh_arg = (int(r), int(c))
    # CLI journal default (the bench.py/revalidate.py/loadgen.py
    # contract): an unattended sweep's evidence lands in the day's
    # health journal unless the operator chose otherwise
    if os.environ.get("TPK_HEALTH_JOURNAL") is None:
        os.environ["TPK_HEALTH_JOURNAL"] = journal.default_path()
    # Mesh FIRST, inventory probe second: the probe's jax.devices()
    # initializes the backend, and jax.distributed.initialize (inside
    # make_mesh -> maybe_distributed_init) must run before any backend
    # init — probing first crashes every coordinator-configured pod
    # host (and on jaxes without the guard would silently mesh only
    # this host's chips). tests/test_distributed.py
    # test_multiprocess_busbw_cli pins this ordering.
    mesh = make_mesh(mesh_arg)
    inv = scaling.emit_inventory("busbw", probe=True)
    res = sweep(mesh=mesh, **kw)
    nranks = 1
    for ax in mesh.axis_names:
        nranks *= int(mesh.shape[ax])
    artifact = scaling.write_busbw_artifact(
        res, kw.get("op", "allreduce"), nranks, inv,
        mesh_shape=mesh_arg,
    )
    # stderr, not stdout: the sweep table above is the byte-stable
    # surface the C driver (and the byte-identity proof) reads
    print(f"# busbw artifact: {artifact}", file=sys.stderr)
