"""Single definition of the persistent compilation-cache knob.

Every entry path into JAX in this repo (bench.py, tpukernels.capi for
the C shim's embedded CPython, __graft_entry__'s driver subprocesses,
tests/conftest.py) wants the same thing: compiled executables persisted
in the repo-shared ``.jax_cache`` so no timing loop or suite re-run
ever eats a 20-40 s remote recompile. One helper instead of one copy
per entry path — a drifted copy silently splits the cache.

Import-order contract: JAX captures env-derived config defaults when
``jax`` itself is imported, so this must run BEFORE the caller imports
jax — which is why this module imports nothing beyond ``os`` and why
``import tpukernels`` stays jax-free (registry loads kernels lazily).
"""

import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ensure_compilation_cache(env: dict | None = None) -> str:
    """Point JAX_COMPILATION_CACHE_DIR at the repo ``.jax_cache``
    unless the caller's environment already chose one.

    Also lowers JAX's persist-this-compile thresholds to zero (again
    setdefault — an explicit env choice wins): the stock 1 s
    min-compile-time floor exists to keep laptop caches small, but
    here EVERY skipped recompile is either a 20-40 s remote compile
    through the flapping tunnel or part of the CPU warm-start proof
    (docs/PERF.md §compile discipline), so no compile is cheap enough
    to throw away.

    env: a subprocess environment dict to update, or None for
    ``os.environ``. Returns the effective cache dir either way.
    """
    target = os.environ if env is None else env
    target.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(_REPO, ".jax_cache")
    )
    target.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    target.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    return target["JAX_COMPILATION_CACHE_DIR"]


def tuning_cache_path(env: dict | None = None) -> str:
    """Path of the persistent tuning cache (docs/TUNING.md §cache).

    Lives beside the compilation cache under the same root — one
    ``tuning.json`` per cache dir — unless ``TPK_TUNING_CACHE_DIR``
    redirects it (tests and throwaway sweeps point it at a tmp dir so
    they never touch the repo's real tuned params). Reading the env on
    every call, not at import, keeps the redirect effective for
    monkeypatched tests.
    """
    target = os.environ if env is None else env
    d = target.get("TPK_TUNING_CACHE_DIR")
    if not d:
        d = target.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            _REPO, ".jax_cache"
        )
    return os.path.join(d, "tuning.json")


def aot_manifest_path(env: dict | None = None) -> str:
    """Path of the AOT executable-cache manifest (docs/PERF.md
    §compile discipline; ``tpukernels/aot.py``).

    Lives beside the compilation cache it describes — one ``aot.json``
    per cache dir — unless ``TPK_AOT_CACHE_DIR`` redirects it (tests
    point it at a tmp dir so they never touch the repo's real warm
    cache). Same read-the-env-per-call rule as the tuning cache.
    """
    target = os.environ if env is None else env
    d = target.get("TPK_AOT_CACHE_DIR")
    if not d:
        d = target.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            _REPO, ".jax_cache"
        )
    return os.path.join(d, "aot.json")


def read_json_memoized(path: str, memo: dict) -> dict:
    """Stat-memoized tolerant JSON reader — the read-side twin of
    :func:`locked_json_update`, shared by the tuning cache, the AOT
    manifest and the integrity guard's state files so the
    memo/degradation rules cannot drift per module. ``memo`` is the
    caller's own ``{path: (stat_key, parsed)}`` dict (per-module so
    ``reset()``/test isolation stays local). Returns {} on
    absent/corrupt/non-dict — unreadable state degrades to cold
    behavior, never raises. Degrading is NOT silent: a file that
    EXISTS but does not parse (a torn write from a crash predating
    resilience/atomic.py, a half-copied checkout) is journaled once
    per process as ``artifact_rejected`` — the rebuild must be
    reconstructable from the health log, not a mystery cache miss
    (docs/RESILIENCE.md §atomic state)."""
    import json

    try:
        st = os.stat(path)
        stat_key = (st.st_mtime_ns, st.st_size)
    except OSError:
        return {}
    hit = memo.get(path)
    if hit and hit[0] == stat_key:
        return hit[1]
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError:
        return {}
    except ValueError as e:
        note_torn_artifact(path, str(e))
        data = {}
    if not isinstance(data, dict):
        data = {}
    memo[path] = (stat_key, data)
    return data


_TORN_NOTED: set = set()  # paths already journaled this process


def note_torn_artifact(path: str, reason: str):
    """Loud-rejection hook for a persisted artifact that exists but
    does not parse: stderr note + ``artifact_rejected`` journal event,
    once per path per process (a hot reader re-hitting the same torn
    file shows up once, not as log spam). Best-effort — observability
    must never take down the read it observes."""
    if path in _TORN_NOTED:
        return
    _TORN_NOTED.add(path)
    try:
        import sys

        from tpukernels.resilience import journal

        print(f"# torn artifact rejected: {path} ({reason})",
              file=sys.stderr)
        journal.emit("artifact_rejected", path=path, reason=reason)
    except Exception:
        pass


def locked_json_update(path: str, mutate, load=None) -> dict:
    """flock-serialized read-modify-write of one JSON state file —
    THE locking discipline the tuning cache established (lock file +
    fresh read under the lock + tmp-write + atomic replace), shared so
    new state files (the AOT manifest edits, the integrity guard's
    envelope/quarantine ledgers) cannot drift their own copy.

    ``mutate(data)`` edits the parsed dict in place; ``load`` lets a
    caller with a stat-memoized reader re-read under the lock (it must
    return a plain dict, {} on absent/corrupt). Returns the written
    dict. Stdlib-only, like everything in this module.
    """
    import fcntl
    import json

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(f"{path}.lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        if load is not None:
            data = load(path)
        else:
            try:
                with open(path) as f:
                    data = json.load(f)
            except OSError:
                data = {}
            except ValueError as e:
                note_torn_artifact(path, str(e))
                data = {}
        if not isinstance(data, dict):
            data = {}
        mutate(data)
        # crash-consistent write step (fsync before AND after the
        # rename): the flock above owns lost-update protection, this
        # owns torn-file protection — docs/RESILIENCE.md §atomic state
        from tpukernels.resilience import atomic

        atomic.dump_json(path, data)
    return data


def integrity_dir(env: dict | None = None) -> str:
    """State directory of the output-integrity guard
    (docs/RESILIENCE.md §output integrity;
    ``tpukernels/resilience/integrity.py``): the fingerprint-envelope
    manifest (``integrity.json``) and the quarantine ledger
    (``integrity_quarantine.json``) live here, beside the caches they
    police — unless ``TPK_INTEGRITY_DIR`` redirects (tests and chaos
    runs point it at a tmp dir so injected corruption can never
    quarantine the repo's real kernel configs). Same
    read-the-env-per-call rule as the tuning/AOT paths.
    """
    target = os.environ if env is None else env
    d = target.get("TPK_INTEGRITY_DIR")
    if not d:
        d = target.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            _REPO, ".jax_cache"
        )
    return d


def slo_path(env: dict | None = None) -> str:
    """Path of the latency-SLO verdict artifact (``slo.json``;
    docs/OBSERVABILITY.md §latency SLOs; ``tpukernels/obs/slo.py``).

    Lives beside the caches whose warm path it judges — one
    ``slo.json`` per cache dir — unless ``TPK_SLO_DIR`` redirects it
    (tests and throwaway loadgen runs point it at a tmp dir so a
    chaos-injected breach can never gate the repo's real
    ``obs_report --check``). Same read-the-env-per-call rule as the
    tuning/AOT/integrity paths.
    """
    target = os.environ if env is None else env
    d = target.get("TPK_SLO_DIR")
    if not d:
        d = target.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            _REPO, ".jax_cache"
        )
    return os.path.join(d, "slo.json")


def adapt_dir(env: dict | None = None) -> str:
    """State directory of the traffic-adaptive bucket optimizer
    (docs/SERVING.md §adaptive buckets; ``tpukernels/serve/adapt.py``):
    the candidate artifact (``adapt.json``) and the promoted bucket
    table (``buckets.json``) live here, beside the caches whose warm
    path the table shapes — unless ``TPK_ADAPT_DIR`` redirects (tests
    isolate it per suite run so a rehearsal proposal can never steer
    the operator's real serving config). Same read-the-env-per-call
    rule as the tuning/AOT/integrity/SLO/serve paths.
    """
    target = os.environ if env is None else env
    d = target.get("TPK_ADAPT_DIR")
    if not d:
        d = target.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            _REPO, ".jax_cache"
        )
    return d


def adapt_path(env: dict | None = None) -> str:
    """Path of the candidate artifact (``adapt.json``)."""
    return os.path.join(adapt_dir(env), "adapt.json")


def adapt_buckets_path(env: dict | None = None) -> str:
    """Path of the PROMOTED bucket table (``buckets.json``) — the
    stable file an operator points ``TPK_SERVE_BUCKETS`` at so a
    promotion lands behind an unchanged env value and ``undrain``
    picks it up live (docs/SERVING.md §adaptive buckets)."""
    return os.path.join(adapt_dir(env), "buckets.json")


def serve_dir(env: dict | None = None) -> str:
    """Runtime directory of the kernel-serving daemon
    (docs/SERVING.md; ``tpukernels/serve/``): the Unix-domain socket
    and the flocked pidfile live here, beside the caches whose warm
    path the daemon serves — unless ``TPK_SERVE_DIR`` redirects (tests
    isolate it per suite run so a test daemon can never collide with,
    or be stopped as, the operator's real one). Same
    read-the-env-per-call rule as the tuning/AOT/integrity/SLO paths.
    """
    target = os.environ if env is None else env
    d = target.get("TPK_SERVE_DIR")
    if not d:
        d = target.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            _REPO, ".jax_cache"
        )
    return d


def serve_socket_path(env: dict | None = None) -> str:
    """Path of the serve daemon's Unix-domain socket. An explicit
    ``TPK_SERVE_SOCKET`` wins (it is also the client-side routing
    switch — docs/SERVING.md); otherwise ``serve.sock`` under
    :func:`serve_dir`."""
    target = os.environ if env is None else env
    explicit = target.get("TPK_SERVE_SOCKET")
    if explicit:
        return explicit
    return os.path.join(serve_dir(env), "serve.sock")


def serve_pidfile_path(env: dict | None = None) -> str:
    """The daemon's flocked pidfile (the ``revalidate_lib.sh`` lock
    convention: test the flock, not just the pid)."""
    return os.path.join(serve_dir(env), "serve.pid")


def integrity_manifest_path(env: dict | None = None) -> str:
    return os.path.join(integrity_dir(env), "integrity.json")


def integrity_quarantine_path(env: dict | None = None) -> str:
    return os.path.join(integrity_dir(env), "integrity_quarantine.json")
