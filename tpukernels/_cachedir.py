"""Single definition of the persistent compilation-cache knob.

Every entry path into JAX in this repo (bench.py, tpukernels.capi for
the C shim's embedded CPython, __graft_entry__'s driver subprocesses,
tests/conftest.py) wants the same thing: compiled executables persisted
in the repo-shared ``.jax_cache`` so no timing loop or suite re-run
ever eats a 20-40 s remote recompile. One helper instead of one copy
per entry path — a drifted copy silently splits the cache.

Import-order contract: JAX captures env-derived config defaults when
``jax`` itself is imported, so this must run BEFORE the caller imports
jax — which is why this module imports nothing beyond ``os`` and why
``import tpukernels`` stays jax-free (registry loads kernels lazily).
"""

import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def ensure_compilation_cache(env: dict | None = None) -> str:
    """Point JAX_COMPILATION_CACHE_DIR at the repo ``.jax_cache``
    unless the caller's environment already chose one.

    Also lowers JAX's persist-this-compile thresholds to zero (again
    setdefault — an explicit env choice wins): the stock 1 s
    min-compile-time floor exists to keep laptop caches small, but
    here EVERY skipped recompile is either a 20-40 s remote compile
    through the flapping tunnel or part of the CPU warm-start proof
    (docs/PERF.md §compile discipline), so no compile is cheap enough
    to throw away.

    env: a subprocess environment dict to update, or None for
    ``os.environ``. Returns the effective cache dir either way.
    """
    target = os.environ if env is None else env
    target.setdefault(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(_REPO, ".jax_cache")
    )
    target.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0")
    target.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
    return target["JAX_COMPILATION_CACHE_DIR"]


def tuning_cache_path(env: dict | None = None) -> str:
    """Path of the persistent tuning cache (docs/TUNING.md §cache).

    Lives beside the compilation cache under the same root — one
    ``tuning.json`` per cache dir — unless ``TPK_TUNING_CACHE_DIR``
    redirects it (tests and throwaway sweeps point it at a tmp dir so
    they never touch the repo's real tuned params). Reading the env on
    every call, not at import, keeps the redirect effective for
    monkeypatched tests.
    """
    target = os.environ if env is None else env
    d = target.get("TPK_TUNING_CACHE_DIR")
    if not d:
        d = target.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            _REPO, ".jax_cache"
        )
    return os.path.join(d, "tuning.json")


def aot_manifest_path(env: dict | None = None) -> str:
    """Path of the AOT executable-cache manifest (docs/PERF.md
    §compile discipline; ``tpukernels/aot.py``).

    Lives beside the compilation cache it describes — one ``aot.json``
    per cache dir — unless ``TPK_AOT_CACHE_DIR`` redirects it (tests
    point it at a tmp dir so they never touch the repo's real warm
    cache). Same read-the-env-per-call rule as the tuning cache.
    """
    target = os.environ if env is None else env
    d = target.get("TPK_AOT_CACHE_DIR")
    if not d:
        d = target.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
            _REPO, ".jax_cache"
        )
    return os.path.join(d, "aot.json")
