"""Distributed-path scaling observability (docs/DISTRIBUTED.md
§observability; docs/OBSERVABILITY.md §scaling).

The paper's metric of record is allreduce bus-bandwidth scaling 8→64
chips, yet until this module the multi-chip path was the one layer the
obs stack could not see: ``parallel/busbw.py`` printed to stdout,
``tools/weak_scaling.sh`` told the operator to grep ``metric=`` lines,
and the ``MULTICHIP_r*.json`` rounds were opaque ``{rc, tail}`` blobs
no trend check ever parsed — a 30% ICI-bandwidth collapse would have
passed every gate. This module is the structured half of the fix:

- **Artifact schema + writers** — every distributed entry point
  (``python -m tpukernels.parallel.busbw``, ``tools/weak_scaling.py``)
  persists per-series JSON artifacts (``docs/logs/scaling_*.json``,
  plus driver-root ``SCALING_r*.json`` rounds when a pod driver adopts
  them) carrying op / message size / n_devices / achieved GB/s or
  wall, the device inventory that produced them, and a ``fake`` flag.
- **Device inventory** — :func:`emit_inventory` stamps a
  ``device_inventory`` journal event at the start of every
  bench/loadgen/busbw/weak-scaling/supervisor process. Processes that
  have not (and must not — the supervisor, the bench suite parent,
  loadgen ``--simulate``) initialized a jax backend stamp an
  env-derived inventory; processes already on a backend stamp the real
  ``jax.devices()`` topology.
- **Series + verdicts** — :func:`analyze_repo` loads every committed
  scaling artifact into per-series time series and judges them with
  the trend vocabulary: bus-bw per (op, size, n_devices) gets
  ``regression`` / ``impossible`` (above the analytic ICI ceiling —
  the roofline pattern) / ``no_data`` / ``ok``; weak-scaling programs
  get the NON-GATING ``below_scaling_efficiency`` verdict when
  efficiency at the largest mesh drops under ``TPK_SCALING_MIN_EFF``.
  ``fake=true`` artifacts (CPU fake devices — the
  ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` rehearsals)
  are loaded, reported, and **excluded from every gating verdict**
  (the PR-8 ``|sim`` pattern).
- **MULTICHIP legacy parsing** — the five committed
  ``MULTICHIP_r*.json`` rounds are mined for per-program dryrun walls
  (``[dryrun +T.Ts] <program>`` deltas in the tail; newer rounds carry
  a structured ``MULTICHIP-PROGRAMS:`` JSON line or a ``programs``
  key), so the existing evidence becomes day-one series data. Dryrun
  rounds run on fake CPU devices by construction and never gate.

``tools/obs_report.py`` renders the scaling section and ``--check``
gates validated (non-fake) bus-bw regressions exactly like bench
regressions. Stdlib-only at import, like the rest of
``tpukernels.obs``.
"""

from __future__ import annotations

import datetime
import glob
import json
import os
import re
import time

from tpukernels.resilience import journal

SCHEMA = "tpk_scaling_v1"
DEFAULT_MIN_EFF = 0.5
DEFAULT_OVERLAP_MIN_FRAC = 0.3

_ROUND_RE = re.compile(r"SCALING_r(\d+)\.json$")
_MULTICHIP_RE = re.compile(r"MULTICHIP_r(\d+)\.json$")
_DRYRUN_LINE_RE = re.compile(r"\[dryrun \+\s*([0-9.]+)s\] (.+)")
_PROGRAMS_LINE = "MULTICHIP-PROGRAMS: "

# Analytic per-link interconnect ceilings in GB/s per device kind —
# the bus-bw twin of tuning/roofline.PEAKS. Ring-allreduce bus
# bandwidth (2(n-1)/n · S/t) and the bare ppermute per-link figure are
# both bounded by what one ICI link direction can carry, so one row
# serves both ops. The v5-lite figure is the datasheet-order 1,600
# Gbps/chip ICI (to be re-anchored the first time a pod capture
# lands); the documented CPU fallback is a loose shared-memory-copy
# bound so the plumbing runs anywhere — fake evidence never gates, so
# the cpu row is for reports only. ``dcn_gb_s`` bounds the multi-slice
# / multi-host-over-network case (200 Gbps NICs).
ICI_CEILINGS = {
    "tpu_v5_lite": {"ici_gb_s": 200.0, "dcn_gb_s": 25.0},
    "cpu": {"ici_gb_s": 100.0, "dcn_gb_s": 100.0},
}
EVIDENCE_KIND = "tpu_v5_lite"

# The weak-scaling program catalog — the completeness-lint surface
# (tests/test_scaling_obs.py): every program tools/weak_scaling.py
# sweeps must have a row here (its artifact series name + what "per
# chip work" means for it), so a new distributed kernel cannot ship
# observability-dark.
WEAK_SERIES = {
    "stencil2d": {
        "series": "weak/stencil2d",
        "work_unit": "rows/chip x cols (iters fixed)",
    },
    "nbody_ring": {
        "series": "weak/nbody_ring",
        "work_unit": "bodies/chip (O(N^2) total = linear/chip when "
                     "i-bodies shard)",
    },
    "scan_hist": {
        "series": "weak/scan_hist",
        "work_unit": "elements/chip (scan + 256-bin histogram)",
    },
    "allreduce": {
        "series": "weak/allreduce",
        "work_unit": "f32 elements/chip in the psum message",
    },
    "allreduce2d": {
        "series": "weak/allreduce2d",
        "work_unit": "f32 elements/chip, two-phase over an (r, c) mesh "
                     "(reduce-scatter along x, allgather along y)",
    },
}

# Overlap capability catalog — the registry-contract lint surface
# (tests/test_registry_contract.py): every WEAK_SERIES program must
# declare whether its comm/compute overlap is depth-searchable
# ("depth": TPK_DIST_DEPTH pipelines it) or documented-exempt
# ("exempt" + why), so a future distributed program cannot ship
# sync-only silently.
OVERLAP_CAPS = {
    "stencil2d": {
        "mode": "depth",
        "why": "k-deep halo bands double-buffer at depth 2 "
               "(_jacobi_dist; docs/DISTRIBUTED.md §overlap)",
    },
    "nbody_ring": {
        "mode": "depth",
        "why": "j-block ring pipelines depth hops of ppermute ahead "
               "of the force block (docs/DISTRIBUTED.md §overlap)",
    },
    "scan_hist": {
        "mode": "exempt",
        "why": "one all_gather/psum phase after all local compute — "
               "there is no second hop to overlap with",
    },
    "allreduce": {
        "mode": "exempt",
        "why": "a single fused psum; overlap is XLA's to schedule, "
               "not expressible at this layer",
    },
    "allreduce2d": {
        "mode": "exempt",
        "why": "two back-to-back psum phases with a data dependency "
               "(phase 2 consumes phase 1's partials); nothing "
               "independent to overlap",
    },
}


def min_eff() -> float:
    """The weak-scaling efficiency floor (``TPK_SCALING_MIN_EFF``,
    default 0.5) under which the largest-mesh point earns the
    non-gating ``below_scaling_efficiency`` verdict. Fail-loud parse,
    the TPK_* knob contract."""
    raw = os.environ.get("TPK_SCALING_MIN_EFF")
    if raw is None:
        return DEFAULT_MIN_EFF
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if not 0.0 <= val <= 1.0:
        raise ValueError(
            f"TPK_SCALING_MIN_EFF={raw!r}: expected a float in [0, 1]"
        )
    return val


def overlap_min_frac() -> float:
    """The comm/compute overlap floor (``TPK_OVERLAP_MIN_FRAC``,
    default 0.3) under which a validated non-fake overlap point earns
    the non-gating ``overlap_low`` verdict. Fail-loud parse, the TPK_*
    knob contract."""
    raw = os.environ.get("TPK_OVERLAP_MIN_FRAC")
    if raw is None:
        return DEFAULT_OVERLAP_MIN_FRAC
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if not 0.0 <= val <= 1.0:
        raise ValueError(
            f"TPK_OVERLAP_MIN_FRAC={raw!r}: expected a float in [0, 1]"
        )
    return val


def scaling_dir(root=None) -> str:
    """Where scaling artifacts are written: ``TPK_SCALING_DIR`` when
    set (tests and throwaway sweeps point it at a tmp dir so rehearsal
    runs never pollute the repo's committed evidence), else
    ``<root>/docs/logs`` beside the bench artifacts."""
    d = os.environ.get("TPK_SCALING_DIR")
    if d:
        return d
    if root is None:
        root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
    return os.path.join(root, "docs", "logs")


def ceiling_gb_s(op: str, kind=None, dcn: bool = False):
    """(ceiling_GB_s, resolved_kind, basis) for one collective op on
    one device kind — resolution mirrors ``roofline.resolve_kind``:
    exact row, unknown-TPU kinds borrow the v5-lite row (flagged
    basis), anything else falls back to the documented cpu row."""
    if kind is None:
        kind = EVIDENCE_KIND
    basis = "exact"
    if kind in ICI_CEILINGS:
        row = ICI_CEILINGS[kind]
    elif str(kind).startswith("tpu"):
        row, basis = ICI_CEILINGS[EVIDENCE_KIND], f"assumed-{EVIDENCE_KIND}"
    else:
        row, basis = ICI_CEILINGS["cpu"], "cpu-fallback"
    return row["dcn_gb_s" if dcn else "ici_gb_s"], kind, basis


# ------------------------------------------------------------------ #
# device inventory                                                   #
# ------------------------------------------------------------------ #

def inventory(probe: bool = False) -> dict:
    """The hardware this process runs on, as a plain dict.

    ``probe=True`` reads the real topology off ``jax.devices()``
    (``source="jax"``) — which INITIALIZES the backend, so only
    processes that are about to run device code anyway (busbw,
    weak-scaling inners, dryrun, bench ``--one`` children) may ask for
    it. ``probe=False`` (the default) imports nothing and derives the
    inventory from the environment (``source="env"``) — the only safe
    mode for a supervisor or bench-suite parent, where touching the
    backend could wedge on a dead tunnel. Explicit, never inferred:
    "jax happens to be imported" is not evidence that backend init is
    safe. ``fake`` is True when the platform is not a TPU one:
    fake-device CPU rehearsals produce logic evidence, never bandwidth
    evidence. ``fake_basis`` says WHY: ``"probe"`` (a backend
    answered), ``"declared-platform"`` (the env named one),
    ``"unknown-platform"`` — nothing declared a platform, which is the
    NORMAL pod configuration (JAX_PLATFORMS unset), so the hardware is
    unknown rather than known-fake — or ``"unprobed-fallback"``: a
    REQUESTED probe failed, and a process that wanted a probe but
    could not get one must never produce chip evidence, whatever the
    env declares. Non-probe bases still stamp ``fake=True`` where the
    platform is not known-real (fail-safe: unknown must never read as
    chip evidence and never gates) but reports render "platform
    unknown", not "FAKE"; gating-eligible artifacts must carry a
    probed (``source="jax"``) inventory — :func:`analyze_busbw`
    enforces it.
    """
    if probe:
        import jax

        try:
            devs = jax.devices()
            d0 = devs[0]
            platform = d0.platform
            return {
                "source": "jax",
                "platform": platform,
                "device_kind": str(
                    getattr(d0, "device_kind", "?")
                ).lower().replace(" ", "_"),
                "n_devices": len(devs),
                "local_devices": len(jax.local_devices()),
                "process_index": jax.process_index(),
                "process_count": jax.process_count(),
                "fake": platform not in ("tpu", "axon"),
                "fake_basis": "probe",
            }
        except Exception:  # noqa: BLE001 — fall through to env
            pass
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
    # first entry of the priority list (the ensure_cpu_collectives
    # parsing rule): JAX_PLATFORMS="tpu,cpu" is a TPU-first host, not
    # a fake one
    platform = (os.environ.get("JAX_PLATFORMS") or "").split(",")[0] \
        or ("axon" if os.environ.get("PALLAS_AXON_POOL_IPS") else None)
    inv = {
        "source": "env",
        "platform": platform,
        "device_kind": None,
        "n_devices": int(m.group(1)) if m else None,
        "local_devices": None,
        "process_index": None,
        "process_count": None,
        # env-derived: only a declared-CPU (or force-fake-device)
        # platform is KNOWN fake; an unset platform (the normal pod
        # config) is unknown until a backend answers, and unknown must
        # not read as chip evidence — so it counts fake here too, with
        # fake_basis distinguishing it so a real pod's stamp renders
        # "platform unknown", never the misleading "FAKE"
        "fake": not (platform in ("tpu", "axon")),
        "fake_basis": ("declared-platform" if platform is not None
                       else "unknown-platform"),
    }
    if probe:
        # a REQUESTED probe fell through to here (jax.devices()
        # errored): whatever the env declares, this process could not
        # attribute its work to a real topology — force the fail-safe
        # so a flaky runtime on a declared-TPU host can never mint
        # chip evidence from an unprobed stamp
        inv["fake"] = True
        inv["fake_basis"] = "unprobed-fallback"
    return inv


def emit_inventory(site: str, probe: bool = False) -> dict:
    """Stamp one ``device_inventory`` journal event for this process
    (no-op when journaling is off, like every emit) and return the
    inventory so artifact writers embed the same dict they stamped.
    ``probe`` as in :func:`inventory` — only pass True where backend
    initialization is already inevitable."""
    inv = inventory(probe)
    journal.emit("device_inventory", site=site, **inv)
    return inv


# ------------------------------------------------------------------ #
# artifact writers                                                   #
# ------------------------------------------------------------------ #

def _write(prefix: str, payload: dict, out_dir=None) -> str:
    d = out_dir or scaling_dir()
    os.makedirs(d, exist_ok=True)
    stamp = datetime.datetime.now().strftime("%Y-%m-%d_%H%M%S")
    path = os.path.join(d, f"{prefix}_{stamp}_{os.getpid()}.json")
    payload = dict(payload)
    payload.setdefault("schema", SCHEMA)
    payload.setdefault("git_head", journal.git_head())
    payload.setdefault("recorded", round(time.time(), 3))
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    return path


def write_busbw_artifact(points, op: str, n_devices: int, inv: dict,
                         out_dir=None, mesh_shape=None) -> str:
    """Persist one bus-bw sweep: ``points`` is the ``sweep()`` result
    ``[(size_bytes, seconds, gb_s), ...]``. ``mesh_shape`` is the
    ``(rows, cols)`` of a 2-D sweep (None = the 1-D ring of record —
    omitted from the payload so existing artifacts stay byte-shaped)."""
    payload = {
        "family": "busbw",
        "op": op,
        "n_devices": int(n_devices),
        "fake": bool(inv.get("fake", True)),
        "device_inventory": inv,
        "points": [
            {"size_bytes": int(s), "seconds": sec, "gb_s": bw}
            for s, sec, bw in points
        ],
    }
    if mesh_shape is not None:
        payload["mesh_shape"] = [int(d) for d in mesh_shape]
    return _write(f"scaling_busbw_{op}", payload, out_dir)


def write_overlap_artifact(points, inv: dict, out_dir=None) -> str:
    """Persist one comm/compute overlap measurement sweep
    (``tpukernels.parallel.overlap``): ``points`` is a list of dicts
    ``{op, n_devices, mesh_shape, depth, t_comm_s, t_compute_s,
    t_full_s, overlap_frac}``."""
    return _write("scaling_overlap", {
        "family": "overlap",
        "fake": bool(inv.get("fake", True)),
        "device_inventory": inv,
        "points": list(points),
    }, out_dir)


def write_weak_artifact(points, inv: dict, out_dir=None) -> str:
    """Persist one weak-scaling sweep: ``points`` is a list of dicts
    ``{program, n_devices, wall_s, per_chip_work, ok}``."""
    return _write("scaling_weak", {
        "family": "weak_scaling",
        "fake": bool(inv.get("fake", True)),
        "device_inventory": inv,
        "points": list(points),
    }, out_dir)


# ------------------------------------------------------------------ #
# loaders                                                            #
# ------------------------------------------------------------------ #

def _read_json(p):
    try:
        with open(p) as f:
            return json.loads(f.read().strip() or "null")
    except (OSError, ValueError):
        return None


def load_artifacts(root) -> list:
    """Every committed scaling artifact under ``root`` — the dated
    ``docs/logs/scaling_*.json`` files (ordered by basename, the trend
    rule) then driver-root ``SCALING_r*.json`` rounds (by round
    number). Unparseable or schema-less files are skipped: a truncated
    artifact must not take down the report that would explain it."""
    out = []
    for p in sorted(
        glob.glob(os.path.join(root, "docs", "logs", "scaling_*.json")),
        key=os.path.basename,
    ):
        rec = _read_json(p)
        if isinstance(rec, dict) and isinstance(rec.get("points"), list):
            rec["_source"] = os.path.relpath(p, root)
            out.append(rec)
    rounds = []
    for p in glob.glob(os.path.join(root, "SCALING_r*.json")):
        m = _ROUND_RE.search(os.path.basename(p))
        if m:
            rounds.append((int(m.group(1)), p))
    for _n, p in sorted(rounds):
        rec = _read_json(p)
        if isinstance(rec, dict) and isinstance(rec.get("points"), list):
            rec["_source"] = os.path.relpath(p, root)
            out.append(rec)
    return out


def parse_dryrun_tail(tail: str) -> list:
    """Per-program walls from a dryrun progress tail.

    Preferred: the structured ``MULTICHIP-PROGRAMS: {...}`` JSON line
    newer ``__graft_entry__`` runs print. Legacy fallback (the five
    committed rounds): consecutive ``[dryrun +T.Ts] <name>`` lines are
    cumulative stamps printed at each program's START, so a program's
    wall is the NEXT stamp minus its own (the final ``all programs
    OK`` stamp closes the last program). Programs whose start scrolled
    off the 2000-char tail are simply absent — partial evidence is
    still evidence."""
    if not isinstance(tail, str):
        return []
    for line in reversed(tail.strip().splitlines()):
        line = line.strip()
        if line.startswith(_PROGRAMS_LINE):
            try:
                rec = json.loads(line[len(_PROGRAMS_LINE):])
            except ValueError:
                break
            progs = rec.get("programs")
            if isinstance(progs, list):
                return [p for p in progs if isinstance(p, dict)]
            break
    stamps = []
    for line in tail.splitlines():
        m = _DRYRUN_LINE_RE.search(line)
        if m:
            stamps.append((float(m.group(1)), m.group(2).strip()))
    out = []
    for (t, name), (t_next, _n2) in zip(stamps, stamps[1:]):
        if name.startswith("importing") or name.startswith("all programs"):
            continue
        # strip the parenthetical detail some notes carry
        name = name.split(" (")[0].strip()
        out.append({"name": name, "wall_s": round(t_next - t, 3),
                    "ok": True})
    return out


def load_multichip(root) -> list:
    """``[{round, n_devices, ok, programs}]`` over the committed
    ``MULTICHIP_r*.json`` driver rounds, oldest round first. A
    ``programs`` key (the structured writer) wins; otherwise the tail
    is parsed (see :func:`parse_dryrun_tail`)."""
    rounds = []
    for p in glob.glob(os.path.join(root, "MULTICHIP_r*.json")):
        m = _MULTICHIP_RE.search(os.path.basename(p))
        if m:
            rounds.append((int(m.group(1)), p))
    out = []
    for n, p in sorted(rounds):
        rec = _read_json(p)
        if not isinstance(rec, dict):
            continue
        progs = rec.get("programs")
        if not isinstance(progs, list):
            progs = parse_dryrun_tail(rec.get("tail"))
        out.append({
            "round": n,
            "n_devices": rec.get("n_devices"),
            "ok": bool(rec.get("ok")),
            "programs": [p for p in progs if isinstance(p, dict)],
            "_source": os.path.relpath(p, root),
        })
    return out


# ------------------------------------------------------------------ #
# series + verdicts                                                  #
# ------------------------------------------------------------------ #

def busbw_series(artifacts) -> dict:
    """``{(op, size_bytes, n_devices, mesh_shape): [point, ...]}`` in
    artifact order; each point carries value/fake/source. 1-D sweeps
    carry ``mesh_shape=None`` so their series keys (and report names)
    are unchanged from before 2-D meshes existed."""
    out: dict = {}
    for art in artifacts:
        if art.get("family") != "busbw":
            continue
        fake = bool(art.get("fake", True))
        op = art.get("op") or "?"
        nd = art.get("n_devices")
        ms = art.get("mesh_shape")
        mesh_shape = tuple(int(d) for d in ms) \
            if isinstance(ms, (list, tuple)) and len(ms) == 2 else None
        inv = art.get("device_inventory") or {}
        kind = inv.get("device_kind")
        inv_source = inv.get("source")
        # multi-host sweeps cross DCN, not ICI: the ceiling such a
        # point is judged against must be the network one
        pc = inv.get("process_count")
        dcn = isinstance(pc, int) and pc > 1
        for pt in art["points"]:
            if not isinstance(pt, dict):
                continue
            gbs = pt.get("gb_s")
            if not isinstance(gbs, (int, float)) or isinstance(gbs, bool):
                continue
            key = (op, pt.get("size_bytes"), nd, mesh_shape)
            out.setdefault(key, []).append({
                "value": gbs,
                "fake": fake,
                "device_kind": kind,
                "inv_source": inv_source,
                "dcn": dcn,
                "source": art.get("_source", "?"),
                # the trend-parser escape hatch: a point marked
                # invalidated at source (truthy value = the reason)
                # is reported but never evidence — without it, one
                # glitched committed capture above the ceiling would
                # gate rc 1 forever
                "invalidated": pt.get("invalidated"),
            })
    return out


def analyze_busbw(artifacts, eps: float) -> dict:
    """Per-(op, size, n_devices) verdicts with the trend vocabulary.
    Only non-fake points are VALID evidence: a fake-only series is
    ``no_data`` with an explanatory flag, never a regression and never
    impossible — exactly how simulated SLO entries never gate."""
    verdicts = {}
    for (op, size, nd, mesh_shape), pts in sorted(
        busbw_series(artifacts).items(),
        key=lambda kv: (kv[0][0], kv[0][2] or 0, kv[0][1] or 0,
                        kv[0][3] or ()),
    ):
        name = f"busbw/{op}/n{nd}/{size}B"
        if mesh_shape is not None:
            name += f"/mesh{mesh_shape[0]}x{mesh_shape[1]}"
        flags = []
        impossible = False
        valid = []
        for p in pts:
            if p["fake"]:
                continue
            if p.get("inv_source") != "jax":
                # the docs/DISTRIBUTED.md contract: gating-eligible
                # evidence carries a PROBED inventory — a non-fake
                # artifact stamped from the env (or with no inventory
                # at all) has unattributed topology, so it must
                # neither fire nor mask a gating verdict
                flags.append(
                    f"{p['value']} GB/s from {p['source']} carries an "
                    f"unprobed device inventory "
                    f"(source={p.get('inv_source')!r}) - excluded "
                    "from gating"
                )
                continue
            ceil, kind, basis = ceiling_gb_s(
                op, p["device_kind"], dcn=p.get("dcn", False)
            )
            over = p["value"] > ceil * (1.0 + eps)
            if p.get("invalidated"):
                # already caught at the source (the trend-parser
                # rule): reported, never evidence either way
                flags.append(
                    f"{p['value']} GB/s from {p['source']} "
                    "invalidated at source "
                    f"({p['invalidated']})"
                    + (f" - exceeds the {kind} ICI ceiling {ceil}"
                       if over else "")
                )
                continue
            if over:
                impossible = True
                flags.append(
                    f"IMPOSSIBLE: {p['value']} GB/s from {p['source']} "
                    f"exceeds the analytic {kind} ICI ceiling "
                    f"{ceil} GB/s (+{eps:.0%}, basis {basis})"
                )
                continue
            valid.append(p)
        info = {
            "op": op, "size_bytes": size, "n_devices": nd,
            "mesh_shape": list(mesh_shape) if mesh_shape else None,
            "points": len(pts), "valid_points": len(valid),
            "latest": valid[-1]["value"] if valid else None,
            "latest_source": valid[-1]["source"] if valid else None,
            "best": max((p["value"] for p in valid), default=None),
            "flags": flags,
        }
        if impossible:
            info["verdict"] = "impossible"
        elif not valid:
            info["verdict"] = "no_data"
            flags.append(
                "no validated evidence (fake-device or unprobed "
                "points only; excluded from gating)" if pts
                else "no points"
            )
        else:
            latest = info["latest"]
            prior_best = max(
                (p["value"] for p in valid[:-1]), default=None
            )
            if prior_best and latest < prior_best * (1.0 - eps):
                info["verdict"] = "regression"
                flags.append(
                    f"REGRESSION: latest {latest} GB/s "
                    f"({info['latest_source']}) is "
                    f"{latest / prior_best:.3f}x of prior best "
                    f"{prior_best} GB/s (band {eps:.0%})"
                )
            else:
                info["verdict"] = "ok"
        verdicts[name] = info
    return verdicts


def analyze_weak(artifacts) -> dict:
    """Per-program weak-scaling verdicts over the NEWEST artifact that
    carries the program (older sweeps are superseded evidence, not a
    time series — the wall at mesh n only compares against the same
    sweep's smallest mesh). ``below_scaling_efficiency`` is NON-GATING
    and fires only on non-fake evidence."""
    floor = min_eff()
    latest: dict = {}
    for art in artifacts:
        if art.get("family") != "weak_scaling":
            continue
        fake = bool(art.get("fake", True))
        per_prog: dict = {}
        for pt in art["points"]:
            if not isinstance(pt, dict) or not pt.get("ok", True):
                continue
            wall = pt.get("wall_s")
            nd = pt.get("n_devices")
            if not isinstance(wall, (int, float)) or not nd:
                continue
            per_prog.setdefault(pt.get("program"), {})[int(nd)] = wall
        for prog, walls in per_prog.items():
            latest[prog] = {
                "walls": walls, "fake": fake,
                "source": art.get("_source", "?"),
            }
    verdicts = {}
    for prog in sorted(latest):
        ent = latest[prog]
        walls = ent["walls"]
        ns = sorted(walls)
        info = {
            "program": prog,
            "series": WEAK_SERIES.get(prog, {}).get(
                "series", f"weak/{prog}"
            ),
            "n_devices": ns,
            "walls": {str(n): walls[n] for n in ns},
            "fake": ent["fake"],
            "source": ent["source"],
            "flags": [],
        }
        if len(ns) < 2:
            info["verdict"] = "no_data"
            info["efficiency"] = None
            info["flags"].append("fewer than two mesh sizes measured")
        else:
            n0, n1 = ns[0], ns[-1]
            eff = walls[n0] / walls[n1] if walls[n1] > 0 else 0.0
            info["efficiency"] = round(eff, 4)
            if ent["fake"]:
                info["verdict"] = "no_data"
                info["flags"].append(
                    "fake-device evidence only (all mesh 'chips' "
                    "timeshare one host; efficiency is meaningless "
                    "and never verdict-ed)"
                )
            elif eff < floor:
                info["verdict"] = "below_scaling_efficiency"
                info["flags"].append(
                    f"BELOW SCALING EFFICIENCY: wall {walls[n1]}s at "
                    f"n={n1} vs {walls[n0]}s at n={n0} -> efficiency "
                    f"{eff:.1%} under the TPK_SCALING_MIN_EFF floor "
                    f"{floor:.0%} (non-gating headroom signal)"
                )
            else:
                info["verdict"] = "ok"
        verdicts[prog] = info
    return verdicts


def analyze_overlap(artifacts) -> dict:
    """Per-(op, n_devices, depth) overlap verdicts over the NEWEST
    artifact carrying each key (superseded-evidence rule, like
    :func:`analyze_weak`). ``overlap_low`` is NON-GATING — the
    ``below_roofline`` pattern: a validated non-fake point whose
    ``overlap_frac`` sits under the ``TPK_OVERLAP_MIN_FRAC`` floor is
    headroom to reclaim, not a broken build. Fake evidence (the CPU
    gloo rehearsals) proves the measurement plumbing and is reported
    as ``no_data``."""
    floor = overlap_min_frac()
    latest: dict = {}
    for art in artifacts:
        if art.get("family") != "overlap":
            continue
        fake = bool(art.get("fake", True))
        for pt in art["points"]:
            if not isinstance(pt, dict):
                continue
            frac = pt.get("overlap_frac")
            if not isinstance(frac, (int, float)) or isinstance(frac, bool):
                continue
            key = (pt.get("op") or "?", pt.get("n_devices"),
                   pt.get("depth"))
            latest[key] = {
                "point": pt, "fake": fake,
                "source": art.get("_source", "?"),
            }
    verdicts = {}
    for (op, nd, depth) in sorted(
        latest, key=lambda k: (k[0], k[1] or 0, k[2] or 0)
    ):
        ent = latest[(op, nd, depth)]
        pt = ent["point"]
        frac = pt["overlap_frac"]
        ms = pt.get("mesh_shape")
        name = f"overlap/{op}/n{nd}/d{depth}"
        info = {
            "op": op, "n_devices": nd, "depth": depth,
            "mesh_shape": list(ms) if ms else None,
            "overlap_frac": round(float(frac), 4),
            "t_comm_s": pt.get("t_comm_s"),
            "t_compute_s": pt.get("t_compute_s"),
            "t_full_s": pt.get("t_full_s"),
            "fake": ent["fake"],
            "source": ent["source"],
            "flags": [],
        }
        if ent["fake"]:
            info["verdict"] = "no_data"
            info["flags"].append(
                "fake-device evidence only (overlap plumbing proven; "
                "the fraction itself never verdict-ed)"
            )
        elif frac < floor:
            info["verdict"] = "overlap_low"
            info["flags"].append(
                f"OVERLAP LOW: measured comm/compute overlap "
                f"{frac:.1%} under the TPK_OVERLAP_MIN_FRAC floor "
                f"{floor:.0%} at depth {depth} (non-gating headroom "
                "signal)"
            )
        else:
            info["verdict"] = "ok"
        verdicts[name] = info
    return verdicts


def analyze_dryrun(root) -> dict:
    """Per-program dryrun-wall series over the MULTICHIP rounds —
    informational only: the rounds run on fake CPU devices by
    construction (dryrun always scrubs to the CPU backend), so these
    walls prove liveness and drift, never bandwidth, and never gate."""
    series: dict = {}
    for rnd in load_multichip(root):
        for prog in rnd["programs"]:
            name = prog.get("name")
            wall = prog.get("wall_s")
            if not name or not isinstance(wall, (int, float)):
                continue
            series.setdefault(name, []).append({
                "round": rnd["round"],
                "n_devices": rnd["n_devices"],
                "wall_s": wall,
                "ok": bool(prog.get("ok", True)),
            })
    return {
        name: {
            "rounds": len(pts),
            "latest_wall_s": pts[-1]["wall_s"],
            "best_wall_s": min(p["wall_s"] for p in pts),
            "points": pts,
        }
        for name, pts in sorted(series.items())
    }


def analyze_repo(root, eps: float = 0.01) -> dict:
    """One-call scaling analysis for the tools: busbw + weak-scaling
    + multichip-dryrun families over every committed artifact under
    ``root``. Emits one ``scaling_computed`` journal event (the
    ``roofline_computed`` twin) so a traced session records which
    verdicts the report was judged against."""
    artifacts = load_artifacts(root)
    out = {
        "busbw": analyze_busbw(artifacts, eps),
        "weak": analyze_weak(artifacts),
        "overlap": analyze_overlap(artifacts),
        "dryrun": analyze_dryrun(root),
        "artifacts": len(artifacts),
    }
    journal.emit(
        "scaling_computed",
        artifacts=len(artifacts),
        min_eff=min_eff(),
        busbw={k: v["verdict"] for k, v in out["busbw"].items()},
        weak={k: v["verdict"] for k, v in out["weak"].items()},
        overlap={k: v["verdict"] for k, v in out["overlap"].items()},
        dryrun_programs=sorted(out["dryrun"]),
    )
    return out


def gating_findings(analysis) -> dict:
    """The subset of an :func:`analyze_repo` result that gates
    ``obs_report --check`` rc 1: validated (non-fake) bus-bw
    ``regression`` / ``impossible`` verdicts. Weak-scaling efficiency
    and dryrun walls never appear here by construction."""
    return {
        name: v for name, v in analysis.get("busbw", {}).items()
        if v["verdict"] in ("regression", "impossible")
    }
