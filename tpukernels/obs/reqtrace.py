"""Per-request timeline assembly over the multi-process serve
journals (docs/OBSERVABILITY.md §request tracing).

The serving path is now a fleet: a p99 breach's tail can hide in
router admission, a spill hop, the batch coalescing window, a bucket
lock, pad staging, a cold compile, or the kernel itself — and every
prior observability layer (spans, SLO histograms, copy accounting)
is per-process, so none of them can say WHICH. This module joins the
journals the fleet's processes already write — the client's
``serve_client_request`` walls, the router's ``serve_route``/
``serve_spill`` placements, the workers' ``serve_request`` records
and request-tagged ``span`` events — on the client-minted
``request_id`` into one causal timeline per request, then decomposes
each into phases and a critical path, so a latency investigation is
a journal query instead of a reproduction.

Assembly rules:

- **Clock anchoring** — event ``t`` stamps come from each process's
  own wall clock; durations (``wall_s``) are monotonic-clock spans
  and therefore skew-immune. Cross-process ORDER is causal (client ⊃
  router ⊃ worker), never derived from comparing raw ``t`` across
  pids; per-process display offsets (``rel0``/``rel1``) anchor each
  segment to its OWN process's ``serve_start`` stamp (or the pid's
  first segment when the journal predates the daemon's start event),
  so a skewed worker clock shifts its lane, not the decomposition.
- **Gaps are explicit** — a ``serve_request_requeued`` marker means
  an abandoned worker attempt whose spans may never close: the
  timeline carries a loud ``abandoned-worker`` gap entry, never a
  silently shorter phase sum. A ``serve_request_replayed`` marker
  (the router re-routed an accepted request off a DEAD worker —
  docs/SERVING.md §self-healing) adds a ``dead-worker`` gap: the
  home attempt's spans and ``serve_request`` record died with the
  process, so the sibling's timeline is the whole surviving story
  and says so. A client-confirmed request with no ``serve_request``
  record at all gets a ``missing-server-record`` gap (the worker
  died between dispatch and journal). A ``serve_hedged`` marker joins
  the home and sibling attempts of one hedged dispatch the way spills
  join (first-response-wins — docs/SERVING.md §hedged dispatch), and
  ``serve_cancelled`` / ``serve_request_expired`` /
  ``serve_deadline_infeasible`` land as explicit ``cancelled`` /
  ``deadline-expired`` / ``deadline-infeasible`` entries, so an
  expired request's timeline says where its budget went. Hedged
  timelines are exempt from the clean/``trace_inconsistent`` gate
  like replays: two server records is the DESIGNED shape of a hedge,
  not an inconsistency.
- **Degrade loudly, never crash** — a pre-request_id journal (old
  server, tracing off) assembles to zero timelines;
  :func:`untraced_serve_requests` counts what could not be joined so
  every consumer (``tools/trace_report.py``, ``obs_report``,
  ``loadgen``'s budget stamp) can say so out loud.

Phase decomposition per request (seconds, exclusive):
``queue_wait`` (admission→worker start, coalescing window included),
``lock_wait`` (bucket-lock acquisition), ``pad`` (staging),
``dispatch`` (the ``serve/<kernel>`` span minus its aot/integrity
children), ``compile`` (``aot/lower`` + ``aot/compile`` children),
``integrity`` (canary checks), ``unaccounted`` (client wall minus
every accounted phase — wire framing, router relay, client-side
work). ``accounted / client_wall`` is both verdict surfaces in one
number: under :func:`coverage_min` it flags ``trace_coverage``
(non-gating — the timeline explains too little of the wall); over
``1 + SUM_TOL`` on a CLEAN request (no requeue, no spill, no
rejection, no tenant throttle — an abandoned attempt's
late-unwinding span may legitimately overrun its client wall, and a
throttled request's wall includes backoff sleeps no span covers) it
is ``trace_inconsistent``
and GATES like the PR-12 copy budget (``trend.analyze_trace_budget``
over the ``serve_trace_budget`` events ``loadgen --serve`` stamps).

Stdlib-only, like ``trend.py``: report tools must run on a
journal-only host.
"""

from __future__ import annotations

import os

# accounted phases may not exceed the client-observed wall beyond
# this fraction on a clean request: durations nest physically, so an
# overrun means double-counted or mis-joined segments (the documented
# tolerance absorbs sub-ms rounding of the journal's stamps)
SUM_TOL = 0.10

DEFAULT_COVERAGE_MIN = 0.5

# report ordering for the phase tables (unaccounted always last)
PHASES = ("queue_wait", "lock_wait", "pad", "dispatch", "compile",
          "integrity", "unaccounted")


def coverage_min() -> float:
    """``TPK_TRACE_COVERAGE_MIN`` (default 0.5), fail-loud parse in
    [0, 1]: the documented fraction of the client-observed wall the
    accounted phases must cover before a timeline stops flagging
    ``trace_coverage`` (non-gating)."""
    raw = os.environ.get("TPK_TRACE_COVERAGE_MIN")
    if raw is None or not raw.strip():
        return DEFAULT_COVERAGE_MIN
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if not 0.0 <= val <= 1.0:
        raise ValueError(
            f"TPK_TRACE_COVERAGE_MIN={raw!r}: expected a float in "
            "[0, 1]"
        )
    return val


def phase_of(name: str) -> str | None:
    """Span path → timeline phase (docs/OBSERVABILITY.md §request
    tracing). aot/integrity children classify by their own area
    wherever they nest; anything else under ``serve/`` or
    ``dispatch/`` is dispatch work."""
    if "aot/" in name:
        return "compile"
    if "integrity/" in name:
        return "integrity"
    if name.startswith("serve/wait/queue"):
        return "queue_wait"
    if name.startswith("serve/wait/lock"):
        return "lock_wait"
    if name.startswith("serve/pad"):
        return "pad"
    if name.startswith(("serve/", "dispatch/")):
        return "dispatch"
    return None


def untraced_serve_requests(events) -> int:
    """``serve_request`` events carrying NO request_id — a
    pre-tracing server or client in the mix. Counted so every
    consumer degrades loudly instead of silently assembling a partial
    story."""
    return sum(
        1 for e in events
        if e.get("kind") == "serve_request"
        and e.get("request_id") is None
    )


def _new_timeline(rid) -> dict:
    return {
        "request_id": rid, "kernel": None, "bucket": None,
        "tenant": None, "worker_id": None,
        "client": None, "server": [], "route": [], "spills": [],
        "rejections": 0, "throttles": 0, "requeued": False,
        "replayed": False, "hedged": False,
        "hedges": [], "cancels": [], "expiries": [],
        "segments": [], "gaps": [],
    }


def assemble(events) -> dict:
    """``{request_id: timeline}`` over journal events (any mix of
    processes/files). Tolerant by design: unknown kinds are skipped,
    malformed stamps contribute what they can, and nothing here ever
    raises on journal content — a truncated journal is exactly when a
    postmortem needs whatever assembles."""
    anchors: dict = {}   # pid -> its own serve_start wall-clock t
    tls: dict = {}

    def tl(rid):
        t = tls.get(rid)
        if t is None:
            t = tls[rid] = _new_timeline(rid)
        return t

    for ev in events:
        kind = ev.get("kind")
        if kind == "serve_start":
            pid = ev.get("pid")
            if pid is not None and pid not in anchors:
                anchors[pid] = ev.get("t")
            continue
        rid = ev.get("request_id")
        if rid is None:
            continue
        rid = str(rid)
        if kind == "serve_client_request":
            t = tl(rid)
            t["client"] = ev
            t["kernel"] = t["kernel"] or ev.get("kernel")
            if ev.get("tenant") is not None:
                t["tenant"] = ev.get("tenant")
        elif kind == "serve_request":
            t = tl(rid)
            t["server"].append(ev)
        elif kind == "serve_route":
            t = tl(rid)
            t["route"].append(ev)
            t["kernel"] = t["kernel"] or ev.get("kernel")
            t["bucket"] = t["bucket"] or ev.get("bucket")
        elif kind == "serve_spill":
            tl(rid)["spills"].append(ev)
        elif kind == "serve_hedged":
            t = tl(rid)
            t["hedged"] = True
            t["hedges"].append(ev)
            t["gaps"].append({
                "kind": "hedged", "pid": ev.get("pid"),
                "t": ev.get("t"),
                "detail": (f"worker {ev.get('from_worker')} outlived "
                           "the hedge threshold "
                           f"({ev.get('threshold_s')}s); same "
                           "request_id re-issued to sibling "
                           f"{ev.get('to_worker')} — first response "
                           "wins, loser cancelled"),
            })
        elif kind == "serve_cancelled":
            t = tl(rid)
            t["cancels"].append(ev)
            where = (f"worker {ev.get('to_worker')}"
                     if ev.get("to_worker") is not None
                     else f"phase {ev.get('phase')}")
            t["gaps"].append({
                "kind": "cancelled", "pid": ev.get("pid"),
                "t": ev.get("t"),
                "detail": (f"hedge loser cancelled at "
                           f"{ev.get('site')} ({where}) — its work "
                           "was dropped or its reply suppressed"),
            })
        elif kind == "serve_request_expired":
            t = tl(rid)
            t["expiries"].append(ev)
            t["gaps"].append({
                "kind": "deadline-expired", "pid": ev.get("pid"),
                "t": ev.get("t"),
                "detail": (f"budget ran out at {ev.get('site')}"
                           f"/{ev.get('where')} before dispatch — "
                           "the wait phases above are where the "
                           "budget went"),
            })
        elif kind == "serve_deadline_infeasible":
            t = tl(rid)
            t["expiries"].append(ev)
            t["gaps"].append({
                "kind": "deadline-infeasible", "pid": ev.get("pid"),
                "t": ev.get("t"),
                "detail": ("refused at router admission: the budget "
                           "was already spent before arrival"),
            })
        elif kind == "serve_rejected":
            tl(rid)["rejections"] += 1
        elif kind == "serve_tenant_throttled":
            # a throttled-then-retried request's wall includes the
            # backoff sleeps no span covers: it must not feed the
            # consistency/coverage gate as "clean"
            tl(rid)["throttles"] += 1
        elif kind == "serve_request_replayed":
            t = tl(rid)
            t["replayed"] = True
            if ev.get("via") == "wal":
                # the ROUTER died holding this accepted request; a
                # respawned router replayed it from its WAL
                # (docs/SERVING.md §guardian)
                t["gaps"].append({
                    "kind": "dead-router", "pid": ev.get("pid"),
                    "t": ev.get("t"),
                    "detail": ("the router died holding this "
                               "accepted request; "
                               + (f"replayed from its WAL on worker "
                                  f"{ev.get('to_worker')}"
                                  if ev.get("ok") is not False else
                                  "its WAL replay skipped it "
                                  f"({ev.get('reason')}) and the "
                                  "client retried")
                               + " — the first attempt's evidence "
                               "died with the router"),
                })
            else:
                t["gaps"].append({
                    "kind": "dead-worker", "pid": ev.get("pid"),
                    "t": ev.get("t"),
                    "detail": (f"worker {ev.get('from_worker')} died "
                               "holding this request; replayed on "
                               f"worker {ev.get('to_worker')} — the "
                               "home attempt's evidence died with "
                               "it"),
                })
        elif kind == "serve_request_requeued":
            t = tl(rid)
            t["requeued"] = True
            t["gaps"].append({
                "kind": "abandoned-worker", "pid": ev.get("pid"),
                "t": ev.get("t"),
                "detail": (f"worker abandoned after "
                           f"{ev.get('timeout_s')}s; the attempt's "
                           "spans may never close"),
            })
        elif kind == "span":
            wall = ev.get("wall_s")
            wall = wall if isinstance(wall, (int, float)) else 0.0
            te = ev.get("t")
            te = te if isinstance(te, (int, float)) else 0.0
            name = str(ev.get("name") or "?")
            tl(rid)["segments"].append({
                "name": name, "phase": phase_of(name),
                "pid": ev.get("pid"), "wall_s": wall,
                "t0": te - wall, "t1": te,
                "depth": ev.get("depth") or 1,
                "ok": ev.get("ok", True),
            })

    for t in tls.values():
        _finalize(t, anchors)
    return tls


def _finalize(t: dict, anchors: dict):
    segs = t["segments"]
    segs.sort(key=lambda s: (str(s["pid"]), s["t0"]))
    # per-process anchoring: each segment's display offset is
    # relative to ITS OWN process's serve_start (fallback: the pid's
    # first segment) — cross-process clock skew moves a lane's
    # anchor, never the phase arithmetic (durations only)
    first_by_pid: dict = {}
    for s in segs:
        first_by_pid.setdefault(s["pid"], s["t0"])
    for s in segs:
        anchor = anchors.get(s["pid"])
        if anchor is None:
            anchor = first_by_pid[s["pid"]]
        s["rel0"] = round(max(0.0, s["t0"] - anchor), 6)
        s["rel1"] = round(max(0.0, s["t1"] - anchor), 6)

    # the request of record among (possibly several — a wedged home
    # attempt plus its spill sibling) server records: prefer the ok
    # answer, else the latest
    final = None
    for ev in sorted(t["server"], key=lambda e: e.get("t") or 0.0):
        if final is None:
            final = ev
        elif bool(ev.get("ok")) or not final.get("ok"):
            # an ok answer beats any failure; among equals the
            # latest wins (the spill sibling supersedes the home)
            final = ev
    t["final"] = final
    if final is not None:
        t["kernel"] = t["kernel"] or final.get("kernel")
        t["bucket"] = final.get("bucket") or t["bucket"]
        t["tenant"] = (final.get("tenant")
                       if final.get("tenant") is not None
                       else t["tenant"])
        t["worker_id"] = final.get("worker_id")
    client = t["client"]
    if (final is None and client is not None and client.get("ok")
            and t["rejections"] == 0):
        t["gaps"].append({
            "kind": "missing-server-record", "pid": None, "t": None,
            "detail": ("client saw a completed request but no worker "
                       "journaled it (worker died or journals "
                       "elsewhere)"),
        })

    phases = {ph: 0.0 for ph in PHASES if ph != "unaccounted"}
    top_dispatch = 0.0
    for s in segs:
        ph = s["phase"]
        if ph in ("queue_wait", "lock_wait", "pad",
                  "compile", "integrity"):
            phases[ph] += s["wall_s"]
        elif ph == "dispatch" and s["depth"] == 1:
            # depth-1 serve/<kernel> (or in-process dispatch/<kernel>)
            # spans only: their nested dispatch/aot children are
            # interior and must not double-count
            top_dispatch += s["wall_s"]
    phases["dispatch"] = max(
        0.0, top_dispatch - phases["compile"] - phases["integrity"]
    )
    accounted = (phases["queue_wait"] + phases["lock_wait"]
                 + phases["pad"] + top_dispatch)
    t["accounted_s"] = round(accounted, 6)
    cw = None
    if client is not None and isinstance(client.get("wall_s"),
                                         (int, float)):
        cw = client["wall_s"]
    t["client_wall_s"] = cw
    if cw and segs:
        t["coverage"] = round(accounted / cw, 4)
        phases["unaccounted"] = max(0.0, cw - accounted)
    else:
        t["coverage"] = None
    t["phases"] = {ph: round(v, 6) for ph, v in phases.items() if v}
    t["clean"] = bool(
        final is not None and final.get("ok")
        and not t["requeued"] and not t["spills"]
        and not t["replayed"] and not t["hedged"]
        and not t["expiries"]
        and t["rejections"] == 0 and t["throttles"] == 0
        and len(t["server"]) == 1
    )
    ranked = sorted(t["phases"].items(), key=lambda kv: -kv[1])
    t["critical_path"] = ranked
    t["dominant"] = ranked[0][0] if ranked else None


def _pct(vals, q):
    if not vals:
        return None
    vals = sorted(vals)
    return vals[min(len(vals) - 1, int(round(q * (len(vals) - 1))))]


def aggregate(timelines) -> dict:
    """Phase-attribution percentiles per (kernel, bucket, tenant)
    over assembled timelines — the table behind ``trace_report`` and
    ``obs_report``'s request-phase section. Keys are
    ``kernel|bucket|tenant``; per key: request count, client-wall
    p50/p99 and per-phase p50/p99/mean seconds."""
    groups: dict = {}
    for t in timelines.values():
        key = (t["kernel"] or "?", t["bucket"] or "-",
               t["tenant"] or "-")
        g = groups.setdefault(key, {"n": 0, "client": [],
                                    "phases": {}, "gaps": 0})
        g["n"] += 1
        g["gaps"] += len(t["gaps"])
        if t["client_wall_s"] is not None:
            g["client"].append(t["client_wall_s"])
        for ph, v in t.get("phases", {}).items():
            g["phases"].setdefault(ph, []).append(v)
    out = {}
    for (kernel, bucket, tenant), g in sorted(groups.items()):
        out[f"{kernel}|{bucket}|{tenant}"] = {
            "kernel": kernel, "bucket": bucket, "tenant": tenant,
            "n": g["n"], "gaps": g["gaps"],
            "client_p50_s": _pct(g["client"], 0.5),
            "client_p99_s": _pct(g["client"], 0.99),
            "phases": {
                ph: {
                    "n": len(vals),
                    "p50_s": _pct(vals, 0.5),
                    "p99_s": _pct(vals, 0.99),
                    "mean_s": round(sum(vals) / len(vals), 6),
                }
                for ph, vals in sorted(g["phases"].items())
            },
        }
    return out


def run_budget(events, request_ids=None) -> dict | None:
    """One run's trace-budget summary — the payload ``loadgen
    --serve`` stamps as a ``serve_trace_budget`` event (the
    ``serve_copy_budget`` pattern) for ``trend.analyze_trace_budget``
    to judge. ``request_ids`` restricts to the ids the run minted so
    a shared journal's other traffic cannot pollute the verdict.
    Returns None when nothing assembled (journal off, no serve
    traffic)."""
    tls = assemble(events)
    if request_ids is not None:
        wanted = {str(r) for r in request_ids}
        tls = {r: t for r, t in tls.items() if r in wanted}
    if not tls:
        return None
    traced = [t for t in tls.values() if t["segments"]]
    cov = [t["coverage"] for t in traced if t["coverage"] is not None]
    clean = [t["coverage"] for t in traced
             if t["clean"] and t["coverage"] is not None]
    out = {
        "requests": (len(request_ids) if request_ids is not None
                     else len(tls)),
        "assembled": len(tls),
        "traced": len(traced),
        "clean": len(clean),
        "gaps": sum(len(t["gaps"]) for t in tls.values()),
        "hedged": sum(1 for t in tls.values() if t["hedged"]),
        "expired": sum(1 for t in tls.values() if t["expiries"]),
        "untraced_serve_requests": untraced_serve_requests(events),
        "coverage_floor": coverage_min(),
        "sum_tol": SUM_TOL,
    }
    if cov:
        out["coverage_mean"] = round(sum(cov) / len(cov), 4)
        out["coverage_low"] = round(min(cov), 4)
    if clean:
        # the gating surface: only CLEAN requests — an abandoned
        # attempt's late-unwinding span can legitimately overrun the
        # client wall that stopped waiting for it
        out["sum_ratio_max"] = round(max(clean), 4)
    return out
