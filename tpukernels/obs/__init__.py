"""Observability layer: tracing, metrics, bench-trend analysis.

Three stdlib-only modules (safe before the jax import, like the
resilience layer they build on — docs/OBSERVABILITY.md):

- ``trace``   — nested span context manager (``with span("measure/
  sgemm")``) recording wall time, phase and kernel params into the
  resilience health journal; a zero-cost no-op when ``TPK_TRACE`` is
  unset, proven byte-identical on the clean bench path.
- ``metrics`` — process-local counters/gauges/histograms (per-kernel
  call counts and latencies, probe retries, watchdog kills,
  tuning-cache hits/misses/rejections), snapshot-emitted through the
  same journal so one JSONL file stays the source of truth.
- ``trend``   — loads ``BENCH_r*.json`` + ``docs/logs/bench_*.json``
  into per-metric time series and machine-checks the perf trajectory:
  regressions beyond the ceiling-epsilon band, physically-impossible
  values (the 72,698-GFLOPS class of error), tunnel-down nulls as
  "no data" — never as a regression.
- ``slo``     — per-kernel latency-SLO targets and the persisted
  ``slo.json`` verdict artifact: judges the per-request latency
  histograms ``tools/loadgen.py`` captures under open-loop load
  (p99 vs target -> ``ok``/``slo_breach``/``no_data``), sha+jax
  validated like the tuning/aot/integrity caches, gated by
  ``obs_report --check`` exactly like a regression.

CLI: ``python tools/obs_report.py`` renders the trend table, span,
metric and latency-SLO summaries and the regression verdicts;
``python tools/loadgen.py`` generates the load.
"""

from tpukernels.obs import metrics, slo, trace, trend  # noqa: F401
