"""Nested span tracing for the bench hot path (docs/OBSERVABILITY.md).

``with span("measure/sgemm", m=1024):`` records the span's wall time,
its position in the enclosing span stack and any keyword params, and
emits one ``span`` event into the resilience health journal on exit —
one JSONL stream stays the single source of truth for a session
(artifacts, health events and spans all correlate by ``t``/``pid``/
``git_head``).

``TPK_TRACE`` routing, mirroring the fault layer's clean-path
contract (``TPK_FAULT_PLAN``): unset — or ``0``/``off``/``none`` —
makes ``span()`` a single module-global check returning a shared
no-op object, so the production bench path pays nothing and its
stdout is byte-identical (``tests/test_obs.py`` proves it the same
way ``test_clean_path_output_byte_identical`` proves the fault
layer's). Any other value enables tracing. The flag is read once at
import (children inherit it through the environment, exactly like
fault plans); tests that flip it mid-process call :func:`reload`.

Span naming scheme (docs/OBSERVABILITY.md §spans): slash-separated,
``<area>/<detail>`` — ``suite/<metric>`` (bench parent, one per
killable child), ``measure/<metric>`` (bench ``--one`` child, whole
measurement), ``slope/compile`` / ``slope/execute`` (the ``_slope``
phases inside it), ``probe/liveness``, ``registry/populate``,
``capi/<kernel>``, ``tune/<kernel>``. Nested spans join their names
onto the enclosing path: ``measure/sgemm`` > ``slope/compile`` lands
as ``measure/sgemm/slope/compile``. The span stack is PER-THREAD
(``threading.local``): the measurement loops stay single-threaded,
but the serve daemon's worker threads (docs/SERVING.md) each trace
their own ``serve/<kernel>`` requests concurrently, and a shared
stack would interleave their paths into nonsense.

Request trace-context (docs/OBSERVABILITY.md §request tracing): the
serving path carries a client-minted ``request_id`` end to end, and
``with request_ctx(rid):`` binds it as the calling thread's AMBIENT
request — every span the thread emits while the context is open
(the serve worker's wait/pad/dispatch spans AND their nested
aot/integrity children, none of which know about requests) carries
``request_id`` with zero per-callsite changes. ``emit_span`` is the
passive-wait twin of :func:`span`: a phase measured from timestamps
(queue wait, lock wait) rather than a with-block still lands as one
``span`` event, so ``obs/reqtrace.py`` assembles timelines from one
event shape.
"""

from __future__ import annotations

import os
import threading
import time

from tpukernels.resilience import journal

_DISABLED = ("", "0", "off", "none")


def _read_enabled() -> bool:
    raw = os.environ.get("TPK_TRACE")
    return raw is not None and raw.strip().lower() not in _DISABLED


_ENABLED = _read_enabled()
_TLS = threading.local()  # .stack: enclosing span names per thread


def _stack() -> list:
    s = getattr(_TLS, "stack", None)
    if s is None:
        s = _TLS.stack = []
    return s


def enabled() -> bool:
    return _ENABLED


def reload() -> bool:
    """Re-read TPK_TRACE (tests flip the env mid-process; real runs
    load once at import, like the fault layer). Clears the calling
    thread's span stack: a stale parent path must not prefix spans
    from the new regime."""
    global _ENABLED
    _ENABLED = _read_enabled()
    _stack().clear()
    return _ENABLED


def current_path() -> str | None:
    """Slash-joined path of the innermost open span, or None."""
    s = _stack()
    return "/".join(s) if s else None


def current_request() -> str | None:
    """The calling thread's ambient request id, or None."""
    return getattr(_TLS, "request", None)


class _RequestCtx:
    """Binds (and restores on exit) the per-thread ambient request id.
    Always active — unlike spans it is two attribute writes, and the
    journal tagging on ``serve_request``/``serve_route`` events is
    unconditional anyway; only SPAN emission stays gated on
    ``TPK_TRACE``."""

    __slots__ = ("rid", "prev")

    def __init__(self, rid):
        self.rid = rid

    def __enter__(self):
        self.prev = getattr(_TLS, "request", None)
        _TLS.request = self.rid
        return self

    def __exit__(self, *exc):
        _TLS.request = self.prev
        return False


def request_ctx(request_id):
    """Context manager binding ``request_id`` as the calling thread's
    ambient request (docs/OBSERVABILITY.md §request tracing): every
    span emitted inside it — including nested aot/integrity children
    that know nothing about requests — carries ``request_id`` on its
    event. ``None`` is a valid binding (an untraced old client's
    request): spans then stay untagged."""
    return _RequestCtx(request_id)


class _NoopSpan:
    """Shared do-nothing context manager for the disabled path — no
    allocation, no clock read, no stack touch per ``span()`` call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


# span-event keys the emitter owns (plus the journal's own stamps): a
# caller field with one of these names — tuning spans forward
# arbitrary tunable names via **params — is prefixed instead of being
# allowed to raise a duplicate-kwarg TypeError out of __exit__ or to
# clobber the journal's timestamp/pid stamps
_RESERVED = ("kind", "ts", "t", "pid", "git_head",
             "name", "wall_s", "depth", "ok", "request_id")


class _Span:
    __slots__ = ("name", "fields", "path", "t0", "depth")

    def __init__(self, name: str, fields: dict):
        self.name = name
        self.fields = fields

    def __enter__(self):
        s = _stack()
        s.append(self.name)
        self.depth = len(s)
        self.path = "/".join(s)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        wall = time.perf_counter() - self.t0
        # unwind by identity, tolerating a stack corrupted by an
        # earlier non-LIFO exit: observability must not mask (or
        # worsen) the failure it is observing
        s = _stack()
        if s and s[-1] == self.name:
            s.pop()
        payload = {
            ("param_" + k if k in _RESERVED else k): v
            for k, v in self.fields.items()
        }
        payload.update(
            name=self.path,
            wall_s=round(wall, 6),
            depth=self.depth,
            ok=exc_type is None,
        )
        rid = getattr(_TLS, "request", None)
        if rid is not None:
            payload["request_id"] = rid
        journal.emit("span", **payload)
        return False


def aggregate_spans(events) -> dict:
    """``{name: {"count", "total_s", "max_s"}}`` over ``span`` journal
    events — the one aggregation behind tools/health_report.py's
    per-phase breakdown and tools/obs_report.py's span section, so a
    span-schema change cannot drift the two reports apart."""
    agg: dict = {}
    for ev in events:
        if ev.get("kind") != "span":
            continue
        name = ev.get("name", "?")
        wall = ev.get("wall_s") or 0.0
        a = agg.get(name)
        if a is None:
            agg[name] = {"count": 1, "total_s": wall, "max_s": wall}
        else:
            a["count"] += 1
            a["total_s"] += wall
            if wall > a["max_s"]:
                a["max_s"] = wall
    return agg


def emit_span(name: str, wall_s: float, /, **fields):
    """Emit one PRE-MEASURED span event: a passive wait whose wall
    was derived from timestamps (a request's queue wait, a bucket-lock
    wait) rather than wrapped in a with-block — the serve path's
    phases land in the journal with the same event shape live spans
    use, so ``reqtrace``/``aggregate_spans`` need no second schema.
    Joins the calling thread's open span path and carries its ambient
    request id, like a live span; with TPK_TRACE unset this is one
    global check and nothing else runs."""
    if not _ENABLED:
        return
    s = _stack()
    payload = {
        ("param_" + k if k in _RESERVED else k): v
        for k, v in fields.items()
    }
    payload.update(
        name="/".join([*s, name]) if s else name,
        wall_s=round(wall_s, 6),
        depth=len(s) + 1,
        ok=True,
    )
    rid = getattr(_TLS, "request", None)
    if rid is not None:
        payload["request_id"] = rid
    journal.emit("span", **payload)


def span(name: str, /, **fields):
    """Context manager timing one named phase. ``fields`` (kernel
    params, shapes, repeat counts) ride along on the emitted event;
    ``name`` is positional-only so a caller field named ``name`` (the
    tuning runner forwards arbitrary tunable names) stays a field.
    With TPK_TRACE unset this is one global check and a shared no-op
    object — nothing else runs."""
    if not _ENABLED:
        return _NOOP
    return _Span(name, fields)
