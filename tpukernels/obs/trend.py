"""Bench-trend time series + regression verdicts (docs/OBSERVABILITY.md).

Loads every committed bench artifact — the driver's ``BENCH_r*.json``
round files at the repo root plus ``docs/logs/bench_*.json`` — into a
per-metric time series and machine-checks the perf trajectory, so the
drift-inflated sgemm figure that BASELINE.md caught BY HAND (72,698
GFLOPS against the ~61 TFLOPS physical ceiling) is caught by machine
the next time, and a down tunnel's all-null rounds read as "no data",
never as a regression.

Parsing rules (the evidence formats in the wild, all tolerated):

- ``docs/logs/bench_*.json`` — one bench JSON line per file, ordered
  by the filename timestamp (git does not preserve mtimes).
- ``BENCH_r*.json`` — driver round files: the bench line sits under
  ``"parsed"`` (fallback: last line of ``"tail"``), ordered by round
  number after the dated artifacts.
- A tunnel-down line nests earlier evidence under
  ``details.last_persisted_artifact`` (``{"path", "line"}``) next to
  the string ``details.error`` — the nested line's surviving metrics
  (e.g. the stencil2d 131,799 Mcells/s inside ``BENCH_r04``) are
  pulled into the series at the NESTED artifact's own position,
  deduplicated by path so five rounds pointing at one artifact count
  it once. String detail values (the error text) are never evidence.
- ``invalidated`` blocks (``{metric: [raw, reason]}``) contribute
  their raw value to the ceiling check only — already caught at the
  source, they are reported as such, and never count as measurements.

Verdicts per metric (:func:`analyze`):

- ``impossible`` — a RAW detail value exceeds the metric's physical
  ceiling (BASELINE.json ``ceilings``) beyond the ceiling-epsilon
  band; dominates everything else.
- ``regression`` — the newest valid value sits more than the epsilon
  band below the best earlier valid value or below the BASELINE.json
  measured median. Deliberately tighter than the revalidate queue's
  15% hard gate: this is a non-gating trend REPORT, so it flags at
  the same 1% epsilon the ceiling logic uses.
- ``no_data`` — no valid measurement anywhere in the series (all
  nulls / tunnel-down / invalidated). Retryable, never a failure.
- ``below_roofline`` — the metric is trend-``ok`` (nothing regressed,
  nothing impossible) but its newest valid value sits under
  ``TPK_ROOFLINE_MIN_FRAC`` (default 0.5) of the analytic roofline
  peak for its config of record (``tuning/roofline.py``). A NON-GATING
  headroom signal: ``tools/obs_report.py --check`` keeps rc 0, and the
  verdict can only ever replace ``ok`` — never ``no_data``,
  ``regression`` or ``impossible`` (test-proven). Metrics whose config
  of record legitimately beats the HBM roofline (the VMEM-resident
  saxpy artifact) are reported but never verdict-ed.
- ``ok`` — otherwise.

The bands mirror bench.py's constants — ``CEILING_EPS`` must equal
``bench._CEILING_EPS`` and ``REGRESSION_TOL`` ``bench._REGRESSION_TOL``
(asserted by ``tests/test_obs.py``; importing bench from here would
drag jax into a stdlib-only module).

These verdicts judge slope throughput only. The per-request
latency-tail story — what users feel before any slope moves — lives
in the sibling ``obs/slo.py``: ``tools/obs_report.py --check`` gates
rc 1 on a confirmed ``slo_breach`` verdict exactly as it does on
``regression``/``impossible`` here and on a confirmed
``output_integrity_failed`` event.
"""

from __future__ import annotations

import glob
import json
import os
import re

# stdlib-only at import, like this module — the analytic per-kernel
# roofline models the below_roofline verdict judges against
from tpukernels.tuning import roofline

CEILING_EPS = 0.01   # == bench._CEILING_EPS (test-enforced mirror)
REGRESSION_TOL = 0.15  # == bench._REGRESSION_TOL (ditto; the hard gate)

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def _is_measurement(v) -> bool:
    """Mirror of bench._is_measurement: numeric, not bool, not the
    string payloads of a tunnel-down error line."""
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def load_baseline(root) -> dict:
    try:
        with open(os.path.join(root, "BASELINE.json")) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _numeric_table(d) -> dict:
    """Numeric-valued entries of a BASELINE.json block (drops the
    ``_note``/``measured_on`` prose keys)."""
    return {
        k: v for k, v in (d or {}).items() if _is_measurement(v)
    }


def _bench_line(rec):
    """The bench JSON line inside an artifact record, or None.

    Accepts a bare line (docs/logs files), a driver round file
    (``parsed`` holds the line; fallback: last line of ``tail``), and
    rejects anything without a ``details`` dict."""
    if not isinstance(rec, dict):
        return None
    if isinstance(rec.get("details"), dict):
        return rec
    parsed = rec.get("parsed")
    if isinstance(parsed, dict) and isinstance(parsed.get("details"), dict):
        return parsed
    tail = rec.get("tail")
    if isinstance(tail, str):
        for raw in reversed(tail.strip().splitlines()):
            raw = raw.strip()
            if raw.startswith("{"):
                try:
                    line = json.loads(raw)
                except ValueError:
                    return None
                if isinstance(line.get("details"), dict):
                    return line
                return None
    return None


def _points_from_line(line, source, order, out):
    """Append this line's evidence to the series dict ``out``:
    measured details as valid points, invalidated raws as
    ceiling-check-only points. Returns the nested
    ``last_persisted_artifact`` dict (or None) for the caller to
    resolve — resolution needs the dedupe state this helper lacks."""
    details = line.get("details") or {}
    for name, v in details.items():
        if _is_measurement(v):
            out.setdefault(name, []).append(
                {"value": v, "raw": v, "source": source, "order": order,
                 "invalidated": None}
            )
    for name, iv in (line.get("invalidated") or {}).items():
        raw = iv[0] if isinstance(iv, (list, tuple)) and iv else None
        if _is_measurement(raw):
            out.setdefault(name, []).append(
                {"value": None, "raw": raw, "source": source,
                 "order": order,
                 "invalidated": str(iv[1]) if len(iv) > 1 else "?"}
            )
    nested = details.get("last_persisted_artifact")
    return nested if isinstance(nested, dict) else None


def load_series(root) -> dict:
    """{metric: [point, ...]} over every committed bench artifact
    under ``root``, each series ordered oldest → newest. Unparseable
    files are skipped (a truncated artifact must not take down the
    report that would explain it)."""
    out: dict = {}
    seen_paths: set = set()

    def _read(p):
        try:
            with open(p) as f:
                return json.loads(f.read().strip() or "null")
        except (OSError, ValueError):
            return None

    def _nested(nest):
        # pull the pointed-at line's metrics in at the NESTED
        # artifact's own position; dedupe by path across rounds (and
        # against the dated files loaded directly above)
        relp = nest.get("path")
        line = _bench_line(nest.get("line"))
        if not isinstance(relp, str) or line is None:
            return
        key = os.path.normpath(relp)
        if key in seen_paths:
            return
        seen_paths.add(key)
        deeper = _points_from_line(
            line, relp, (0, os.path.basename(relp)), out
        )
        if deeper is not None:
            _nested(deeper)

    for p in sorted(
        glob.glob(os.path.join(root, "docs", "logs", "bench_*.json")),
        key=os.path.basename,
    ):
        line = _bench_line(_read(p))
        if line is None:
            continue
        rel = os.path.relpath(p, root)
        seen_paths.add(os.path.normpath(rel))
        nest = _points_from_line(
            line, rel, (0, os.path.basename(p)), out
        )
        if nest is not None:
            _nested(nest)

    rounds = []
    for p in glob.glob(os.path.join(root, "BENCH_r*.json")):
        m = _ROUND_RE.search(os.path.basename(p))
        if m:
            rounds.append((int(m.group(1)), p))
    for n, p in sorted(rounds):
        line = _bench_line(_read(p))
        if line is None:
            continue
        nest = _points_from_line(
            line, os.path.relpath(p, root), (1, n), out
        )
        if nest is not None:
            _nested(nest)

    for pts in out.values():
        pts.sort(key=lambda pt: pt["order"])
    return out


def analyze(series, baseline=None, eps=CEILING_EPS) -> dict:
    """Per-metric verdicts over :func:`load_series` output. See the
    module docstring for the verdict rules; ``flags`` carries one
    human-readable line per finding so the report needs no re-derive.
    Metrics the baseline knows but the series lacks report ``no_data``
    too — coverage holes are part of the trend story."""
    baseline = baseline or {}
    ceilings = _numeric_table(baseline.get("ceilings"))
    measured = _numeric_table(baseline.get("measured"))
    verdicts = {}
    for metric in sorted(set(series) | set(measured)):
        pts = series.get(metric, [])
        ceiling = ceilings.get(metric)
        flags, valid = [], []
        impossible = False
        for pt in pts:
            raw = pt["raw"]
            if (
                ceiling is not None
                and raw is not None
                and raw > ceiling * (1.0 + eps)
            ):
                if pt["invalidated"]:
                    flags.append(
                        f"{raw} from {pt['source']} exceeds ceiling "
                        f"{ceiling} - already invalidated at source "
                        f"({pt['invalidated']})"
                    )
                else:
                    impossible = True
                    flags.append(
                        f"IMPOSSIBLE: {raw} from {pt['source']} exceeds "
                        f"physical ceiling {ceiling} (+{eps:.0%}) and was "
                        "never invalidated"
                    )
                continue
            if pt["value"] is not None:
                valid.append(pt)
        base = measured.get(metric)
        info = {
            "valid_points": len(valid),
            "latest": valid[-1]["value"] if valid else None,
            "latest_source": valid[-1]["source"] if valid else None,
            "best": max((p["value"] for p in valid), default=None),
            "baseline": base,
            "flags": flags,
        }
        if impossible:
            info["verdict"] = "impossible"
        elif not valid:
            info["verdict"] = "no_data"
            flags.append(
                "no valid measurement in any artifact (tunnel-down "
                "nulls are no data, not a regression)"
            )
        else:
            latest = info["latest"]
            regressed = False
            prior_best = max(
                (p["value"] for p in valid[:-1]), default=None
            )
            if prior_best and latest < prior_best * (1.0 - eps):
                regressed = True
                flags.append(
                    f"REGRESSION: latest {latest} "
                    f"({info['latest_source']}) is "
                    f"{latest / prior_best:.3f}x of prior best "
                    f"{prior_best} (band {eps:.0%})"
                )
            if base and latest < base * (1.0 - eps):
                regressed = True
                flags.append(
                    f"REGRESSION: latest {latest} is "
                    f"{latest / base:.3f}x of the BASELINE.json "
                    f"measured median {base} (band {eps:.0%}; hard "
                    f"gate fails below {1.0 - REGRESSION_TOL:.2f}x)"
                )
            info["verdict"] = "regression" if regressed else "ok"
            if info["verdict"] == "ok":
                # the roofline check runs ONLY on an ok verdict: a
                # regression/impossible finding is strictly more
                # actionable, and a no_data metric has no value to
                # judge — below_roofline can never mask or replace
                # either (test-enforced)
                roof = _roofline_check(metric, latest)
                if roof is not None:
                    info["roofline"] = roof
                    if roof["below"]:
                        info["verdict"] = "below_roofline"
                        flags.append(
                            f"BELOW ROOFLINE: latest {latest} is "
                            f"{roof['frac']:.1%} of the analytic "
                            f"{roof['bound']}-bound peak "
                            f"{roof['peak']:,.0f} on "
                            f"{roof['device_kind']} (threshold "
                            f"{roof['min_frac']:.0%}, "
                            "TPK_ROOFLINE_MIN_FRAC; non-gating "
                            "headroom signal)"
                        )
        verdicts[metric] = info
    return verdicts


def _roofline_check(metric, latest):
    """{peak, frac, bound, device_kind, min_frac, below} for a metric
    with an analytic roofline model, else None. ``below`` is False for
    documented artifact configs (VMEM-resident saxpy) no matter the
    fraction."""
    if metric not in roofline.MODELS:
        return None
    p = roofline.peak(metric)
    frac = latest / p["peak"]
    mf = roofline.min_frac()
    return {
        "peak": p["peak"],
        "frac": frac,
        "bound": p["bound"],
        "device_kind": p["device_kind"],
        "min_frac": mf,
        "below": (not p["artifact"]) and frac < mf,
    }


def analyze_copy_budget(events) -> dict:
    """Zero-copy wire-path verdicts over the ``serve_copy_budget``
    journal events ``loadgen --serve`` stamps (docs/SERVING.md §copy
    accounting). The budget is ABSOLUTE, not a time series, so only
    the latest event per (socket, lane) is judged: a run stamped
    ``expected_zero`` — the shm lane fully negotiated, every operand
    staged, every response under the threshold — that still copied
    payload bytes is a ``copy_regression`` and gates in
    ``obs_report --check`` exactly like a bench regression. Inline
    runs are ``ok`` with their per-request byte count reported: the
    inline lane is O(tensor) by construction, its budget is the lane
    choice itself."""
    latest = {}
    for e in events:
        if e.get("kind") == "serve_copy_budget":
            latest[(str(e.get("socket")), str(e.get("lane")))] = e
    verdicts = {}
    for (sock, lane), e in sorted(latest.items()):
        bpr = e.get("bytes_per_request") or 0
        # gate on the RAW delta, not the per-request rounding: a few
        # copied bytes over thousands of requests round to 0.0/req
        # but still break the zero-copy contract
        raw = e.get("daemon_bytes_copied")
        copied = raw if _is_measurement(raw) else bpr
        name = f"copy/{lane}[{os.path.basename(sock)}]"
        flags = []
        if e.get("expected_zero") and copied > 0:
            verdict = "copy_regression"
            flags.append(
                f"COPY REGRESSION: {copied} payload byte(s) copied "
                f"({bpr}/request) on a fully-negotiated shm run over "
                f"{e.get('requests')} request(s) - the zero-copy "
                "warm path is no longer zero"
            )
        else:
            verdict = "ok"
        verdicts[name] = {
            "verdict": verdict,
            "lane": lane,
            "bytes_per_request": bpr,
            "requests": e.get("requests"),
            "expected_zero": bool(e.get("expected_zero")),
            "flags": flags,
        }
    return verdicts


def analyze_trace_budget(events) -> dict:
    """Request-tracing verdicts over the ``serve_trace_budget``
    events ``loadgen --serve`` stamps (docs/OBSERVABILITY.md §request
    tracing) — the ``analyze_copy_budget`` pattern: only the latest
    event per socket is judged.

    - ``trace_inconsistent`` (GATES like a copy/bench regression): a
      clean request's accounted phases summed past the
      client-observed wall beyond the documented tolerance
      (``reqtrace.SUM_TOL``) — durations nest physically, so an
      overrun means the timeline assembly (or the span evidence
      under it) is lying, and every conclusion drawn from it would
      be too.
    - ``trace_coverage`` (non-gating, the ``below_roofline``
      pattern): timelines assembled but their accounted phases
      explain less than the documented fraction
      (``TPK_TRACE_COVERAGE_MIN``) of the client wall — the tail
      lives somewhere the spans don't reach yet.
    - ``ok`` otherwise (including runs with nothing traced: a
      journal-off daemon is a coverage hole for the REPORT to shout
      about, not a trend finding)."""
    from tpukernels.obs import reqtrace

    latest = {}
    for e in events:
        if e.get("kind") == "serve_trace_budget":
            latest[str(e.get("socket"))] = e
    verdicts = {}
    for sock, e in sorted(latest.items()):
        traced = e.get("traced") or 0
        tol = e.get("sum_tol")
        tol = tol if _is_measurement(tol) else reqtrace.SUM_TOL
        floor = e.get("coverage_floor")
        floor = (floor if _is_measurement(floor)
                 else reqtrace.DEFAULT_COVERAGE_MIN)
        srm = e.get("sum_ratio_max")
        cov = e.get("coverage_mean")
        name = f"trace[{os.path.basename(sock)}]"
        flags = []
        verdict = "ok"
        if traced and _is_measurement(srm) and srm > 1.0 + tol:
            verdict = "trace_inconsistent"
            flags.append(
                f"TRACE INCONSISTENT: accounted phases sum to "
                f"{srm}x of the client-observed wall on a clean "
                f"request (tolerance {tol:.0%}) over {traced} traced "
                "request(s) - the timeline assembly cannot be "
                "trusted"
            )
        elif traced and _is_measurement(cov) and cov < floor:
            verdict = "trace_coverage"
            flags.append(
                f"TRACE COVERAGE: accounted phases explain only "
                f"{cov:.0%} of the client-observed wall (floor "
                f"{floor:.0%}, TPK_TRACE_COVERAGE_MIN; non-gating) "
                f"over {traced} traced request(s)"
            )
        verdicts[name] = {
            "verdict": verdict,
            "requests": e.get("requests"),
            "traced": traced,
            "gaps": e.get("gaps"),
            "untraced_serve_requests":
                e.get("untraced_serve_requests"),
            "coverage_mean": cov if _is_measurement(cov) else None,
            "sum_ratio_max": srm if _is_measurement(srm) else None,
            "flags": flags,
        }
    return verdicts


# live pad_frac may exceed a promotion's measured canary pad by this
# absolute slack before the promise counts as broken: traffic drifts,
# and the verdict exists to catch a promotion that never delivered,
# not to re-litigate every shape-mix wobble
PAD_WASTE_SLACK = 0.05
PAD_WASTE_MIN_REQUESTS = 20


def analyze_pad_waste(events) -> dict:
    """Promoted-bucket-table verdicts over the journal: did the live
    traffic's pad_frac stay at the level the promotion MEASURED
    (docs/SERVING.md §adaptive buckets)? The ``analyze_copy_budget``
    pattern — only the latest ``adapt_promoted`` event is judged, and
    only against the OK ``serve_request`` evidence that postdates it.

    - ``pad_waste_regression`` (GATES like a copy/bench regression):
      the live mean pad_frac exceeds the promoted table's measured
      canary pad_frac by more than ``PAD_WASTE_SLACK`` — the
      promotion's premise (this traffic, this table, this waste) no
      longer holds, and the optimizer should re-propose.
    - ``no_data``: a promotion with fewer than
      ``PAD_WASTE_MIN_REQUESTS`` subsequent requests — drift judged
      off a handful of dispatches is an anecdote.
    - ``ok`` otherwise; no ``adapt_promoted`` event yields no verdict
      at all (an unadapted fleet has made no promise to break)."""
    promoted = None
    for e in events:
        if e.get("kind") == "adapt_promoted":
            promoted = e
    if promoted is None:
        return {}
    promised = promoted.get("pad_frac")
    if not _is_measurement(promised):
        return {}
    t0 = promoted.get("t")
    pads = []
    seen_promo = False
    for e in events:
        if e is promoted:
            seen_promo = True
            continue
        if e.get("kind") != "serve_request" or not e.get("ok"):
            continue
        t = e.get("t")
        if _is_measurement(t) and _is_measurement(t0):
            if t < t0:
                continue
        elif not seen_promo:
            continue  # no timestamps: fall back to journal order
        pads.append(float(e.get("pad_frac") or 0.0))
    name = f"pad_waste[{os.path.basename(str(promoted.get('table') or 'buckets.json'))}]"
    flags = []
    if len(pads) < PAD_WASTE_MIN_REQUESTS:
        verdict = "no_data"
        live = (sum(pads) / len(pads)) if pads else None
        flags.append(
            f"{len(pads)} request(s) since the promotion < min "
            f"{PAD_WASTE_MIN_REQUESTS} - no drift verdict yet"
        )
    else:
        live = sum(pads) / len(pads)
        if live > promised + PAD_WASTE_SLACK:
            verdict = "pad_waste_regression"
            flags.append(
                f"PAD WASTE REGRESSION: live mean pad_frac {live:.3f} "
                f"over {len(pads)} request(s) exceeds the promoted "
                f"table's measured {promised:.3f} by more than "
                f"{PAD_WASTE_SLACK} - the traffic has drifted off the "
                "promoted buckets; re-propose"
            )
        else:
            verdict = "ok"
    return {name: {
        "verdict": verdict,
        "promised_pad_frac": promised,
        "live_pad_frac": round(live, 6) if live is not None else None,
        "requests": len(pads),
        "slack": PAD_WASTE_SLACK,
        "flags": flags,
    }}


# a kernel's latest daily p99 may sit this far above the median of
# its prior days before the drift counts as creep: wider than the 1%
# eps band on purpose — daily p99s are noisier than bench medians, and
# this verdict exists to catch the slow multi-day drift the per-run
# band structurally misses, not to re-fire on single noisy days
P99_CREEP_FRAC = 0.05
P99_CREEP_MIN_DAYS = 3


def analyze_p99_creep(series) -> dict:
    """Long-horizon tail-drift verdicts over a daily rollup series
    (``tpukernels/obs/rollup.py`` :func:`load_series` output:
    ``[(date, rollup), ...]`` ascending). Per kernel, the daily p99
    comes off the rollup's ``requests`` wall-time histogram rows.

    - ``p99_creep`` (NON-GATING, the ``below_roofline`` pattern —
      ``obs_report --check`` keeps rc 0): the latest day's p99 sits
      more than ``P99_CREEP_FRAC`` above the MEDIAN of the prior
      days' p99s AND is the worst day in the window — a tail that is
      both elevated and still rising. A single mid-window spike that
      already recovered stays ``ok``: that is yesterday's incident,
      not a trend.
    - ``no_data``: fewer than ``P99_CREEP_MIN_DAYS`` days carry a p99
      for the kernel — two points are a line, not a drift.
    - ``ok`` otherwise. An empty series yields no verdicts at all."""
    by_kernel: dict = {}
    for date, r in series:
        for kernel, row in sorted((r.get("requests") or {}).items()):
            p99 = (row or {}).get("p99")
            if _is_measurement(p99) and (row or {}).get("count"):
                by_kernel.setdefault(kernel, []).append(
                    (date, float(p99), row.get("count"))
                )
    verdicts = {}
    for kernel, pts in sorted(by_kernel.items()):
        name = f"p99_creep[{kernel}]"
        flags = []
        if len(pts) < P99_CREEP_MIN_DAYS:
            verdicts[name] = {
                "verdict": "no_data",
                "days": len(pts),
                "latest": pts[-1][1],
                "baseline": None,
                "flags": [
                    f"{len(pts)} day(s) with p99 data < min "
                    f"{P99_CREEP_MIN_DAYS} - no drift verdict yet"
                ],
            }
            continue
        prior = sorted(p for _, p, _ in pts[:-1])
        mid = len(prior) // 2
        baseline = (
            prior[mid] if len(prior) % 2
            else 0.5 * (prior[mid - 1] + prior[mid])
        )
        date, latest, count = pts[-1]
        creeping = (
            baseline > 0.0
            and latest > baseline * (1.0 + P99_CREEP_FRAC)
            and latest >= max(p for _, p, _ in pts)
        )
        if creeping:
            verdict = "p99_creep"
            flags.append(
                f"P99 CREEP: {kernel} p99 {latest:.6f}s on {date} "
                f"({count} request(s)) is "
                f"{latest / baseline:.3f}x the median of the prior "
                f"{len(prior)} day(s) ({baseline:.6f}s) and the worst "
                f"day in the window (band {P99_CREEP_FRAC:.0%}; "
                "non-gating long-horizon signal)"
            )
        else:
            verdict = "ok"
        verdicts[name] = {
            "verdict": verdict,
            "days": len(pts),
            "latest": round(latest, 6),
            "latest_date": date,
            "baseline": round(baseline, 6),
            "creep_frac": P99_CREEP_FRAC,
            "flags": flags,
        }
    return verdicts


def analyze_repo(root, eps=CEILING_EPS) -> dict:
    """One-call path for tools: series + baseline + verdicts."""
    return analyze(load_series(root), load_baseline(root), eps=eps)


def analyze_scaling_repo(root, eps=CEILING_EPS) -> dict:
    """The distributed-path series families (docs/OBSERVABILITY.md
    §scaling; ``tpukernels/obs/scaling.py``): bus-bw per (op, size,
    n_devices) judged with this module's vocabulary — ``regression``
    at the same epsilon band, ``impossible`` above the analytic
    ICI ceiling (the roofline pattern), ``no_data`` for fake-only
    series — plus the non-gating weak-scaling
    ``below_scaling_efficiency`` verdict and the MULTICHIP dryrun-wall
    series. Fake-device artifacts never produce a gating verdict."""
    from tpukernels.obs import scaling

    return scaling.analyze_repo(root, eps=eps)
