"""Process-local metrics: counters, gauges, histograms.

The catalog lives in docs/OBSERVABILITY.md §metrics — per-kernel call
counts and wall-time histograms (capi dispatch), bench probe retry
counts, watchdog fires/kills, tuning-cache hits/misses/rejections.

Design constraints, in order:

1. **Recording must be allowed on the clean path.** Unlike spans
   (gated on ``TPK_TRACE``), a counter bump is a dict update with no
   I/O and no output — it cannot perturb stdout or timing at any
   observable scale, so the instrumented callsites increment
   unconditionally and the byte-identical clean-path proof still
   holds (``tests/test_obs.py``).
2. **Emission is journal-routed and survives failures.** Nothing
   leaves the process unless :func:`emit_snapshot` runs AND the
   resilience journal is enabled (``TPK_HEALTH_JOURNAL``); the
   snapshot lands as one ``metrics`` event in the same JSONL stream
   as spans and health events. An atexit hook (registered at import)
   flushes the final state of every process automatically — a bench
   child dying on a watchdog Timeout, a failing autotune sweep —
   because the failing run is exactly the one a postmortem reads.
   C hosts never finalize the interpreter, so ``capi.shutdown_from_c``
   calls :func:`emit_snapshot` explicitly (the same split the
   profiler-flush uses). Only a hard SIGKILL loses the snapshot —
   and with the periodic flusher below enabled, at most one flush
   interval of it.
3. **Histograms are streaming: summaries plus log buckets.** Each
   histogram keeps count/sum/min/max (mean derivable) AND a
   log-bucketed distribution (base 2^(1/4) ≈ 19%-wide buckets — one
   shared boundary scheme, so two runs observing the same values
   produce IDENTICAL buckets, the loadgen determinism contract).
   Snapshots surface the exact max and count-weighted p50/p95/p99
   derived from the buckets, so consumers (``tools/health_report.py``,
   ``tpukernels/obs/slo.py``'s latency-SLO verdicts) read percentiles
   without re-deriving bucket arithmetic. Memory stays bounded: a
   bucket per occupied power-of-2^(1/4), never a sample list.
4. **Live streaming is opt-in and delta-encoded.** With
   ``TPK_METRICS_FLUSH_S`` set (default OFF — the TPK_TRACE opt-in
   pattern, clean-path stdout stays byte-identical either way), a
   daemon flusher thread emits one ``metrics_snapshot`` journal event
   per interval: a monotonic per-process ``seq``, counter DELTAS
   since the previous flush (zero deltas omitted), full gauges, and
   only the histogram rows whose count moved (each emitted row is
   full-cumulative, so the latest row per name stands alone). The
   atexit ``metrics`` event stays the final authoritative FULL
   snapshot; consumers must dedupe by (pid, seq), fold snapshot
   deltas in seq order, and let a final ``metrics`` event supersede
   the folds entirely — never sum the two.
   :func:`merge_journal_metrics` is the one shared reconstruction
   every reader uses (docs/OBSERVABILITY.md §live telemetry).

State is per-process (bench ``--one`` children snapshot their own)
and THREAD-SAFE: a single module lock guards every record/snapshot,
because the serve daemon's worker threads (docs/SERVING.md) bump the
same counters concurrently and a ``get + set`` race would silently
lose increments the tests assert on. The lock is uncontended on
every single-threaded path, so the clean-path cost stays a dict
update; :func:`reset` exists for tests.
"""

from __future__ import annotations

import math
import os
import sys
import threading
import time

from tpukernels.resilience import journal

_LOCK = threading.Lock()
_COUNTERS: dict = {}
_GAUGES: dict = {}
_HISTS: dict = {}  # name -> [count, sum, min, max, {bucket: count}]

# log-bucket geometry: index = floor(log(v) / log(2^(1/4))), i.e. four
# buckets per octave (~19% relative width — tight enough that a p99
# read off a bucket's upper bound is honest, coarse enough that a
# long-lived histogram stays tens of buckets). Non-positive samples
# (clock skew could in principle produce a 0.0 wall) collapse into one
# sentinel bucket whose upper bound is 0.
_BUCKET_LOG = math.log(2.0) / 4.0
_NONPOS_BUCKET = -(1 << 30)


def bucket_index(value: float) -> int:
    """The shared log-bucket index of one sample — exposed so tests
    and the SLO layer agree with the recorder on boundaries."""
    if value <= 0.0:
        return _NONPOS_BUCKET
    return math.floor(math.log(value) / _BUCKET_LOG)


def bucket_upper(idx: int) -> float:
    """Upper value bound of bucket ``idx`` (0.0 for the non-positive
    sentinel) — what a count-weighted percentile reports."""
    if idx == _NONPOS_BUCKET:
        return 0.0
    return math.exp((idx + 1) * _BUCKET_LOG)


def percentiles(count: int, max_value: float, buckets: dict,
                qs=(0.5, 0.95, 0.99)) -> list:
    """Count-weighted percentiles from a log-bucket dict: the value of
    quantile ``q`` is the upper bound of the bucket holding the
    ceil(q*count)-th sample, clamped to the EXACT observed max (so
    p99 of a 10-sample histogram never exceeds its real worst case).
    Bucket keys may be ints or their str() twins (a snapshot that was
    through JSON)."""
    items = sorted((int(k), v) for k, v in buckets.items())
    out = []
    for q in qs:
        rank = max(1, math.ceil(q * count))
        val = max_value
        cum = 0
        for idx, c in items:
            cum += c
            if cum >= rank:
                val = min(bucket_upper(idx), max_value)
                break
        out.append(val)
    return out


def inc(name: str, n: float = 1):
    """Add ``n`` (default 1) to counter ``name``, creating it at 0."""
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def gauge(name: str, value: float):
    """Set gauge ``name`` to ``value`` (last write wins)."""
    with _LOCK:
        _GAUGES[name] = value


def observe(name: str, value: float):
    """Record one sample into histogram ``name``."""
    with _LOCK:
        h = _HISTS.get(name)
        if h is None:
            _HISTS[name] = [1, value, value, value,
                            {bucket_index(value): 1}]
        else:
            h[0] += 1
            h[1] += value
            if value < h[2]:
                h[2] = value
            if value > h[3]:
                h[3] = value
            b = bucket_index(value)
            h[4][b] = h[4].get(b, 0) + 1


def _hist_row(v) -> dict:
    p50, p95, p99 = percentiles(v[0], v[3], v[4])
    return {
        "count": v[0],
        "sum": round(v[1], 6),
        "min": round(v[2], 6),
        "max": round(v[3], 6),
        "p50": round(p50, 6),
        "p95": round(p95, 6),
        "p99": round(p99, 6),
        # str keys: the snapshot rides a JSON journal event, and a
        # round-tripped consumer must read the same dict shape the
        # in-process one does
        "buckets": {str(i): c for i, c in sorted(v[4].items())},
    }


def snapshot() -> dict:
    """Copy of the current state: ``{"counters": {...}, "gauges":
    {...}, "histograms": {name: {count, sum, min, max, p50, p95, p99,
    buckets}}}`` — max is exact, p50/p95/p99 are count-weighted from
    the log buckets (clamped to max)."""
    with _LOCK:
        return {
            "counters": dict(_COUNTERS),
            "gauges": dict(_GAUGES),
            "histograms": {k: _hist_row(v) for k, v in _HISTS.items()},
        }


def emit_snapshot(site: str | None = None):
    """Emit one ``metrics`` journal event holding the full snapshot.
    No-op when nothing was recorded or journaling is off — a library
    import must never create a journal file just by exiting."""
    if not (_COUNTERS or _GAUGES or _HISTS):
        return
    if not journal.enabled():
        return
    journal.emit("metrics", site=site, **snapshot())


# --- periodic snapshot flusher (docstring item 4) -----------------
#
# All flusher bookkeeping lives under the same _LOCK as the recorders:
# _SEQ is the per-process monotonic snapshot sequence, _FLUSH_COUNTERS
# holds counter values as of the last flush (deltas are computed
# against it), _FLUSH_HIST_COUNTS holds each histogram's count at the
# last flush (a row is re-emitted only when its count moved), and
# _LAST_FLUSH is the wall time of the last successful flush — the
# stats op turns it into last_snapshot_age_s so a dead flusher thread
# is visible before metrics silently go stale.

_SEQ = 0
_FLUSH_COUNTERS: dict = {}
_FLUSH_HIST_COUNTS: dict = {}
_LAST_FLUSH: list = []  # [] = never flushed; [t_wall] otherwise
_FLUSHER: threading.Thread | None = None
_FLUSHER_STOP = threading.Event()


def flush_interval_s(env=None) -> float | None:
    """Parse ``TPK_METRICS_FLUSH_S``: ``None`` (flusher off) when the
    knob is unset, empty, or one of 0/off/none/false; otherwise the
    interval in seconds. Anything else — a typo'd value, a negative
    interval — raises ValueError naming the knob, the fail-loud knob
    contract (docs/KNOBS.md): a daemon started with a broken telemetry
    config must refuse to start, not silently serve blind."""
    target = os.environ if env is None else env
    raw = target.get("TPK_METRICS_FLUSH_S")
    if raw is None or not raw.strip():
        return None
    if raw.strip().lower() in ("0", "off", "none", "false"):
        return None
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if not val > 0.0:
        raise ValueError(
            f"TPK_METRICS_FLUSH_S={raw!r}: expected a positive number"
            " of seconds, or 0/off/none/false to disable"
        )
    return val


def emit_periodic_snapshot(site: str | None = None) -> int | None:
    """Emit one delta-encoded ``metrics_snapshot`` journal event and
    return its seq (None when skipped: journaling off or nothing ever
    recorded). Counter values are DELTAS since the previous snapshot
    (zero deltas omitted); gauges are full (last-write-wins already);
    histogram rows are emitted only when their count moved since the
    last flush, but each emitted row is the full cumulative row — the
    latest row per name stands alone, no fold needed."""
    global _SEQ
    if not journal.enabled():
        return None
    with _LOCK:
        if not (_COUNTERS or _GAUGES or _HISTS):
            return None
        deltas = {}
        for k, v in _COUNTERS.items():
            d = v - _FLUSH_COUNTERS.get(k, 0)
            if d:
                deltas[k] = d
        hists = {
            k: _hist_row(v)
            for k, v in _HISTS.items()
            if v[0] != _FLUSH_HIST_COUNTS.get(k)
        }
        gauges = dict(_GAUGES)
        _SEQ += 1
        seq = _SEQ
        _FLUSH_COUNTERS.clear()
        _FLUSH_COUNTERS.update(_COUNTERS)
        _FLUSH_HIST_COUNTS.clear()
        _FLUSH_HIST_COUNTS.update({k: v[0] for k, v in _HISTS.items()})
        _LAST_FLUSH[:] = [time.time()]
    journal.emit(
        "metrics_snapshot",
        seq=seq,
        site=site,
        counters=deltas,
        gauges=gauges,
        histograms=hists,
    )
    return seq


def last_flush_age_s() -> float | None:
    """Seconds since the last periodic snapshot, None when the flusher
    never flushed (off, or nothing recorded yet). A daemon whose value
    keeps growing past its flush interval has a dead flusher thread."""
    with _LOCK:
        if not _LAST_FLUSH:
            return None
        return max(0.0, time.time() - _LAST_FLUSH[0])


def _flusher_loop(interval_s: float):
    site = "flush:" + os.path.basename(sys.argv[0] or "?")
    # No blanket except: journal.emit never raises by contract, so an
    # exception here is a real bug — letting it kill the thread is what
    # makes last_snapshot_age_s an honest liveness signal.
    while not _FLUSHER_STOP.wait(interval_s):
        emit_periodic_snapshot(site=site)


def start_flusher(interval_s: float | None = None) -> bool:
    """Start the periodic flusher thread (idempotent). With no
    argument the interval comes from TPK_METRICS_FLUSH_S; returns
    False (no thread) when the knob is off."""
    global _FLUSHER
    if interval_s is None:
        interval_s = flush_interval_s()
    if interval_s is None:
        return False
    if _FLUSHER is not None and _FLUSHER.is_alive():
        return True
    _FLUSHER_STOP.clear()
    t = threading.Thread(
        target=_flusher_loop,
        args=(interval_s,),
        daemon=True,
        name="tpk-metrics-flusher",
    )
    _FLUSHER = t
    t.start()
    return True


def stop_flusher():
    """Stop the flusher thread if running (tests, clean shutdown)."""
    global _FLUSHER
    t = _FLUSHER
    _FLUSHER = None
    if t is not None and t.is_alive():
        _FLUSHER_STOP.set()
        t.join(timeout=5.0)
    _FLUSHER_STOP.clear()


def merge_journal_metrics(events) -> dict:
    """The one shared reconstruction of per-process metric state from
    journal events, fixing the snapshot/atexit double-count seam:

    - a pid with a full ``metrics`` event (atexit or explicit flush)
      uses its LATEST such event outright — snapshots never add to it;
    - otherwise ``metrics_snapshot`` events are deduped by (pid, seq)
      and folded in seq order: counter deltas summed, gauges and
      histogram rows latest-seq-wins per name.

    Returns ``{pid: {"counters", "gauges", "histograms", "site",
    "seq", "final", "t", "ts"}}`` where ``final`` says whether the pid
    ended with an authoritative full snapshot and ``seq`` is the
    highest snapshot sequence seen (None when only ``metrics``)."""
    finals: dict = {}
    snaps: dict = {}
    for e in events:
        kind = e.get("kind")
        if kind == "metrics":
            finals[e.get("pid")] = e
        elif kind == "metrics_snapshot":
            seq = e.get("seq")
            if isinstance(seq, int):
                snaps.setdefault(e.get("pid"), {})[seq] = e
    out: dict = {}
    for pid, by_seq in snaps.items():
        if pid in finals:
            continue
        counters: dict = {}
        gauges: dict = {}
        hists: dict = {}
        last = None
        for seq in sorted(by_seq):
            e = by_seq[seq]
            for k, d in (e.get("counters") or {}).items():
                if isinstance(d, (int, float)):
                    counters[k] = counters.get(k, 0) + d
            for k, v in (e.get("gauges") or {}).items():
                gauges[k] = v
            for k, row in (e.get("histograms") or {}).items():
                if isinstance(row, dict):
                    hists[k] = row
            last = e
        out[pid] = {
            "counters": counters,
            "gauges": gauges,
            "histograms": hists,
            "site": last.get("site"),
            "seq": max(by_seq),
            "final": False,
            "t": last.get("t"),
            "ts": last.get("ts"),
        }
    for pid, e in finals.items():
        seqs = snaps.get(pid)
        out[pid] = {
            "counters": dict(e.get("counters") or {}),
            "gauges": dict(e.get("gauges") or {}),
            "histograms": dict(e.get("histograms") or {}),
            "site": e.get("site"),
            "seq": max(seqs) if seqs else None,
            "final": True,
            "t": e.get("t"),
            "ts": e.get("ts"),
        }
    return out


def reset():
    """Drop all recorded state (tests; never called on real paths)."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTS.clear()
        _FLUSH_COUNTERS.clear()
        _FLUSH_HIST_COUNTS.clear()
        _LAST_FLUSH.clear()
        global _SEQ
        _SEQ = 0


def _atexit_flush():
    import os
    import sys

    emit_snapshot(
        site="atexit:" + os.path.basename(sys.argv[0] or "?")
    )


import atexit  # noqa: E402 — placed with its registration on purpose

atexit.register(_atexit_flush)

# Opt-in streaming: started at import so ANY process that records
# metrics (daemon, router, bench child, loadgen) streams snapshots
# under TPK_METRICS_FLUSH_S without per-callsite wiring. Default off;
# a malformed knob value raises HERE, at import — the fail-loud knob
# contract means a process with a broken telemetry config refuses to
# run rather than serving blind.
if flush_interval_s() is not None:
    start_flusher()
