"""Process-local metrics: counters, gauges, histograms.

The catalog lives in docs/OBSERVABILITY.md §metrics — per-kernel call
counts and wall-time histograms (capi dispatch), bench probe retry
counts, watchdog fires/kills, tuning-cache hits/misses/rejections.

Design constraints, in order:

1. **Recording must be allowed on the clean path.** Unlike spans
   (gated on ``TPK_TRACE``), a counter bump is a dict update with no
   I/O and no output — it cannot perturb stdout or timing at any
   observable scale, so the instrumented callsites increment
   unconditionally and the byte-identical clean-path proof still
   holds (``tests/test_obs.py``).
2. **Emission is journal-routed and survives failures.** Nothing
   leaves the process unless :func:`emit_snapshot` runs AND the
   resilience journal is enabled (``TPK_HEALTH_JOURNAL``); the
   snapshot lands as one ``metrics`` event in the same JSONL stream
   as spans and health events. An atexit hook (registered at import)
   flushes the final state of every process automatically — a bench
   child dying on a watchdog Timeout, a failing autotune sweep —
   because the failing run is exactly the one a postmortem reads.
   C hosts never finalize the interpreter, so ``capi.shutdown_from_c``
   calls :func:`emit_snapshot` explicitly (the same split the
   profiler-flush uses). Only a hard SIGKILL loses the snapshot.
3. **Histograms are summaries, not buckets.** count/sum/min/max per
   name (mean derivable) — enough for "where did the wall time go"
   without inventing bucket boundaries per metric.

State is per-process (bench ``--one`` children snapshot their own);
:func:`reset` exists for tests.
"""

from __future__ import annotations

from tpukernels.resilience import journal

_COUNTERS: dict = {}
_GAUGES: dict = {}
_HISTS: dict = {}  # name -> [count, sum, min, max]


def inc(name: str, n: float = 1):
    """Add ``n`` (default 1) to counter ``name``, creating it at 0."""
    _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def gauge(name: str, value: float):
    """Set gauge ``name`` to ``value`` (last write wins)."""
    _GAUGES[name] = value


def observe(name: str, value: float):
    """Record one sample into histogram ``name``."""
    h = _HISTS.get(name)
    if h is None:
        _HISTS[name] = [1, value, value, value]
    else:
        h[0] += 1
        h[1] += value
        if value < h[2]:
            h[2] = value
        if value > h[3]:
            h[3] = value


def snapshot() -> dict:
    """Copy of the current state: ``{"counters": {...}, "gauges":
    {...}, "histograms": {name: {count, sum, min, max}}}``."""
    return {
        "counters": dict(_COUNTERS),
        "gauges": dict(_GAUGES),
        "histograms": {
            k: {
                "count": v[0],
                "sum": round(v[1], 6),
                "min": round(v[2], 6),
                "max": round(v[3], 6),
            }
            for k, v in _HISTS.items()
        },
    }


def emit_snapshot(site: str | None = None):
    """Emit one ``metrics`` journal event holding the full snapshot.
    No-op when nothing was recorded or journaling is off — a library
    import must never create a journal file just by exiting."""
    if not (_COUNTERS or _GAUGES or _HISTS):
        return
    if not journal.enabled():
        return
    journal.emit("metrics", site=site, **snapshot())


def reset():
    """Drop all recorded state (tests; never called on real paths)."""
    _COUNTERS.clear()
    _GAUGES.clear()
    _HISTS.clear()


def _atexit_flush():
    import os
    import sys

    emit_snapshot(
        site="atexit:" + os.path.basename(sys.argv[0] or "?")
    )


import atexit  # noqa: E402 — placed with its registration on purpose

atexit.register(_atexit_flush)
