"""Process-local metrics: counters, gauges, histograms.

The catalog lives in docs/OBSERVABILITY.md §metrics — per-kernel call
counts and wall-time histograms (capi dispatch), bench probe retry
counts, watchdog fires/kills, tuning-cache hits/misses/rejections.

Design constraints, in order:

1. **Recording must be allowed on the clean path.** Unlike spans
   (gated on ``TPK_TRACE``), a counter bump is a dict update with no
   I/O and no output — it cannot perturb stdout or timing at any
   observable scale, so the instrumented callsites increment
   unconditionally and the byte-identical clean-path proof still
   holds (``tests/test_obs.py``).
2. **Emission is journal-routed and survives failures.** Nothing
   leaves the process unless :func:`emit_snapshot` runs AND the
   resilience journal is enabled (``TPK_HEALTH_JOURNAL``); the
   snapshot lands as one ``metrics`` event in the same JSONL stream
   as spans and health events. An atexit hook (registered at import)
   flushes the final state of every process automatically — a bench
   child dying on a watchdog Timeout, a failing autotune sweep —
   because the failing run is exactly the one a postmortem reads.
   C hosts never finalize the interpreter, so ``capi.shutdown_from_c``
   calls :func:`emit_snapshot` explicitly (the same split the
   profiler-flush uses). Only a hard SIGKILL loses the snapshot.
3. **Histograms are streaming: summaries plus log buckets.** Each
   histogram keeps count/sum/min/max (mean derivable) AND a
   log-bucketed distribution (base 2^(1/4) ≈ 19%-wide buckets — one
   shared boundary scheme, so two runs observing the same values
   produce IDENTICAL buckets, the loadgen determinism contract).
   Snapshots surface the exact max and count-weighted p50/p95/p99
   derived from the buckets, so consumers (``tools/health_report.py``,
   ``tpukernels/obs/slo.py``'s latency-SLO verdicts) read percentiles
   without re-deriving bucket arithmetic. Memory stays bounded: a
   bucket per occupied power-of-2^(1/4), never a sample list.

State is per-process (bench ``--one`` children snapshot their own)
and THREAD-SAFE: a single module lock guards every record/snapshot,
because the serve daemon's worker threads (docs/SERVING.md) bump the
same counters concurrently and a ``get + set`` race would silently
lose increments the tests assert on. The lock is uncontended on
every single-threaded path, so the clean-path cost stays a dict
update; :func:`reset` exists for tests.
"""

from __future__ import annotations

import math
import threading

from tpukernels.resilience import journal

_LOCK = threading.Lock()
_COUNTERS: dict = {}
_GAUGES: dict = {}
_HISTS: dict = {}  # name -> [count, sum, min, max, {bucket: count}]

# log-bucket geometry: index = floor(log(v) / log(2^(1/4))), i.e. four
# buckets per octave (~19% relative width — tight enough that a p99
# read off a bucket's upper bound is honest, coarse enough that a
# long-lived histogram stays tens of buckets). Non-positive samples
# (clock skew could in principle produce a 0.0 wall) collapse into one
# sentinel bucket whose upper bound is 0.
_BUCKET_LOG = math.log(2.0) / 4.0
_NONPOS_BUCKET = -(1 << 30)


def bucket_index(value: float) -> int:
    """The shared log-bucket index of one sample — exposed so tests
    and the SLO layer agree with the recorder on boundaries."""
    if value <= 0.0:
        return _NONPOS_BUCKET
    return math.floor(math.log(value) / _BUCKET_LOG)


def bucket_upper(idx: int) -> float:
    """Upper value bound of bucket ``idx`` (0.0 for the non-positive
    sentinel) — what a count-weighted percentile reports."""
    if idx == _NONPOS_BUCKET:
        return 0.0
    return math.exp((idx + 1) * _BUCKET_LOG)


def percentiles(count: int, max_value: float, buckets: dict,
                qs=(0.5, 0.95, 0.99)) -> list:
    """Count-weighted percentiles from a log-bucket dict: the value of
    quantile ``q`` is the upper bound of the bucket holding the
    ceil(q*count)-th sample, clamped to the EXACT observed max (so
    p99 of a 10-sample histogram never exceeds its real worst case).
    Bucket keys may be ints or their str() twins (a snapshot that was
    through JSON)."""
    items = sorted((int(k), v) for k, v in buckets.items())
    out = []
    for q in qs:
        rank = max(1, math.ceil(q * count))
        val = max_value
        cum = 0
        for idx, c in items:
            cum += c
            if cum >= rank:
                val = min(bucket_upper(idx), max_value)
                break
        out.append(val)
    return out


def inc(name: str, n: float = 1):
    """Add ``n`` (default 1) to counter ``name``, creating it at 0."""
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + n


def gauge(name: str, value: float):
    """Set gauge ``name`` to ``value`` (last write wins)."""
    with _LOCK:
        _GAUGES[name] = value


def observe(name: str, value: float):
    """Record one sample into histogram ``name``."""
    with _LOCK:
        h = _HISTS.get(name)
        if h is None:
            _HISTS[name] = [1, value, value, value,
                            {bucket_index(value): 1}]
        else:
            h[0] += 1
            h[1] += value
            if value < h[2]:
                h[2] = value
            if value > h[3]:
                h[3] = value
            b = bucket_index(value)
            h[4][b] = h[4].get(b, 0) + 1


def _hist_row(v) -> dict:
    p50, p95, p99 = percentiles(v[0], v[3], v[4])
    return {
        "count": v[0],
        "sum": round(v[1], 6),
        "min": round(v[2], 6),
        "max": round(v[3], 6),
        "p50": round(p50, 6),
        "p95": round(p95, 6),
        "p99": round(p99, 6),
        # str keys: the snapshot rides a JSON journal event, and a
        # round-tripped consumer must read the same dict shape the
        # in-process one does
        "buckets": {str(i): c for i, c in sorted(v[4].items())},
    }


def snapshot() -> dict:
    """Copy of the current state: ``{"counters": {...}, "gauges":
    {...}, "histograms": {name: {count, sum, min, max, p50, p95, p99,
    buckets}}}`` — max is exact, p50/p95/p99 are count-weighted from
    the log buckets (clamped to max)."""
    with _LOCK:
        return {
            "counters": dict(_COUNTERS),
            "gauges": dict(_GAUGES),
            "histograms": {k: _hist_row(v) for k, v in _HISTS.items()},
        }


def emit_snapshot(site: str | None = None):
    """Emit one ``metrics`` journal event holding the full snapshot.
    No-op when nothing was recorded or journaling is off — a library
    import must never create a journal file just by exiting."""
    if not (_COUNTERS or _GAUGES or _HISTS):
        return
    if not journal.enabled():
        return
    journal.emit("metrics", site=site, **snapshot())


def reset():
    """Drop all recorded state (tests; never called on real paths)."""
    with _LOCK:
        _COUNTERS.clear()
        _GAUGES.clear()
        _HISTS.clear()


def _atexit_flush():
    import os
    import sys

    emit_snapshot(
        site="atexit:" + os.path.basename(sys.argv[0] or "?")
    )


import atexit  # noqa: E402 — placed with its registration on purpose

atexit.register(_atexit_flush)
