"""Daily journal rollups: long-horizon series artifacts
(docs/OBSERVABILITY.md §live telemetry; ROADMAP item 5's multi-day
headroom).

The journal is the repo's evidence stream, but it is per-day and
per-run: ``health_<date>.jsonl`` files grow unboundedly detailed and
the verdict layer only ever reads tails. Long-horizon questions —
"has sgemm's p99 crept 8% over a week?", "what shape mix should the
bucket optimizer mine when today had no traffic?" — need a compact,
validated series. This module compacts ONE day's journal files into
one ``rollup_<date>.json`` artifact holding exactly what the
long-horizon consumers read:

- ``counters``: fleet-total metric counters, reconstructed per pid by
  :func:`tpukernels.obs.metrics.merge_journal_metrics` (snapshots
  deduped by (pid, seq), atexit events authoritative — the rollup
  inherits the double-count fix, it does not re-implement it);
- ``requests``: per-kernel wall-time histograms over OK
  ``serve_request`` events, in the metrics module's shared log-bucket
  geometry so rows MERGE with live histograms and feed the same
  ``percentiles`` arithmetic;
- ``shape_mix``: :func:`tpukernels.serve.adapt.shape_mix` rows, so
  the optimizer mines yesterday from 20 lines of rollup instead of
  200k lines of journal;
- ``kinds``: an event-kind census (cheap forensics: "how many
  watchdog kills last Tuesday?").

Discipline is the tuning/aot/slo artifact contract: atomic write
(:func:`tpukernels.resilience.atomic.dump_json`), stamped with the
jax version and the newest commit sha touching :data:`SOURCES`,
validated at read, and a stale/torn/malformed artifact is LOUDLY
rejected (stderr + ``rollup_rejected`` journal event, once per
process per cause) — a week-old rollup written by last week's mining
code must not silently steer today's bucket table. The artifact body
is deliberately TIMESTAMP-FREE: rolling up the same journal twice
yields byte-identical files, so the daily supervisor step
(``rollup_daily``) is idempotent and a changed rollup always means
changed evidence.

Consumers: ``tools/obs_report.py`` (the ``p99_creep`` trend verdict
over :func:`load_series`), ``tools/serve_optimize.py`` (multi-day
mining under ``TPK_ADAPT_WINDOW_DAYS``), and humans. Writer: the
``python -m tpukernels.obs.rollup`` CLI, run daily and non-gating by
the supervisor, with :data:`RETENTION_DAYS` pruning.

Rollups live in ``TPK_ROLLUP_DIR`` (default ``docs/logs``, beside
the journals they compact — the TPK_SCALING_DIR series-artifact
convention, not the cache-dir one: rollups are evidence, not cache).
"""

from __future__ import annotations

import glob
import os
import re
import sys

from tpukernels import _cachedir
from tpukernels.obs import metrics as obs_metrics
from tpukernels.resilience import journal

SCHEMA = 1
# pruned by the daily CLI: long enough for quarterly forensics, short
# enough that docs/logs never becomes an unbounded artifact graveyard
RETENTION_DAYS = 90

# sources whose newer commit invalidates a persisted rollup: the
# compactor itself, the histogram/merge arithmetic the aggregates
# depend on, and the miner whose shape_mix rows the artifact stores
SOURCES = (
    "tpukernels/obs/rollup.py",
    "tpukernels/obs/metrics.py",
    "tpukernels/serve/adapt.py",
)

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_DATE_RE = re.compile(r"health_(\d{4}-\d{2}-\d{2})\.jsonl$")
_ROLLUP_RE = re.compile(r"rollup_(\d{4}-\d{2}-\d{2})\.json$")

_MEMO: dict = {}
_REJECT_NOTED: set = set()


def reset():
    """Drop per-process state (tests)."""
    _MEMO.clear()
    _REJECT_NOTED.clear()


def rollup_dir(env=None) -> str:
    """``TPK_ROLLUP_DIR`` (re-read per call, the _cachedir dir-helper
    convention), defaulting to the repo's ``docs/logs`` — rollups are
    series evidence and live beside the journals they compact."""
    target = os.environ if env is None else env
    d = target.get("TPK_ROLLUP_DIR")
    if d:
        return d
    return os.path.join(_REPO, "docs", "logs")


def rollup_path(date_str: str, env=None) -> str:
    return os.path.join(rollup_dir(env), f"rollup_{date_str}.json")


def journal_dir() -> str:
    """The directory holding dated journal files: wherever the live
    journal resolves (or would resolve) to."""
    return os.path.dirname(journal.path() or journal.default_path())


def journal_dates() -> dict:
    """``{date: [paths]}`` of dated journal files present on disk,
    sorted ascending by date."""
    out: dict = {}
    for p in sorted(glob.glob(os.path.join(journal_dir(),
                                           "health_*.jsonl"))):
        m = _DATE_RE.search(os.path.basename(p))
        if m:
            out.setdefault(m.group(1), []).append(p)
    return dict(sorted(out.items()))


def _jax_version():
    import jax  # lazy: keeps this module importable without jax

    return jax.__version__


def build(date_str: str, events, bad_lines: int = 0) -> dict:
    """The rollup artifact body for one day's events — pure and
    TIMESTAMP-FREE: same events in, byte-identical JSON out."""
    from tpukernels.serve import adapt
    from tpukernels.tuning import cache as tcache

    kinds: dict = {}
    for e in events:
        k = e.get("kind")
        if isinstance(k, str):
            kinds[k] = kinds.get(k, 0) + 1

    merged = obs_metrics.merge_journal_metrics(events)
    counters: dict = {}
    for state in merged.values():
        for name, v in state["counters"].items():
            if isinstance(v, (int, float)):
                counters[name] = counters.get(name, 0) + v

    hists: dict = {}
    for e in events:
        if e.get("kind") != "serve_request" or not e.get("ok"):
            continue
        kernel = e.get("kernel")
        w = e.get("wall_s")
        if not kernel or not isinstance(w, (int, float)):
            continue
        h = hists.get(kernel)
        if h is None:
            hists[kernel] = [1, float(w), float(w), float(w),
                             {obs_metrics.bucket_index(w): 1}]
        else:
            h[0] += 1
            h[1] += float(w)
            h[2] = min(h[2], float(w))
            h[3] = max(h[3], float(w))
            b = obs_metrics.bucket_index(w)
            h[4][b] = h[4].get(b, 0) + 1
    requests = {
        k: obs_metrics._hist_row(v) for k, v in sorted(hists.items())
    }

    mix = adapt.shape_mix(events)

    return {
        "schema": SCHEMA,
        "date": date_str,
        "jax": _jax_version(),
        "source_sha": tcache.source_sha(SOURCES),
        "git_head": journal.git_head(),
        "events": len(events),
        "bad_lines": bad_lines,
        "pids": len(merged),
        "kinds": kinds,
        "counters": counters,
        "requests": requests,
        "shape_mix": mix,
    }


def write_day(date_str: str, paths=None) -> str | None:
    """Compact one day's journal files into ``rollup_<date>.json``
    (atomic, ``rollup_written`` journal event). Returns the path, or
    None when the day has no events to roll up."""
    if paths is None:
        paths = journal_dates().get(date_str, [])
    events, bad = journal.load_events(paths)
    if not events:
        return None
    art = build(date_str, events, bad_lines=bad)
    p = rollup_path(date_str)
    d = os.path.dirname(p)
    if d:
        os.makedirs(d, exist_ok=True)
    from tpukernels.resilience import atomic

    atomic.dump_json(p, art)
    _MEMO.pop(p, None)
    journal.emit(
        "rollup_written", path=p, date=date_str,
        events=len(events), bad_lines=bad,
        kernels=sorted(art["requests"]),
        requests=sum(r["count"] for r in art["requests"].values()),
    )
    return p


def _reject(p: str, reason: str, **fields):
    """Loud-rejection contract shared with tuning/aot/slo/adapt:
    stderr note + ``rollup_rejected`` journal event, once per process
    per (path, cause)."""
    memo = (p, reason)
    if memo in _REJECT_NOTED:
        return
    _REJECT_NOTED.add(memo)
    print(f"# rollup rejected: {os.path.basename(p)}: {reason}",
          file=sys.stderr)
    journal.emit("rollup_rejected", path=p, reason=reason, **fields)


def load_day(date_str: str, validate: bool = True):
    """The validated rollup for one date, or None. A torn file reads
    as absent via the shared tolerant reader and is rejected loudly
    here (the reader's own ``artifact_rejected`` note fires too); a
    rollup written under a different jax version, or predating a
    commit to :data:`SOURCES`, is stale — yesterday compacted by last
    month's mining code must not steer today's bucket table."""
    p = rollup_path(date_str)
    data = _cachedir.read_json_memoized(p, _MEMO)
    if not data:
        if os.path.exists(p):
            _reject(p, "torn or empty")
        return None
    if data.get("schema") != SCHEMA:
        _reject(p, f"schema {data.get('schema')!r}, expected {SCHEMA}")
        return None
    if data.get("date") != date_str:
        _reject(p, f"date {data.get('date')!r} does not match filename")
        return None
    if not validate:
        return data
    if data.get("jax") != _jax_version():
        _reject(
            p,
            f"written under jax {data.get('jax')}, "
            f"running {_jax_version()}",
        )
        return None
    from tpukernels.tuning import cache as tcache

    sha = tcache.source_sha(SOURCES)
    if sha is not None and data.get("source_sha") not in (None, sha):
        _reject(
            p,
            "stale: a commit touching " + ",".join(SOURCES)
            + " postdates this rollup",
            entry_sha=data.get("source_sha"), current_sha=sha,
        )
        return None
    return data


def rollup_dates() -> list:
    """Dates (ascending) with a rollup artifact on disk — validity
    checked only at :func:`load_day` time."""
    out = []
    for p in sorted(glob.glob(os.path.join(rollup_dir(),
                                           "rollup_*.json"))):
        m = _ROLLUP_RE.search(os.path.basename(p))
        if m:
            out.append(m.group(1))
    return out


def load_series(days: int | None = None, end_date: str | None = None,
                validate: bool = True) -> list:
    """``[(date, rollup), ...]`` ascending over the validated rollups
    on disk — at most the last ``days`` dates, excluding any after
    ``end_date``. Invalid artifacts are rejected (loudly, by
    :func:`load_day`) and skipped, never silently substituted."""
    dates = rollup_dates()
    if end_date is not None:
        dates = [d for d in dates if d <= end_date]
    if days is not None:
        dates = dates[-days:]
    out = []
    for d in dates:
        data = load_day(d, validate=validate)
        if data is not None:
            out.append((d, data))
    return out


def prune(retention_days: int = RETENTION_DAYS,
          today: str | None = None) -> list:
    """Unlink rollups older than ``retention_days`` (by filename
    date, lexicographic — ISO dates sort). Returns pruned paths."""
    if today is None:
        import datetime

        today = datetime.date.today().isoformat()
    import datetime

    cutoff = (
        datetime.date.fromisoformat(today)
        - datetime.timedelta(days=retention_days)
    ).isoformat()
    pruned = []
    for d in rollup_dates():
        if d < cutoff:
            p = rollup_path(d)
            try:
                os.unlink(p)
            except OSError:
                continue
            _MEMO.pop(p, None)
            pruned.append(p)
    return pruned


def main(argv=None) -> int:
    """``python -m tpukernels.obs.rollup [--date YYYY-MM-DD]``:
    compact every dated journal present (or one date) into its rollup
    and prune past retention. Idempotent and deterministic — the
    daily supervisor step reruns it freely."""
    argv = list(sys.argv[1:] if argv is None else argv)
    date = None
    while argv:
        a = argv.pop(0)
        if a == "--date" and argv:
            date = argv.pop(0)
        else:
            print(f"usage: rollup [--date YYYY-MM-DD]  (got {a!r})",
                  file=sys.stderr)
            return 2
    by_date = journal_dates()
    if date is not None:
        by_date = {date: by_date.get(date, [])}
    wrote = 0
    for d, paths in by_date.items():
        p = write_day(d, paths)
        if p:
            wrote += 1
            print(f"rollup: {p}")
        else:
            print(f"rollup: {d}: no events, skipped")
    for p in prune():
        print(f"rollup: pruned {p}")
    print(f"rollup: {wrote} day(s) written, "
          f"{len(by_date) - wrote} skipped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
