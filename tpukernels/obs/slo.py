"""Per-kernel latency-SLO targets + the persisted verdict artifact
(docs/OBSERVABILITY.md §latency SLOs).

Every number the stack observed before this module was steady-state
slope throughput; a service for millions of users is judged on
per-request latency under bursty arrivals — queueing, compile leaks
and cache eviction all hide behind a healthy slope and all show up in
p99. This module is the judging half of the latency-SLO layer
(``tools/loadgen.py`` is the measuring half):

- :data:`TARGETS` — per-kernel p99 wall-time targets, stated per
  ``device_kind|shape_class`` row exactly the way the roofline model
  states peaks per device kind (``tuning/roofline.py`` is the
  sibling table). The evidence rows of record are ``tpu_v5_lite|
  record`` (the BENCH_CONFIGS avatar shapes on the chip the BASELINE
  medians came from — PROVISIONAL until a chip session captures real
  tails) and ``cpu|probe`` (the integrity-canary probe shapes on any
  host, sized generously above measured warm-dispatch walls so a
  clean CPU run never false-breaches). The registry completeness
  lint (``tests/test_registry_contract.py``) requires both rows for
  every registry kernel.
- :func:`judge` — turns captured latency histograms (the log-bucketed
  ``slo.latency_s.<kernel>`` histograms ``obs/metrics.py`` records)
  into per-kernel verdicts: ``ok`` / ``slo_breach`` (count-weighted
  p99 over target) / ``no_data`` (fewer than
  ``TPK_SLO_MIN_REQUESTS`` samples — a thin tail is no tail). A
  confirmed breach emits an ``slo_breach`` journal event. When the
  caller supplies per-kernel deadline-met counts (loadgen
  ``--deadline-ms`` runs — docs/SERVING.md §deadlines), an ``ok``
  verdict whose goodput fraction sits under
  :data:`DEFAULT_GOODPUT_MIN_FRAC` becomes ``goodput_low`` — NON-
  gating, the ``below_roofline`` pattern: it only ever replaces an
  ``ok``, never masks ``no_data``, never outranks a breach, and
  :func:`breaches` never selects it.
- :func:`record` / :func:`load_entries` — the persisted ``slo.json``
  verdict artifact (path via ``TPK_SLO_DIR``, beside tuning.json/
  aot.json/integrity.json), entries keyed
  ``kernel|shape_class|device_kind`` (simulated runs under their own
  ``|sim``-suffixed keys so a plumbing proof can never overwrite — and
  thereby un-gate — a real verdict) and validated at READ time
  against the jax version and the sha of the last commit touching the
  kernel's sources — a stale verdict is LOUDLY rejected
  (``slo_rejected`` stderr note + journal event, the
  tuning/aot/integrity contract), never silently trusted.
  ``simulated`` entries (loadgen ``--simulate`` runs: virtual clock,
  no jax) are persisted for plumbing proofs but NEVER gate.
- :func:`breaches` — the gating surface: ``tools/obs_report.py
  --check`` exits 1 on any validated, non-simulated ``slo_breach``
  entry, exactly the way it gates ``regression`` and
  ``output_integrity_failed``.

Stdlib-only at import time, like the rest of ``tpukernels.obs``.
"""

from __future__ import annotations

import os
import sys
import time

from tpukernels import _cachedir
from tpukernels.obs import metrics as obs_metrics
from tpukernels.resilience import journal

DEFAULT_MIN_REQUESTS = 20

# Deadline-met fraction below which an ok verdict downgrades to the
# non-gating goodput_low (judge(goodput=...) callers only).
DEFAULT_GOODPUT_MIN_FRAC = 0.95

# The device rows every kernel must state (contract-lint floor):
# the chip evidence row and the any-host CPU proof row.
EVIDENCE_ROW = "tpu_v5_lite|record"
CPU_ROW = "cpu|probe"
REQUIRED_ROWS = (CPU_ROW, EVIDENCE_ROW)

# Per-kernel p99 targets in MILLISECONDS per "device_kind|shape_class"
# row. cpu|probe rows are calibrated ~1000x above the measured warm
# interpret-mode dispatch walls (sub-ms for most kernels, ~25 ms for
# the MXU-nibble histogram family) so OS scheduler hiccups on a busy
# CI host never false-breach, while an injected slow-dispatch fault
# (docs/RESILIENCE.md §fault plans) breaches unambiguously.
# tpu_v5_lite|record rows are PROVISIONAL: derived from the
# BASELINE.json medians' per-pass walls plus a generous dispatch
# margin, to be re-anchored by the supervisor's slo_probe step once a
# healthy window captures a real tail.
TARGETS = {
    "vector_add": {CPU_ROW: 400.0, EVIDENCE_ROW: 10.0},
    "sgemm": {CPU_ROW: 400.0, EVIDENCE_ROW: 50.0},
    "stencil2d": {CPU_ROW: 400.0, EVIDENCE_ROW: 300.0},
    "stencil3d": {CPU_ROW: 400.0, EVIDENCE_ROW: 800.0},
    "scan": {CPU_ROW: 400.0, EVIDENCE_ROW: 60.0},
    "scan_exclusive": {CPU_ROW: 400.0, EVIDENCE_ROW: 60.0},
    "histogram": {CPU_ROW: 1500.0, EVIDENCE_ROW: 80.0},
    "scan_histogram": {CPU_ROW: 1500.0, EVIDENCE_ROW: 120.0},
    "nbody": {CPU_ROW: 400.0, EVIDENCE_ROW: 300.0},
}

_REJECT_NOTED: set = set()
_FILE_MEMO: dict = {}


def path() -> str:
    return _cachedir.slo_path()


def reset():
    """Drop per-process state (tests)."""
    _REJECT_NOTED.clear()
    _FILE_MEMO.clear()


def scale() -> float:
    """Target multiplier (``TPK_SLO_SCALE``, default 1.0) — how an
    operator widens every target on a known-slow host without editing
    the table. Fail-loud parse, the TPK_* knob contract."""
    raw = os.environ.get("TPK_SLO_SCALE")
    if raw is None:
        return 1.0
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if val <= 0.0:
        raise ValueError(
            f"TPK_SLO_SCALE={raw!r}: expected a float > 0"
        )
    return val


def min_requests() -> int:
    """Samples below which a histogram judges ``no_data``
    (``TPK_SLO_MIN_REQUESTS``, default 20): p99 of a handful of
    requests is an anecdote, not a tail."""
    raw = os.environ.get("TPK_SLO_MIN_REQUESTS")
    if raw is None:
        return DEFAULT_MIN_REQUESTS
    try:
        val = int(raw)
    except ValueError:
        val = 0
    if val < 1:
        raise ValueError(
            f"TPK_SLO_MIN_REQUESTS={raw!r}: expected an int >= 1"
        )
    return val


def base_kernel(kernel: str) -> str:
    """Strip a ``@tenant`` suffix off a per-tenant series name
    (``loadgen --tenant hot`` records ``scan@hot`` histograms so a
    fleet's per-tenant tails earn their own ``slo.json`` rows —
    docs/SERVING.md §fleet). Targets and kernel sources always
    resolve against the base kernel; the verdict keyspace keeps the
    tenant, so one tenant's breach never masks (or clears)
    another's."""
    return kernel.split("@", 1)[0]


def resolve_target_s(kernel: str, kind: str, shape_class: str):
    """(target_seconds, basis) for one kernel on one device kind and
    shape class, or (None, reason) when no row applies. Resolution
    mirrors ``roofline.resolve_kind``: an exact ``kind|class`` row
    wins; an unknown TPU kind borrows the v5-lite row (basis flagged
    ``assumed-...``); anything else falls back to the cpu row for the
    same shape class. A ``kernel@tenant`` series resolves the base
    kernel's row. The ``TPK_SLO_SCALE`` multiplier applies last."""
    rows = TARGETS.get(base_kernel(kernel))
    if not rows:
        return None, "no-target-row"
    key = f"{kind}|{shape_class}"
    basis = "exact"
    if key not in rows:
        if kind.startswith("tpu"):
            key, basis = f"tpu_v5_lite|{shape_class}", "assumed-tpu_v5_lite"
        else:
            key, basis = f"cpu|{shape_class}", "cpu-fallback"
    ms = rows.get(key)
    if not isinstance(ms, (int, float)):
        return None, f"no-row-for-{key}"
    return ms / 1000.0 * scale(), basis


def fmt_ms(v, width: int | None = None) -> str:
    """Milliseconds rendering shared by every SLO report surface
    (loadgen's table, obs_report's section/--check lines,
    health_report's narration) — one precision/placeholder rule, so
    the surfaces cannot drift apart. ``width`` column-aligns
    (``-`` placeholder); without it the compact ``12.3ms`` form
    (``?`` placeholder)."""
    if not isinstance(v, (int, float)):
        return f"{'-':>{width}}" if width else "?"
    if width:
        return f"{v * 1e3:{width}.2f}"
    return f"{v * 1e3:.1f}ms"


LATENCY_PREFIX = "slo.latency_s."


def histograms_by_kernel(hists: dict) -> dict:
    """{kernel: histogram_row} for the ``slo.latency_s.<kernel>``
    histograms inside one metrics snapshot (``metrics.snapshot()``
    shape, or the same dict off a ``metrics`` journal event)."""
    return {
        name[len(LATENCY_PREFIX):]: row
        for name, row in (hists or {}).items()
        if name.startswith(LATENCY_PREFIX)
    }


def judge(per_kernel: dict, kind: str, shape_class: str,
          simulated: bool = False, goodput: dict | None = None) -> dict:
    """Per-kernel verdict rows over captured latency histograms.

    ``per_kernel`` is :func:`histograms_by_kernel` output. Each row
    carries the count-weighted p50/p95/p99, the exact max, the
    resolved target and one of the three verdicts. A confirmed breach
    (enough samples, p99 over target) emits an ``slo_breach`` journal
    event and bumps ``slo.breaches`` — the journal twin of the
    persisted artifact row.

    ``goodput`` maps kernel -> ``(deadline_met, deadline_total)``
    from a deadline-carrying loadgen run; an ``ok`` row with enough
    deadline samples and a met fraction under
    :data:`DEFAULT_GOODPUT_MIN_FRAC` downgrades to the non-gating
    ``goodput_low``."""
    floor = min_requests()
    out = {}
    for kernel in sorted(per_kernel):
        h = per_kernel[kernel]
        count = int(h.get("count") or 0)
        target_s, basis = resolve_target_s(kernel, kind, shape_class)
        row = {
            "kernel": kernel,
            "count": count,
            "p50_s": h.get("p50"),
            "p95_s": h.get("p95"),
            "p99_s": h.get("p99"),
            "max_s": h.get("max"),
            "buckets": h.get("buckets") or {},
            "target_p99_s": target_s,
            "basis": basis,
            "device_kind": kind,
            "shape_class": shape_class,
            "simulated": bool(simulated),
        }
        if target_s is None or count < floor or row["p99_s"] is None:
            row["verdict"] = "no_data"
            row["why"] = (
                basis if target_s is None
                else f"{count} request(s) < min {floor}"
                if count < floor else "histogram carries no p99"
            )
        elif row["p99_s"] > target_s:
            row["verdict"] = "slo_breach"
            obs_metrics.inc("slo.breaches")
            journal.emit(
                "slo_breach", kernel=kernel, p99_s=row["p99_s"],
                p50_s=row["p50_s"], target_p99_s=target_s,
                count=count, device_kind=kind,
                shape_class=shape_class, basis=basis,
                simulated=bool(simulated),
            )
        else:
            row["verdict"] = "ok"
        gp = (goodput or {}).get(kernel)
        if gp:
            met, total = int(gp[0]), int(gp[1])
            row["goodput_met"] = met
            row["goodput_total"] = total
            row["goodput_frac"] = (met / total) if total else None
            if (row["verdict"] == "ok" and total >= floor
                    and row["goodput_frac"] is not None
                    and row["goodput_frac"]
                    < DEFAULT_GOODPUT_MIN_FRAC):
                # the below_roofline rule: only ever REPLACES an ok —
                # never masks no_data, never outranks a breach, and
                # breaches() (verdict == "slo_breach") never gates on
                # it.
                row["verdict"] = "goodput_low"
        out[kernel] = row
    return out


# ------------------------------------------------------------------ #
# the persisted slo.json verdict artifact                            #
# ------------------------------------------------------------------ #

def entry_key(kernel: str, shape_class: str, kind: str,
              simulated: bool = False) -> str:
    """Simulated runs get their own ``|sim``-suffixed keyspace: a
    virtual-clock plumbing proof must never OVERWRITE (and thereby
    un-gate) a real measurement's verdict at the same
    (kernel, shape_class, kind)."""
    key = "|".join((kernel, shape_class, kind))
    return key + "|sim" if simulated else key


def _sources(kernel: str):
    from tpukernels import aot

    return aot.KERNEL_SOURCES.get(base_kernel(kernel), ())


def record(verdicts: dict, run_info: dict | None = None,
           jax_version: str | None = None) -> str:
    """Atomically upsert one run's verdict rows into ``slo.json``
    (flock-serialized read-modify-write, the tuning-cache
    discipline); returns the artifact path. Each entry records the
    evidence that scoped it — jax version (None for simulated runs),
    per-kernel source sha, repo HEAD, wall clock, and the run's
    arrival/seed parameters — so a later reader can validate it the
    way tuning/aot/integrity entries are validated."""
    from tpukernels.tuning import cache as tcache

    p = path()
    info = dict(run_info or {})
    head = journal.git_head()
    now = round(time.time(), 3)

    def _mutate(data):
        entries = data.setdefault("entries", {})
        for kernel, row in verdicts.items():
            key = entry_key(
                kernel, row["shape_class"], row["device_kind"],
                simulated=bool(row.get("simulated")),
            )
            entries[key] = {
                **{k: v for k, v in row.items() if k != "kernel"},
                "jax": jax_version,
                "source_sha": tcache.source_sha(
                    tuple(_sources(kernel))
                ),
                "git_head": head,
                "recorded": now,
                "run": info,
            }

    _cachedir.locked_json_update(p, _mutate)
    _FILE_MEMO.pop(p, None)
    return p


def _reject(key: str, reason: str, **fields):
    """Loud-rejection contract shared with the tuning/aot/integrity
    caches: stderr note + ``slo_rejected`` journal event once per
    process per cause, counter per occurrence."""
    obs_metrics.inc("slo.rejections")
    memo = (key, reason)
    if memo in _REJECT_NOTED:
        return
    _REJECT_NOTED.add(memo)
    print(f"# slo verdict rejected: {key} ({reason})", file=sys.stderr)
    journal.emit("slo_rejected", key=key, reason=reason, **fields)


def load_entries() -> dict:
    """Validated ``slo.json`` entries ({key: entry}). Validation
    mirrors the tuning cache: a non-simulated entry whose jax version
    differs from the running one, or whose kernel sources have a newer
    commit than its ``source_sha``, is rejected loudly and dropped —
    a p99 captured against last week's kernel must not gate (or
    clear) today's queue. Simulated entries skip the jax check (they
    never ran jax) but still sha-validate."""
    data = _cachedir.read_json_memoized(path(), _FILE_MEMO)
    entries = data.get("entries")
    if not isinstance(entries, dict):
        return {}
    from tpukernels.tuning import cache as tcache

    out = {}
    jax_version = None
    for key, entry in sorted(entries.items()):
        if not isinstance(entry, dict):
            continue
        kernel = key.split("|", 1)[0]
        if not entry.get("simulated"):
            if jax_version is None:
                import jax

                jax_version = jax.__version__
            if entry.get("jax") != jax_version:
                _reject(
                    key,
                    f"measured under jax {entry.get('jax')}, "
                    f"running {jax_version}",
                )
                continue
        sources = _sources(kernel)
        if sources:
            sha = tcache.source_sha(tuple(sources))
            if sha is not None and entry.get("source_sha") not in (
                None, sha,
            ):
                _reject(
                    key,
                    "stale: a commit touching "
                    + ",".join(sources)
                    + " postdates this verdict",
                    entry_sha=entry.get("source_sha"),
                    current_sha=sha,
                )
                continue
        out[key] = entry
    return out


def breaches() -> dict:
    """The gating surface: validated, NON-simulated entries whose
    verdict is ``slo_breach`` ({key: entry}) — what flips
    ``obs_report --check`` to rc 1."""
    return {
        k: e for k, e in load_entries().items()
        if e.get("verdict") == "slo_breach" and not e.get("simulated")
    }
