"""Kernel registry: the TPU column of the C dispatch table (SURVEY.md C3).

The C driver dispatches `--device=tpu` through the shim (C10) into
`tpukernels.capi`, which looks kernels up here by the same string key
the C dispatch table uses. Python-side callers (bench.py, tests) use it
directly.

Population is lazy: kernel modules (and with them JAX and the TPU
runtime) are only imported on the first lookup()/names() call, so a C
host embedding Python pays nothing for `import tpukernels` until it
actually dispatches a kernel.
"""

from __future__ import annotations

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}
_POPULATED = False


def lookup(name: str) -> Callable:
    _populate()
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown kernel {name!r}; known: {sorted(_REGISTRY)}"
        ) from None


def names():
    _populate()
    return sorted(_REGISTRY)


def _populate():
    global _POPULATED
    if _POPULATED:
        return
    _POPULATED = True

    import tpukernels.kernels.vector_add as _vector_add
    import tpukernels.kernels.sgemm as _sgemm

    _REGISTRY["vector_add"] = _vector_add.saxpy
    _REGISTRY["sgemm"] = _sgemm.sgemm
    try:
        import tpukernels.kernels.stencil as _stencil

        _REGISTRY["stencil2d"] = _stencil.jacobi2d
        _REGISTRY["stencil3d"] = _stencil.jacobi3d
    except ImportError:
        pass
    try:
        import tpukernels.kernels.scan as _scan
        import tpukernels.kernels.histogram as _histogram

        _REGISTRY["scan"] = _scan.inclusive_scan
        _REGISTRY["scan_exclusive"] = _scan.exclusive_scan
        _REGISTRY["histogram"] = _histogram.histogram
    except ImportError:
        pass
    try:
        import tpukernels.kernels.nbody as _nbody

        _REGISTRY["nbody"] = _nbody.nbody_step
    except ImportError:
        pass
