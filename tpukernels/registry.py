"""Kernel registry: the TPU column of the C dispatch table (SURVEY.md C3).

The C driver dispatches `--device=tpu` through the shim (C10) into
`tpukernels.capi`, which looks kernels up here by the same string key
the C dispatch table uses. Python-side callers (bench.py, tests) use it
directly.

Population is lazy: kernel modules (and with them JAX and the TPU
runtime) are only imported on the first lookup()/names() call, so a C
host embedding Python pays nothing for `import tpukernels` until it
actually dispatches a kernel.

Tuning integration (docs/TUNING.md): kernel modules export declarative
``TUNABLES`` search spaces, registered here alongside the callables
(``tunables(name)`` / ``tunable_kernels()``). Dispatch consults the
persistent tuning cache at kernel RESOLUTION time — each kernel
wrapper calls ``tpukernels.tuning.resolve`` per call with its actual
shape/dtype, and ``resolve_params(name, shape, dtype)`` exposes the
same path for introspection — with documented precedence:

    env-override  >  tuned-cache  >  shipped-default

i.e. a set ``TPK_*`` knob always wins, else a validated cache entry
for (kernel, shape, dtype, device_kind), else the defaults the module
ships. Resolution lives in the wrapper, not in lookup(): the cache is
keyed per shape/dtype, which only exist at call time.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict

# stdlib-only (no jax), so importing it here keeps `import tpukernels`
# jax-free; gives _populate its fault-injection point and journals
# real import failures as health events (docs/RESILIENCE.md).
# tuning.space, the observability layer (docs/OBSERVABILITY.md) and
# the AOT layer (docs/PERF.md §compile discipline) are likewise
# stdlib-only at import time.
from tpukernels import aot as _aot
from tpukernels.obs import metrics as _obs_metrics
from tpukernels.obs import trace as _trace
from tpukernels.resilience import faults, integrity as _integrity, journal
from tpukernels.tuning import space as _tuning_space

_REGISTRY: Dict[str, Callable] = {}
_TUNABLES: Dict[str, "_tuning_space.SearchSpace"] = {}
_IMPORT_ERRORS: Dict[str, BaseException] = {}  # kernel -> why it's absent
_POPULATED = False

# Derived registry entries ride a BASE kernel's tuning/roofline
# surface instead of declaring their own: scan_exclusive is a
# one-element shift of scan's output, so it tunes through scan's
# TUNABLES and shares scan's roofline model. The registry completeness
# lint (tests/test_registry_contract.py) resolves through this table —
# every registered kernel must carry the full contract (TUNABLES, an
# aot.BENCH_CONFIGS avatar, a roofline entry) either directly or via
# its base, so a new kernel can't silently skip one.
DERIVED_KERNELS = {"scan_exclusive": "scan"}


def lookup(name: str) -> Callable:
    _populate()
    try:
        return _REGISTRY[name]
    except KeyError:
        if name in _IMPORT_ERRORS:
            raise KeyError(
                f"kernel {name!r} failed to import: {_IMPORT_ERRORS[name]!r}"
            ) from _IMPORT_ERRORS[name]
        raise KeyError(
            f"unknown kernel {name!r}; known: {sorted(_REGISTRY)}"
        ) from None


def names():
    _populate()
    return sorted(_REGISTRY)


def tunables(name: str) -> "_tuning_space.SearchSpace":
    """The declarative search space a kernel module exported for
    `name` (docs/TUNING.md §schema). KeyError for kernels without one
    (derived entries like scan_exclusive tune through their base
    kernel's space)."""
    _populate()
    try:
        return _TUNABLES[name]
    except KeyError:
        raise KeyError(
            f"kernel {name!r} exports no TUNABLES; tunable kernels: "
            f"{sorted(_TUNABLES)}"
        ) from None


def tunable_kernels():
    _populate()
    return sorted(_TUNABLES)


def dispatch(name: str, *args, **statics):
    """Run one kernel call through the process-wide compiled-executable
    memo (docs/PERF.md §compile discipline).

    Positional ``args`` are traced operands (callers canonicalize host
    scalars — ``jnp.float32(alpha)`` — so the memo key matches the
    precompiled avatar exactly); keyword ``statics`` select the
    program (iters, nbins, steps, dt, eps). The first call at a given
    (shape, dtype, statics) compiles once through the AOT choke point;
    every later call from ANY entry path — a C-shim dispatch after a
    bench child, a tuning candidate after a prewarm — reuses the
    compiled executable. With ``TPK_AOT_CACHE=0`` this is exactly
    ``lookup(name)(*args, **statics)``: the plain eager wrapper, no
    memo, no manifest.

    Every dispatched result passes through the output-integrity guard
    (docs/RESILIENCE.md §output integrity): an always-on NaN/Inf
    tripwire plus first-trust/sampled oracle canary checks. The guard
    never raises — a wrong answer becomes an
    ``output_integrity_failed`` journal event, the kernel's AOT
    executable memo is invalidated, and repeat offenders are
    quarantined. ``TPK_INTEGRITY=0`` makes this a single check.

    Dispatch is the serving path of record — the serve daemon
    (``tpukernels/serve``, docs/SERVING.md) funnels every client
    request through this exact function, so the fault point, the
    executable memo and the integrity guard police the service the
    same way they police a batch run — and it is
    latency-instrumented for the SLO layer
    (docs/OBSERVABILITY.md §latency SLOs): a ``dispatch/<kernel>``
    span (no-op unless ``TPK_TRACE``), a ``dispatch.calls.<kernel>``
    counter and a ``dispatch.wall_s.<kernel>`` histogram per call —
    dict updates and two clock reads, no I/O, so the clean-path
    stdout proof holds. The wall covers fault injection, the memo
    lookup/compile and the integrity guard; with the guard on its
    host-side tripwire read makes the wall effectively synchronous,
    with everything off it is async submit time."""
    t0 = _time.perf_counter()
    with _trace.span(f"dispatch/{name}"):
        faults.dispatch_fault(name)
        fn = lookup(name)
        if not _aot.enabled():
            out = fn(*args, **statics)
        else:
            out = _aot.run_cached(name, fn, args, statics)
        out = _integrity.guard("registry", name, out, statics=statics)
    _obs_metrics.inc(f"dispatch.calls.{name}")
    _obs_metrics.observe(
        f"dispatch.wall_s.{name}", _time.perf_counter() - t0
    )
    return out


def precompile(name: str) -> dict:
    """Compile ``name``'s registered benchmark config ahead of time
    (``aot.BENCH_CONFIGS`` avatars — nothing allocates, nothing
    executes) into the same memo :func:`dispatch` reads. Exposed
    beside the callables so ``tools/prewarm.py`` and the supervisor's
    prewarm step are registry-driven, not a hand-kept kernel list."""
    lookup(name)  # populate + surface import failures as the real cause
    return _aot.precompile(name)


def precompilable_kernels():
    """Registered kernels with a benchmark config to precompile —
    the registry-driven prewarm surface."""
    _populate()
    return sorted(n for n in _REGISTRY if n in _aot.BENCH_CONFIGS)


def resolve_params(name: str, shape=None, dtype=None) -> dict:
    """Resolved tunable values for one prospective `name` call at
    (shape, dtype), with the documented precedence env-override >
    tuned-cache > shipped-default — the same path the kernel wrapper
    takes at dispatch, exposed for tools and tests."""
    return _tuning_space.resolve(tunables(name), shape=shape, dtype=dtype)


def _populate():
    global _POPULATED
    if _POPULATED:
        return

    # Modules register in groups; a failed import leaves its kernels
    # absent but lookup() then reports the REAL cause instead of
    # "unknown kernel" (a bare except:pass here once meant a syntax
    # error in a kernel module surfaced as a dispatch-table miss).
    # Tracebacks are stripped before storing: the module-level dict
    # lives as long as the (possibly C-embedded) interpreter, and a
    # live traceback would pin every frame in the failed import.
    # A failed REQUIRED group leaves _POPULATED false so a transient
    # failure (e.g. TPU runtime hiccup at first import) is retryable.
    def _group(names, load, required=False):
        try:
            faults.import_fault(names)  # no-op without a TPK_FAULT_PLAN
            load()
        except Exception as e:  # noqa: BLE001 — recorded, re-raised on use
            stripped = e.with_traceback(None)
            for n in names:
                _IMPORT_ERRORS[n] = stripped
            journal.emit(
                "import_failure", kernels=list(names),
                required=required, error=repr(stripped),
            )
            if required:
                raise

    def _spaces(mod):
        # search spaces register beside the callables so one failed
        # group leaves the others' tuning surface intact too
        for sp in _tuning_space.spaces_of(mod):
            _TUNABLES[sp.kernel] = sp

    def _load_core():
        import tpukernels.kernels.vector_add as _vector_add
        import tpukernels.kernels.sgemm as _sgemm

        _REGISTRY["vector_add"] = _vector_add.saxpy
        _REGISTRY["sgemm"] = _sgemm.sgemm
        _spaces(_vector_add)
        _spaces(_sgemm)

    def _load_stencil():
        import tpukernels.kernels.stencil as _stencil

        _REGISTRY["stencil2d"] = _stencil.jacobi2d
        _REGISTRY["stencil3d"] = _stencil.jacobi3d
        _spaces(_stencil)

    def _load_scan_hist():
        import tpukernels.kernels.scan as _scan
        import tpukernels.kernels.histogram as _histogram
        import tpukernels.kernels.scan_histogram as _scan_histogram

        _REGISTRY["scan"] = _scan.inclusive_scan
        _REGISTRY["scan_exclusive"] = _scan.exclusive_scan
        _REGISTRY["histogram"] = _histogram.histogram
        _REGISTRY["scan_histogram"] = _scan_histogram.scan_histogram
        _spaces(_scan)
        _spaces(_histogram)
        _spaces(_scan_histogram)

    def _load_nbody():
        import tpukernels.kernels.nbody as _nbody

        _REGISTRY["nbody"] = _nbody.nbody_step
        _spaces(_nbody)

    # the populate span brackets the first-lookup cost — kernel module
    # imports, and with them jax + the TPU runtime — the lazy-import
    # design exists to defer; the counter proves laziness held (one
    # populate per process, not one per lookup)
    _obs_metrics.inc("registry.populates")
    with _trace.span("registry/populate"):
        _group(("vector_add", "sgemm"), _load_core, required=True)
        _group(("stencil2d", "stencil3d"), _load_stencil)
        _group(
            ("scan", "scan_exclusive", "histogram", "scan_histogram"),
            _load_scan_hist,
        )
        _group(("nbody",), _load_nbody)
    _POPULATED = True
