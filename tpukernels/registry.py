"""Kernel registry: the TPU column of the C dispatch table (SURVEY.md C3).

The C driver dispatches `--device=tpu` through the shim (C10) into
`tpukernels.capi`, which looks kernels up here by the same string key
the C dispatch table uses. Python-side callers (bench.py, tests) use it
directly.

Population is lazy: kernel modules (and with them JAX and the TPU
runtime) are only imported on the first lookup()/names() call, so a C
host embedding Python pays nothing for `import tpukernels` until it
actually dispatches a kernel.
"""

from __future__ import annotations

from typing import Callable, Dict

# stdlib-only (no jax), so importing it here keeps `import tpukernels`
# jax-free; gives _populate its fault-injection point and journals
# real import failures as health events (docs/RESILIENCE.md)
from tpukernels.resilience import faults, journal

_REGISTRY: Dict[str, Callable] = {}
_IMPORT_ERRORS: Dict[str, BaseException] = {}  # kernel -> why it's absent
_POPULATED = False


def lookup(name: str) -> Callable:
    _populate()
    try:
        return _REGISTRY[name]
    except KeyError:
        if name in _IMPORT_ERRORS:
            raise KeyError(
                f"kernel {name!r} failed to import: {_IMPORT_ERRORS[name]!r}"
            ) from _IMPORT_ERRORS[name]
        raise KeyError(
            f"unknown kernel {name!r}; known: {sorted(_REGISTRY)}"
        ) from None


def names():
    _populate()
    return sorted(_REGISTRY)


def _populate():
    global _POPULATED
    if _POPULATED:
        return

    # Modules register in groups; a failed import leaves its kernels
    # absent but lookup() then reports the REAL cause instead of
    # "unknown kernel" (a bare except:pass here once meant a syntax
    # error in a kernel module surfaced as a dispatch-table miss).
    # Tracebacks are stripped before storing: the module-level dict
    # lives as long as the (possibly C-embedded) interpreter, and a
    # live traceback would pin every frame in the failed import.
    # A failed REQUIRED group leaves _POPULATED false so a transient
    # failure (e.g. TPU runtime hiccup at first import) is retryable.
    def _group(names, load, required=False):
        try:
            faults.import_fault(names)  # no-op without a TPK_FAULT_PLAN
            load()
        except Exception as e:  # noqa: BLE001 — recorded, re-raised on use
            stripped = e.with_traceback(None)
            for n in names:
                _IMPORT_ERRORS[n] = stripped
            journal.emit(
                "import_failure", kernels=list(names),
                required=required, error=repr(stripped),
            )
            if required:
                raise

    def _load_core():
        import tpukernels.kernels.vector_add as _vector_add
        import tpukernels.kernels.sgemm as _sgemm

        _REGISTRY["vector_add"] = _vector_add.saxpy
        _REGISTRY["sgemm"] = _sgemm.sgemm

    def _load_stencil():
        import tpukernels.kernels.stencil as _stencil

        _REGISTRY["stencil2d"] = _stencil.jacobi2d
        _REGISTRY["stencil3d"] = _stencil.jacobi3d

    def _load_scan_hist():
        import tpukernels.kernels.scan as _scan
        import tpukernels.kernels.histogram as _histogram

        _REGISTRY["scan"] = _scan.inclusive_scan
        _REGISTRY["scan_exclusive"] = _scan.exclusive_scan
        _REGISTRY["histogram"] = _histogram.histogram

    def _load_nbody():
        import tpukernels.kernels.nbody as _nbody

        _REGISTRY["nbody"] = _nbody.nbody_step

    _group(("vector_add", "sgemm"), _load_core, required=True)
    _group(("stencil2d", "stencil3d"), _load_stencil)
    _group(("scan", "scan_exclusive", "histogram"), _load_scan_hist)
    _group(("nbody",), _load_nbody)
    _POPULATED = True
