"""Kernel registry: the TPU column of the C dispatch table (SURVEY.md C3).

The C driver dispatches `--device=tpu` through the shim (C10) into
`tpukernels.capi`, which looks kernels up here by the same string key
the C dispatch table uses. Python-side callers (bench.py, tests) use it
directly.

Population is lazy: kernel modules (and with them JAX and the TPU
runtime) are only imported on the first lookup()/names() call, so a C
host embedding Python pays nothing for `import tpukernels` until it
actually dispatches a kernel.

Tuning integration (docs/TUNING.md): kernel modules export declarative
``TUNABLES`` search spaces, registered here alongside the callables
(``tunables(name)`` / ``tunable_kernels()``). Dispatch consults the
persistent tuning cache at kernel RESOLUTION time — each kernel
wrapper calls ``tpukernels.tuning.resolve`` per call with its actual
shape/dtype, and ``resolve_params(name, shape, dtype)`` exposes the
same path for introspection — with documented precedence:

    env-override  >  tuned-cache  >  shipped-default

i.e. a set ``TPK_*`` knob always wins, else a validated cache entry
for (kernel, shape, dtype, device_kind), else the defaults the module
ships. Resolution lives in the wrapper, not in lookup(): the cache is
keyed per shape/dtype, which only exist at call time.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict

# stdlib-only (no jax), so importing it here keeps `import tpukernels`
# jax-free; gives _populate its fault-injection point and journals
# real import failures as health events (docs/RESILIENCE.md).
# tuning.space, the observability layer (docs/OBSERVABILITY.md) and
# the AOT layer (docs/PERF.md §compile discipline) are likewise
# stdlib-only at import time.
from tpukernels import aot as _aot
from tpukernels.obs import metrics as _obs_metrics
from tpukernels.obs import trace as _trace
from tpukernels.resilience import faults, integrity as _integrity, journal
from tpukernels.tuning import space as _tuning_space

_REGISTRY: Dict[str, Callable] = {}
_TUNABLES: Dict[str, "_tuning_space.SearchSpace"] = {}
_IMPORT_ERRORS: Dict[str, BaseException] = {}  # kernel -> why it's absent
_POPULATED = False

# Derived registry entries ride a BASE kernel's tuning/roofline
# surface instead of declaring their own: scan_exclusive is a
# one-element shift of scan's output, so it tunes through scan's
# TUNABLES and shares scan's roofline model. The registry completeness
# lint (tests/test_registry_contract.py) resolves through this table —
# every registered kernel must carry the full contract (TUNABLES, an
# aot.BENCH_CONFIGS avatar, a roofline entry) either directly or via
# its base, so a new kernel can't silently skip one.
DERIVED_KERNELS = {"scan_exclusive": "scan"}

# Kernels with a mesh-backed distributed twin (parallel/collectives.py)
# — the serve tier's over-avatar escape hatch (docs/SERVING.md §mesh
# tier): a request too big for every single-device avatar routes to
# :func:`dispatch_mesh` instead of being rejected, but only for
# kernels that actually have a sharded formulation. The admission side
# (serve/bucketing.mesh_tier_for) reads this tuple lazily so the
# capability list has ONE home.
MESH_KERNELS = ("histogram", "nbody", "scan", "scan_exclusive",
                "stencil2d", "stencil3d")


def lookup(name: str) -> Callable:
    _populate()
    try:
        return _REGISTRY[name]
    except KeyError:
        if name in _IMPORT_ERRORS:
            raise KeyError(
                f"kernel {name!r} failed to import: {_IMPORT_ERRORS[name]!r}"
            ) from _IMPORT_ERRORS[name]
        raise KeyError(
            f"unknown kernel {name!r}; known: {sorted(_REGISTRY)}"
        ) from None


def names():
    _populate()
    return sorted(_REGISTRY)


def tunables(name: str) -> "_tuning_space.SearchSpace":
    """The declarative search space a kernel module exported for
    `name` (docs/TUNING.md §schema). KeyError for kernels without one
    (derived entries like scan_exclusive tune through their base
    kernel's space)."""
    _populate()
    try:
        return _TUNABLES[name]
    except KeyError:
        raise KeyError(
            f"kernel {name!r} exports no TUNABLES; tunable kernels: "
            f"{sorted(_TUNABLES)}"
        ) from None


def tunable_kernels():
    _populate()
    return sorted(_TUNABLES)


def dispatch(name: str, *args, **statics):
    """Run one kernel call through the process-wide compiled-executable
    memo (docs/PERF.md §compile discipline).

    Positional ``args`` are traced operands (callers canonicalize host
    scalars — ``jnp.float32(alpha)`` — so the memo key matches the
    precompiled avatar exactly); keyword ``statics`` select the
    program (iters, nbins, steps, dt, eps). The first call at a given
    (shape, dtype, statics) compiles once through the AOT choke point;
    every later call from ANY entry path — a C-shim dispatch after a
    bench child, a tuning candidate after a prewarm — reuses the
    compiled executable. With ``TPK_AOT_CACHE=0`` this is exactly
    ``lookup(name)(*args, **statics)``: the plain eager wrapper, no
    memo, no manifest.

    Every dispatched result passes through the output-integrity guard
    (docs/RESILIENCE.md §output integrity): an always-on NaN/Inf
    tripwire plus first-trust/sampled oracle canary checks. The guard
    never raises — a wrong answer becomes an
    ``output_integrity_failed`` journal event, the kernel's AOT
    executable memo is invalidated, and repeat offenders are
    quarantined. ``TPK_INTEGRITY=0`` makes this a single check.

    Dispatch is the serving path of record — the serve daemon
    (``tpukernels/serve``, docs/SERVING.md) funnels every client
    request through this exact function, so the fault point, the
    executable memo and the integrity guard police the service the
    same way they police a batch run — and it is
    latency-instrumented for the SLO layer
    (docs/OBSERVABILITY.md §latency SLOs): a ``dispatch/<kernel>``
    span (no-op unless ``TPK_TRACE``), a ``dispatch.calls.<kernel>``
    counter and a ``dispatch.wall_s.<kernel>`` histogram per call —
    dict updates and two clock reads, no I/O, so the clean-path
    stdout proof holds. The wall covers fault injection, the memo
    lookup/compile and the integrity guard; with the guard on its
    host-side tripwire read makes the wall effectively synchronous,
    with everything off it is async submit time."""
    t0 = _time.perf_counter()
    with _trace.span(f"dispatch/{name}"):
        faults.dispatch_fault(name)
        fn = lookup(name)
        if not _aot.enabled():
            out = fn(*args, **statics)
        else:
            out = _aot.run_cached(name, fn, args, statics)
        out = _integrity.guard("registry", name, out, statics=statics)
    _obs_metrics.inc(f"dispatch.calls.{name}")
    _obs_metrics.observe(
        f"dispatch.wall_s.{name}", _time.perf_counter() - t0
    )
    return out


# (kernel, ring size) -> the mesh-twin wrapper callable. Cached so the
# AOT memo key and the executable behind it are stable across calls:
# the wrapper's identity never matters (run_cached keys on the name
# string), but rebuilding the mesh per call would re-run make_mesh's
# device enumeration on every request.
_MESH_FNS: Dict[tuple, Callable] = {}


def _mesh_callable(name: str, n: int) -> Callable:
    key = (name, n)
    fn = _MESH_FNS.get(key)
    if fn is not None:
        return fn
    from tpukernels.parallel import collectives as _coll
    from tpukernels.parallel.mesh import make_mesh as _make_mesh

    # the 1-D ring of record: every dist kernel's comm pattern
    # (halo sendrecv, ring body rotation, two-level scan) rides it.
    # make_mesh raises ValueError when fewer than n devices exist —
    # the honest answer when the admission env promised more chips
    # than the backend has (the env inventory is a promise, not a
    # measurement), surfaced to the client as an error reply.
    mesh = _make_mesh(n)
    if name == "scan":
        fn = lambda x: _coll.scan_dist(x, mesh)  # noqa: E731
    elif name == "scan_exclusive":
        fn = lambda x: _coll.scan_dist(  # noqa: E731
            x, mesh, exclusive=True)
    elif name == "histogram":
        fn = lambda x, nbins=256: _coll.histogram_dist(  # noqa: E731
            x, int(nbins), mesh)
    elif name == "stencil2d":
        fn = lambda x, iters=8: _coll.jacobi2d_dist(  # noqa: E731
            x, int(iters), mesh)
    elif name == "stencil3d":
        fn = lambda x, iters=8: _coll.jacobi3d_dist(  # noqa: E731
            x, int(iters), mesh)
    elif name == "nbody":
        def fn(px, py, pz, vx, vy, vz, m, dt=1e-3, eps=1e-2, steps=1):
            return _coll.nbody_dist_ring(
                (px, py, pz, vx, vy, vz, m), int(steps), mesh,
                dt=dt, eps=eps,
            )
    else:
        raise KeyError(
            f"kernel {name!r} has no mesh-tier twin; mesh kernels: "
            f"{sorted(MESH_KERNELS)}"
        )
    _MESH_FNS[key] = fn
    return fn


def dispatch_mesh(name: str, *args, mesh_shape=None, **statics):
    """Run one kernel call on its mesh-backed distributed twin —
    the over-avatar serve tier (docs/SERVING.md §mesh tier).

    Same machinery as :func:`dispatch` end to end: the
    ``dispatch/<kernel>`` span (stamped ``mesh=``), the dispatch fault
    point, the AOT executable memo — keyed ``<name>@mesh<n>`` so the
    mesh program memoizes beside (never instead of) the single-device
    one, while ``aot.invalidate_kernel(name)`` still drops it (the
    base-name match splits on ``@``) — and the output-integrity guard
    under the base kernel name, whose canary cross-checks the
    single-device formulation. ``mesh_shape`` is the admission-time
    tier decision (serve/bucketing.mesh_tier_for), a tuple whose
    product is the ring size; the worker-side ``make_mesh`` revalidates
    it against the live backend, so an env inventory that promised
    more chips than exist becomes a clean dispatch error, not silent
    wrong-device execution. Metrics: ``dispatch.calls.<kernel>`` and
    ``dispatch.wall_s.<kernel>`` as on the native path, plus a
    ``dispatch.mesh.<kernel>`` counter so the mesh tier's share is
    readable without log archaeology."""
    if not isinstance(mesh_shape, (tuple, list)) or not mesh_shape:
        raise ValueError(
            f"mesh_shape={mesh_shape!r}: expected a non-empty tuple"
        )
    n = 1
    for d in mesh_shape:
        n *= int(d)
    if n < 2:
        raise ValueError(
            f"mesh_shape={mesh_shape!r}: a mesh tier needs >= 2 devices"
        )
    t0 = _time.perf_counter()
    with _trace.span(f"dispatch/{name}",
                     mesh="x".join(str(int(d)) for d in mesh_shape)):
        faults.dispatch_fault(name)
        fn = _mesh_callable(name, n)
        if not _aot.enabled():
            out = fn(*args, **statics)
        else:
            # staleness sources: the dist formulation lives in
            # collectives.py, not the base kernel's module — a halo
            # or ring change must stale the mesh twin's manifest rows
            out = _aot.run_cached(
                f"{name}@mesh{n}", fn, args, statics,
                sources=("tpukernels/parallel/collectives.py",)
                + tuple(_aot.KERNEL_SOURCES.get(name, ())),
            )
        out = _integrity.guard("registry", name, out, statics=statics)
    _obs_metrics.inc(f"dispatch.calls.{name}")
    _obs_metrics.inc(f"dispatch.mesh.{name}")
    _obs_metrics.observe(
        f"dispatch.wall_s.{name}", _time.perf_counter() - t0
    )
    return out


def precompile(name: str) -> dict:
    """Compile ``name``'s registered benchmark config ahead of time
    (``aot.BENCH_CONFIGS`` avatars — nothing allocates, nothing
    executes) into the same memo :func:`dispatch` reads. Exposed
    beside the callables so ``tools/prewarm.py`` and the supervisor's
    prewarm step are registry-driven, not a hand-kept kernel list."""
    lookup(name)  # populate + surface import failures as the real cause
    return _aot.precompile(name)


def precompilable_kernels():
    """Registered kernels with a benchmark config to precompile —
    the registry-driven prewarm surface."""
    _populate()
    return sorted(n for n in _REGISTRY if n in _aot.BENCH_CONFIGS)


def resolve_params(name: str, shape=None, dtype=None) -> dict:
    """Resolved tunable values for one prospective `name` call at
    (shape, dtype), with the documented precedence env-override >
    tuned-cache > shipped-default — the same path the kernel wrapper
    takes at dispatch, exposed for tools and tests."""
    return _tuning_space.resolve(tunables(name), shape=shape, dtype=dtype)


def _populate():
    global _POPULATED
    if _POPULATED:
        return

    # Modules register in groups; a failed import leaves its kernels
    # absent but lookup() then reports the REAL cause instead of
    # "unknown kernel" (a bare except:pass here once meant a syntax
    # error in a kernel module surfaced as a dispatch-table miss).
    # Tracebacks are stripped before storing: the module-level dict
    # lives as long as the (possibly C-embedded) interpreter, and a
    # live traceback would pin every frame in the failed import.
    # A failed REQUIRED group leaves _POPULATED false so a transient
    # failure (e.g. TPU runtime hiccup at first import) is retryable.
    def _group(names, load, required=False):
        try:
            faults.import_fault(names)  # no-op without a TPK_FAULT_PLAN
            load()
        except Exception as e:  # noqa: BLE001 — recorded, re-raised on use
            stripped = e.with_traceback(None)
            for n in names:
                _IMPORT_ERRORS[n] = stripped
            journal.emit(
                "import_failure", kernels=list(names),
                required=required, error=repr(stripped),
            )
            if required:
                raise

    def _spaces(mod):
        # search spaces register beside the callables so one failed
        # group leaves the others' tuning surface intact too
        for sp in _tuning_space.spaces_of(mod):
            _TUNABLES[sp.kernel] = sp

    def _load_core():
        import tpukernels.kernels.vector_add as _vector_add
        import tpukernels.kernels.sgemm as _sgemm

        _REGISTRY["vector_add"] = _vector_add.saxpy
        _REGISTRY["sgemm"] = _sgemm.sgemm
        _spaces(_vector_add)
        _spaces(_sgemm)

    def _load_stencil():
        import tpukernels.kernels.stencil as _stencil

        _REGISTRY["stencil2d"] = _stencil.jacobi2d
        _REGISTRY["stencil3d"] = _stencil.jacobi3d
        _spaces(_stencil)

    def _load_scan_hist():
        import tpukernels.kernels.scan as _scan
        import tpukernels.kernels.histogram as _histogram
        import tpukernels.kernels.scan_histogram as _scan_histogram

        _REGISTRY["scan"] = _scan.inclusive_scan
        _REGISTRY["scan_exclusive"] = _scan.exclusive_scan
        _REGISTRY["histogram"] = _histogram.histogram
        _REGISTRY["scan_histogram"] = _scan_histogram.scan_histogram
        _spaces(_scan)
        _spaces(_histogram)
        _spaces(_scan_histogram)

    def _load_nbody():
        import tpukernels.kernels.nbody as _nbody

        _REGISTRY["nbody"] = _nbody.nbody_step
        _spaces(_nbody)

    # the populate span brackets the first-lookup cost — kernel module
    # imports, and with them jax + the TPU runtime — the lazy-import
    # design exists to defer; the counter proves laziness held (one
    # populate per process, not one per lookup)
    _obs_metrics.inc("registry.populates")
    with _trace.span("registry/populate"):
        _group(("vector_add", "sgemm"), _load_core, required=True)
        _group(("stencil2d", "stencil3d"), _load_stencil)
        _group(
            ("scan", "scan_exclusive", "histogram", "scan_histogram"),
            _load_scan_hist,
        )
        _group(("nbody",), _load_nbody)
    _POPULATED = True
