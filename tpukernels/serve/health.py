"""Fleet health manager: crash detection, supervised respawn,
graceful degradation (docs/SERVING.md §self-healing).

PR 11's fleet survives a *wedged worker thread* (the daemon watchdog
abandons it, the router spills and cools) but not a *dead worker
process*: a ``kill -9``'d daemon was permanent transport loss the
router spilled around forever — its ``/dev/shm`` segments and
pidfile leaked until some later start-time sweep, its in-flight
requests vanished, and nobody ever restarted it. This module closes
the loop from failure detection to recovery, run as a thread inside
the router process (``router.main`` attaches it) and usable
standalone through ``serve_ctl health``:

- **Liveness detection** — every ``TPK_FLEET_PROBE_S`` (default 5 s)
  each ring member is probed twice over: its flocked pidfile (the
  ``revalidate_lib.sh`` convention — a dead process RELEASES the
  flock, so a free lock is a definitive death certificate, where a
  hung ping is merely ambiguous) and a protocol ping. The
  ``classify_timeout``-style discrimination: flock held + ping dead
  = SLOW (the process lives; its own watchdog owns wedged requests —
  journaled through ``watchdog.classify_timeout`` on the
  transition), flock free = DEAD (``worker_dead`` within one probe
  interval, instead of one spilled request at a time). The router
  also reports every mid-forward transport loss here
  (:meth:`HealthManager.note_transport_loss`), so a crash under
  traffic is declared the moment its first request fails, not a
  probe interval later.
- **Supervised respawn** — a dead worker is respawned on its
  ORIGINAL socket/worker_id (``fleet.spawn_worker``), with
  per-worker exponential backoff (``TPK_FLEET_RESTART_BACKOFF_S``
  doubling per consecutive crash) and a crash-loop quarantine:
  ``TPK_FLEET_RESTART_MAX`` confirmed crashes without an intervening
  stable period → ``worker_quarantined``, the worker is left out of
  the ring LOUDLY (stderr + journal + `serve_ctl status` column) —
  the supervisor's step-quarantine contract applied to processes.
  ``serve_ctl undrain I`` resets the quarantine.
- **Rejoin gate** — a respawned worker takes traffic only after a
  clean ping AND a prewarm smoke (one small correctness-checked
  ``scan`` dispatch straight at the worker socket, forcing backend
  init + a real compile through the full serve path), so a half-up
  worker — daemon bound but jax wedged — never rejoins the ring.
  Death during the smoke (the crash-loop case) counts as a
  confirmed crash.
- **Immediate shm sweep** — a dead worker's ``tpkserve-<pid>-*``
  segments are unlinked the moment it is declared dead
  (``protocol.sweep_segments_for_pid``; the swept byte count rides
  the ``worker_dead`` event) instead of waiting for the next
  daemon/router start.

Evidence: ``worker_dead`` / ``worker_respawned`` /
``worker_quarantined`` journal kinds, ``fleet.restarts`` counter and
``fleet.live_workers`` gauge (docs/OBSERVABILITY.md). The in-flight
replay (``serve_request_replayed``) and the degradation levels
(``fleet_degraded``, priority-ordered shedding) live in
``router.py`` — the router owns the requests; this module owns the
processes.

Stdlib + numpy at import, like the rest of the serve package's
server side: nothing here can compile or wedge.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from tpukernels.obs import metrics as obs_metrics
from tpukernels.resilience import journal, watchdog
from tpukernels.serve import fleet, protocol

DEFAULT_PROBE_S = 5.0
DEFAULT_RESTART_MAX = 3
DEFAULT_BACKOFF_S = 1.0

# consecutive healthy probes after which a worker's crash counter
# resets — the "window" of the crash-loop contract: crashes only
# accumulate toward quarantine while the worker never stays up this
# long (docs/SERVING.md §self-healing)
STABLE_PROBES = 10

# a worker that has NEVER been seen holding its pidfile flock gets
# this much startup grace (floored — a loaded CI host can take
# seconds just to import the daemon) before a free flock can read as
# death: start-fleet's workers bind/flock asynchronously. Respawned
# workers don't need it — the manager owns their Popen and polls it.
START_GRACE_PROBES = 6
START_GRACE_FLOOR_S = 20.0

# the rejoin smoke's client timeout: it deliberately rides out the
# respawned worker's backend init + first compile
SMOKE_TIMEOUT_S = 120.0

# shed-hint ceiling: an honest "the worker is respawning" hint, not a
# ban (the router's MAX_RETRY_HINT_S is for pacing; degradation waits
# are longer but still bounded)
MAX_DEGRADED_HINT_S = 30.0


def _float_env(name: str, default: float, floor: float = 0.0) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = float(raw)
    except ValueError:
        val = floor - 1.0
    if val < floor:
        raise ValueError(
            f"{name}={raw!r}: expected a number >= {floor}"
        )
    return val


def _int_env(name: str, default: int, floor: int = 1) -> int:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = int(raw)
    except ValueError:
        val = floor - 1
    if val < floor:
        raise ValueError(f"{name}={raw!r}: expected an int >= {floor}")
    return val


def pidfile_state(path: str):
    """``(held, pid_or_None)``: ``held`` means a LIVE process flocks
    the pidfile (the revalidate_lib convention — test the lock, never
    trust the recorded pid alone). Shared by this module's probes and
    ``tools/serve_ctl.py``."""
    import fcntl

    try:
        f = open(path)
    except OSError:
        return False, None
    with f:
        content = f.readline().strip()
        pid = int(content) if content.isdigit() else None
        try:
            fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
            fcntl.flock(f.fileno(), fcntl.LOCK_UN)
        except OSError:
            return True, pid
    return False, pid


def worker_pidfile(socket_path: str) -> str:
    """A fleet worker daemon's pidfile lives beside its socket (its
    ``TPK_SERVE_DIR`` is the socket's directory — ``fleet.py``)."""
    return os.path.join(os.path.dirname(socket_path), "serve.pid")


def probe_worker(socket_path: str, timeout_s: float = 2.0):
    """One standalone liveness probe of one worker: ``(state, pid)``
    with state ``up`` (ping answers) / ``slow`` (flock held, ping
    dead) / ``dead`` (flock free). The read-only half of the manager
    loop, shared with ``serve_ctl health``."""
    held, pid = pidfile_state(worker_pidfile(socket_path))
    answered = _ping_ok(socket_path, timeout_s)
    if answered:
        return "up", pid
    return ("slow" if held else "dead"), pid


def _ping_ok(socket_path: str, timeout_s: float) -> bool:
    import socket as socket_mod

    s = socket_mod.socket(socket_mod.AF_UNIX, socket_mod.SOCK_STREAM)
    s.settimeout(timeout_s)
    try:
        s.connect(socket_path)
        protocol.send_frame(s, {"v": protocol.VERSION, "op": "ping"})
        frame = protocol.recv_frame(s)
        return frame is not None and bool(frame[0].get("ok"))
    except (OSError, protocol.ProtocolError):
        return False
    finally:
        try:
            s.close()
        except OSError:
            pass


class _Worker:
    """One ring member's health state (guarded by the manager lock)."""

    __slots__ = ("idx", "socket", "state", "pid", "crashes",
                 "restarts", "next_attempt", "up_streak",
                 "seen_alive", "born", "died_at", "proc",
                 "smoke_fails")

    def __init__(self, idx: int, socket_path: str):
        self.idx = idx
        self.socket = socket_path
        self.state = "up"       # up | slow | down | joining | quarantined
        self.pid = None
        self.crashes = 0        # confirmed crashes this window
        self.restarts = 0       # respawns attempted, lifetime
        self.next_attempt = 0.0
        self.up_streak = 0
        self.seen_alive = False
        self.born = time.perf_counter()
        self.died_at = None
        self.proc = None        # last respawn Popen (reaped lazily)
        self.smoke_fails = 0    # consecutive failed rejoin smokes


class HealthManager:
    """The fleet's self-healing loop. ``router`` is duck-typed: it
    needs ``set_worker_down(idx, down, quarantined=False)`` and
    ``worker_draining(idx) -> bool``; ``None`` runs the manager
    standalone (probe + respawn, no routing integration)."""

    def __init__(self, workers, repo: str, router=None,
                 probe_s=None, restart_max=None, backoff_s=None):
        self.workers = [_Worker(i, w) for i, w in enumerate(workers)]
        self.repo = repo
        self.router = router
        self.probe_s = (probe_s if probe_s is not None
                        else _float_env("TPK_FLEET_PROBE_S",
                                        DEFAULT_PROBE_S))
        self.restart_max = (restart_max if restart_max is not None
                            else _int_env("TPK_FLEET_RESTART_MAX",
                                          DEFAULT_RESTART_MAX))
        self.backoff_s = (backoff_s if backoff_s is not None
                          else _float_env("TPK_FLEET_RESTART_BACKOFF_S",
                                          DEFAULT_BACKOFF_S,
                                          floor=0.05))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._smoke_seq = 0

    # -------------------------------------------------------------- #
    # lifecycle                                                      #
    # -------------------------------------------------------------- #

    def start(self):
        if self.probe_s <= 0:
            return  # TPK_FLEET_PROBE_S=0: detection/respawn disabled
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="fleet-health",
        )
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.wait(self.probe_s):
            try:
                self.probe_pass()
            except Exception as e:  # noqa: BLE001 — the loop must survive
                print(f"# fleet-health: probe pass errored: {e!r}",
                      file=sys.stderr)

    # -------------------------------------------------------------- #
    # queries (router / serve_ctl surfaces)                          #
    # -------------------------------------------------------------- #

    def row(self, idx: int) -> dict:
        """The worker's health columns for ping/status payloads."""
        w = self.workers[idx]
        with self._lock:
            return {
                "state": w.state,
                "restarts": w.restarts,
                "crashes": w.crashes,
                "quarantined": w.state == "quarantined",
            }

    def live_count(self) -> int:
        with self._lock:
            return sum(1 for w in self.workers
                       if w.state in ("up", "slow"))

    def retry_hint(self, indices=None) -> float:
        """Honest ``retry_after_s`` for a shed request: the soonest
        moment any of the named down workers could be back (next
        respawn attempt + a probe/smoke margin), capped. Quarantined
        workers contribute the cap — they are not coming back without
        an operator."""
        now = time.perf_counter()
        hints = []
        with self._lock:
            for w in self.workers:
                if indices is not None and w.idx not in indices:
                    continue
                if w.state == "down":
                    hints.append(max(0.0, w.next_attempt - now)
                                 + self.probe_s)
                elif w.state == "joining":
                    hints.append(self.probe_s)
                elif w.state == "quarantined":
                    hints.append(MAX_DEGRADED_HINT_S)
        if not hints:
            return max(0.1, self.probe_s)
        return round(min(MAX_DEGRADED_HINT_S, max(0.1, min(hints))), 3)

    def reset(self, idx: int):
        """Operator override (``serve_ctl undrain``): forget the
        crash window and quarantine — but PROBE before re-ringing.
        The raw undrain control op can arrive without serve_ctl's
        restart-first discipline, and trusting it blindly would put
        a corpse back in the ring behind a fresh startup grace. A
        flock-holding worker rejoins immediately; a dead one is
        scheduled for an IMMEDIATE supervised respawn instead."""
        w = self.workers[idx]
        with self._lock:
            w.crashes = 0
            w.smoke_fails = 0
            w.up_streak = 0
            w.next_attempt = 0.0
        held, pid = pidfile_state(worker_pidfile(w.socket))
        if held or self.probe_s <= 0:
            # alive (liveness IS the flock) — or the manager is
            # disabled and cannot revive anything: restore the
            # pre-self-healing contract of trusting the operator
            with self._lock:
                w.state = "up"
                w.pid = pid if held else w.pid
                w.seen_alive = held
                w.born = time.perf_counter()
            if self.router is not None:
                self.router.set_worker_down(idx, False)
            return
        with self._lock:
            w.state = "down"
            if w.died_at is None:
                w.died_at = time.perf_counter()
        print(f"# fleet-health: undrained worker {idx} is still "
              "DEAD - respawning it now instead of ringing a corpse",
              file=sys.stderr)
        if self.router is not None:
            self.router.set_worker_down(idx, True)

    # -------------------------------------------------------------- #
    # detection                                                      #
    # -------------------------------------------------------------- #

    def _draining(self, idx: int) -> bool:
        if self.router is None:
            return False
        return self.router.worker_draining(idx)

    def note_transport_loss(self, idx: int) -> bool:
        """Router hook on a failed forward: is this worker DEAD (vs a
        drain window / a transient hiccup)? A free pidfile flock is
        the definitive answer; a confirmed death runs the full
        declaration (sweep, respawn scheduling, ring removal) NOW —
        in-flight recovery must not wait out a probe interval."""
        if self.probe_s <= 0:
            # TPK_FLEET_PROBE_S=0 disables detection AND respawn:
            # declaring a death here with no loop to revive it would
            # remove the worker from the ring permanently — the
            # pre-self-healing spill behavior is the honest fallback
            return False
        if self._draining(idx):
            return False
        w = self.workers[idx]
        with self._lock:
            if w.state in ("down", "joining", "quarantined"):
                return True  # already declared; the respawn loop owns it
        # SIGKILL teardown closes the worker's fds one at a time: the
        # forward's socket can error a few ms BEFORE the pidfile
        # flock releases, and reading that window as "alive" would
        # demote the replay to a plain spill. Give death two short
        # rechecks; a genuinely live (wedged) worker costs this rare
        # path ~150 ms, a dying one is caught at the price of none.
        held, pid = pidfile_state(worker_pidfile(w.socket))
        for wait in (0.05, 0.1):
            if not held:
                break
            time.sleep(wait)
            held, pid = pidfile_state(worker_pidfile(w.socket))
        if held:
            return False
        with self._lock:
            if w.state in ("down", "joining", "quarantined"):
                # the probe thread declared it during our recheck
                # window: it IS dead — the answer this request needs
                # for its replay
                return True
            if not w.seen_alive and (
                    time.perf_counter() - w.born
                    < self._start_grace_s()):
                return False  # still starting up, not dead
        self._declare_dead(w, pid, via="transport")
        return True

    def _start_grace_s(self) -> float:
        return max(START_GRACE_FLOOR_S,
                   START_GRACE_PROBES * max(self.probe_s, 0.1))

    def probe_pass(self):
        """One sweep over every ring member; drives the per-worker
        state machine. Called from the manager thread (and directly
        by tests)."""
        now = time.perf_counter()
        for w in self.workers:
            if self._draining(w.idx):
                continue
            with self._lock:
                state = w.state
            if state == "quarantined":
                continue
            if state in ("up", "slow"):
                self._probe_live(w)
            elif state == "down":
                with self._lock:
                    due = now >= w.next_attempt
                if due:
                    self._respawn(w)
            elif state == "joining":
                self._try_rejoin(w)
        obs_metrics.gauge("fleet.live_workers", self.live_count())

    def _probe_live(self, w: _Worker):
        held, pid = pidfile_state(worker_pidfile(w.socket))
        if held:
            with self._lock:
                w.seen_alive = True
                w.pid = pid
            if _ping_ok(w.socket, max(0.5, min(2.0, self.probe_s))):
                with self._lock:
                    was = w.state
                    w.state = "up"
                    w.up_streak += 1
                    if w.up_streak >= STABLE_PROBES and w.crashes:
                        # stable window survived: the crash-loop
                        # counter starts over
                        w.crashes = 0
                if was == "slow" and self.router is not None:
                    self.router.set_worker_down(w.idx, False)
                return
            with self._lock:
                transition = w.state == "up" and w.seen_alive
                w.state = "slow"
                w.up_streak = 0
            if transition:
                # dead-vs-slow discrimination, journaled: the flock
                # answers (process alive) so this is SLOW — a wedged
                # request is the daemon watchdog's job, not a death
                watchdog.classify_timeout(
                    True, site="fleet_health", worker=w.idx,
                    socket=w.socket,
                )
            return
        # flock free: either a worker that never came up (startup
        # grace) or a confirmed death
        with self._lock:
            starting = not w.seen_alive and (
                time.perf_counter() - w.born < self._start_grace_s()
            )
        if starting:
            return
        if self._draining(w.idx):
            return  # the drain stopped it on purpose (late re-check)
        self._declare_dead(w, pid, via="probe")

    def _declare_dead(self, w: _Worker, pid, via: str):
        with self._lock:
            if w.state in ("down", "quarantined"):
                return  # already declared (probe/transport race)
            w.state = "down"
            w.up_streak = 0
            # seen_alive stays True: it means "alive at some point
            # since its last (re)start", the predicate the startup
            # grace keys on — resetting it HERE would let a death
            # masquerade as a slow start (_respawn resets it)
            w.died_at = time.perf_counter()
            w.crashes += 1
            crashes = w.crashes
            pid = pid if pid is not None else w.pid
            w.pid = None
            # exponential per-consecutive-crash backoff before the
            # respawn; the first crash respawns after one base wait
            backoff = self.backoff_s * (2 ** (crashes - 1))
            w.next_attempt = time.perf_counter() + backoff
        # the dead worker's shm segments must not wait for the next
        # daemon start's sweep (satellite: fix the leak-on-crash
        # window) — reclaim them NOW, and put the byte count on the
        # event so the leak is observable
        swept_n, swept_b = (0, 0)
        if pid is not None:
            swept_n, swept_b = protocol.sweep_segments_for_pid(pid)
        # worker_pid, not pid: the journal's common `pid` stamp names
        # the EMITTING process (this router) and must not be shadowed
        journal.emit(
            "worker_dead", worker=w.idx, socket=w.socket,
            worker_pid=pid,
            via=via, crashes=crashes, backoff_s=round(backoff, 3),
            swept_segments=swept_n, swept_bytes=swept_b,
        )
        print(f"# fleet-health: worker {w.idx} DEAD ({via}, crash "
              f"{crashes}) - respawn in {backoff:.1f}s", file=sys.stderr)
        if self.router is not None:
            self.router.set_worker_down(w.idx, True)
        if crashes >= self.restart_max:
            self._quarantine(w)

    def _quarantine(self, w: _Worker, reason: str = "crash-loop"):
        with self._lock:
            w.state = "quarantined"
            crashes = w.crashes
            smoke_fails = w.smoke_fails
        journal.emit(
            "worker_quarantined", worker=w.idx, socket=w.socket,
            reason=reason, crashes=crashes, smoke_fails=smoke_fails,
            threshold=self.restart_max,
            stable_probes=STABLE_PROBES,
        )
        print(f"# fleet-health: worker {w.idx} QUARANTINED "
              f"({reason}: {crashes} crash(es), {smoke_fails} failed "
              f"smoke(s); threshold {self.restart_max}) - "
              "left out of the ring; `serve_ctl undrain "
              f"{w.idx}` resets", file=sys.stderr)
        if self.router is not None:
            self.router.set_worker_down(w.idx, True, quarantined=True)

    # -------------------------------------------------------------- #
    # recovery                                                       #
    # -------------------------------------------------------------- #

    def _respawn(self, w: _Worker):
        if w.proc is not None:
            w.proc.poll()  # reap the previous incarnation's zombie
        try:
            proc, _sock = fleet.spawn_worker(
                w.idx, self.repo, d=os.path.dirname(w.socket)
            )
        except OSError as e:
            with self._lock:
                w.next_attempt = (time.perf_counter()
                                  + self.backoff_s * (2 ** w.crashes))
            print(f"# fleet-health: respawn of worker {w.idx} failed "
                  f"({e}) - retrying", file=sys.stderr)
            return
        with self._lock:
            w.proc = proc
            w.restarts += 1
            w.state = "joining"
            w.seen_alive = False   # the NEW process: not yet observed
            w.born = time.perf_counter()
        obs_metrics.inc("fleet.restarts")
        print(f"# fleet-health: worker {w.idx} respawned "
              f"(pid {proc.pid}, attempt {w.restarts}) - awaiting "
              "ping + smoke before rejoin", file=sys.stderr)

    def _try_rejoin(self, w: _Worker):
        held, pid = pidfile_state(worker_pidfile(w.socket))
        if not held:
            # we OWN the respawned Popen: a live child that has not
            # flocked yet is still INITIALIZING (imports, bind) — an
            # exited one died before (or during) its join window,
            # which is a confirmed crash (the crash-loop path)
            if w.proc is not None and w.proc.poll() is None:
                return
            self._declare_dead(w, pid, via="join")
            return
        with self._lock:
            w.seen_alive = True
            w.pid = pid
        if not _ping_ok(w.socket, max(0.5, min(2.0, self.probe_s))):
            return  # daemon still initializing; next pass retries
        if not self._smoke(w):
            # the smoke failing is EITHER death-mid-smoke (the next
            # pass's flock check catches that as a crash) or a
            # HALF-UP worker: pings, dispatches, answers WRONG — the
            # exact suspect the gate exists for. Retrying forever
            # would keep the fleet degraded invisibly, so repeated
            # live-but-failing smokes escalate to the same loud
            # quarantine as a crash loop.
            with self._lock:
                alive = w.proc is None or w.proc.poll() is None
                if alive:
                    w.smoke_fails += 1
                fails = w.smoke_fails
            if alive and fails >= self.restart_max:
                self._quarantine(w, reason="smoke")
            return
        with self._lock:
            w.state = "up"
            w.up_streak = 1
            w.smoke_fails = 0
            down_s = (round(time.perf_counter() - w.died_at, 3)
                      if w.died_at is not None else None)
        journal.emit(
            "worker_respawned", worker=w.idx, socket=w.socket,
            worker_pid=pid, restarts=w.restarts, crashes=w.crashes,
            down_s=down_s,
        )
        print(f"# fleet-health: worker {w.idx} REJOINED the ring "
              f"(pid {pid}, down {down_s}s)", file=sys.stderr)
        if self.router is not None:
            self.router.set_worker_down(w.idx, False)

    def _smoke(self, w: _Worker) -> bool:
        """The rejoin gate's prewarm smoke: one small,
        correctness-checked ``scan`` dispatch straight at the worker
        socket — it forces backend init and a real compile through
        the full serve path, so a worker that pings but cannot
        dispatch never takes traffic."""
        import numpy as np

        from tpukernels.serve import client as serve_client

        x = (np.arange(64) % 7).astype(np.int32)
        want = np.cumsum(x, dtype=np.int64).astype(np.int32)
        self._smoke_seq += 1
        try:
            with serve_client.ServeClient(
                w.socket, timeout_s=SMOKE_TIMEOUT_S,
            ) as cli:
                cli.next_request_id = (
                    f"fleet-smoke-{w.idx}-{self._smoke_seq}"
                )
                out = cli.dispatch("scan", x)
        except (OSError, serve_client.ServeError,
                protocol.ProtocolError) as e:
            print(f"# fleet-health: worker {w.idx} rejoin smoke "
                  f"failed ({e!r}) - holding it out of the ring",
                  file=sys.stderr)
            return False
        if not np.array_equal(out, want):
            # a WRONG answer is louder than a dead socket: the worker
            # dispatches but cannot be trusted with traffic
            print(f"# fleet-health: worker {w.idx} rejoin smoke "
                  "returned a WRONG result - holding it out of the "
                  "ring", file=sys.stderr)
            return False
        return True
