"""Router guardian: the fleet's LAST single point of failure, closed
(docs/SERVING.md §guardian; docs/RESILIENCE.md §failure domains).

PR 14's health manager rides INSIDE the router process, so a ``kill
-9``'d router takes the whole self-healing loop down with it: workers
keep serving their sockets, but the front socket is gone, nobody
probes, nobody respawns, and every client ECONNREFUSEs until an
operator notices. This module is the router's supervisor — a separate
process (``serve_ctl guardian`` / ``fleet.spawn_guardian``) holding
the same pidfile-flock liveness contract the router holds over its
workers:

- **Detection** — every ``TPK_FLEET_PROBE_S`` the router's flocked
  pidfile is tested. A free flock is a death certificate (the
  revalidate_lib convention: dead processes RELEASE flocks; there is
  no ambiguous hang case). Declared within one probe interval as
  ``router_dead``, with the dead pid's ``/dev/shm`` segments swept
  immediately (``protocol.sweep_segments_for_pid``) — same
  leak-closing discipline as a worker death.
- **Supervised respawn** — the router is respawned on the ORIGINAL
  front socket from the config of record (``fleet.load_config``), with
  exponential backoff (``TPK_ROUTER_RESTART_BACKOFF_S`` doubling per
  consecutive crash) and a crash-loop quarantine at
  ``TPK_ROUTER_RESTART_MAX`` crashes without an intervening stable
  window (``router_quarantined``; the guardian keeps running, inert —
  ``serve_ctl start-fleet`` resets). The respawned router's OWN
  health manager converges to true fleet state by probing worker
  pidfiles + sockets — healthy workers are NOT restarted.
- **Rejoin gate** — the respawn only counts (``router_respawned``)
  after the new router holds its flock, answers a ping, AND routes a
  small correctness-checked ``scan`` smoke through the front socket
  to a live worker. A router that binds but cannot route never
  silently "recovers".

The other half of the crash story — the accepted requests in flight
inside the dead router — is the WAL's (``serve/wal.py``): the
respawned router replays them before its front socket opens, and
clients absorb the refused-connection window
(``client.dispatch_with_backpressure``'s ``TPK_CLIENT_RECONNECT_S``
budget). Together: a router SIGKILL under load costs zero accepted
requests.

Evidence: ``router_dead`` / ``router_respawned`` /
``router_quarantined`` journal kinds (docs/OBSERVABILITY.md). Clean
path prints NOTHING to stdout (notes go to stderr, evidence to the
journal) — daemon discipline like the rest of the serve package.

Stdlib + numpy at import: the guardian must never compile or wedge.
"""

from __future__ import annotations

import os
import sys
import threading
import time

from tpukernels.resilience import journal
from tpukernels.serve import fleet, health, protocol

DEFAULT_RESTART_MAX = 3
DEFAULT_BACKOFF_S = 1.0


class Guardian:
    """The router's supervisor loop. State machine mirrors one
    ``health._Worker``: up | down | joining | quarantined, with
    startup grace keyed on "never seen holding the flock"."""

    def __init__(self, repo: str, probe_s=None, restart_max=None,
                 backoff_s=None):
        self.repo = repo
        self.probe_s = (probe_s if probe_s is not None
                        else health._float_env("TPK_FLEET_PROBE_S",
                                               health.DEFAULT_PROBE_S))
        self.restart_max = (
            restart_max if restart_max is not None
            else health._int_env("TPK_ROUTER_RESTART_MAX",
                                 DEFAULT_RESTART_MAX))
        self.backoff_s = (
            backoff_s if backoff_s is not None
            else health._float_env("TPK_ROUTER_RESTART_BACKOFF_S",
                                   DEFAULT_BACKOFF_S, floor=0.05))
        self.state = "up"
        self.pid = None
        self.crashes = 0
        self.restarts = 0
        self.up_streak = 0
        self.seen_alive = False
        self.born = time.perf_counter()
        self.died_at = None
        self.next_attempt = 0.0
        self.proc = None
        self._smoke_seq = 0
        self._stop = threading.Event()

    # ------------------------------------------------------------ #
    # lifecycle                                                    #
    # ------------------------------------------------------------ #

    def run(self):
        while not self._stop.is_set():
            self.probe_pass()
            self._stop.wait(self.probe_s)

    def stop(self, *_):
        self._stop.set()

    # ------------------------------------------------------------ #
    # the state machine                                            #
    # ------------------------------------------------------------ #

    def _start_grace_s(self) -> float:
        return max(health.START_GRACE_FLOOR_S,
                   health.START_GRACE_PROBES * max(self.probe_s, 0.1))

    def probe_pass(self):
        if self.state == "quarantined":
            return
        if self.state == "down":
            if time.perf_counter() >= self.next_attempt:
                self._respawn()
            return
        if self.state == "joining":
            self._try_rejoin()
            return
        held, pid = health.pidfile_state(fleet.router_pidfile_path())
        if held:
            self.seen_alive = True
            self.pid = pid
            self.up_streak += 1
            if self.up_streak >= health.STABLE_PROBES and self.crashes:
                # stable window survived: crash-loop counter restarts
                self.crashes = 0
            return
        if not self.seen_alive and (
                time.perf_counter() - self.born < self._start_grace_s()):
            return  # start-fleet's router binds/flocks asynchronously
        self._declare_dead(pid, via="probe")

    def _declare_dead(self, pid, via: str):
        self.state = "down"
        self.up_streak = 0
        self.died_at = time.perf_counter()
        self.crashes += 1
        pid = pid if pid is not None else self.pid
        self.pid = None
        backoff = self.backoff_s * (2 ** (self.crashes - 1))
        self.next_attempt = time.perf_counter() + backoff
        # the dead router never relayed shm payloads of its own, but a
        # crash mid-reply can leave response segments it re-homed —
        # sweep anything its pid created NOW, like a worker death
        swept_n, swept_b = (0, 0)
        if pid is not None:
            swept_n, swept_b = protocol.sweep_segments_for_pid(pid)
        journal.emit(
            "router_dead", router_pid=pid, via=via,
            crashes=self.crashes, backoff_s=round(backoff, 3),
            swept_segments=swept_n, swept_bytes=swept_b,
        )
        print(f"# guardian: router DEAD ({via}, crash {self.crashes})"
              f" - respawn in {backoff:.1f}s", file=sys.stderr)
        if self.crashes >= self.restart_max:
            self._quarantine()

    def _quarantine(self):
        self.state = "quarantined"
        journal.emit(
            "router_quarantined", crashes=self.crashes,
            threshold=self.restart_max,
            stable_probes=health.STABLE_PROBES,
        )
        print(f"# guardian: router QUARANTINED ({self.crashes} "
              f"crash(es); threshold {self.restart_max}) - not "
              "respawning; `serve_ctl start-fleet` resets",
              file=sys.stderr)

    def _respawn(self):
        cfg = fleet.load_config()
        if cfg is None:
            # no config of record (torn, or the fleet was stopped out
            # from under us): nothing to respawn FROM — retry later,
            # loudly, rather than invent a topology
            self.next_attempt = (time.perf_counter()
                                 + self.backoff_s * (2 ** self.crashes))
            print("# guardian: no readable fleet.json - cannot "
                  "respawn the router yet", file=sys.stderr)
            return
        if self.proc is not None:
            self.proc.poll()  # reap the previous incarnation's zombie
        try:
            self.proc = fleet.spawn_router(
                cfg["front"], cfg["workers"], self.repo
            )
        except OSError as e:
            self.next_attempt = (time.perf_counter()
                                 + self.backoff_s * (2 ** self.crashes))
            print(f"# guardian: router respawn failed ({e}) - "
                  "retrying", file=sys.stderr)
            return
        self.state = "joining"
        self.seen_alive = False   # the NEW process: not yet observed
        self.born = time.perf_counter()
        self.restarts += 1
        print(f"# guardian: router respawned (pid {self.proc.pid}, "
              f"attempt {self.restarts}) - awaiting flock + ping + "
              "smoke", file=sys.stderr)

    def _try_rejoin(self):
        held, pid = health.pidfile_state(fleet.router_pidfile_path())
        if not held:
            # we OWN the respawned Popen: a live child that has not
            # flocked yet is still initializing (imports, bind, WAL
            # replay); an exited one is a confirmed crash
            if self.proc is not None and self.proc.poll() is None:
                return
            self._declare_dead(pid, via="join")
            return
        self.seen_alive = True
        self.pid = pid
        cfg = fleet.load_config()
        front = (cfg or {}).get("front") or fleet.front_socket_path()
        if not health._ping_ok(front,
                               max(0.5, min(2.0, self.probe_s))):
            return  # router still initializing; next pass retries
        if not self._smoke(front):
            return  # death-mid-smoke is caught by the next flock pass
        self.state = "up"
        self.up_streak = 1
        down_s = (round(time.perf_counter() - self.died_at, 3)
                  if self.died_at is not None else None)
        journal.emit(
            "router_respawned", router_pid=pid,
            restarts=self.restarts, crashes=self.crashes,
            down_s=down_s,
        )
        print(f"# guardian: router RECOVERED (pid {pid}, down "
              f"{down_s}s)", file=sys.stderr)

    def _smoke(self, front: str) -> bool:
        """The rejoin gate's dispatch smoke: one small
        correctness-checked ``scan`` THROUGH the front socket — it
        proves the respawned router can actually route to a live
        worker, not merely bind."""
        import numpy as np

        from tpukernels.serve import client as serve_client

        x = (np.arange(64) % 7).astype(np.int32)
        want = np.cumsum(x, dtype=np.int64).astype(np.int32)
        self._smoke_seq += 1
        try:
            with serve_client.ServeClient(
                front, timeout_s=health.SMOKE_TIMEOUT_S,
            ) as cli:
                cli.next_request_id = f"router-smoke-{self._smoke_seq}"
                out = cli.dispatch("scan", x)
        except (OSError, serve_client.ServeError,
                protocol.ProtocolError) as e:
            print(f"# guardian: router rejoin smoke failed ({e!r})",
                  file=sys.stderr)
            return False
        if not np.array_equal(out, want):
            print("# guardian: router rejoin smoke returned a WRONG "
                  "result - holding", file=sys.stderr)
            return False
        return True


# ------------------------------------------------------------------ #
# CLI entry (python -m tpukernels.serve.guardian)                    #
# ------------------------------------------------------------------ #


def main(argv=None):
    import signal

    from tpukernels.serve import server as serve_server

    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__, file=sys.stderr)
        return 0
    if argv:
        print(f"guardian: unknown argument {argv[0]!r}",
              file=sys.stderr)
        return 2
    if fleet.load_config() is None:
        print("guardian: no fleet here (fleet.json missing or "
              "unreadable) - start one first", file=sys.stderr)
        return 2
    try:
        g = Guardian(repo=os.getcwd())
    except ValueError as e:
        print(f"guardian: {e}", file=sys.stderr)
        return 2
    try:
        pidfile = serve_server._hold_pidfile(
            fleet.guardian_pidfile_path()
        )
    except RuntimeError as e:
        print(f"guardian: {e}", file=sys.stderr)
        return 3
    if os.environ.get("TPK_HEALTH_JOURNAL") is None:
        os.environ["TPK_HEALTH_JOURNAL"] = journal.default_path()
    signal.signal(signal.SIGTERM, g.stop)
    signal.signal(signal.SIGINT, g.stop)
    print(f"# guardian: watching {fleet.router_pidfile_path()} "
          f"(pid {os.getpid()}, probe {g.probe_s}s)", file=sys.stderr)
    try:
        g.run()
    finally:
        try:
            pidfile.close()
            os.unlink(fleet.guardian_pidfile_path())
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
