"""Front-end router for the sharded serving fleet (docs/SERVING.md
§fleet).

PR 10's daemon is one process: a single wedged bucket or one hot
client caps the whole service. This module is the scale-out front
end: a daemon that accepts the SAME ``protocol.py`` framing on one
front socket and forwards every dispatch to one of N worker daemons
(each a plain ``python -m tpukernels.serve`` process on its own
socket). Because the router is protocol-compatible with the single
daemon, every existing client — ``ServeClient``, ``capi.run_from_c``
via ``TPK_SERVE_SOCKET``, ``loadgen --serve`` — talks to a fleet by
pointing at the front socket, unchanged.

The routing disciplines, each CPU-chaos-proven (tests/test_fleet.py):

- **Consistent bucket routing** — each request is hashed by its
  (kernel, bucket) key (``bucketing.bucket_id``, the same key the
  worker's batch/lock layer uses) with a deterministic md5 ring, so
  every request for one bucket lands on ONE worker: that worker's
  executable memo owns the bucket and the PR-10 one-compile assertion
  holds across the whole fleet (test-asserted from ``aot_hit``/
  ``aot_miss`` journal evidence).
- **Spill on backpressure** — a worker's admission-control rejection
  (``retry_after_s``) is NOT bounced to the client: the router
  forwards the request to the bucket's deterministic ring sibling
  instead (``serve_spill``, reason ``overloaded``). At most two
  workers ever compile one bucket — the primary and its fixed
  sibling — so spill trades one extra compile for absorbed bursts,
  never a fleet-wide compile storm. Only when the sibling also
  rejects does the client see ``retry_after_s``.
- **Failover on transport loss / wedge** — a worker that dies
  mid-request (the drain-stop window) or answers ``kind: "wedged"``
  (its own watchdog gave up twice) triggers the same deterministic
  spill (reasons ``transport`` / ``wedged``); a wedge additionally
  puts the worker on a routing cooldown (``TPK_ROUTE_COOLDOWN_S``)
  so its buckets fail over FIRST instead of re-feeding the wedge.
  Kernels are pure functions of their operands, which is what makes
  re-dispatching an accepted request on a sibling safe.
- **Live drain** — the ``{"op": "drain", "worker": i}`` control op
  (sent by ``serve_ctl drain``) removes a worker from the ring for
  NEW requests; its buckets deterministically fail over to the ring
  sibling while in-flight forwards finish (or, if the worker is
  stopped with forwards still in flight, the transport failover
  re-queues them through the router — PR 10's requeue path
  generalized across processes). ``undrain`` restores it. Zero
  accepted requests drop across a drain + supervisor-managed restart
  (test-asserted).
- **Self-healing** (docs/SERVING.md §self-healing) — the router
  process runs the fleet health manager (``serve/health.py``):
  periodic pidfile-flock + ping probes declare a crashed worker
  ``worker_dead`` within one probe interval (``TPK_FLEET_PROBE_S``),
  sweep its leaked ``/dev/shm`` segments immediately, and respawn it
  on its original socket with exponential backoff and a crash-loop
  quarantine (``TPK_FLEET_RESTART_MAX``); ring rejoin waits for a
  clean ping + prewarm smoke. An accepted request whose worker died
  mid-flight is re-routed ONCE to the ring sibling as a REPLAY
  (``serve_request_replayed``; the ``replay`` header documents the
  idempotency contract), so zero accepted requests drop across
  process death. When a bucket's home and sibling are both out, the
  router sheds by priority class — batch first, with an honest
  ``retry_after_s`` derived from the respawn backoff — and journals
  ``fleet_degraded`` level changes instead of timing clients out.
- **Per-tenant fairness** — admission at the router runs a token
  bucket per ``tenant`` (header field; ``TPK_ROUTE_TENANT_RATE``
  tokens/s up to ``TPK_ROUTE_TENANT_BURST``, 0 = quotas off). A
  tenant over quota is answered ``kind: "overloaded"`` with a
  refill-derived ``retry_after_s`` (``serve_tenant_throttled``) so
  one hot client backs off while the rest of the fleet's capacity
  stays available. Priority classes ride the same bucket: a
  ``"batch"`` request is only admitted while the tenant's bucket
  retains a reserve (1 + burst/2 tokens) kept for ``"interactive"``
  traffic, so background load yields first.

- **Deadlines + hedged dispatch** (docs/SERVING.md §deadlines,
  §hedged dispatch) — a request whose wire ``budget_ms`` is already
  gone is refused at the front door (``serve_deadline_infeasible``)
  and a WAL entry whose budget died across a crash is expired at
  dequeue time (``serve_request_expired``) instead of dispatched as
  doomed work; a forward that outlives the fleet's own forward-wall
  percentile (``TPK_ROUTE_HEDGE_PCTL``, default p95, 0 = off)
  re-issues the SAME request_id to the ring sibling as an idempotent
  replay — first response wins, the loser is cancelled best-effort
  (``serve_hedged`` / ``serve_cancelled``), at most one hedge per
  request, hedged fraction capped by ``TPK_ROUTE_HEDGE_MAX_FRAC``.

The router is deliberately **jax-free** (stdlib + numpy + the
bucket table): it computes bucket keys from the request header's arg
SPECS alone (``bucketing.spec_stubs`` — it never reads a payload
byte) and relays inline payloads verbatim — no device, no compile,
nothing to wedge. On the shm lane (docs/SERVING.md §wire format) it
relays only segment DESCRIPTORS: the client writes a tensor once
into ``/dev/shm`` and the owning worker maps it, so the fleet
front-end stops being O(tensor) entirely
(``serve.bytes_copied.<kernel>`` counts what still crosses it
inline). Clean-path stdout is EMPTY (notes to stderr, evidence to
the journal), like the worker daemon.

Run it: ``python -m tpukernels.serve.router --socket FRONT --worker
W0.sock --worker W1.sock ...`` — or let ``tools/serve_ctl.py
start-fleet N`` spawn workers + router together
(``tpukernels/serve/fleet.py``).
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import sys
import threading
import time

from tpukernels.obs import metrics as obs_metrics
from tpukernels.resilience import faults, journal
from tpukernels.serve import bucketing, protocol
from tpukernels.serve import wal as serve_wal

from tpukernels.serve.server import (  # the daemon's shared fail-loud
    DEFAULT_REQUEST_TIMEOUT_S,         # knob parser — one copy, not
    _float_knob,                       # a drifted twin
)

DEFAULT_TENANT_RATE = 0.0     # tokens/s; 0 = per-tenant quotas OFF
DEFAULT_TENANT_BURST = 8.0    # token-bucket capacity per tenant
DEFAULT_COOLDOWN_S = 30.0     # wedged-worker routing cooldown

# hedged dispatch (docs/SERVING.md §hedged dispatch): a request whose
# forward outlives the fleet's own p-th forward-wall percentile is
# re-issued to its ring sibling as an idempotent replay — the
# tail-at-scale tolerance move. 0 disables hedging entirely.
DEFAULT_HEDGE_PCTL = 95.0     # TPK_ROUTE_HEDGE_PCTL
DEFAULT_HEDGE_MAX_FRAC = 0.1  # TPK_ROUTE_HEDGE_MAX_FRAC: hedges/routed
HEDGE_MIN_SAMPLES = 20        # forward walls before the pctl is trusted

PRIORITIES = ("interactive", "batch")

# hint cap for throttle replies: at tiny refill rates the raw
# (need - tokens) / rate hint could tell a client to sleep for
# minutes — backpressure is a pacing signal, not a ban
MAX_RETRY_HINT_S = 5.0

# durable-admission bound (docs/SERVING.md §guardian): inline request
# payloads up to this many bytes ride into router.wal base64'd, so a
# respawned router can replay the request self-contained. Bigger
# requests (and shm-lane requests whose client already unlinked the
# segments) are skipped LOUDLY at replay time — the client's reconnect
# budget owns their retry.
WAL_MAX_PAYLOAD_B = 262144


def ring_order(bucket: str, n: int) -> list:
    """Deterministic worker preference order for one bucket key over
    an ``n``-worker fleet: md5 (stable across processes and runs —
    python's own ``hash`` is salted) picks the primary, then the ring
    walks forward. Index 0 is the bucket's home, index 1 its one
    deterministic spill sibling — the whole sharding contract in four
    lines, importable by tests and operators alike."""
    h = int(hashlib.md5(bucket.encode()).hexdigest(), 16)
    return [(h + k) % n for k in range(n)]


class _Upstream:
    """One worker's connection pool. Each pooled socket carries one
    outstanding request at a time (the protocol's pipelining
    contract); concurrent forwards to the same worker each take their
    own connection."""

    def __init__(self, path: str, timeout_s: float):
        self.path = path
        self.timeout_s = timeout_s
        self._idle: list = []
        self._lock = threading.Lock()

    def acquire(self):
        with self._lock:
            if self._idle:
                return self._idle.pop()
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout_s)
        try:
            s.connect(self.path)
        except OSError:
            s.close()
            raise
        return s

    def release(self, sock, poisoned: bool):
        if poisoned:
            try:
                sock.close()
            except OSError:
                pass
            return
        with self._lock:
            self._idle.append(sock)

    def close_all(self):
        with self._lock:
            idle, self._idle = self._idle, []
        for s in idle:
            try:
                s.close()
            except OSError:
                pass


class _Conn:
    """Front-socket connection + send lock (the server.py discipline:
    frames from concurrent repliers must never interleave)."""

    __slots__ = ("sock", "send_lock")

    def __init__(self, sock):
        self.sock = sock
        self.send_lock = threading.Lock()

    def send(self, header, payloads=()) -> int:
        with self.send_lock:
            return protocol.send_frame(self.sock, header, payloads)


class _Attempt:
    """One racing upstream forward of a hedged dispatch. ``done`` is
    guarded by the shared race condition variable; ``alock`` guards
    the socket handoff so ``abort`` (the loser's fast exit) can never
    close a socket the pool already owns again."""

    __slots__ = ("idx", "resp", "payloads", "exc", "done", "cond",
                 "sock", "alock", "aborted")

    def __init__(self, idx: int, cond):
        self.idx = idx
        self.resp = None
        self.payloads = ()
        self.exc = None
        self.done = False
        self.cond = cond
        self.sock = None
        self.alock = threading.Lock()
        self.aborted = False

    def abort(self):
        """Close the attempt's live socket from outside: a loser whose
        reply the worker suppressed (in-flight cancel) would otherwise
        sit in recv until the pool timeout — the close errors it out
        NOW, and the release path poisons the connection."""
        with self.alock:
            self.aborted = True
            s = self.sock
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass


class Router:
    def __init__(self, socket_path: str, workers,
                 tenant_rate=None, tenant_burst=None, cooldown_s=None):
        if not workers:
            raise ValueError("router needs at least one --worker socket")
        self.socket_path = socket_path
        self.workers = list(workers)
        self.tenant_rate = (tenant_rate if tenant_rate is not None
                            else _float_knob("TPK_ROUTE_TENANT_RATE",
                                             DEFAULT_TENANT_RATE))
        self.tenant_burst = (tenant_burst if tenant_burst is not None
                             else _float_knob("TPK_ROUTE_TENANT_BURST",
                                              DEFAULT_TENANT_BURST,
                                              floor=1.0))
        self.cooldown_s = (cooldown_s if cooldown_s is not None
                           else _float_knob("TPK_ROUTE_COOLDOWN_S",
                                            DEFAULT_COOLDOWN_S))
        # hedged dispatch knobs (fail-loud, the _float_knob contract):
        # pctl 0 = off; max_frac caps the hedged fraction of routed
        # traffic so a fleet-wide slowdown cannot double its own load
        self.hedge_pctl = _float_knob("TPK_ROUTE_HEDGE_PCTL",
                                      DEFAULT_HEDGE_PCTL)
        self.hedge_max_frac = _float_knob("TPK_ROUTE_HEDGE_MAX_FRAC",
                                          DEFAULT_HEDGE_MAX_FRAC)
        # upstream patience: the worker's own watchdog bounds a
        # request (slow-grace + requeue-once + wedged-twice), so the
        # router waits comfortably past that before calling transport
        req_t = _float_knob("TPK_SERVE_REQUEST_TIMEOUT_S",
                            DEFAULT_REQUEST_TIMEOUT_S, floor=0.1)
        self._pools = [_Upstream(w, timeout_s=req_t * 8 + 30)
                       for w in self.workers]
        self._stop = threading.Event()
        self._listener = None
        self._lock = threading.Lock()
        self._draining: set = set()          # worker indices
        # self-healing state (docs/SERVING.md §self-healing): workers
        # the health manager declared dead/quarantined leave the ring
        # until their respawn passes the rejoin gate; the degradation
        # level derives from the down set and is journaled on change
        self._down: set = set()              # dead / not-yet-rejoined
        self._quarantined: set = set()       # crash-looped, operator-gated
        self._health = None                  # HealthManager, if attached
        self._level = "ok"                   # ok | degraded | critical
        self._cooldown: dict = {}            # idx -> until (perf_counter)
        self._inflight = [0] * len(self.workers)
        self._routed_to = [0] * len(self.workers)
        self._routed = 0
        self._spilled = 0
        self._throttled = 0
        self._rejected = 0
        self._hedged = 0
        self._expired = 0      # deadline died at router/WAL dequeue
        self._infeasible = 0   # refused at admission: budget already 0
        # forward-wall log-bucket histogram (obs/metrics.py buckets —
        # the hedge threshold is its p-th percentile): [count, max,
        # {bucket_index: n}], guarded by self._lock
        self._fwd_walls = [0, 0.0, {}]
        self._tenants: dict = {}             # tenant -> [tokens, last]
        self._meta = {"device_kind": None, "jax": None}
        self._meta_next_try = 0.0            # unresolved-meta rate limit
        # lane advertisement relayed from the workers: the router
        # itself never maps a segment — it forwards descriptors — but
        # clients negotiate against the FRONT socket, so the pong must
        # carry what the workers can do (docs/SERVING.md §wire format)
        self._lanes_cache = None
        self._shm_min_cache = None
        self._req_trace_cache = None   # workers' request_trace pong
        self._bytes_copied = 0               # relayed inline payload B
        # durable admission (docs/SERVING.md §guardian): accepted
        # requests land in router.wal before the forward; a respawned
        # router replays the unacked ones and STASHES their results so
        # the client's same-request_id retry is answered from the
        # stash — one delivery to the worker per request_id
        self._wal = None                     # serve_wal.Wal, if attached
        self._wal_seq = 0
        self._stash: dict = {}  # request_id -> {resp,payloads,worker,t}
        self._stash_ttl_s = req_t * 8 + 30   # same patience as the pools
        self._t0 = time.time()
        # fail-fast on a misconfigured bucket table, like the worker:
        # the router and its workers MUST shard on the same table
        bucketing.bucket_configs()

    # -------------------------------------------------------------- #
    # lifecycle                                                      #
    # -------------------------------------------------------------- #

    def serve_forever(self):
        d = os.path.dirname(self.socket_path)
        if d:
            os.makedirs(d, exist_ok=True)
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._listener = socket.socket(socket.AF_UNIX,
                                       socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(128)
        self._listener.settimeout(0.5)
        # the router is a start point too: reclaim segments whose
        # creator died before its peer unlinked them
        swept = protocol.sweep_stale_segments()
        journal.emit(
            "serve_start", role="router", socket=self.socket_path,
            workers=len(self.workers), worker_sockets=self.workers,
            tenant_rate=self.tenant_rate,
            tenant_burst=self.tenant_burst, shm_swept=swept,
        )
        try:
            while not self._stop.is_set():
                try:
                    conn, _addr = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(
                    target=self._client_loop, args=(_Conn(conn),),
                    daemon=True, name="route-client",
                ).start()
        finally:
            self.shutdown()

    def stop(self, *_sig):
        self._stop.set()

    def shutdown(self):
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
            for pool in self._pools:
                pool.close_all()
            # unclaimed replay results: free their response segments
            # now — no client is coming for them through THIS process
            with self._lock:
                stash, self._stash = self._stash, {}
            for hit in stash.values():
                self._drop_stashed(hit)
            if self._wal is not None:
                self._wal.close()
            journal.emit(
                "serve_stop", role="router", routed=self._routed,
                spilled=self._spilled, throttled=self._throttled,
                rejected=self._rejected, hedged=self._hedged,
                expired=self._expired, infeasible=self._infeasible,
                uptime_s=round(time.time() - self._t0, 3),
            )

    # -------------------------------------------------------------- #
    # self-healing hooks (serve/health.py)                           #
    # -------------------------------------------------------------- #

    def attach_health(self, hm):
        self._health = hm

    # -------------------------------------------------------------- #
    # durable admission (docs/SERVING.md §guardian)                  #
    # -------------------------------------------------------------- #

    def attach_wal(self, w):
        self._wal = w

    def _wal_record(self, header, payloads, kernel, bucket):
        """Persist one accepted request before its forward; returns
        the WAL key (None when no WAL is attached). Inline payloads up
        to WAL_MAX_PAYLOAD_B ride along base64'd so the replay is
        self-contained; oversize ones record their size and are
        skipped loudly at replay time."""
        if self._wal is None:
            return None
        with self._lock:
            self._wal_seq += 1
            seq = self._wal_seq
        key = f"{os.getpid()}-{seq}"
        # epoch wall time, not monotonic: replay happens in a FRESH
        # process after a crash, and epoch time is the only clock
        # that bridges incarnations on one host — it turns the
        # entry's budget_ms into a remaining budget at dequeue time
        entry = {"header": dict(header), "kernel": kernel,
                 "bucket": bucket, "t_wal": round(time.time(), 6)}
        total = sum(len(p) for p in payloads)
        if total <= WAL_MAX_PAYLOAD_B:
            entry["p64"] = [base64.b64encode(bytes(p)).decode("ascii")
                            for p in payloads]
        else:
            entry["oversize_b"] = total
        self._wal.append(key, entry)
        return key

    def replay_wal(self) -> int:
        """Drain the PREVIOUS incarnation's replay debt — called by
        ``main()`` after the pidfile is held and BEFORE the front
        socket opens, so every stashable result is stashed before any
        reconnecting client's same-request_id retry can arrive (no
        double-delivery window). Returns the entries processed."""
        if self._wal is None:
            return 0
        pending = self._wal.take_pending()
        for key, entry in pending.items():
            try:
                self._replay_one(key, entry if isinstance(entry, dict)
                                 else {})
            except Exception as e:  # one bad entry must not kill start
                print(f"# route: wal replay {key} failed: {e!r}",
                      file=sys.stderr)
                self._wal.ack(key)
        return len(pending)

    def _replay_one(self, key: str, entry: dict):
        header = dict(entry.get("header") or {})
        kernel = entry.get("kernel")
        bucket = entry.get("bucket")
        rid = header.get("id")
        req_id = header.get("request_id")
        req_id = str(req_id) if req_id is not None else None
        tenant = header.get("tenant") or "-"

        def skip(reason):
            journal.emit(
                "serve_request_replayed", via="wal", ok=False,
                reason=reason, kernel=kernel, bucket=bucket,
                request=rid, request_id=req_id, tenant=tenant,
            )
            print(f"# route: wal replay skipped "
                  f"{req_id or key}: {reason}", file=sys.stderr)
            self._wal.ack(key)

        p64 = entry.get("p64")
        if p64 is None:
            return skip("payload-not-journaled")
        if not kernel or not bucket:
            return skip("malformed-entry")
        # shm-lane operands: the client unlinks its request segments
        # the moment its round trip errors, so they are usually gone
        # by now — the client's reconnect retry owns those
        for d in (header.get("_shm") or ()):
            if isinstance(d, dict):
                name = str(d.get("name") or "")
                if not os.path.exists(
                        os.path.join(protocol.SHM_DIR, name)):
                    return skip("shm-gone")
        payloads = [base64.b64decode(s) for s in p64]
        # dequeue-time expiry (docs/SERVING.md §deadlines): the budget
        # kept draining while this entry sat in the WAL across the
        # crash — a dead budget is skipped as doomed work, a live one
        # is re-stamped with what actually remains for the forward hop
        budget = header.get("budget_ms")
        t_wal = entry.get("t_wal")
        if (isinstance(budget, (int, float))
                and not isinstance(budget, bool)
                and isinstance(t_wal, (int, float))):
            rem_ms = float(budget) - (time.time() - float(t_wal)) * 1e3
            if rem_ms <= 0.0:
                with self._lock:
                    self._expired += 1
                obs_metrics.inc("serve.expired")
                journal.emit(
                    "serve_request_expired", site="router",
                    where="wal_replay", kernel=kernel, bucket=bucket,
                    request=rid, request_id=req_id, tenant=tenant,
                )
                return skip("expired")
            header = protocol.stamp_budget(
                header, time.monotonic() + rem_ms / 1000.0)
        order = self._order(bucket)
        if not order:
            return skip("no-live-worker")
        try:
            prior = int(header.get("replay") or 0)
        except (TypeError, ValueError):
            prior = 0
        header["replay"] = prior + 1
        idx = order[0]
        journal.emit(
            "serve_request_replayed", via="wal", kernel=kernel,
            bucket=bucket, request=rid, request_id=req_id,
            to_worker=idx, tenant=tenant,
        )
        resp, out_payloads = None, ()
        for hop in range(2):
            try:
                resp, out_payloads = self._forward(idx, header,
                                                   payloads)
            except (OSError, protocol.ProtocolError):
                if self._health is not None:
                    self._health.note_transport_loss(idx)
                sibling = next((j for j in order if j != idx), None)
                if hop == 1 or sibling is None:
                    return skip("workers-unreachable")
                idx = sibling
                continue
            break
        with self._lock:
            self._routed += 1
            self._routed_to[idx] += 1
        obs_metrics.inc("serve.routed")
        journal.emit(
            "serve_route", kernel=kernel, bucket=bucket,
            request=rid, request_id=req_id, worker=idx,
            tenant=tenant,
            priority=header.get("priority") or "interactive",
            spilled_from=None, ok=bool(resp.get("ok")),
            wal_replay=True,
        )
        if req_id is not None:
            with self._lock:
                self._stash[req_id] = {
                    "resp": resp, "payloads": out_payloads,
                    "worker": idx, "t": time.perf_counter(),
                }
        else:
            # no request_id = no retry can ever claim it: the work
            # is done (and journaled); free any response segments
            self._drop_stashed({"resp": resp})
        self._wal.ack(key)

    def _take_stash(self, req_id: str):
        """Claim (and expire) stashed replay results. Expiry mirrors
        the reply()-to-a-gone-client path: response segments no one
        will map must not wait for an aged sweep."""
        now = time.perf_counter()
        expired = []
        with self._lock:
            hit = self._stash.pop(req_id, None)
            for k in [k for k, v in self._stash.items()
                      if now - v["t"] > self._stash_ttl_s]:
                expired.append(self._stash.pop(k))
        for v in expired:
            self._drop_stashed(v)
        return hit

    def _drop_stashed(self, hit):
        resp = (hit or {}).get("resp") or {}
        for d in (resp.get("_shm") or ()):
            if isinstance(d, dict):
                protocol.unlink_shm(d.get("name"))

    def worker_draining(self, idx: int) -> bool:
        with self._lock:
            return idx in self._draining

    def set_worker_down(self, idx: int, down: bool,
                        quarantined: bool = False):
        """Health-manager hook: a worker left (or rejoined) the ring.
        The idle connection pool is flushed BOTH ways — a dead
        worker's pooled sockets are corpses, and a respawned worker
        on the same socket path must never be spoken to through a
        connection to its predecessor."""
        with self._lock:
            if down:
                self._down.add(idx)
                if quarantined:
                    self._quarantined.add(idx)
            else:
                self._down.discard(idx)
                self._quarantined.discard(idx)
                self._cooldown.pop(idx, None)
        self._pools[idx].close_all()
        self._recompute_level()

    def _recompute_level(self):
        """Degradation level from the down set, journaled on CHANGE
        (``fleet_degraded``): ``degraded`` = at least one worker out
        but every bucket still has its home or sibling; ``critical``
        = some ring-adjacent pair is fully out, i.e. some buckets'
        home AND sibling are both gone and the router is shedding
        their load by priority class."""
        n = len(self.workers)
        with self._lock:
            down = set(self._down)
            quarantined = sorted(self._quarantined)
            if not down:
                level = "ok"
            elif len(down) >= n or any(
                    (i + 1) % n in down for i in down):
                level = "critical"
            else:
                level = "degraded"
            changed, self._level = level != self._level, level
        if not changed:
            return
        hint = (self._health.retry_hint(down) if self._health
                and down else 0.0)
        journal.emit(
            "fleet_degraded", level=level, down=sorted(down),
            quarantined=quarantined, n_workers=n,
            retry_after_s=hint,
        )
        print(f"# route: fleet {level.upper()}"
              + (f" - workers {sorted(down)} out of the ring"
                 f" (retry hint {hint}s)" if down else
                 " - all workers restored"), file=sys.stderr)

    def _shed(self, conn_reply, rid, req_id, kernel, bucket, tenant,
              priority, down):
        """Degradation shedding: answer the client honestly NOW —
        ``retry_after_s`` derived from the respawn backoff — instead
        of timing it out against workers that cannot answer
        (docs/SERVING.md §self-healing)."""
        retry = (self._health.retry_hint(down) if self._health
                 else max(0.1, DEFAULT_COOLDOWN_S / 10))
        with self._lock:
            self._rejected += 1
        obs_metrics.inc("serve.rejected")
        journal.emit(
            "serve_rejected", kernel=kernel, request=rid,
            request_id=req_id, reason="fleet_degraded",
            bucket=bucket, tenant=tenant, priority=priority,
            down=sorted(down), retry_after_s=retry,
        )
        conn_reply({
            "v": protocol.VERSION, "id": rid, "ok": False,
            "kind": "overloaded", "degraded": True,
            "retry_after_s": retry,
            "error": (f"fleet degraded: workers {sorted(down)} down; "
                      f"{priority} {kernel} shed - retry after "
                      f"{retry}s"),
        })

    def _refuse_infeasible(self, conn_reply, rid, req_id, kernel,
                           bucket, tenant, priority):
        """Admission-time deadline triage (docs/SERVING.md
        §deadlines): a request whose remaining budget is already zero
        cannot possibly make it — refuse it at the front door instead
        of spending a WAL fsync and a worker queue slot on doomed
        work. The hint is honest: 0.0, because a retry is welcome
        immediately — but only with a FRESH budget (the client maps
        this to ``ServeExpired``, which deliberately does not
        auto-retry the same shrinking one)."""
        with self._lock:
            self._infeasible += 1
        obs_metrics.inc("serve.deadline_infeasible")
        journal.emit(
            "serve_deadline_infeasible", kernel=kernel, bucket=bucket,
            request=rid, request_id=req_id, tenant=tenant,
            priority=priority, retry_after_s=0.0,
        )
        conn_reply({
            "v": protocol.VERSION, "id": rid, "ok": False,
            "kind": "deadline_infeasible", "retry_after_s": 0.0,
            "error": ("deadline infeasible: request budget already "
                      "spent before admission"),
        })

    def _expire_route(self, conn_reply, rid, req_id, kernel, bucket,
                      tenant, where):
        """Dequeue-time expiry (docs/SERVING.md §deadlines): the
        budget died while the request waited inside the router —
        answer ``expired`` instead of dispatching doomed work."""
        with self._lock:
            self._expired += 1
        obs_metrics.inc("serve.expired")
        journal.emit(
            "serve_request_expired", site="router", where=where,
            kernel=kernel, bucket=bucket, request=rid,
            request_id=req_id, tenant=tenant,
        )
        conn_reply({
            "v": protocol.VERSION, "id": rid, "ok": False,
            "kind": "expired",
            "error": f"deadline expired before forward ({where})",
        })

    # -------------------------------------------------------------- #
    # front side                                                     #
    # -------------------------------------------------------------- #

    def _client_loop(self, conn: _Conn):
        try:
            while not self._stop.is_set():
                frame = protocol.recv_frame(conn.sock)
                if frame is None:
                    return
                header, payloads = frame
                op = header.get("op")
                if op == "ping":
                    conn.send(self._stats())
                elif op == "stats":
                    conn.send(self._stats_fleet())
                elif op == "dispatch":
                    self._route(conn, header, payloads)
                elif op in ("drain", "undrain"):
                    conn.send(self._control(op, header))
                else:
                    conn.send({"v": protocol.VERSION,
                               "id": header.get("id"), "ok": False,
                               "kind": "error",
                               "error": f"unknown op {op!r}"})
        except (protocol.ProtocolError, OSError):
            pass  # poisoned/hung-up FRONT connection: only it dies
        finally:
            try:
                conn.sock.close()
            except OSError:
                pass

    def _stats(self) -> dict:
        meta = self._worker_meta()
        health = self._health
        hrows = ([health.row(i) for i in range(len(self.workers))]
                 if health is not None else
                 [{} for _ in self.workers])
        now = time.perf_counter()
        with self._lock:
            rows = [
                {
                    "socket": w,
                    "draining": i in self._draining,
                    "cooling": self._cooldown.get(i, 0.0) > now,
                    "down": i in self._down,
                    "inflight": self._inflight[i],
                    "routed": self._routed_to[i],
                    # liveness / restart-count / quarantine columns
                    # (docs/SERVING.md §self-healing; None without a
                    # health manager — a bare `--worker` router)
                    "state": hrows[i].get("state"),
                    "restarts": hrows[i].get("restarts"),
                    "quarantined": bool(hrows[i].get("quarantined")),
                }
                for i, w in enumerate(self.workers)
            ]
            level = self._level
            return {
                "op": "pong", "ok": True, "v": protocol.VERSION,
                "role": "router", "pid": os.getpid(),
                "workers": rows, "n_workers": len(self.workers),
                "level": level,
                "routed": self._routed, "spilled": self._spilled,
                "throttled": self._throttled,
                "rejected": self._rejected,
                "hedged": self._hedged, "expired": self._expired,
                "infeasible": self._infeasible,
                # lane negotiation happens against the FRONT socket:
                # relay what the workers advertised (None until one
                # answered = clients stay inline, the safe default)
                "lanes": self._lanes_cache or ["inline"],
                "shm_min_bytes": self._shm_min_cache,
                # relayed like lanes/shm_min_bytes: the fleet is
                # traced when its workers tag their journals (None
                # passes through until one answered — "unknown" must
                # not masquerade as an untraced fleet)
                "request_trace": self._req_trace_cache,
                "bytes_copied": self._bytes_copied,
                "uptime_s": round(time.time() - self._t0, 3),
                # loadgen --serve stamps its verdicts with these —
                # the fleet's device identity is its workers'
                "device_kind": meta["device_kind"],
                "jax": meta["jax"],
            }

    def _stats_fleet(self) -> dict:
        """The read-only ``stats`` op, fleet view (docs/SERVING.md
        §stats op): the router's own pong + live metrics snapshot,
        plus one upstream ``stats`` round trip per non-down worker
        (the ``_worker_meta`` pool pattern — acquire, frame, release,
        poison on transport failure) aggregated under ``worker_stats``
        (index-aligned with ``workers``; None for a worker that is
        down or did not answer) and summed into one ``fleet`` row.
        Touches only ``self._lock`` between fan-outs — a wedged
        worker costs its own row, never the whole view."""
        base = self._stats()
        base.update(
            op="stats",
            metrics=obs_metrics.snapshot(),
            last_snapshot_age_s=obs_metrics.last_flush_age_s(),
        )
        with self._lock:
            down = set(self._down)
        wstats: list = []
        for idx in range(len(self.workers)):
            if idx in down:
                wstats.append(None)
                continue
            pool = self._pools[idx]
            sock = None
            ok = False
            row = None
            try:
                sock = pool.acquire()
                protocol.send_frame(
                    sock, {"v": protocol.VERSION, "op": "stats"}
                )
                frame = protocol.recv_frame(sock)
                if frame is not None and frame[0].get("ok"):
                    row = frame[0]
                    ok = True
            except (OSError, protocol.ProtocolError):
                row = None
            finally:
                if sock is not None:
                    pool.release(sock, poisoned=not ok)
            wstats.append(row)
        fleet = {"served": 0, "rejected": 0, "requeued": 0,
                 "depth": 0, "inflight": 0, "bytes_copied": 0,
                 "answering": 0}
        for row in wstats:
            if not isinstance(row, dict):
                continue
            fleet["answering"] += 1
            for k in ("served", "rejected", "requeued", "depth",
                      "inflight", "bytes_copied"):
                v = row.get(k)
                if isinstance(v, (int, float)):
                    fleet[k] += v
        base["worker_stats"] = wstats
        base["fleet"] = fleet
        return base

    def _worker_meta(self) -> dict:
        """device_kind / jax version borrowed from the first worker
        that knows them (workers resolve both lazily at their first
        dispatch). Cached once ANY field resolves — the same
        predicate the store uses — and unresolved retries are
        rate-limited to one fan-out per second: a status/drain poll
        loop pinging the front socket 5x/s must not multiply into
        N upstream pings each. Meta pings skip draining workers and
        bypass the in-flight accounting drain waits on."""
        now = time.perf_counter()
        with self._lock:
            if (self._meta["jax"] is not None
                    or self._meta["device_kind"] is not None):
                return dict(self._meta)
            if now < self._meta_next_try:
                return dict(self._meta)
            self._meta_next_try = now + 1.0
            candidates = [i for i in range(len(self.workers))
                          if i not in self._draining]
        for idx in candidates:
            pool = self._pools[idx]
            sock = None
            ok = False
            try:
                sock = pool.acquire()
                protocol.send_frame(
                    sock, {"v": protocol.VERSION, "op": "ping"}
                )
                frame = protocol.recv_frame(sock)
                ok = frame is not None
            except (OSError, protocol.ProtocolError):
                continue
            finally:
                if sock is not None:
                    pool.release(sock, poisoned=not ok)
            if not ok:
                continue
            header = frame[0]
            with self._lock:
                if self._lanes_cache is None:
                    # lanes are static per worker process — cache them
                    # off the FIRST pong, before any dispatch resolves
                    # device_kind, so a client's negotiation ping gets
                    # an answer immediately
                    lanes = header.get("lanes")
                    self._lanes_cache = (
                        [str(x) for x in lanes]
                        if isinstance(lanes, list) else ["inline"]
                    )
                    self._shm_min_cache = header.get("shm_min_bytes")
                    self._req_trace_cache = bool(
                        header.get("request_trace")
                    )
            if header.get("device_kind") or header.get("jax"):
                with self._lock:
                    self._meta = {
                        "device_kind": header.get("device_kind"),
                        "jax": header.get("jax"),
                    }
                break
        with self._lock:
            return dict(self._meta)

    def _control(self, op: str, header: dict) -> dict:
        idx = header.get("worker")
        if not isinstance(idx, int) or isinstance(idx, bool) or \
                not 0 <= idx < len(self.workers):
            return {"v": protocol.VERSION, "ok": False, "kind": "error",
                    "error": f"bad worker index {idx!r} "
                             f"(fleet has {len(self.workers)})"}
        with self._lock:
            if op == "drain":
                self._draining.add(idx)
            else:
                self._draining.discard(idx)
                self._cooldown.pop(idx, None)
            inflight = self._inflight[idx]
        if op == "undrain":
            # undrain is the operator's "config changed" signal: a
            # promoted adaptive bucket table lands as a rewritten file
            # behind the unchanged TPK_SERVE_BUCKETS path, and the
            # router hashes buckets itself (spec_stubs/bucket_for) —
            # re-read it NOW or keep routing on yesterday's avatars
            # (docs/SERVING.md §adaptive buckets). A malformed table
            # answers as a control-channel error (the __init__
            # fail-fast rule, surfaced to the operator who undrained)
            # and the old parsed table stays in effect.
            try:
                bucketing.reload()
            except (OSError, ValueError) as e:
                return {"v": protocol.VERSION, "ok": False,
                        "kind": "error",
                        "error": f"undrain refused: TPK_SERVE_BUCKETS "
                                 f"reload failed: {e}"}
        if op == "undrain" and self._health is not None:
            # the operator restored this worker on purpose: forget its
            # crash window and quarantine — the next probe pass
            # re-verifies it (and respawns it if it is actually dead)
            self._health.reset(idx)
        # flush the worker's idle connection pool both ways: drained
        # workers get stopped (their pooled sockets go stale), and an
        # undrained worker is usually a FRESH process on the same
        # socket path — forwarding on a stale socket would read as a
        # spurious transport spill against a healthy restored worker
        self._pools[idx].close_all()
        journal.emit(
            "serve_drain", worker=idx, socket=self.workers[idx],
            phase="begin" if op == "drain" else "undrain",
            inflight=inflight,
        )
        print(f"# route: worker {idx} "
              + ("DRAINING" if op == "drain" else "restored")
              + f" ({inflight} in flight)", file=sys.stderr)
        return {"v": protocol.VERSION, "ok": True, "worker": idx,
                "draining": op == "drain", "inflight": inflight}

    # -------------------------------------------------------------- #
    # admission: per-tenant token buckets, priority reserve          #
    # -------------------------------------------------------------- #

    def _admit_tenant(self, tenant: str, priority: str):
        """(admitted, retry_after_s). Quotas off (rate <= 0) admit
        everything. A batch request must leave 1 + burst/2 tokens —
        the reserve interactive traffic draws on — so background load
        yields first when a tenant runs hot."""
        rate = self.tenant_rate
        if rate <= 0:
            return True, 0.0
        need = 1.0 if priority == "interactive" else \
            1.0 + self.tenant_burst / 2.0
        now = time.perf_counter()
        with self._lock:
            tokens, last = self._tenants.get(
                tenant, (self.tenant_burst, now)
            )
            tokens = min(self.tenant_burst,
                         tokens + (now - last) * rate)
            if tokens >= need:
                self._tenants[tenant] = [tokens - 1.0, now]
                return True, 0.0
            self._tenants[tenant] = [tokens, now]
        retry = min(MAX_RETRY_HINT_S,
                    max(0.05, (need - tokens) / rate))
        return False, round(retry, 3)

    # -------------------------------------------------------------- #
    # routing                                                        #
    # -------------------------------------------------------------- #

    def _order(self, bucket: str) -> list:
        """[primary, spill_sibling, ...] for one bucket: the md5 ring
        with DOWN (dead/quarantined — docs/SERVING.md §self-healing)
        and draining workers removed and cooling (recently wedged)
        workers deferred to last resort. Falls back to the raw
        draining/cooling members when nothing is warm — routing
        SOMEWHERE loudly beats rejecting everything silently — but
        never to a down worker: the connection cannot succeed, and
        the shed path owes the client an honest answer instead. An
        EMPTY return means every ring member is down: the caller
        sheds."""
        ring = ring_order(bucket, len(self.workers))
        now = time.perf_counter()
        with self._lock:
            draining = set(self._draining)
            down = set(self._down)
            cooling = {i for i, t in self._cooldown.items() if t > now}
        alive = [i for i in ring if i not in draining
                 and i not in down]
        warm = [i for i in alive if i not in cooling]
        ordered = warm + [i for i in alive if i in cooling]
        if ordered:
            return ordered
        return [i for i in ring if i not in down]

    def _forward(self, idx: int, header: dict, payloads):
        """One upstream round trip; raises OSError/ProtocolError on
        transport loss. In-flight accounting is what ``drain`` waits
        on."""
        with self._lock:
            self._inflight[idx] += 1
        pool = self._pools[idx]
        sock = None
        ok = False
        t0 = time.perf_counter()
        try:
            sock = pool.acquire()
            protocol.send_frame(sock, header, payloads)
            frame = protocol.recv_frame(sock)
            if frame is None:
                raise protocol.ProtocolError(
                    "worker hung up mid-request"
                )
            ok = True
            self._note_fwd_wall(time.perf_counter() - t0)
            return frame
        finally:
            if sock is not None:
                pool.release(sock, poisoned=not ok)
            with self._lock:
                self._inflight[idx] -= 1

    # -------------------------------------------------------------- #
    # hedged dispatch (docs/SERVING.md §hedged dispatch)             #
    # -------------------------------------------------------------- #

    def _note_fwd_wall(self, wall: float):
        """One completed forward's wall into the hedge histogram (and
        the metrics snapshot, where operators read the same tail)."""
        obs_metrics.observe("serve.forward_wall_s", wall)
        b = obs_metrics.bucket_index(wall)
        with self._lock:
            h = self._fwd_walls
            h[0] += 1
            if wall > h[1]:
                h[1] = wall
            h[2][b] = h[2].get(b, 0) + 1

    def _hedge_threshold_s(self):
        """The elapsed time past which a forward is hedged: the
        ``TPK_ROUTE_HEDGE_PCTL``-th percentile of this router's OWN
        completed forward walls (count-weighted over the shared
        log buckets). None = hedging off, a one-worker fleet (no
        sibling to hedge to), or not enough samples to trust a tail
        estimate yet."""
        if self.hedge_pctl <= 0 or len(self.workers) < 2:
            return None
        with self._lock:
            count, mx, buckets = self._fwd_walls
            if count < HEDGE_MIN_SAMPLES:
                return None
            buckets = dict(buckets)
        return obs_metrics.percentiles(
            count, mx, buckets,
            qs=(min(self.hedge_pctl, 100.0) / 100.0,),
        )[0]

    def _hedge_frac_ok(self) -> bool:
        """The hedge-budget cap: hedging past
        ``TPK_ROUTE_HEDGE_MAX_FRAC`` of routed traffic would let a
        fleet-wide slowdown double its own load — exactly when extra
        load hurts most."""
        with self._lock:
            return (self._hedged + 1
                    <= self.hedge_max_frac * max(1, self._routed))

    def _start_attempt(self, idx: int, header, payloads, cond):
        att = _Attempt(idx, cond)

        def run():
            with self._lock:
                self._inflight[idx] += 1
            pool = self._pools[idx]
            sock = None
            ok = False
            t0 = time.perf_counter()
            try:
                with att.alock:
                    if att.aborted:
                        raise OSError("attempt aborted before start")
                    sock = pool.acquire()
                    att.sock = sock
                protocol.send_frame(sock, header, payloads)
                frame = protocol.recv_frame(sock)
                if frame is None:
                    raise protocol.ProtocolError(
                        "worker hung up mid-request"
                    )
                att.resp, att.payloads = frame
                ok = True
                self._note_fwd_wall(time.perf_counter() - t0)
            except (OSError, protocol.ProtocolError) as e:
                att.exc = e
            finally:
                with att.alock:
                    att.sock = None
                    if sock is not None:
                        pool.release(sock,
                                     poisoned=not ok or att.aborted)
                with self._lock:
                    self._inflight[idx] -= 1
                with cond:
                    att.done = True
                    cond.notify_all()

        threading.Thread(target=run, daemon=True,
                         name="route-attempt").start()
        return att

    def _cancel_upstream(self, idx: int, req_id, kernel=None):
        """Issue the best-effort ``cancel`` op for the hedge loser
        (docs/SERVING.md §hedged dispatch): a queued loser is dropped
        before it wastes a dispatch, an in-flight one has its send
        suppressed. Failure is fine — cancel is advisory, the replay
        idempotency contract already makes the duplicate safe."""
        if req_id is None:
            return
        obs_metrics.inc("serve.cancels_sent")
        journal.emit(
            "serve_cancelled", site="router", to_worker=idx,
            kernel=kernel, request_id=req_id,
        )
        pool = self._pools[idx]
        sock = None
        ok = False
        try:
            sock = pool.acquire()
            protocol.send_frame(sock, {
                "v": protocol.VERSION, "op": "cancel",
                "request_id": req_id,
            })
            ok = protocol.recv_frame(sock) is not None
        except (OSError, protocol.ProtocolError):
            pass
        finally:
            if sock is not None:
                pool.release(sock, poisoned=not ok)

    def _forward_hedged(self, idx: int, order, header, payloads,
                        deadline_at, kernel, bucket, rid, req_id,
                        tenant):
        """The primary forward with tail-tolerant hedging: if the
        primary outlives the fleet's own forward-wall percentile
        (``_hedge_threshold_s``) and budget remains, the SAME
        request_id is re-issued to the ring sibling stamped as a
        replay (the PR-14 idempotency contract — kernels are pure),
        first response wins, the loser is cancelled best-effort.
        Returns ``(resp, payloads, winner_idx, hedged)``; raises like
        ``_forward`` only when no hedge was launched."""
        hdr = protocol.stamp_budget(header, deadline_at)
        threshold = self._hedge_threshold_s()
        sibling = next((j for j in order if j != idx), None)
        if (threshold is None or sibling is None or req_id is None
                or not self._hedge_frac_ok()):
            resp, out_payloads = self._forward(idx, hdr, payloads)
            return resp, out_payloads, idx, False
        cond = threading.Condition()
        primary = self._start_attempt(idx, hdr, payloads, cond)
        with cond:
            end = time.perf_counter() + threshold
            while not primary.done:
                rem = end - time.perf_counter()
                if rem <= 0:
                    break
                cond.wait(rem)
        hedge = None
        if not primary.done and (
                deadline_at is None
                or protocol.budget_ms_remaining(deadline_at) > 0.0):
            h_hdr = dict(header)
            try:
                prior = int(h_hdr.get("replay") or 0)
            except (TypeError, ValueError):
                prior = 0
            h_hdr["replay"] = prior + 1
            h_hdr = protocol.stamp_budget(h_hdr, deadline_at)
            with self._lock:
                self._hedged += 1
            obs_metrics.inc("serve.hedges")
            journal.emit(
                "serve_hedged", kernel=kernel, bucket=bucket,
                request=rid, request_id=req_id, from_worker=idx,
                to_worker=sibling, tenant=tenant,
                threshold_s=round(threshold, 6),
            )
            hedge = self._start_attempt(sibling, h_hdr, payloads, cond)
        attempts = [primary] + ([hedge] if hedge is not None else [])

        def _settled():
            done = [a for a in attempts if a.done]
            if any(a.exc is None and (a.resp or {}).get("ok")
                   for a in done):
                # first OK response wins outright; an early honest
                # error waits for the race mate — it might still win
                return True
            return len(done) == len(attempts)

        with cond:
            while not _settled():
                cond.wait(1.0)
            done = [a for a in attempts if a.done]
        winner = next((a for a in done
                       if a.exc is None and (a.resp or {}).get("ok")),
                      None)
        if winner is None:
            winner = next((a for a in done if a.exc is None), done[0])
        for a in attempts:
            if a is winner:
                continue
            if not a.done:
                # cancel FIRST (a queued loser is dropped before it
                # wastes a dispatch), then abort the blocked recv so
                # its suppressed reply cannot hold the thread until
                # the pool timeout
                self._cancel_upstream(a.idx, req_id, kernel=kernel)
                a.abort()
            elif a.exc is None:
                # the loser finished anyway: its response segments
                # will never be mapped by anyone — free them now
                self._drop_stashed({"resp": a.resp})
        if winner.exc is not None:
            if hedge is None:
                raise winner.exc
            resp = {"v": protocol.VERSION, "id": rid, "ok": False,
                    "kind": "error",
                    "error": (f"workers {idx},{sibling} unreachable "
                              f"after hedge: {winner.exc!r}")}
            return resp, (), winner.idx, True
        return winner.resp, winner.payloads, winner.idx, \
            hedge is not None

    def _count_copied(self, kernel: str, nbytes: int):
        """Relayed inline payload bytes — the router's share of the
        ``serve.bytes_copied`` story. Shm-lane requests relay only
        descriptors, so the fleet front-end stops being O(tensor)."""
        if not nbytes:
            return
        obs_metrics.inc(f"serve.bytes_copied.{kernel}", nbytes)
        with self._lock:
            self._bytes_copied += nbytes

    def _route(self, conn: _Conn, header: dict, payloads):
        rid = header.get("id")
        # the client-minted causal id rides the relayed header
        # untouched; the router only TAGS its own routing evidence
        # with it so cross-process timelines join (docs/OBSERVABILITY
        # .md §request tracing)
        req_id = header.get("request_id")
        req_id = str(req_id) if req_id is not None else None

        def reply(h, p=()):
            try:
                conn.send(h, p)
            except (OSError, protocol.ProtocolError):
                # client gone; the decision is journaled anyway — but
                # a worker's response segments no one will ever map
                # must not wait for its aged sweep
                for d in (h.get("_shm") or ()):
                    if isinstance(d, dict):
                        protocol.unlink_shm(d.get("name"))

        tenant = header.get("tenant") or "-"
        priority = header.get("priority") or "interactive"
        try:
            if priority not in PRIORITIES:
                raise ValueError(
                    f"unknown priority {priority!r}; known: "
                    f"{PRIORITIES}"
                )
            kernel = header["kernel"]
            statics = dict(header.get("statics") or {})
            # layout-only stubs: routing needs shapes and dtypes, not
            # data — the router never reads (with the shm lane, never
            # even receives) a payload byte. Byte-count validation is
            # the worker's unpack, one hop later.
            arrays = bucketing.spec_stubs(header.get("args") or [])
            # structural _shm validation at the front door: a
            # malformed descriptor must be a bad request HERE, not a
            # worker-side ProtocolError the spill logic would misread
            # as transport loss against two healthy workers
            protocol.check_shm_descs(header, len(payloads))
            spec, _how = bucketing.bucket_for(kernel, arrays, statics)
            bucket = bucketing.bucket_id(kernel, spec, statics, arrays)
        except (KeyError, ValueError, TypeError, AttributeError,
                protocol.ProtocolError) as e:
            # malformed dispatches die HERE, at the front door — a
            # worker never sees a request the router could not hash
            reply({"v": protocol.VERSION, "id": rid, "ok": False,
                   "kind": "error", "error": f"bad request: {e}"})
            return
        # deadline triage at admission (docs/SERVING.md §deadlines):
        # the wire budget becomes a router-local monotonic instant; a
        # request that already cannot make it is refused NOW — before
        # it burns tenant tokens, a WAL fsync, or a worker queue slot
        deadline_at = protocol.deadline_from_header(header)
        if (deadline_at is not None
                and protocol.budget_ms_remaining(deadline_at) <= 0.0):
            self._refuse_infeasible(reply, rid, req_id, kernel,
                                    bucket, tenant, priority)
            return
        if req_id is not None and self._stash:
            # a reconnecting client retrying a request the WAL replay
            # already executed: answer from the stash — the worker saw
            # this request_id exactly once (docs/SERVING.md §guardian)
            hit = self._take_stash(req_id)
            if hit is not None:
                resp = dict(hit["resp"])
                resp["id"] = rid
                out_payloads = hit["payloads"]
                with self._lock:
                    self._routed += 1
                    self._routed_to[hit["worker"]] += 1
                obs_metrics.inc("serve.routed")
                self._count_copied(
                    kernel, sum(len(p) for p in out_payloads)
                )
                journal.emit(
                    "serve_route", kernel=kernel, bucket=bucket,
                    request=rid, request_id=req_id,
                    worker=hit["worker"], tenant=tenant,
                    priority=priority, spilled_from=None,
                    ok=bool(resp.get("ok")), wal_stash=True,
                )
                reply(resp, out_payloads)
                return
        admitted, retry = self._admit_tenant(tenant, priority)
        if not admitted:
            with self._lock:
                self._throttled += 1
            obs_metrics.inc("serve.throttled")
            journal.emit(
                "serve_tenant_throttled", tenant=tenant,
                priority=priority, kernel=kernel, request=rid,
                request_id=req_id,
                retry_after_s=retry,
            )
            reply({"v": protocol.VERSION, "id": rid, "ok": False,
                   "kind": "overloaded", "throttled": True,
                   "tenant": tenant, "retry_after_s": retry,
                   "error": (f"tenant {tenant!r} over quota "
                             f"({priority}); retry after {retry}s")})
            return
        # durable admission: the accepted request becomes crash-proof
        # HERE — fsync'd into router.wal before any forward — and the
        # kill_router chaos injection point sits exactly between the
        # append and the forward, so a fired kill proves the replay
        wal_key = self._wal_record(header, payloads, kernel, bucket)
        faults.router_fault()
        try:
            order = self._order(bucket)
            with self._lock:
                down = set(self._down)
            # graceful degradation (docs/SERVING.md §self-healing): with
            # the bucket's home AND sibling both out, batch load sheds
            # FIRST (an honest retry_after_s derived from the respawn
            # backoff) while interactive traffic keeps riding whatever
            # ring members remain; nothing alive at all sheds everything
            # — a client told when to come back beats a client timing out
            home_pair = set(ring_order(bucket, len(self.workers))[:2])
            if not order or (priority == "batch" and down
                             and home_pair <= down):
                self._shed(reply, rid, req_id, kernel, bucket, tenant,
                           priority, down or home_pair)
                return
            idx = order[0]
            spilled_from = None
            reason = None
            dead = False
            hedged = False
            for hop in range(2):
                dead = False
                if (deadline_at is not None
                        and protocol.budget_ms_remaining(
                            deadline_at) <= 0.0):
                    # dequeue-time expiry: the budget died while this
                    # request waited (spill pacing, a slow first hop)
                    # — expire it instead of dispatching doomed work
                    self._expire_route(reply, rid, req_id, kernel,
                                       bucket, tenant, where="route")
                    return
                try:
                    if hop == 0:
                        resp, out_payloads, idx, hedged = \
                            self._forward_hedged(
                                idx, order, header, payloads,
                                deadline_at, kernel=kernel,
                                bucket=bucket, rid=rid,
                                req_id=req_id, tenant=tenant)
                    else:
                        resp, out_payloads = self._forward(
                            idx,
                            protocol.stamp_budget(header,
                                                  deadline_at),
                            payloads)
                except (OSError, protocol.ProtocolError) as e:
                    resp, out_payloads = None, ()
                    reason = "transport"
                    err = e
                    # dead-vs-transient discrimination at the moment of
                    # failure: a free pidfile flock is a death
                    # certificate, and declaring it NOW (sweep, respawn
                    # scheduling, ring removal) is what turns in-flight
                    # loss into a replay instead of a client error
                    dead = (self._health.note_transport_loss(idx)
                            if self._health is not None else False)
                else:
                    if resp.get("ok"):
                        reason = None
                    elif resp.get("kind") == "overloaded":
                        reason = "overloaded"
                    elif resp.get("kind") == "wedged":
                        reason = "wedged"
                        with self._lock:
                            self._cooldown[idx] = (time.perf_counter()
                                                   + self.cooldown_s)
                        print(f"# route: worker {idx} WEDGED on "
                              f"{kernel} - cooling "
                              f"{self.cooldown_s:.0f}s", file=sys.stderr)
                    else:
                        reason = None  # an honest dispatch error: relay it
                    if hedged:
                        # the hedge already delivered this request_id
                        # to the ring sibling — first-response-wins IS
                        # the failover; never dispatch a third copy
                        reason = None
                if reason is None:
                    break
                sibling = next((j for j in order if j != idx), None)
                if hop == 1 or sibling is None:
                    if resp is None:
                        if dead:
                            # the last candidate DIED under this request:
                            # answer like the shed path — the worker is
                            # being respawned, and "come back in Ns" is
                            # the honest reply, not a hard error
                            self._shed(reply, rid, req_id, kernel, bucket,
                                       tenant, priority, {idx})
                            return
                        # no (further) sibling: surface the failure honestly
                        resp = {"v": protocol.VERSION, "id": rid,
                                "ok": False, "kind": "error",
                                "error": (f"worker {idx} unreachable: "
                                          f"{err!r}")}
                        with self._lock:
                            self._rejected += 1
                    break
                with self._lock:
                    self._spilled += 1
                obs_metrics.inc("serve.spills")
                journal.emit(
                    "serve_spill", kernel=kernel, bucket=bucket,
                    request=rid, request_id=req_id,
                    from_worker=idx, to_worker=sibling,
                    reason=reason, tenant=tenant,
                )
                if dead:
                    # in-flight recovery (docs/SERVING.md §self-healing):
                    # the home worker DIED holding this accepted request —
                    # re-route it ONCE to the ring sibling, stamped as a
                    # replay. The `replay` header is the idempotency
                    # contract (protocol.py): the dead worker may already
                    # have executed it, kernels are pure, the request_id
                    # stays the same, so every consumer counts it once.
                    journal.emit(
                        "serve_request_replayed", kernel=kernel,
                        bucket=bucket, request=rid, request_id=req_id,
                        from_worker=idx, to_worker=sibling, tenant=tenant,
                    )
                    header = dict(header)
                    try:
                        prior = int(header.get("replay") or 0)
                    except (TypeError, ValueError):
                        prior = 0
                    header["replay"] = prior + 1
                spilled_from, idx = idx, sibling
            with self._lock:
                self._routed += 1
                self._routed_to[idx] += 1
            obs_metrics.inc("serve.routed")
            # inline payload bytes this request made the router relay
            # (request upstream + response downstream); an shm-lane
            # request contributes 0 — only names crossed this process
            self._count_copied(
                kernel,
                sum(len(p) for p in payloads)
                + sum(len(p) for p in out_payloads),
            )
            journal.emit(
                "serve_route", kernel=kernel, bucket=bucket, request=rid,
                request_id=req_id,
                worker=idx, tenant=tenant, priority=priority,
                spilled_from=spilled_from,
                ok=bool(resp.get("ok")),
            )
            reply(resp, out_payloads)
        finally:
            # ANY terminal outcome (reply sent, shed, relayed error)
            # settles the entry; only a crash leaves it for replay
            if wal_key is not None and self._wal is not None:
                self._wal.ack(wal_key)


# ------------------------------------------------------------------ #
# CLI entry (python -m tpukernels.serve.router)                      #
# ------------------------------------------------------------------ #

def main(argv=None):
    import signal

    from tpukernels.serve import fleet as serve_fleet
    from tpukernels.serve import server as serve_server

    argv = sys.argv[1:] if argv is None else list(argv)
    socket_path = None
    workers: list = []
    it = iter(argv)
    try:
        for a in it:
            if a == "--socket":
                socket_path = next(it)
            elif a == "--worker":
                workers.append(next(it))
            elif a in ("-h", "--help"):
                print(__doc__, file=sys.stderr)
                return 0
            else:
                print(__doc__, file=sys.stderr)
                print(f"route: unknown argument {a!r}", file=sys.stderr)
                return 2
    except StopIteration:
        print(f"route: {a} needs a value", file=sys.stderr)
        return 2
    if socket_path is None:
        socket_path = serve_fleet.front_socket_path()
    if not workers:
        print("route: at least one --worker SOCKET is required",
              file=sys.stderr)
        return 2

    if os.environ.get("TPK_HEALTH_JOURNAL") is None:
        os.environ["TPK_HEALTH_JOURNAL"] = journal.default_path()
    try:
        router = Router(socket_path, workers)
    except (ValueError, OSError) as e:
        print(f"route: {e}", file=sys.stderr)
        return 2
    try:
        pidfile = serve_server._hold_pidfile(
            serve_fleet.router_pidfile_path()
        )
    except RuntimeError as e:
        print(f"route: {e}", file=sys.stderr)
        return 3

    from tpukernels.obs import scaling as obs_scaling
    from tpukernels.serve import health as serve_health

    # env-derived stamp only: the router is jax-free by design and
    # must never initialize a backend (the workers stamp probed
    # inventories of their own)
    obs_scaling.emit_inventory("serve_router")
    # the self-healing loop rides in this process (docs/SERVING.md
    # §self-healing): worker pidfiles live beside their sockets, and
    # respawns reuse the exact dir/socket the ring already points at.
    # TPK_FLEET_PROBE_S=0 disables detection + respawn.
    try:
        hm = serve_health.HealthManager(workers, repo=os.getcwd(),
                                        router=router)
    except ValueError as e:
        print(f"route: {e}", file=sys.stderr)
        return 2
    router.attach_health(hm)
    hm.start()
    # durable admission (docs/SERVING.md §guardian): open (recover)
    # the WAL now the pidfile is held, and drain the previous
    # incarnation's replay debt BEFORE the front socket opens — every
    # stashable result is stashed before any reconnecting client's
    # same-request_id retry can arrive
    router.attach_wal(serve_wal.Wal(serve_fleet.wal_path()))
    replayed = router.replay_wal()
    if replayed:
        print(f"# route: replayed {replayed} unacknowledged request(s)"
              f" from {serve_fleet.wal_path()}", file=sys.stderr)
    signal.signal(signal.SIGTERM, router.stop)
    signal.signal(signal.SIGINT, router.stop)
    print(f"# route: listening on {socket_path} "
          f"(pid {os.getpid()}, {len(workers)} worker(s), health "
          f"probe {hm.probe_s}s)", file=sys.stderr)
    try:
        router.serve_forever()
    except OSError as e:
        print(f"route: cannot serve on {socket_path}: {e}",
              file=sys.stderr)
        return 1
    finally:
        hm.stop()
        try:
            pidfile.close()
            os.unlink(serve_fleet.router_pidfile_path())
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
