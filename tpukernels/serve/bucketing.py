"""Shape bucketing: pad requests up to registered AOT avatars
(docs/SERVING.md §bucketing).

The AOT layer (docs/PERF.md §compile discipline) makes the SECOND
dispatch at a shape compile-free; a service whose clients send
arbitrary shapes would compile forever. Bucketing folds the incoming
shape space onto the registered ``aot.BENCH_CONFIGS`` avatars: a
request whose operands fit under an avatar (every dim <=, same rank,
same dtype, same statics) is zero-padded UP to it, dispatched through
the avatar's warm executable, and its outputs are sliced/corrected
back to the native shapes. Pad-up, never pad-down — truncating user
data is not an optimization.

Not every kernel tolerates padding, so the rule is per-kernel and
EXPLICIT (``PAD_RULES``; the registry completeness lint pins a row
per kernel):

- ``"zero"``  — zero padding is algebraically invisible: saxpy/sgemm
  (zero rows/cols contribute zero), scan (suffix zeros leave every
  prefix untouched), nbody (a zero-mass body at the origin exerts and
  feels no net force under the eps softening).
- ``"hist0"`` — zero padding is visible exactly once: each pad
  element lands in bin 0, so the correction subtracts the pad count
  from ``counts[0]`` after dispatch (the scan half of scan_histogram
  follows the scan rule).
- ``None``    — padding changes the answer (the stencils: a padded
  boundary is a different boundary condition). Exact avatar matches
  still bucket (pad_frac 0); anything else dispatches at its native
  shape.

Padding is wasted compute, so it is capped (``TPK_SERVE_MAX_PAD_FRAC``,
default 0.5: never burn more than half the dispatched elements on
padding) and observable (the server records every bucketed request's
waste into the ``serve.bucket_pad_frac`` histogram). Requests over
the cap, over the avatar, or at alien statics dispatch natively —
correct first, warm second.

``TPK_SERVE_BUCKETS`` (inline JSON or a file path, the
``TPK_FAULT_PLAN`` convention) overrides the avatar table — how the
CPU tests prove the pad math without materializing the record shapes,
and how an operator serves a custom shape population. A table value
may be one avatar spec (the historical shape) or a LIST of specs —
what the traffic-adaptive optimizer's bucket SPLITS produce
(docs/SERVING.md §adaptive buckets); a request lands on the fitting
avatar with the least padding. A promoted table arrives as a changed
FILE behind the unchanged ``TPK_SERVE_BUCKETS`` path, so
:func:`reload` (called by the router/daemon on ``undrain``) busts the
parse cache and picks it up without a fleet restart.

Stdlib + numpy only; the avatar table comes from ``tpukernels.aot``
(stdlib at import).
"""

from __future__ import annotations

import json
import os

import numpy as np

from tpukernels import aot

DEFAULT_MAX_PAD_FRAC = 0.5

# kernel -> padding rule (module docstring). Explicit None rows are
# deliberate: tests/test_registry_contract.py requires every registry
# kernel to state its rule, so a new kernel cannot silently become
# unbucketable (or worse, wrongly bucketable).
PAD_RULES = {
    "vector_add": "zero",
    "sgemm": "zero",
    "stencil2d": None,
    "stencil3d": None,
    "scan": "zero",
    "scan_exclusive": "zero",
    "histogram": "hist0",
    "scan_histogram": "hist0",
    "nbody": "zero",
}

_DTYPE_NAMES = {"f32": "float32", "i32": "int32"}


def max_pad_frac() -> float:
    """``TPK_SERVE_MAX_PAD_FRAC`` (default 0.5), fail-loud parse in
    [0, 1] — the TPK_* knob contract."""
    raw = os.environ.get("TPK_SERVE_MAX_PAD_FRAC")
    if raw is None:
        return DEFAULT_MAX_PAD_FRAC
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if not 0.0 <= val <= 1.0:
        raise ValueError(
            f"TPK_SERVE_MAX_PAD_FRAC={raw!r}: expected a float in [0, 1]"
        )
    return val


# parse-once cache keyed on the raw knob value: admission runs
# bucket_for per incoming request in the reader thread, and a
# file-path knob must not cost a disk open + JSON parse per request.
# (A changed FILE behind an unchanged path is not re-read — tests and
# operators flip the env value, which busts the cache.)
_CONFIG_CACHE: dict = {"raw": None, "table": None}


def bucket_configs() -> dict:
    """The avatar table bucketing folds onto: ``TPK_SERVE_BUCKETS``
    (inline JSON object or a JSON file path — the fault-plan loading
    convention) when set, else the registered ``aot.BENCH_CONFIGS``.
    Spec shape mirrors BENCH_CONFIGS: ``{kernel: {"args": [(kind,
    shape), ...], "statics": {...}}}``."""
    raw = os.environ.get("TPK_SERVE_BUCKETS")
    if not raw or not raw.strip():
        return aot.BENCH_CONFIGS
    if _CONFIG_CACHE["raw"] == raw:
        return _CONFIG_CACHE["table"]
    if raw.lstrip()[:1] == "{":
        table = json.loads(raw)
    else:
        with open(raw) as f:
            table = json.load(f)
    if not isinstance(table, dict):
        raise ValueError(
            "TPK_SERVE_BUCKETS must be a JSON object "
            f"({type(table).__name__} given)"
        )
    _CONFIG_CACHE["table"] = table
    _CONFIG_CACHE["raw"] = raw
    return table


def reload():
    """Bust the parse-once config cache and re-read the table — the
    promoted-table pickup hook (docs/SERVING.md §adaptive buckets).
    The cache is keyed on the RAW env value, so a promotion that
    atomically rewrites the file behind a stable ``TPK_SERVE_BUCKETS``
    path is invisible until this runs; the router and daemon call it
    on ``undrain``, the operator's "config changed" signal. Raises
    like :func:`bucket_configs` on a malformed table — an undrain
    must not silently serve yesterday's avatars — but a reload that
    FAILS leaves the previously parsed table in effect, so one torn
    promotion cannot take the request path down with it."""
    old = dict(_CONFIG_CACHE)
    _CONFIG_CACHE["raw"] = _CONFIG_CACHE["table"] = None
    try:
        return bucket_configs()
    except (OSError, ValueError):
        _CONFIG_CACHE.update(old)
        raise


def kernel_specs(kernel: str) -> list:
    """The kernel's avatar specs as a list — one entry for the
    historical single-spec table shape, N after an adaptive split."""
    spec = bucket_configs().get(kernel)
    if spec is None:
        return []
    return list(spec) if isinstance(spec, list) else [spec]


def _spec_args(spec):
    """[(dtype_name, shape_tuple), ...] for one avatar spec (tolerates
    JSON lists where BENCH_CONFIGS has tuples)."""
    out = []
    for kind, shape in spec["args"]:
        out.append((_DTYPE_NAMES.get(kind, kind),
                    tuple(int(d) for d in shape)))
    return out


def bucket_for(kernel: str, arrays, statics: dict):
    """Match one request against the kernel's avatar(s).

    ``arrays`` are the request's numpy operands (0-d = host scalar).
    Returns ``(spec, pad_frac)`` when the request buckets — ``spec``
    is the avatar entry, ``pad_frac`` the wasted-element fraction
    (0.0 for an exact fit) — or ``(None, reason)`` when it must
    dispatch natively. Pad-up only: any dim over the avatar's is a
    non-match, never a truncation. A kernel with SEVERAL avatars (an
    adaptively split table) lands the request on the fitting avatar
    with the LEAST padding — the projected-cost rule the optimizer's
    proposal math assumes (``tpukernels/serve/adapt.py``)."""
    try:
        raw = bucket_configs().get(kernel)
    except (OSError, ValueError) as e:
        raise ValueError(f"TPK_SERVE_BUCKETS: {e}") from None
    if raw is None:
        return None, "no-avatar"
    specs = list(raw) if isinstance(raw, list) else [raw]
    if not specs:
        return None, "no-avatar"
    best = reason = None
    for spec in specs:
        got, how = _match_one(kernel, arrays, statics, spec)
        if got is None:
            if reason is None:
                reason = how  # first avatar's reason, deterministic
            continue
        if how == 0.0:
            return got, 0.0  # exact fit: nothing beats zero pad
        if best is None or how < best[1]:
            best = (got, how)
    if best is not None:
        return best
    return None, reason


def _match_one(kernel: str, arrays, statics: dict, spec):
    """One request against ONE avatar spec — the single-avatar match
    body :func:`bucket_for` ranks over."""
    want = _spec_args(spec)
    if len(want) != len(arrays):
        return None, "arg-count-mismatch"
    if dict(spec.get("statics") or {}) != dict(statics or {}):
        return None, "statics-mismatch"
    orig = padded = 0
    exact = True
    for a, (dtype, shape) in zip(arrays, want):
        a = np.asarray(a)
        if a.dtype.name != dtype or a.ndim != len(shape):
            return None, "layout-mismatch"
        if any(d > w for d, w in zip(a.shape, shape)):
            return None, "over-avatar"
        if tuple(a.shape) != shape:
            exact = False
        orig += int(a.size)
        padded += int(np.prod(shape, dtype=np.int64)) if shape else 1
    if exact:
        return spec, 0.0
    if PAD_RULES.get(kernel) is None:
        return None, "no-pad-rule"
    if not _consistent(kernel, arrays):
        # cross-operand shape disagreements (sgemm inner dims,
        # mismatched vector lengths) that registry.dispatch would
        # REJECT must never be padded into a plausible-but-wrong
        # answer — dispatch natively and let the kernel error honestly
        return None, "inconsistent-args"
    pad_frac = 1.0 - (orig / padded if padded else 1.0)
    if pad_frac > max_pad_frac():
        return None, "pad-over-cap"
    return spec, pad_frac


def spec_stubs(specs):
    """Allocation-free operand stand-ins built from wire arg specs
    (``[{"shape", "dtype"}, ...]``) — zero-stride broadcasts with the
    right shape/dtype/size, enough for :func:`bucket_for` /
    :func:`bucket_id` which only read layout. How the fleet router
    hashes a request without touching (or even receiving) its payload
    bytes: with the shm lane the tensors never pass through the
    front-end at all. Malformed specs raise ``ValueError``/
    ``TypeError`` — the router's bad-request surface."""
    from tpukernels.serve import protocol

    out = []
    for spec in specs or ():
        name = spec.get("dtype")
        if name not in protocol.DTYPES:
            raise ValueError(f"unsupported dtype {name!r} in spec")
        shape = tuple(int(d) for d in spec.get("shape", ()))
        out.append(
            np.broadcast_to(np.zeros((), protocol.DTYPES[name]), shape)
        )
    return out


def _consistent(kernel: str, arrays) -> bool:
    """Cross-operand shape agreement for multi-operand kernels — the
    constraints ``registry.dispatch`` itself would enforce. Only
    consulted for non-exact (padding) matches: an exact avatar fit is
    consistent by construction."""
    shapes = [tuple(np.asarray(a).shape) for a in arrays]
    if kernel == "vector_add":
        return shapes[1] == shapes[2]
    if kernel == "sgemm":
        (m, k), (k2, n), (m2, n2) = shapes[1], shapes[2], shapes[4]
        return k == k2 and m == m2 and n == n2
    if kernel == "nbody":
        return len(set(shapes)) == 1
    return True  # single-data-operand kernels


def pad_args(kernel: str, spec, arrays, pool=None):
    """Zero-pad the request's operands up to the avatar shapes.
    Returns ``(padded_arrays, meta)`` — ``meta`` carries what
    :func:`unpad_outputs` needs (native shapes + the data-arg pad
    count for the hist0 correction) plus ``copied_bytes``, the
    staging bytes this call copied (the serve daemon's
    ``serve.bytes_copied`` evidence).

    ``pool`` (a per-bucket ``{arg_index: buffer}`` dict) reuses the
    avatar-shaped staging buffers across requests so the warm padded
    path allocates nothing — the CALLER must serialize access (the
    daemon holds the bucket lock)."""
    want = _spec_args(spec)
    padded, orig_shapes = [], []
    copied = 0
    for i, (a, (dtype, shape)) in enumerate(zip(arrays, want)):
        a = np.asarray(a)
        orig_shapes.append(tuple(a.shape))
        if tuple(a.shape) == shape:
            padded.append(a)
            continue
        buf = pool.get(i) if pool is not None else None
        if (buf is None or buf.shape != shape
                or buf.dtype != a.dtype):
            buf = np.zeros(shape, dtype=a.dtype)
            if pool is not None:
                pool[i] = buf
        else:
            # reused staging buffer: re-zero before the copy so the
            # previous request's longer operand cannot bleed through
            buf[...] = 0
        buf[tuple(slice(0, d) for d in a.shape)] = a
        copied += int(a.nbytes)
        padded.append(buf)
    data_pad = 0
    for a, (dtype, shape) in zip(arrays, want):
        if shape:  # first non-scalar arg is the data array by contract
            data_pad = int(np.prod(shape, dtype=np.int64)) - int(
                np.asarray(a).size
            )
            break
    return padded, {"orig_shapes": orig_shapes, "data_pad": data_pad,
                    "rule": PAD_RULES.get(kernel),
                    "copied_bytes": copied}


def unpad_outputs(kernel: str, meta, outputs):
    """Slice/correct the avatar-shaped outputs back to the request's
    native shapes. ``outputs`` is the flat tuple of numpy result
    leaves; returns the corrected tuple. The inverse map is
    per-kernel because output shapes are functions of INPUT shapes:

    - vector_add / scan / scan_exclusive — one output shaped like the
      data arg: slice to it.
    - sgemm — output shaped like C (arg 4): slice to it.
    - nbody — six outputs shaped like the body arrays: slice each.
    - histogram — counts are avatar-shaped already (nbins is a
      static); subtract the pad count from bin 0 (every pad element
      is a zero).
    - scan_histogram — scan half sliced, counts half bin-0-corrected.
    """
    shapes = meta["orig_shapes"]
    pad = meta["data_pad"]

    def _cut(a, shape):
        a = np.asarray(a)
        if tuple(a.shape) == tuple(shape):
            return a
        return np.ascontiguousarray(
            a[tuple(slice(0, d) for d in shape)]
        )

    def _fix_counts(c):
        c = np.array(c, copy=True)
        c[0] -= np.asarray(pad, dtype=c.dtype)
        return c

    if kernel == "vector_add":
        return (_cut(outputs[0], shapes[1]),)
    if kernel == "sgemm":
        return (_cut(outputs[0], shapes[4]),)
    if kernel in ("scan", "scan_exclusive"):
        return (_cut(outputs[0], shapes[0]),)
    if kernel == "histogram":
        return (_fix_counts(outputs[0]),)
    if kernel == "scan_histogram":
        return (_cut(outputs[0], shapes[0]), _fix_counts(outputs[1]))
    if kernel == "nbody":
        return tuple(_cut(o, s) for o, s in zip(outputs, shapes))
    # exact-fit buckets of rule-less kernels never pad, so outputs
    # are already native-shaped
    return tuple(np.asarray(o) for o in outputs)


def bucket_id(kernel: str, spec, statics: dict, arrays=None) -> str:
    """Stable batching/locking key for one (kernel, compiled-program)
    bucket. Bucketed requests share the avatar's key; native
    dispatches key on their own shapes (same-shape natives still
    coalesce and still compile once)."""
    if spec is not None:
        shapes = "+".join(
            "x".join(str(d) for d in shape) or "-"
            for _dt, shape in _spec_args(spec)
        )
    else:
        shapes = "+".join(
            "x".join(str(d) for d in np.asarray(a).shape) or "-"
            for a in (arrays or ())
        )
    stat = ",".join(f"{k}={v}" for k, v in sorted((statics or {}).items()))
    return f"{kernel}|{shapes}|{stat or '-'}"


def mesh_tier_for(kernel: str, arrays, statics: dict):
    """Mesh shape tuple ``(n,)`` when an over-avatar request can route
    to the mesh tier (``registry.dispatch_mesh``), else ``None`` —
    consulted by the server ONLY after :func:`bucket_for` came back
    ``(None, "over-avatar")``, so a request that merely mismatched
    layout or statics never lands here (docs/SERVING.md §mesh tier).

    Admission must not initialize a backend (the bucket_for /
    bucket_id rule: layout-only, numpy-only), so the device count
    comes from the ENV inventory (``scaling.inventory(probe=False)``
    reads ``--xla_force_host_platform_device_count`` — how the CPU
    fleet harness fakes a multi-chip worker). A host whose env
    declares no count (the normal real-pod config) gets no mesh tier
    at admission; the worker-side ``make_mesh`` inside dispatch_mesh
    is where the live backend gets the last word either way.

    Eligibility: the kernel has a mesh twin (``registry.MESH_KERNELS``
    — the one home of the capability list), >1 device, and the
    sharded leading dim divides the ring (every dist kernel's
    ``N % P == 0`` contract). nbody additionally needs its full
    7-array SoA state on one common length — anything else would
    error inside the kernel; better to dispatch natively and let the
    single-device kernel reject it honestly."""
    from tpukernels import registry

    if kernel not in registry.MESH_KERNELS:
        return None
    from tpukernels.obs import scaling

    n = scaling.inventory(probe=False).get("n_devices")
    if not isinstance(n, int) or n <= 1:
        return None
    shapes = [tuple(np.asarray(a).shape) for a in arrays]
    lead = next((s[0] for s in shapes if s), None)
    if not lead or lead % n:
        return None
    if kernel == "nbody" and (
        len(shapes) != 7 or any(s != (lead,) for s in shapes)
    ):
        return None
    return (n,)
