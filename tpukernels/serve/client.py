"""Client side of the kernel-serving daemon (docs/SERVING.md).

``ServeClient.dispatch(kernel, *arrays, **statics)`` mirrors
``registry.dispatch``'s signature over the wire: numpy operands in,
numpy results out (a single array, or a tuple when the kernel returns
several). That symmetry is the point — ``capi.run_from_c`` and
``tools/loadgen.py --serve`` swap the in-process serving path for the
daemon by swapping one callable, and the daemon itself dispatches
through the real ``registry.dispatch`` on the other end.

Deliberately jax-free: a client host (the C driver's embedded
interpreter, a loadgen probe box) needs numpy and a socket, nothing
else — backend init, compilation and the executable memo all live in
the daemon.

Failure surface: :class:`ServeError` for daemon-reported dispatch
errors, :class:`ServeRejected` (carrying ``retry_after_s``) for
admission-control rejections — backpressure is a first-class answer
the caller must see, not an exception to swallow — and plain
``OSError``/``ProtocolError`` for transport trouble (the caller
decides whether an in-process fallback exists; ``capi`` retains one).
"""

from __future__ import annotations

import os
import socket
import time

import numpy as np

from tpukernels import _cachedir
from tpukernels.obs import metrics as obs_metrics
from tpukernels.serve import protocol

# transport failures a RESPAWNED daemon explains: the old connection
# died with the old process (reset / broken pipe / mid-frame EOF) but
# the socket path is live again — dispatch_with_backpressure retries
# these ONCE through a fresh connection (docs/SERVING.md
# §self-healing). A refused reconnect (daemon actually down)
# propagates as the hard error it is.
_RECONNECTABLE = (ConnectionResetError, BrokenPipeError,
                  protocol.ProtocolError)

# the ROUTER-crash window (docs/SERVING.md §guardian): between a
# router SIGKILL and its guardian-supervised respawn, connects are
# REFUSED (the socket file outlives the process) or the path briefly
# vanishes (the respawn re-binds). dispatch_with_backpressure absorbs
# this whole window — refused connects AND repeated resets — under the
# TPK_CLIENT_RECONNECT_S budget, with the same request_id throughout.
_REFUSED = (ConnectionRefusedError, FileNotFoundError)
_ABSORBABLE = _RECONNECTABLE + _REFUSED

# seconds between reconnect attempts inside the budget window (scaled
# 0.5x-1.5x by the caller's seeded jitter, same decorrelation story
# as the rejection retries)
_RECONNECT_STEP_S = 0.25


def _reconnect_budget_s() -> float:
    """``TPK_CLIENT_RECONNECT_S`` (docs/KNOBS.md): how long a client
    keeps re-trying a dead front socket before the transport error
    surfaces. 0 disables the window (only the single stale-connection
    retry remains). Fail-loud parse, like every knob."""
    raw = os.environ.get("TPK_CLIENT_RECONNECT_S")
    if raw is None or not raw.strip():
        return 5.0
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if val < 0.0:
        raise ValueError(
            f"TPK_CLIENT_RECONNECT_S={raw!r}: expected a number >= 0"
        )
    return val


class ServeError(Exception):
    """The daemon answered, and the answer is a dispatch failure."""


class ServeRejected(ServeError):
    """Admission control turned the request away; ``retry_after_s``
    is the daemon's load-derived retry hint."""

    def __init__(self, msg, retry_after_s=0.1):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class ServeExpired(ServeError):
    """The request's deadline ran out before the fleet could (or
    would) serve it — the router refused an infeasible budget
    (``serve_deadline_infeasible``) or a layer expired it in flight
    (``serve_request_expired``). Deliberately NOT a
    :class:`ServeRejected`: retrying the same ever-shrinking budget
    is doomed, so backpressure retries must not absorb it —
    ``retry_after_s`` is the honest hint for a FRESH deadline."""

    def __init__(self, msg, retry_after_s=0.0):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


def default_socket_path() -> str:
    """``TPK_SERVE_SOCKET`` when set (also the capi routing switch),
    else the serve dir's ``serve.sock`` (``tpukernels/_cachedir.py``)."""
    return _cachedir.serve_socket_path()


def default_deadline_ms():
    """``TPK_DEADLINE_DEFAULT_MS`` (docs/SERVING.md §deadlines): the
    deadline stamped on every dispatch when neither the client nor
    the caller set one. Unset/0 = off — requests carry no deadline
    and every layer's expiry check stays a single ``is None`` test,
    the fault-plan hot-path discipline. Fail-loud parse, >= 0."""
    raw = os.environ.get("TPK_DEADLINE_DEFAULT_MS")
    if raw is None or not raw.strip():
        return None
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if val < 0.0:
        raise ValueError(
            f"TPK_DEADLINE_DEFAULT_MS={raw!r}: expected a number >= 0"
        )
    return val or None


def dispatch_with_backpressure(cli, kernel, args, statics,
                               max_rejections: int = 10,
                               jitter=None):
    """``cli.dispatch`` honoring admission control: a
    :class:`ServeRejected` is retried after the daemon's
    ``retry_after_s`` hint, up to ``max_rejections`` times, then
    re-raised — the one backpressure policy both standing clients
    (``capi._dispatch``, ``loadgen.run_serve``) share; only the
    give-up action differs, so it stays with the caller. Transport
    errors and daemon-reported :class:`ServeError` propagate
    untouched.

    ``jitter`` (a ``random.Random``, deterministically seeded by the
    caller) decorrelates the retries: the raw hint is scaled by a
    uniform 0.5x-1.5x draw per retry, so a burst of clients rejected
    together does not sleep the same hint and re-stampede a
    recovering daemon in lockstep (the thundering-herd fix — seeded,
    so a loadgen run's schedule stays byte-reproducible). ``None``
    keeps the raw hint.

    One stale-connection transport failure is also absorbed: a
    client that held a connection to a daemon which was since
    RESTARTED on the same socket (the health manager's respawn, a
    rolling restart) sees ECONNRESET/EPIPE/mid-frame EOF on its next
    dispatch — that is retried exactly once through a fresh
    connection, with the SAME request_id (the PR-13 one-id
    discipline: it is still one logical request). Kernels are pure,
    so the replay is safe even if the old daemon executed before
    dying.

    Beyond that single free retry, a ``TPK_CLIENT_RECONNECT_S``
    budget (default 5 s) absorbs the ROUTER-crash window: refused
    connects and repeated resets are re-tried on a short seeded-jitter
    cadence — same request_id every attempt, so the respawned
    router's WAL-replay stash recognizes the retry — until the budget
    runs out, at which point the transport error surfaces as the hard
    failure it is (no silent hang). ``TPK_CLIENT_RECONNECT_S=0``
    disables the window."""
    # one LOGICAL request, one causal id: backpressure retries of the
    # same request must not mint fresh request_ids, or the timeline
    # assembler would see N unrelated one-hop requests instead of one
    # request that waited out admission control
    rid = getattr(cli, "next_request_id", None)
    if rid is None:
        mint = getattr(cli, "mint_request_id", None)
        if mint is not None:
            rid = cli.next_request_id = mint()
    tries = 0
    reconnected = False
    deadline = None  # first transport failure starts the budget clock

    def _re_arm():
        # one logical request, one id AND one deadline: a retry must
        # not restart the budget clock any more than it may mint a
        # fresh request_id — the remaining budget keeps shrinking
        # across admission/reconnect retries (docs/SERVING.md
        # §deadlines)
        if rid is not None:
            cli.next_request_id = rid
        dl_at = getattr(cli, "last_deadline_at", None)
        if dl_at is not None:
            cli.next_deadline_at = dl_at

    while True:
        try:
            return cli.dispatch(kernel, *args, **statics)
        except ServeRejected as e:
            tries += 1
            if tries >= max_rejections:
                raise
            wait = e.retry_after_s
            if jitter is not None:
                wait *= 0.5 + jitter.random()
            time.sleep(wait)
            _re_arm()
        except _ABSORBABLE as e:
            # dispatch() already closed the poisoned socket; the next
            # call reconnects on the same path
            now = time.monotonic()
            if deadline is None:
                deadline = now + _reconnect_budget_s()
            if isinstance(e, _RECONNECTABLE) and not reconnected:
                # the stale-connection case: one immediate free retry
                # (the respawned-daemon story above)
                reconnected = True
            else:
                # the router-crash window: pace the reconnects until
                # the budget is spent, then surface the hard error
                if now >= deadline:
                    raise
                step = _RECONNECT_STEP_S
                if jitter is not None:
                    step *= 0.5 + jitter.random()
                time.sleep(min(step, max(0.0, deadline - now)))
            _re_arm()


class ServeClient:
    """One connection, one outstanding request at a time (the
    protocol's pipelining contract). Connects lazily and reconnects
    after transport errors; not thread-safe — give each client thread
    its own instance.

    Payload lanes (docs/SERVING.md §wire format): the first dispatch
    on a connection negotiates via a ping — a server advertising
    ``shm`` in its ``lanes`` gets operands at or over
    ``TPK_SERVE_SHM_MIN_BYTES`` written straight into ``/dev/shm``
    segments (unlinked once the response arrives) and may answer the
    same way (this client maps, copies out, and unlinks immediately
    — the receiver-unlinks contract). Everything else — old servers,
    hosts without ``/dev/shm``, ``TPK_SERVE_SHM=0`` — stays on the
    inline lane unchanged. ``inline_payloads``/``staged_payloads``/
    ``bytes_copied`` expose this side's lane traffic (mirrored into
    ``serve.bytes_copied.<kernel>``) so loadgen can stamp the
    copy-budget evidence."""

    def __init__(self, socket_path=None, timeout_s=None,
                 tenant=None, priority=None, deadline_ms=None):
        # tenant/priority ride every dispatch header: the fleet
        # router's admission point (per-tenant token buckets,
        # priority classes — docs/SERVING.md §fleet) reads them; the
        # single daemon carries tenant through to its journal
        # evidence and ignores priority
        self.socket_path = socket_path or default_socket_path()
        self.timeout_s = timeout_s
        self.tenant = tenant
        self.priority = priority
        self._sock = None
        self._rid = 0
        self._lanes = None      # negotiated at ping time; None=unknown
        self.inline_payloads = 0
        self.staged_payloads = 0
        self.bytes_copied = 0
        # request tracing (docs/OBSERVABILITY.md §request tracing):
        # every dispatch header carries a CLIENT-MINTED request_id —
        # set next_request_id to choose it (loadgen seeds them
        # deterministically), else one is minted per dispatch. Old
        # servers ignore the field (the shm-lane negotiation pattern:
        # request_trace in the pong says the server tags its journal).
        self.next_request_id = None
        self.last_request_id = None
        self.request_trace = None   # from the pong; None = unknown
        self._trace_seq = 0
        # deadlines (docs/SERVING.md §deadlines): per-client default
        # total budget in ms (falls back to TPK_DEADLINE_DEFAULT_MS;
        # None/0 = no deadline). next_deadline_ms overrides ONE
        # dispatch; next_deadline_at (a local monotonic absolute)
        # CONTINUES an in-flight logical request's budget across
        # retries instead of restarting it — last_deadline_at is what
        # dispatch_with_backpressure restores from.
        self.deadline_ms = deadline_ms
        self.next_deadline_ms = None
        self.next_deadline_at = None
        self.last_deadline_at = None

    # ---------------------------------------------------------- #
    # transport                                                  #
    # ---------------------------------------------------------- #

    def _connected(self):
        if self._sock is None:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            if self.timeout_s is not None:
                s.settimeout(self.timeout_s)
            try:
                s.connect(self.socket_path)
            except OSError:
                s.close()
                raise
            self._sock = s
        return self._sock

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        # a reconnect may land on a restarted (or different) server:
        # renegotiate lanes rather than trust a stale advertisement
        self._lanes = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def _roundtrip(self, header, payloads=()):
        """One frame out, one frame back; returns ``(header, payloads,
        sent_inline_bytes)`` — the send-side copy accounting rides
        along so :meth:`dispatch` can attribute it per kernel."""
        sock = self._connected()
        try:
            sent = protocol.send_frame(sock, header, payloads)
            frame = protocol.recv_frame(sock)
        except (OSError, protocol.ProtocolError):
            self.close()  # poisoned stream: next call reconnects
            raise
        if frame is None:
            self.close()
            raise protocol.ProtocolError(
                "daemon hung up before answering"
            )
        return frame[0], frame[1], sent

    # ---------------------------------------------------------- #
    # operations                                                 #
    # ---------------------------------------------------------- #

    def ping(self) -> dict:
        """Liveness + stats (pid, served/rejected/requeued counts,
        queue depth, device_kind, jax version) — and the lane
        negotiation point: the pong's ``lanes`` (absent on old
        servers = inline only) decides whether later dispatches may
        use the shm lane."""
        header, _payloads, _sent = self._roundtrip(
            {"v": protocol.VERSION, "op": "ping"}
        )
        lanes = header.get("lanes")
        self._lanes = ([str(x) for x in lanes]
                       if isinstance(lanes, list) else ["inline"])
        self.request_trace = bool(header.get("request_trace"))
        return header

    def stats(self) -> dict:
        """The read-only live-telemetry op (docs/SERVING.md §stats
        op): the pong plus the live metrics snapshot, pad-pool state
        and — against a router — per-worker ``worker_stats`` and the
        summed ``fleet`` row. An old server answers ``ok: False``
        with an unknown-op error; callers treat that as 'no stats
        plane', not a dead daemon."""
        header, _payloads, _sent = self._roundtrip(
            {"v": protocol.VERSION, "op": "stats"}
        )
        return header

    def mint_request_id(self) -> str:
        """One fresh causal request id (pid-scoped, monotonic): the
        default when the caller never set ``next_request_id``."""
        self._trace_seq += 1
        return f"c{os.getpid():x}-{self._trace_seq}"

    def dispatch(self, kernel: str, *args, **statics):
        """One kernel request: numpy operands (host scalars as 0-d
        arrays — pass ``np.float32(x)``/``np.int32(n)``), numpy
        result(s) back, already sliced to the request's native shapes
        when the daemon bucketed it."""
        arrays = [np.asarray(a) for a in args]
        specs, payloads = protocol.pack_arrays(arrays)
        use_shm = False
        if protocol.shm_enabled():
            if self._lanes is None:
                self.ping()  # negotiate once per connection
            use_shm = "shm" in (self._lanes or ())
        self._rid += 1
        rid_trace = self.next_request_id
        self.next_request_id = None
        if rid_trace is None:
            rid_trace = self.mint_request_id()
        self.last_request_id = str(rid_trace)
        req = {"v": protocol.VERSION, "op": "dispatch",
               "id": self._rid, "kernel": kernel, "statics": statics,
               "request_id": self.last_request_id,
               "args": specs}
        if self.tenant is not None:
            req["tenant"] = self.tenant
        if self.priority is not None:
            req["priority"] = self.priority
        # deadline stamping: the total budget (deadline_ms) plus the
        # monotonic-safe remaining budget at THIS send (budget_ms,
        # recomputed per hop — docs/SERVING.md §deadlines). A retry of
        # the same logical request arrives with next_deadline_at set
        # and keeps the original clock.
        dl_ms = self.next_deadline_ms
        self.next_deadline_ms = None
        if dl_ms is None:
            dl_ms = self.deadline_ms
            if dl_ms is None:
                dl_ms = default_deadline_ms()
        dl_at = self.next_deadline_at
        self.next_deadline_at = None
        if dl_ms:
            if dl_at is None:
                dl_at = time.monotonic() + dl_ms / 1000.0
            req["deadline_ms"] = dl_ms
            req = protocol.stamp_budget(req, dl_at)
        else:
            dl_at = None
        self.last_deadline_at = dl_at
        segs: list = []
        if use_shm:
            req["shm_ok"] = True  # the server may answer via shm too
            try:
                descs, wire, segs, _staged = (
                    protocol.stage_shm_payloads(payloads)
                )
            except OSError:
                descs = None  # exhausted /dev/shm: inline still works
            if descs is not None:
                req["_shm"] = descs
                payloads = wire
        try:
            header, out_payloads, sent = self._roundtrip(req, payloads)
        finally:
            # request-segment lifecycle: the creator unlinks once the
            # round trip is over (the worker mapped them, or never
            # will) — crash windows are covered by the daemon's
            # dead-creator sweep
            for seg in segs:
                seg.close()
                seg.unlink()
        self._count(kernel, sent,
                    inline=len(payloads), staged=len(segs))
        if not header.get("ok"):
            msg = header.get("error") or "daemon error"
            if header.get("kind") == "overloaded":
                raise ServeRejected(
                    msg, float(header.get("retry_after_s") or 0.1)
                )
            if header.get("kind") in ("expired", "deadline_infeasible"):
                raise ServeExpired(
                    msg, float(header.get("retry_after_s") or 0.0)
                )
            raise ServeError(msg)
        resp_descs = [d for d in (header.get("_shm") or ()) if d]
        out_payloads, resp_inline, maps = (
            protocol.resolve_shm_payloads(header, out_payloads)
        )
        self._count(kernel, resp_inline)
        outs = protocol.unpack_arrays(
            header.get("outputs") or [], out_payloads
        )
        if maps:
            # receiver-unlinks contract: copy the results out of the
            # server's response segments, then free + unlink them NOW
            # (the returned arrays must not pin shared memory)
            outs = [np.array(o) for o in outs]
            del out_payloads
            for mm in maps:
                try:
                    mm.close()
                except BufferError:
                    pass
            for d in resp_descs:
                protocol.unlink_shm(d.get("name"))
        return outs[0] if len(outs) == 1 else tuple(outs)

    def _count(self, kernel: str, nbytes: int, inline: int = 0,
               staged: int = 0):
        """Client-side half of the copy accounting: inline payload
        bytes through the socket, mirrored into the same
        ``serve.bytes_copied.<kernel>`` counter the daemon keeps —
        every layer's number is its own socket traffic."""
        self.inline_payloads += inline
        self.staged_payloads += staged
        if nbytes:
            self.bytes_copied += nbytes
            obs_metrics.inc(f"serve.bytes_copied.{kernel}", nbytes)
