"""The kernel-serving daemon (docs/SERVING.md).

Every entry point before this module — bench.py, the C shim, the
autotune sweep, the load generator — was a batch process that paid
backend init and first-compile per invocation. This is the long-lived
process in the middle: a Unix-domain-socket server that accepts
dispatch requests (kernel, shapes, dtypes, statics, raw operand
bytes — ``tpukernels/serve/protocol.py``) from any number of
concurrent clients and runs every one through ``registry.dispatch``,
i.e. through the process-wide compiled-executable memo, the
fault-injection point and the output-integrity guard the batch paths
already trust. After the first request per (kernel, bucket), serving
is compile-free.

The service disciplines, each CPU-chaos-proven (tests/test_serve.py):

- **Shape bucketing** (``bucketing.py``) — operands are zero-padded
  up to the nearest registered AOT avatar (never down, waste-capped,
  per-kernel correctness rules) so a diverse client shape population
  collapses onto a handful of warm executables;
  ``serve.bucket_pad_frac`` makes the padding waste observable.
- **Continuous batching** — same-bucket requests are coalesced to one
  worker and served back-to-back on one warm executable
  (``serve.batch_size``). The coalescing window is ADAPTIVE
  (``TPK_SERVE_BATCH_ADAPT``, on by default): it collapses to 0 the
  moment the queue is empty — an idle request dispatches immediately
  — and widens toward the ``TPK_SERVE_BATCH_WINDOW_MS`` cap under
  burst, steered by the admission path's inter-arrival EWMA
  (``serve.batch_window_ms`` gauges the live value).
- **Zero-copy wire path** — payloads at or over
  ``TPK_SERVE_SHM_MIN_BYTES`` ride ``/dev/shm`` segments the client
  writes and this daemon maps read-only (negotiated at ping time;
  inline remains for small tensors and old clients), response
  payloads are written ONCE into segments of their own (the single
  producer-to-consumer move every lane needs), and the per-bucket
  pad staging buffers are reused across requests — the warm shm
  path copies zero payload bytes beyond that handoff and allocates
  no staging buffers (``serve.bytes_copied.<kernel>`` is the
  machine-checked evidence; docs/SERVING.md §wire format).
- **Admission control** — the request queue is bounded
  (``TPK_SERVE_QUEUE_MAX``); at depth, new requests are REJECTED
  immediately with a ``retry_after_s`` hint (``serve_rejected``)
  instead of queueing into unbounded latency — the client sees the
  overload, the p99 of admitted requests stays honest.
- **Request deadlines** (docs/SERVING.md §deadlines) — a client-set
  budget rides every hop (``budget_ms``, recomputed per hop so no
  absolute clock crosses processes); doomed work is EXPIRED at the
  worker instead of dispatched (``serve_request_expired``), the
  coalescing window never widens past half the tightest remaining
  budget in the batch, and a best-effort ``cancel`` op lets the
  router's hedged dispatch drop the losing attempt
  (``serve_cancelled``) — pre-dispatch cancel removes the queue
  entry, in-flight cancel just suppresses the send.
- **Worker watchdog** — an in-flight request stuck past
  ``TPK_SERVE_REQUEST_TIMEOUT_S`` gets the bench treatment: its
  worker thread is abandoned (a wedged PJRT call cannot be cancelled
  — the thread is marked, replaced, and its eventual result
  discarded), the timeout is classified slow-vs-wedged through
  ``watchdog.classify_timeout``, and the request is re-queued ONCE
  (``serve_request_requeued``) before failing loudly to the client.

Observability rides the existing stack: a ``serve/<kernel>`` span per
request, ``serve.*`` counters/histograms, and the
``serve_start``/``serve_request``/``serve_rejected``/
``serve_request_requeued``/``serve_stop`` journal kinds
(docs/OBSERVABILITY.md). Requests carry a client-minted
``request_id`` (§request tracing): the worker thread binds it as its
ambient trace context, so the wait/lock/pad/dispatch spans — and
their nested aot/integrity children — plus every journal record of
the request are causally joinable across the whole fleet. The daemon prints NOTHING to stdout on the
clean path (notes go to stderr, evidence to the journal) — the
byte-identical proof the fault/trace/AOT layers established, applied
to a server.

Run it: ``python -m tpukernels.serve [--socket PATH ...]`` (or
``tools/serve_ctl.py start``). SIGTERM/SIGINT shut it down cleanly:
the listener closes, the socket and flocked pidfile are removed, and
``serve_stop`` records the session totals.
"""

from __future__ import annotations

import collections
import os
import queue as _queue_mod
import socket
import struct
import sys
import threading
import time

from tpukernels import _cachedir
from tpukernels.obs import metrics as obs_metrics
from tpukernels.obs import trace
from tpukernels.resilience import faults, journal, watchdog
from tpukernels.serve import bucketing, protocol

DEFAULT_QUEUE_MAX = 64
DEFAULT_WORKERS = 2
DEFAULT_BATCH_WINDOW_MS = 2.0
DEFAULT_REQUEST_TIMEOUT_S = 60.0

# adaptive batching aims to gather about this many same-bucket
# requests per window under burst: the window widens to ~7 projected
# inter-arrival gaps (capped by TPK_SERVE_BATCH_WINDOW_MS) and
# collapses to 0 the moment the queue is empty, so an idle request
# never pays the window (docs/SERVING.md §continuous batching)
BATCH_TARGET = 8

# response shm segments the client should have mapped-and-unlinked
# long ago (its own socket timeout bounds the wait) are reclaimed by
# the watchdog after this grace — the leak-on-crash backstop for a
# client that died between our send and its map
SHM_RESPONSE_GRACE_S = 120.0

# kernel-level SO_SNDTIMEO on accepted sockets: a client that stops
# READING (SIGSTOP'd, hung) would otherwise block a worker forever in
# sendall once the response outgrows the socket buffer — invisibly to
# the watchdog, which tracks dispatch, not delivery. Send-only, so an
# idle client's connection (blocked in recv on our side) lives forever.
SEND_TIMEOUT_S = 30.0


def _int_knob(name: str, default: int, floor: int = 1) -> int:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = int(raw)
    except ValueError:
        val = floor - 1
    if val < floor:
        raise ValueError(
            f"{name}={raw!r}: expected an int >= {floor}"
        )
    return val


def _float_knob(name: str, default: float, floor: float = 0.0) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        val = float(raw)
    except ValueError:
        val = floor - 1.0
    if val < floor:
        raise ValueError(
            f"{name}={raw!r}: expected a number >= {floor}"
        )
    return val


def _on_knob(name: str, default: bool = True) -> bool:
    """An on-by-default switch knob (the TPK_AOT_CACHE convention):
    ``0``/``off``/``none``/``false`` disable, anything else keeps the
    default."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    return raw.strip().lower() not in ("0", "off", "none", "false")


class _Request:
    """One in-flight dispatch request and its lifecycle state."""

    __slots__ = ("serial", "rid", "kernel", "statics", "arrays",
                 "spec", "pad_frac", "bucket", "conn", "t_enq",
                 "t_start", "requeues", "patience", "done", "lock",
                 "worker_ident", "tenant", "shm_ok", "request_id",
                 "shapes", "dtypes", "replayed", "deadline_at",
                 "mesh_shape")

    def __init__(self, serial, rid, kernel, statics, arrays, spec,
                 pad_frac, bucket, conn, tenant=None, shm_ok=False,
                 request_id=None, replayed=None, deadline_at=None,
                 mesh_shape=None):
        self.serial = serial  # server-side key: client ids can collide
        self.rid = rid
        # the client-minted causal id (docs/OBSERVABILITY.md §request
        # tracing); None for pre-tracing clients. The requested (pre-
        # pad) shapes/dtypes ride to the serve_request shape-mix
        # record — the bucket-table optimizer's input (ROADMAP 5).
        self.request_id = request_id
        self.shapes = [list(a.shape) for a in arrays]
        self.dtypes = [a.dtype.name for a in arrays]
        self.kernel = kernel
        self.statics = statics
        self.arrays = arrays
        self.spec = spec
        self.pad_frac = pad_frac
        self.bucket = bucket
        self.conn = conn
        self.tenant = tenant
        # the router's replay-idempotency count (protocol.py): >0
        # means a dead sibling may already have executed this request
        # — safe (kernels are pure), recorded on the serve_request
        # evidence so postmortems see the delivery history
        self.replayed = replayed
        # this process's monotonic instant the client's budget runs
        # out (protocol.deadline_from_header) — no absolute client
        # time ever crosses the wire, so clock skew cannot expire (or
        # resurrect) a request; None means no deadline
        self.deadline_at = deadline_at
        # the admission-time mesh-tier decision (bucketing.
        # mesh_tier_for): a non-None shape routes this over-avatar
        # request through registry.dispatch_mesh instead of the
        # single-device dispatch (docs/SERVING.md §mesh tier)
        self.mesh_shape = tuple(mesh_shape) if mesh_shape else None
        self.shm_ok = shm_ok       # client negotiated the shm lane
        self.t_enq = time.perf_counter()
        self.t_start = None
        self.requeues = 0
        self.patience = 0          # grace extensions granted (max 1)
        self.done = False          # guarded by self.lock
        self.lock = threading.Lock()
        self.worker_ident = None

    def claim_done(self) -> bool:
        """Atomically claim the right to respond — the one guard that
        makes a watchdog-requeued request and its abandoned original
        worker unable to both answer the client."""
        with self.lock:
            if self.done:
                return False
            self.done = True
            return True


class _Conn:
    """A client connection plus its send lock: worker threads answer
    requests while the reader thread may be rejecting the client's
    next one — frames must never interleave on the wire."""

    __slots__ = ("sock", "send_lock", "lane_logged")

    def __init__(self, sock):
        self.sock = sock
        self.send_lock = threading.Lock()
        self.lane_logged = False   # serve_lane_negotiated once per conn

    def send(self, header, payloads=()) -> int:
        """Returns the inline payload bytes pushed through the socket
        (``send_frame``'s copy accounting)."""
        with self.send_lock:
            return protocol.send_frame(self.sock, header, payloads)


class _BoundedQueue:
    """Bounded FIFO with same-bucket extraction — the admission-control
    and coalescing surface. ``queue.Full`` at depth is the
    backpressure contract; ``take_matching`` pulls every queued
    request of one bucket WITHOUT disturbing the order of the rest."""

    def __init__(self, maxlen: int):
        self._d = collections.deque()
        self._cv = threading.Condition()
        self._max = maxlen

    def put_nowait(self, item, force: bool = False):
        with self._cv:
            if not force and len(self._d) >= self._max:
                raise _queue_mod.Full
            self._d.append(item)
            self._cv.notify()

    def get(self, timeout: float):
        with self._cv:
            if not self._d:
                self._cv.wait(timeout)
            if not self._d:
                return None
            return self._d.popleft()

    def take_matching(self, bucket: str, limit: int):
        with self._cv:
            taken, keep = [], collections.deque()
            for item in self._d:
                if item.bucket == bucket and len(taken) < limit:
                    taken.append(item)
                else:
                    keep.append(item)
            self._d = keep
            return taken

    def remove_request(self, request_id: str):
        """Pull ONE queued entry by its client-minted request_id — the
        pre-dispatch half of the best-effort ``cancel`` op. Returns
        the removed request or None (already dispatched / unknown)."""
        with self._cv:
            for item in self._d:
                if item.request_id == request_id:
                    self._d.remove(item)
                    return item
            return None

    def depth(self) -> int:
        with self._cv:
            return len(self._d)


class Server:
    def __init__(self, socket_path=None, queue_max=None, workers=None,
                 batch_window_ms=None, request_timeout_s=None):
        self.socket_path = socket_path or _cachedir.serve_socket_path()
        self.queue_max = (queue_max if queue_max is not None
                          else _int_knob("TPK_SERVE_QUEUE_MAX",
                                         DEFAULT_QUEUE_MAX))
        self.workers = (workers if workers is not None
                        else _int_knob("TPK_SERVE_WORKERS",
                                       DEFAULT_WORKERS))
        self.batch_window_s = (
            batch_window_ms if batch_window_ms is not None
            else _float_knob("TPK_SERVE_BATCH_WINDOW_MS",
                             DEFAULT_BATCH_WINDOW_MS)
        ) / 1000.0
        self.request_timeout_s = (
            request_timeout_s if request_timeout_s is not None
            else _float_knob("TPK_SERVE_REQUEST_TIMEOUT_S",
                             DEFAULT_REQUEST_TIMEOUT_S, floor=0.1)
        )
        self._q = _BoundedQueue(self.queue_max)
        self._stop = threading.Event()
        self._listener = None
        self._lock = threading.Lock()       # shared mutable maps below
        self._inflight: dict = {}           # serial -> _Request (started)
        self._bucket_locks: dict = {}       # bucket -> [lock, holder]
        self._abandoned: set = set()        # wedged worker idents
        self._worker_pending: dict = {}     # ident -> deque of batch rest
        self._next_rid = 0
        self._served = 0
        self._rejected = 0
        self._requeued = 0
        self._expired = 0
        self._cancelled = 0
        self._t0 = time.time()
        self._service_ewma = 0.05           # retry-after hint basis
        # continuous batching: the admission path tracks an
        # inter-arrival EWMA (fast attack — one short gap IS a burst —
        # slow release) the coalescing window is derived from
        self.batch_adapt = _on_knob("TPK_SERVE_BATCH_ADAPT")
        self._arrival_ewma = None
        self._last_arrival = None
        self._last_window_ms = 0.0
        # zero-copy wire path: lane capability + copy accounting
        # (knobs validated here so a typo refuses to start the daemon,
        # the TPK_SERVE_BUCKETS fail-fast rule)
        self._shm = protocol.shm_enabled()
        self._shm_min = protocol.shm_min_bytes()
        self._bytes_copied = 0
        self._shm_ledger: list = []         # (name, t) response segs
        self._pad_pool: dict = {}           # bucket -> {arg_i: buf}
        self._device_kind = None            # resolved by 1st dispatch
        # fail-fast: a misconfigured TPK_SERVE_BUCKETS (typo'd path,
        # malformed JSON) must refuse to start the daemon, not surface
        # as a per-request "bad request" to every client — which capi
        # treats as authoritative and never falls back from
        bucketing.bucket_configs()

    # -------------------------------------------------------------- #
    # lifecycle                                                      #
    # -------------------------------------------------------------- #

    def serve_forever(self):
        d = os.path.dirname(self.socket_path)
        if d:
            os.makedirs(d, exist_ok=True)
        if os.path.exists(self.socket_path):
            # a dead daemon's stale socket; a LIVE one holds the
            # flocked pidfile and serve_ctl refuses to double-start
            os.unlink(self.socket_path)
        self._listener = socket.socket(socket.AF_UNIX,
                                       socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(64)
        self._listener.settimeout(0.5)
        # leak-on-crash cleanup (docs/SERVING.md §shm lifecycle):
        # segments whose creator died before its peer unlinked them
        swept = protocol.sweep_stale_segments()
        journal.emit(
            "serve_start", socket=self.socket_path,
            queue_max=self.queue_max, workers=self.workers,
            batch_window_ms=round(self.batch_window_s * 1e3, 3),
            batch_adapt=self.batch_adapt,
            request_timeout_s=self.request_timeout_s,
            lanes=self._lanes(), shm_swept=swept,
        )
        for _ in range(self.workers):
            self._spawn_worker()
        threading.Thread(target=self._watchdog_loop, daemon=True,
                         name="serve-watchdog").start()
        try:
            while not self._stop.is_set():
                try:
                    conn, _addr = self._listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                conn.setsockopt(
                    socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                    struct.pack("ll", int(SEND_TIMEOUT_S), 0),
                )
                threading.Thread(
                    target=self._client_loop, args=(_Conn(conn),),
                    daemon=True, name="serve-client",
                ).start()
        finally:
            self.shutdown()

    def stop(self, *_sig):
        """Signal-handler-safe stop request."""
        self._stop.set()

    def shutdown(self):
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
            self._listener = None
            try:
                os.unlink(self.socket_path)
            except OSError:
                pass
            journal.emit(
                "serve_stop", served=self._served,
                rejected=self._rejected, requeued=self._requeued,
                uptime_s=round(time.time() - self._t0, 3),
            )

    def _spawn_worker(self):
        threading.Thread(target=self._worker_loop, daemon=True,
                         name="serve-worker").start()

    # -------------------------------------------------------------- #
    # client side: read, admit or reject                             #
    # -------------------------------------------------------------- #

    def _client_loop(self, conn: _Conn):
        try:
            while not self._stop.is_set():
                frame = protocol.recv_frame(conn.sock)
                if frame is None:
                    return
                header, payloads = frame
                op = header.get("op")
                if op == "ping":
                    conn.send(dict(self._stats(), v=protocol.VERSION,
                                   ok=True))
                elif op == "dispatch":
                    # shm resolution happens HERE, not in _admit: a
                    # torn segment is a desynced/hostile stream and
                    # must poison this CONNECTION (the ProtocolError
                    # contract), never become a per-request error
                    payloads, inline_bytes, shm_maps = (
                        protocol.resolve_shm_payloads(header, payloads)
                    )
                    if shm_maps and not conn.lane_logged:
                        conn.lane_logged = True
                        journal.emit("serve_lane_negotiated",
                                     lane="shm",
                                     kernel=header.get("kernel"),
                                     request=header.get("id"))
                    self._admit(conn, header, payloads,
                                inline_bytes=inline_bytes)
                elif op == "stats":
                    conn.send(self._stats_full())
                elif op == "cancel":
                    conn.send(self._cancel(header))
                elif op == "undrain":
                    conn.send(self._undrain())
                else:
                    conn.send({"v": protocol.VERSION,
                               "id": header.get("id"), "ok": False,
                               "kind": "error",
                               "error": f"unknown op {op!r}"})
        except (protocol.ProtocolError, OSError):
            pass  # poisoned/hung-up connection: drop it, serve on
        finally:
            try:
                conn.sock.close()
            except OSError:
                pass

    def _undrain(self) -> dict:
        """The standalone daemon's promoted-table pickup (the router
        forwards nothing here — its own ``undrain`` busts its own
        cache): re-read TPK_SERVE_BUCKETS through ``bucketing.reload``
        and drop the avatar-shaped pad staging pool, whose buffers
        were sized for the OLD table's buckets. A malformed new table
        answers as an error and the old one stays in effect
        (docs/SERVING.md §adaptive buckets)."""
        try:
            table = bucketing.reload()
        except (OSError, ValueError) as e:
            return {"v": protocol.VERSION, "ok": False,
                    "kind": "error",
                    "error": f"undrain refused: TPK_SERVE_BUCKETS "
                             f"reload failed: {e}"}
        with self._lock:
            self._pad_pool.clear()
        journal.emit(
            "serve_drain", worker=None, socket=self.socket_path,
            phase="undrain", inflight=len(self._inflight),
            kernels=sorted(table),
        )
        return {"v": protocol.VERSION, "ok": True,
                "reloaded": sorted(table)}

    def _stats(self) -> dict:
        with self._lock:
            inflight = len(self._inflight)
            # the bucket-lock table is exactly the set of compiled-
            # program buckets this daemon has ever dispatched — the
            # per-bucket memo-ownership answer a fleet status wants
            buckets = sorted(self._bucket_locks)
        return {
            "op": "pong", "pid": os.getpid(),
            "served": self._served, "rejected": self._rejected,
            "requeued": self._requeued, "expired": self._expired,
            "cancelled": self._cancelled, "depth": self._q.depth(),
            "inflight": inflight, "buckets": buckets,
            "worker_id": os.environ.get("TPK_SERVE_WORKER_ID"),
            "queue_max": self.queue_max, "workers": self.workers,
            # lane negotiation (docs/SERVING.md §wire format): a
            # client enables shm ONLY after seeing it advertised here,
            # so an old server (no "lanes" key) is spoken to inline
            "lanes": self._lanes(),
            "shm_min_bytes": self._shm_min if self._shm else None,
            # request-tracing advertisement (the lane-negotiation
            # pattern): this server tags its journal evidence with the
            # client-minted request_id; old servers lack the key and
            # simply ignore the header field
            "request_trace": True,
            # the zero-copy + continuous-batching evidence operators
            # read off `serve_ctl status` without opening the journal
            "bytes_copied": self._bytes_copied,
            "batch_window_ms": self._last_window_ms,
            "batch_adapt": self.batch_adapt,
            "uptime_s": round(time.time() - self._t0, 3),
            # report-only, like jax below: a liveness ping must never
            # force backend init in the reader thread (None until the
            # first dispatch resolves it)
            "device_kind": self._device_kind,
            "jax": self._jax_version(),
            # flusher liveness (docs/OBSERVABILITY.md §live telemetry):
            # None when TPK_METRICS_FLUSH_S is off; a value growing
            # past the flush interval means the flusher thread died
            "last_snapshot_age_s": obs_metrics.last_flush_age_s(),
        }

    def _stats_full(self) -> dict:
        """The read-only ``stats`` op (docs/SERVING.md §stats op): the
        ping pong plus the live metrics snapshot and the per-bucket
        pad staging pool. Touches ONLY ``self._lock`` and the metrics
        module lock — never a per-bucket dispatch lock, so `serve_ctl
        top` against a daemon wedged in a dispatch still answers."""
        with self._lock:
            pad_pool = {
                b: {
                    "bufs": len(pool),
                    "bytes": sum(
                        int(getattr(buf, "nbytes", 0) or 0)
                        for buf in pool.values()
                    ),
                }
                for b, pool in self._pad_pool.items()
            }
        base = self._stats()
        base.update(
            op="stats", ok=True, v=protocol.VERSION,
            role="daemon",
            metrics=obs_metrics.snapshot(),
            pad_pool=pad_pool,
        )
        return base

    @staticmethod
    def _jax_version():
        # report-only: never force the import before the first dispatch
        mod = sys.modules.get("jax")
        return getattr(mod, "__version__", None)

    def _lanes(self) -> list:
        return ["inline", "shm"] if self._shm else ["inline"]

    def _count_copied(self, kernel: str, nbytes: int):
        """One process-wide + one per-kernel bytes-copied bump — the
        counter the copy-budget smoke regresses (docs/SERVING.md
        §copy accounting)."""
        if not nbytes:
            return
        obs_metrics.inc(f"serve.bytes_copied.{kernel}", nbytes)
        with self._lock:
            self._bytes_copied += nbytes

    def _admit(self, conn: _Conn, header: dict, payloads,
               inline_bytes: int = 0):
        rid = header.get("id")
        now = time.perf_counter()
        with self._lock:
            # inter-arrival EWMA: fast attack (one short gap IS a
            # burst — the window must widen on the second arrival, not
            # the tenth), slow release back toward idle
            if self._last_arrival is not None:
                gap = now - self._last_arrival
                if (self._arrival_ewma is None
                        or gap < self._arrival_ewma):
                    self._arrival_ewma = gap
                else:
                    self._arrival_ewma = (0.8 * self._arrival_ewma
                                          + 0.2 * gap)
            self._last_arrival = now
        try:
            kernel = header["kernel"]
            statics = dict(header.get("statics") or {})
            arrays = protocol.unpack_arrays(
                header.get("args") or [], payloads
            )
            spec, how = bucketing.bucket_for(kernel, arrays, statics)
            pad_frac = how if spec is not None else 0.0
            # the over-avatar escape hatch (docs/SERVING.md §mesh
            # tier): a request too big for every avatar may still run
            # — on the kernel's mesh-backed distributed twin. Only the
            # over-avatar reason consults the tier; every other native
            # reason (layout/statics mismatch, pad-over-cap) keeps the
            # plain single-device dispatch it always had.
            mesh_shape = (
                bucketing.mesh_tier_for(kernel, arrays, statics)
                if spec is None and how == "over-avatar" else None
            )
            bucket = bucketing.bucket_id(kernel, spec, statics, arrays)
            if mesh_shape is not None:
                # its own coalescing/locking key: the mesh program is
                # a different executable than a native dispatch at
                # the same shapes would compile
                bucket += "|mesh" + "x".join(
                    str(d) for d in mesh_shape
                )
        except (KeyError, ValueError, TypeError, AttributeError,
                protocol.ProtocolError) as e:
            # TypeError/AttributeError cover structurally malformed
            # headers (scalar shapes, non-dict args/statics) that the
            # field accessors raise before any explicit validation —
            # they must become an error REPLY, not an unhandled
            # exception that kills this client's handler thread
            conn.send({"v": protocol.VERSION, "id": rid, "ok": False,
                       "kind": "error", "error": f"bad request: {e}"})
            return
        # the request's inline payload bytes crossed the socket — the
        # recv-side half of the copy accounting (an shm-lane request
        # counts 0 here: the worker maps what the client wrote)
        self._count_copied(kernel, inline_bytes)
        with self._lock:
            self._next_rid += 1
            serial = self._next_rid
        req_id = header.get("request_id")
        replay = header.get("replay")
        req = _Request(serial, rid if rid is not None else serial,
                       kernel, statics, arrays, spec, pad_frac,
                       bucket, conn, tenant=header.get("tenant"),
                       shm_ok=bool(header.get("shm_ok")),
                       request_id=(str(req_id) if req_id is not None
                                   else None),
                       replayed=(int(replay)
                                 if isinstance(replay, int)
                                 and not isinstance(replay, bool)
                                 and replay > 0 else None),
                       deadline_at=protocol.deadline_from_header(
                           header),
                       mesh_shape=mesh_shape)
        try:
            self._q.put_nowait(req)
        except _queue_mod.Full:
            self._reject(req)

    def _reject(self, req: _Request):
        with self._lock:
            self._rejected += 1
        obs_metrics.inc("serve.rejected")
        depth = self._q.depth()
        retry = round(max(0.05, (depth + 1) * self._service_ewma), 3)
        journal.emit(
            "serve_rejected", kernel=req.kernel, request=req.rid,
            request_id=req.request_id,
            depth=depth, queue_max=self.queue_max, retry_after_s=retry,
        )
        try:
            req.conn.send({
                "v": protocol.VERSION, "id": req.rid, "ok": False,
                "kind": "overloaded", "retry_after_s": retry,
                "error": (f"queue at depth {depth} >= "
                          f"{self.queue_max}; retry after {retry}s"),
            })
        except OSError:
            pass

    def _expire(self, req: _Request, where: str, queue_wait=None):
        """Answer a request whose budget died before dispatch — the
        doomed-work refusal (docs/SERVING.md §deadlines): the pad and
        dispatch phases are skipped entirely, the expiry is journaled
        where the budget went, and the client sees ``expired`` (NOT
        ``overloaded`` — retrying the same shrinking budget is
        doomed, so no retry_after_s choreography)."""
        if not req.claim_done():
            return
        with self._lock:
            self._expired += 1
        obs_metrics.inc("serve.expired")
        journal.emit(
            "serve_request_expired", site="server", where=where,
            kernel=req.kernel, request=req.rid,
            request_id=req.request_id, bucket=req.bucket,
            worker_id=os.environ.get("TPK_SERVE_WORKER_ID"),
            queue_wait_s=(round(queue_wait, 6)
                          if queue_wait is not None else None),
        )
        try:
            req.conn.send({
                "v": protocol.VERSION, "id": req.rid, "ok": False,
                "kind": "expired",
                "error": (f"deadline expired before dispatch "
                          f"({where})"),
            })
        except (OSError, protocol.ProtocolError):
            pass

    def _cancel(self, header: dict) -> dict:
        """The best-effort ``cancel`` op (docs/SERVING.md §deadlines):
        a pre-dispatch cancel drops the queued entry outright; an
        in-flight (or batch-pending) cancel just claims the request's
        done flag so its eventual result is discarded instead of sent
        — a running PJRT dispatch cannot be interrupted, only its
        answer suppressed. A miss (already answered, unknown id) is
        success too: cancel is advisory, never load-bearing."""
        req_id = header.get("request_id")
        rid = str(req_id) if req_id is not None else None
        phase, kernel = "miss", None
        if rid is not None:
            dropped = self._q.remove_request(rid)
            if dropped is not None and dropped.claim_done():
                phase, kernel = "queued", dropped.kernel
            else:
                with self._lock:
                    cands = [r for r in self._inflight.values()
                             if r.request_id == rid]
                    for pend in self._worker_pending.values():
                        cands.extend(r for r in pend
                                     if r.request_id == rid)
                for r in cands:
                    if r.claim_done():
                        phase, kernel = "inflight", r.kernel
                        break
        if phase != "miss":
            with self._lock:
                self._cancelled += 1
            obs_metrics.inc("serve.cancelled")
            journal.emit(
                "serve_cancelled", site="server", phase=phase,
                kernel=kernel, request_id=rid,
                worker_id=os.environ.get("TPK_SERVE_WORKER_ID"),
            )
        return {"v": protocol.VERSION, "op": "cancel", "ok": True,
                "id": header.get("id"),
                "cancelled": phase != "miss", "phase": phase}

    # -------------------------------------------------------------- #
    # worker side: coalesce, dispatch, respond                       #
    # -------------------------------------------------------------- #

    def _worker_loop(self):
        while not self._stop.is_set():
            if self._retire_if_abandoned():
                return
            first = self._q.get(timeout=0.5)
            if first is None:
                continue
            window = self._window_s(self._q.depth())
            window = self._clamp_window(window, (first,))
            self._last_window_ms = round(window * 1e3, 3)
            obs_metrics.gauge("serve.batch_window_ms",
                              self._last_window_ms)
            batch = [first]
            if window > 0:
                end = time.perf_counter() + window
                while True:
                    taken = self._q.take_matching(
                        first.bucket, self.queue_max - len(batch)
                    )
                    if taken:
                        batch.extend(taken)
                        # a tighter-deadline member joining the batch
                        # pulls the window in — coalescing must never
                        # spend budget the tightest member lacks
                        end = min(end, time.perf_counter()
                                  + self._clamp_window(window, taken))
                    rem = end - time.perf_counter()
                    if rem <= 0:
                        break
                    time.sleep(min(rem, 0.001))
            else:
                batch.extend(self._q.take_matching(
                    first.bucket, self.queue_max - len(batch)
                ))
            obs_metrics.observe("serve.batch_size", len(batch))
            # the unstarted remainder is SHARED with the watchdog
            # (self._worker_pending): members coalesced behind a
            # permanently wedged request live only on this thread's
            # stack, so the watchdog must be able to rescue them —
            # a hand-back that waits for the wedged _execute to
            # return would wait forever
            ident = threading.get_ident()
            pending = collections.deque(batch)
            with self._lock:
                self._worker_pending[ident] = pending
            size = len(batch)
            while True:
                with self._lock:
                    if not pending:
                        self._worker_pending.pop(ident, None)
                        break
                    req = pending.popleft()
                try:
                    self._execute(req, size)
                except Exception as e:  # noqa: BLE001 — pool must survive
                    # _execute answers dispatch failures itself; a bug
                    # that still escapes (a response-path surprise)
                    # must not kill the worker thread and strand the
                    # rest of the batch
                    print(f"# serve: worker error on {req.kernel}: "
                          f"{e!r}", file=sys.stderr)
                    if req.claim_done():
                        try:
                            req.conn.send({
                                "v": protocol.VERSION, "id": req.rid,
                                "ok": False, "kind": "error",
                                "error": f"internal worker error: {e!r}",
                            })
                        except (OSError, protocol.ProtocolError):
                            pass
                if self._retire_if_abandoned():
                    # the watchdog abandoned this worker and already
                    # requeued whatever was left in `pending`
                    return

    def _window_s(self, depth: int) -> float:
        """The continuous-batching coalescing window for one pickup.
        Fixed mode (``TPK_SERVE_BATCH_ADAPT=0``) returns the knob
        verbatim. Adaptive mode: an EMPTY queue means the request is
        alone — dispatch NOW, idle traffic never pays the window; a
        non-empty queue under burst (inter-arrival EWMA shorter than
        the max window) widens to ~``BATCH_TARGET`` projected
        arrivals, capped at ``TPK_SERVE_BATCH_WINDOW_MS``; arrivals
        slower than the cap mean waiting buys nothing — 0 again."""
        if not self.batch_adapt:
            return self.batch_window_s
        if depth <= 0:
            return 0.0
        gap = self._arrival_ewma
        if gap is None or gap >= self.batch_window_s:
            return 0.0
        return min(self.batch_window_s, gap * (BATCH_TARGET - 1))

    @staticmethod
    def _clamp_window(window: float, reqs) -> float:
        """The deadline clamp on the coalescing window: never widen
        past HALF the tightest remaining budget among ``reqs`` — the
        other half is left for the dispatch itself, so coalescing can
        delay a deadline-carrying request but never doom it.
        Deadline-free members leave the window alone."""
        if window <= 0:
            return window
        now = time.monotonic()
        for r in reqs:
            if r.deadline_at is not None:
                window = min(
                    window, max(0.0, (r.deadline_at - now) / 2)
                )
        return window

    def _retire_if_abandoned(self) -> bool:
        """True when the watchdog abandoned THIS worker — and forget
        its ident on the way out: thread idents are recycled after
        exit, and a stale entry would make a future worker be born
        'abandoned' and silently shrink the pool."""
        ident = threading.get_ident()
        with self._lock:
            if ident not in self._abandoned:
                return False
            self._abandoned.discard(ident)
        return True

    def _bucket_lock(self, bucket: str):
        """The bucket's ``[lock, holder_ident]`` cell, on demand."""
        with self._lock:
            cell = self._bucket_locks.get(bucket)
            if cell is None:
                cell = self._bucket_locks[bucket] = [
                    threading.Lock(), None
                ]
            return cell

    def _acquire_bucket(self, bucket: str):
        """Serialize same-bucket dispatches (one compile per bucket is
        an assertion, not a hope). A legitimately slow holder — a cold
        record-shape compile can outlast any fixed fraction of the
        request timeout — is waited out indefinitely; ONLY a lock
        whose holder the watchdog abandoned as wedged is replaced, so
        the bucket cannot stay poisoned forever and two workers can
        never compile the same bucket concurrently."""
        poll = max(0.05, min(0.5, self.request_timeout_s / 4))
        while True:
            cell = self._bucket_lock(bucket)
            if cell[0].acquire(timeout=poll):
                with self._lock:
                    if self._bucket_locks.get(bucket) is not cell:
                        # replaced while we were blocked on the stale
                        # lock: drop it, race for the current one
                        cell[0].release()
                        continue
                    cell[1] = threading.get_ident()
                return cell
            with self._lock:
                holder = cell[1]
                if (holder is not None
                        and holder in self._abandoned
                        and self._bucket_locks.get(bucket) is cell):
                    self._bucket_locks[bucket] = [
                        threading.Lock(), None
                    ]
                    # the abandoned holder may still be INSIDE its
                    # dispatch, aliasing this bucket's pad staging
                    # buffers (jnp.asarray is zero-copy on CPU) — the
                    # retry must never re-zero/overwrite them under a
                    # live attempt, so it gets a fresh pool
                    self._pad_pool.pop(bucket, None)

    def _execute(self, req: _Request, batch_size: int):
        # ambient trace context for the whole attempt: every span the
        # worker thread emits below — the wait/pad phases here AND the
        # aot/integrity children nested under dispatch, which know
        # nothing about requests — carries req.request_id
        # (docs/OBSERVABILITY.md §request tracing)
        with trace.request_ctx(req.request_id):
            self._execute_attempt(req, batch_size)

    def _execute_attempt(self, req: _Request, batch_size: int):
        import numpy as np

        from tpukernels import registry

        with req.lock:
            if req.done:
                # cancelled while queued behind this batch (the
                # in-flight cancel path claimed the done flag): the
                # work was never started — skip it entirely
                return
        req.worker_ident = threading.get_ident()
        # local t_start: the watchdog nulls req.t_start on a requeue,
        # and this attempt may be the abandoned original unwinding
        # late — its own wall must not read a field the retry owns
        t_start = time.perf_counter()
        req.t_start = t_start
        with self._lock:
            self._inflight[req.serial] = req
        queue_wait = t_start - req.t_enq
        obs_metrics.observe("serve.queue_wait_s", queue_wait)
        # the admission-to-worker-start wait (batch coalescing window
        # included) as a pre-measured span: the request's first
        # timeline phase (docs/OBSERVABILITY.md §request tracing)
        trace.emit_span("serve/wait/queue", queue_wait,
                        kernel=req.kernel, bucket=req.bucket,
                        batch_size=batch_size,
                        window_ms=self._last_window_ms)
        if (req.deadline_at is not None
                and time.monotonic() >= req.deadline_at):
            # the budget died in the queue/coalescing window — skip
            # the pad/dispatch phases entirely (the wait span above
            # shows where it went) and answer the expiry now
            with self._lock:
                if self._inflight.get(req.serial) is req:
                    self._inflight.pop(req.serial, None)
            self._expire(req, where="worker", queue_wait=queue_wait)
            return
        if req.spec is not None and req.requeues == 0:
            # once per request, not per attempt: a retry would count
            # the same padding waste twice
            obs_metrics.observe("serve.bucket_pad_frac", req.pad_frac)
        cell = None
        try:
            import jax
            import jax.numpy as jnp

            # bucket lock FIRST: the per-bucket pad staging pool can
            # only be reused while this thread owns the bucket (and by
            # the time the lock releases, jnp.asarray + the completed
            # dispatch are done with the staging buffers)
            l0 = time.perf_counter()
            cell = self._acquire_bucket(req.bucket)
            trace.emit_span("serve/wait/lock",
                            time.perf_counter() - l0,
                            bucket=req.bucket)
            if req.spec is not None:
                with self._lock:
                    pool = self._pad_pool.setdefault(req.bucket, {})
                with trace.span("serve/pad", kernel=req.kernel,
                                bucket=req.bucket):
                    args, meta = bucketing.pad_args(
                        req.kernel, req.spec, req.arrays, pool=pool)
                # padding is a genuinely extra staging copy — counted,
                # unlike the one producer-to-consumer payload move
                self._count_copied(req.kernel,
                                   meta.get("copied_bytes") or 0)
            else:
                args, meta = req.arrays, None
            jargs = tuple(jnp.asarray(a) for a in args)
            with trace.span(f"serve/{req.kernel}", bucket=req.bucket):
                if req.mesh_shape is not None:
                    # the over-avatar mesh tier (docs/SERVING.md):
                    # same span/fault/AOT/integrity machinery, the
                    # kernel's distributed twin as the executable
                    out = registry.dispatch_mesh(
                        req.kernel, *jargs,
                        mesh_shape=req.mesh_shape, **req.statics)
                else:
                    out = registry.dispatch(req.kernel, *jargs,
                                            **req.statics)
                jax.block_until_ready(out)
            if self._device_kind is None:
                from tpukernels.tuning import cache as tcache

                self._device_kind = tcache.device_kind()
            outs = tuple(
                np.asarray(o)
                for o in (out if isinstance(out, (tuple, list))
                          else (out,))
            )
            if meta is not None:
                outs = bucketing.unpad_outputs(req.kernel, meta, outs)
        except Exception as e:  # noqa: BLE001 — reported to the client
            if req.claim_done():
                self._finish(req, None, error=repr(e),
                             wall=time.perf_counter() - t_start)
            return
        finally:
            if cell is not None:
                with self._lock:
                    if cell[1] == threading.get_ident():
                        cell[1] = None
                cell[0].release()
            # deregister only THIS attempt: after a watchdog requeue
            # the same request object is re-registered by its retry
            # worker, and an abandoned worker unwinding late must not
            # blind the watchdog to that retry
            with self._lock:
                if (self._inflight.get(req.serial) is req
                        and req.worker_ident == threading.get_ident()):
                    self._inflight.pop(req.serial, None)
        if req.claim_done():
            wall = time.perf_counter() - t_start
            with self._lock:
                self._service_ewma = (0.8 * self._service_ewma
                                      + 0.2 * wall)
            self._finish(req, outs, queue_wait=queue_wait,
                         batch_size=batch_size, wall=wall)
        # else: the watchdog already answered for this request (the
        # wedge finally unwound, or the requeue raced us) — discard

    def _finish(self, req: _Request, outs, error=None,
                queue_wait=None, batch_size=None, wall=None,
                kind="error"):
        if wall is None:
            # watchdog caller (wedged-twice): the retry attempt's own
            # start is still in req.t_start here. _execute passes its
            # attempt-local wall instead — req.t_start may belong to a
            # different attempt by the time a slow original unwinds.
            wall = time.perf_counter() - (req.t_start or req.t_enq)
        payloads = ()
        segs: list = []
        if error is None:
            # an out-of-contract output (a dtype outside the wire's
            # two) must become an error RESPONSE, not an exception
            # that kills the worker thread
            try:
                specs, payloads = protocol.pack_arrays(outs)
            except protocol.ProtocolError as e:
                error = f"unserializable output: {e}"
                payloads = ()
        if error is None:
            with self._lock:
                self._served += 1
            obs_metrics.inc(f"serve.requests.{req.kernel}")
            obs_metrics.observe(f"serve.wall_s.{req.kernel}", wall)
            header = {"v": protocol.VERSION, "id": req.rid, "ok": True,
                      "outputs": specs}
            if req.shm_ok and self._shm:
                # response lane: big outputs land in segments the
                # client maps-and-unlinks; only names ride the wire.
                # An exhausted /dev/shm degrades to inline, never to
                # a failed response.
                try:
                    shm_descs, payloads2, segs, _staged = (
                        protocol.stage_shm_payloads(payloads,
                                                    self._shm_min)
                    )
                except OSError:
                    shm_descs = None
                if shm_descs is not None:
                    header["_shm"] = shm_descs
                    payloads = payloads2
        else:
            obs_metrics.inc("serve.errors")
            header = {"v": protocol.VERSION, "id": req.rid, "ok": False,
                      "kind": kind, "error": error}
            payloads = ()
        if req.request_id is not None:
            obs_metrics.inc("serve.requests_traced")
        journal.emit(
            "serve_request", kernel=req.kernel, request=req.rid,
            request_id=req.request_id,
            worker_id=os.environ.get("TPK_SERVE_WORKER_ID"),
            tenant=req.tenant,
            bucket=req.bucket, pad_frac=round(req.pad_frac, 6),
            bucketed=req.spec is not None,
            # non-None iff the request ran on the mesh tier — the
            # capacity-planning signal (how much traffic outgrows the
            # single-device table) rides the same shape-mix record
            mesh_shape=(list(req.mesh_shape)
                        if req.mesh_shape is not None else None),
            # the per-request shape-mix record (requested, PRE-pad
            # shapes/dtypes): the exact input ROADMAP item 5's
            # bucket-table optimizer mines, aggregated by
            # obs_report's shapes-seen table
            shapes=req.shapes, dtypes=req.dtypes,
            wall_s=round(wall, 6),
            queue_wait_s=round(queue_wait, 6)
            if queue_wait is not None else None,
            batch_size=batch_size, requeues=req.requeues,
            replayed=req.replayed,
            ok=error is None, error=error,
        )
        # delay_response fault point (docs/RESILIENCE.md): holds THIS
        # completed response on the floor for N s — the deterministic
        # slow-but-alive worker the hedged-dispatch chaos proof pins
        faults.response_fault(req.kernel)
        try:
            sent = req.conn.send(header, payloads)
        except (OSError, protocol.ProtocolError):
            # client gone/stalled; the work is journaled anyway — and
            # response segments no one will ever map are unlinked NOW
            for seg in segs:
                seg.close()
                seg.unlink()
        else:
            self._count_copied(req.kernel, sent)
            if segs:
                now = time.perf_counter()
                with self._lock:
                    self._shm_ledger.extend(
                        (seg.name, now) for seg in segs
                    )
                for seg in segs:
                    seg.close()  # the client unlinks on map; the aged
                    #              ledger is the crash backstop
                if not req.conn.lane_logged:
                    req.conn.lane_logged = True
                    journal.emit("serve_lane_negotiated", lane="shm",
                                 kernel=req.kernel, request=req.rid)

    # -------------------------------------------------------------- #
    # watchdog: abandon wedged workers, requeue once                 #
    # -------------------------------------------------------------- #

    def _probe_alive(self, timeout_s: float = 2.0) -> bool:
        """Backend liveness from a side thread (SIGALRM is main-thread
        only): a trivial device op either completes inside the window
        (SLOW — the backend answers, one request/worker is stuck) or
        does not (WEDGED — the backend itself is gone)."""
        result = []

        def _probe():
            try:
                import jax
                import jax.numpy as jnp

                jax.block_until_ready(jnp.zeros((2,)) + 1)
                result.append(True)
            except Exception:  # noqa: BLE001 — a dead backend IS the answer
                pass

        t = threading.Thread(target=_probe, daemon=True,
                             name="serve-probe")
        t.start()
        t.join(timeout_s)
        return bool(result)

    def _watchdog_loop(self):
        period = min(1.0, max(0.1, self.request_timeout_s / 4))
        grace = self.request_timeout_s * 1.5
        while not self._stop.is_set():
            time.sleep(period)
            now = time.perf_counter()
            with self._lock:
                overdue = [
                    r for r in self._inflight.values()
                    if r.t_start is not None
                    and now - r.t_start > grace * (1 + r.patience)
                ]
                expired = [
                    n for n, t in self._shm_ledger
                    if now - t > SHM_RESPONSE_GRACE_S
                ]
                if expired:
                    self._shm_ledger = [
                        (n, t) for n, t in self._shm_ledger
                        if now - t <= SHM_RESPONSE_GRACE_S
                    ]
            for name in expired:
                # normally ENOENT (the client mapped and unlinked);
                # a real unlink here is the crashed-client backstop
                protocol.unlink_shm(name)
            for req in overdue:
                self._handle_wedge(req)

    def _handle_wedge(self, req: _Request):
        with self._lock:
            if self._inflight.get(req.serial) is not req:
                return
        # classify BEFORE abandoning: a live backend means this may be
        # a legitimately slow attempt — a cold record-shape compile can
        # outlast any fixed grace — and abandoning it would replace the
        # bucket lock under a live compile, putting a second compile of
        # the same bucket in flight (the executable memo is unlocked).
        # One doubled grace beats that; an attempt still overdue at 2x
        # grace is treated as wedged regardless of the probe.
        verdict = watchdog.classify_timeout(
            self._probe_alive(), site="serve", kernel=req.kernel,
            request=req.rid,
        )
        if verdict == "slow" and req.patience == 0:
            req.patience = 1
            print(f"# serve: {req.kernel} request {req.rid} overdue "
                  f"(> {self.request_timeout_s * 1.5:.1f}s) but the "
                  "backend answers - extending grace once",
                  file=sys.stderr)
            return
        with self._lock:
            still = self._inflight.pop(req.serial, None)
        if still is None:
            return  # the attempt completed during the probe
        obs_metrics.inc("watchdog.kills")
        journal.emit(
            "watchdog_fire", mechanism="serve-abandon", site="serve",
            timeout_s=self.request_timeout_s, kernel=req.kernel,
            request=req.rid,
        )
        if req.worker_ident is not None:
            with self._lock:
                self._abandoned.add(req.worker_ident)
                # rescue batch members coalesced behind the wedge:
                # they were never started (not in _inflight) and the
                # abandoned thread will never reach its hand-back —
                # drain under the lock so a late-unwinding worker
                # cannot pop a request we are about to requeue
                pend = self._worker_pending.pop(req.worker_ident, None)
                stranded = list(pend) if pend else []
                if pend:
                    pend.clear()
            self._spawn_worker()
            for rest in stranded:
                # forced: already admitted, must not bounce off
                # backpressure on the rescue
                self._q.put_nowait(rest, force=True)
        if req.requeues < 1:
            req.requeues += 1
            req.t_start = None
            req.worker_ident = None
            # the retry's queue wait measures ITS queueing, not the
            # failed attempt it replaces (~grace worth of wedge time
            # would dominate the serve.queue_wait_s tail otherwise)
            req.t_enq = time.perf_counter()
            with self._lock:
                self._requeued += 1
            obs_metrics.inc("serve.requeued")
            journal.emit(
                "serve_request_requeued", kernel=req.kernel,
                request=req.rid, request_id=req.request_id,
                bucket=req.bucket,
                timeout_s=self.request_timeout_s,
            )
            # forced: a request the service already accepted must not
            # bounce off its own backpressure on the retry
            self._q.put_nowait(req, force=True)
        elif req.claim_done():
            # structured kind: the fleet router keys failover on it —
            # a worker that wedged twice should not be fed this
            # bucket again until it cools (docs/SERVING.md §fleet)
            self._finish(
                req, None, kind="wedged",
                error=(f"request wedged twice (> "
                       f"{self.request_timeout_s}s each attempt)"),
            )


# ------------------------------------------------------------------ #
# CLI entry (python -m tpukernels.serve)                             #
# ------------------------------------------------------------------ #

def _hold_pidfile(path: str):
    """Write-and-flock the daemon pidfile for the process lifetime —
    the revalidate_lib.sh watcher-lock convention: liveness is the
    flock, the recorded pid is the diagnosis. Returns the held fd
    (kept open) or raises RuntimeError when another daemon holds it."""
    import fcntl

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    # a+ so a losing contender can never truncate the holder's pid
    f = open(path, "a+")
    # a few NB retries: serve_ctl's liveness probe takes the flock for
    # a flash — a status check racing our startup must not read as
    # "another daemon" and abort us
    for attempt in range(5):
        try:
            fcntl.flock(f, fcntl.LOCK_EX | fcntl.LOCK_NB)
            break
        except OSError:
            if attempt < 4:
                time.sleep(0.1)
                continue
            f.seek(0)
            pid = f.readline().strip()
            f.close()
            raise RuntimeError(
                f"another serve daemon holds {path}"
                + (f" (pid {pid})" if pid else "")
            ) from None
    f.seek(0)
    f.truncate()
    f.write(f"{os.getpid()}\n")
    f.flush()
    return f


def main(argv=None):
    import signal

    argv = sys.argv[1:] if argv is None else list(argv)
    socket_path = queue_max = workers = None
    batch_window_ms = request_timeout_s = None
    it = iter(argv)
    try:
        for a in it:
            if a == "--socket":
                socket_path = next(it)
            elif a == "--queue-max":
                queue_max = int(next(it))
            elif a == "--workers":
                workers = int(next(it))
            elif a == "--batch-window-ms":
                batch_window_ms = float(next(it))
            elif a == "--request-timeout-s":
                request_timeout_s = float(next(it))
            elif a in ("-h", "--help"):
                print(__doc__, file=sys.stderr)
                return 0
            else:
                print(__doc__, file=sys.stderr)
                print(f"serve: unknown argument {a!r}", file=sys.stderr)
                return 2
    except (StopIteration, ValueError):
        print(f"serve: {a} needs a value", file=sys.stderr)
        return 2

    # CLI journal default (the bench.py/loadgen.py contract): an
    # unattended daemon's evidence must land in the day's journal
    if os.environ.get("TPK_HEALTH_JOURNAL") is None:
        os.environ["TPK_HEALTH_JOURNAL"] = journal.default_path()
    # sampled oracle canaries are multi-ms outliers in exactly the
    # request tail this daemon is judged on (the loadgen rationale);
    # the always-on tripwire stays, and an explicit env choice wins
    os.environ.setdefault("TPK_INTEGRITY", "tripwire")

    try:
        server = Server(socket_path, queue_max, workers,
                        batch_window_ms, request_timeout_s)
    except (ValueError, OSError) as e:
        # OSError: an unreadable TPK_SERVE_BUCKETS file path
        print(f"serve: {e}", file=sys.stderr)
        return 2
    try:
        pidfile = _hold_pidfile(_cachedir.serve_pidfile_path())
    except RuntimeError as e:
        print(f"serve: {e}", file=sys.stderr)
        return 3

    from tpukernels.obs import scaling as obs_scaling

    obs_scaling.emit_inventory("serve")
    signal.signal(signal.SIGTERM, server.stop)
    signal.signal(signal.SIGINT, server.stop)
    print(f"# serve: listening on {server.socket_path} "
          f"(pid {os.getpid()}, workers {server.workers}, "
          f"queue max {server.queue_max})", file=sys.stderr)
    try:
        server.serve_forever()
    except OSError as e:
        print(f"serve: cannot serve on {server.socket_path}: {e}",
              file=sys.stderr)
        return 1
    finally:
        try:
            pidfile.close()
            os.unlink(_cachedir.serve_pidfile_path())
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
