"""Bounded fsync'd write-ahead log for the fleet router's accepted
requests (docs/SERVING.md §guardian; docs/RESILIENCE.md §failure
domains).

The router is the fleet's admission point: once it answers (or will
answer) "accepted", the request must survive the router's own death.
This JSONL log (``router.wal`` beside ``fleet.json``) records one
``req`` line per accepted dispatch — appended and ``fsync``'d BEFORE
the forward, so a SIGKILL at any later instant leaves a durable
descriptor — and one ``ack`` line when the request reaches ANY
terminal reply (success, shed, relayed error: the client got an
answer, nothing left to replay). A respawned router replays the
unacknowledged entries once through the ``replay`` idempotency header
(protocol.py; kernels are pure, request_ids are preserved, consumers
dedupe by id).

Bounded, O(inflight): appends and acks grow the file, but every
``COMPACT_SLACK``-or-``4 x pending`` operations it is rewritten
crash-consistently (``resilience/atomic.py``) to hold only the
still-pending entries — steady-state size tracks the in-flight window,
not traffic volume. A torn LAST line (the crash landed mid-append,
before the fsync returned) is normal crash residue, skipped on read:
that request was never durably accepted, and the client's reconnect
budget owns its retry. Torn MIDDLE lines cannot happen — every append
is fsync'd before the next starts.

Single-writer by design (the router process; ``threading.Lock``
serializes its client threads). Stdlib-only, like the rest of the
serve package's server side.
"""

from __future__ import annotations

import json
import os
import sys
import threading

# compaction cadence: rewrite once the op count since the last
# compaction exceeds max(this, 4 x pending) — rare enough to amortize,
# tight enough that the file stays O(inflight)
COMPACT_SLACK = 64


def read_pending(path: str) -> dict:
    """Unacknowledged entries of a (possibly crash-torn) WAL, in
    append order: ``{key: entry}``. Tolerant of a torn tail line
    (normal crash residue — see module docstring); missing file reads
    as empty. Usable without a :class:`Wal` instance (fsck, tests)."""
    pending: dict = {}
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except OSError:
        return pending
    for line in raw.split(b"\n"):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue  # torn tail: never durably accepted
        if not isinstance(rec, dict):
            continue
        key = rec.get("key")
        if rec.get("op") == "req" and key is not None:
            pending[key] = rec.get("e")
        elif rec.get("op") == "ack":
            pending.pop(key, None)
    return pending


class Wal:
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # recover-then-append: entries pending at open time are the
        # previous incarnation's replay debt — the router drains them
        # via take_pending() before serving
        self._pending = read_pending(path)
        self._f = open(path, "ab")
        self._ops = 0

    def append(self, key: str, entry: dict):
        with self._lock:
            self._write({"op": "req", "key": key, "e": entry})
            self._pending[key] = entry
            self._maybe_compact()

    def ack(self, key: str):
        with self._lock:
            if key not in self._pending:
                return
            del self._pending[key]
            self._write({"op": "ack", "key": key})
            self._maybe_compact()

    def take_pending(self) -> dict:
        """Snapshot of the pending entries (append order) for replay.
        Entries stay pending until individually ack'd — a second crash
        mid-replay re-replays the remainder."""
        with self._lock:
            return dict(self._pending)

    def depth(self) -> int:
        with self._lock:
            return len(self._pending)

    def close(self):
        """Close the handle; a WAL with nothing pending is removed —
        a clean shutdown leaves no file to mistake for replay debt."""
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass
            if not self._pending:
                try:
                    os.unlink(self.path)
                except OSError:
                    pass

    # ------------------------------------------------------------ #
    # internals (call under self._lock)                            #
    # ------------------------------------------------------------ #

    def _write(self, rec: dict):
        line = (json.dumps(rec, sort_keys=True) + "\n").encode("utf-8")
        try:
            self._f.write(line)
            self._f.flush()
            os.fsync(self._f.fileno())
        except (OSError, ValueError) as e:  # ValueError: closed file

            # a WAL that cannot persist must not take down serving —
            # it degrades (loudly) to the client-retry-only story
            print(f"# wal: append failed on {self.path}: {e}",
                  file=sys.stderr)

    def _maybe_compact(self):
        self._ops += 1
        if self._ops < max(COMPACT_SLACK, 4 * len(self._pending)):
            return
        from tpukernels.resilience import atomic

        text = "".join(
            json.dumps({"op": "req", "key": k, "e": e}, sort_keys=True)
            + "\n"
            for k, e in self._pending.items()
        )
        try:
            self._f.close()
            atomic.write_text(self.path, text)
        except OSError as e:
            print(f"# wal: compaction failed on {self.path}: {e}",
                  file=sys.stderr)
        finally:
            self._f = open(self.path, "ab")
        self._ops = 0
