"""``python -m tpukernels.serve`` — run the kernel-serving daemon
(tpukernels/serve/server.py; docs/SERVING.md)."""

import sys

from tpukernels.serve.server import main

if __name__ == "__main__":
    sys.exit(main())
