"""Fleet layout + process lifecycle for the sharded serving fleet
(docs/SERVING.md §fleet).

One fleet = one front-end router (``tpukernels/serve/router.py``) +
N worker daemons (each a plain ``python -m tpukernels.serve`` on its
own socket, pidfile and log). This module owns where all of that
lives on disk and how the processes are spawned — ``tools/
serve_ctl.py``'s fleet verbs (``start-fleet``/``stop-fleet``/
``drain``/``undrain``/``status``) are thin over it.

Layout (under ``fleet_dir()``, default ``<serve_dir>/fleet``;
``TPK_SERVE_FLEET_DIR`` redirects — tests isolate it via the
already-isolated ``TPK_SERVE_DIR``):

    fleet.json          # config of record: front socket + workers
    front.sock          # the router's socket — point clients here
    router.pid          # router's flocked pidfile (revalidate_lib
                        # convention, like the worker daemons')
    router.log          # router stderr
    worker0/            # worker 0's TPK_SERVE_DIR: socket, pidfile,
    worker1/            # daemon log — the PR-10 single-daemon layout,
    ...                 # one instance per worker

Each worker is spawned with ``TPK_SERVE_WORKER_ID=<i>`` in its
environment — the hook ``TPK_FAULT_PLAN`` ``env`` clauses use to
fault ONE worker of a fleet (the wedged-worker failover chaos proof)
and the tag its daemon log lines carry. ``TPK_SERVE_SOCKET`` is
scrubbed from worker/router children: it is the CLIENT routing
switch, and a fleet member resolving its own socket through it would
dispatch into itself (or worse, into a different fleet).

Stdlib-only at import, like the rest of the serve package's server
side.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from tpukernels import _cachedir


def fleet_dir(env=None) -> str:
    """``TPK_SERVE_FLEET_DIR`` when set, else ``fleet/`` under the
    serve dir (same read-the-env-per-call rule as every _cachedir
    path)."""
    target = os.environ if env is None else env
    d = target.get("TPK_SERVE_FLEET_DIR")
    if d:
        return d
    return os.path.join(_cachedir.serve_dir(env), "fleet")


def config_path(env=None) -> str:
    return os.path.join(fleet_dir(env), "fleet.json")


def front_socket_path(env=None) -> str:
    return os.path.join(fleet_dir(env), "front.sock")


def router_pidfile_path(env=None) -> str:
    return os.path.join(fleet_dir(env), "router.pid")


def guardian_pidfile_path(env=None) -> str:
    return os.path.join(fleet_dir(env), "guardian.pid")


def wal_path(env=None) -> str:
    """The router's durable-admission journal (``serve/wal.py``) —
    beside fleet.json so a respawned router finds its predecessor's
    replay debt."""
    return os.path.join(fleet_dir(env), "router.wal")


def worker_dir(i: int, env=None) -> str:
    return os.path.join(fleet_dir(env), f"worker{i}")


def worker_socket_path(i: int, env=None) -> str:
    return os.path.join(worker_dir(i, env), "serve.sock")


def load_config():
    """The fleet.json config of record, or None when no fleet was
    started here. Tolerant read: a corrupt file reads as no fleet
    (start-fleet rewrites it, ``serve_ctl fsck`` reaps it) — but
    LOUDLY: the config of record tearing is journaled, not a silent
    "no fleet" (docs/RESILIENCE.md §atomic state)."""
    try:
        with open(config_path()) as f:
            cfg = json.load(f)
    except OSError:
        return None
    except ValueError as e:
        _cachedir.note_torn_artifact(config_path(), str(e))
        return None
    if not isinstance(cfg, dict) or not cfg.get("workers"):
        return None
    return cfg


def write_config(front: str, workers) -> dict:
    from tpukernels.resilience import atomic

    cfg = {
        "front": front,
        "workers": list(workers),
        "written": round(time.time(), 3),
        "pid": os.getpid(),
    }
    d = fleet_dir()
    os.makedirs(d, exist_ok=True)
    # fsync'd tmp+rename: the config of record is what a respawned
    # router/guardian rebuilds the fleet view from — it must read as
    # old-or-new across any crash (docs/RESILIENCE.md §atomic state)
    atomic.dump_json(config_path(), cfg)
    return cfg


def _child_env(extra=None) -> dict:
    """A fleet child's environment: the operator's env minus the
    client routing switch (module docstring), plus overrides."""
    env = dict(os.environ)
    env.pop("TPK_SERVE_SOCKET", None)
    env.update(extra or {})
    return env


def spawn_worker(i: int, repo: str, d=None):
    """Spawn worker ``i`` detached (own session, stderr appended to
    its daemon log), on its own socket/dir. Returns (proc,
    socket_path). ``d`` overrides the worker dir — the fleet health
    manager respawns a dead worker at the EXACT dir/socket the router
    already points at (docs/SERVING.md §self-healing), not wherever
    the current env would resolve ``worker_dir(i)``."""
    if d is None:
        d = worker_dir(i)
    os.makedirs(d, exist_ok=True)
    sock = os.path.join(d, "serve.sock")
    log = open(os.path.join(d, "serve_daemon.log"), "a")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpukernels.serve",
             "--socket", sock],
            cwd=repo, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=log,
            env=_child_env({"TPK_SERVE_DIR": d,
                            "TPK_SERVE_WORKER_ID": str(i)}),
        )
    finally:
        log.close()
    return proc, sock


def spawn_guardian(repo: str):
    """Spawn the router's guardian detached (docs/SERVING.md
    §guardian): it supervises the router pidfile flock and respawns a
    crashed router on the original front socket. Returns the Popen."""
    d = fleet_dir()
    os.makedirs(d, exist_ok=True)
    log = open(os.path.join(d, "guardian.log"), "a")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-m", "tpukernels.serve.guardian"],
            cwd=repo, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=log,
            env=_child_env(),
        )
    finally:
        log.close()
    return proc


def spawn_router(front: str, worker_sockets, repo: str):
    """Spawn the router detached on the front socket over the given
    worker sockets. Returns the Popen."""
    d = fleet_dir()
    os.makedirs(d, exist_ok=True)
    log = open(os.path.join(d, "router.log"), "a")
    argv = [sys.executable, "-m", "tpukernels.serve.router",
            "--socket", front]
    for w in worker_sockets:
        argv += ["--worker", w]
    try:
        proc = subprocess.Popen(
            argv, cwd=repo, start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=log,
            env=_child_env(),
        )
    finally:
        log.close()
    return proc
