"""tpukernels.serve — the persistent multi-client kernel service
(docs/SERVING.md; ROADMAP item 1).

The batch suite's serving half: a Unix-domain-socket daemon
(``server.py``) that dispatches every request through
``registry.dispatch`` — the compiled-executable memo, fault point and
integrity guard the batch paths already trust — plus the wire
protocol (``protocol.py``), shape bucketing onto the AOT avatars
(``bucketing.py``), the jax-free client (``client.py``) that
``capi.run_from_c`` and ``tools/loadgen.py --serve`` use, and the
scale-out fleet (``router.py``/``fleet.py``, §fleet): a front-end
router that consistently hashes each (kernel, bucket) onto one of N
worker daemons with deterministic spill, live drain and per-tenant
token-bucket fairness.

Stdlib + numpy at import time; jax loads inside the daemon's dispatch
path only.
"""

from tpukernels.serve.client import (  # noqa: F401
    ServeClient,
    ServeError,
    ServeRejected,
    default_socket_path,
)
