"""Traffic-adaptive bucket optimizer: serve telemetry in, promoted
config out (docs/SERVING.md §adaptive buckets; ROADMAP item 5).

The serving fleet pads live traffic up onto a hand-picked avatar
table (``tpukernels/serve/bucketing.py``), and every request already
leaves the evidence an optimizer needs: the ``serve_request`` journal
record carries the requested (pre-pad) shapes/dtypes, the chosen
bucket and the wasted-element ``pad_frac``, and the daemon's
``serve.bucket_pad_frac`` histogram aggregates the same waste. This
module CLOSES that loop:

- :func:`shape_mix` mines the journal's ``serve_request`` shape-mix
  records into per-kernel (shapes, dtypes, count) groups, and
  :func:`histogram_pad_frac` reads the live ``serve.bucket_pad_frac``
  aggregate off ``metrics`` events.
- :func:`propose` turns a mix + the incumbent table into ranked
  bucket SPLITs (add an avatar at a hot observed shape) and MERGEs
  (drop an avatar no traffic touches), under an explicit projected
  cost model: each new bucket costs one compile + one
  executable-memo slot, each merge pays the pad_frac its traffic
  re-absorbs — so proposals are ranked by waste-saved-per-compile
  and applied greedily until the projected pad waste falls below
  ``TPK_ADAPT_PAD_TARGET``.
- :func:`record_candidate` / :func:`load` persist the winner as a
  ``TPK_SERVE_BUCKETS`` candidate artifact (``adapt.json``) —
  atomic-written and validated at read against the jax version and
  the serve-source shas exactly like tuning.json/aot.json/slo.json;
  a stale or torn candidate is LOUDLY rejected (stderr +
  ``adapt_rejected`` journal event), never silently canaried.
- :func:`judge_canary` is the promotion gate: a candidate table is
  promoted only on a measured pad_frac win of more than the tuning
  layer's ``PROMOTE_MARGIN`` (>3% over control — PR 2's promotion
  discipline lifted to serving config) AND a p99 win at identical
  replay seeds. ``tools/serve_optimize.py`` drives the end-to-end
  canary; ``tools/revalidate.py`` owns the off-window scheduling.
- :func:`traffic_order` ranks kernels by live request frequency so
  ``tools/prewarm.py --order traffic`` warms what traffic actually
  hits first, not whatever sorts first in the registry.

Stdlib-only at import time (the ``tpukernels.obs`` contract): the
proposal math is pure arithmetic over shape tuples, unit-testable
without jax, numpy or a daemon.
"""

from __future__ import annotations

import os
import sys
import time

from tpukernels import _cachedir
from tpukernels.resilience import journal

DEFAULT_PAD_TARGET = 0.25
DEFAULT_MIN_REQUESTS = 50
# split proposals applied per candidate table, at most: each one is a
# compile + an executable-memo slot, and a table that shadows every
# observed shape is a memo leak wearing an optimizer's hat
MAX_SPLITS = 4

# sources whose newer commit invalidates a persisted candidate: the
# pad math that projected it, this module's own proposal model, and
# the avatar registry the table overrides
SOURCES = (
    "tpukernels/serve/adapt.py",
    "tpukernels/serve/bucketing.py",
    "tpukernels/aot.py",
)

_DTYPE_KINDS = {"float32": "f32", "int32": "i32"}

_REJECT_NOTED: set = set()


def reset():
    """Drop per-process state (tests)."""
    _REJECT_NOTED.clear()


# ------------------------------------------------------------------ #
# knobs (fail-loud parse — the TPK_* contract)                       #
# ------------------------------------------------------------------ #

def pad_target() -> float:
    """``TPK_ADAPT_PAD_TARGET`` (default 0.25): the projected mean
    pad_frac a proposal must drive the observed mix below. Fail-loud
    parse, in (0, 1]."""
    raw = os.environ.get("TPK_ADAPT_PAD_TARGET")
    if raw is None:
        return DEFAULT_PAD_TARGET
    try:
        val = float(raw)
    except ValueError:
        val = -1.0
    if not 0.0 < val <= 1.0:
        raise ValueError(
            f"TPK_ADAPT_PAD_TARGET={raw!r}: expected a float in (0, 1]"
        )
    return val


def min_requests() -> int:
    """``TPK_ADAPT_MIN_REQUESTS`` (default 50): journal requests below
    which no proposal is made — a bucket table re-shaped around an
    anecdote would thrash on every traffic blip. Fail-loud parse,
    >= 1."""
    raw = os.environ.get("TPK_ADAPT_MIN_REQUESTS")
    if raw is None:
        return DEFAULT_MIN_REQUESTS
    try:
        val = int(raw)
    except ValueError:
        val = 0
    if val < 1:
        raise ValueError(
            f"TPK_ADAPT_MIN_REQUESTS={raw!r}: expected an int >= 1"
        )
    return val


def window_days() -> int:
    """``TPK_ADAPT_WINDOW_DAYS`` (default 1): how many days of
    evidence the miners see — 1 is today's live journal only (the
    PR 16 behavior); N > 1 widens the mix with the prior N-1 days'
    rollup artifacts (``tpukernels/obs/rollup.py``), so a quiet
    morning still proposes off a week of real traffic. Fail-loud
    parse, >= 1."""
    raw = os.environ.get("TPK_ADAPT_WINDOW_DAYS")
    if raw is None:
        return 1
    try:
        val = int(raw)
    except ValueError:
        val = 0
    if val < 1:
        raise ValueError(
            f"TPK_ADAPT_WINDOW_DAYS={raw!r}: expected an int >= 1"
        )
    return val


def promote_margin() -> float:
    """The >3%-over-control promotion margin — borrowed from the
    tuning layer (one authority; docs/TUNING.md) so the serving-config
    gate cannot drift from the kernel-params gate."""
    from tpukernels.tuning import runner

    return runner.PROMOTE_MARGIN


def path() -> str:
    return _cachedir.adapt_path()


def buckets_path() -> str:
    return _cachedir.adapt_buckets_path()


# ------------------------------------------------------------------ #
# journal mining                                                     #
# ------------------------------------------------------------------ #

def shape_mix(events) -> dict:
    """Aggregate ``serve_request`` events into the optimizer's input:
    ``{kernel: [{"shapes", "dtypes", "count", "pad_frac_sum",
    "bucketed"}, ...]}`` with one row per distinct requested
    (pre-pad) shape tuple, counts over OK requests only — a request
    the daemon failed tells us nothing about what padding it paid."""
    groups: dict = {}
    for e in events:
        if e.get("kind") != "serve_request" or not e.get("ok"):
            continue
        kernel, shapes, dtypes = (
            e.get("kernel"), e.get("shapes"), e.get("dtypes"),
        )
        if not kernel or not isinstance(shapes, list) \
                or not isinstance(dtypes, list):
            continue
        key = (
            kernel,
            tuple(tuple(int(d) for d in s) for s in shapes),
            tuple(dtypes),
        )
        row = groups.get(key)
        if row is None:
            row = groups[key] = {
                "kernel": kernel,
                "shapes": [tuple(int(d) for d in s) for s in shapes],
                "dtypes": list(dtypes),
                "count": 0,
                "pad_frac_sum": 0.0,
                "bucketed": 0,
            }
        row["count"] += 1
        row["pad_frac_sum"] += float(e.get("pad_frac") or 0.0)
        row["bucketed"] += bool(e.get("bucketed"))
    out: dict = {}
    for row in groups.values():
        out.setdefault(row["kernel"], []).append(row)
    for rows in out.values():
        rows.sort(key=lambda r: (-r["count"], r["shapes"]))
    return out


def mix_requests(mix: dict) -> int:
    return sum(r["count"] for rows in mix.values() for r in rows)


def merge_mix(mixes) -> dict:
    """Combine :func:`shape_mix` outputs (today's live journal, prior
    days' rollups) into one mix: rows merge by (kernel, shapes,
    dtypes) with count/pad_frac_sum/bucketed summed — the sums are
    exactly what re-mining the concatenated events would yield, so
    the proposal math cannot tell a window from a single day."""
    groups: dict = {}
    for mix in mixes:
        for kernel, rows in (mix or {}).items():
            for r in rows:
                try:
                    shapes = [
                        tuple(int(d) for d in s) for s in r["shapes"]
                    ]
                    dtypes = list(r["dtypes"])
                except (KeyError, TypeError, ValueError):
                    continue
                key = (kernel, tuple(shapes), tuple(dtypes))
                row = groups.get(key)
                if row is None:
                    row = groups[key] = {
                        "kernel": kernel,
                        "shapes": shapes,
                        "dtypes": dtypes,
                        "count": 0,
                        "pad_frac_sum": 0.0,
                        "bucketed": 0,
                    }
                row["count"] += int(r.get("count") or 0)
                row["pad_frac_sum"] += float(r.get("pad_frac_sum")
                                             or 0.0)
                row["bucketed"] += int(r.get("bucketed") or 0)
    out: dict = {}
    for row in groups.values():
        out.setdefault(row["kernel"], []).append(row)
    for rows in out.values():
        rows.sort(key=lambda r: (-r["count"], r["shapes"]))
    return out


def window_mix(events, days: int | None = None,
               end_date: str | None = None):
    """The miner's multi-day entry point (ROADMAP item 5's remaining
    headroom): today's mix from ``events`` (the live journal) widened
    with the prior ``days - 1`` days' validated rollup mixes. Returns
    ``(mix, days_used)`` where ``days_used`` counts the rollup days
    actually folded in (+1 for today). A rollup dated ``end_date``
    (default: today) is SKIPPED — today's evidence comes from the
    live journal, and folding today's own rollup in would count every
    request twice."""
    if days is None:
        days = window_days()
    today_mix = shape_mix(events)
    if days <= 1:
        return today_mix, 1
    from tpukernels.obs import rollup  # lazy: stdlib-only contract

    if end_date is None:
        end_date = time.strftime("%Y-%m-%d")
    prior = [
        (date, art)
        for date, art in rollup.load_series()
        if date < end_date
    ][-(days - 1):]
    mixes = [today_mix] + [
        art.get("shape_mix") or {} for _, art in prior
    ]
    return merge_mix(mixes), 1 + len(prior)


def histogram_pad_frac(events):
    """Mean live pad_frac (sum/count) of the ``serve.bucket_pad_frac``
    histogram, or None — the daemon-side aggregate twin of the
    per-request evidence. Reconstructed per pid by
    ``metrics.merge_journal_metrics`` (snapshots deduped by (pid,
    seq), a final ``metrics`` event authoritative — never summed with
    its own snapshots), then pooled across pids: sum-of-sums over
    sum-of-counts, each process's traffic weighted by its count."""
    from tpukernels.obs import metrics as obs_metrics

    total = 0.0
    count = 0
    for state in obs_metrics.merge_journal_metrics(events).values():
        row = state["histograms"].get("serve.bucket_pad_frac")
        if isinstance(row, dict) and row.get("count"):
            total += float(row["sum"])
            count += int(row["count"])
    if not count:
        return None
    return total / count


def traffic_order(events, known) -> tuple:
    """(ordered_kernels, counts) — ``known`` re-ranked by journal
    ``serve_request`` frequency (descending, ties by name); kernels
    with no observed traffic keep their registry order at the tail.
    ``counts`` is empty when the journal holds no traffic evidence —
    the caller's cue to say so and fall back."""
    counts: dict = {}
    for e in events:
        if e.get("kind") == "serve_request":
            k = e.get("kernel")
            if k in known:
                counts[k] = counts.get(k, 0) + 1
    if not counts:
        return list(known), {}
    hot = sorted(counts, key=lambda k: (-counts[k], k))
    return hot + [k for k in known if k not in counts], counts


# ------------------------------------------------------------------ #
# pure projection math                                               #
# ------------------------------------------------------------------ #

def _elems(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def _spec_shapes(spec):
    """[(kind, shape_tuple), ...] for one avatar spec (tolerates JSON
    lists where BENCH_CONFIGS has tuples)."""
    return [
        (kind, tuple(int(d) for d in shape))
        for kind, shape in spec["args"]
    ]


def pad_frac_for(shapes, dtypes, spec):
    """Projected pad_frac of one request group under one avatar spec,
    or None when it cannot bucket there (rank/dtype mismatch, any dim
    over the avatar — the pad-up-never-down rule). Mirrors
    ``bucketing.bucket_for``'s wasted-element arithmetic exactly:
    1 - sum(orig_elems) / sum(avatar_elems)."""
    want = _spec_shapes(spec)
    if len(want) != len(shapes):
        return None
    orig = padded = 0
    for shape, dtype, (kind, avatar) in zip(shapes, dtypes, want):
        if _DTYPE_KINDS.get(dtype, dtype) != kind:
            return None
        shape = tuple(int(d) for d in shape)
        if len(shape) != len(avatar):
            return None
        if any(d > w for d, w in zip(shape, avatar)):
            return None
        orig += _elems(shape) if shape else 1
        padded += _elems(avatar) if avatar else 1
    return 1.0 - (orig / padded if padded else 1.0)


def _kernel_specs(table, kernel):
    """Normalized avatar list for one kernel — a table value may be a
    single spec dict (the historical shape) or a list of them (what a
    split produces)."""
    spec = table.get(kernel)
    if spec is None:
        return []
    return list(spec) if isinstance(spec, list) else [spec]


def project(table: dict, mix: dict, max_pad: float = 0.5) -> dict:
    """Projected fate of an observed mix under a candidate table:
    every request group lands on its cheapest fitting avatar (the
    ``bucket_for`` choice rule) or stays native (no fit, or pad over
    ``max_pad`` — the ``TPK_SERVE_MAX_PAD_FRAC`` cap). Returns
    ``{"pad_frac", "bucketed", "native", "buckets"}`` where
    ``pad_frac`` is the request-weighted mean over BUCKETED traffic
    and ``buckets`` counts the distinct (kernel, avatar) programs the
    mix would occupy — the executable-memo-slot side of the cost
    model."""
    pad_sum = 0.0
    bucketed = native = 0
    used: set = set()
    for kernel, rows in mix.items():
        specs = _kernel_specs(table, kernel)
        for row in rows:
            best = best_i = None
            for i, spec in enumerate(specs):
                pf = pad_frac_for(row["shapes"], row["dtypes"], spec)
                if pf is None or pf > max_pad:
                    continue
                if best is None or pf < best:
                    best, best_i = pf, i
            if best is None:
                native += row["count"]
            else:
                bucketed += row["count"]
                pad_sum += best * row["count"]
                used.add((kernel, best_i))
    return {
        "pad_frac": (pad_sum / bucketed) if bucketed else 0.0,
        "bucketed": bucketed,
        "native": native,
        "buckets": len(used),
    }


# ------------------------------------------------------------------ #
# proposals: splits and merges under the compile-cost model          #
# ------------------------------------------------------------------ #

def _split_candidates(table, mix, max_pad):
    """One SPLIT candidate per observed shape group that pays padding
    today: a new avatar exactly at the group's requested shapes.
    ``waste_saved`` is the projected drop in total wasted elements per
    replay of the mix (the group lands exact, and any sibling group
    that fits the new avatar cheaper re-homes too); each split costs
    exactly one compile + one executable-memo slot."""
    out = []
    for kernel, rows in mix.items():
        specs = _kernel_specs(table, kernel)
        if not specs:
            continue  # never invent avatars for kernels without one
        statics = dict(specs[0].get("statics") or {})
        for row in rows:
            fits = [
                pf for spec in specs
                if (pf := pad_frac_for(row["shapes"], row["dtypes"],
                                       spec)) is not None
            ]
            current = min((pf for pf in fits if pf <= max_pad),
                          default=None)
            if current is not None and current <= 0.0:
                continue  # already exact somewhere
            new_spec = {
                "args": [
                    [_DTYPE_KINDS.get(dt, dt), list(shape)]
                    for dt, shape in zip(row["dtypes"], row["shapes"])
                ],
                "statics": statics,
            }
            if pad_frac_for(row["shapes"], row["dtypes"],
                            new_spec) != 0.0:
                continue  # malformed group (defensive)
            before = project(table, mix, max_pad)
            trial = dict(table)
            trial[kernel] = specs + [new_spec]
            after = project(trial, mix, max_pad)
            waste_saved = (
                before["pad_frac"] * before["bucketed"]
                - after["pad_frac"] * after["bucketed"]
                # traffic pulled off the native path saved its whole
                # padless dispatch from running cold-shaped; count it
                # as the pad it now pays (0 for an exact split)
            )
            if waste_saved <= 0.0 and after["bucketed"] <= \
                    before["bucketed"]:
                continue
            out.append({
                "action": "split",
                "kernel": kernel,
                "spec": new_spec,
                "count": row["count"],
                "pad_frac_before": current,
                "compiles": 1,
                "waste_saved": round(waste_saved, 6),
                "score": round(waste_saved / 1.0, 6),
            })
    return out


def _merge_candidates(table, mix, max_pad):
    """One MERGE candidate per avatar the observed mix never lands on:
    dropping it frees a compile + an executable-memo slot and, by
    construction, pays no pad_frac (zero traffic re-homes). An avatar
    that IS carrying traffic is never merged away — its traffic would
    pay the sibling's pad_frac, and the split ranking already decided
    that avatar was worth a compile."""
    out = []
    for kernel in sorted(table):
        specs = _kernel_specs(table, kernel)
        if len(specs) < 2:
            continue  # never leave a kernel avatar-less
        for i, spec in enumerate(specs):
            carrying = 0
            for row in mix.get(kernel, ()):
                fits = [
                    (pf, j) for j, s in enumerate(specs)
                    if (pf := pad_frac_for(row["shapes"],
                                           row["dtypes"], s))
                    is not None and pf <= max_pad
                ]
                if fits and min(fits)[1] == i:
                    carrying += row["count"]
            if carrying:
                continue
            out.append({
                "action": "merge",
                "kernel": kernel,
                "spec": spec,
                "count": 0,
                "compiles": -1,
                "waste_saved": 0.0,
                "score": 0.0,
            })
    return out


def propose(mix: dict, table: dict, target: float,
            max_pad: float = 0.5, max_splits: int = MAX_SPLITS) -> dict:
    """The proposal model, pure: rank split candidates by
    waste-saved-per-compile, greedily apply them until the projected
    mean pad_frac of the mix falls below ``target`` (or the split
    budget runs out), then apply every free merge. Returns
    ``{"proposals", "table", "before", "after"}`` — ``table`` is the
    candidate (input table deep-copied; the incumbent is never
    mutated), ``proposals`` the applied actions in rank order."""
    import copy

    candidate = copy.deepcopy(dict(table))
    before = project(candidate, mix, max_pad)
    applied = []
    for _ in range(max_splits):
        now = project(candidate, mix, max_pad)
        if now["pad_frac"] < target and now["native"] == 0:
            break
        splits = _split_candidates(candidate, mix, max_pad)
        if not splits:
            break
        splits.sort(key=lambda p: (-p["score"], p["kernel"],
                                   p["spec"]["args"]))
        best = splits[0]
        specs = _kernel_specs(candidate, best["kernel"])
        candidate[best["kernel"]] = specs + [best["spec"]]
        applied.append(best)
    for merge in _merge_candidates(candidate, mix, max_pad):
        specs = _kernel_specs(candidate, merge["kernel"])
        candidate[merge["kernel"]] = [
            s for s in specs if s != merge["spec"]
        ]
        applied.append(merge)
    after = project(candidate, mix, max_pad)
    return {
        "proposals": applied,
        "table": candidate,
        "before": before,
        "after": after,
    }


# ------------------------------------------------------------------ #
# the promotion gate                                                 #
# ------------------------------------------------------------------ #

def judge_canary(candidate: dict, incumbent: dict,
                 margin: float | None = None) -> dict:
    """The promotion gate over one canary replay at identical seeds.
    ``candidate``/``incumbent`` are measured ``{"pad_frac", "p99_s"}``
    rows. Promote ONLY when the candidate's measured pad_frac beats
    the incumbent's by more than ``margin`` (default: the tuning
    layer's >3% PROMOTE_MARGIN) AND its p99 is strictly better — a
    table that pads less but queues worse did not win. Returns
    ``{"promote": bool, "reason": str, ...}``."""
    if margin is None:
        margin = promote_margin()
    c_pad, i_pad = candidate.get("pad_frac"), incumbent.get("pad_frac")
    c_p99, i_p99 = candidate.get("p99_s"), incumbent.get("p99_s")
    row = {
        "candidate": dict(candidate), "incumbent": dict(incumbent),
        "margin": margin, "promote": False,
    }
    if not all(isinstance(v, (int, float))
               for v in (c_pad, i_pad, c_p99, i_p99)):
        row["reason"] = "no-measurement"
        return row
    if i_pad <= 0.0:
        row["reason"] = "nothing-to-save: incumbent pad_frac is 0"
        return row
    pad_win = (i_pad - c_pad) / i_pad
    row["pad_win"] = round(pad_win, 6)
    if pad_win <= margin:
        row["reason"] = (
            f"pad_frac win {pad_win:.1%} <= margin {margin:.0%}"
        )
        return row
    if c_p99 >= i_p99:
        row["reason"] = (
            f"p99 did not win ({c_p99:.4f}s vs {i_p99:.4f}s)"
        )
        return row
    row["promote"] = True
    row["reason"] = (
        f"pad_frac {i_pad:.3f}->{c_pad:.3f} ({pad_win:.1%} win), "
        f"p99 {i_p99:.4f}s->{c_p99:.4f}s"
    )
    return row


# ------------------------------------------------------------------ #
# the persisted candidate artifact                                   #
# ------------------------------------------------------------------ #

def _jax_version():
    import jax  # lazy: stdlib-only import contract

    return jax.__version__


def record_candidate(result: dict, mix: dict, target: float,
                     jax_version: str | None = None) -> str:
    """Atomically persist a proposal as the ``adapt.json`` candidate
    (status ``proposed``), stamped with the evidence a later reader
    validates it against — jax version, serve-source sha, repo HEAD —
    the tuning/aot/slo artifact discipline. Returns the path."""
    from tpukernels.tuning import cache as tcache

    p = path()
    d = os.path.dirname(p)
    if d:
        os.makedirs(d, exist_ok=True)
    from tpukernels.resilience import atomic

    atomic.dump_json(p, {
        "version": 1,
        "status": "proposed",
        "jax": jax_version if jax_version is not None
        else _jax_version(),
        "source_sha": tcache.source_sha(SOURCES),
        "git_head": journal.git_head(),
        "created": round(time.time(), 3),
        "pad_target": target,
        "requests_mined": mix_requests(mix),
        "before": result["before"],
        "after": result["after"],
        "proposals": result["proposals"],
        "table": result["table"],
        # the frozen replay spec: the canary must drive candidate AND
        # incumbent with the mix the proposal was projected from, not
        # whatever the journal says on canary day
        "replay": replay_entries(mix, result["table"]),
        "canary": None,
    })
    return p


def _reject(reason: str, **fields):
    """Loud-rejection contract shared with tuning/aot/slo: stderr note
    + ``adapt_rejected`` journal event, once per process per cause."""
    memo = (path(), reason)
    if memo in _REJECT_NOTED:
        return
    _REJECT_NOTED.add(memo)
    print(f"# adapt candidate rejected: {reason}", file=sys.stderr)
    journal.emit("adapt_rejected", path=path(), reason=reason,
                 **fields)


def load(validate: bool = True):
    """The validated ``adapt.json`` candidate, or None. Validation
    mirrors the tuning cache: a candidate proposed under a different
    jax version, or whose serve sources have a newer commit than its
    ``source_sha``, is rejected loudly and dropped — a bucket table
    projected by last week's pad math must not be canaried (let alone
    promoted) today. A torn file reads as absent via the shared
    tolerant reader, with its own ``artifact_rejected`` note."""
    data = _cachedir.read_json_memoized(path(), {})
    if not data:
        return None
    if not isinstance(data.get("table"), dict):
        _reject("malformed: no candidate table")
        return None
    if not validate:
        return data
    if data.get("jax") != _jax_version():
        _reject(
            f"proposed under jax {data.get('jax')}, "
            f"running {_jax_version()}",
        )
        return None
    from tpukernels.tuning import cache as tcache

    sha = tcache.source_sha(SOURCES)
    if sha is not None and data.get("source_sha") not in (None, sha):
        _reject(
            "stale: a commit touching " + ",".join(SOURCES)
            + " postdates this candidate",
            entry_sha=data.get("source_sha"), current_sha=sha,
        )
        return None
    return data


def update(mutate) -> dict:
    """flock-serialized read-modify-write of ``adapt.json`` (the
    canary writes its verdict beside the proposal it judged)."""
    return _cachedir.locked_json_update(path(), mutate)


def promote(table: dict) -> str:
    """Atomically write the promoted bucket table to the stable
    ``buckets.json`` path ``TPK_SERVE_BUCKETS`` points at. The
    promotion changes the FILE behind an unchanged env value, so a
    running router/daemon picks it up on ``undrain`` via
    ``bucketing.reload()`` — no fleet restart."""
    from tpukernels.resilience import atomic

    p = buckets_path()
    d = os.path.dirname(p)
    if d:
        os.makedirs(d, exist_ok=True)
    atomic.dump_json(p, table)
    return p


# ------------------------------------------------------------------ #
# replay plumbing (the canary's loadgen input)                       #
# ------------------------------------------------------------------ #

def replay_entries(mix: dict, table: dict, top: int = 8) -> list:
    """The observed mix as a loadgen replay spec (``--shapes FILE``
    entries): the ``top`` heaviest shape groups whose kernel has an
    avatar in ``table``, weights = observed counts, statics borrowed
    from the kernel's avatar (the only traffic that buckets). The
    canary replays THIS against candidate and incumbent at identical
    seeds."""
    rows = [
        (row, kernel)
        for kernel, kernel_rows in sorted(mix.items())
        for row in kernel_rows
        if _kernel_specs(table, kernel)
    ]
    rows.sort(key=lambda rk: (-rk[0]["count"], rk[1],
                              rk[0]["shapes"]))
    out = []
    for row, kernel in rows[:top]:
        statics = dict(
            _kernel_specs(table, kernel)[0].get("statics") or {}
        )
        out.append({
            "kernel": kernel,
            "args": [
                [_DTYPE_KINDS.get(dt, dt), list(shape)]
                for dt, shape in zip(row["dtypes"], row["shapes"])
            ],
            "statics": statics,
            "weight": row["count"],
        })
    return out


def measured_side(events, request_ids_prefix=None) -> dict:
    """One canary side's measurement off its isolated journal:
    ``pad_frac`` is the mean over OK ``serve_request`` events (native
    dispatches count their recorded 0.0 — a table that buckets more
    traffic at low pad must not look worse than one that buckets
    none), ``p99_s`` the request-weighted mean of the loadgen
    ``slo_probe`` verdict p99s."""
    pads, n_bucketed = [], 0
    for e in events:
        if e.get("kind") == "serve_request" and e.get("ok"):
            pads.append(float(e.get("pad_frac") or 0.0))
            n_bucketed += bool(e.get("bucketed"))
    p99 = None
    for e in events:
        if e.get("kind") != "slo_probe":
            continue
        num = den = 0.0
        for v in (e.get("verdicts") or {}).values():
            if isinstance(v.get("p99_s"), (int, float)) \
                    and v.get("count"):
                num += v["p99_s"] * v["count"]
                den += v["count"]
        if den:
            p99 = num / den
    return {
        "pad_frac": (sum(pads) / len(pads)) if pads else None,
        "p99_s": p99,
        "requests": len(pads),
        "bucketed": n_bucketed,
        "hist_pad_frac": histogram_pad_frac(events),
    }
