"""Length-prefixed JSON + raw-buffer wire protocol (docs/SERVING.md).

One frame = a fixed 16-byte preamble (``TPK1`` magic, big-endian
header length, big-endian total payload length), a UTF-8 JSON header,
then the concatenated raw little-endian C-order array buffers the
header describes. JSON carries everything small and structural
(kernel name, statics, shapes, dtypes, verdict fields); the buffers
carry the operand/output bytes verbatim — a 16 MiB sgemm operand must
never ride through a JSON string.

The same framing serves both directions. Requests:

    {"v": 1, "op": "dispatch", "id": 7, "kernel": "scan",
     "statics": {}, "args": [{"shape": [4093], "dtype": "int32"}]}
    + one payload buffer per ``args`` entry

    {"v": 1, "op": "ping"}        # liveness / stats, no payload

Responses:

    {"v": 1, "id": 7, "ok": true,
     "outputs": [{"shape": [4093], "dtype": "int32"}], ...}
    + one payload buffer per ``outputs`` entry

    {"v": 1, "id": 7, "ok": false, "error": "...",
     "kind": "overloaded", "retry_after_s": 0.25}

The module is transport-math only — no sockets are created here, no
jax is imported, and the dtype table is exactly the C ABI's
(``capi._DTYPES``): the serve daemon is one more consumer of the same
two-dtype contract, not a new one.
"""

from __future__ import annotations

import json
import struct

import numpy as np

VERSION = 1
MAGIC = b"TPK1"
_PREAMBLE = struct.Struct(">4sIQ")

# sanity bounds, not resource limits: a header over 1 MiB or a frame
# over 4 GiB is a desynced/hostile stream, not a big request
MAX_HEADER = 1 << 20
MAX_PAYLOAD = 1 << 32

# the C ABI's dtype surface (capi._DTYPES), by canonical numpy name
DTYPES = {
    "float32": np.float32,
    "int32": np.int32,
}


class ProtocolError(Exception):
    """The stream is not speaking this protocol (bad magic, absurd
    lengths, truncated frame, unknown dtype). Callers must treat the
    connection as poisoned — there is no resync."""


def _recv_exact(sock, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise — a short read mid-frame is a
    peer that died, and half a frame is worse than none."""
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ProtocolError(
                f"connection closed mid-frame ({n} byte(s) short)"
            )
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def send_frame(sock, header: dict, payloads=()) -> None:
    """Serialize one frame onto ``sock``. ``payloads`` is a sequence
    of bytes-like buffers; their lengths are recorded in the wire
    header (``_lens``) so :func:`recv_frame` can split the blob
    without trusting the semantic fields."""
    payloads = [bytes(p) for p in payloads]
    wire = dict(header)
    wire["_lens"] = [len(p) for p in payloads]
    hb = json.dumps(wire, separators=(",", ":")).encode()
    total = sum(len(p) for p in payloads)
    if len(hb) > MAX_HEADER or total > MAX_PAYLOAD:
        raise ProtocolError(
            f"frame too large (header {len(hb)}B, payload {total}B)"
        )
    head = _PREAMBLE.pack(MAGIC, len(hb), total) + hb
    if total <= (1 << 16):
        # small frames: one syscall beats avoiding a tiny copy
        sock.sendall(head + b"".join(payloads))
        return
    # multi-MB operand/output frames: send buffers as-is instead of
    # materializing an extra full-frame copy on the hot path
    sock.sendall(head)
    for p in payloads:
        sock.sendall(p)


def recv_frame(sock):
    """Read one frame; returns ``(header, [payload_bytes, ...])`` or
    ``None`` on a clean EOF at a frame boundary (the peer hung up
    between requests — not an error)."""
    first = sock.recv(1)
    if not first:
        return None
    raw = first + _recv_exact(sock, _PREAMBLE.size - 1)
    magic, hlen, total = _PREAMBLE.unpack(raw)
    if magic != MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if hlen > MAX_HEADER or total > MAX_PAYLOAD:
        raise ProtocolError(
            f"absurd frame lengths (header {hlen}B, payload {total}B)"
        )
    try:
        header = json.loads(_recv_exact(sock, hlen))
    except ValueError as e:
        raise ProtocolError(f"unparseable frame header: {e}") from None
    if not isinstance(header, dict):
        raise ProtocolError("frame header is not a JSON object")
    lens = header.pop("_lens", [])
    if not isinstance(lens, list) or any(
        not isinstance(n, int) or isinstance(n, bool) or n < 0
        for n in lens
    ):
        raise ProtocolError(f"malformed _lens {lens!r}")
    if sum(lens) != total:
        raise ProtocolError(
            f"payload lengths {lens} disagree with frame total {total}"
        )
    blob = _recv_exact(sock, total)
    payloads, off = [], 0
    for n in lens:
        payloads.append(blob[off:off + n])
        off += n
    return header, payloads


# ------------------------------------------------------------------ #
# array <-> (spec, bytes)                                            #
# ------------------------------------------------------------------ #

def pack_arrays(arrays):
    """``([{"shape", "dtype"}, ...], [bytes, ...])`` for a sequence of
    numpy arrays (0-d arrays carry host scalars — the dispatch memo's
    canonicalization contract)."""
    specs, payloads = [], []
    for a in arrays:
        a = np.asarray(a)
        name = a.dtype.name
        if name not in DTYPES:
            raise ProtocolError(
                f"unsupported dtype {name!r}; the wire carries "
                f"{sorted(DTYPES)}"
            )
        specs.append({"shape": list(a.shape), "dtype": name})
        payloads.append(np.ascontiguousarray(a).tobytes())
    return specs, payloads


def unpack_arrays(specs, payloads):
    """Rebuild numpy arrays from specs + raw buffers; validates byte
    counts so a desynced stream fails loudly, never reshapes
    garbage."""
    if len(specs) != len(payloads):
        raise ProtocolError(
            f"{len(specs)} array spec(s) but {len(payloads)} payload(s)"
        )
    out = []
    for spec, raw in zip(specs, payloads):
        name = spec.get("dtype")
        if name not in DTYPES:
            raise ProtocolError(f"unsupported dtype {name!r} in spec")
        dt = np.dtype(DTYPES[name])
        shape = tuple(int(d) for d in spec.get("shape", ()))
        want = dt.itemsize * int(np.prod(shape, dtype=np.int64))
        if len(raw) != want:
            raise ProtocolError(
                f"payload is {len(raw)}B but {shape} {name} needs {want}B"
            )
        out.append(np.frombuffer(raw, dtype=dt).reshape(shape))
    return out
