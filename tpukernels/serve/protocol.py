"""Length-prefixed JSON + raw-buffer wire protocol (docs/SERVING.md).

One frame = a fixed 16-byte preamble (``TPK1`` magic, big-endian
header length, big-endian total payload length), a UTF-8 JSON header,
then the concatenated raw little-endian C-order array buffers the
header describes. JSON carries everything small and structural
(kernel name, statics, shapes, dtypes, verdict fields); the buffers
carry the operand/output bytes verbatim — a 16 MiB sgemm operand must
never ride through a JSON string.

The same framing serves both directions. Requests:

    {"v": 1, "op": "dispatch", "id": 7, "kernel": "scan",
     "statics": {}, "request_id": "c3f2a-12",
     "args": [{"shape": [4093], "dtype": "int32"}]}
    + one payload buffer per ``args`` entry

    {"v": 1, "op": "ping"}        # liveness / stats, no payload

A dispatch header may also carry a client-set DEADLINE
(docs/SERVING.md §deadlines): ``deadline_ms`` is the request's total
budget (informational — it never crosses a clock boundary), and
``budget_ms`` is the REMAINING budget at the moment the frame was
sent, recomputed at every hop (client send, router forward). Absolute
wall-clock deadlines are meaningless across skewed processes, so no
absolute time ever rides the wire: each receiver converts the budget
into its OWN local monotonic deadline at receive time
(:func:`deadline_from_header`) and each forwarder re-stamps the
remainder (:func:`stamp_budget`) — the reqtrace skew rule (durations
only, never cross-pid clock comparison) applied to admission. Old
servers ignore both fields, like any other unknown header field.

A dispatch header may also carry ``replay`` (int, set by the fleet
router, never by clients): the count of prior delivery attempts this
request already survived — the router re-forwards an accepted request
whose worker DIED mid-flight to the bucket's ring sibling
(``serve_request_replayed``, docs/SERVING.md §self-healing). The
field is the replay-idempotency contract made explicit: the dead
worker MAY already have executed the request, and re-execution is
safe because every served kernel is a pure function of its operands —
the worker records the count on its ``serve_request`` evidence
(``replayed``), the ``request_id`` stays the same across the hops, so
every journal consumer that dedupes by id counts the request once.
Old servers ignore the unknown field, like any other.

``request_id`` is the CLIENT-MINTED causal trace id
(docs/OBSERVABILITY.md §request tracing): the router relays it
untouched and tags its routing evidence with it, the server tags its
``serve_request``/span evidence, and ``obs/reqtrace.py`` joins the
multi-process journals on it. It is negotiated like the shm lane —
the pong advertises ``request_trace`` when the server tags its
journal — and, like any unknown header field, is simply ignored by
old servers, so a tracing client never needs a compatibility switch.

Responses:

    {"v": 1, "id": 7, "ok": true,
     "outputs": [{"shape": [4093], "dtype": "int32"}], ...}
    + one payload buffer per ``outputs`` entry

    {"v": 1, "id": 7, "ok": false, "error": "...",
     "kind": "overloaded", "retry_after_s": 0.25}

Two payload lanes (docs/SERVING.md §wire format):

- **inline** — payload bytes ride the socket after the header, split
  by ``_lens``. Every byte crosses the kernel socket buffer twice
  (send + recv), which is what ``serve.bytes_copied`` counts; the
  user-space side is zero-copy both ways (buffers stream through
  ``sendall`` as memoryviews, and :func:`recv_frame` hands back
  memoryview slices of one recv blob).
- **shm** — a payload at or over ``TPK_SERVE_SHM_MIN_BYTES`` moves
  through a named ``/dev/shm`` segment the sender writes and the
  receiver maps read-only; only ``{"name", "nbytes"}`` rides the
  header (``_shm``, one slot per payload, null = inline). Negotiated
  at ping time (``lanes`` in the pong): a peer that never advertises
  ``shm`` is spoken to inline forever, so old servers and mapping-
  incapable clients keep working unchanged. Raw files + ``mmap``
  rather than ``multiprocessing.shared_memory`` on purpose: no
  resource-tracker side effects in either process, and the reader
  needs only two syscalls. Lifecycle contract: request segments are
  created AND unlinked by the client (after its response arrives);
  response segments are created by the server and unlinked by the
  client as soon as it maps them (the server keeps an aged ledger and
  a start-time dead-creator sweep for the crash windows) — see
  docs/SERVING.md §shm lifecycle.

The module is transport-math only — no sockets are created here, no
jax is imported, and the dtype table is exactly the C ABI's
(``capi._DTYPES``): the serve daemon is one more consumer of the same
two-dtype contract, not a new one.
"""

from __future__ import annotations

import itertools
import json
import mmap
import os
import re
import struct
import time

import numpy as np

VERSION = 1
MAGIC = b"TPK1"
_PREAMBLE = struct.Struct(">4sIQ")

# sanity bounds, not resource limits: a header over 1 MiB or a frame
# over 4 GiB is a desynced/hostile stream, not a big request
MAX_HEADER = 1 << 20
MAX_PAYLOAD = 1 << 32

# at or under this many payload bytes, one syscall (head + payloads
# joined) beats streaming buffers separately; over it, buffers stream
# as-is so no user-space frame copy is ever materialized
SMALL_FRAME = 1 << 16

# the C ABI's dtype surface (capi._DTYPES), by canonical numpy name
DTYPES = {
    "float32": np.float32,
    "int32": np.int32,
}

# ------------------------------------------------------------------ #
# request deadlines (docs/SERVING.md §deadlines)                     #
# ------------------------------------------------------------------ #

def deadline_from_header(header, now=None):
    """The frame's remaining budget converted into THIS process's own
    local monotonic deadline, or ``None`` when the request carries no
    deadline. ``budget_ms`` (the per-hop remainder) wins; a header
    with only ``deadline_ms`` (a minimal client that never recomputes)
    falls back to it. Malformed values read as no-deadline — a wire
    field from an arbitrary client is tolerated like any unknown
    field, never a crash surface."""
    raw = header.get("budget_ms")
    if raw is None:
        raw = header.get("deadline_ms")
    if (not isinstance(raw, (int, float)) or isinstance(raw, bool)
            or raw < 0):
        return None
    if now is None:
        now = time.monotonic()
    return now + raw / 1000.0


def budget_ms_remaining(deadline_at, now=None) -> float:
    """Milliseconds left until a local monotonic deadline, clamped at
    0 — the one subtraction every layer's expiry check shares. Only
    ever called with a deadline THIS process derived from a received
    budget, so no cross-process clock comparison can occur."""
    if now is None:
        now = time.monotonic()
    return max(0.0, (deadline_at - now) * 1000.0)


def stamp_budget(header, deadline_at, now=None) -> dict:
    """A copy of ``header`` with ``budget_ms`` recomputed from a local
    monotonic deadline — the per-hop re-stamp a forwarder (client
    retry, router forward/hedge) applies so the downstream process
    sees the budget net of time already spent here. ``deadline_at``
    None returns the header unchanged (no deadline, nothing to
    stamp)."""
    if deadline_at is None:
        return header
    out = dict(header)
    out["budget_ms"] = round(budget_ms_remaining(deadline_at, now), 3)
    return out


# ------------------------------------------------------------------ #
# shm lane plumbing                                                  #
# ------------------------------------------------------------------ #

SHM_DIR = "/dev/shm"
DEFAULT_SHM_MIN_BYTES = 1 << 16

# creator pid is IN the name: leak-on-crash cleanup needs nothing but
# a directory listing and a kill -0 (sweep_stale_segments)
_SHM_NAME_RE = re.compile(r"^tpkserve-(\d+)-\d+-[0-9a-f]+$")
_SHM_SEQ = itertools.count()
_SHM_PROBE: list = []  # memoized shm_available() verdict


class ProtocolError(Exception):
    """The stream is not speaking this protocol (bad magic, absurd
    lengths, truncated frame, unknown dtype, torn shm segment).
    Callers must treat the connection as poisoned — there is no
    resync."""


def _view(p) -> memoryview:
    """A flat byte view of one payload buffer — no copy for bytes /
    bytearray / C-contiguous arrays, which is every payload the
    serving stack produces (``pack_arrays`` canonicalizes)."""
    m = p if isinstance(p, memoryview) else memoryview(p)
    if m.format != "B" or m.ndim != 1:
        m = m.cast("B")
    return m


def shm_min_bytes() -> int:
    """``TPK_SERVE_SHM_MIN_BYTES`` (default 64 KiB), fail-loud parse:
    below it, one inline syscall beats creating + mapping a segment."""
    raw = os.environ.get("TPK_SERVE_SHM_MIN_BYTES")
    if raw is None or not raw.strip():
        return DEFAULT_SHM_MIN_BYTES
    try:
        val = int(raw)
    except ValueError:
        val = -1
    if val < 0:
        raise ValueError(
            f"TPK_SERVE_SHM_MIN_BYTES={raw!r}: expected an int >= 0"
        )
    return val


def shm_available() -> bool:
    """Can this process create and map ``/dev/shm`` segments? Probed
    once (create + map + unlink of a page) and memoized — the
    negotiation predicate, not a knob."""
    if not _SHM_PROBE:
        try:
            seg = ShmSegment(8)
            try:
                seg.write(b"\0" * 8)
                mm = open_shm(seg.name, 8)
                mm.close()
            finally:
                seg.close()
                seg.unlink()
            _SHM_PROBE.append(True)
        except (OSError, ProtocolError, ValueError):
            _SHM_PROBE.append(False)
    return _SHM_PROBE[0]


def shm_enabled() -> bool:
    """The shm lane's routing predicate: ``TPK_SERVE_SHM`` not
    switched off (``0``/``off``/``none``/``false``) AND the host can
    actually map (:func:`shm_available`)."""
    raw = os.environ.get("TPK_SERVE_SHM")
    if raw is not None and raw.strip().lower() in (
            "0", "off", "none", "false"):
        return False
    return shm_available()


class ShmSegment:
    """One creator-owned shared-memory segment: a raw ``/dev/shm``
    file sized exactly ``nbytes``, mapped read-write by its creator.
    The creator writes payload bytes in (:meth:`write`), ships only
    ``{"name", "nbytes"}`` over the wire, and — per the lifecycle
    contract in the module docstring — whoever the contract names
    unlinks it; :meth:`unlink` after the fact is idempotent."""

    __slots__ = ("name", "nbytes", "_mm")

    def __init__(self, nbytes: int):
        if nbytes <= 0 or nbytes > MAX_PAYLOAD:
            raise ValueError(f"bad shm segment size {nbytes}")
        self.nbytes = nbytes
        fd = None
        for _attempt in range(4):
            name = (f"tpkserve-{os.getpid()}-{next(_SHM_SEQ)}-"
                    f"{os.urandom(4).hex()}")
            try:
                fd = os.open(os.path.join(SHM_DIR, name),
                             os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
                break
            except FileExistsError:
                continue
        if fd is None:
            raise OSError(f"cannot create an shm segment under {SHM_DIR}")
        try:
            # fallocate, not ftruncate: tmpfs truncation is sparse, so
            # an exhausted /dev/shm would pass creation and SIGBUS the
            # first write — allocation must fail HERE as ENOSPC so the
            # caller's documented inline fallback can fire
            os.posix_fallocate(fd, 0, nbytes)
            self._mm = mmap.mmap(fd, nbytes)
        except BaseException:
            # never leak the file: the dead-pid sweep skips segments
            # whose creator (us) is alive
            with_err = os.path.join(SHM_DIR, name)
            try:
                os.unlink(with_err)
            except OSError:
                pass
            raise
        finally:
            os.close(fd)
        self.name = name

    def write(self, buf, offset: int = 0) -> int:
        """Copy ``buf`` into the segment at ``offset``; returns the
        byte count (the caller's ``serve.bytes_copied`` evidence —
        staging an already-materialized buffer is a counted copy)."""
        v = _view(buf)
        self._mm[offset:offset + v.nbytes] = v
        return v.nbytes

    def close(self):
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass  # live numpy exports keep the mapping until GC

    def unlink(self):
        try:
            os.unlink(os.path.join(SHM_DIR, self.name))
        except OSError:
            pass


def open_shm(name, nbytes):
    """Map one named segment read-only; returns the ``mmap`` (itself a
    valid payload buffer for :func:`unpack_arrays`). Any defect — a
    name outside the ``tpkserve-`` namespace, a missing file, a file
    shorter than the header claims — is a TORN segment: the stream
    that described it is desynced or hostile, so this raises
    :class:`ProtocolError` and the connection dies, never the
    daemon."""
    if not isinstance(name, str) or not _SHM_NAME_RE.match(name):
        raise ProtocolError(f"bad shm segment name {name!r}")
    if (not isinstance(nbytes, int) or isinstance(nbytes, bool)
            or nbytes <= 0 or nbytes > MAX_PAYLOAD):
        raise ProtocolError(f"bad shm segment size {nbytes!r}")
    try:
        fd = os.open(os.path.join(SHM_DIR, name), os.O_RDONLY)
    except OSError as e:
        raise ProtocolError(f"torn shm segment {name}: {e}") from None
    try:
        if os.fstat(fd).st_size < nbytes:
            raise ProtocolError(
                f"torn shm segment {name}: file is "
                f"{os.fstat(fd).st_size}B, header claims {nbytes}B"
            )
        try:
            return mmap.mmap(fd, nbytes, prot=mmap.PROT_READ)
        except (ValueError, OSError) as e:
            raise ProtocolError(
                f"torn shm segment {name}: {e}"
            ) from None
    finally:
        os.close(fd)


def unlink_shm(name) -> bool:
    """Unlink one segment by name (idempotent; bad names are ignored
    rather than trusted). The receiver-unlinks half of the response
    lifecycle, and the failed-send cleanup hook."""
    if not isinstance(name, str) or not _SHM_NAME_RE.match(name):
        return False
    try:
        os.unlink(os.path.join(SHM_DIR, name))
        return True
    except OSError:
        return False


def sweep_segments_for_pid(pid) -> tuple:
    """Targeted leak-on-crash cleanup: unlink every ``tpkserve-<pid>-*``
    segment of ONE dead creator, returning ``(count, bytes)`` so the
    caller's evidence (the fleet health manager's ``worker_dead``
    event) can carry the reclaimed byte count. The creator must
    actually be dead — a live (or recycled) pid is left alone; the
    generic start-time :func:`sweep_stale_segments` remains the
    backstop."""
    if not isinstance(pid, int) or pid <= 0:
        return 0, 0
    try:
        os.kill(pid, 0)
        return 0, 0             # alive (or recycled): not ours to sweep
    except ProcessLookupError:
        pass
    except OSError:
        return 0, 0             # EPERM: alive under another uid
    try:
        names = os.listdir(SHM_DIR)
    except OSError:
        return 0, 0
    removed, nbytes = 0, 0
    for name in names:
        m = _SHM_NAME_RE.match(name)
        if not m or int(m.group(1)) != pid:
            continue
        path = os.path.join(SHM_DIR, name)
        try:
            size = os.stat(path).st_size
            os.unlink(path)
        except OSError:
            continue
        removed += 1
        nbytes += size
    return removed, nbytes


def sweep_stale_segments() -> int:
    """Leak-on-crash cleanup: unlink every ``tpkserve-*`` segment
    whose creator pid is dead (the name carries it). Run at daemon /
    router start — a process that died between creating a segment and
    its peer unlinking it can leak at most until the next start."""
    try:
        names = os.listdir(SHM_DIR)
    except OSError:
        return 0
    removed = 0
    for name in names:
        m = _SHM_NAME_RE.match(name)
        if not m:
            continue
        try:
            os.kill(int(m.group(1)), 0)
            continue            # creator alive: its lifecycle, not ours
        except ProcessLookupError:
            pass
        except OSError:
            continue            # EPERM: alive under another uid
        try:
            os.unlink(os.path.join(SHM_DIR, name))
            removed += 1
        except OSError:
            pass
    return removed


def stage_shm_payloads(payloads, min_bytes=None):
    """Sender half of the shm lane: move every payload at or over the
    threshold into a fresh segment. Returns ``(descs, wire_payloads,
    segments, staged_bytes)`` — ``descs`` is the header's ``_shm``
    list (one slot per payload, null = still inline) or None when
    nothing crossed the threshold (the frame then has no shm marker
    at all and an old receiver parses it untouched);
    ``wire_payloads`` are the inline remainder in order; ``segments``
    must outlive the round trip and be closed/unlinked per the
    lifecycle contract; ``staged_bytes`` is the counted staging
    copy."""
    if min_bytes is None:
        min_bytes = shm_min_bytes()
    descs, wire, segs, staged = [], [], [], 0
    try:
        for p in payloads:
            v = _view(p)
            if v.nbytes >= max(1, min_bytes):
                seg = ShmSegment(v.nbytes)
                segs.append(seg)
                staged += seg.write(v)
                descs.append({"name": seg.name, "nbytes": v.nbytes})
            else:
                descs.append(None)
                wire.append(v)
    except (OSError, ValueError):
        # a failed creation mid-list (exhausted /dev/shm) must not
        # leak the segments already created — the caller falls back
        # to the inline lane
        for seg in segs:
            seg.close()
            seg.unlink()
        raise
    if not segs:
        return None, wire, [], 0
    return descs, wire, segs, staged


def check_shm_descs(header, n_payloads: int):
    """Structural validation of a frame's ``_shm`` against its arg
    specs and inline payload count WITHOUT opening anything — the
    fleet router's front-door check (docs/SERVING.md §fleet): a
    malformed descriptor must die there as a bad request, not ride
    upstream to poison worker connections and masquerade as
    transport loss. Raises :class:`ProtocolError`; a frame with no
    ``_shm`` passes untouched."""
    descs = header.get("_shm")
    if descs is None:
        return
    args = header.get("args") or []
    if not isinstance(descs, list) or len(descs) != len(args):
        raise ProtocolError(
            f"malformed _shm: expected {len(args)} slot(s), "
            f"got {descs!r}"
        )
    inline = 0
    for d in descs:
        if d is None:
            inline += 1
            continue
        if not (isinstance(d, dict)
                and isinstance(d.get("name"), str)
                and _SHM_NAME_RE.match(d["name"])
                and isinstance(d.get("nbytes"), int)
                and not isinstance(d.get("nbytes"), bool)
                and 0 < d["nbytes"] <= MAX_PAYLOAD):
            raise ProtocolError(f"malformed _shm slot {d!r}")
    if inline != n_payloads:
        raise ProtocolError(
            f"_shm leaves {inline} inline payload(s) but the frame "
            f"carries {n_payloads}"
        )


def resolve_shm_payloads(header, payloads):
    """Receiver half: splice mapped segments back into payload order.
    Pops ``_shm`` from ``header`` and returns ``(full_payloads,
    inline_bytes, maps)`` — ``maps`` are the read-only mmaps backing
    the spliced entries (kept alive by the numpy views
    :func:`unpack_arrays` builds over them; freed by refcount once
    the arrays die). A malformed ``_shm`` or a torn segment raises
    :class:`ProtocolError` — the poisoned-connection contract."""
    descs = header.pop("_shm", None)
    inline_bytes = sum(len(p) for p in payloads)
    if descs is None:
        return list(payloads), inline_bytes, []
    if not isinstance(descs, list):
        raise ProtocolError(f"malformed _shm {descs!r}")
    full, maps = [], []
    it = iter(payloads)
    try:
        for d in descs:
            if d is None:
                full.append(next(it))
                continue
            if not isinstance(d, dict):
                raise ProtocolError(f"malformed _shm slot {d!r}")
            mm = open_shm(d.get("name"), d.get("nbytes"))
            maps.append(mm)
            full.append(mm)
    except StopIteration:
        raise ProtocolError(
            "_shm names fewer inline payloads than the frame carries"
        ) from None
    if next(it, None) is not None:
        raise ProtocolError(
            "frame carries inline payloads _shm does not account for"
        )
    return full, inline_bytes, maps


# ------------------------------------------------------------------ #
# framing                                                            #
# ------------------------------------------------------------------ #

def _recv_exact(sock, n: int) -> bytes:
    """Read exactly ``n`` bytes or raise — a short read mid-frame is a
    peer that died, and half a frame is worse than none."""
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ProtocolError(
                f"connection closed mid-frame ({n} byte(s) short)"
            )
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def send_frame(sock, header, payloads=()) -> int:
    """Serialize one frame onto ``sock``. ``payloads`` is a sequence
    of buffer-likes (bytes, memoryviews, contiguous arrays) streamed
    as-is — no ``bytes()`` materialization on the send path; their
    lengths are recorded in the wire header (``_lens``) so
    :func:`recv_frame` can split the blob without trusting the
    semantic fields. Returns the inline payload bytes pushed through
    the socket — the send-side half of the ``serve.bytes_copied``
    accounting (an shm-lane frame returns 0: only names ride the
    wire)."""
    views = [_view(p) for p in payloads]
    wire = dict(header)
    wire["_lens"] = [v.nbytes for v in views]
    hb = json.dumps(wire, separators=(",", ":")).encode()
    total = sum(v.nbytes for v in views)
    if len(hb) > MAX_HEADER or total > MAX_PAYLOAD:
        raise ProtocolError(
            f"frame too large (header {len(hb)}B, payload {total}B)"
        )
    head = _PREAMBLE.pack(MAGIC, len(hb), total) + hb
    if total <= SMALL_FRAME:
        # small frames: one syscall beats avoiding a tiny join
        sock.sendall(b"".join([head, *views]))
        return total
    # multi-MB operand/output frames: stream each buffer as-is — the
    # kernel socket copy is the only byte-touching left on this path
    sock.sendall(head)
    for v in views:
        sock.sendall(v)
    return total


def recv_frame(sock):
    """Read one frame; returns ``(header, [payload_view, ...])`` —
    payloads are zero-copy memoryview slices over the one received
    blob — or ``None`` on a clean EOF at a frame boundary (the peer
    hung up between requests — not an error)."""
    first = sock.recv(1)
    if not first:
        return None
    raw = first + _recv_exact(sock, _PREAMBLE.size - 1)
    magic, hlen, total = _PREAMBLE.unpack(raw)
    if magic != MAGIC:
        raise ProtocolError(f"bad magic {magic!r}")
    if hlen > MAX_HEADER or total > MAX_PAYLOAD:
        raise ProtocolError(
            f"absurd frame lengths (header {hlen}B, payload {total}B)"
        )
    try:
        header = json.loads(_recv_exact(sock, hlen))
    except ValueError as e:
        raise ProtocolError(f"unparseable frame header: {e}") from None
    if not isinstance(header, dict):
        raise ProtocolError("frame header is not a JSON object")
    lens = header.pop("_lens", [])
    if not isinstance(lens, list) or any(
        not isinstance(n, int) or isinstance(n, bool) or n < 0
        for n in lens
    ):
        raise ProtocolError(f"malformed _lens {lens!r}")
    if sum(lens) != total:
        raise ProtocolError(
            f"payload lengths {lens} disagree with frame total {total}"
        )
    blob = memoryview(_recv_exact(sock, total))
    payloads, off = [], 0
    for n in lens:
        payloads.append(blob[off:off + n])
        off += n
    return header, payloads


# ------------------------------------------------------------------ #
# array <-> (spec, buffer)                                           #
# ------------------------------------------------------------------ #

def pack_arrays(arrays):
    """``([{"shape", "dtype"}, ...], [buffer, ...])`` for a sequence
    of numpy arrays (0-d arrays carry host scalars — the dispatch
    memo's canonicalization contract). Payloads are memoryviews over
    the arrays themselves — zero-copy for C-contiguous operands,
    which is every array this stack produces (``ascontiguousarray``
    canonicalizes the rest)."""
    specs, payloads = [], []
    for a in arrays:
        a = np.asarray(a)
        name = a.dtype.name
        if name not in DTYPES:
            raise ProtocolError(
                f"unsupported dtype {name!r}; the wire carries "
                f"{sorted(DTYPES)}"
            )
        specs.append({"shape": list(a.shape), "dtype": name})
        payloads.append(_view(np.ascontiguousarray(a)))
    return specs, payloads


def unpack_arrays(specs, payloads):
    """Rebuild numpy arrays from specs + raw buffers (bytes,
    memoryviews, or read-only shm mmaps — all zero-copy views);
    validates byte counts so a desynced stream fails loudly, never
    reshapes garbage."""
    if len(specs) != len(payloads):
        raise ProtocolError(
            f"{len(specs)} array spec(s) but {len(payloads)} payload(s)"
        )
    out = []
    for spec, raw in zip(specs, payloads):
        name = spec.get("dtype")
        if name not in DTYPES:
            raise ProtocolError(f"unsupported dtype {name!r} in spec")
        dt = np.dtype(DTYPES[name])
        shape = tuple(int(d) for d in spec.get("shape", ()))
        want = dt.itemsize * int(np.prod(shape, dtype=np.int64))
        if len(raw) != want:
            raise ProtocolError(
                f"payload is {len(raw)}B but {shape} {name} needs {want}B"
            )
        out.append(np.frombuffer(raw, dtype=dt).reshape(shape))
    return out
