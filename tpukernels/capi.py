"""Marshalling layer the C shim imports (SURVEY.md C10, Python side).

The shim passes raw host pointers plus a JSON description of shapes,
dtypes and scalar parameters. Each adapter wraps the pointers as numpy
views (zero-copy on the host side), dispatches the jitted kernel,
blocks until device completion, and copies results back into the
driver-owned buffers *before returning* — the C timing loop around
tpu_run() therefore measures H2D + compute + D2H, symmetric with a CUDA
variant that times memcpy+kernel+sync (SURVEY.md §7 "honest timing").
"""

from __future__ import annotations

import ctypes
import functools
import json
import math
import os
import time

import numpy as np

# Compilation-cache (SURVEY.md §5 "checkpoint/resume"): persist
# compiled executables across C-driver processes so the timing loop
# never eats a recompile. Must run before the jax import below (see
# tpukernels/_cachedir.py).
from tpukernels._cachedir import ensure_compilation_cache

ensure_compilation_cache()

# Resilience layer (stdlib-only, honors the env-before-jax-import
# rule): fault-injection point + health journal for the C entry, plus
# the output-integrity guard over the buffers the C driver reads back.
from tpukernels.resilience import faults, integrity, journal

# Observability (stdlib-only too, docs/OBSERVABILITY.md): per-kernel
# dispatch spans/counters/latency histograms for the C entry.
from tpukernels.obs import metrics as obs_metrics
from tpukernels.obs import trace

_PROFILE_DIR = os.environ.get("TPU_KERNELS_PROFILE")
_profiling = False


def _maybe_start_profiler():
    """Opt-in tracing (SURVEY.md §5): TPU_KERNELS_PROFILE=<dir> wraps
    all shim-dispatched kernel work in a jax.profiler trace
    (Perfetto/XProf) so MXU utilization and DMA traffic are visible.
    The trace only flushes to disk on stop_trace, so two flush paths
    cover both host kinds: a Python atexit hook (Python hosts finalize
    the interpreter, which runs atexit) and shutdown_from_c (C hosts
    never finalize — the shim's tpu_shutdown, registered with C
    atexit, calls it instead)."""
    global _profiling
    if _PROFILE_DIR and not _profiling:
        import atexit

        import jax

        jax.profiler.start_trace(_PROFILE_DIR)
        _profiling = True
        atexit.register(stop_profiler)


def stop_profiler():
    """Flush the opt-in profiler trace (idempotent)."""
    global _profiling
    if _profiling:
        _profiling = False
        import jax

        jax.profiler.stop_trace()


def shutdown_from_c() -> int:
    """Called by the shim's tpu_shutdown (C atexit): flush anything
    that only flushes on clean teardown — the profiler trace and the
    final metrics snapshot (C hosts never finalize the interpreter,
    so obs.metrics' own atexit hook would never fire there)."""
    stop_profiler()
    obs_metrics.emit_snapshot(site="capi.shutdown")
    return 0

# Exactly the dtypes the C drivers emit in their buffer specs (grep
# '"dtype"' under c/) — no speculative surface. The suite is single
# precision by contract (SGEMM = *S*GEMM) and TPU has no native f64;
# a new dtype gets added here the day a driver actually sends it.
_DTYPES = {
    "f32": np.float32,
    "i32": np.int32,
}

# Serve-daemon client routing (docs/SERVING.md): with
# TPK_SERVE_SOCKET set, the single-device adapters become CLIENTS of
# the long-lived kernel-serving daemon — the C shim is then one
# client among many sharing the daemon's warm executable memo across
# driver processes. One client PER THREAD (ServeClient is one
# connection with one outstanding request; a multi-threaded host
# sharing a connection would interleave frames and cross-deliver
# responses), rebuilt when the knob changes; any transport failure
# falls back to the in-process registry.dispatch path (retained by
# contract) with one stderr note. Payload lanes ride the client's
# ping-time negotiation (docs/SERVING.md §wire format): against a
# daemon that advertises shm, operands at or over
# TPK_SERVE_SHM_MIN_BYTES move through /dev/shm segments instead of
# the socket — the C driver's big buffers stop being copied per hop —
# and against anything else the inline lane works unchanged.
import threading as _threading

_SERVE_TLS = _threading.local()  # .client: this thread's ServeClient
_SERVE_WARNED = False


def _dispatch(kernel: str, *args, **statics):
    """``registry.dispatch``, or the serve daemon when
    ``TPK_SERVE_SOCKET`` names a reachable socket. Callers pass HOST
    operands (numpy views/scalars — ``np.float32`` for traced scalars
    so the memo key matches the precompiled avatar); device placement
    happens here, and only on the in-process branch — the serve route
    ships host bytes straight to the daemon's device instead of paying
    a client-side H2D+D2H round trip per request. Results come back
    numpy on the serve side, device arrays in-process —
    ``np.copyto``/``np.asarray`` at the callsites absorb both.

    Failure policy: only TRANSPORT trouble (dead socket, desynced
    stream) falls back in-process — the retained batch path. An
    admission-control rejection is honored per the daemon's
    ``retry_after_s`` hint (backpressure is an answer, not an outage)
    up to 10 tries before the loud in-process fallback, and a
    daemon-REPORTED dispatch error re-raises: the daemon runs the
    same registry path, so retrying the same bad request in-process
    would just mask a deterministic failure."""
    global _SERVE_WARNED
    sock = os.environ.get("TPK_SERVE_SOCKET")
    if sock:
        from tpukernels.serve import client as serve_client
        from tpukernels.serve import protocol as serve_protocol

        np_args = tuple(np.asarray(a) for a in args)
        try:
            cli = getattr(_SERVE_TLS, "client", None)
            if cli is None or cli.socket_path != sock:
                cli = serve_client.ServeClient(sock)
                _SERVE_TLS.client = cli
            return serve_client.dispatch_with_backpressure(
                cli, kernel, np_args, statics
            )
        except serve_client.ServeRejected:
            import sys

            print(
                f"# capi: serve daemon at {sock} rejected {kernel} "
                "10x - falling back in-process",
                file=sys.stderr,
            )
        except serve_client.ServeError:
            raise  # the daemon ran it and it failed: that IS the answer
        except (OSError, serve_protocol.ProtocolError) as e:
            _SERVE_TLS.client = None
            if not _SERVE_WARNED:
                _SERVE_WARNED = True
                import sys

                print(
                    f"# capi: serve daemon at {sock} unusable "
                    f"({e!r}) - falling back in-process",
                    file=sys.stderr,
                )
    import jax.numpy as jnp

    from tpukernels import registry

    # no-op for operands already on device (the shared scan/histogram
    # upload); one H2D for the host views the adapters now pass
    return registry.dispatch(
        kernel, *(jnp.asarray(a) for a in args), **statics
    )


def _mesh_size() -> int:
    """TPK_MESH (SURVEY.md §5 config system): device count the
    shim-dispatched kernels shard over. >1 routes the stencils,
    N-body, scan and histogram through the shard_map collective
    variants (C9) on a ring mesh — the C driver's `mpirun -np N`
    analog with zero new C flags.
    Unset/1 keeps the single-device Pallas path (the allreduce
    adapter is the one TPK_MESH=1-vs-unset difference: an explicit 1
    pins its rank count to 1, unset means all visible devices)."""
    n = int(os.environ.get("TPK_MESH", "1"))
    if n < 1:
        raise ValueError(f"TPK_MESH={n}: must be >= 1")
    if n == 1:
        return 1
    import jax

    from tpukernels.parallel.mesh import maybe_distributed_init

    # join the multi-host job BEFORE the first topology read —
    # device_count() initializes the backend, and a pre-join backend
    # sees only this host's chips (and poisons the later
    # jax.distributed.initialize)
    maybe_distributed_init()
    have = jax.device_count()
    if have < n:
        raise RuntimeError(
            f"TPK_MESH={n} but only {have} device(s) visible. For "
            "logic runs without a pod: JAX_PLATFORMS=cpu "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={n}"
        )
    return n


def _to_global(a, mesh, spec):
    """Mesh-path adapter input: see parallel.mesh.host_to_global."""
    from jax.sharding import NamedSharding

    from tpukernels.parallel.mesh import host_to_global

    return host_to_global(a, NamedSharding(mesh, spec))


def _to_host(o) -> np.ndarray:
    """Mesh-path adapter output: see parallel.mesh.global_to_host."""
    from tpukernels.parallel.mesh import global_to_host

    return global_to_host(o)


def _wrap(addr: int, spec: dict) -> np.ndarray:
    dtype_name = spec.get("dtype")
    if dtype_name not in _DTYPES:
        raise ValueError(
            f"unsupported buffer dtype {dtype_name!r}; the C ABI "
            f"carries {sorted(_DTYPES)}"
        )
    dt = np.dtype(_DTYPES[dtype_name])
    shape = tuple(spec["shape"])
    nbytes = dt.itemsize * math.prod(shape)
    raw = (ctypes.c_char * nbytes).from_address(addr)
    return np.frombuffer(raw, dtype=dt).reshape(shape)


def _adapt_vector_add(p, arrs):
    # single-device dispatches route through _dispatch: in-process
    # that is registry.dispatch (the process-wide compiled-executable
    # memo, docs/PERF.md §compile discipline) — a shim call after a
    # prewarm or an earlier dispatch at the same shapes reuses the
    # compiled executable instead of re-tracing; with TPK_SERVE_SOCKET
    # set it is the serving daemon's even-longer-lived memo. Operands
    # stay host-side numpy here (np.float32 canonicalizes traced
    # scalars so the memo key matches the precompiled avatar);
    # _dispatch owns device placement.
    x, y = arrs
    out = _dispatch(
        "vector_add", np.float32(p.get("alpha", 1.0)), x, y
    )
    np.copyto(y, np.asarray(out))


def _adapt_sgemm(p, arrs):
    a, b, c = arrs
    out = _dispatch(
        "sgemm",
        np.float32(p.get("alpha", 1.0)), a, b,
        np.float32(p.get("beta", 0.0)), c,
    )
    np.copyto(c, np.asarray(out))


def _adapt_stencil(name, p, arrs):
    (x,) = arrs
    n = _mesh_size()
    if n > 1:
        from jax.sharding import PartitionSpec as P

        from tpukernels.parallel import make_mesh
        from tpukernels.parallel import collectives

        dist = {
            "stencil2d": collectives.jacobi2d_dist,
            "stencil3d": collectives.jacobi3d_dist,
        }[name]
        mesh = make_mesh(n)
        xg = _to_global(x, mesh, P("x", *[None] * (x.ndim - 1)))
        # honor the temporal-blocking knob in mesh mode too (the
        # dist k is the comm-avoiding halo depth, the multi-chip
        # mirror of the single-device TPK_STENCIL_K)
        kw = {}
        if "TPK_STENCIL_K" in os.environ:
            kw["k"] = int(os.environ["TPK_STENCIL_K"])
        # TPK_STENCIL_RESIDUAL=1: also run the loop's residual
        # allreduce (SURVEY.md §3(b)) and report it on stderr, with
        # zero new C flags. Diagnostic knob: it adds one extra sweep
        # + a global psum per tpu_run() call, so timed benchmark runs
        # should leave it unset (use it with --check / --reps=1)
        if os.environ.get("TPK_STENCIL_RESIDUAL") == "1":
            out, res = dist(xg, int(p["iters"]), mesh, residual=True, **kw)
            import sys

            print(
                f"tpukernels: {name} residual "
                f"||x_k+1 - x_k||^2 = {float(res):.6e}",
                file=sys.stderr,
            )
        else:
            out = dist(xg, int(p["iters"]), mesh, **kw)
        np.copyto(x, _to_host(out))
    else:
        # iters selects the program (fori trip count), so it rides as
        # a static param on the executable-memo key
        out = _dispatch(name, x, iters=int(p["iters"]))
        np.copyto(x, np.asarray(out))


def _mesh_ctx():
    """(mesh_size, mesh-or-None) for the element-sharded adapters."""
    n = _mesh_size()
    if n == 1:
        return 1, None
    from tpukernels.parallel import make_mesh

    return n, make_mesh(n)


def _upload_1d(x, n, mesh):
    """One H2D of a 1-D buffer, element-sharded when a mesh is up.
    With the serve daemon routed (TPK_SERVE_SOCKET) the host buffer is
    returned as-is — the daemon owns the device, and a local upload
    would be copied straight back to host for the wire."""
    if n > 1:
        from jax.sharding import PartitionSpec as P

        return _to_global(x, mesh, P("x"))
    if os.environ.get("TPK_SERVE_SOCKET"):
        return x
    import jax.numpy as jnp

    return jnp.asarray(x)


def _run_scan(xd, exclusive, n, mesh):
    if n > 1:
        from tpukernels.parallel.collectives import scan_dist

        return scan_dist(xd, mesh, exclusive=exclusive)
    return _dispatch("scan_exclusive" if exclusive else "scan", xd)


def _run_histogram(xd, nbins, n, mesh):
    if n > 1:
        from tpukernels.parallel.collectives import histogram_dist

        return histogram_dist(xd, nbins, mesh)
    return _dispatch("histogram", xd, nbins=int(nbins))


def _adapt_scan(p, arrs):
    x, out = arrs
    n, mesh = _mesh_ctx()
    xd = _upload_1d(x, n, mesh)
    np.copyto(
        out, _to_host(_run_scan(xd, bool(p.get("exclusive")), n, mesh))
    )


def _adapt_histogram(p, arrs):
    x, counts = arrs
    n, mesh = _mesh_ctx()
    xd = _upload_1d(x, n, mesh)
    np.copyto(counts, _to_host(_run_histogram(xd, int(p["nbins"]), n, mesh)))


def _adapt_scan_histogram(p, arrs):
    """Combined benchmark pass: one H2D of x feeds both halves (two
    separate dispatches would re-upload x — through the tunnel that
    doubles both the transfer bytes and the fixed dispatch cost inside
    the C driver's timed loop; a CUDA variant would likewise reuse the
    device-resident input)."""
    x, scan_out, counts = arrs
    n, mesh = _mesh_ctx()
    xd = _upload_1d(x, n, mesh)
    if n == 1 and not p.get("exclusive"):
        # single-device inclusive pass dispatches the registry's
        # combined kernel, so the TPK_SCANHIST_FUSE knob (and any
        # promoted tuning entry) rides the C path too — fuse=off
        # inside the wrapper IS the old two-kernel dispatch
        s, h = _dispatch(
            "scan_histogram", xd, nbins=int(p["nbins"])
        )
    else:
        s = _run_scan(xd, bool(p.get("exclusive")), n, mesh)
        h = _run_histogram(xd, int(p["nbins"]), n, mesh)
    np.copyto(scan_out, _to_host(s))
    np.copyto(counts, _to_host(h))


def _adapt_nbody(p, arrs):
    px, py, pz, vx, vy, vz, m = arrs
    n = _mesh_size()
    if n > 1:
        from tpukernels.parallel import make_mesh
        from tpukernels.parallel import collectives

        # TPK_NBODY_DIST picks the formulation: 'psum' (j-sharded
        # partial forces, the north-star's named scheme) or 'ring'
        # (i-sharded with j-blocks rotating via ppermute)
        variant = os.environ.get("TPK_NBODY_DIST", "psum")
        variants = {
            "psum": collectives.nbody_dist_psum,
            "ring": collectives.nbody_dist_ring,
        }
        if variant not in variants:
            raise ValueError(
                f"TPK_NBODY_DIST={variant!r}: expected one of "
                f"{sorted(variants)}"
            )
        fn = variants[variant]
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh(n)
        # the psum formulation replicates positions/velocities and
        # shards masses (force *sources*); the ring shards everything
        if variant == "psum":
            specs = (P(),) * 6 + (P("x"),)
        else:
            specs = (P("x"),) * 7
        state = tuple(
            _to_global(a, mesh, s)
            for a, s in zip((px, py, pz, vx, vy, vz, m), specs)
        )
        out = fn(
            state,
            int(p.get("steps", 1)),
            mesh,
            dt=p.get("dt", 1e-3),
            eps=p.get("eps", 1e-2),
        )
        for host, dev in zip((px, py, pz, vx, vy, vz), out):
            np.copyto(host, _to_host(dev))
    else:
        out = _dispatch(
            "nbody", px, py, pz, vx, vy, vz, m,
            dt=float(p.get("dt", 1e-3)),
            eps=float(p.get("eps", 1e-2)),
            steps=int(p.get("steps", 1)),
        )
        for host, dev in zip((px, py, pz, vx, vy, vz), out):
            np.copyto(host, np.asarray(dev))


_busbw_swept = False


def _maybe_busbw_sweep(mesh):
    """TPK_BUSBW_SWEEP=1 (SURVEY.md §3(d), zero new C flags): one
    `allreduce_bench --device=tpu` invocation per host also emits the
    swept message-size bus-bandwidth table — the metric of record on a
    pod — without needing `python -m tpukernels.parallel.busbw`
    alongside the C binary. Runs exactly once per process, on the
    driver's FIRST allreduce call (the untimed --check pass), so the
    timed reps that follow are undisturbed. TPK_BUSBW_MIN/MAX (sizes,
    e.g. 1K/64M), TPK_BUSBW_REPS and TPK_BUSBW_OP
    (allreduce|ppermute) tune the sweep."""
    global _busbw_swept
    if _busbw_swept or os.environ.get("TPK_BUSBW_SWEEP") != "1":
        return
    _busbw_swept = True
    from tpukernels.parallel.busbw import sweep_from_env

    sweep_from_env(mesh=mesh)


def _adapt_allreduce(p, arrs):
    import jax
    from jax.sharding import PartitionSpec as P

    from tpukernels.parallel import make_mesh
    from tpukernels.parallel.collectives import allreduce_sum
    from tpukernels.parallel.mesh import maybe_distributed_init

    x, out = arrs
    # multi-host pod runs (one C invocation per host, coordinator env
    # vars set) must join the job BEFORE device_count() reads the
    # topology; make_mesh repeats the (idempotent) call for every
    # other adapter. No-op without the coordinator env.
    maybe_distributed_init()
    ndev = _mesh_size() if "TPK_MESH" in os.environ else jax.device_count()
    mesh = make_mesh(ndev)
    _maybe_busbw_sweep(mesh)
    contrib = _to_global(
        np.broadcast_to(x, (ndev, x.shape[0])), mesh, P("x", None)
    )
    res = allreduce_sum(contrib, mesh)
    # every row is identical, so fetch ONE locally-addressable shard
    # row — a full-result D2H (let alone a cross-host gather) would
    # multiply the timed transfer cost ndev-fold for identical data
    np.copyto(out, np.asarray(res.addressable_shards[0].data)[0])


# Buffer indices each adapter WRITES (the driver-visible outputs the
# integrity guard scans). Inputs are deliberately excluded: a C caller
# may legitimately pass non-finite input data (masked elements,
# padding garbage) and a correct kernel must not be failed —
# let alone quarantined — for it. Unlisted kernels guard every buffer.
_OUTPUT_BUFFERS = {
    "vector_add": (1,),          # y (in/out)
    "sgemm": (2,),               # c (in/out)
    "stencil2d": (0,),           # x (in/out)
    "stencil3d": (0,),
    "scan": (1,),                # out
    "histogram": (1,),           # counts
    "scan_histogram": (1, 2),    # scan_out, counts
    "nbody": (0, 1, 2, 3, 4, 5),  # px..vz (m is input-only)
    "allreduce": (1,),           # out
}

_ADAPTERS = {
    "vector_add": _adapt_vector_add,
    "sgemm": _adapt_sgemm,
    "stencil2d": functools.partial(_adapt_stencil, "stencil2d"),
    "stencil3d": functools.partial(_adapt_stencil, "stencil3d"),
    "scan": _adapt_scan,
    "histogram": _adapt_histogram,
    "scan_histogram": _adapt_scan_histogram,
    "nbody": _adapt_nbody,
    "allreduce": _adapt_allreduce,
}


def run_from_c(kernel: str, params_json: str, addrs) -> int:
    _maybe_start_profiler()
    faults.capi_fault(kernel)  # single is-None check without a plan
    p = json.loads(params_json)
    specs = p.get("buffers", [])
    if len(specs) != len(addrs):
        raise ValueError(
            f"{kernel}: {len(addrs)} pointers but {len(specs)} buffer specs"
        )
    arrs = [_wrap(int(a), s) for a, s in zip(addrs, specs)]
    try:
        fn = _ADAPTERS[kernel]
    except KeyError:
        raise KeyError(
            f"no C adapter for kernel {kernel!r}; known: {sorted(_ADAPTERS)}"
        ) from None
    t0 = time.perf_counter()
    try:
        with trace.span(f"capi/{kernel}", kernel=kernel):
            fn(p, arrs)
    except Exception as e:  # noqa: BLE001 — journaled, then re-raised
        # the C host sees the exception through the shim; the journal
        # keeps a structured record even when the host's stderr is
        # lost (opt-in: no-op unless TPK_HEALTH_JOURNAL is set)
        obs_metrics.inc(f"capi.errors.{kernel}")
        journal.emit("capi_error", kernel=kernel, error=repr(e))
        raise
    # wall time includes H2D + compute + D2H — the same window the C
    # driver's timing loop sees (module docstring "honest timing");
    # clocked BEFORE the integrity guard so a canary check (a compile
    # + oracle run on first-trust/sampled calls) never inflates the
    # dispatch latency histogram
    wall_s = time.perf_counter() - t0
    # Output-integrity guard (docs/RESILIENCE.md §output integrity)
    # over the very buffers the C driver is about to trust — the
    # adapter-WRITTEN ones only (_OUTPUT_BUFFERS): tier-1 NaN/Inf
    # scan on every call, first-trust/sampled oracle canary for
    # registered kernels. Never raises — a corrupt result becomes a
    # journaled, quarantined event, and the C host still gets its
    # rc 0 (the shim's error contract is reserved for real failures).
    out_idx = _OUTPUT_BUFFERS.get(kernel)
    integrity.guard(
        "capi", kernel,
        [arrs[i] for i in out_idx if i < len(arrs)]
        if out_idx is not None else arrs,
    )
    obs_metrics.inc(f"capi.calls.{kernel}")
    obs_metrics.observe(f"capi.wall_s.{kernel}", wall_s)
    return 0
