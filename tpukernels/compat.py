"""Single choke point for JAX/Pallas API drift (the 0.4.x <-> 0.9 port).

The kernels were written against the newer Pallas TPU surface; the
environment they run in may ship an older jax. Every symbol that has
been renamed across that span is resolved HERE, once, so a drift hit
is one edit in this file instead of a sweep over every kernel module.
Kernel modules import ``pl``/``pltpu``/``CompilerParams`` from here and
never touch ``jax.experimental.pallas`` directly for drift-prone names.

Known drift resolved today:

- ``pltpu.CompilerParams`` (jax >= 0.7) vs ``pltpu.TPUCompilerParams``
  (jax 0.4.x, e.g. the 0.4.37 this container bakes in). Same fields
  either way (``dimension_semantics``, ``vmem_limit_bytes``, ...), so
  the alias is a plain name fix, not an adapter.
- ``jax.shard_map`` (jax >= 0.6 top-level export, ``check_vma``
  kwarg) vs ``jax.experimental.shard_map.shard_map`` (0.4.x,
  ``check_rep`` kwarg). :func:`shard_map` resolves the import AND
  translates the kwarg, so ``parallel/collectives.py`` states the
  modern surface once. This was the root cause of the 37 pre-existing
  ``test_distributed``/``test_graft_entry`` tier-1 failures: every
  collective import died on ``from jax import shard_map`` before any
  fake-device logic even ran.
- ``jax.lax.pcast`` (jax >= 0.7 varying-type system). 0.4.x has no
  device-varying type distinction, so the cast is simply unnecessary
  there: :func:`pcast` forwards when the primitive exists and returns
  the operand unchanged when it does not.

Import-order note: this module imports jax, so it must NOT be imported
by ``import tpukernels`` (registry stays lazy / jax-free). Only kernel
modules and other already-jax-bound code may import it.
"""

from __future__ import annotations

import inspect
import os

import jax
from jax.experimental import pallas as pl  # noqa: F401  (re-export)
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (re-export)

# the rename: prefer the current name, fall back to the 0.4.x one
CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)
if CompilerParams is None:  # pragma: no cover - would mean a 3rd rename
    raise ImportError(
        "jax.experimental.pallas.tpu exposes neither CompilerParams nor "
        "TPUCompilerParams - a new Pallas API drift; teach "
        "tpukernels/compat.py the new name"
    )

# shard_map: top-level on new jax, experimental on 0.4.x
if hasattr(jax, "shard_map"):
    _shard_map_impl = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_impl

# the replication-check kwarg rename: check_vma (new) vs check_rep
# (0.4.x). Introspect once so the adapter below never guesses.
_SHARD_MAP_KWARGS = set(
    inspect.signature(_shard_map_impl).parameters
)


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None):
    """``jax.shard_map`` with the modern signature on any jax.

    ``check_vma`` (None = backend default) is translated to the 0.4.x
    ``check_rep`` spelling when that is what the installed jax takes —
    same semantics either way: False disables the replication/varying
    type check for programs (the psum-of-replicated N-body) that are
    intentionally outside it.
    """
    kw = {}
    if check_vma is not None:
        if "check_vma" in _SHARD_MAP_KWARGS:
            kw["check_vma"] = check_vma
        elif "check_rep" in _SHARD_MAP_KWARGS:
            kw["check_rep"] = check_vma
        # neither kwarg: a future jax dropped the knob — run with its
        # default rather than erroring on a check we only ever relax
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )


def ensure_cpu_collectives() -> None:
    """Enable cross-process collectives on the CPU backend.

    Newer jax defaults ``jax_cpu_collectives_implementation`` to gloo;
    0.4.x ships it off, so a multi-process fake-device job dies with
    "Multiprocess computations aren't implemented on the CPU backend"
    at the first psum. Call BEFORE ``jax.distributed.initialize`` on a
    CPU-platform job (the fake-device test rigs and dev-box pod
    rehearsals; real pods run the TPU backend and never enter this).
    """
    if os.environ.get("JAX_PLATFORMS", "").split(",")[0] != "cpu":
        return
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # noqa: BLE001
        pass  # option gone = a jax where gloo is already the default


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` where it exists; on 0.4.x
    (which never grew the predicate) the same answer read off the
    distributed client's global state — the idempotence guard
    ``mesh.maybe_distributed_init`` needs either way."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return fn()
    try:  # the 0.4.x spelling of "has initialize() already run"
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:  # noqa: BLE001 — treat unknowable as "no"
        return False


def pcast(x, axes, to: str):
    """``jax.lax.pcast`` where it exists; identity where the installed
    jax predates the varying-type system (0.4.x) and the cast has
    nothing to do."""
    fn = getattr(jax.lax, "pcast", None)
    if fn is None:
        return x
    return fn(x, axes, to=to)
