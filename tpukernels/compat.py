"""Single choke point for JAX/Pallas API drift (the 0.4.x <-> 0.9 port).

The kernels were written against the newer Pallas TPU surface; the
environment they run in may ship an older jax. Every symbol that has
been renamed across that span is resolved HERE, once, so a drift hit
is one edit in this file instead of a sweep over every kernel module.
Kernel modules import ``pl``/``pltpu``/``CompilerParams`` from here and
never touch ``jax.experimental.pallas`` directly for drift-prone names.

Known drift resolved today:

- ``pltpu.CompilerParams`` (jax >= 0.7) vs ``pltpu.TPUCompilerParams``
  (jax 0.4.x, e.g. the 0.4.37 this container bakes in). Same fields
  either way (``dimension_semantics``, ``vmem_limit_bytes``, ...), so
  the alias is a plain name fix, not an adapter.

Import-order note: this module imports jax, so it must NOT be imported
by ``import tpukernels`` (registry stays lazy / jax-free). Only kernel
modules and other already-jax-bound code may import it.
"""

from __future__ import annotations

from jax.experimental import pallas as pl  # noqa: F401  (re-export)
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (re-export)

# the rename: prefer the current name, fall back to the 0.4.x one
CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)
if CompilerParams is None:  # pragma: no cover - would mean a 3rd rename
    raise ImportError(
        "jax.experimental.pallas.tpu exposes neither CompilerParams nor "
        "TPUCompilerParams - a new Pallas API drift; teach "
        "tpukernels/compat.py the new name"
    )
