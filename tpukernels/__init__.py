"""tpukernels — TPU-native rebuild of the `anonyomous4/parallel-c-programs` suite.

A self-checking parallel-kernel benchmark framework in which every kernel
(SAXPY vector add, tiled SGEMM, 2D/3D Jacobi stencil, prefix-scan +
histogram, O(N^2) direct N-body) has a JAX/Pallas TPU implementation,
reached from a plain-C benchmark driver through a C-ABI shim
(`c/shim/tpu_shim.c`), and whose multi-node collectives are
`jax.lax.psum`/`ppermute` over a `jax.sharding.Mesh` (ICI/DCN) instead
of MPI.

Layer map (see SURVEY.md §1–§2; the reference tree was empty at survey
time, so component numbers C1–C12 refer to SURVEY.md §2's inventory):

- ``tpukernels.kernels``  — Pallas kernel variants (C4–C8 equivalents)
- ``tpukernels.parallel`` — mesh / collectives / bus-bw harness (C9)
- ``tpukernels.registry`` — name -> jitted callable (the TPU column of
  the C dispatch table, C3)
- ``tpukernels.capi``     — marshalling layer the C shim (C10) imports
- ``tpukernels.utils``    — shape/tiling helpers (slope timing for the
  metrics lives in ``bench.py``; C timers are C12)
"""

__version__ = "0.1.0"

from tpukernels import registry  # noqa: F401
