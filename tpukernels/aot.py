"""AOT precompile + persistent executable cache (docs/PERF.md
§compile discipline).

On the flapping axon tunnel the scarce healthy windows were partly
burned on XLA compilation: every new process re-traced and re-compiled
every kernel from scratch, the old revalidate step 0 hand-prewarmed
only stencil3d, and one slab-compile experiment "sent compile times
through the roof and once wedged the remote-compile tunnel for hours"
(docs/PERF.md). This module makes compilation a cached, ahead-of-time,
per-kernel-accounted phase so chip minutes go to measuring:

- **One choke point** — :func:`compile_jitted` is the only place the
  repo lowers-and-compiles a program it intends to reuse. It splits
  the wall into an ``aot/lower/<name>`` span (tracing + lowering,
  never cacheable) and an ``aot/compile/<name>`` span (the XLA backend
  compile — exactly the part JAX's persistent compilation cache under
  ``.jax_cache/`` elides on a warm start), journals ``aot_hit`` /
  ``aot_miss`` evidence, and feeds compile-wall metrics.
- **Per-process executable memo** — :func:`run_cached` /
  :func:`registry.dispatch` give bench, ``capi.run_from_c`` and the
  tuning sweep one compiled executable per (kernel, shape, dtype,
  statics) per process instead of up to three independent jit caches
  compiling the same program.
- **Persistent manifest** — ``.jax_cache/aot.json`` records which keys
  have been compiled, under which jax version and kernel-source
  commit, with measured lower/compile walls. Keys follow the tuning
  cache's scheme (``kernel|shape|dtype|device_kind``) and are
  validated at read time the same way: a stale entry (jax upgraded, a
  commit touching the kernel's sources) is LOUDLY rejected
  (``aot_rejected`` stderr note + journal event) and the key is
  treated as cold — never silently trusted. The manifest is evidence
  ("a warm executable should exist; expect the compile span to be
  cheap"), the XLA cache is the store; disagreement between them shows
  up as an ``aot_hit`` event with a cold-sized ``compile_s``.
- **Prewarm** — :func:`precompile` / :func:`prewarm_all` compile every
  registered benchmark config from :data:`BENCH_CONFIGS` avatars
  (``jax.ShapeDtypeStruct`` — no operands, nothing executes), so
  ``tools/prewarm.py`` can fill the cache off-window and a healthy
  window opens hot.

``TPK_AOT_CACHE=0`` (or ``off``/``none``) disables the layer cleanly:
:func:`registry.dispatch` falls through to the plain eager wrapper,
bench's ``_slope`` keeps its old warm-call compile, no manifest is
read or written, and no ``aot_*`` event is emitted — clean-path bench
stdout is byte-identical either way (tests/test_aot.py proves it the
same way the fault and trace layers are proven).

Stdlib-only at import time (jax loads lazily inside the compile
paths), like the tuning and obs layers.
"""

from __future__ import annotations

import os
import sys
import time

from tpukernels import _cachedir
from tpukernels.obs import metrics as obs_metrics
from tpukernels.obs import trace
from tpukernels.resilience import journal

_DISABLED = ("0", "off", "none")

# per-process caches (reset() for tests)
_EXEC_MEMO: dict = {}     # (name, avals_key, statics_key) -> executable
_JIT_MEMO: dict = {}      # (id-keyed fn, statics names) -> jitted wrapper
_MANIFEST_MEMO: dict = {} # path -> (stat_key, parsed)
_REJECT_NOTED: set = set()


def enabled() -> bool:
    raw = os.environ.get("TPK_AOT_CACHE")
    return raw is None or raw.strip().lower() not in _DISABLED


def manifest_path() -> str:
    return _cachedir.aot_manifest_path()


def reset():
    """Drop per-process state (tests only — real processes want the
    memo to live exactly as long as the backend client does)."""
    global _TUNABLE_ENVS
    _EXEC_MEMO.clear()
    _JIT_MEMO.clear()
    _MANIFEST_MEMO.clear()
    _REJECT_NOTED.clear()
    _TUNING_TOKEN_MEMO.clear()
    _TUNABLE_ENVS = None


# ------------------------------------------------------------------ #
# keys                                                               #
# ------------------------------------------------------------------ #

def _aval_of(x):
    """(shape_tuple, dtype_str) for a concrete array, a ShapeDtypeStruct
    avatar, or a host scalar (canonicalized the way jnp.asarray will)."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        # host scalar: the dispatch path canonicalizes these to f32/i32
        # before tracing, so the key must agree
        if isinstance(x, bool):
            return ((), "bool")
        if isinstance(x, int):
            return ((), "int32")
        return ((), "float32")
    return (tuple(int(d) for d in shape), str(dtype))


def _avals_key(args) -> tuple:
    return tuple(_aval_of(a) for a in args)


def _statics_key(statics: dict) -> tuple:
    return tuple(sorted(statics.items()))


def device_kind() -> str:
    """Canonical backend device kind — same spelling as the tuning
    cache so the two caches' keys line up in reports."""
    from tpukernels.tuning import cache as tcache

    return tcache.device_kind()


# program-selecting env knobs that are not declared Tunables (the
# TUNABLES env names are collected from the registry)
_EXTRA_PROGRAM_ENV = ("TPK_SGEMM_PRECISION",)
_TUNABLE_ENVS: set | None = None  # memoized name set (values read live)


def _tunable_env_names() -> set:
    global _TUNABLE_ENVS
    if _TUNABLE_ENVS is not None:
        return _TUNABLE_ENVS
    names = set(_EXTRA_PROGRAM_ENV)
    try:
        from tpukernels import registry

        for k in registry.tunable_kernels():
            for t in registry.tunables(k).tunables:
                names.add(t.env)
    except Exception:
        # a failed kernel-import group must not take the AOT layer
        # down; the un-memoized partial set retries next call
        return names
    _TUNABLE_ENVS = names
    return names


_TUNING_TOKEN_MEMO: dict = {}  # path -> (stat_key, token)


def _tuning_cache_token() -> str:
    """Content identity of the tuning cache file, or "" when the cache
    is disabled/absent. Tuned params resolve inside the kernel at
    trace time with the same key-invisibility as env knobs (precedence
    env > tuned-cache > default), so an autotune PROMOTION changes the
    compiled program under otherwise-unchanged keys — without this
    token the first post-promotion compile would claim ``aot_hit``
    while paying a full cold compile. One whole-file digest (not
    per-kernel): promotions are rare, and over-invalidating toward
    "miss" is the honest direction."""
    from tpukernels.tuning import cache as tcache

    if not tcache.enabled():
        return ""
    p = tcache.path()
    try:
        st = os.stat(p)
    except OSError:
        return ""
    stat_key = (st.st_mtime_ns, st.st_size)
    memo = _TUNING_TOKEN_MEMO.get(p)
    if memo and memo[0] == stat_key:
        return memo[1]
    import hashlib

    try:
        with open(p, "rb") as f:
            digest = hashlib.md5(f.read()).hexdigest()[:10]
    except OSError:
        return ""
    token = f"tuned={digest}"
    _TUNING_TOKEN_MEMO[p] = (stat_key, token)
    return token


def tunable_env_fingerprint() -> str:
    """Everything that selects a different compiled program at the
    SAME shapes without showing up in the operand avals: the set
    tunable TPK_* knobs (block geometries, impl choices —
    docs/TUNING.md) plus the tuning-cache content token. An autotune
    candidate at rows=256 is a different program than rows=512, and
    calling its compile a "hit" because the default-rows entry exists
    would overstate the sweep's warmth (and a process-local memo
    ignoring these would serve stale executables after an env flip or
    a mid-process promotion)."""
    parts = sorted(
        f"{n}={os.environ[n]}"
        for n in _tunable_env_names()
        if n in os.environ
    )
    token = _tuning_cache_token()
    if token:
        parts.append(token)
    return ",".join(parts)


def cache_key(name: str, args, statics=None, kind=None) -> str:
    """``kernel|shape|dtype|device_kind`` — the tuning cache's key
    scheme. Multi-operand programs join per-operand shapes/dtypes with
    ``+``; static params ride on the kernel field (``histogram@nbins=
    256``) because they select a different program, not a different
    operand layout."""
    if kind is None:
        kind = device_kind()
    avals = _avals_key(args)
    shapes = "+".join(
        "x".join(str(d) for d in s) if s else "-" for s, _dt in avals
    )
    dtypes = sorted({dt for _s, dt in avals})
    field = name
    if statics:
        field += "@" + ",".join(
            f"{k}={v}" for k, v in _statics_key(statics)
        )
    env_fp = tunable_env_fingerprint()
    if env_fp:
        field += "@" + env_fp
    return "|".join((field, shapes or "-", "+".join(dtypes) or "-", kind))


# ------------------------------------------------------------------ #
# the persistent manifest                                            #
# ------------------------------------------------------------------ #

def _load_manifest(p: str) -> dict:
    """Parsed manifest via the shared stat-memoized tolerant reader
    (``_cachedir.read_json_memoized``) — {} when absent/corrupt: an
    unreadable manifest degrades to cold-cache behavior, never raises
    (the tuning cache's contract)."""
    return _cachedir.read_json_memoized(p, _MANIFEST_MEMO)


def _reject(key: str, reason: str, **fields):
    """Loud-rejection contract shared with the tuning cache: surfaced
    (counter + stderr + ``aot_rejected`` journal event) once per
    process per cause. Unlike the tuning cache's per-occurrence
    counting (a hot dispatch loop is a volume signal there), a stale
    AOT entry is legitimately validated twice per precompile (the
    ``expected`` probe + the choke point) — counting occurrences
    would double every rejection in the metrics snapshot."""
    memo = (key, reason)
    if memo in _REJECT_NOTED:
        return
    _REJECT_NOTED.add(memo)
    obs_metrics.inc("aot.rejections")
    print(f"# aot-cache rejected: {key} ({reason})", file=sys.stderr)
    journal.emit("aot_rejected", key=key, reason=reason, **fields)


def manifest_entry(key: str, sources=()) -> dict | None:
    """The validated manifest entry for ``key``, or None when absent /
    stale. Validation mirrors the tuning cache: jax version must match
    and no commit touching ``sources`` may postdate the entry's
    ``source_sha`` (outside git the sha check degrades to
    version-scoped). A stale entry is rejected loudly and treated as
    cold — the XLA cache may well still hold the old executable, and
    trusting it would hand a pre-change kernel's compile to a
    post-change benchmark."""
    entry = _load_manifest(manifest_path()).get("entries", {}).get(key)
    if not isinstance(entry, dict):
        return None
    import jax

    if entry.get("jax") != jax.__version__:
        _reject(
            key,
            f"compiled under jax {entry.get('jax')}, "
            f"running {jax.__version__}",
        )
        return None
    if sources:
        from tpukernels.tuning import cache as tcache

        sha = tcache.source_sha(tuple(sources))
        if sha is not None and entry.get("source_sha") not in (None, sha):
            _reject(
                key,
                "stale: a commit touching "
                + ",".join(sources)
                + " postdates this entry",
                entry_sha=entry.get("source_sha"),
                current_sha=sha,
            )
            return None
    return entry


def _record(key: str, sources, lower_s: float, compile_s: float):
    """Atomically upsert one manifest entry (flock-serialized
    read-modify-write, same discipline as tuning.cache.put)."""
    import fcntl

    from tpukernels.resilience import atomic
    from tpukernels.tuning import cache as tcache

    p = manifest_path()
    d = os.path.dirname(p)
    if d:
        os.makedirs(d, exist_ok=True)
    import jax

    entry = {
        "jax": jax.__version__,
        "source_sha": tcache.source_sha(tuple(sources)) if sources else None,
        "git_head": journal.git_head(),
        "lower_s": round(lower_s, 6),
        "compile_s": round(compile_s, 6),
        "recorded": round(time.time(), 3),
    }
    with open(f"{p}.lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        _MANIFEST_MEMO.pop(p, None)
        data = _load_manifest(p)
        data.setdefault("entries", {})[key] = entry
        # fsync'd tmp+rename (docs/RESILIENCE.md §atomic state)
        atomic.dump_json(p, data)
    _MANIFEST_MEMO.pop(p, None)
    return entry


# ------------------------------------------------------------------ #
# the compile choke point                                            #
# ------------------------------------------------------------------ #

def compile_jitted(name: str, jitted, args, statics=None, sources=()):
    """Lower and compile one jitted program ahead of time; returns the
    compiled executable (callable with the traced args; statics are
    baked in).

    THE choke point: every reusable compile in the repo runs through
    here so the wall is split into its cacheable and uncacheable
    halves — ``aot/lower/<name>`` (tracing + lowering; re-paid every
    process) and ``aot/compile/<name>`` (the XLA backend compile; a
    warm ``.jax_cache`` turns it into a disk read) — and every compile
    leaves ``aot_hit``/``aot_miss`` journal evidence with both walls.
    "hit" means the persistent manifest held a validated entry for the
    key, i.e. a prior process compiled this exact program under the
    same jax + kernel sources and the XLA cache should serve it; the
    recorded ``compile_s`` is the ground truth either way.
    """
    statics = statics or {}
    key = cache_key(name, args, statics)
    prior = manifest_entry(key, sources) if enabled() else None
    t0 = time.perf_counter()
    with trace.span(f"aot/lower/{name}"):
        lowered = jitted.lower(*args, **statics)
    t1 = time.perf_counter()
    with trace.span(f"aot/compile/{name}"):
        compiled = lowered.compile()
    t2 = time.perf_counter()
    lower_s, compile_s = t1 - t0, t2 - t1
    obs_metrics.inc("aot.compiles")
    obs_metrics.observe("aot.lower_wall_s", lower_s)
    obs_metrics.observe("aot.compile_wall_s", compile_s)
    if enabled():
        if prior is not None:
            obs_metrics.inc("aot.hits")
            journal.emit(
                "aot_hit", key=key, lower_s=round(lower_s, 6),
                compile_s=round(compile_s, 6),
                prior_compile_s=prior.get("compile_s"),
            )
        else:
            obs_metrics.inc("aot.misses")
            journal.emit(
                "aot_miss", key=key, lower_s=round(lower_s, 6),
                compile_s=round(compile_s, 6),
            )
        _record(key, sources, lower_s, compile_s)
    return compiled


def invalidate_kernel(name: str, prefixes=()) -> dict:
    """Drop every compiled-executable trace of one kernel: its
    per-process executable/jit memo entries and its persistent
    manifest rows (key base field == ``name``, statics variants
    included). ``prefixes`` additionally drops manifest rows whose
    base field starts with any of them — how a bench-site integrity
    failure also invalidates the metric's loop-program entries
    (``bench_sgemm.R50@...``), which are the executables that actually
    produced the corrupt warm result. Called by the output-integrity
    guard (resilience/integrity.py) when a kernel's result fails a
    check — the next dispatch/bench recompiles from source instead of
    re-trusting a suspect executable, and no later process reads the
    manifest as warm-cache evidence for it. Returns
    ``{"memo_dropped": n, "manifest_dropped": [keys]}`` for the
    journal record."""
    def _matches(key: str) -> bool:
        base = key.split("|", 1)[0]
        return base.split("@", 1)[0] == name or any(
            base.startswith(p) for p in prefixes
        )

    # base-name match splits on "@" so a kernel's mesh-tier variants
    # (registry.dispatch_mesh memoizes under "<name>@mesh<n>") drop
    # with it — in the in-process memos here exactly as in the
    # manifest rows below
    memo_keys = [k for k in _EXEC_MEMO
                 if k[0].split("@", 1)[0] == name]
    for k in memo_keys:
        _EXEC_MEMO.pop(k, None)
    for k in [k for k in _JIT_MEMO
              if k[0].split("@", 1)[0] == name]:
        _JIT_MEMO.pop(k, None)
    dropped: list = []
    if enabled():
        p = manifest_path()
        if os.path.exists(p):
            def _mutate(data):
                entries = data.get("entries") or {}
                dropped.extend(k for k in entries if _matches(k))
                for k in dropped:
                    entries.pop(k, None)

            def _load(path):
                _MANIFEST_MEMO.pop(path, None)
                return _load_manifest(path)

            _cachedir.locked_json_update(p, _mutate, load=_load)
            _MANIFEST_MEMO.pop(p, None)
    obs_metrics.inc("aot.invalidations")
    journal.emit(
        "aot_invalidated", kernel=name,
        memo_dropped=len(memo_keys), manifest_dropped=dropped,
    )
    return {"memo_dropped": len(memo_keys), "manifest_dropped": dropped}


# ------------------------------------------------------------------ #
# registry-level executable memo                                     #
# ------------------------------------------------------------------ #

# Per-kernel sources for manifest staleness — the same files whose
# commits gate bench evidence (bench._METRIC_KERNEL_SOURCES) and
# tuning-cache entries (TUNABLES.sources). tests/test_aot.py asserts
# every BENCH_CONFIGS kernel has a row.
KERNEL_SOURCES = {
    "vector_add": ("tpukernels/kernels/vector_add.py",),
    "sgemm": ("tpukernels/kernels/sgemm.py",),
    "stencil2d": ("tpukernels/kernels/stencil.py",),
    "stencil3d": ("tpukernels/kernels/stencil.py",),
    "scan": ("tpukernels/kernels/scan.py",),
    "scan_exclusive": ("tpukernels/kernels/scan.py",),
    "histogram": ("tpukernels/kernels/histogram.py",),
    "scan_histogram": (
        "tpukernels/kernels/scan_histogram.py",
        "tpukernels/kernels/scan.py",
        "tpukernels/kernels/histogram.py",
    ),
    "nbody": ("tpukernels/kernels/nbody.py",),
}


def _jitted_wrapper(name: str, fn, statics: dict):
    """One memoized ``jax.jit`` wrapper per (kernel, static-name-set)
    per process — bench children, capi dispatches and precompile must
    share the SAME wrapper object or each would key its own jit cache
    (the PR-2 lesson from ``bench._normal_generator``)."""
    import jax

    memo = (name, tuple(sorted(statics)))
    jitted = _JIT_MEMO.get(memo)
    if jitted is None:
        jitted = jax.jit(fn, static_argnames=tuple(sorted(statics)))
        _JIT_MEMO[memo] = jitted
    return jitted


def _ensure_executable(name: str, fn, args, statics: dict, sources):
    """The memo-or-compile step shared by dispatch and precompile —
    ONE construction of the memo key, so a future key component (the
    env fingerprint was added for exactly this reason) can never be
    applied to one entry path and not the other. The fingerprint is
    part of the memo: flipping a tunable knob mid-process
    (TPK_HIST_IMPL and friends) selects a different program and must
    never be served the old executable."""
    memo = (name, _avals_key(args), _statics_key(statics),
            tunable_env_fingerprint())
    exe = _EXEC_MEMO.get(memo)
    if exe is None:
        jitted = _jitted_wrapper(name, fn, statics)
        exe = compile_jitted(name, jitted, args, statics, sources)
        _EXEC_MEMO[memo] = exe
    return exe


def run_cached(name: str, fn, args, statics=None, sources=None):
    """Run one kernel call through the per-process executable memo:
    the first call at a given (shape, dtype, statics) compiles through
    :func:`compile_jitted`; every later call — from any entry path in
    the same process — reuses the compiled executable with zero
    re-trace and zero re-compile (tests assert exactly one compile per
    (kernel, shape, dtype) per process)."""
    statics = statics or {}
    if sources is None:
        sources = KERNEL_SOURCES.get(name, ())
    return _ensure_executable(name, fn, args, statics, sources)(*args)


# ------------------------------------------------------------------ #
# registered benchmark configs + prewarm                             #
# ------------------------------------------------------------------ #

# The configs of record (BASELINE.json "configs" / bench.py shapes),
# as ShapeDtypeStruct avatar specs: ("f32"|"i32", shape) operands plus
# the static params the C adapters pass. precompile() lowers these —
# nothing is allocated, nothing executes, so the whole registered
# suite precompiles on any host (CPU-provable; on a TPU host the same
# call fills the remote-compile cache off-window).
# These avatars are ALSO the serving daemon's default shape-bucket
# table (tpukernels/serve/bucketing.py, docs/SERVING.md): incoming
# requests are zero-padded up to the nearest avatar so client traffic
# lands on exactly the executables prewarm compiled — change a shape
# here and both the prewarm surface and the serving buckets move
# together.
BENCH_CONFIGS = {
    "vector_add": {
        "args": (("f32", ()), ("f32", (1 << 20,)), ("f32", (1 << 20,))),
        "statics": {},
    },
    "sgemm": {
        "args": (("f32", ()), ("f32", (1024, 1024)), ("f32", (1024, 1024)),
                 ("f32", ()), ("f32", (1024, 1024))),
        "statics": {},
    },
    "stencil2d": {
        "args": (("f32", (4096, 4096)),),
        "statics": {"iters": 8},
    },
    "stencil3d": {
        "args": (("f32", (384, 384, 384)),),
        "statics": {"iters": 8},
    },
    "scan": {
        "args": (("i32", (1 << 22,)),),
        "statics": {},
    },
    "scan_exclusive": {
        "args": (("i32", (1 << 22,)),),
        "statics": {},
    },
    "histogram": {
        "args": (("i32", (1 << 22,)),),
        "statics": {"nbins": 256},
    },
    "scan_histogram": {
        # the combined benchmark pass (capi's scan_histogram adapter /
        # bench_scan_hist); the fuse knob rides the env fingerprint so
        # fused and unfused precompile as distinct programs
        "args": (("i32", (1 << 22,)),),
        "statics": {"nbins": 256},
    },
    "nbody": {
        # dt/eps/steps mirror the C adapter's defaults so a capi
        # dispatch at the config of record reuses the precompiled
        # executable (statics are part of the memo key)
        "args": (("f32", (65536,)),) * 7,
        "statics": {"dt": 1e-3, "eps": 1e-2, "steps": 1},
    },
}


def _avatar_args(spec):
    import jax
    import jax.numpy as jnp

    dt = {"f32": jnp.float32, "i32": jnp.int32}
    return tuple(
        jax.ShapeDtypeStruct(shape, dt[kind])
        for kind, shape in spec["args"]
    )


def precompile(name: str) -> dict:
    """Compile one registered kernel's benchmark config ahead of time
    into the per-process memo + persistent cache. Returns a summary
    row ``{kernel, key, expected, lower_s, compile_s}`` (``expected``
    = hit/miss, what the manifest predicted before compiling). Raises
    KeyError for kernels without a registered config and RuntimeError
    when the layer is disabled — a prewarm that silently compiles
    nothing is worse than a loud refusal."""
    if not enabled():
        raise RuntimeError(
            "aot.precompile: TPK_AOT_CACHE=0 disables the AOT layer; "
            "unset it to prewarm"
        )
    try:
        spec = BENCH_CONFIGS[name]
    except KeyError:
        raise KeyError(
            f"kernel {name!r} has no registered benchmark config; "
            f"known: {sorted(BENCH_CONFIGS)}"
        ) from None
    from tpukernels import registry

    fn = registry.lookup(name)
    args = _avatar_args(spec)
    statics = dict(spec["statics"])
    sources = KERNEL_SOURCES.get(name, ())
    key = cache_key(name, args, statics)
    expected = "hit" if manifest_entry(key, sources) else "miss"
    t0 = time.perf_counter()
    _ensure_executable(name, fn, args, statics, sources)
    wall = time.perf_counter() - t0
    # first-trust smoke check (docs/RESILIENCE.md §output integrity):
    # a prewarm is exactly "a new process about to trust the warm
    # cache on this device_kind", and no dispatch follows it — so the
    # integrity canary runs HERE, and a failure invalidates the
    # executable that was just blessed instead of letting the next
    # healthy window measure garbage. No-op under TPK_INTEGRITY=0.
    from tpukernels.resilience import integrity

    integrity.aot_smoke(name)
    return {
        "kernel": name, "key": key, "expected": expected,
        "wall_s": round(wall, 6),
    }


def prewarm_all(names=None, echo=None):
    """Precompile every registered benchmark config (or the ``names``
    subset); returns a list of per-kernel rows — succeeded rows from
    :func:`precompile` plus ``{"kernel", "error"}`` rows for failures
    (one kernel's broken compile must not abort the rest of the
    prewarm; the caller decides whether that's fatal)."""
    echo = echo or (lambda line: None)
    rows = []
    for name in names if names is not None else sorted(BENCH_CONFIGS):
        try:
            row = precompile(name)
        except Exception as e:  # noqa: BLE001 — reported per kernel
            row = {"kernel": name, "error": repr(e)}
            echo(f"  {name:<16} FAILED: {e!r}")
        else:
            echo(
                f"  {name:<16} expected={row['expected']:<4} "
                f"wall={row['wall_s']:.3f}s"
            )
        rows.append(row)
    return rows
