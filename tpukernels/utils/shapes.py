"""Shape/tiling helpers shared by all Pallas kernels.

TPU tiling constraints (float32): last dim a multiple of 128 lanes,
second-to-last a multiple of 8 sublanes. Kernels pad/reshape 1-D
problem arrays into (rows, 128)-shaped 2-D arrays to satisfy them.
"""

from __future__ import annotations

import functools
import os

import jax

LANES = 128
SUBLANES_F32 = 8


def cdiv(a: int, b: int) -> int:
    return -(-a // b)


@functools.cache
def default_interpret() -> bool:
    """Run Pallas kernels in interpreter mode when no TPU is attached.

    Tests run on CPU (with fake devices for collectives); the real
    compiled path is exercised on the TPU chip. Override with
    TPU_KERNELS_INTERPRET=0/1.
    """
    env = os.environ.get("TPU_KERNELS_INTERPRET")
    if env is not None:
        return env not in ("0", "false", "False")
    return jax.default_backend() != "tpu"
