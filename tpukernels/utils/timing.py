"""Wall-clock timing of jitted callables, matching the C driver's rules.

The C benchmark driver (SURVEY.md C1/C12) owns the authoritative timing
loop; this module reproduces its discipline for the pure-Python path
(bench.py, busbw sweeps): warm up to exclude compile time, then time
repetitions with a monotonic clock, blocking on device completion inside
the timed region so GFLOPS are honest.
"""

from __future__ import annotations

import time

import jax


def time_jitted(fn, *args, reps: int = 10, warmup: int = 2):
    """Return (best_seconds_per_call, last_result)."""
    result = None
    for _ in range(max(warmup, 1)):
        result = jax.block_until_ready(fn(*args))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        result = jax.block_until_ready(fn(*args))
        t1 = time.perf_counter()
        best = min(best, t1 - t0)
    return best, result
