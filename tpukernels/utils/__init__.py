from tpukernels.utils.shapes import (  # noqa: F401
    cdiv,
    default_interpret,
)
