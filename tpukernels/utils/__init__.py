from tpukernels.utils.shapes import (  # noqa: F401
    cdiv,
    default_interpret,
)
from tpukernels.utils.timing import time_jitted  # noqa: F401
