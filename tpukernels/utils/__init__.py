from tpukernels.utils.shapes import (  # noqa: F401
    cdiv,
    round_up,
    pad_to_multiple,
    default_interpret,
)
from tpukernels.utils.timing import time_jitted  # noqa: F401
