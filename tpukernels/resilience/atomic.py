"""Crash-consistent file writes for the persisted JSON artifacts
(docs/RESILIENCE.md §atomic state).

Every validated artifact the repo persists — ``fleet.json``,
``tuning.json``, ``aot.json``, ``integrity.json``,
``integrity_quarantine.json``, ``slo.json``, the revalidate stamps —
was written tmp + ``os.replace``: atomic against CONCURRENT readers,
but not against a crash. ``os.replace`` only promises the directory
entry flips atomically; without an ``fsync`` of the data first, a
power cut (or a SIGKILL racing the page cache on some filesystems)
can leave the NEW name pointing at truncated or empty data. A fleet
that self-heals worker and router death (docs/SERVING.md) cannot
afford its config of record tearing under the same crash it is busy
surviving.

:func:`write_text`/:func:`dump_json` close the gap with the full
sequence — write tmp in the same directory, flush, ``fsync(fd)``,
``os.replace``, ``fsync(dir)`` — so a reader sees the old bytes or
the new bytes, never a torn file. The helpers are flock-compatible
(callers like ``_cachedir.locked_json_update`` keep their own
``.lock`` file serialization around the read-modify-write; this owns
only the write step) and stdlib-only, importable from the bottom of
the dependency stack (``tpukernels/_cachedir.py`` pulls it lazily,
inside the function, preserving its jax-free import contract).

The ``torn_write`` fault key (``tpukernels/resilience/faults.py``)
injects the crash this module defends against: a matching write
leaves a HALF-written tmp file and aborts before the rename — the
target must still read as the old state. ``tools/chaos.py`` fires it
against a live artifact; the per-artifact-family tests prove the
old-or-new contract in-process.
"""

from __future__ import annotations

import os

# written-but-unrenamed tmp suffix; fsck and humans can recognize and
# reap leftovers from a crash (or an injected torn_write) mid-write
TMP_SUFFIX_FMT = ".tmp.{pid}"


def _fsync_dir(path: str):
    """Persist the directory entry itself (the rename) — best effort:
    some filesystems refuse O_RDONLY dir fsync; the data fsync already
    happened, so degrading here loses only the rename's durability."""
    d = os.path.dirname(path) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def write_text(path: str, text: str):
    """Crash-consistent whole-file write: after this returns, ``path``
    holds ``text``; if the process dies at ANY point inside, ``path``
    holds whatever it held before. Raises OSError on write trouble."""
    from tpukernels.resilience import faults  # lazy: no import cycle

    tmp = path + TMP_SUFFIX_FMT.format(pid=os.getpid())
    data = text.encode("utf-8") if isinstance(text, str) else bytes(text)
    spec = faults.torn_write_fault(path)
    if spec is not None:
        faults.apply_torn_write(spec, path, tmp, data)
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        os.write(fd, data)
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, path)
    _fsync_dir(path)


def dump_json(path: str, obj, indent=1, sort_keys=True,
              trailing_newline=False):
    """The artifact writers' shared serialization + crash-consistent
    write (json is imported lazily — same reason as faults above)."""
    import json

    text = json.dumps(obj, indent=indent, sort_keys=sort_keys)
    if trailing_newline:
        text += "\n"
    write_text(path, text)
