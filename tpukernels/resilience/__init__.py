"""Unified resilience layer for the wedge-prone TPU path.

The axon PJRT tunnel's documented failure mode is FLAPPING: ~2-25
healthy minutes, then a mid-run wedge that HANGS rather than errors
(docs/NEXT.md, BASELINE.md status notes). The repo grew three separate
defenses against it — a SIGALRM guard, per-metric killable
subprocesses, a probe-retry patience loop — plus stderr-breadcrumb
postmortems. None of that was testable without a live chip. This
package makes the wedge-handling paths deterministic, observable and
regression-testable on CPU:

- ``faults``   — deterministic fault injection driven by the
  ``TPK_FAULT_PLAN`` env var (inline JSON or a path to a JSON file).
  Injection points are threaded through bench.py's probe/measure
  phases, ``registry._populate`` and ``capi.run_from_c``; with no plan
  set every injection point is a single ``is None`` check.
- ``watchdog`` — the one home for the three timeout mechanisms
  (SIGALRM soft guard, subprocess hard kill, probe retry patience)
  with explicit "slow vs wedged" classification semantics.
- ``journal``  — structured JSONL health-event log
  (``docs/logs/health_*.jsonl``) replacing grep-the-stderr
  postmortems; ``tools/health_report.py`` turns one into a narrative.
- ``supervisor`` — the checkpointed revalidation run-queue behind
  ``tools/revalidate.py``: crash-safe resume from an append-only
  JSONL checkpoint, per-day step quarantine after repeated wedges,
  flap-aware admission by value-per-chip-minute, backoff-scheduled
  probing. The shell queue scripts are thin wrappers over it.

Import-order contract: everything here is stdlib-only (no jax, no
numpy) so bench.py/capi.py can import it BEFORE jax, and
``import tpukernels`` stays jax-free. See docs/RESILIENCE.md.
"""

from tpukernels.resilience import (  # noqa: F401
    faults,
    journal,
    supervisor,
    watchdog,
)
