"""Deterministic fault injection for the wedge-prone TPU path.

``TPK_FAULT_PLAN`` holds either inline JSON or the path of a JSON
file; unset (the production case) makes every injection point a single
``_PLAN is None`` check — no dict lookups, no string compares — so the
hot paths (capi's C timing loop, bench's slope loop) pay nothing.

Plan schema (all keys optional; see docs/RESILIENCE.md for the full
contract and examples):

- ``"probe": ["hang", "hang", "ok"]`` — scripted liveness-probe
  outcomes, consumed one per probe ATTEMPT in the consuming process
  (the last entry repeats once exhausted). ``"ok"`` forces alive
  without spawning the probe subprocess, ``"hang"`` behaves as a
  probe timeout, ``"dead"`` as a probe error; anything else falls
  through to the real probe.
- ``"hang_probe": N`` — sugar: the first N probe attempts hang, later
  ones run the real probe.
- ``"wedge_metric": {"metric": "stencil3d_mcells_s", "phase":
  "execute"}`` — the bench child measuring that metric hangs at that
  phase (``operand`` | ``compile`` | ``execute``), immune to SIGALRM
  exactly like a wedged C-level PJRT call, so only the parent's hard
  kill can reap it. Omitting ``"metric"`` matches any metric;
  ``"phase"`` defaults to ``execute``. An optional ``"env": {"VAR":
  "value", ...}`` narrows the match to processes whose environment
  carries exactly those values — how the tuning chaos tests wedge ONE
  sweep candidate (candidates differ only by their TPK_* knobs) while
  its siblings run clean.
- ``"fail_metric": {...}`` — same matching, but raises instead of
  hanging (the child errors loudly — the NON-wedge failure mode).
- ``"fail_import": "nbody"`` — registry._populate's group containing
  that kernel name raises ImportError at load time.
- ``"fail_capi": "sgemm"`` / ``"wedge_capi": "sgemm"`` — the C-shim
  entry ``capi.run_from_c`` raises / hangs when dispatching that
  kernel.
- ``"kill_supervisor": "stepname"`` (or ``{"step": ...}``) — the
  revalidation supervisor SIGKILLs ITSELF right after checkpointing
  that step's ``step_start`` — the crash-safe-resume chaos proof.
- ``"slow_dispatch": {"kernel": "scan", "delay_s": 0.6, "every":
  20}`` — every ``every``-th ``registry.dispatch`` of that kernel
  sleeps ``delay_s`` before running: the latency-tail fault the SLO
  layer exists to catch (docs/OBSERVABILITY.md §latency SLOs). A
  slope/throughput metric barely moves (bench's ``_slope`` loop
  programs never pass through ``registry.dispatch``, and the mean
  shifts by delay/every) while the p99 of an open-loop load run
  breaches — the headline claim, CPU-proven in
  ``tests/test_slo.py``. ``kernel`` omitted matches any; ``every``
  defaults to 1; a bare string is ``{"kernel": ...}`` sugar; the
  same ``"env"`` clause as wedge_metric narrows the match.
- ``"delay_response": {"kernel": "scan", "delay_s": 0.6, "every": 1,
  "times": 0}`` — a matching serve WORKER holds its COMPLETED
  response on the floor for ``delay_s`` before sending: the
  slow-but-alive tail worker (dispatch done, delivery late) that the
  router's hedged dispatch exists to tolerate — the deterministic
  hedging chaos proof (docs/SERVING.md §hedged dispatch). Unlike
  ``slow_dispatch`` this fires AFTER the kernel ran, so a hedge that
  wins against it proves first-response-wins without duplicate side
  effects. ``kernel`` omitted matches any; ``every`` defaults to 1;
  ``times`` caps total firings (0 = unlimited, the default); a bare
  string is ``{"kernel": ...}`` sugar; the same ``"env"`` clause
  narrows to ONE fleet worker via its ``TPK_SERVE_WORKER_ID``.
- ``"wedge_dispatch": {"kernel": "scan", "times": 1}`` — the first
  ``times`` matching ``registry.dispatch`` calls WEDGE (the same
  SIGALRM-immune hang as ``wedge_metric``, but at the serving
  dispatch point): the serve daemon's worker-watchdog chaos proof —
  a wedged worker thread is abandoned, the request re-queued once,
  and the retry (past the ``times`` budget) runs clean
  (docs/SERVING.md §watchdog). ``times`` defaults to 1 (0 = every
  matching call); ``kernel`` omitted matches any; a bare string is
  ``{"kernel": ...}`` sugar; the same ``"env"`` clause narrows.
- ``"kill_worker": {"kernel": "scan", "on_call": 3}`` — the process
  SIGKILLs ITSELF on its ``on_call``-th matching ``registry.dispatch``
  (default 1): the serve fleet's dead-worker chaos proof — unlike
  ``wedge_dispatch`` (thread wedged, process alive, flock held) this
  is true process death, the pidfile flock releases and the health
  manager must detect, sweep, respawn and rejoin
  (docs/SERVING.md §self-healing). ``kernel`` omitted matches any; a
  bare string is ``{"kernel": ...}`` sugar; the same ``"env"`` clause
  narrows to ONE fleet worker via its ``TPK_SERVE_WORKER_ID``. An
  optional ``"once_file": path`` makes the kill one-shot ACROSS
  respawns (the file is created before dying; later incarnations see
  it and run clean) — without it every incarnation dies on its
  ``on_call``-th dispatch, which is exactly the crash-loop →
  quarantine proof.
- ``"corrupt_output": {"kernel": "sgemm", "site": "registry"}`` /
  ``"nan_output": {...}`` — the output-integrity guard
  (resilience/integrity.py) corrupts the guarded result it is about
  to check: ``corrupt`` perturbs the first element by a
  plausible-garbage delta (finite — only the oracle tiers can catch
  it), ``nan`` poisons the first FLOAT leaf with a NaN (the tier-1
  tripwire's prey; on a kernel with int-only outputs — scan,
  histogram — there is no NaN to write, so it degrades to the
  ``corrupt`` perturbation, which the canary tiers catch but tier 1
  cannot: target float kernels for tripwire proofs).
  ``kernel`` omitted matches any kernel; ``site`` (``registry`` |
  ``capi`` | ``bench`` | ``aot`` — the prewarm first-trust smoke;
  the tuning path is its candidates' bench children, so target it
  with site ``bench`` + an ``env`` clause) omitted matches any
  guarded path; a bare string is sugar for ``{"kernel": ...}``. The
  same ``"env"`` clause as wedge/fail_metric narrows to one autotune
  candidate. Because the guard's oracle canary runs through the same
  corruption point, an injected corruption is detectable — the
  detect → journal → quarantine chaos proof (docs/RESILIENCE.md
  §output integrity).

- ``"kill_router": {"on_call": 2}`` — the fleet ROUTER SIGKILLs
  itself on its ``on_call``-th accepted dispatch (default 1), AFTER
  the request's ``router.wal`` entry is durable and BEFORE it is
  forwarded: the router-death chaos proof — the guardian
  (``tpukernels/serve/guardian.py``) must detect the freed pidfile
  flock, sweep, respawn, and the respawned router must replay the
  journaled request (docs/SERVING.md §guardian). ``once_file`` works
  as for ``kill_worker`` (one-shot across respawns); the same
  ``"env"`` clause narrows. The injection point only exists in the
  router process, so a fleet-wide plan is already router-scoped.
- ``"torn_write": {"path_substr": "tuning.json", "on_call": 1}`` — a
  matching ``resilience/atomic.py`` write aborts MID-WRITE: half the
  payload lands in the tmp file, the rename never happens, and the
  process either raises (``"mode": "raise"``, the default — the
  in-process test shape) or SIGKILLs itself (``"mode": "kill"`` — the
  chaos-campaign crash shape). The target artifact must still read as
  the OLD state: the crash-consistency proof for every persisted
  JSON artifact (docs/RESILIENCE.md §atomic state). ``path_substr``
  omitted matches any atomic write; ``on_call`` counts matching
  writes per process (default 1); ``once_file`` and ``"env"`` narrow
  as for ``kill_worker``.

Fault state (probe script position, current metric) is per-process;
plans reach bench's ``--one`` children through env inheritance. Every
fired fault emits a ``fault_injected`` journal event so chaos runs are
self-describing in the health log.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import time

from tpukernels.resilience import journal


def _load_plan():
    raw = os.environ.get("TPK_FAULT_PLAN")
    if not raw or not raw.strip():
        return None
    if raw.lstrip()[:1] in ("{", "["):  # inline JSON (a non-object
        plan = json.loads(raw)          # still fails the check below)
    else:
        with open(raw) as f:
            plan = json.load(f)
    if not isinstance(plan, dict):
        raise ValueError(
            f"TPK_FAULT_PLAN must be a JSON object, got {type(plan).__name__}"
        )
    return plan


_PLAN = _load_plan()
_PROBE_IDX = 0       # probe attempts consumed (per process)
_CURRENT_METRIC = None  # set by bench's --one/--prewarm child entry
_DISPATCH_CALLS: dict = {}  # kernel -> dispatches seen (slow_dispatch)
_RESPONSE_CALLS: dict = {}  # kernel -> responses seen (delay_response)
_WEDGE_CALLS: dict = {}     # kernel -> dispatches seen (wedge_dispatch)
_KILL_CALLS: dict = {}      # kernel -> dispatches seen (kill_worker)
_ROUTE_CALLS = 0            # router admissions seen (kill_router)
_TORN_CALLS = 0             # matching atomic writes seen (torn_write)


def active() -> bool:
    return _PLAN is not None


def reload_plan():
    """Re-read TPK_FAULT_PLAN (tests flip the env mid-process; real
    runs load once at import). Resets per-process fault state."""
    global _PLAN, _PROBE_IDX, _CURRENT_METRIC, _ROUTE_CALLS, _TORN_CALLS
    _PLAN = _load_plan()
    _PROBE_IDX = 0
    _CURRENT_METRIC = None
    _DISPATCH_CALLS.clear()
    _RESPONSE_CALLS.clear()
    _WEDGE_CALLS.clear()
    _KILL_CALLS.clear()
    _ROUTE_CALLS = 0
    _TORN_CALLS = 0
    return _PLAN


def _wedge(desc: str):
    """Simulate a C-level wedge: the hang must survive the SIGALRM
    soft guard (signal handlers only run between Python bytecodes, and
    a real wedged PJRT call never yields one) so that only the
    subprocess-kill watchdog layer can end it — the exact signature
    bench.py's slow-vs-wedged classification keys on."""
    print(f"# fault: wedging ({desc})", file=sys.stderr, flush=True)
    try:
        signal.signal(signal.SIGALRM, signal.SIG_IGN)
    except ValueError:
        pass  # non-main thread: the sleep loop below still hangs
    while True:
        time.sleep(60)


def _fail(desc: str):
    raise RuntimeError(f"injected fault: {desc}")


def probe_outcome():
    """Scripted outcome for the next liveness-probe attempt, or None
    to run the real probe. One entry consumed per attempt."""
    global _PROBE_IDX
    if _PLAN is None:
        return None
    idx = _PROBE_IDX
    out = None
    script = _PLAN.get("probe")
    if script:
        out = script[min(idx, len(script) - 1)]
    elif idx < int(_PLAN.get("hang_probe", 0)):
        out = "hang"
    if out is None:
        return None
    _PROBE_IDX += 1
    journal.emit("fault_injected", site="probe", outcome=out, attempt=idx)
    return out


def enter_metric(name: str):
    """Record which bench metric this (child) process is measuring so
    phase_fault can match wedge_metric/fail_metric plans."""
    global _CURRENT_METRIC
    if _PLAN is None:
        return
    _CURRENT_METRIC = name


def phase_fault(phase: str):
    """Injection point for bench's measure phases (operand, compile,
    execute — the _slope breadcrumb phases)."""
    if _PLAN is None:
        return
    for key, action in (("wedge_metric", _wedge), ("fail_metric", _fail)):
        spec = _PLAN.get(key)
        if not spec:
            continue
        want = spec.get("metric")
        if want is not None and want != _CURRENT_METRIC:
            continue
        if spec.get("phase", "execute") != phase:
            continue
        want_env = spec.get("env")
        if want_env and any(
            os.environ.get(k) != v for k, v in want_env.items()
        ):
            # env-narrowed spec: this process is not the target
            continue
        journal.emit(
            "fault_injected",
            site="metric",
            fault=key,
            metric=_CURRENT_METRIC,
            phase=phase,
        )
        action(f"{key} {_CURRENT_METRIC or '<any>'}:{phase}")


def import_fault(kernels):
    """Injection point for registry._populate: raise when the plan's
    fail_import kernel belongs to the group being loaded."""
    if _PLAN is None:
        return
    want = _PLAN.get("fail_import")
    if want and want in kernels:
        journal.emit("fault_injected", site="import", kernels=list(kernels))
        raise ImportError(f"injected fault: fail_import {want}")


def supervisor_fault(step: str):
    """Injection point for the revalidation supervisor
    (resilience/supervisor.py): a ``"kill_supervisor"`` plan key —
    ``"stepname"`` or ``{"step": "stepname"}`` (omit the step to match
    any) — SIGKILLs the SUPERVISOR process itself at the worst instant
    for resume correctness: after ``step_start`` is durably
    checkpointed, before any outcome can be recorded. The crash-safe
    resume proof (tests/test_supervisor.py) re-runs without the plan
    and must converge without redoing green steps."""
    if _PLAN is None:
        return
    spec = _PLAN.get("kill_supervisor")
    if spec is None:
        return
    want = spec.get("step") if isinstance(spec, dict) else spec
    if want and want != step:
        return
    journal.emit("fault_injected", site="supervisor", step=step,
                 fault="kill_supervisor")
    print(f"# fault: SIGKILL supervisor mid-{step}", file=sys.stderr,
          flush=True)
    os.kill(os.getpid(), signal.SIGKILL)


def dispatch_fault(kernel: str):
    """Injection point for ``registry.dispatch``: a ``slow_dispatch``
    plan key delays every ``every``-th matching dispatch by
    ``delay_s`` — a latency-TAIL fault, invisible to slope throughput
    (which amortizes it) and exactly what the SLO layer's p99
    verdicts must catch. Counting is per (process, kernel): requests
    1..every-1 run clean, request ``every`` stalls.

    A ``wedge_dispatch`` key instead WEDGES the first ``times``
    matching dispatches (SIGALRM-immune, like ``wedge_metric``) —
    the serve daemon's worker-watchdog chaos proof: the wedged
    worker's request is re-queued once and its RETRY, past the
    ``times`` budget, runs clean."""
    if _PLAN is None:
        return
    kspec = _PLAN.get("kill_worker")
    if kspec:
        if isinstance(kspec, str):
            kspec = {"kernel": kspec}
        want = kspec.get("kernel")
        want_env = kspec.get("env")
        if (want is None or want == kernel) and not (
            want_env and any(
                os.environ.get(k) != v for k, v in want_env.items()
            )
        ):
            n = _KILL_CALLS[kernel] = _KILL_CALLS.get(kernel, 0) + 1
            once = kspec.get("once_file")
            if n == int(kspec.get("on_call", 1)) and not (
                    once and os.path.exists(once)):
                if once:
                    # mark BEFORE dying: the one-shot contract must
                    # hold even though nothing after the kill runs
                    with open(once, "w") as f:
                        f.write(f"{os.getpid()}\n")
                journal.emit(
                    "fault_injected", site="dispatch", kernel=kernel,
                    fault="kill_worker", call=n,
                )
                print(f"# fault: SIGKILL self mid-{kernel} dispatch "
                      f"(call {n})", file=sys.stderr, flush=True)
                os.kill(os.getpid(), signal.SIGKILL)
    wspec = _PLAN.get("wedge_dispatch")
    if wspec:
        if isinstance(wspec, str):
            wspec = {"kernel": wspec}
        want = wspec.get("kernel")
        want_env = wspec.get("env")
        if (want is None or want == kernel) and not (
            want_env and any(
                os.environ.get(k) != v for k, v in want_env.items()
            )
        ):
            n = _WEDGE_CALLS[kernel] = _WEDGE_CALLS.get(kernel, 0) + 1
            times = int(wspec.get("times", 1))
            once = wspec.get("once_file")
            if (times <= 0 or n <= times) and not (
                    once and os.path.exists(once)):
                if once:
                    # mark BEFORE wedging: the thread never returns,
                    # and a respawned worker (fresh counters) must
                    # not re-arm — the one-shot contract spans
                    # processes, same as kill_worker's
                    with open(once, "w") as f:
                        f.write(f"{os.getpid()}\n")
                journal.emit(
                    "fault_injected", site="dispatch", kernel=kernel,
                    fault="wedge_dispatch", call=n,
                )
                _wedge(f"wedge_dispatch {kernel} (call {n})")
    spec = _PLAN.get("slow_dispatch")
    if not spec:
        return
    if isinstance(spec, str):
        spec = {"kernel": spec}
    want = spec.get("kernel")
    if want is not None and want != kernel:
        return
    want_env = spec.get("env")
    if want_env and any(
        os.environ.get(k) != v for k, v in want_env.items()
    ):
        return
    n = _DISPATCH_CALLS[kernel] = _DISPATCH_CALLS.get(kernel, 0) + 1
    every = int(spec.get("every", 1))
    if every > 1 and n % every:
        return
    delay = float(spec.get("delay_s", 0.1))
    journal.emit(
        "fault_injected", site="dispatch", kernel=kernel,
        fault="slow_dispatch", delay_s=delay, call=n,
    )
    time.sleep(delay)


def response_fault(kernel: str):
    """Injection point for the serve daemon's response path
    (``server._finish``, AFTER the dispatch completed, BEFORE the
    send): a ``delay_response`` plan key holds a matching worker's
    finished response for ``delay_s`` — the slow-but-alive tail
    worker the hedged-dispatch chaos proof pins (the kernel already
    ran, so a winning hedge proves first-response-wins with zero
    duplicate side effects). Counting is per (process, kernel);
    ``times`` caps total firings (0 = unlimited)."""
    if _PLAN is None:
        return
    spec = _PLAN.get("delay_response")
    if not spec:
        return
    if isinstance(spec, str):
        spec = {"kernel": spec}
    want = spec.get("kernel")
    if want is not None and want != kernel:
        return
    if not _env_match(spec):
        return
    n = _RESPONSE_CALLS[kernel] = _RESPONSE_CALLS.get(kernel, 0) + 1
    every = int(spec.get("every", 1))
    if every > 1 and n % every:
        return
    times = int(spec.get("times", 0))
    if times > 0 and n > times * every:
        return
    delay = float(spec.get("delay_s", 0.1))
    journal.emit(
        "fault_injected", site="response", kernel=kernel,
        fault="delay_response", delay_s=delay, call=n,
    )
    print(f"# fault: delaying {kernel} response {delay}s (call {n})",
          file=sys.stderr, flush=True)
    time.sleep(delay)


def _env_match(spec: dict) -> bool:
    want_env = spec.get("env")
    return not (want_env and any(
        os.environ.get(k) != v for k, v in want_env.items()
    ))


def router_fault():
    """Injection point for the fleet router's accept path
    (``router._route``, AFTER the request's ``router.wal`` entry is
    durable, BEFORE the forward): ``kill_router`` SIGKILLs the router
    on its ``on_call``-th accepted dispatch — the kill_worker kill
    pattern (journal + stderr breadcrumb + SIGKILL self), ``once_file``
    one-shot across respawns included."""
    global _ROUTE_CALLS
    if _PLAN is None:
        return
    spec = _PLAN.get("kill_router")
    if not spec:
        return
    if not isinstance(spec, dict):
        spec = {}
    if not _env_match(spec):
        return
    _ROUTE_CALLS += 1
    n = _ROUTE_CALLS
    once = spec.get("once_file")
    if n != int(spec.get("on_call", 1)) or (
            once and os.path.exists(once)):
        return
    if once:
        # mark BEFORE dying: the one-shot contract must hold even
        # though nothing after the kill runs
        with open(once, "w") as f:
            f.write(f"{os.getpid()}\n")
    journal.emit("fault_injected", site="route", fault="kill_router",
                 call=n)
    print(f"# fault: SIGKILL self mid-route (call {n})",
          file=sys.stderr, flush=True)
    os.kill(os.getpid(), signal.SIGKILL)


def torn_write_fault(path: str):
    """Decision half of the ``torn_write`` key: the matching spec for
    this atomic write (``resilience/atomic.py`` applies it via
    :func:`apply_torn_write`), or None. Split so the decision and its
    counters live with every other plan key while the mechanics stay
    with the write they corrupt."""
    global _TORN_CALLS
    if _PLAN is None:
        return None
    spec = _PLAN.get("torn_write")
    if not spec:
        return None
    if isinstance(spec, str):
        spec = {"path_substr": spec}
    sub = spec.get("path_substr")
    if sub and sub not in path:
        return None
    if not _env_match(spec):
        return None
    _TORN_CALLS += 1
    n = _TORN_CALLS
    once = spec.get("once_file")
    if n != int(spec.get("on_call", 1)) or (
            once and os.path.exists(once)):
        return None
    return dict(spec, _call=n)


def apply_torn_write(spec: dict, path: str, tmp: str, data):
    """Mechanics half of ``torn_write``: strand HALF the payload in
    the tmp file (the torn bytes a real crash leaves), then abort
    before the rename — ``"mode": "raise"`` (default) raises OSError
    in-process, ``"mode": "kill"`` SIGKILLs self. Either way the
    target artifact keeps its OLD bytes."""
    once = spec.get("once_file")
    if once:
        with open(once, "w") as f:
            f.write(f"{os.getpid()}\n")
    with open(tmp, "wb") as f:
        f.write(bytes(data)[: max(1, len(data) // 2)])
        f.flush()
    n = spec.get("_call")
    journal.emit("fault_injected", site="atomic_write",
                 fault="torn_write", path=path, call=n)
    print(f"# fault: torn write mid-{os.path.basename(path)} "
          f"(call {n})", file=sys.stderr, flush=True)
    if spec.get("mode") == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    raise OSError(f"injected fault: torn_write on {path}")


def output_fault(site: str, kernel):
    """Injection point for the output-integrity guard
    (resilience/integrity.py): returns ``"nan"`` / ``"corrupt"`` when
    the plan wants this (site, kernel)'s guarded result corrupted, or
    None. The GUARD applies the corruption (it owns the result's
    representation); this only decides and journals — matching the
    single-`_PLAN is None`-check contract of every other point."""
    if _PLAN is None:
        return None
    for key, mode in (("nan_output", "nan"), ("corrupt_output", "corrupt")):
        spec = _PLAN.get(key)
        if not spec:
            continue
        if isinstance(spec, str):
            spec = {"kernel": spec}
        want = spec.get("kernel")
        if want is not None and want != kernel:
            continue
        want_site = spec.get("site")
        if want_site is not None and want_site != site:
            continue
        want_env = spec.get("env")
        if want_env and any(
            os.environ.get(k) != v for k, v in want_env.items()
        ):
            continue
        journal.emit(
            "fault_injected", site=f"output:{site}", kernel=kernel,
            fault=key,
        )
        return mode
    return None


def capi_fault(kernel: str):
    """Injection point for capi.run_from_c (the C shim's entry)."""
    if _PLAN is None:
        return
    if _PLAN.get("fail_capi") == kernel:
        journal.emit("fault_injected", site="capi", kernel=kernel,
                     fault="fail_capi")
        _fail(f"fail_capi {kernel}")
    if _PLAN.get("wedge_capi") == kernel:
        journal.emit("fault_injected", site="capi", kernel=kernel,
                     fault="wedge_capi")
        _wedge(f"wedge_capi {kernel}")
