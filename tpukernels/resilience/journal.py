"""Structured JSONL health-event log (docs/RESILIENCE.md §journal).

Every resilience-relevant decision — probe outcomes, watchdog fires,
slow-vs-wedged classifications, partial-result decisions, evidence
rejections, injected faults — is appended as one JSON line so a
flapping session can be reconstructed from the journal alone
(tools/health_report.py) instead of grepping stderr breadcrumbs.

Routing (``TPK_HEALTH_JOURNAL``):
- unset        — journaling DISABLED. Library contexts (the C shim's
  embedded interpreter, unit tests importing bench) stay silent;
  ``bench.py`` run as a CLI defaults the var to
  ``docs/logs/health_<date>.jsonl`` so its ``--one`` children inherit
  the same file and a whole run lands in one journal.
- ``0``/``off``/``none`` — explicitly disabled.
- a directory  — ``health_<date>.jsonl`` inside it.
- anything else — used verbatim as the journal file path.

Events are best-effort by design: a full disk or unwritable path must
degrade observability, never take down the run being observed. Each
record carries a wall-clock ISO timestamp, a unix ``t``, the emitting
``pid`` and the repo ``git_head`` sha, so artifacts and journal lines
from the same session can be correlated (the ISSUE's
"stamped with HEAD sha and wall clock").
"""

from __future__ import annotations

import datetime
import json
import os
import time

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_DISABLED = ("", "0", "off", "none")
_HEAD_CACHE: list = []  # [sha_or_None] once resolved (per process)


def git_head(root=None):
    """HEAD sha of `root` (default: this repo), or None outside a git
    repo / without git. Cached per process for the default root — the
    journal stamps every event and must not fork git each time."""
    import subprocess

    if root is None:
        if _HEAD_CACHE:
            return _HEAD_CACHE[0]
        root = _REPO
        cache = _HEAD_CACHE
    else:
        cache = None
    try:
        r = subprocess.run(
            ["git", "-C", root, "rev-parse", "HEAD"],
            capture_output=True,
            text=True,
            timeout=30,
        )
        sha = r.stdout.strip()
        sha = sha if r.returncode == 0 and sha else None
    except Exception:
        sha = None
    if cache is not None:
        cache.append(sha)
    return sha


def default_path():
    """Where bench.py's CLI entry routes the journal when the operator
    didn't choose: one file per day next to the bench artifacts."""
    return os.path.join(
        _REPO,
        "docs",
        "logs",
        f"health_{datetime.date.today().isoformat()}.jsonl",
    )


def resolve(raw):
    """Resolve one TPK_HEALTH_JOURNAL value to a file path, or None
    when it means "off". THE resolution rule — a directory value means
    a dated file inside it — shared with callers that resolve a
    CHILD's env rather than this process's (the tuning runner tails
    the file its bench children append to)."""
    if raw is None or raw.strip().lower() in _DISABLED:
        return None
    if os.path.isdir(raw):
        return os.path.join(
            raw, f"health_{datetime.date.today().isoformat()}.jsonl"
        )
    return raw


def path():
    """Resolved journal file path, or None when journaling is off.
    Re-read from the environment on every call: events are rare and
    tests (and bench children) retarget the journal via env."""
    return resolve(os.environ.get("TPK_HEALTH_JOURNAL"))


def enabled() -> bool:
    return path() is not None


def load_events(paths):
    """Parse events from JSONL journal files, in file order then line
    order; returns (events, bad_line_count). Tolerant by design —
    blank lines skipped, unparseable lines counted not fatal, missing
    files skipped — because a journal truncated by a crash is exactly
    when a postmortem reader needs whatever survives. The one loader
    behind tools/health_report.py and tools/obs_report.py."""
    events, bad = [], 0
    for p in paths:
        try:
            f = open(p)
        except OSError:
            continue
        with f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    bad += 1
                    continue
                if isinstance(rec, dict):
                    events.append(rec)
    return events, bad


def emit(kind: str, **fields):
    """Append one health event; never raises (observability must not
    become a new failure mode of the path it observes)."""
    p = path()
    if p is None:
        return
    now = time.time()
    rec = {
        "ts": datetime.datetime.fromtimestamp(now).isoformat(
            timespec="seconds"
        ),
        "t": round(now, 3),
        "pid": os.getpid(),
        "git_head": git_head(),
        "kind": kind,
    }
    rec.update(fields)
    try:
        d = os.path.dirname(p)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(p, "a") as f:
            f.write(json.dumps(rec, default=repr) + "\n")
    except OSError:
        pass
