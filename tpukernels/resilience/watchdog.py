"""The one home for the repo's three timeout mechanisms.

bench.py grew three divergent defenses against the flapping axon
tunnel; each now lives here once, with its semantics written down:

1. ``run_with_alarm`` — SIGALRM soft guard. Interrupts PURE-PYTHON
   slowness only: signal handlers run between Python bytecodes, so a
   hung C-level PJRT call (the real wedge mode, observed 2026-07-31)
   sails straight past it. Use it as a second layer inside a process
   something else can kill, never as the only defense.
2. ``kill_after`` — subprocess hard kill. The only mechanism that
   ends a true wedge: the child is killable from outside regardless
   of where it hangs. Anything that might touch the tunnel for real
   runs under this.
3. ``patient_probe`` — retry/backoff patience for liveness probes.
   Tunnel outages of 10+ minutes recover, so probes retry with a
   deliberate wait; a DEFINITIVE answer ("no TPU configured on this
   box") aborts the patience early — waiting cannot conjure hardware.

Slow vs wedged (``classify_timeout``): after a hard-kill fires, one
quick liveness re-probe decides which world we are in. Probe answers
→ the child was merely SLOW (the tunnel is fine; later work may
proceed). Probe fails → the tunnel WEDGED mid-run (skip remaining
work immediately rather than burning a full watchdog window on each
item). Both verdicts are journaled, as is every watchdog fire.
"""

from __future__ import annotations

import signal
import subprocess
import sys
import time

from tpukernels.obs import metrics
from tpukernels.resilience import journal


class Timeout(Exception):
    """Raised by run_with_alarm when the SIGALRM guard fires."""


def run_with_alarm(fn, seconds: int, site: str | None = None):
    """Layer 1 (soft): run fn() under SIGALRM, raising Timeout after
    `seconds`. Restores the previous handler and cancels the alarm on
    every exit path — a stale alarm firing later would kill an
    innocent caller."""

    def handler(signum, frame):
        metrics.inc("watchdog.sigalrm_fires")
        journal.emit(
            "watchdog_fire", mechanism="sigalrm", site=site,
            timeout_s=seconds,
        )
        raise Timeout(f"exceeded {seconds}s")

    old = signal.signal(signal.SIGALRM, handler)
    signal.alarm(int(seconds))
    try:
        return fn()
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def kill_after(argv, timeout_s: float, site: str | None = None, **run_kw):
    """Layer 2 (hard): run `argv` as a killable subprocess. Returns
    (CompletedProcess, "ok") or (None, "timeout") once the kill fired.
    The caller interprets the child's returncode — a nonzero exit is
    the child failing LOUDLY, which is not a wedge."""
    try:
        proc = subprocess.run(argv, timeout=timeout_s, **run_kw)
    except subprocess.TimeoutExpired:
        metrics.inc("watchdog.kills")
        journal.emit(
            "watchdog_fire", mechanism="subprocess-kill", site=site,
            timeout_s=timeout_s, argv=[str(a) for a in argv[:4]],
        )
        return None, "timeout"
    return proc, "ok"


def patient_probe(
    probe_once,
    attempts: int,
    retry_wait_s: float,
    label: str = "probe",
):
    """Layer 3 (patience): retry `probe_once(attempt)` up to `attempts`
    times, sleeping `retry_wait_s` between goes. probe_once returns
    "alive" (stop: True), "dead" (stop: False — a definitive negative
    that waiting cannot fix), or "retry" (hang/error: patience
    continues). Exhausted patience is False."""
    for attempt in range(attempts):
        r = probe_once(attempt)
        if r == "alive":
            return True
        if r == "dead":
            return False
        print(
            f"# {label} failed (attempt {attempt + 1}/{attempts})",
            file=sys.stderr,
        )
        # structured twin of the stderr line: trend analysis needs to
        # separate "tunnel down" (probe retries, then nulls) from
        # "kernel slow" (clean probes, bad slope) without grepping
        backoff = retry_wait_s if attempt + 1 < attempts else 0.0
        metrics.inc("probe.retries")
        journal.emit(
            "probe_failed", label=label, attempt=attempt + 1,
            attempts=attempts, backoff_s=backoff,
        )
        if attempt + 1 < attempts:
            time.sleep(retry_wait_s)
    return False


def classify_timeout(probe_alive: bool, **ctx) -> str:
    """Post-hard-kill verdict: "slow" (tunnel answers — continue with
    remaining work) or "wedged" (tunnel gone — skip the rest). The
    classification is journaled with the caller's context (metric
    name etc.) so a postmortem reads the decision, not just its
    side effects."""
    verdict = "slow" if probe_alive else "wedged"
    metrics.inc(f"watchdog.classified_{verdict}")
    journal.emit("wedge_classification", verdict=verdict, **ctx)
    return verdict
