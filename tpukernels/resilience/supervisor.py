"""Checkpointed revalidation supervisor (docs/RESILIENCE.md §supervisor).

The revalidation queue used to live as ~300 lines of bash
(tools/tpu_revalidate.sh + tools/tpu_wait_and_revalidate.sh): per-day
wall-clock stamps, a fixed 5-minute probe poll, and no memory of WHICH
steps keep wedging — a step that wedges could re-eat every 2–25 minute
healthy window all day. This module is the declarative, checkpointed
replacement; the shell scripts are now thin wrappers that keep the
$HOME flock machinery and exit-code contract, then delegate to
``tools/revalidate.py``.

Three robustness behaviors are the core:

1. **Crash-safe resume** — every supervisor decision (step attempts,
   outcomes, quarantines) is appended to a JSONL *checkpoint* under
   ``docs/logs/`` before/after each step, flushed+fsynced, so a
   ``kill -9`` at any instant loses at most the in-flight step: a
   re-run replays the checkpoint and converges to the same green queue
   without redoing green steps. (The checkpoint is authoritative
   state; the same decisions are mirrored into the best-effort health
   journal for observability.)
2. **Step quarantine / circuit breaker** — a step that WEDGES
   ``quarantine_after`` times (default 2) in one day is demoted to
   non-gating and skipped with a loud ``step_quarantined`` event, so
   the third healthy window goes to the next step instead of re-eating
   the flap window on the same wedge.
3. **Flap-aware scheduling** — recent ``probe``/``wedge`` events in
   the health journal estimate the current healthy-window length;
   chip-touching steps are admitted only when their chip-minute cost
   estimate fits, preferring highest value-per-chip-minute (the
   NEXT.md ordering, enforced in code). When NOTHING fits the
   estimate, the best-density step is force-admitted (estimates are
   estimates; livelock is worse) and the decision journaled.

Execution: each step runs as a killable subprocess under
``watchdog.kill_after``; a timeout is classified slow-vs-wedged via
``watchdog.classify_timeout`` exactly like bench's per-metric
children. Probing (wait mode) uses exponential backoff with
deterministic jitter, capped, each decision journaled as
``probe_scheduled`` — replacing the fixed 5-minute poll.

Stamps stay compatible both ways: a green step writes the same
git-aware ``<name>_<date>.done`` stamp file the shell lib
(tools/revalidate_lib.sh) writes, and ``stamp_fresh`` honors stamps
the shell lib wrote — a queue half-run by either driver resumes under
the other (tests/test_supervisor.py proves the equivalence).

Stdlib-only, like the rest of the package: importable before jax.
"""

from __future__ import annotations

import datetime
import hashlib
import json
import os
import re
import subprocess
import sys
import time

from tpukernels.obs import metrics, trace
from tpukernels.resilience import faults, journal, watchdog

_REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# outcome vocabulary for step_done checkpoint/journal records
GREEN = "green"          # exit 0
FAILED = "failed"        # loud nonzero exit (never a wedge)
WEDGED = "wedged"        # watchdog kill + dead re-probe
SLOW = "slow"            # watchdog kill + live re-probe (not a wedge)

# exit-code contract shared with the shell wrappers (and the watcher
# loop): 0 green; 2 incomplete-but-nothing-regressed (deferred steps /
# partial coverage — retryable next window); 124 wedge or step timeout
# (retryable); any other nonzero = a gating step failed loudly.
RC_GREEN = 0
RC_INCOMPLETE = 2
RC_WEDGE = 124

# no flap history: assume the TOP of the observed 2-25 min band
# (BASELINE.md) — with no evidence of short windows the scheduler
# must not invert the value ordering by deferring the expensive
# high-value steps; only OBSERVED flaps constrain admission
_DEFAULT_WINDOW_MIN = 25.0
_WINDOW_CLAMP = (1.0, 60.0)


class StepSpec:
    """One declarative revalidation step.

    ``shell`` is the step body (run via ``bash -c``, its own killable
    subprocess). ``gating`` steps abort the queue on loud failure;
    non-gating ones warn. ``cost_min``/``value`` drive the
    value-per-chip-minute admission ordering; ``needs_chip=False``
    steps (sanitizers, autotune smoke) ignore the window estimate.
    ``stamp`` policy: ``daily`` (stamp on success, skip while fresh),
    ``attempt`` (stamp BEFORE running — a wedge here must not re-eat
    every window; the prewarm contract), ``never`` (always runs).
    ``inputs`` are the repo paths whose commits invalidate a same-day
    stamp (git-aware staleness; satellite of the PR-1 footgun).
    ``after`` lists steps that must have been attempted (any outcome)
    earlier in the queue — dependency edges the bash ordering implied.
    ``cost_from="prewarm"`` makes ``cost_min`` a live estimate: each
    queue run re-derives it from the newest per-kernel
    ``prewarm_kernel`` compile walls in the health journal (see
    :func:`observed_prewarm_cost_min`) so flap-window admission uses
    measured compile cost, not a hand-guessed constant.
    """

    __slots__ = ("name", "shell", "gating", "timeout_s", "cost_min",
                 "value", "max_attempts_per_day", "quarantine_after",
                 "stamp", "needs_chip", "inputs", "after", "cost_from")

    def __init__(self, name, shell, *, gating=True, timeout_s=1200.0,
                 cost_min=5.0, value=1.0, max_attempts_per_day=6,
                 quarantine_after=2, stamp="daily", needs_chip=True,
                 inputs=(), after=(), cost_from=None):
        if stamp not in ("daily", "attempt", "never"):
            raise ValueError(f"step {name!r}: bad stamp policy {stamp!r}")
        if cost_from not in (None, "prewarm"):
            raise ValueError(
                f"step {name!r}: bad cost_from {cost_from!r} "
                "(known: prewarm)")
        self.name = name
        self.shell = shell
        self.gating = bool(gating)
        self.timeout_s = float(timeout_s)
        self.cost_min = float(cost_min)
        self.value = float(value)
        self.max_attempts_per_day = int(max_attempts_per_day)
        self.quarantine_after = int(quarantine_after)
        self.stamp = stamp
        self.needs_chip = bool(needs_chip)
        self.inputs = tuple(inputs)
        self.after = tuple(after)
        self.cost_from = cost_from

    @property
    def density(self) -> float:
        """Value per chip-minute — the admission preference key."""
        return self.value / max(self.cost_min, 0.01)

    @classmethod
    def from_dict(cls, d: dict) -> "StepSpec":
        d = dict(d)
        name = d.pop("name")
        shell = d.pop("shell")
        return cls(name, shell, **d)


def load_queue_file(path: str) -> list:
    """Parse a JSON queue definition (a list of StepSpec dicts) — how
    the CPU chaos tests drive the real supervisor against stub steps,
    and how an operator can run a cut-down queue."""
    with open(path) as f:
        raw = json.load(f)
    if not isinstance(raw, list):
        raise ValueError(f"{path}: queue file must be a JSON list")
    specs = [StepSpec.from_dict(d) for d in raw]
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate step names")
    known = set(names)
    for s in specs:
        missing = [a for a in s.after if a not in known]
        if missing:
            raise ValueError(
                f"{path}: step {s.name!r} depends on unknown {missing}"
            )
    # a dependency cycle must fail HERE as a config error: at run time
    # it would surface as rc 2 ("incomplete, retryable") and the watch
    # loop would re-run an unrunnable queue until its deadline
    after = {s.name: set(s.after) for s in specs}
    progress = True
    while progress and after:
        progress = False
        for n in [n for n, deps in after.items() if not deps]:
            del after[n]
            for deps in after.values():
                deps.discard(n)
            progress = True
    if after:
        raise ValueError(
            f"{path}: dependency cycle among {sorted(after)}")
    return specs


# ------------------------------------------------------------------ #
# git-aware stamps (shared on-disk format with tools/revalidate_lib.sh)
# ------------------------------------------------------------------ #

def stamp_dir(repo=_REPO) -> str:
    return os.environ.get("TPK_REVALIDATE_STAMP_DIR") or os.path.join(
        repo, "docs", "logs", ".revalidate_stamps"
    )

def _stamp_path(name: str, repo=_REPO) -> str:
    day = datetime.date.today().isoformat()
    return os.path.join(stamp_dir(repo), f"{name}_{day}.done")


def write_stamp(name: str, repo=_REPO):
    """Same format the shell lib writes: the stamp file holds the HEAD
    sha (empty outside git), scoped to today by filename. Written
    crash-consistently (docs/RESILIENCE.md §atomic state): a stamp
    torn mid-write would read as a sha-less legacy stamp and skip the
    step wall-clock-only — a silent staleness hole."""
    from tpukernels.resilience import atomic

    p = _stamp_path(name, repo)
    os.makedirs(os.path.dirname(p), exist_ok=True)
    sha = journal.git_head(repo) or ""
    atomic.write_text(p, sha + "\n" if sha else "")


def _commits_touching(since_sha: str, head: str, inputs, repo=_REPO):
    """True if a commit in (since_sha, head] touched any of `inputs`;
    None when git can't answer (unknown sha after a rewrite) — the
    caller must treat that as stale, re-running is the safe side."""
    try:
        r = subprocess.run(
            ["git", "-C", repo, "log", "--format=%H",
             f"{since_sha}..{head}", "--", *inputs],
            capture_output=True, text=True, timeout=30,
        )
    except Exception:
        return None
    if r.returncode != 0:
        return None
    return bool(r.stdout.strip())


def stamp_fresh(spec: StepSpec, repo=_REPO) -> bool:
    """Is the step's same-day stamp still valid? Mirrors the shell
    lib's step_done: TPK_REVALIDATE_FORCE=1 always re-runs; a legacy
    sha-less stamp (or no git) is honored wall-clock-only; a sha stamp
    goes stale as soon as a later commit touches the step's inputs."""
    if os.environ.get("TPK_REVALIDATE_FORCE") == "1":
        return False
    p = _stamp_path(spec.name, repo)
    try:
        with open(p) as f:
            sha = f.readline().strip()
    except OSError:
        return False
    if not sha:
        return True           # legacy / no-git stamp: wall-clock only
    head = journal.git_head(repo)
    if head is None or head == sha:
        return True
    inputs = spec.inputs or ("bench.py", "tools", "tpukernels", "c")
    touched = _commits_touching(sha, head, inputs, repo)
    if touched is None:
        return False          # git can't judge: re-run, the safe side
    return not touched


# ------------------------------------------------------------------ #
# checkpoint: append-only JSONL, the supervisor's authoritative state #
# ------------------------------------------------------------------ #

def checkpoint_path(repo=_REPO) -> str:
    """TPK_SUPERVISOR_CHECKPOINT: a file path, a directory (dated file
    inside), or unset — docs/logs/supervisor_<date>.jsonl."""
    raw = os.environ.get("TPK_SUPERVISOR_CHECKPOINT")
    day = datetime.date.today().isoformat()
    if raw and os.path.isdir(raw):
        return os.path.join(raw, f"supervisor_{day}.jsonl")
    if raw:
        return raw
    return os.path.join(repo, "docs", "logs", f"supervisor_{day}.jsonl")


class Checkpoint:
    """Append-only JSONL state log. Unlike the health journal (best
    effort by contract), checkpoint appends are flushed AND fsynced —
    resume correctness rides on them — and an unwritable checkpoint
    fails the supervisor loudly rather than silently forgetting
    state. Every append is mirrored to journal.emit for the
    observability stream."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def append(self, kind: str, **fields):
        now = time.time()
        rec = {
            "ts": datetime.datetime.fromtimestamp(now).isoformat(
                timespec="seconds"),
            "t": round(now, 3),
            "pid": os.getpid(),
            "git_head": journal.git_head(),
            "kind": kind,
        }
        rec.update(fields)
        with open(self.path, "a") as f:
            f.write(json.dumps(rec, default=repr) + "\n")
            f.flush()
            os.fsync(f.fileno())
        return rec

    def replay(self) -> dict:
        """Reconstruct per-step state for TODAY from the checkpoint:
        {"steps": {name: {"attempts", "wedges", "green",
        "quarantined", "interrupted"}}, "events": N}. A step_start
        with no matching step_done is an INTERRUPTED attempt (the
        kill -9 case): it counts as an attempt — the step re-runs —
        but never toward the wedge quarantine (the supervisor died,
        not necessarily the step)."""
        events, _bad = journal.load_events([self.path])
        today = datetime.date.today().isoformat()
        steps: dict = {}
        open_start: dict = {}

        def st(name):
            return steps.setdefault(name, {
                "attempts": 0, "wedges": 0, "green": False,
                "quarantined": False, "interrupted": 0,
            })

        n = 0
        for ev in events:
            if not str(ev.get("ts", "")).startswith(today):
                continue
            n += 1
            kind, name = ev.get("kind"), ev.get("step")
            if kind == "step_start":
                s = st(name)
                s["attempts"] += 1
                open_start[name] = open_start.get(name, 0) + 1
            elif kind == "step_done":
                s = st(name)
                if open_start.get(name):
                    open_start[name] -= 1
                if ev.get("outcome") == GREEN:
                    s["green"] = True
                elif ev.get("outcome") == WEDGED:
                    s["wedges"] += 1
            elif kind == "step_quarantined":
                st(name)["quarantined"] = True
        for name, cnt in open_start.items():
            if cnt > 0:
                steps[name]["interrupted"] += cnt
        return {"steps": steps, "events": n}


# ------------------------------------------------------------------ #
# flap-aware window estimation                                        #
# ------------------------------------------------------------------ #

def estimate_window_minutes(events, now=None) -> dict:
    """Estimate the current healthy-window length from recent health
    events: each (alive probe -> later wedge) pair inside the last
    24 h is one observed window; the estimate is their median, clamped
    to the documented flap band. ``TPK_SUPERVISOR_WINDOW_MIN`` pins it
    (operator override). Returns {"minutes", "basis", "windows"}."""
    pinned = os.environ.get("TPK_SUPERVISOR_WINDOW_MIN")
    if pinned:
        try:
            return {"minutes": float(pinned), "basis": "env",
                    "windows": 0}
        except ValueError:
            print(f"# supervisor: bad TPK_SUPERVISOR_WINDOW_MIN "
                  f"{pinned!r} ignored", file=sys.stderr)
    now = time.time() if now is None else now
    horizon = now - 24 * 3600
    alive_t = None
    windows = []
    for ev in sorted(events, key=lambda e: e.get("t", 0.0)):
        t = ev.get("t")
        if not isinstance(t, (int, float)) or t < horizon:
            continue
        kind = ev.get("kind")
        if kind == "probe" and ev.get("outcome") == "alive":
            if alive_t is None:
                alive_t = t
        elif (kind == "wedge_classification"
              and ev.get("verdict") == "wedged") or (
                kind == "step_done" and ev.get("outcome") == WEDGED):
            if alive_t is not None and t > alive_t:
                windows.append((t - alive_t) / 60.0)
            alive_t = None
    if not windows:
        return {"minutes": _DEFAULT_WINDOW_MIN, "basis": "default",
                "windows": 0}
    windows.sort()
    mid = windows[len(windows) // 2]
    lo, hi = _WINDOW_CLAMP
    return {"minutes": min(max(mid, lo), hi), "basis": "observed",
            "windows": len(windows)}


def observed_prewarm_cost_min(events, now=None):
    """Chip-minute cost estimate for the prewarm step from measured
    evidence: the newest successful ``prewarm_kernel`` wall per kernel
    inside the last 24 h (tools/prewarm.py journals one per kernel and
    per bench metric), summed and clamped to the flap band. None when
    the journal holds no prewarm evidence yet — the spec's shipped
    ``cost_min`` then stands. A warm cache shrinks the estimate toward
    zero, which is exactly the point: a prewarmed suite should be
    admitted into windows the cold-compile guess would have deferred
    it out of."""
    now = time.time() if now is None else now
    horizon = now - 24 * 3600
    newest: dict = {}
    for ev in events:
        if ev.get("kind") != "prewarm_kernel":
            continue
        t = ev.get("t")
        if not isinstance(t, (int, float)) or t < horizon:
            continue
        if ev.get("status") not in (None, "ok"):
            continue
        kernel, wall = ev.get("kernel"), ev.get("wall_s")
        if kernel is None or not isinstance(wall, (int, float)):
            continue
        if kernel not in newest or t >= newest[kernel][0]:
            newest[kernel] = (t, wall)
    if not newest:
        return None
    total_min = sum(w for _t, w in newest.values()) / 60.0
    lo, hi = 0.5, _WINDOW_CLAMP[1]
    return round(min(max(total_min, lo), hi), 2)


# ------------------------------------------------------------------ #
# probe + backoff schedule                                            #
# ------------------------------------------------------------------ #

# same probe, same question, as the old watcher loop: the backend
# assert catches jax's silent CPU fallback declaring a dead tunnel
# alive; -k escalation is handled by kill_after's hard timeout
_PROBE_SNIPPET = (
    "import jax; assert jax.default_backend() != 'cpu', "
    "jax.default_backend(); import jax.numpy as jnp; "
    "(jnp.ones((8,8)) @ jnp.ones((8,8))).block_until_ready()"
)


def probe_alive(attempt: int = 0, timeout_s: float = 90.0) -> bool:
    """One liveness probe in a killable subprocess; fault-plan
    scriptable ("ok"/"hang"/"dead") exactly like bench's probe so the
    chaos suite can flap the tunnel deterministically."""
    # any consumed script entry is honored: "ok" is alive, everything
    # else ("hang"/"dead"/"error"/...) is not-alive — the supervisor
    # has no per-call retry concept, and falling through to a REAL
    # probe after journaling fault_injected would make a chaos run
    # claim an injection that never took effect
    injected = faults.probe_outcome()
    if injected is not None:
        alive = injected == "ok"
        journal.emit("probe", site="supervisor", attempt=attempt,
                     outcome="alive" if alive else injected,
                     injected=True)
        return alive
    proc, status = watchdog.kill_after(
        [sys.executable, "-c", _PROBE_SNIPPET], timeout_s,
        site="supervisor_probe", capture_output=True,
    )
    alive = status == "ok" and proc.returncode == 0
    journal.emit("probe", site="supervisor", attempt=attempt,
                 outcome="alive" if alive else
                 ("hang" if status == "timeout" else "error"))
    return alive


def probe_delay_s(attempt: int, base_s=None, cap_s=None) -> float:
    """Deterministic exponential backoff with jitter for dead-tunnel
    probing (replaces the fixed 300 s poll): ``min(cap, base*2^n)``
    minus up to 25% md5-derived jitter — deterministic per attempt (a
    resumed watcher reproduces the same schedule, test-enforced), but
    de-synchronized across attempts."""
    if base_s is None:
        base_s = float(os.environ.get("TPK_SUPERVISOR_PROBE_BASE_S",
                                      30.0))
    if cap_s is None:
        cap_s = float(os.environ.get("TPK_SUPERVISOR_PROBE_CAP_S",
                                     600.0))
    raw = min(cap_s, base_s * (2.0 ** min(attempt, 32)))
    digest = hashlib.md5(f"tpk-probe-{attempt}".encode()).digest()
    frac = int.from_bytes(digest[:4], "big") / 2 ** 32
    return round(raw * (1.0 - 0.25 * frac), 3)


# ------------------------------------------------------------------ #
# the supervisor                                                      #
# ------------------------------------------------------------------ #

def _inherited_lock_fds() -> tuple:
    """The watcher wrapper acquires the machine-wide chip lock on
    fd 9 before exec'ing the supervisor. STEP children must inherit
    that fd — the old queue's deliberate invariant: if the supervisor
    dies mid-step, the orphaned chip work still holds the lock and a
    replacement watcher cannot interleave timed runs with it (the
    orphan's hold is bounded by the step timeout, and the wrapper
    waits out a held lock rather than exiting immediately). PROBE
    children must NOT inherit it (the old loop's ``9>&-``): a
    killable probe must never end up owning the lock. Returns ``(9,)``
    only when fd 9 currently refers to the watcher lock file."""
    home = os.environ.get("HOME")
    if not home:
        return ()
    try:
        st9 = os.fstat(9)
        stl = os.stat(os.path.join(home, ".tpk_tpu_wait.lock"))
    except OSError:
        return ()
    if (st9.st_dev, st9.st_ino) == (stl.st_dev, stl.st_ino):
        return (9,)
    return ()


class Supervisor:
    def __init__(self, specs, repo=_REPO, checkpoint=None,
                 announce=True):
        """`announce=False` (the --plan preview) replays state without
        appending the supervisor_resume record — a read-only mode must
        not write the checkpoint it is reporting on."""
        self.specs = list(specs)
        self.repo = repo
        self.checkpoint = checkpoint or Checkpoint(checkpoint_path(repo))
        self.state = self.checkpoint.replay()
        # this-run bookkeeping. _settled = "this run will not touch
        # this step again"; _attempted = "attempted or deliberately
        # skipped" and is what satisfies `after` edges — a DEFERRED
        # step settles without attempting, so its dependents stay
        # blocked and defer with it (c_scan_timing must not record a
        # number in a window where c_gate never ran)
        self._settled: set = set()
        self._attempted: set = set()
        self._deferred: list = []
        self._cost_override: dict = {}  # name -> measured cost_min
        self._last_rc: int | None = None
        self._last_wall_s: float = 0.0
        if self.state["events"] and announce:
            resumed = {
                n: s for n, s in self.state["steps"].items()
                if s["attempts"] or s["quarantined"]
            }
            interrupted = [n for n, s in resumed.items()
                           if s["interrupted"]]
            self.checkpoint.append(
                "supervisor_resume",
                green=[n for n, s in resumed.items() if s["green"]],
                quarantined=[n for n, s in resumed.items()
                             if s["quarantined"]],
                interrupted=interrupted,
            )
            journal.emit(
                "supervisor_resume", events=self.state["events"],
                green=[n for n, s in resumed.items() if s["green"]],
                quarantined=[n for n, s in resumed.items()
                             if s["quarantined"]],
                interrupted=interrupted,
            )
            if interrupted:
                print(f"# supervisor: resuming after interruption "
                      f"mid-{','.join(interrupted)}", file=sys.stderr)

    # -- state helpers ------------------------------------------------
    def _st(self, name):
        return self.state["steps"].setdefault(name, {
            "attempts": 0, "wedges": 0, "green": False,
            "quarantined": False, "interrupted": 0,
        })

    def _quarantined(self, spec) -> bool:
        s = self._st(spec.name)
        return s["quarantined"] or s["wedges"] >= spec.quarantine_after

    def _green(self, spec) -> bool:
        # stamp="never" means never skippable, not even by a same-day
        # green in the checkpoint: bench's canary + union gate must
        # run on EVERY attempt (the old queue's un-stamped step 1)
        if spec.stamp == "never":
            return False
        s = self._st(spec.name)
        if s["green"]:
            return True
        # shell-era compatibility: honor a valid stamp file even when
        # this checkpoint never saw the step (attempt-stamped steps
        # are "done for today" by stamping, green or not)
        return stamp_fresh(spec, self.repo)

    def _skip(self, spec, reason):
        self._settled.add(spec.name)
        self._attempted.add(spec.name)
        self.checkpoint.append("step_skipped", step=spec.name,
                               reason=reason)
        journal.emit("step_skipped", step=spec.name, reason=reason)
        print(f"supervisor: step '{spec.name}' skipped ({reason})")

    def _history_paths(self):
        """Journal files feeding the flap-window estimate. The journal
        rotates per day, so a run just after midnight must also read
        YESTERDAY's file or the estimator's documented 24 h horizon
        silently collapses to since-midnight and reverts to the
        optimistic default against an evening of observed flaps."""
        p = journal.path()
        if not p:
            return []
        paths = [p]
        m = re.match(r"health_(\d{4}-\d{2}-\d{2})\.jsonl$",
                     os.path.basename(p))
        if m:
            yday = (datetime.date.today()
                    - datetime.timedelta(days=1)).isoformat()
            paths.insert(0, os.path.join(os.path.dirname(p),
                                         f"health_{yday}.jsonl"))
        return paths   # load_events tolerates the missing-file case

    # -- scheduling ---------------------------------------------------
    def _schedulable(self, pending):
        return [s for s in pending
                if all(a in self._attempted for a in s.after)]

    def _cost_min(self, spec) -> float:
        """Effective chip-minute cost for admission: this run's
        measured refinement when one exists, else the shipped
        estimate. Kept OFF the spec object: PRODUCTION_QUEUE specs are
        module-level and shared by every Supervisor a watch process
        builds — mutating them would make later runs' "prior" the last
        estimate instead of the shipped cost."""
        return self._cost_override.get(spec.name, spec.cost_min)

    def _density(self, spec) -> float:
        return spec.value / max(self._cost_min(spec), 0.01)

    def plan(self, remaining_min: float, may_force: bool):
        """Pick the next step for the remaining window budget: highest
        value-per-chip-minute among schedulable steps whose cost fits
        (CPU-only steps always fit). When nothing fits and the window
        is still untouched (`may_force`), the best-density chip step
        is forced — estimates are estimates, and admitting nothing
        forever is the one unacceptable schedule. Returns
        (spec, forced) or (None, False) when nothing is schedulable."""
        pending = [s for s in self.specs
                   if s.name not in self._settled]
        sched = self._schedulable(pending)
        if not sched:
            return None, False
        sched.sort(key=lambda s: -self._density(s))
        fits = [s for s in sched
                if not s.needs_chip
                or self._cost_min(s) <= remaining_min]
        if fits:
            return fits[0], False
        if may_force:
            return sched[0], True
        return None, False

    # -- execution ----------------------------------------------------
    def _run_step(self, spec: StepSpec, forced: bool) -> str:
        st = self._st(spec.name)
        st["attempts"] += 1
        self.checkpoint.append("step_start", step=spec.name,
                               attempt=st["attempts"],
                               gating=spec.gating, forced=forced,
                               timeout_s=spec.timeout_s,
                               cost_min=self._cost_min(spec))
        journal.emit("step_start", step=spec.name,
                     attempt=st["attempts"], gating=spec.gating,
                     forced=forced)
        # chaos injection point: the SIGKILL-mid-step proof fires HERE
        # — after step_start is durably checkpointed, before any
        # outcome can be — the worst instant for resume correctness
        faults.supervisor_fault(spec.name)
        if spec.stamp == "attempt":
            write_stamp(spec.name, self.repo)  # attempted = done today
        t0 = time.time()
        with trace.span(f"step/{spec.name}", gating=spec.gating,
                        cost_min=spec.cost_min):
            proc, status = watchdog.kill_after(
                ["bash", "-c", spec.shell], spec.timeout_s,
                site=f"step/{spec.name}", cwd=self.repo,
                pass_fds=_inherited_lock_fds(),
            )
        wall = round(time.time() - t0, 3)
        if status == "timeout":
            alive = probe_alive()
            verdict = watchdog.classify_timeout(alive, step=spec.name)
            outcome = WEDGED if verdict == "wedged" else SLOW
            rc = None
        else:
            rc = proc.returncode
            outcome = GREEN if rc == 0 else FAILED
        if outcome == WEDGED:
            st["wedges"] += 1
        if outcome == GREEN:
            st["green"] = True
            if spec.stamp == "daily":
                write_stamp(spec.name, self.repo)
        metrics.inc(f"supervisor.steps_{outcome}")
        self.checkpoint.append("step_done", step=spec.name,
                               outcome=outcome, rc=rc, wall_s=wall,
                               wedges_today=st["wedges"])
        journal.emit("step_done", step=spec.name, outcome=outcome,
                     rc=rc, wall_s=wall, wedges_today=st["wedges"])
        # no wall time on stdout: the clean-path byte-identical proof
        # (tests/test_supervisor.py) needs deterministic output; wall
        # time lives in the checkpoint/journal and the reports
        print(f"supervisor: step '{spec.name}' {outcome}"
              + (f" (rc={rc})" if rc not in (0, None) else ""))
        if (outcome == WEDGED
                and st["wedges"] >= spec.quarantine_after
                and not st["quarantined"]):
            self._quarantine(spec, st)
        self._settled.add(spec.name)
        self._attempted.add(spec.name)
        self._last_rc = rc
        self._last_wall_s = wall
        return outcome

    def _quarantine(self, spec, st):
        st["quarantined"] = True
        metrics.inc("supervisor.steps_quarantined")
        self.checkpoint.append("step_quarantined", step=spec.name,
                               wedges=st["wedges"],
                               threshold=spec.quarantine_after)
        journal.emit("step_quarantined", step=spec.name,
                     wedges=st["wedges"],
                     threshold=spec.quarantine_after)
        print(f"supervisor: step '{spec.name}' QUARANTINED after "
              f"{st['wedges']} wedge(s) today - demoted to non-gating,"
              " next window goes to the next step", file=sys.stderr)

    def run_queue(self) -> int:
        """One queue attempt (one healthy window). Returns the
        exit-code contract value (RC_* above)."""
        # env-derived hardware stamp (docs/OBSERVABILITY.md §scaling):
        # the supervisor must never initialize a backend (a wedged
        # tunnel would hang the whole queue), so probe stays off — the
        # step children that touch devices stamp their own jax-backed
        # inventories
        from tpukernels.obs import scaling as _scaling

        _scaling.emit_inventory("supervisor")
        events, _bad = journal.load_events(self._history_paths())
        est = estimate_window_minutes(events)
        # measured-cost refinement: steps that opted in (cost_from)
        # re-derive their chip-minute estimate from journal evidence
        # BEFORE admission, so the value-density ordering and the
        # window fit both see real compile walls. Journal-only (not
        # checkpointed): an estimate is scheduling input, not state.
        for spec in self.specs:
            if spec.cost_from == "prewarm":
                obs = observed_prewarm_cost_min(events)
                if obs is not None and obs != spec.cost_min:
                    journal.emit("step_cost_estimated", step=spec.name,
                                 cost_min=obs,
                                 prior_cost_min=spec.cost_min,
                                 basis="prewarm_kernel")
                    self._cost_override[spec.name] = obs
        journal.emit("window_estimate", minutes=est["minutes"],
                     basis=est["basis"], windows=est["windows"])
        print(f"supervisor: healthy-window estimate "
              f"{est['minutes']:.1f} min ({est['basis']}, "
              f"{est['windows']} observed)")
        remaining = est["minutes"]
        chip_spent = 0.0
        with trace.span("queue/run", window_min=remaining):
            while True:
                # pre-pass: settle green/quarantined/exhausted steps so
                # dependency edges and the planner see only real work
                for spec in self.specs:
                    if spec.name in self._settled:
                        continue
                    if self._green(spec):
                        self._skip(spec, "green-today")
                    elif self._quarantined(spec):
                        st = self._st(spec.name)
                        if not st["quarantined"]:
                            self._quarantine(spec, st)
                        self._skip(spec, "quarantined")
                    elif (self._st(spec.name)["attempts"]
                          >= spec.max_attempts_per_day):
                        self._skip(spec, "attempts-exhausted")
                spec, forced = self.plan(
                    remaining, may_force=chip_spent == 0.0)
                if spec is None:
                    # nothing fits the remaining window: defer the
                    # rest of the chip work to the next healthy window
                    # (rc 2 — incomplete, retryable, like the bench
                    # gate's coverage rc). Steps blocked on a deferred
                    # dependency defer WITH it — an `after` edge means
                    # "ran first", and deferral is not an attempt.
                    rest = self._schedulable(
                        [s for s in self.specs
                         if s.name not in self._settled])
                    if not rest:
                        for s in self.specs:
                            if s.name not in self._settled:
                                self._defer(s, "dependency-deferred")
                        break
                    for s in rest:
                        self._defer(s)
                    continue
                outcome = self._run_step(spec, forced)
                if spec.needs_chip:
                    chip_spent += max(self._last_wall_s / 60.0, 0.0)
                    remaining -= max(self._last_wall_s / 60.0, 0.0)
                if outcome == WEDGED:
                    # the window is gone: defer every remaining chip
                    # step and bail to probe duty (rc 124, retryable)
                    for rest in self.specs:
                        if (rest.name not in self._settled
                                and rest.needs_chip):
                            self._defer(rest)
                    print("supervisor: tunnel WEDGED - returning to "
                          "probe duty", file=sys.stderr)
                    return RC_WEDGE
                if outcome == SLOW and spec.gating:
                    # timed out but the tunnel answers: loud, gating,
                    # retryable by contract (the old `timeout` rc)
                    print(f"supervisor: gating step '{spec.name}' "
                          "timed out (tunnel alive)", file=sys.stderr)
                    return RC_WEDGE
                if outcome == FAILED and spec.gating:
                    rc = self._last_rc or 1
                    print(f"supervisor: gating step '{spec.name}' "
                          f"FAILED rc={rc} - aborting queue",
                          file=sys.stderr)
                    return rc if rc != RC_GREEN else 1
        return self._final_rc()

    def _defer(self, spec, reason="deferred-window"):
        self._deferred.append(spec.name)
        self._settled.add(spec.name)   # NOT _attempted: deps stay blocked
        self.checkpoint.append("step_skipped", step=spec.name,
                               reason=reason)
        journal.emit("step_skipped", step=spec.name,
                     reason=reason, cost_min=spec.cost_min)
        print(f"supervisor: step '{spec.name}' deferred ({reason})")

    def _final_rc(self) -> int:
        deferred_gating = [
            n for n in self._deferred
            if any(s.name == n and s.gating for s in self.specs)
        ]
        quarantined = [s.name for s in self.specs
                       if self._st(s.name)["quarantined"]]
        not_green = [
            s.name for s in self.specs
            if s.gating and not self._st(s.name)["green"]
            and s.name not in quarantined
            and not (s.stamp in ("daily", "attempt")
                     and stamp_fresh(s, self.repo))
        ]
        if quarantined:
            print("supervisor: QUARANTINED steps (wedged repeatedly, "
                  f"demoted to non-gating): {','.join(quarantined)}",
                  file=sys.stderr)
        if deferred_gating or not_green:
            print(f"supervisor: queue INCOMPLETE (deferred="
                  f"{','.join(deferred_gating) or '-'} "
                  f"pending={','.join(not_green) or '-'}) - "
                  "retryable next window")
            return RC_INCOMPLETE
        print("supervisor: queue GREEN")
        return RC_GREEN


# ------------------------------------------------------------------ #
# watch loop (the old tpu_wait_and_revalidate.sh body)                #
# ------------------------------------------------------------------ #

def watch(make_supervisor, max_hours: float, harvest=None,
          sleep=time.sleep) -> int:
    """Probe the tunnel and run the queue on every healthy probe until
    the first fully green queue or the deadline. Replaces the fixed
    5-minute poll with capped exponential backoff + deterministic
    jitter; every scheduling decision is journaled
    (``probe_scheduled``). `make_supervisor` builds a FRESH Supervisor
    per attempt (each attempt must replay the latest checkpoint);
    `harvest` (optional) runs once after the first green queue — the
    best-effort sgemm sweep of the old watcher, never gating.

    Exit codes (unchanged from the shell watcher): 0 green; 1
    deadline; a gating step's rc when it failed with the tunnel still
    healthy (deterministic failure — retrying cannot fix it); never
    exits on rc 124/2 (wedge / incomplete coverage are what the watch
    exists to ride out)."""
    deadline = time.time() + max_hours * 3600
    dead_streak = 0
    while time.time() < deadline:
        if probe_alive(attempt=dead_streak):
            dead_streak = 0
            now = datetime.datetime.now().isoformat(timespec="seconds")
            print(f"supervisor: tunnel ALIVE at {now}; running queue")
            rc = make_supervisor().run_queue()
            if rc == RC_GREEN:
                print(f"supervisor: revalidation PASSED at "
                      f"{datetime.datetime.now().isoformat(timespec='seconds')}")
                if harvest is not None:
                    harvest()
                return RC_GREEN
            # wedge (124) and incomplete coverage (2) are ALWAYS
            # retryable; any other failure with the tunnel still
            # answering is deterministic — surface it, don't re-run
            # the expensive queue against it for hours
            if (rc not in (RC_WEDGE, RC_INCOMPLETE)
                    and probe_alive(attempt=0)):
                print(f"supervisor: queue FAILED (rc={rc}) with the "
                      "tunnel still healthy - deterministic failure; "
                      "exiting", file=sys.stderr)
                return rc
            print(f"supervisor: queue attempt rc={rc} (wedge or "
                  "incomplete coverage); back on probe duty")
        else:
            dead_streak += 1
        delay = probe_delay_s(dead_streak)
        journal.emit("probe_scheduled", attempt=dead_streak,
                     delay_s=delay,
                     reason="tunnel-dead" if dead_streak else
                     "post-attempt")
        print(f"supervisor: next probe in {delay:.0f}s "
              f"(attempt {dead_streak})")
        sleep(delay)
    print(f"supervisor: gave up after {max_hours}h")
    return 1
