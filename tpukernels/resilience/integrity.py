"""Dispatch-time output-integrity guard (docs/RESILIENCE.md
§output integrity).

The paper's contract is that every benchmark "passes its reference
check", yet until this module the stack verified timing, liveness and
compile provenance but never *outputs* at dispatch time: a flapping
chip, a miscompiled pipelined variant (TPK_SGEMM_DEPTH and friends) or
a stale AOT executable could return plausible garbage and every layer
— bench, trend, supervisor — would call it healthy. This module makes
a wrong answer a detected, journaled, quarantined event instead of a
silent one, on every guarded path: ``registry.dispatch``, the bench
measure phases, ``capi.run_from_c`` and (through their bench
children) autotune sweep candidates.

Three tiers, cheapest always-on (``TPK_INTEGRITY``: unset/``1`` =
full, ``tripwire`` = tier 1 only, ``0``/``off``/``none`` = off):

1. **Finite tripwire** — every guarded result's float leaves are
   scanned for NaN/Inf. One reduction per call; catches the classic
   silent-corruption signature (a NaN launched into a fori_loop
   poisons the whole chain, so bench's warm-call sum is a
   whole-program tripwire).
2. **Fingerprint bands** — per-(kernel, canary config) checksum/norm
   envelopes recorded from the jnp oracles (the CPU-interpret golden
   authority) into a persistent manifest (``integrity.json`` under
   the ``_cachedir`` root, ``TPK_INTEGRITY_DIR`` redirects), keyed
   and sha-validated exactly like ``tuning/cache.py`` and
   ``aot.json``: a stale envelope (jax upgrade, a commit touching the
   kernel's sources) is LOUDLY rejected and treated as absent. The
   exact (int32) kernels compare bitwise via CRC — any flip is
   caught, with no oracle re-run. On the guarded DISPATCH paths only
   the exact kernels consume their envelope (float kernels go
   straight to the stronger elementwise tier 3 at near-identical
   cost); the float envelopes' norm bands serve
   ``tools/integrity_envelopes.py --check`` and cross-process/device
   drift records. The first time a process trusts a kernel's
   compiled path on a device (first guarded call per (site, kernel);
   ``aot.precompile``'s prewarm smoke), a tier-2/3 canary check runs
   before results are believed.
3. **Sampled oracle cross-check** — every Nth guarded call
   (``TPK_INTEGRITY_SAMPLE``, default 64; 0 disables sampling but
   keeps the first-call check) re-runs the kernel at its small canary
   config THROUGH the same (possibly corrupted) path and compares
   elementwise against the existing jnp oracle
   (``sgemm_reference``/``inclusive_scan_reference``/...) within the
   documented per-kernel tolerance.

A failure NEVER crashes the surrounding run. It emits an
``output_integrity_failed`` journal event (kernel, site, tier,
config), invalidates the kernel's AOT executable memo + manifest
entries (``aot.invalidate_kernel`` — the next call recompiles instead
of re-trusting a suspect executable), and counts toward quarantine:
``TPK_INTEGRITY_QUARANTINE_AFTER`` (default 2) failures for one
(kernel, config) in a day demote it — loud
``output_integrity_quarantined`` event + stderr, persisted in
``integrity_quarantine.json``, and every later guarded call of a
suspect kernel is canary-checked instead of sampled (the PR-4
step-quarantine pattern applied to kernel configs). Clean-path bench
stdout stays byte-identical whether the guard is on-and-passing or
``TPK_INTEGRITY=0`` (test-proven like the fault/trace/AOT layers).

The whole path is CPU-chaos-provable: the ``TPK_FAULT_PLAN`` keys
``corrupt_output`` / ``nan_output`` (resilience/faults.py) corrupt
guarded results — including the guard's own canary runs, which is
what makes a finite corruption detectable against the clean oracle.

Stdlib-only at import (numpy/jax load lazily inside the check paths),
like every other resilience/obs/tuning module.
"""

from __future__ import annotations

import datetime
import json
import os
import sys
import time
import zlib

from tpukernels import _cachedir
from tpukernels.obs import metrics as obs_metrics
from tpukernels.obs import trace
from tpukernels.resilience import faults, journal

_DISABLED = ("0", "off", "none")

# per-process state (reset() for tests)
_CALLS: dict = {}        # (site, kernel) -> guarded-call count
_DEEP_DONE: set = set()  # (site, kernel) first-trust canary already ran
_SUSPECT: set = set()    # kernels whose last check failed: check every call
_QUAR_WARNED: set = set()  # quarantined keys already stderr-noted
_REJECT_NOTED: set = set()
_FILE_MEMO: dict = {}    # path -> (stat_key, parsed)


def enabled() -> bool:
    raw = os.environ.get("TPK_INTEGRITY")
    return raw is None or raw.strip().lower() not in _DISABLED


def tier1_only() -> bool:
    """``TPK_INTEGRITY=tripwire``: keep the always-on finite scan but
    skip the canary tiers — the chip-ops escape hatch when small-shape
    canary compiles through a cold tunnel are not worth it."""
    raw = os.environ.get("TPK_INTEGRITY")
    return raw is not None and raw.strip().lower() == "tripwire"


def sample_every() -> int:
    """Every-Nth-call cadence of the sampled oracle cross-check; 0
    disables sampling (first-trust checks still run). Fail-loud parse,
    like every tunable knob."""
    raw = os.environ.get("TPK_INTEGRITY_SAMPLE")
    if raw is None or not raw.strip():
        return 64
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"TPK_INTEGRITY_SAMPLE={raw!r}: expected a non-negative int"
        ) from None
    if n < 0:
        raise ValueError(f"TPK_INTEGRITY_SAMPLE={n}: must be >= 0")
    return n


def quarantine_after() -> int:
    raw = os.environ.get("TPK_INTEGRITY_QUARANTINE_AFTER")
    if raw is None or not raw.strip():
        return 2
    try:
        n = int(raw)
    except ValueError:
        raise ValueError(
            f"TPK_INTEGRITY_QUARANTINE_AFTER={raw!r}: expected a "
            "positive int"
        ) from None
    if n < 1:
        raise ValueError(
            f"TPK_INTEGRITY_QUARANTINE_AFTER={n}: must be >= 1"
        )
    return n


def manifest_path() -> str:
    return _cachedir.integrity_manifest_path()


def quarantine_path() -> str:
    return _cachedir.integrity_quarantine_path()


def reset():
    """Drop per-process state (tests only)."""
    _CALLS.clear()
    _DEEP_DONE.clear()
    _SUSPECT.clear()
    _QUAR_WARNED.clear()
    _REJECT_NOTED.clear()
    _FILE_MEMO.clear()


# ------------------------------------------------------------------ #
# canary configs + oracles (the registry completeness surface)       #
# ------------------------------------------------------------------ #

# Per-kernel canary config: deterministic small-shape inputs (seeded,
# built by _ARG_BUILDERS below), the statics the kernel runs with, and
# the comparison contract — "exact" for the int32 kernels (the fuzz
# suite already pins them bitwise to their oracles) or the documented
# (rtol, atol) for float kernels (bands wide enough for a TPU's bf16
# matmul passes, narrow enough that any plausible-garbage corruption
# is orders of magnitude outside them).
# tests/test_registry_contract.py asserts every registry kernel —
# including DERIVED_KERNELS like scan_exclusive — has a row here AND
# in ORACLES: a new kernel cannot ship without an integrity surface.
CANARY_CONFIGS = {
    "vector_add": {"statics": {}, "rtol": 1e-5, "atol": 1e-5},
    "sgemm": {"statics": {}, "rtol": 1e-3, "atol": 1e-2},
    "stencil2d": {"statics": {"iters": 4}, "rtol": 1e-4, "atol": 1e-4},
    "stencil3d": {"statics": {"iters": 2}, "rtol": 1e-4, "atol": 1e-4},
    "scan": {"statics": {}, "exact": True},
    "scan_exclusive": {"statics": {}, "exact": True},
    "histogram": {"statics": {"nbins": 256}, "exact": True},
    "scan_histogram": {"statics": {"nbins": 256}, "exact": True},
    "nbody": {
        "statics": {"dt": 1e-3, "eps": 1e-2, "steps": 1},
        "rtol": 1e-3, "atol": 1e-3,
    },
}

# kernel -> "module:attr" of its jnp oracle, resolved lazily (imports
# stay stdlib-only; the oracles are the ones the golden tests already
# trust — one authority, two consumers)
ORACLES = {
    "vector_add": "tpukernels.kernels.vector_add:saxpy_reference",
    "sgemm": "tpukernels.kernels.sgemm:sgemm_reference",
    "stencil2d": "tpukernels.kernels.stencil:jacobi2d_reference",
    "stencil3d": "tpukernels.kernels.stencil:jacobi3d_reference",
    "scan": "tpukernels.kernels.scan:inclusive_scan_reference",
    "scan_exclusive": "tpukernels.kernels.scan:exclusive_scan_reference",
    "histogram": "tpukernels.kernels.histogram:histogram_reference",
    "scan_histogram":
        "tpukernels.kernels.scan_histogram:scan_histogram_reference",
    "nbody": "tpukernels.kernels.nbody:nbody_reference",
}


def tolerance(name: str):
    """("exact", None, None) or ("band", rtol, atol) for one kernel's
    canary comparison — the documented tolerance of the cross-check."""
    cfg = CANARY_CONFIGS[name]
    if cfg.get("exact"):
        return ("exact", None, None)
    return ("band", cfg["rtol"], cfg["atol"])


def _build_args(name: str):
    """Deterministic canary operands for one kernel (np/host values;
    the runner converts arrays to jnp). Small, off-tile-boundary
    shapes: padding/edge paths are where silent corruption hides."""
    import numpy as np

    rng = np.random.default_rng(20260804)
    f32 = lambda *s: np.asarray(rng.standard_normal(s), np.float32)
    if name == "vector_add":
        return (0.7, f32(1000), f32(1000))
    if name == "sgemm":
        return (1.25, f32(40, 72), f32(72, 56), -0.5, f32(40, 56))
    if name == "stencil2d":
        return (f32(40, 200),)
    if name == "stencil3d":
        return (f32(8, 24, 132),)
    if name in ("scan", "scan_exclusive"):
        return (np.asarray(rng.integers(-1000, 1000, 4093), np.int32),)
    if name in ("histogram", "scan_histogram"):
        return (np.asarray(rng.integers(0, 256, 4093), np.int32),)
    if name == "nbody":
        return tuple(f32(192) for _ in range(6)) + (
            np.asarray(rng.uniform(0.5, 1.5, 192), np.float32),
        )
    raise KeyError(f"no canary operands for kernel {name!r}")


def canary_key(name: str) -> str:
    """``kernel|shapes|dtypes|statics`` — the tuning-cache key scheme
    over the canary operands. Device-agnostic on purpose: the envelope
    is the ORACLE's fingerprint and the bands absorb backend drift, so
    one recorded envelope polices every device_kind."""
    import numpy as np

    args = _build_args(name)
    shapes, dtypes = [], []
    for a in args:
        if isinstance(a, np.ndarray):
            shapes.append("x".join(str(d) for d in a.shape))
            dtypes.append(str(a.dtype))
        else:
            shapes.append("-")
    statics = CANARY_CONFIGS[name]["statics"]
    stat = ",".join(f"{k}={v}" for k, v in sorted(statics.items())) or "-"
    return "|".join(
        (name, "+".join(shapes), "+".join(sorted(set(dtypes))) or "-",
         stat)
    )


def _oracle(name: str):
    import importlib

    mod, attr = ORACLES[name].split(":")
    return getattr(importlib.import_module(mod), attr)


def _leaves(outputs):
    if isinstance(outputs, (tuple, list)):
        return list(outputs)
    return [outputs]


def fingerprint(outputs) -> list:
    """Compact per-leaf fingerprint: shape/dtype, finiteness, CRC of
    the raw bytes (the bitwise authority for exact kernels), and the
    float64 norm statistics the band comparison uses."""
    import numpy as np

    rows = []
    for leaf in _leaves(outputs):
        a = np.asarray(leaf)
        row = {
            "shape": "x".join(str(d) for d in a.shape) or "-",
            "dtype": str(a.dtype),
            "crc": zlib.crc32(np.ascontiguousarray(a).tobytes()),
        }
        if np.issubdtype(a.dtype, np.floating):
            a64 = a.astype(np.float64)
            row["finite"] = bool(np.isfinite(a).all())
            row["l2"] = float(np.sqrt(np.sum(a64 * a64)))
            row["sum"] = float(np.sum(a64))
            row["absmax"] = float(np.max(np.abs(a64))) if a.size else 0.0
        rows.append(row)
    return rows


# ------------------------------------------------------------------ #
# fingerprint-envelope manifest (tier 2)                              #
# ------------------------------------------------------------------ #

def _read_json(p: str) -> dict:
    """Parsed state file via the shared stat-memoized tolerant reader
    (``_cachedir.read_json_memoized``) — {} when absent/corrupt,
    never raises (the tuning-cache contract)."""
    return _cachedir.read_json_memoized(p, _FILE_MEMO)


def _write_json(p: str, mutate):
    """flock-serialized read-modify-write via the shared
    ``_cachedir.locked_json_update`` discipline, with this module's
    stat-memo refreshed around it."""
    def _load(path):
        _FILE_MEMO.pop(path, None)
        return _read_json(path)

    data = _cachedir.locked_json_update(p, mutate, load=_load)
    _FILE_MEMO.pop(p, None)
    return data


def _sources(name: str):
    """Git-epoch sources for one kernel's envelope — the same files
    whose commits gate its tuning-cache and AOT-manifest entries."""
    from tpukernels import aot

    return aot.KERNEL_SOURCES.get(name, ())


def _reject(key: str, reason: str, **fields):
    memo = (key, reason)
    if memo in _REJECT_NOTED:
        return
    _REJECT_NOTED.add(memo)
    obs_metrics.inc("integrity.rejections")
    print(f"# integrity-envelope rejected: {key} ({reason})",
          file=sys.stderr)
    journal.emit("output_integrity_rejected", key=key, reason=reason,
                 **fields)


def envelope(name: str):
    """The validated fingerprint envelope for ``name``'s canary
    config, or None when absent/stale. Validation mirrors the tuning
    cache: jax version must match and no commit touching the kernel's
    sources may postdate the entry — a stale envelope is rejected
    loudly and treated as absent, never silently trusted."""
    key = canary_key(name)
    entry = _read_json(manifest_path()).get("entries", {}).get(key)
    if not isinstance(entry, dict):
        return None
    import jax

    if entry.get("jax") != jax.__version__:
        _reject(
            key,
            f"recorded under jax {entry.get('jax')}, "
            f"running {jax.__version__}",
        )
        return None
    sources = _sources(name)
    if sources:
        from tpukernels.tuning import cache as tcache

        sha = tcache.source_sha(tuple(sources))
        if sha is not None and entry.get("source_sha") not in (None, sha):
            _reject(
                key,
                "stale: a commit touching " + ",".join(sources)
                + " postdates this envelope",
                entry_sha=entry.get("source_sha"), current_sha=sha,
            )
            return None
    return entry


def record_envelope(name: str) -> dict:
    """Record ``name``'s oracle fingerprint envelope into the
    manifest (the daily ``integrity_envelopes`` supervisor step and
    ``tools/integrity_envelopes.py --record``). The ORACLE — not the
    kernel — is the recorded authority; envelopes are meant to be
    captured on CPU where the jnp reference is golden."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpukernels.tuning import cache as tcache

    args = _build_args(name)
    statics = CANARY_CONFIGS[name]["statics"]
    jargs = tuple(
        jnp.asarray(a) if isinstance(a, np.ndarray) else a for a in args
    )
    ref = _oracle(name)(*jargs, **statics)
    fps = fingerprint(ref)
    key = canary_key(name)
    sources = _sources(name)
    entry = {
        "fingerprints": fps,
        "jax": jax.__version__,
        "source_sha": tcache.source_sha(tuple(sources)) if sources
        else None,
        "git_head": journal.git_head(),
        "recorded": round(time.time(), 3),
        "recorded_on": tcache.device_kind(),
    }
    _write_json(
        manifest_path(),
        lambda data: data.setdefault("entries", {}).__setitem__(
            key, entry
        ),
    )
    journal.emit("output_integrity_envelope", kernel=name, key=key,
                 leaves=len(fps))
    return entry


def record_all(names=None, echo=None):
    """Record every kernel's envelope (or the ``names`` subset);
    returns per-kernel rows, ``{"kernel", "error"}`` on failure — one
    broken oracle must not abort the rest of the refresh."""
    echo = echo or (lambda line: None)
    rows = []
    for name in (names if names is not None else sorted(CANARY_CONFIGS)):
        try:
            entry = record_envelope(name)
        except Exception as e:  # noqa: BLE001 — reported per kernel
            rows.append({"kernel": name, "error": repr(e)})
            echo(f"  {name:<16} FAILED: {e!r}")
        else:
            rows.append({"kernel": name, "key": canary_key(name),
                         "leaves": len(entry["fingerprints"])})
            echo(f"  {name:<16} recorded "
                 f"({len(entry['fingerprints'])} leaf fingerprint(s))")
    return rows


def _band_close(a, b, rel, absolute) -> bool:
    return abs(a - b) <= absolute + rel * max(abs(a), abs(b))


def _fingerprint_mismatch(name, got_rows, want_rows):
    """Compare a canary run's fingerprints against the envelope;
    returns a failure description or None. Exact kernels compare
    bitwise (CRC — any flip is caught); float kernels compare the
    norm bands (gross corruption; tier 3 is the elementwise
    authority)."""
    if len(got_rows) != len(want_rows):
        return (f"leaf count {len(got_rows)} != envelope "
                f"{len(want_rows)}")
    kind, rtol, _atol = tolerance(name)
    for i, (got, want) in enumerate(zip(got_rows, want_rows)):
        if got.get("shape") != want.get("shape") or (
            got.get("dtype") != want.get("dtype")
        ):
            return (f"leaf {i}: shape/dtype "
                    f"{got.get('shape')}/{got.get('dtype')} != envelope "
                    f"{want.get('shape')}/{want.get('dtype')}")
        if kind == "exact":
            if got.get("crc") != want.get("crc"):
                return (f"leaf {i}: checksum {got.get('crc')} != "
                        f"envelope {want.get('crc')} (exact kernel)")
            continue
        if got.get("finite") is not True:
            return f"leaf {i}: non-finite values"
        band_rel = max(1e-3, 10.0 * (rtol or 0.0))
        for stat in ("l2", "absmax", "sum"):
            g, w = got.get(stat), want.get(stat)
            if g is None or w is None:
                continue
            scale = max(abs(want.get("absmax") or 0.0), 1.0)
            if not _band_close(g, w, band_rel, band_rel * scale):
                return (f"leaf {i}: {stat} {g} outside the envelope "
                        f"band around {w}")
    return None


# ------------------------------------------------------------------ #
# canary runs + the deep checks (tiers 2/3)                           #
# ------------------------------------------------------------------ #

def _run_canary(name: str, site: str):
    """One small deterministic run of the REAL kernel path — through
    the same output-corruption point as the guarded call (that is
    what makes a finite injected corruption detectable against the
    clean oracle)."""
    import jax.numpy as jnp
    import numpy as np

    from tpukernels import registry

    args = _build_args(name)
    statics = CANARY_CONFIGS[name]["statics"]
    jargs = tuple(
        jnp.asarray(a) if isinstance(a, np.ndarray) else a for a in args
    )
    out = registry.lookup(name)(*jargs, **statics)
    mode = faults.output_fault(site, name)
    if mode:
        out = _corrupt(out, mode)
    return jargs, statics, out


def cross_check(name: str, site: str = "manual"):
    """Tier-3 oracle cross-check: canary kernel run vs the jnp oracle
    on identical inputs, elementwise within the documented tolerance.
    Returns a failure description or None."""
    import numpy as np

    jargs, statics, out = _run_canary(name, site)
    ref = _oracle(name)(*jargs, **statics)
    got, want = _leaves(out), _leaves(ref)
    if len(got) != len(want):
        return f"kernel returned {len(got)} leaves, oracle {len(want)}"
    kind, rtol, atol = tolerance(name)
    for i, (g, w) in enumerate(zip(got, want)):
        g, w = np.asarray(g), np.asarray(w)
        if g.shape != w.shape:
            return f"leaf {i}: shape {g.shape} != oracle {w.shape}"
        if kind == "exact":
            if not np.array_equal(g, w):
                bad = int(np.sum(g != w))
                return (f"leaf {i}: {bad} element(s) differ from the "
                        "oracle (exact kernel)")
        elif not np.allclose(g, w, rtol=rtol, atol=atol,
                             equal_nan=False):
            bad = int(np.sum(
                ~np.isclose(g, w, rtol=rtol, atol=atol)
            ))
            return (f"leaf {i}: {bad} element(s) outside "
                    f"rtol={rtol}/atol={atol} of the oracle")
    return None


def fingerprint_check(name: str, site: str = "manual"):
    """Tier-2 check: canary kernel run fingerprints vs the persisted
    oracle envelope. Returns (ran, failure): ``ran`` False when no
    validated envelope exists (caller falls through to tier 3)."""
    ent = envelope(name)
    if ent is None:
        return False, None
    _jargs, _statics, out = _run_canary(name, site)
    return True, _fingerprint_mismatch(
        name, fingerprint(out), ent.get("fingerprints") or []
    )


def _deep_check(site: str, name: str):
    """(tier, failure_or_None): exact kernels prefer the persisted
    envelope's bitwise CRC (tier 2 — catches any flip, no oracle
    re-run); float kernels and envelope-less kernels go to the live
    elementwise oracle (tier 3 — the authority)."""
    obs_metrics.inc("integrity.deep_checks")
    kind, _rtol, _atol = tolerance(name)
    if kind == "exact":
        ran, failure = fingerprint_check(name, site)
        if ran:
            return 2, failure
    return 3, cross_check(name, site)


# ------------------------------------------------------------------ #
# quarantine ledger                                                   #
# ------------------------------------------------------------------ #

def _config_token() -> str:
    """The (kernel, config) quarantine key's config half: everything
    that selects a different compiled program at the same shapes — the
    AOT layer's tunable env fingerprint, so an autotune candidate's
    corrupt variant quarantines under ITS knob values, not the
    default's."""
    try:
        from tpukernels import aot

        return aot.tunable_env_fingerprint() or "default"
    except Exception:
        return "default"


def _quarantine_key(kernel, config=None) -> str:
    return f"{kernel}|{config or _config_token()}"


def _today() -> str:
    return datetime.date.today().isoformat()


def note_failure(kernel, config=None, detail=None):
    """Count one confirmed integrity failure for (kernel, config);
    returns (failures_today, quarantined, transitioned). Counts are
    per-day (the PR-4 pattern: a new day is a fresh chance); the
    ledger persists across processes via ``integrity_quarantine.json``
    so repeat offenses accumulate across bench children and sweep
    candidates."""
    key = _quarantine_key(kernel, config)
    today = _today()
    threshold = quarantine_after()
    state = {}

    def mutate(data):
        entries = data.setdefault("entries", {})
        ent = entries.get(key)
        if not isinstance(ent, dict) or ent.get("day") != today:
            ent = {"day": today, "failures": 0, "quarantined": False}
        ent["failures"] += 1
        ent["last_detail"] = str(detail)[:200] if detail else None
        ent["last_t"] = round(time.time(), 3)
        transitioned = (
            not ent["quarantined"] and ent["failures"] >= threshold
        )
        if transitioned:
            ent["quarantined"] = True
        entries[key] = ent
        state.update(ent, transitioned=transitioned)

    _write_json(quarantine_path(), mutate)
    return state["failures"], state["quarantined"], state["transitioned"]


def is_quarantined(kernel, config=None) -> bool:
    ent = _read_json(quarantine_path()).get("entries", {}).get(
        _quarantine_key(kernel, config)
    )
    return (
        isinstance(ent, dict)
        and ent.get("day") == _today()
        and bool(ent.get("quarantined"))
    )


def quarantined_entries() -> dict:
    """Today's quarantined (kernel, config) entries — the report
    surface for tools/obs_report.py / health narration."""
    today = _today()
    return {
        k: v
        for k, v in _read_json(quarantine_path()).get(
            "entries", {}
        ).items()
        if isinstance(v, dict) and v.get("day") == today
        and v.get("quarantined")
    }


# ------------------------------------------------------------------ #
# corruption + tripwire                                               #
# ------------------------------------------------------------------ #

def _corrupt_value(v, mode):
    if mode == "nan":
        return float("nan")
    # plausible-garbage, guaranteed-visible: |delta| >= 1 even at v=0
    return v + max(1.0, abs(float(v)))


def _corrupt(outputs, mode):
    """Apply one injected corruption to the first (float-preferring,
    for ``nan``) leaf — in place for writable numpy buffers (the capi
    views the C driver reads back), functionally otherwise."""
    import numpy as np

    leaves = _leaves(outputs)
    idx = 0
    if mode == "nan":
        for i, leaf in enumerate(leaves):
            dt = getattr(np.asarray(leaf), "dtype", None)
            if dt is not None and np.issubdtype(dt, np.floating):
                idx = i
                break
    leaf = leaves[idx]
    if isinstance(leaf, np.ndarray) and leaf.flags.writeable:
        flat = leaf.reshape(-1)
        if np.issubdtype(leaf.dtype, np.floating):
            flat[0] = _corrupt_value(float(flat[0]), mode)
        else:
            flat[0] = int(flat[0]) + 41
        return outputs
    a = np.array(np.asarray(leaf))  # writable copy (jax / read-only np)
    was_scalar = a.ndim == 0
    flat = a.reshape(-1)
    if np.issubdtype(a.dtype, np.floating):
        flat[0] = _corrupt_value(float(flat[0]), mode)
    else:
        flat[0] = int(flat[0]) + 41
    new_leaf = a if not was_scalar else a[()]
    if hasattr(leaf, "at"):  # jax array: rebuild on-device
        import jax.numpy as jnp

        new_leaf = jnp.asarray(a)
    if isinstance(outputs, (tuple, list)):
        out = list(outputs)
        out[idx] = new_leaf
        return tuple(out) if isinstance(outputs, tuple) else out
    return new_leaf


def _tripwire_ok(outputs) -> bool:
    """Tier 1: every float leaf is fully finite."""
    import math

    import numpy as np

    for leaf in _leaves(outputs):
        if isinstance(leaf, float):
            if not math.isfinite(leaf):
                return False
            continue
        dt = getattr(leaf, "dtype", None)
        if dt is None or not np.issubdtype(np.dtype(dt), np.floating):
            continue
        if isinstance(leaf, np.ndarray):
            if not bool(np.isfinite(leaf).all()):
                return False
        else:  # jax array: reduce on device, fetch one bool
            import jax.numpy as jnp

            if not bool(jnp.isfinite(leaf).all()):
                return False
    return True


# ------------------------------------------------------------------ #
# the guard                                                           #
# ------------------------------------------------------------------ #

def _fail(site, kernel, tier, detail, statics=None,
          invalidate_prefixes=()):
    obs_metrics.inc("integrity.failures")
    if kernel:
        _SUSPECT.add(kernel)
    config = _config_token()
    invalidated = {}
    if kernel:
        try:
            from tpukernels import aot

            invalidated = aot.invalidate_kernel(
                kernel, prefixes=invalidate_prefixes
            )
        except Exception:  # noqa: BLE001 — invalidation is best-effort
            pass
    print(
        f"# output-integrity FAILED: {kernel or '<unknown>'} at {site} "
        f"(tier {tier}: {detail})",
        file=sys.stderr,
    )
    journal.emit(
        "output_integrity_failed",
        kernel=kernel, site=site, tier=tier, detail=detail,
        config=config, statics=dict(statics) if statics else None,
        aot_memo_dropped=invalidated.get("memo_dropped"),
        aot_manifest_dropped=invalidated.get("manifest_dropped"),
    )
    if kernel:
        try:
            failures, quarantined, transitioned = note_failure(
                kernel, config, detail
            )
        except Exception as e:  # noqa: BLE001 — an unwritable ledger
            # must not turn a DETECTED corruption into a crash; the
            # output_integrity_failed event above already landed
            obs_metrics.inc("integrity.check_errors")
            journal.emit(
                "output_integrity_check_error", kernel=kernel,
                site=site, error=f"quarantine ledger write failed: {e!r}",
            )
            return
        if transitioned:
            obs_metrics.inc("integrity.quarantines")
            print(
                f"# output-integrity QUARANTINED: {kernel} "
                f"(config {config}) after {failures} failure(s) today "
                "- results from this config are suspect until the "
                "envelope step clears it",
                file=sys.stderr,
            )
            journal.emit(
                "output_integrity_quarantined",
                kernel=kernel, config=config, failures=failures,
                threshold=quarantine_after(),
            )
        elif quarantined:
            journal.emit(
                "output_integrity_quarantined_repeat",
                kernel=kernel, config=config, failures=failures,
            )


def guard(site: str, kernel, outputs, statics=None,
          invalidate_prefixes=()):
    """THE guard: called with one dispatch's result on every guarded
    path. Applies any injected chaos corruption, runs the tiers, and
    returns the outputs — it NEVER raises (a wrong answer must become
    a journaled, quarantined event, not a crash of the surrounding
    run). ``kernel`` may be None (bench driving an unknown loop
    program): tier 1 still applies. ``invalidate_prefixes`` ride into
    ``aot.invalidate_kernel`` on failure — bench passes its loop-
    program label so the executables that produced the corrupt warm
    result are dropped too, not just the kernel's dispatch entries."""
    if not enabled():
        return outputs
    obs_metrics.inc("integrity.checks")
    n = _CALLS[(site, kernel)] = _CALLS.get((site, kernel), 0) + 1
    failure, tier = None, None
    try:
        mode = faults.output_fault(site, kernel)
        if mode:
            outputs = _corrupt(outputs, mode)
        if not _tripwire_ok(outputs):
            failure, tier = "non-finite value in guarded result", 1
        elif not tier1_only() and kernel in CANARY_CONFIGS:
            every = sample_every()
            quarantined = kernel in _SUSPECT or is_quarantined(kernel)
            if quarantined and kernel not in _QUAR_WARNED:
                _QUAR_WARNED.add(kernel)
                print(
                    f"# output-integrity: {kernel} is quarantined/"
                    "suspect - canary-checking every call",
                    file=sys.stderr,
                )
            deep = (
                (site, kernel) not in _DEEP_DONE
                or quarantined
                or (every > 0 and n % every == 0)
            )
            if deep:
                with trace.span(f"integrity/canary/{kernel}",
                                site=site):
                    tier, failure = _deep_check(site, kernel)
                _DEEP_DONE.add((site, kernel))
    except Exception as e:  # noqa: BLE001 — the guard must not crash
        obs_metrics.inc("integrity.check_errors")
        journal.emit(
            "output_integrity_check_error",
            kernel=kernel, site=site, error=repr(e),
        )
        return outputs
    if failure is not None:
        try:
            _fail(site, kernel, tier, failure, statics,
                  invalidate_prefixes=invalidate_prefixes)
        except Exception as e:  # noqa: BLE001 — never crash the run
            obs_metrics.inc("integrity.check_errors")
            journal.emit(
                "output_integrity_check_error", kernel=kernel,
                site=site, error=f"failure handling errored: {e!r}",
            )
    elif tier is not None and kernel in _SUSPECT:
        # a clean deep check lifts the per-process escalation (the
        # persisted quarantine ledger stays until its day rolls)
        _SUSPECT.discard(kernel)
    return outputs


def aot_smoke(name: str):
    """The first-trust smoke check for a prewarm-time compile
    (``aot.precompile`` — no dispatch follows, so the guard's own
    first-call check would never run). Shares the per-process
    first-trust memo under site ``aot``; a failure invalidates the
    executable it was about to bless."""
    if not enabled() or tier1_only() or name not in CANARY_CONFIGS:
        return
    if ("aot", name) in _DEEP_DONE:
        return
    _DEEP_DONE.add(("aot", name))
    try:
        with trace.span(f"integrity/canary/{name}", site="aot"):
            tier, failure = _deep_check("aot", name)
        if failure is not None:
            _fail("aot", name, tier, failure)
    except Exception as e:  # noqa: BLE001 — never crash a prewarm
        obs_metrics.inc("integrity.check_errors")
        journal.emit(
            "output_integrity_check_error",
            kernel=name, site="aot", error=repr(e),
        )
