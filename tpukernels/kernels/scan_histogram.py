"""Fused single-pass prefix scan + histogram (SURVEY.md C7, combined).

The paper's "CUB-style" benchmark times scan AND histogram over the
same input; today's metric path dispatches them as two kernels, so x
streams from HBM twice (scan read + histogram read) on top of the scan
output write — 12 B/elem. This module fuses both into ONE Pallas pass:
each (bm, 128) block is read once, fed to the shared MXU scan
(``scan.scan_block``) and to the shared histogram accumulation
(``histogram.hist_mxu_block`` for nbins <= 256 — the 8x-faster nibble
path the standalone kernel defaults to; ``histogram.hist_vpu_block``
above, or under ``TPK_HIST_IMPL=vpu``) in the same grid step —
8 B/elem, lifting the bandwidth roofline of the ``scan_hist_melem_s``
metric by 1.5x (docs/PERF.md §rooflines). The histogram impl/acc
knobs resolve through histogram's own TUNABLES here too, so the two
entry points can never disagree about what TPK_HIST_IMPL/ACC mean.
The decoupled-lookback machinery CUB needs does not apply: the TPU
grid is sequential per core, so the scan carry stays an SMEM scalar
exactly as in ``kernels/scan.py``.

The ``fuse`` knob (``TPK_SCANHIST_FUSE``, default ``off``) keeps the
two-kernel dispatch of record as the shipped default — the fused
variant is an autotuner-searchable experiment (docs/TUNING.md): the
sweep measures it on the real ``scan_hist_melem_s`` path and promotes
it only if it beats the control by >3% on chip. Both paths are exact
for int32 (the benchmark's dtype) and golden-checked against the
cumsum/bincount oracles.

Padding: the wrapper pads with ZEROS (scan-neutral) and subtracts the
pad count from bin 0 afterwards — one pad value cannot satisfy both
halves (scan needs 0, histogram needs out-of-range), so the histogram
half is corrected instead (on the MXU path the zero pads land on the
joint matrix's (hi=0, lo=0) segment diagonal, i.e. bin 0 again).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpukernels.compat import pl, pltpu
from tpukernels.kernels import histogram as _hist
from tpukernels.kernels.scan import _BLOCK_ROWS, inclusive_scan, scan_block
from tpukernels.tuning import SearchSpace, Tunable, resolve
from tpukernels.utils import cdiv, default_interpret
from tpukernels.utils.shapes import LANES

# Declarative search space (docs/TUNING.md): one categorical knob —
# "off" dispatches the two proven kernels (scan + histogram, each with
# its own TUNABLES), "on" runs the fused single-pass kernel below. The
# knob rides the AOT cache key via the tunable env fingerprint, so the
# fused and unfused programs cache as distinct executables.
TUNABLES = SearchSpace(
    kernel="scan_histogram",
    metric="scan_hist_melem_s",
    bench_shape=(1 << 22, 256),
    bench_dtype="int32",
    sources=(
        "tpukernels/kernels/scan_histogram.py",
        "tpukernels/kernels/scan.py",
        "tpukernels/kernels/histogram.py",
    ),
    tunables=(
        Tunable("fuse", env="TPK_SCANHIST_FUSE", default="off",
                values=("off", "on"), choice=True),
    ),
)


def _fused_kernel(impl, nbins, chunk, acc_dtype,
                  x_ref, o_scan_ref, o_hist_ref, carry_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        carry_ref[0] = jnp.zeros((), x_ref.dtype)
        o_hist_ref[:] = jnp.zeros_like(o_hist_ref)

    # scan half: the shared MXU block scan + SMEM carry (scan.py)
    scanned, total = scan_block(x_ref[:])
    o_scan_ref[:] = scanned + carry_ref[0]
    carry_ref[0] = carry_ref[0] + total

    # histogram half on the SAME resident block, via the shared
    # accumulation helpers (one formula per path, two consumers):
    # MXU nibble counts into the (128, 128) joint matrix, or the VPU
    # one-hot compare into (1, nbins). Per-block counts stay exact
    # (bm*128 < 2^24 in f32 / int32 sums); blocks merge in int32.
    if impl == "mxu":
        o_hist_ref[:] += _hist.hist_mxu_block(x_ref).astype(jnp.int32)
    else:
        o_hist_ref[:] += _hist.hist_vpu_block(
            x_ref, nbins, chunk, acc_dtype
        ).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=("impl", "nbins", "acc_name", "block_rows",
                     "interpret"),
)
def _fused_2d(x2, impl, nbins, acc_name, block_rows, interpret=False):
    acc_dtype = jnp.float32 if acc_name == "f32" else jnp.int8
    chunk = _hist._pick_chunk(nbins, acc_dtype)
    hist_shape = (128, 128) if impl == "mxu" else (1, nbins)
    grid = (x2.shape[0] // block_rows,)
    return pl.pallas_call(
        functools.partial(_fused_kernel, impl, nbins, chunk, acc_dtype),
        out_shape=(
            jax.ShapeDtypeStruct(x2.shape, x2.dtype),
            jax.ShapeDtypeStruct(hist_shape, jnp.int32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (block_rows, LANES), lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            )
        ],
        out_specs=(
            pl.BlockSpec(
                (block_rows, LANES), lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(
                hist_shape, lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
        ),
        scratch_shapes=[pltpu.SMEM((1,), x2.dtype)],
        interpret=interpret,
    )(x2)


def scan_histogram(x, nbins: int, interpret: bool | None = None):
    """(inclusive_scan(x), histogram(x, nbins)) for int32 values —
    the combined benchmark pass. The `fuse` knob resolves through the
    tuning subsystem (env TPK_SCANHIST_FUSE > tuned cache > shipped
    default "off"): "off" dispatches the two standalone kernels,
    "on" runs the fused single-pass kernel (one HBM read of x). The
    fused histogram half honors histogram's own impl/acc knobs with
    the same defaults and fail-loud validation as the standalone
    kernel."""
    if interpret is None:
        interpret = default_interpret()
    nbins = int(nbins)
    params = resolve(
        TUNABLES, shape=(int(x.size), nbins), dtype="int32"
    )
    x = x.reshape(-1).astype(jnp.int32)
    if params["fuse"] == "off":
        return (
            inclusive_scan(x, interpret=interpret),
            _hist.histogram(x, nbins, interpret=interpret),
        )
    n = x.size
    if n == 0:
        # mirror histogram's empty-input guard: a zero-step grid would
        # never run the init step
        return jnp.zeros((0,), jnp.int32), jnp.zeros((nbins,), jnp.int32)
    hparams = _hist.resolve(
        _hist.TUNABLES, shape=(n, nbins), dtype="int32"
    )
    impl = _hist.resolve_impl(hparams["impl"], nbins)
    acc_name = hparams["acc"]
    rows = max(cdiv(n, LANES), 1)
    if impl == "mxu":
        # the nibble groups walk 8·_MXU_T = 128 rows per step
        step = 8 * _hist._MXU_T
        bm = min(_hist._MXU_BM, max(step, (rows // step) * step))
    else:
        # bm must be a chunk multiple (the in-kernel VPU loop), so no
        # trailing rows are dropped
        chunk = _hist._pick_chunk(
            nbins, jnp.float32 if acc_name == "f32" else jnp.int8
        )
        bm = max(chunk, (_BLOCK_ROWS // chunk) * chunk)
        if rows < bm:  # small problems: one chunk-aligned block
            bm = max(chunk, (rows // chunk) * chunk)
    padded = cdiv(rows, bm) * bm * LANES
    if padded != n:
        x = jnp.pad(x, (0, padded - n))  # zeros: scan-neutral
    s, h = _fused_2d(
        x.reshape(-1, LANES), impl, nbins, acc_name, bm,
        interpret=interpret,
    )
    if impl == "mxu":
        h = _hist.joint_to_hist(h, nbins)
    else:
        h = h.reshape(-1)
    pad_elems = padded - n
    if pad_elems:
        # the zero padding counted into bin 0; take it back out
        h = h.at[0].add(jnp.int32(-pad_elems))
    return s.reshape(-1)[:n], h


def scan_histogram_reference(x, nbins: int):
    """jnp oracle pair (mirrors the serial-C running sum + counts)."""
    from tpukernels.kernels.histogram import histogram_reference
    from tpukernels.kernels.scan import inclusive_scan_reference

    x = x.reshape(-1).astype(jnp.int32)
    return inclusive_scan_reference(x), histogram_reference(x, nbins)
