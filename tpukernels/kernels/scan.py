"""Inclusive prefix scan (SURVEY.md C7, scan half).

Reference behavior: CUB-style parallel prefix sum over N elements
(BASELINE.json configs[3]). CUB's GPU formulation (block scan +
decoupled lookback) exists because CUDA thread blocks run concurrently;
the TPU grid is *sequential* per core, so the carry is simply a running
total in scratch that persists across grid steps — same contract,
simpler algorithm (SURVEY.md §7 "scan carry on TPU").

Layout: the 1-D input is reshaped to (rows, 128) lanes. Each grid step
scans one (bm, 128) block in row-major element order:

    within-row inclusive scan  (MXU: block @ upper-triangular ones)
  + exclusive prefix of row totals  (cumsum along sublanes, VPU)
  + carry from all previous blocks  (SMEM scratch)

The within-row scan runs on the MXU instead of log-step lane shifts:
lane-axis shifts are cross-lane relayouts (slow on TPU), while a
(bm, 128) x (128, 128) matmul against an upper-triangular ones matrix
is one MXU op. For int32 the matmul stays *exact* by splitting each
value into four 8-bit digits (three masked, top one arithmetic-shifted
for the sign): each digit is in [-128, 255], exactly representable in
the MXU's default single-pass bf16 operand path (8 significand bits —
a 16-bit split would NOT survive bf16), and every within-row digit
partial sum is < 128*256 = 2^15, inside the fp32 accumulator's exact
window. The Horner reconstruction ((d3*256 + d2)*256 + d1)*256 + d0
wraps mod 2^32 exactly like the serial-C oracle's int32 adds.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpukernels.compat import pl, pltpu
from tpukernels.tuning import SearchSpace, Tunable, resolve
from tpukernels.utils import cdiv, default_interpret
from tpukernels.utils.shapes import LANES

_BLOCK_ROWS = 256


def _vmem_bytes(params, shape=None):
    """Streamed in/out int32 blocks, pipeline double-buffered, plus
    the (bm, 128) triangular-ones matmul operands — small at every
    sweep value; the model keeps the axis budget-honest."""
    bm = params["rows"]
    return 2 * 2 * bm * LANES * 4 + LANES * LANES * 4


# Declarative search space (docs/TUNING.md). rows trades grid-step
# overhead against the (bm, 128) MXU scan matmul's tile size. The
# scan_hist bench metric drives scan AND histogram together, so a
# promotion here reflects the combined loop — documented in TUNING.md.
TUNABLES = SearchSpace(
    kernel="scan",
    metric="scan_hist_melem_s",
    bench_shape=(1 << 22,),
    bench_dtype="int32",
    sources=("tpukernels/kernels/scan.py",),
    tunables=(
        Tunable("rows", env="TPK_SCAN_ROWS", default=_BLOCK_ROWS,
                values=(256, 128, 512)),
    ),
    vmem_budget_bytes=16 * 1024 * 1024,
    vmem_bytes=_vmem_bytes,
)


def _cumsum_log(x, axis: int):
    """Inclusive prefix sum via Hillis-Steele log-step shifted adds
    (jnp.cumsum has no Pallas TPU lowering). Static unrolled loop:
    log2(size) VPU adds."""
    size = x.shape[axis]
    k = 1
    while k < size:
        zeros_shape = list(x.shape)
        zeros_shape[axis] = k
        zeros = jnp.zeros(zeros_shape, x.dtype)
        if axis == 1:
            shifted = jnp.concatenate([zeros, x[:, :-k]], axis=1)
        else:
            shifted = jnp.concatenate([zeros, x[:-k]], axis=0)
        x = x + shifted
        k *= 2
    return x


def _tri_ones(n: int):
    """(n, n) upper-triangular ones: U[k, c] = 1 iff k <= c, so
    (x @ U)[r, c] = sum_{k<=c} x[r, k] — an inclusive lane scan as one
    MXU matmul."""
    rk = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    ck = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    return (rk <= ck).astype(jnp.float32)


def scan_block(block):
    """Carry-free inclusive scan of one (bm, lanes) block in row-major
    element order; returns ``(scanned, block_total)``. The in-kernel
    computation shared by :func:`_scan_kernel` and the fused
    single-pass ``kernels/scan_histogram.py`` — callers add their own
    cross-block carry."""
    lanes = block.shape[1]
    u = _tri_ones(lanes)
    if jnp.issubdtype(block.dtype, jnp.integer):
        # exact int32 on the MXU at default (single-pass bf16)
        # precision: split each value into four 8-bit digits — every
        # digit is in [-128, 255] (exact in bf16) and every within-row
        # digit partial sum is < 128*256 = 2^15 (exact in the MXU's
        # fp32 accumulator). Horner reconstruction in int32 wraps
        # mod 2^32 exactly like the serial oracle's adds.
        digits = [
            (block & 0xFF),
            (jax.lax.shift_right_logical(block, 8) & 0xFF),
            (jax.lax.shift_right_logical(block, 16) & 0xFF),
            jax.lax.shift_right_arithmetic(block, 24),
        ]
        scans = [
            jnp.dot(
                d.astype(jnp.float32), u,
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)
            for d in digits
        ]
        within = scans[3]
        for s in (scans[2], scans[1], scans[0]):
            within = within * 256 + s
    else:
        within = jnp.dot(
            block, u,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
    row_tot = within[:, lanes - 1 : lanes]
    # Mosaic can't concat (k, 1)-shaped single-lane arrays ("offset
    # mismatch on non-concat dimension"), so run the sublane scan on a
    # full-lane broadcast and take one column. Sublane shifts are cheap
    # (no cross-lane relayout), unlike the lane shifts the MXU replaced.
    row_tot_b = jnp.broadcast_to(row_tot, block.shape)
    row_prefix_incl = _cumsum_log(row_tot_b, axis=0)[:, :1]
    # negative int indexing lowers to dynamic_slice (no TPU lowering);
    # a full reduction is supported and equivalent
    return within + (row_prefix_incl - row_tot), jnp.sum(row_tot)


def _scan_kernel(x_ref, o_ref, carry_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        carry_ref[0] = jnp.zeros((), x_ref.dtype)

    scanned, total = scan_block(x_ref[:])
    o_ref[:] = scanned + carry_ref[0]
    carry_ref[0] = carry_ref[0] + total


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _scan_2d(x2, block_rows=_BLOCK_ROWS, interpret=False):
    rows = x2.shape[0]
    bm = min(block_rows, rows)
    grid = (cdiv(rows, bm),)
    return pl.pallas_call(
        _scan_kernel,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(
            (bm, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.SMEM((1,), x2.dtype)],
        interpret=interpret,
    )(x2)


def inclusive_scan(x, interpret: bool | None = None):
    """Inclusive prefix sum of a 1-D array (float32 or int32).

    Block rows resolve through the tuning subsystem (env
    TPK_SCAN_ROWS > tuned cache for this shape/dtype/device >
    shipped default 256)."""
    if interpret is None:
        interpret = default_interpret()
    n = x.size
    block_rows = resolve(TUNABLES, shape=(n,), dtype=x.dtype.name)["rows"]
    x = x.reshape(-1)
    rows = max(cdiv(n, LANES), 1)
    bm = min(block_rows, rows)  # mirrors _scan_2d's choice
    padded = cdiv(rows, bm) * bm * LANES
    if padded != n:
        x = jnp.pad(x, (0, padded - n))  # zeros don't disturb the scan
    out = _scan_2d(
        x.reshape(-1, LANES), block_rows=block_rows, interpret=interpret
    )
    return out.reshape(-1)[:n]


def exclusive_scan(x, interpret: bool | None = None):
    """Exclusive prefix sum of a 1-D array (float32 or int32):
    out[i] = sum(x[:i]), out[0] = 0 — CUB DeviceScan::ExclusiveSum's
    contract, derived from the inclusive kernel by a one-element
    right shift (bitwise-identical partial sums, no re-rounding)."""
    incl = inclusive_scan(x, interpret=interpret)
    if incl.size == 0:
        return incl
    return jnp.concatenate(
        [jnp.zeros((1,), incl.dtype), incl[:-1]]
    )


def inclusive_scan_reference(x):
    """jnp oracle (mirrors the serial-C running-sum golden)."""
    return jnp.cumsum(x)


def exclusive_scan_reference(x):
    """jnp oracle: cumsum shifted right with a leading zero."""
    c = jnp.cumsum(x)
    if c.size == 0:
        return c
    return jnp.concatenate([jnp.zeros((1,), c.dtype), c[:-1]])
