"""Inclusive prefix scan (SURVEY.md C7, scan half).

Reference behavior: CUB-style parallel prefix sum over N elements
(BASELINE.json configs[3]). CUB's GPU formulation (block scan +
decoupled lookback) exists because CUDA thread blocks run concurrently;
the TPU grid is *sequential* per core, so the carry is simply a running
total in scratch that persists across grid steps — same contract,
simpler algorithm (SURVEY.md §7 "scan carry on TPU").

Layout: the 1-D input is reshaped to (rows, 128) lanes. Each grid step
scans one (bm, 128) block in row-major element order:

    within-row inclusive scan  (cumsum along lanes)
  + exclusive prefix of row totals  (cumsum along sublanes)
  + carry from all previous blocks  (SMEM scratch)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpukernels.utils import cdiv, default_interpret
from tpukernels.utils.shapes import LANES

_BLOCK_ROWS = 256


def _cumsum_log(x, axis: int):
    """Inclusive prefix sum via Hillis-Steele log-step shifted adds
    (jnp.cumsum has no Pallas TPU lowering). Static unrolled loop:
    log2(size) VPU adds."""
    size = x.shape[axis]
    k = 1
    while k < size:
        zeros_shape = list(x.shape)
        zeros_shape[axis] = k
        zeros = jnp.zeros(zeros_shape, x.dtype)
        if axis == 1:
            shifted = jnp.concatenate([zeros, x[:, :-k]], axis=1)
        else:
            shifted = jnp.concatenate([zeros, x[:-k]], axis=0)
        x = x + shifted
        k *= 2
    return x


def _scan_kernel(x_ref, o_ref, carry_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        carry_ref[0] = jnp.zeros((), x_ref.dtype)

    block = x_ref[:]
    within = _cumsum_log(block, axis=1)
    row_tot = within[:, -1:]
    # Mosaic can't concat (k, 1)-shaped single-lane arrays ("offset
    # mismatch on non-concat dimension"), so run the sublane scan on a
    # full-lane broadcast and take one column.
    row_tot_b = jnp.broadcast_to(row_tot, block.shape)
    row_prefix_incl = _cumsum_log(row_tot_b, axis=0)[:, :1]
    o_ref[:] = within + (row_prefix_incl - row_tot) + carry_ref[0]
    # negative int indexing lowers to dynamic_slice (no TPU lowering);
    # a full reduction is supported and equivalent
    carry_ref[0] = carry_ref[0] + jnp.sum(row_tot)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _scan_2d(x2, interpret=False):
    rows = x2.shape[0]
    bm = min(_BLOCK_ROWS, rows)
    grid = (cdiv(rows, bm),)
    return pl.pallas_call(
        _scan_kernel,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(
            (bm, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.SMEM((1,), x2.dtype)],
        interpret=interpret,
    )(x2)


def inclusive_scan(x, interpret: bool | None = None):
    """Inclusive prefix sum of a 1-D array (float32 or int32)."""
    if interpret is None:
        interpret = default_interpret()
    n = x.size
    x = x.reshape(-1)
    padded = cdiv(n, LANES) * LANES
    if padded != n:
        x = jnp.pad(x, (0, padded - n))  # zeros don't disturb the scan
    out = _scan_2d(x.reshape(-1, LANES), interpret=interpret)
    return out.reshape(-1)[:n]


def inclusive_scan_reference(x):
    """jnp oracle (mirrors the serial-C running-sum golden)."""
    return jnp.cumsum(x)
