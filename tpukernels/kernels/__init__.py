"""Pallas kernel variants (SURVEY.md C4–C8).

Import kernels via their modules (e.g. ``tpukernels.kernels.sgemm``) or
look them up by benchmark name through ``tpukernels.registry``. Names
are NOT re-exported here: several modules export a function with the
same name as the module, and re-exporting would shadow the submodule
attribute on this package.
"""
