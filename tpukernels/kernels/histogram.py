"""Histogram (SURVEY.md C7, histogram half).

Reference behavior: count occurrences of integer values in
[0, nbins) (BASELINE.json configs[3], "CUB-style"). The OpenMP/CUDA
formulations privatize per-thread/per-block bins and merge; on TPU
there are no scatter atomics worth using — instead each grid step
compares its (bm, 128) value block against the bin-index row vector
(a broadcasted VPU compare) and reduces matches per bin, accumulating
into the output block, which Pallas keeps resident in VMEM across the
sequential grid (the TPU-native analog of bin privatization + merge).

Out-of-range values (and the padding the wrapper adds) count nothing.
Counts are exact: int32 adds on the VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpukernels.utils import cdiv, default_interpret
from tpukernels.utils.shapes import LANES

_BLOCK_ROWS = 256


def _hist_kernel(nbins, x_ref, o_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    block = x_ref[:]  # (bm, 128) int32 values
    bm = block.shape[0]
    # 3D broadcast compare: (bm, 128, 1) == (1, 1, nbins) keeps bins on
    # the lane dim and needs no layout-hostile reshape. The (bm, 128,
    # nbins) one-hot is the VMEM governor; _pick_bm sizes bm to fit.
    bins = jax.lax.broadcasted_iota(jnp.int32, (1, 1, nbins), 2)
    onehot = (block[:, :, None] == bins).astype(jnp.int32)
    o_ref[:] += jnp.sum(onehot, axis=(0, 1), keepdims=False)[None, :]


def _pick_bm(rows: int, nbins: int) -> int:
    """Largest block rows whose one-hot fits ~2 MiB of VMEM."""
    limit = 2 * 1024 * 1024 // (LANES * nbins * 4)
    return max(8, min(_BLOCK_ROWS, limit // 8 * 8, rows))


@functools.partial(jax.jit, static_argnames=("nbins", "interpret"))
def _hist_2d(x2, nbins, interpret=False):
    rows = x2.shape[0]
    bm = _pick_bm(rows, nbins)
    grid = (cdiv(rows, bm),)
    return pl.pallas_call(
        functools.partial(_hist_kernel, nbins),
        out_shape=jax.ShapeDtypeStruct((1, nbins), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(
            (1, nbins), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(x2)


def histogram(x, nbins: int, interpret: bool | None = None):
    """Count int32 values in [0, nbins); returns (nbins,) int32."""
    if interpret is None:
        interpret = default_interpret()
    x = x.reshape(-1).astype(jnp.int32)
    n = x.size
    padded = cdiv(n, LANES) * LANES
    if padded != n:
        # pad with an out-of-range value so padding counts nothing
        x = jnp.pad(x, (0, padded - n), constant_values=nbins)
    out = _hist_2d(x.reshape(-1, LANES), int(nbins), interpret=interpret)
    return out.reshape(-1)


def histogram_reference(x, nbins: int):
    """jnp oracle (mirrors the serial-C counting loop)."""
    x = x.reshape(-1).astype(jnp.int32)
    return jnp.bincount(
        jnp.clip(x, 0, nbins), length=nbins + 1
    )[:nbins].astype(jnp.int32)
