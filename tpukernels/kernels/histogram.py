"""Histogram (SURVEY.md C7, histogram half).

Reference behavior: count occurrences of integer values in
[0, nbins) (BASELINE.json configs[3], "CUB-style"). The OpenMP/CUDA
formulations privatize per-thread/per-block bins and merge; on TPU
there are no scatter atomics worth using. Two Pallas paths:

* MXU (default, nbins <= 256): decompose the bin index into hi/lo
  nibbles (bin = 16*hi + lo) and count with matmuls. Each (8, 128)
  VMEM tile is treated as 8 *sublane segments*; a tiny constant
  (128, 8) replicator matmul broadcasts each segment's values to 16
  sublane rows, one compare against a per-row nibble constant builds
  the one-hot masks mh/ml (128, K) for ALL 8 segments at once — no
  lane relayouts, which is what sank an earlier lane-segmented
  variant (docs/PERF.md) — and mh @ ml^T on the MXU produces every
  segment pair's joint (hi, lo) counts; the 8 segment-diagonal 16x16
  blocks are the histogram. T tiles are lane-concatenated per matmul
  (K = 128*T) to amortize loop overhead. Measured 0.29 ms for 2^22
  elements x 256 bins on v5 lite — 8x the VPU path's 2.36 ms, or
  ~1.6 elem/cycle vs the VPU's hard n*nbins compare floor.
  Counts are exact: masks are 0/1 in bf16, products accumulate in
  f32 where a per-block count can't exceed bm*128 < 2^24, and blocks
  merge in int32.

* VPU (nbins > 256, or TPK_HIST_IMPL=vpu): each grid step compares
  its (bm, 128) value block against the bin-index row vector (a
  broadcasted VPU compare) and reduces matches per bin, accumulating
  into the output block, which Pallas keeps resident in VMEM across
  the sequential grid (the TPU-native analog of bin privatization +
  merge). One compare+accumulate per (element, bin).

Out-of-range values (and the padding the wrappers add) count nothing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpukernels.compat import pl, pltpu
from tpukernels.tuning import SearchSpace, Tunable, resolve
from tpukernels.utils import cdiv, default_interpret
from tpukernels.utils.shapes import LANES

_BLOCK_ROWS = 256
_MXU_BM = 2048  # rows per grid block on the MXU path
_MXU_T = 16  # (8, 128) tiles lane-concatenated per matmul (K = 2048)

# Declarative search space (docs/TUNING.md): both knobs are
# categorical. impl's default is None — it is nbins-dependent (mxu
# only exists for nbins <= 256), so the kernel computes the fallback;
# env/cache values still resolve through the same precedence. The
# scan_hist metric drives scan AND histogram together (see
# kernels/scan.py TUNABLES note).
TUNABLES = SearchSpace(
    kernel="histogram",
    metric="scan_hist_melem_s",
    bench_shape=(1 << 22, 256),
    bench_dtype="int32",
    sources=("tpukernels/kernels/histogram.py",),
    tunables=(
        Tunable("impl", env="TPK_HIST_IMPL", default=None,
                values=("mxu", "vpu"), choice=True),
        Tunable("acc", env="TPK_HIST_ACC", default="i8",
                values=("i8", "f32"), choice=True),
    ),
)


# ------------------------------------------------------------ MXU path

def hist_mxu_block(x_ref):
    """(128, 128) f32 joint (hi, lo) nibble counts of one (bm, 128)
    int32 block ref (bm a multiple of 8·_MXU_T) — the in-kernel MXU
    accumulation shared by :func:`_hist_mxu_kernel` and the fused
    ``kernels/scan_histogram.py`` kernel (one formula, two consumers,
    like ``scan.scan_block``). Callers merge into int32 and extract
    the segment diagonal via :func:`joint_to_hist`."""
    bm = x_ref.shape[0]
    # constants: R replicates sublane s to rows [16s, 16s+16); hvec is
    # the per-row nibble value those rows test against
    r128 = jax.lax.broadcasted_iota(jnp.int32, (128, 8), 0)
    s8 = jax.lax.broadcasted_iota(jnp.int32, (128, 8), 1)
    repl = (r128 // 16 == s8).astype(jnp.bfloat16)
    hvec = (
        jax.lax.broadcasted_iota(jnp.int32, (128, 1), 0) % 16
    ).astype(jnp.float32)
    dotf = functools.partial(jnp.dot, preferred_element_type=jnp.float32)

    def group_body(t, joint):
        tiles = [
            x_ref[pl.ds((t * _MXU_T + u) * 8, 8), :] for u in range(_MXU_T)
        ]
        wide = jnp.concatenate(tiles, axis=1)  # (8, 128*T) int32
        # hi/lo nibble values, replicated to all 16 candidate rows via
        # the MXU (values <= 16 are exact in bf16/f32); out-of-range
        # values give hi outside [0, 16) -> all-zero mh row -> count 0
        hi = (dotf(repl, (wide >> 4).astype(jnp.bfloat16)) == hvec)
        lo = (dotf(repl, (wide & 15).astype(jnp.bfloat16)) == hvec)
        return joint + jax.lax.dot_general(
            hi.astype(jnp.bfloat16),
            lo.astype(jnp.bfloat16),
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    return jax.lax.fori_loop(
        0,
        bm // (8 * _MXU_T),
        group_body,
        jnp.zeros((128, 128), jnp.float32),
    )


def joint_to_hist(joint, nbins):
    """Collapse the (128, 128) joint (hi, lo) matrix to the (nbins,)
    histogram: joint[16s+h, 16s'+l] — only same-segment (s == s')
    pairs count."""
    diag = jnp.einsum("shsl->hl", joint.reshape(8, 16, 8, 16))
    return diag.reshape(256)[:nbins]


def _hist_mxu_kernel(x_ref, o_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    # per-block counts are <= bm*128 < 2^24: exact in f32; merge in i32
    o_ref[:] += hist_mxu_block(x_ref).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("nbins", "interpret"))
def _hist_mxu(x2, nbins, interpret=False):
    pad_rows = cdiv(x2.shape[0], _MXU_BM) * _MXU_BM - x2.shape[0]
    if pad_rows:
        # pad value nbins lands in bin `nbins`, outside the [:nbins]
        # slice (or, at nbins=256, matches no hi nibble at all)
        x2 = jnp.pad(x2, ((0, pad_rows), (0, 0)), constant_values=nbins)
    joint = pl.pallas_call(
        _hist_mxu_kernel,
        out_shape=jax.ShapeDtypeStruct((128, 128), jnp.int32),
        grid=(x2.shape[0] // _MXU_BM,),
        in_specs=[
            pl.BlockSpec(
                (_MXU_BM, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
            )
        ],
        out_specs=pl.BlockSpec(
            (128, 128), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(x2)
    return joint_to_hist(joint, nbins)


# ------------------------------------------------------------ VPU path

def hist_vpu_block(x_ref, nbins, chunk, acc_dtype):
    """(1, nbins) counts of one (bm, 128) int32 block ref (bm a chunk
    multiple) — the in-kernel VPU accumulation shared by
    :func:`_hist_kernel` and the fused ``kernels/scan_histogram.py``
    kernel.

    3D broadcast compare: (chunk, 128, 1) == (1, 1, nbins) keeps bins
    on the lane dim and needs no layout-hostile reshape. The
    compare+accumulate per (element, bin) is the VPU issue-rate
    floor; acc_dtype picks the one-hot/accumulator type (int8 halves
    VMEM; float32 counts are exact below 2^24 per block and may issue
    at a different VPU rate — see TPK_HIST_ACC). The inner fori_loop
    keeps only a (chunk, 128, nbins) slab live while the block stays
    large enough to amortize grid-step overhead."""
    bm = x_ref.shape[0]
    bins = jax.lax.broadcasted_iota(jnp.int32, (1, 1, nbins), 2)
    sum_dtype = jnp.float32 if acc_dtype == jnp.float32 else jnp.int32

    def body(c, acc):
        blk = x_ref[pl.ds(c * chunk, chunk), :]
        onehot = (blk[:, :, None] == bins).astype(acc_dtype)
        return acc + jnp.sum(onehot, axis=(0, 1), dtype=sum_dtype)[None, :]

    zero = jnp.zeros((1, nbins), sum_dtype)
    return jax.lax.fori_loop(0, bm // chunk, body, zero)


def _hist_kernel(nbins, chunk, acc_dtype, x_ref, o_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    o_ref[:] += hist_vpu_block(x_ref, nbins, chunk, acc_dtype).astype(
        jnp.int32
    )


def resolve_impl(impl, nbins: int) -> str:
    """The nbins-dependent impl default ('mxu' only exists for
    nbins <= 256) + the fail-loud validity check — shared by
    :func:`histogram` and the fused scan_histogram wrapper so the two
    entry points can never disagree about what TPK_HIST_IMPL means."""
    if impl is None:
        impl = "mxu" if nbins <= 256 else "vpu"
    if impl == "mxu" and nbins > 256:
        raise ValueError(
            f"TPK_HIST_IMPL=mxu supports nbins <= 256, got {nbins} "
            "(the hi/lo nibble decomposition is 16x16)"
        )
    return impl


def _pick_chunk(nbins: int, acc_dtype) -> int:
    """Rows per inner one-hot slab: (chunk, 128, nbins) in ~2 MiB at
    the accumulator dtype's width."""
    itemsize = jnp.dtype(acc_dtype).itemsize
    limit = 2 * 1024 * 1024 // (LANES * nbins * itemsize)
    return max(8, min(_BLOCK_ROWS, limit // 8 * 8))


@functools.partial(
    jax.jit, static_argnames=("nbins", "acc_name", "interpret")
)
def _hist_2d(x2, nbins, acc_name="i8", interpret=False):
    acc_dtype = jnp.float32 if acc_name == "f32" else jnp.int8
    chunk = _pick_chunk(nbins, acc_dtype)
    # bm must be an exact chunk multiple or the in-kernel loop would
    # silently skip the trailing bm % chunk rows of every block
    bm = max(chunk, (2048 // chunk) * chunk)
    pad_rows = cdiv(x2.shape[0], bm) * bm - x2.shape[0]
    if pad_rows:
        # out-of-range pad value: counts nothing
        x2 = jnp.pad(x2, ((0, pad_rows), (0, 0)), constant_values=nbins)
    rows = x2.shape[0]
    grid = (cdiv(rows, bm),)
    return pl.pallas_call(
        functools.partial(_hist_kernel, nbins, chunk, acc_dtype),
        out_shape=jax.ShapeDtypeStruct((1, nbins), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(
            (1, nbins), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(x2)


def histogram(x, nbins: int, interpret: bool | None = None):
    """Count int32 values in [0, nbins); returns (nbins,) int32.

    Impl/accumulator knobs resolve through the tuning subsystem
    (resolved here, outside jit, so toggling is never masked by a
    cached trace; precedence env > tuned cache > default):
    TPK_HIST_IMPL picks the path — 'mxu' (nibble matmuls; default for
    nbins <= 256) or 'vpu' (broadcast compares; the only choice above
    256 bins). TPK_HIST_ACC picks the VPU path's one-hot accumulator
    dtype: 'i8' (default) or 'f32'. Counts are exact on every path."""
    if interpret is None:
        interpret = default_interpret()
    params = resolve(
        TUNABLES, shape=(int(x.size), int(nbins)), dtype="int32"
    )
    impl = resolve_impl(params["impl"], nbins)
    acc_name = params["acc"]
    x = x.reshape(-1).astype(jnp.int32)
    n = x.size
    if n == 0:
        # grid=(0,) would never run the kernel step that zeroes the
        # accumulator, returning an uninitialized buffer
        return jnp.zeros((nbins,), jnp.int32)
    padded = cdiv(n, LANES) * LANES
    if padded != n:
        # pad with an out-of-range value so padding counts nothing
        x = jnp.pad(x, (0, padded - n), constant_values=nbins)
    x2 = x.reshape(-1, LANES)
    if impl == "mxu":
        return _hist_mxu(x2, int(nbins), interpret=interpret)
    out = _hist_2d(
        x2, int(nbins), acc_name=acc_name, interpret=interpret
    )
    return out.reshape(-1)


def histogram_reference(x, nbins: int):
    """jnp oracle (mirrors the serial-C counting loop)."""
    x = x.reshape(-1).astype(jnp.int32)
    return jnp.bincount(
        jnp.clip(x, 0, nbins), length=nbins + 1
    )[:nbins].astype(jnp.int32)
