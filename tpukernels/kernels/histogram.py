"""Histogram (SURVEY.md C7, histogram half).

Reference behavior: count occurrences of integer values in
[0, nbins) (BASELINE.json configs[3], "CUB-style"). The OpenMP/CUDA
formulations privatize per-thread/per-block bins and merge; on TPU
there are no scatter atomics worth using — instead each grid step
compares its (bm, 128) value block against the bin-index row vector
(a broadcasted VPU compare) and reduces matches per bin, accumulating
into the output block, which Pallas keeps resident in VMEM across the
sequential grid (the TPU-native analog of bin privatization + merge).

Out-of-range values (and the padding the wrapper adds) count nothing.
Counts are exact: int32 adds on the VPU.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpukernels.utils import cdiv, default_interpret
from tpukernels.utils.shapes import LANES

_BLOCK_ROWS = 256


def _hist_kernel(nbins, chunk, acc_dtype, x_ref, o_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    bm = x_ref.shape[0]
    # 3D broadcast compare: (chunk, 128, 1) == (1, 1, nbins) keeps bins
    # on the lane dim and needs no layout-hostile reshape. The
    # compare+accumulate per (element, bin) is the VPU issue-rate
    # floor; acc_dtype picks the one-hot/accumulator type (int8 halves
    # VMEM; float32 counts are exact below 2^24 per block and may issue
    # at a different VPU rate — see TPK_HIST_ACC). The inner fori_loop
    # keeps only a (chunk, 128, nbins) slab live while the block stays
    # large enough to amortize grid-step overhead.
    bins = jax.lax.broadcasted_iota(jnp.int32, (1, 1, nbins), 2)
    sum_dtype = jnp.float32 if acc_dtype == jnp.float32 else jnp.int32

    def body(c, acc):
        blk = x_ref[pl.ds(c * chunk, chunk), :]
        onehot = (blk[:, :, None] == bins).astype(acc_dtype)
        return acc + jnp.sum(onehot, axis=(0, 1), dtype=sum_dtype)[None, :]

    zero = jnp.zeros((1, nbins), sum_dtype)
    total = jax.lax.fori_loop(0, bm // chunk, body, zero)
    o_ref[:] += total.astype(jnp.int32)


def _pick_chunk(nbins: int, acc_dtype) -> int:
    """Rows per inner one-hot slab: (chunk, 128, nbins) in ~2 MiB at
    the accumulator dtype's width."""
    itemsize = jnp.dtype(acc_dtype).itemsize
    limit = 2 * 1024 * 1024 // (LANES * nbins * itemsize)
    return max(8, min(_BLOCK_ROWS, limit // 8 * 8))


@functools.partial(
    jax.jit, static_argnames=("nbins", "acc_name", "interpret")
)
def _hist_2d(x2, nbins, acc_name="i8", interpret=False):
    acc_dtype = jnp.float32 if acc_name == "f32" else jnp.int8
    chunk = _pick_chunk(nbins, acc_dtype)
    # bm must be an exact chunk multiple or the in-kernel loop would
    # silently skip the trailing bm % chunk rows of every block
    bm = max(chunk, (2048 // chunk) * chunk)
    pad_rows = cdiv(x2.shape[0], bm) * bm - x2.shape[0]
    if pad_rows:
        # out-of-range pad value: counts nothing
        x2 = jnp.pad(x2, ((0, pad_rows), (0, 0)), constant_values=nbins)
    rows = x2.shape[0]
    grid = (cdiv(rows, bm),)
    return pl.pallas_call(
        functools.partial(_hist_kernel, nbins, chunk, acc_dtype),
        out_shape=jax.ShapeDtypeStruct((1, nbins), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(
            (1, nbins), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        interpret=interpret,
    )(x2)


def histogram(x, nbins: int, interpret: bool | None = None):
    """Count int32 values in [0, nbins); returns (nbins,) int32.

    Env TPK_HIST_ACC picks the one-hot accumulator dtype: 'i8'
    (default) or 'f32'. Counts are exact either way (a block's per-bin
    count is far below 2^24, float32's exact-integer window). Read
    here, outside jit, so toggling the knob is never masked by a
    cached trace."""
    if interpret is None:
        interpret = default_interpret()
    acc_name = os.environ.get("TPK_HIST_ACC", "i8")
    if acc_name not in ("i8", "f32"):
        raise ValueError(
            f"TPK_HIST_ACC={acc_name!r}: expected 'i8' or 'f32'"
        )
    x = x.reshape(-1).astype(jnp.int32)
    n = x.size
    padded = cdiv(n, LANES) * LANES
    if padded != n:
        # pad with an out-of-range value so padding counts nothing
        x = jnp.pad(x, (0, padded - n), constant_values=nbins)
    out = _hist_2d(
        x.reshape(-1, LANES), int(nbins), acc_name=acc_name,
        interpret=interpret,
    )
    return out.reshape(-1)


def histogram_reference(x, nbins: int):
    """jnp oracle (mirrors the serial-C counting loop)."""
    x = x.reshape(-1).astype(jnp.int32)
    return jnp.bincount(
        jnp.clip(x, 0, nbins), length=nbins + 1
    )[:nbins].astype(jnp.int32)
