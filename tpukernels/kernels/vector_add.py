"""SAXPY vector add: y_out = alpha * x + y  (SURVEY.md C4).

Reference config: N = 2**20, float32 (BASELINE.json configs[0]; the
reference tree was empty, so no file:line citation is possible — the
contract comes from the serial-C oracle the C driver runs).

TPU design: a VPU elementwise kernel. The 1-D problem array is reshaped
to (rows, 128) to satisfy lane tiling, gridded over row blocks so
arbitrarily large N streams through VMEM. alpha rides in SMEM as a
(1, 1) scalar.

The y operand is aliased to the output (input_output_aliases): without
it, chaining saxpy through a fori_loop carry makes XLA copy the
custom-call result back into the carry buffer every iteration — two
extra HBM streams that cap the measured bandwidth at ~400 GB/s vs
~655 with the alias (XLA's own fused a*x+y measures 683). Functional
semantics are preserved: XLA inserts a defensive copy only when the
caller's y is still live after the call.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from tpukernels.compat import pl, pltpu
from tpukernels.tuning import SearchSpace, Tunable, resolve
from tpukernels.utils import cdiv, default_interpret
from tpukernels.utils.shapes import LANES

_BLOCK_ROWS = 512  # (512, 128) f32 block = 256 KiB per operand in VMEM


def _vmem_bytes(params, shape=None):
    """3 streamed f32 blocks (x, y, out — y aliases out but XLA may
    keep a defensive copy), pipeline double-buffered (docs/TUNING.md).
    Generous headroom at every sweep value; the model exists so the
    sweep axis stays budget-honest if values grow."""
    return 2 * 3 * params["rows"] * LANES * 4


# Declarative search space (docs/TUNING.md). rows trades grid-step
# overhead against VMEM residency; 512 is the shipped default the
# 655 GB/s capture was measured at.
TUNABLES = SearchSpace(
    kernel="vector_add",
    metric="saxpy_gb_s",
    bench_shape=(1 << 20,),
    bench_dtype="float32",
    sources=("tpukernels/kernels/vector_add.py",),
    tunables=(
        Tunable("rows", env="TPK_SAXPY_ROWS", default=_BLOCK_ROWS,
                values=(512, 256, 1024, 2048)),
    ),
    vmem_budget_bytes=16 * 1024 * 1024,
    vmem_bytes=_vmem_bytes,
)


def _saxpy_kernel(alpha_ref, x_ref, y_ref, o_ref):
    o_ref[:] = alpha_ref[0, 0] * x_ref[:] + y_ref[:]


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def _saxpy_2d(alpha, x2, y2, block_rows=_BLOCK_ROWS, interpret=False):
    rows = x2.shape[0]
    grid = (cdiv(rows, block_rows),)
    block = (min(block_rows, rows), LANES)
    return pl.pallas_call(
        _saxpy_kernel,
        out_shape=jax.ShapeDtypeStruct(x2.shape, x2.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(block, lambda i: (i, 0), memory_space=pltpu.VMEM),
        input_output_aliases={2: 0},
        interpret=interpret,
    )(alpha, x2, y2)


def saxpy(alpha, x, y, interpret: bool | None = None):
    """y_out = alpha*x + y for 1-D float arrays of any length.

    Block rows resolve through the tuning subsystem (env
    TPK_SAXPY_ROWS > tuned cache for this shape/dtype/device >
    shipped default 512)."""
    if interpret is None:
        interpret = default_interpret()
    n = x.size
    rows = resolve(TUNABLES, shape=(n,), dtype=x.dtype.name)["rows"]
    x = x.reshape(-1)
    y = y.reshape(-1)
    padded = cdiv(n, LANES) * LANES
    if padded != n:
        x = jnp.pad(x, (0, padded - n))
        y = jnp.pad(y, (0, padded - n))
    x2 = x.reshape(-1, LANES)
    y2 = y.reshape(-1, LANES)
    alpha2 = jnp.asarray(alpha, dtype=x.dtype).reshape(1, 1)
    out = _saxpy_2d(alpha2, x2, y2, block_rows=rows, interpret=interpret)
    return out.reshape(-1)[:n]


def saxpy_reference(alpha, x, y):
    """jnp oracle (mirrors the serial-C golden variant)."""
    return alpha * x + y
