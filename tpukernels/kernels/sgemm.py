"""SGEMM: C_out = alpha * A @ B + beta * C  (SURVEY.md C5).

Reference config: 1024x1024x1024 float32 (BASELINE.json configs[1]).
Metric of record: GFLOPS/chip = 2*M*N*K / t (BASELINE.md).

TPU design: MXU-tiled Pallas matmul. Grid is (M/bm, N/bn, K/bk) with the
K dimension innermost (sequential on TPU), accumulating partial products
into a float32 VMEM scratch block and committing alpha*acc + beta*C on
the final K step. Block sizes are chosen so A/B/acc tiles sit in VMEM
(default 256x512 + 512x256 + 256x256 f32 ≈ 1.25 MiB) and every matmul
is a multiple of the 128x128 systolic array.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpukernels.utils import cdiv, default_interpret


def _pick_block(dim: int, preferred: int, align: int) -> int:
    if dim >= preferred:
        return preferred
    if dim % align == 0:
        return dim
    return min(dim, align)


def _sgemm_kernel(alpha_ref, beta_ref, a_ref, b_ref, c_ref, o_ref, acc_ref):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    acc_ref[:] += jnp.dot(
        a_ref[:],
        b_ref[:],
        preferred_element_type=jnp.float32,
        # 'float32' keeps full fp32 accuracy on the MXU (measured
        # 2.6e-5 max abs err at K=1024 vs 0.45 for 'default' bf16) and
        # benches *faster* than 'highest' on v5e.
        precision="float32",
    )

    @pl.when(k == pl.num_programs(2) - 1)
    def _commit():
        o_ref[:] = alpha_ref[0, 0] * acc_ref[:] + beta_ref[0, 0] * c_ref[:]


@functools.partial(
    jax.jit, static_argnames=("bm", "bn", "bk", "interpret")
)
def _sgemm_padded(alpha, beta, a, b, c, bm, bn, bk, interpret=False):
    m, k = a.shape
    _, n = b.shape
    grid = (cdiv(m, bm), cdiv(n, bn), cdiv(k, bk))
    return pl.pallas_call(
        _sgemm_kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk), memory_space=pltpu.VMEM),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (bm, bn), lambda i, j, kk: (i, j), memory_space=pltpu.VMEM
        ),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * n * k,
            bytes_accessed=4 * (m * k + k * n + 2 * m * n),
            transcendentals=0,
        ),
        interpret=interpret,
    )(alpha, beta, a, b, c)


def sgemm(alpha, a, b, beta, c, interpret: bool | None = None):
    """alpha*A@B + beta*C for float32 matrices; pads to tile multiples."""
    if interpret is None:
        interpret = default_interpret()
    m, k = a.shape
    k2, n = b.shape
    assert k == k2 and c.shape == (m, n)
    bm = _pick_block(m, 256, 8)
    bn = _pick_block(n, 256, 128)
    bk = _pick_block(k, 512, 128)
    pm, pn, pk = (cdiv(m, bm) * bm, cdiv(n, bn) * bn, cdiv(k, bk) * bk)
    if (pm, pk) != (m, k):
        a = jnp.pad(a, ((0, pm - m), (0, pk - k)))
    if (pk, pn) != (k, n):
        b = jnp.pad(b, ((0, pk - k), (0, pn - n)))
    if (pm, pn) != (m, n):
        c = jnp.pad(c, ((0, pm - m), (0, pn - n)))
    alpha2 = jnp.asarray(alpha, jnp.float32).reshape(1, 1)
    beta2 = jnp.asarray(beta, jnp.float32).reshape(1, 1)
    out = _sgemm_padded(alpha2, beta2, a, b, c, bm, bn, bk, interpret=interpret)
    return out[:m, :n]


def sgemm_reference(alpha, a, b, beta, c):
    """jnp oracle (mirrors the serial-C ijk golden variant).

    precision is pinned so the oracle stays fp32-accurate even when it
    happens to run on a TPU backend (default matmul precision is bf16
    there, which would corrupt the golden).
    """
    return alpha * jnp.dot(a, b, precision="float32") + beta * c
